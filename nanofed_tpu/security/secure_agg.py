"""Secure aggregation: the server learns only the SUM of client updates.

Capability parity with ``nanofed/server/aggregator/secure.py`` — but that file's crypto is
placeholder-grade (XOR of RSA-OAEP ciphertexts presented as homomorphic addition,
``secure.py:143-153``; a masking scheme where the server decrypts every individual update,
``secure.py:275-313``).  Per SURVEY.md §7, the *capability* is re-implemented honestly here
with the standard constructions:

* **Pairwise additive masking** (the SecAgg construction of Bonawitz et al., CCS 2017,
  single-round, no-dropout variant): every client pair (i, j) derives a shared seed via
  X25519 ECDH + HKDF; client i adds ``PRG(seed_ij)`` for j > i and subtracts it for j < i.
  In the modular sum over all clients the masks cancel *exactly* — updates are fixed-point
  quantized to uint32 so cancellation is bit-exact, not float-approximate.  The server sees
  only uniformly-masked vectors and the final sum.

* **Shamir threshold secret sharing** over the Mersenne prime 2^31 − 1: each client splits
  its quantized update into ``n`` shares of which any ``threshold`` reconstruct; share
  addition is pointwise, so summing every client's share ``k`` and reconstructing yields the
  cohort sum while fewer than ``threshold`` servers learn nothing.  (Honest replacement for
  ``ThresholdSecureAggregation``, ``nanofed/server/aggregator/privacy.py:72-110``, which is
  a plain stacked sum.)

* **AES-GCM transport encryption** for update payloads in the real-network mode (the honest
  role of ``SecureMaskingAggregator``'s AES layer, ``secure.py:221-247``).

Everything here is host-path code: secure aggregation is a cross-trust-domain feature that
only exists when clients are genuinely separate parties (SURVEY.md §7 stage 8).  The
in-simulator SPMD path never pays for it.
"""

from __future__ import annotations

import os
import secrets
from dataclasses import dataclass
from typing import Iterable, Sequence

import numpy as np

try:
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF

    _CRYPTOGRAPHY_ERROR: str | None = None
except ImportError as _e:  # pragma: no cover - depends on the environment
    # The pure fixed-point/Shamir/mask arithmetic (quantize, dequantize, PRG
    # expansion, share reconstruction) is numpy-only and must stay importable
    # without the optional ``cryptography`` package; anything touching X25519 /
    # HKDF / AES-GCM raises a pointed error at call time instead.
    hashes = serialization = AESGCM = HKDF = None  # type: ignore[assignment]
    X25519PrivateKey = X25519PublicKey = None  # type: ignore[assignment]
    _CRYPTOGRAPHY_ERROR = str(_e)

from nanofed_tpu.core.exceptions import AggregationError
from nanofed_tpu.core.types import Params
from nanofed_tpu.utils.trees import tree_ravel


def _require_cryptography() -> None:
    if _CRYPTOGRAPHY_ERROR is not None:
        raise ImportError(
            "secure aggregation's key agreement and share sealing require the "
            f"'cryptography' package, which failed to import: {_CRYPTOGRAPHY_ERROR}"
        )


@dataclass(frozen=True)
class SecureAggregationConfig:
    """Parity: ``SecureAggregationConfig`` (``nanofed/server/aggregator/secure.py:32-40``).

    ``frac_bits`` sets fixed-point precision (quantization step 2^-frac_bits); the masked
    ring is uint32.  The sum of all clients' scaled values must stay within ±2^31·2^-frac_bits
    to avoid wraparound — with the default 16 fractional bits that is ±32768 total mass,
    far above any normalized model update.

    ``dropout_tolerant=True`` switches masked rounds to the double-masking SecAgg
    variant (Bonawitz et al. §4): every client adds a SELF mask on top of the pairwise
    masks, and at the START OF EVERY ROUND draws a fresh ephemeral mask key + self
    seed and Shamir-shares both with the round's cohort (per-execution freshness —
    see ``make_dropout_shares``).  When a client drops mid-round, any ``threshold``
    survivors' shares let the server reconstruct the dropped client's round pairwise
    seeds (cancelling its orphaned masks) and the survivors' self-mask seeds — the
    round completes as the weighted FedAvg of the survivors instead of failing.  The
    self mask is what keeps a *delivered-but-presumed-dropped* update private:
    reconstructing a client's pairwise seeds alone never exposes its update.  Default
    False = the single-round no-dropout variant (any missing cohort member fails the
    round).  In tolerant mode ``min_clients`` doubles as the recovery privacy floor
    (no sum over fewer survivors is ever revealed) and ``threshold`` must exceed half
    the cohort (split-view defense).
    """

    min_clients: int = 3
    frac_bits: int = 16
    threshold: int = 2  # Shamir reconstruction threshold
    dropout_tolerant: bool = False


# ---------------------------------------------------------------------------------------
# Fixed-point quantization (exact modular arithmetic ⇒ exact mask cancellation)
# ---------------------------------------------------------------------------------------


def quantize(vec: np.ndarray, frac_bits: int) -> np.ndarray:
    """Float vector → uint32 fixed-point (two's-complement wraparound encodes sign)."""
    scaled = np.round(np.asarray(vec, np.float64) * (1 << frac_bits)).astype(np.int64)
    return (scaled % (1 << 32)).astype(np.uint32)


def dequantize(vec: np.ndarray, frac_bits: int) -> np.ndarray:
    """uint32 fixed-point → float64, interpreting values as centered (signed) residues."""
    as_int = vec.astype(np.int64)
    centered = np.where(as_int >= 1 << 31, as_int - (1 << 32), as_int)
    return centered.astype(np.float64) / (1 << frac_bits)


# ---------------------------------------------------------------------------------------
# Pairwise additive masking (SecAgg)
# ---------------------------------------------------------------------------------------


@dataclass(frozen=True)
class ClientKeyPair:
    """One client's X25519 keypair for pairwise seed agreement."""

    private: X25519PrivateKey

    @staticmethod
    def generate() -> "ClientKeyPair":
        _require_cryptography()
        return ClientKeyPair(private=X25519PrivateKey.generate())

    def public_bytes(self) -> bytes:
        return self.private.public_key().public_bytes(
            encoding=serialization.Encoding.Raw, format=serialization.PublicFormat.Raw
        )


def _pair_seed(my_key: ClientKeyPair, peer_public: bytes, round_context: bytes) -> bytes:
    """Shared 32-byte seed for a client pair: ECDH → HKDF bound to the round context.

    Symmetric by construction (X25519(sk_i, pk_j) == X25519(sk_j, pk_i)), so both ends of
    the pair expand the identical mask and the ± cancellation is exact.
    """
    _require_cryptography()
    shared = my_key.private.exchange(X25519PublicKey.from_public_bytes(peer_public))
    return HKDF(
        algorithm=hashes.SHA256(), length=32, salt=b"nanofed-tpu-secagg", info=round_context
    ).derive(shared)


def _prg_uint32(seed: bytes, size: int) -> np.ndarray:
    """Expand a 32-byte seed into ``size`` uniform uint32 words (Philox counter PRG).

    numpy's Philox key is 2x uint64 (128 bits), so the 256-bit HKDF seed is XOR-folded
    onto it; the parse is explicitly little-endian so two parties on different-endian
    hosts expand identical pairwise mask streams (the ± cancellation depends on it).
    """
    words = np.frombuffer(seed, dtype="<u8")  # 4 little-endian words from all 32 bytes
    key = words[:2] ^ words[2:]
    return np.random.Generator(np.random.Philox(key=key)).integers(
        0, 1 << 32, size=size, dtype=np.uint32
    )


def _self_mask_seed(self_seed: bytes, round_context: bytes) -> bytes:
    """Per-round self-mask seed: the enrollment-time 32-byte secret ``b_i`` is shared
    ONCE, so each round's self mask must be a fresh derivation bound to the round."""
    _require_cryptography()
    return HKDF(
        algorithm=hashes.SHA256(), length=32, salt=b"nanofed-tpu-secagg-self",
        info=round_context,
    ).derive(self_seed)


def _fold_seed_words(seed: bytes) -> np.ndarray:
    """256-bit seed -> the device kernel's 4 int32 words (endian-independent
    two's-complement centering; a .view would reinterpret in NATIVE byte order and
    break cross-endian mask cancellation — the invariant _prg_uint32 pins for the
    host path)."""
    words = np.frombuffer(seed, dtype="<u4")
    folded = (words[:4] ^ words[4:]).astype(np.int64)
    return np.where(folded >= 1 << 31, folded - (1 << 32), folded).astype(np.int32)


def expand_mask(seed: bytes, size: int, backend: str = "host") -> np.ndarray:
    """Expand a 32-byte seed into the uint32 mask stream a client with this
    ``backend`` would have added — the server-side primitive for dropout recovery
    (reconstructed seeds must expand the SAME stream the clients used)."""
    if backend == "host":
        return _prg_uint32(seed, size)
    if backend != "device":
        raise ValueError(f"unknown backend {backend!r}; use 'host' or 'device'")
    import jax
    import jax.numpy as jnp

    from nanofed_tpu.ops import add_mask

    zeros = jnp.zeros((size,), jnp.uint32)
    return np.asarray(
        jax.device_get(add_mask(zeros, jnp.asarray(_fold_seed_words(seed)), jnp.int32(1)))
    )


def mask_update(
    params: Params,
    client_index: int,
    my_key: ClientKeyPair,
    all_public_keys: Sequence[bytes],
    round_number: int,
    config: SecureAggregationConfig | None = None,
    weight: float = 1.0,
    backend: str = "host",
    self_seed: bytes | None = None,
) -> np.ndarray:
    """Client side: quantize ``weight · params`` and add the pairwise masks.

    Returns the masked flat uint32 vector to send to the server.  ``weight`` lets FedAvg
    weighting survive secure aggregation: clients pre-scale by (their weight / total) so the
    server-side sum IS the weighted mean.

    ``self_seed`` (dropout-tolerant mode) additionally adds the per-round SELF mask
    ``PRG(HKDF(self_seed, round))``: it keeps the update private even if the server
    later reconstructs this client's pairwise seeds, and is removed during the unmask
    round via the Shamir shares the client distributed at the round's start.

    ``backend="device"`` runs quantization and mask expansion on the accelerator via the
    ``ops.quantize`` Pallas kernels — for large models this replaces several
    host-memory passes per pair with on-chip PRNG expansion, and the masked vector
    round-trips to the host exactly once for the wire.  The device PRNG stream differs
    from the host Philox stream, so the WHOLE cohort must use the same backend for the
    pairwise masks to cancel (the seeds are the same HKDF pair seeds either way; only
    the expansion differs) — the roster pins one backend per cohort and registration
    rejects mixed cohorts.  ``unmask_sum`` is stream-agnostic.
    """
    config = config or SecureAggregationConfig()
    if len(all_public_keys) < config.min_clients:
        raise AggregationError(
            f"Need at least {config.min_clients} clients, got {len(all_public_keys)}"
        )
    ctx = f"round:{round_number}".encode()
    if backend == "device":
        return _mask_update_device(
            params, client_index, my_key, all_public_keys, ctx, config, weight, self_seed
        )
    if backend != "host":
        raise ValueError(f"unknown backend {backend!r}; use 'host' or 'device'")
    flat, _ = tree_ravel(params)
    vec = quantize(np.asarray(flat, np.float64) * weight, config.frac_bits)
    for j, peer_pk in enumerate(all_public_keys):
        if j == client_index:
            continue
        mask = _prg_uint32(_pair_seed(my_key, peer_pk, ctx), vec.size)
        if j > client_index:
            vec = vec + mask  # uint32 wraps mod 2^32 by construction
        else:
            vec = vec - mask
    if self_seed is not None:
        vec = vec + _prg_uint32(_self_mask_seed(self_seed, ctx), vec.size)
    return vec


def _mask_update_device(
    params: Params,
    client_index: int,
    my_key: ClientKeyPair,
    all_public_keys: Sequence[bytes],
    ctx: bytes,
    config: SecureAggregationConfig,
    weight: float,
    self_seed: bytes | None = None,
) -> np.ndarray:
    """Device-backend masking: ``ops.quantize`` kernels + on-core PRNG expansion.

    The 256-bit HKDF pair seed is XOR-folded to the kernel's 128-bit seed (both parties
    fold identically, so cancellation is preserved); mask bits never touch host memory.
    """
    import jax
    import jax.numpy as jnp

    from nanofed_tpu.ops import add_mask, quantize_u32

    flat, _ = tree_ravel(params)
    vec = quantize_u32(jnp.asarray(flat, jnp.float32) * weight, config.frac_bits)
    for j, peer_pk in enumerate(all_public_keys):
        if j == client_index:
            continue
        words = jnp.asarray(_fold_seed_words(_pair_seed(my_key, peer_pk, ctx)))
        vec = add_mask(vec, words, jnp.int32(1 if j > client_index else -1))
    if self_seed is not None:
        words = jnp.asarray(_fold_seed_words(_self_mask_seed(self_seed, ctx)))
        vec = add_mask(vec, words, jnp.int32(1))
    return np.asarray(jax.device_get(vec))


def unmask_sum(
    masked_updates: Iterable[np.ndarray],
    template: Params,
    config: SecureAggregationConfig | None = None,
) -> Params:
    """Server side: modular sum of masked vectors — pairwise masks cancel — then
    dequantize and unravel back into the model pytree."""
    config = config or SecureAggregationConfig()
    vectors = list(masked_updates)
    if len(vectors) < config.min_clients:
        raise AggregationError(
            f"Need at least {config.min_clients} clients, got {len(vectors)}"
        )
    total = np.zeros_like(vectors[0])
    for v in vectors:
        total = total + v
    _, unravel = tree_ravel(template)
    import jax.numpy as jnp

    return unravel(jnp.asarray(dequantize(total, config.frac_bits), jnp.float32))


# ---------------------------------------------------------------------------------------
# Shamir threshold secret sharing over GF(2^31 - 1)
# ---------------------------------------------------------------------------------------

_PRIME = (1 << 31) - 1  # Mersenne prime; int64 products of residues stay < 2^62


def _mod(x: np.ndarray) -> np.ndarray:
    return np.mod(x, _PRIME)


@dataclass(frozen=True)
class Share:
    """One party's share: evaluation point ``x`` and the share vector."""

    x: int
    values: np.ndarray  # int64 residues mod _PRIME


def _csprng_residues(shape: tuple[int, ...]) -> np.ndarray:
    """Uniform residues mod p straight from OS entropy.  Shamir's secrecy is
    information-theoretic ONLY if the polynomial coefficients are unpredictable: a
    64-bit-seeded PCG64 draw would let an attacker holding a single share (plus the
    published ephemeral public key to verify guesses against) brute-force the seed and
    recover the secret.  The 2^64-mod-p bias is ~2^-33 — negligible."""
    n = int(np.prod(shape)) if shape else 1
    words = np.frombuffer(os.urandom(8 * n), dtype="<u8")
    return (words % np.uint64(_PRIME)).astype(np.int64).reshape(shape)


def share_vector(
    values: np.ndarray, num_shares: int, threshold: int, rng: np.random.Generator | None = None
) -> list[Share]:
    """Split an int64 vector (entries in (−2^30, 2^30), negatives encoded mod p) into
    ``num_shares`` Shamir shares with reconstruction threshold ``threshold``.

    Coefficients come from OS entropy (see ``_csprng_residues``); pass ``rng`` only
    for deterministic tests — never when sharing real key material."""
    if not 1 <= threshold <= num_shares:
        raise AggregationError(f"invalid threshold {threshold} for {num_shares} shares")
    secret = _mod(np.asarray(values, np.int64))
    # Random degree-(t-1) polynomial per element with constant term = secret.
    if rng is None:
        coeffs = _csprng_residues((threshold - 1, secret.size))
    else:
        coeffs = rng.integers(0, _PRIME, size=(threshold - 1, secret.size), dtype=np.int64)
    shares = []
    for x in range(1, num_shares + 1):
        acc = np.zeros_like(secret)
        for c in coeffs[::-1]:  # Horner: acc = acc*x + c
            acc = _mod(acc * x + c)
        shares.append(Share(x=x, values=_mod(acc * x + secret)))
    return shares


def _lagrange_at_zero(xs: Sequence[int]) -> list[int]:
    """Lagrange basis coefficients ℓ_k(0) mod p for the given evaluation points."""
    coeffs = []
    for k, xk in enumerate(xs):
        num, den = 1, 1
        for m, xm in enumerate(xs):
            if m == k:
                continue
            num = (num * (-xm)) % _PRIME
            den = (den * (xk - xm)) % _PRIME
        coeffs.append((num * pow(den, _PRIME - 2, _PRIME)) % _PRIME)
    return coeffs


def reconstruct_vector(shares: Sequence[Share], threshold: int) -> np.ndarray:
    """Recover the secret vector from any ``threshold`` shares (centered back to signed)."""
    if len(shares) < threshold:
        raise AggregationError(f"need {threshold} shares, got {len(shares)}")
    use = shares[:threshold]
    acc = np.zeros_like(use[0].values)
    for coef, share in zip(_lagrange_at_zero([s.x for s in use]), use):
        acc = _mod(acc + _mod(share.values * coef))
    return np.where(acc > _PRIME // 2, acc - _PRIME, acc)


def add_shares(per_client_shares: Sequence[Sequence[Share]]) -> list[Share]:
    """Pointwise share addition: party k sums every client's k-th share.  Reconstructing
    the result yields the SUM of all client secrets — the threshold secure-sum."""
    num_parties = len(per_client_shares[0])
    out = []
    for k in range(num_parties):
        x = per_client_shares[0][k].x
        acc = np.zeros_like(per_client_shares[0][k].values)
        for client in per_client_shares:
            if client[k].x != x:
                raise AggregationError("share evaluation points misaligned across clients")
            acc = _mod(acc + client[k].values)
        out.append(Share(x=x, values=acc))
    return out


class ThresholdSecureAggregator:
    """Threshold secure-sum of model updates via Shamir sharing.

    Honest replacement for ``ThresholdSecureAggregation``
    (``nanofed/server/aggregator/privacy.py:72-110``).  Values are fixed-point quantized
    (entries must stay within ±2^30·2^-frac_bits after summation).
    """

    def __init__(self, num_parties: int, config: SecureAggregationConfig | None = None):
        self._config = config or SecureAggregationConfig()
        self._num_parties = num_parties

    def share_update(self, params: Params, weight: float = 1.0) -> list[Share]:
        flat, _ = tree_ravel(params)
        scaled = np.round(
            np.asarray(flat, np.float64) * weight * (1 << self._config.frac_bits)
        ).astype(np.int64)
        return share_vector(scaled, self._num_parties, self._config.threshold)

    def aggregate(self, per_client_shares: Sequence[Sequence[Share]], template: Params) -> Params:
        if len(per_client_shares) < self._config.min_clients:
            raise AggregationError(
                f"Need at least {self._config.min_clients} clients, "
                f"got {len(per_client_shares)}"
            )
        summed = add_shares(per_client_shares)
        total = reconstruct_vector(summed, self._config.threshold)
        _, unravel = tree_ravel(template)
        import jax.numpy as jnp

        return unravel(
            jnp.asarray(total.astype(np.float64) / (1 << self._config.frac_bits), jnp.float32)
        )


# ---------------------------------------------------------------------------------------
# Dropout-tolerant SecAgg (Bonawitz et al. §4: double masking + share-based recovery)
# ---------------------------------------------------------------------------------------


def _bytes_to_words(secret: bytes) -> np.ndarray:
    """32-byte secret -> 16 little-endian uint16 words as int64 (every word < 2^16 ≪ p,
    so Shamir over GF(2^31−1) shares it losslessly)."""
    if len(secret) != 32:
        raise AggregationError(f"expected a 32-byte secret, got {len(secret)}")
    return np.frombuffer(secret, dtype="<u2").astype(np.int64)


def _words_to_bytes(words: np.ndarray) -> bytes:
    return np.asarray(words, dtype="<u2").tobytes()


def share_secret_bytes(
    secret: bytes, num_shares: int, threshold: int,
    rng: np.random.Generator | None = None,
) -> list[Share]:
    """Shamir-share a 32-byte secret (an X25519 private key or a self-mask seed)."""
    return share_vector(_bytes_to_words(secret), num_shares, threshold, rng)


def reconstruct_secret_bytes(shares: Sequence[Share], threshold: int) -> bytes:
    """Recover a 32-byte secret from any ``threshold`` shares."""
    words = reconstruct_vector(shares, threshold)
    if words.shape != (16,) or (words < 0).any() or (words >= 1 << 16).any():
        raise AggregationError("reconstructed share vector is not a 32-byte secret")
    return _words_to_bytes(words)


def _transport_key(my_key: ClientKeyPair, peer_public: bytes) -> bytes:
    """Pairwise AES-256 key for share transport through the (untrusted-for-content)
    server — an HKDF derivation of the same X25519 agreement as the mask seeds, under
    a DIFFERENT salt so transport keys and mask seeds are cryptographically independent."""
    _require_cryptography()
    shared = my_key.private.exchange(X25519PublicKey.from_public_bytes(peer_public))
    return HKDF(
        algorithm=hashes.SHA256(), length=32, salt=b"nanofed-tpu-secagg-share",
        info=b"share-transport",
    ).derive(shared)


def _share_aad(context: str, sender: str, recipient: str) -> bytes:
    """AES-GCM associated data binding a sealed share blob to its cohort session,
    round, sender, and recipient.  Without this a malicious server could replay a
    PRIOR round's inbox (whose self seeds it already learned in that round's unmask)
    and harvest the matching mask keys this round — collecting both secrets of a
    victim across two rounds."""
    return f"secagg-share|{context}|{sender}|{recipient}".encode()


def seal_share_payload(
    my_key: ClientKeyPair, peer_public: bytes, payload: dict,
    aad: bytes = b"secagg-share",
) -> str:
    """Encrypt a share payload to one cohort peer (``TransportBox`` under the pairwise
    transport key, base64 wire form; ``aad`` from ``_share_aad`` binds it to the wire
    context).  The server stores and routes these blobs but cannot read them."""
    import base64
    import json

    box = TransportBox(_transport_key(my_key, peer_public))
    return base64.b64encode(
        box.encrypt(json.dumps(payload).encode(), aad)
    ).decode()


def open_share_payload(
    my_key: ClientKeyPair, sender_public: bytes, blob: str,
    aad: bytes = b"secagg-share",
) -> dict:
    """Decrypt a share blob addressed to this client (raises on tamper or on a wire
    context mismatch — AES-GCM authenticates ``aad``)."""
    import base64
    import json

    box = TransportBox(_transport_key(my_key, sender_public))
    return json.loads(box.decrypt(base64.b64decode(blob), aad))


def open_share_inbox(
    identity_key: ClientKeyPair,
    my_id: str,
    identity_public_keys: dict[str, bytes],
    inbox: dict[str, str],
    epks: dict[str, bytes],
    context: str,
) -> dict[str, dict]:
    """Open this client's full share inbox with replay-bound AADs and cross-check the
    server-relayed ephemeral keys against each sender's SEALED attestation.

    The epk map travels in an unsigned GET response; a server substituting its own
    keypairs could compute every pair seed and strip the pairwise masks, reducing
    double-masking to the self mask alone.  Each sender therefore seals its epk
    inside the authenticated blob; a mismatch with the relayed map aborts the round
    client-side before anything is masked.
    """
    import base64

    held = {}
    for sender, blob in inbox.items():
        payload = open_share_payload(
            identity_key, identity_public_keys[sender], blob,
            aad=_share_aad(context, sender, my_id),
        )
        attested = base64.b64decode(payload.get("epk", ""))
        if attested != epks.get(sender):
            raise AggregationError(
                f"server-relayed ephemeral key for {sender!r} does not match its "
                "sealed attestation — refusing to mask (possible epk substitution)"
            )
        held[sender] = payload
    return held


def make_dropout_shares(
    identity_key: ClientKeyPair,
    mask_key: ClientKeyPair,
    client_order: Sequence[str],
    identity_public_keys: dict[str, bytes],
    threshold: int,
    *,
    my_id: str,
    context: str,
    rng: np.random.Generator | None = None,
) -> tuple[bytes, dict[str, str]]:
    """Client side, start of each round: draw the round's self-mask secret ``b_i^r``
    and Shamir-share it and the round's EPHEMERAL mask key across the active cohort.

    Freshness is the security (Bonawitz §4 is a per-execution protocol): revealing a
    dropped client's mask key burns only THIS round's pairwise seeds, and revealing a
    survivor's self seed burns only this round's self mask — earlier and later rounds
    used different secrets, so the server can never retroactively combine a key reveal
    with an old self-seed reveal to unmask a delivered update.  The long-lived
    ``identity_key`` (enrollment) is used only to SEAL the share blobs to each peer;
    the shared secrets are the per-round ``mask_key`` and ``b``.

    ``my_id`` + ``context`` (cohort session + round, e.g. ``"<session>:<round>"``)
    bind each sealed blob's AAD to the wire context (see ``_share_aad``) — recipients
    open with the same binding, so a replayed blob from another round/cohort fails
    authentication.  The blob also carries this client's ephemeral PUBLIC key as a
    sealed attestation recipients cross-check against the server-relayed epk map
    (``open_share_inbox``).

    Returns ``(self_seed, {recipient_id: sealed_blob})``: the blob for round-roster
    member j carries share x=j+1 of each secret, sealed to j's identity key.  The self
    share (to our own id) keeps the share-count invariant — every cohort member holds
    exactly one share of every secret.
    """
    n = len(client_order)
    if 2 * threshold <= n:
        # With t <= n/2 a MALICIOUS server could partition the cohort into two
        # disjoint groups of >= t survivors, feed each a different unmask request,
        # and collect t shares of a victim's mask KEY from one group and t shares of
        # its SELF seed from the other — both secrets, one round, every per-request
        # refusal in build_unmask_reveals satisfied.  t > n/2 makes two disjoint
        # threshold-sized reveal sets impossible, so the invariant holds against an
        # actively-misbehaving server, not just an honest-but-curious one.
        raise AggregationError(
            f"dropout-tolerance threshold {threshold} must exceed half the cohort "
            f"({n}): smaller thresholds allow a split-view unmask attack"
        )
    self_seed = secrets.token_bytes(32)
    sk_raw = mask_key.private.private_bytes(
        encoding=serialization.Encoding.Raw,
        format=serialization.PrivateFormat.Raw,
        encryption_algorithm=serialization.NoEncryption(),
    )
    sk_shares = share_secret_bytes(sk_raw, n, threshold, rng)
    b_shares = share_secret_bytes(self_seed, n, threshold, rng)
    import base64

    epk_b64 = base64.b64encode(mask_key.public_bytes()).decode()
    sealed = {}
    for j, cid in enumerate(client_order):
        payload = {
            "x": j + 1,
            "sk": sk_shares[j].values.tolist(),
            "b": b_shares[j].values.tolist(),
            "epk": epk_b64,
        }
        sealed[cid] = seal_share_payload(
            identity_key, identity_public_keys[cid], payload,
            aad=_share_aad(context, my_id, cid),
        )
    return self_seed, sealed


def build_unmask_reveals(
    request: dict, my_id: str, held_shares: dict[str, dict]
) -> dict:
    """Client side, unmask round: assemble this survivor's reveals for the server's
    request — shares of SELF-mask seeds for survivors, shares of X25519 KEYS for
    dropped clients.

    Safety refusals (the Bonawitz §4 invariant — never both secrets of one client):
    a request listing any id as both dropped and survivor, or listing *this* client as
    dropped (it is alive and submitted), is rejected outright.
    """
    dropped, survivors = set(request["dropped"]), set(request["survivors"])
    if dropped & survivors:
        raise AggregationError(
            "refusing unmask request: ids listed as both dropped and survivor "
            "(revealing both secrets of one client would unmask its update)"
        )
    if my_id in dropped:
        raise AggregationError(
            "refusing unmask request that lists this live client as dropped"
        )
    if my_id not in survivors:
        raise AggregationError("this client is not in the request's survivor set")
    if (dropped | survivors) != set(held_shares):
        # The request must PARTITION the exact round cohort this client distributed
        # shares to — a subset/superset view is a server trying to carve the cohort
        # into inconsistent reveal groups (see make_dropout_shares on why t > n/2
        # closes the remaining split-partition angle).
        raise AggregationError(
            "refusing unmask request: dropped+survivors must partition the round "
            f"cohort exactly (request covers {sorted(dropped | survivors)}, "
            f"cohort is {sorted(held_shares)})"
        )
    return {
        "sk": {d: {"x": held_shares[d]["x"], "values": held_shares[d]["sk"]}
               for d in sorted(dropped)},
        "b": {s: {"x": held_shares[s]["x"], "values": held_shares[s]["b"]}
              for s in sorted(survivors)},
    }


def recover_unmasked_sum(
    masked_updates: dict[str, np.ndarray],
    client_order: Sequence[str],
    public_keys: dict[str, bytes],
    round_number: int,
    reveals: dict[str, dict],
    config: SecureAggregationConfig | None = None,
    backend: str = "host",
    self_seed_commitments: dict[str, bytes] | None = None,
) -> np.ndarray:
    """Server side, dropout-tolerant unmask: modular sum of the survivors' vectors with
    the orphaned masks reconstructed and removed.

    ``client_order`` / ``public_keys`` are THIS ROUND's active roster and EPHEMERAL
    mask public keys (see ``make_dropout_shares`` on per-round freshness).

    Correction terms (all from ≥ ``threshold`` Shamir shares in ``reveals``):
    * every survivor's SELF mask ``PRG(HKDF(b_s, round))`` is subtracted;
    * for every dropped client d, its pairwise masks with each survivor i are
      re-derived from d's reconstructed ephemeral X25519 key and removed with the sign
      i originally applied (+ if d follows i in the roster order, − otherwise).

    Returns the corrected uint32 sum = the quantized weighted sum of the SURVIVORS'
    updates; the caller dequantizes and renormalizes by the survivors' weight mass.
    """
    _require_cryptography()
    config = config or SecureAggregationConfig()
    t = config.threshold
    survivors = [c for c in client_order if c in masked_updates]
    dropped = [c for c in client_order if c not in masked_updates]
    if len(survivors) < config.min_clients:
        # min_clients is the privacy floor every client enforced at mask time: a
        # client that consented to hide in a crowd of >= min_clients must not have
        # its update exposed in a smaller recovered sum.
        raise AggregationError(
            f"only {len(survivors)} survivors; refusing to reveal a sum below the "
            f"min_clients={config.min_clients} privacy floor"
        )
    ctx = f"round:{round_number}".encode()
    size = next(iter(masked_updates.values())).size

    def collect(kind: str, target: str) -> list[Share]:
        shares, seen_x = [], set()
        for rv in reveals.values():
            entry = rv.get(kind, {}).get(target)
            if entry is None:
                continue
            x = int(entry["x"])
            if x in seen_x:
                continue  # duplicate evaluation point adds nothing
            seen_x.add(x)
            shares.append(Share(x=x, values=np.asarray(entry["values"], np.int64)))
        if len(shares) < t:
            raise AggregationError(
                f"only {len(shares)} shares revealed for {kind}:{target}; need {t}"
            )
        return shares

    total = np.zeros_like(next(iter(masked_updates.values())))
    for s in survivors:
        total = total + masked_updates[s]
    # Remove survivors' self masks.  A corrupt/malicious share would make Lagrange
    # interpolation yield a WRONG seed silently (any 32 bytes are "valid"), and the
    # garbage-corrected sum would be installed as the global model with no error —
    # verify each reconstruction against the commitment deposited with the epk.
    for s in survivors:
        b = reconstruct_secret_bytes(collect("b", s), t)
        commit = (self_seed_commitments or {}).get(s)
        if commit is not None:
            digest = hashes.Hash(hashes.SHA256())
            digest.update(b)
            if digest.finalize() != commit:
                raise AggregationError(
                    f"reconstructed self seed for {s!r} fails its commitment "
                    "(corrupt or malicious share) — failing the round"
                )
        total = total - expand_mask(_self_mask_seed(b, ctx), size, backend)
    # Remove dropped clients' orphaned pairwise masks.
    index = {c: i for i, c in enumerate(client_order)}
    for d in dropped:
        sk_raw = reconstruct_secret_bytes(collect("sk", d), t)
        d_key = ClientKeyPair(private=X25519PrivateKey.from_private_bytes(sk_raw))
        # Same silent-corruption hazard: verify the reconstructed key against the
        # client's deposited ephemeral PUBLIC key before trusting its pair seeds.
        if d_key.public_bytes() != public_keys[d]:
            raise AggregationError(
                f"reconstructed mask key for {d!r} does not match its deposited "
                "ephemeral public key (corrupt or malicious share) — failing the round"
            )
        for s in survivors:
            seed = _pair_seed(d_key, public_keys[s], ctx)
            mask = expand_mask(seed, size, backend)
            if index[d] > index[s]:
                total = total - mask  # survivor s had ADDED this mask
            else:
                total = total + mask  # survivor s had SUBTRACTED it
    return total


# ---------------------------------------------------------------------------------------
# AES-GCM transport encryption
# ---------------------------------------------------------------------------------------


class TransportBox:
    """Authenticated encryption for update payloads on the wire.

    The honest role of the reference's AES-GCM layer (``secure.py:221-247``): confidentiality
    + integrity between one client and the server, NOT aggregate privacy (that is the
    masking/Shamir layer's job).
    """

    def __init__(self, key: bytes | None = None) -> None:
        _require_cryptography()
        self._key = key if key is not None else AESGCM.generate_key(bit_length=256)

    @property
    def key(self) -> bytes:
        return self._key

    def encrypt(self, payload: bytes, associated_data: bytes = b"") -> bytes:
        nonce = os.urandom(12)
        return nonce + AESGCM(self._key).encrypt(nonce, payload, associated_data)

    def decrypt(self, blob: bytes, associated_data: bytes = b"") -> bytes:
        return AESGCM(self._key).decrypt(blob[:12], blob[12:], associated_data)
