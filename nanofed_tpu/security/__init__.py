"""Update validation, signing, and secure aggregation.

Replaces ``nanofed/server/validation.py`` and ``nanofed/server/aggregator/secure.py``
(stage 8 of SURVEY.md §7): statistical validation runs vectorized over the stacked client
axis and folds into aggregation weights; signing and secure aggregation are host-path,
cross-trust-domain features for the real-network mode.
"""

# secure_agg and signing need the optional `cryptography` dependency ([net] extra); they
# are exposed lazily so importing the validation path (pulled in by the core round engine)
# works on a base install.
_CRYPTO_EXPORTS = {
    "ClientKeyPair": "secure_agg",
    "SecureAggregationConfig": "secure_agg",
    "Share": "secure_agg",
    "ThresholdSecureAggregator": "secure_agg",
    "TransportBox": "secure_agg",
    "add_shares": "secure_agg",
    "build_unmask_reveals": "secure_agg",
    "dequantize": "secure_agg",
    "expand_mask": "secure_agg",
    "make_dropout_shares": "secure_agg",
    "mask_update": "secure_agg",
    "open_share_inbox": "secure_agg",
    "open_share_payload": "secure_agg",
    "quantize": "secure_agg",
    "reconstruct_secret_bytes": "secure_agg",
    "reconstruct_vector": "secure_agg",
    "recover_unmasked_sum": "secure_agg",
    "seal_share_payload": "secure_agg",
    "share_secret_bytes": "secure_agg",
    "share_vector": "secure_agg",
    "unmask_sum": "secure_agg",
    "SecurityManager": "signing",
    "canonical_bytes": "signing",
    "verify_signature": "signing",
}


def __getattr__(name: str):
    if name in _CRYPTO_EXPORTS:
        import importlib

        mod = importlib.import_module(f"nanofed_tpu.security.{_CRYPTO_EXPORTS[name]}")
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


from nanofed_tpu.security.validation import (  # noqa: E402
    ValidationConfig,
    ValidationReport,
    ValidationResult,
    apply_validation_mask,
    reference_shapes,
    validate_client_updates,
    validate_range,
    validate_shape,
    validate_statistics,
)

__all__ = [
    "ClientKeyPair",
    "SecureAggregationConfig",
    "SecurityManager",
    "Share",
    "ThresholdSecureAggregator",
    "TransportBox",
    "ValidationConfig",
    "ValidationReport",
    "ValidationResult",
    "add_shares",
    "apply_validation_mask",
    "build_unmask_reveals",
    "canonical_bytes",
    "dequantize",
    "expand_mask",
    "make_dropout_shares",
    "mask_update",
    "open_share_inbox",
    "open_share_payload",
    "quantize",
    "reconstruct_secret_bytes",
    "reconstruct_vector",
    "recover_unmasked_sum",
    "reference_shapes",
    "seal_share_payload",
    "share_secret_bytes",
    "share_vector",
    "unmask_sum",
    "validate_client_updates",
    "validate_range",
    "validate_shape",
    "validate_statistics",
    "verify_signature",
]
