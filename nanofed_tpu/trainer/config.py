"""Training configuration.

Parity with ``TrainingConfig`` (``nanofed/trainer/base.py:16-24``: epochs, batch_size,
learning_rate, device, max_batches, log_interval) — device/log_interval are meaningless in
a jitted SPMD program and are replaced by TPU-relevant knobs (momentum/weight_decay/
prox_mu/dtype).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True, slots=True)
class TrainingConfig:
    """Static hyperparameters of local training (hashable: it is a jit-static argument).

    ``prox_mu > 0`` turns FedAvg local training into FedProx (Li et al. 2020): the local
    objective gains ``mu/2 * ||w - w_global||^2``, pulling client iterates toward the
    round's starting point (new capability; required by BASELINE.json config #3).
    ``collect_batch_metrics`` returns per-step loss curves for host-side batch callbacks
    (parity with ``MetricsLogger.on_batch_end``, ``nanofed/trainer/callback.py:38-53``).
    ``compute_dtype="bfloat16"`` runs forward/backward in bf16 on the MXU while params,
    gradients, and the optimizer update stay float32 (mixed precision; loss and metrics
    are reduced in float32).
    """

    batch_size: int = 64
    local_epochs: int = 1
    learning_rate: float = 0.1
    momentum: float = 0.0
    weight_decay: float = 0.0
    max_batches: int | None = None
    prox_mu: float = 0.0
    collect_batch_metrics: bool = False
    compute_dtype: str | None = None  # e.g. "bfloat16"; None = params' native dtype

    def __post_init__(self) -> None:
        if self.batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if self.local_epochs < 1:
            raise ValueError("local_epochs must be >= 1")
        if self.learning_rate <= 0:
            raise ValueError("learning_rate must be > 0")
        if self.max_batches is not None and self.max_batches < 1:
            raise ValueError("max_batches must be >= 1 when set")
        if self.prox_mu < 0:
            raise ValueError("prox_mu must be >= 0")
        if self.compute_dtype is not None:
            import numpy as np

            try:
                import ml_dtypes  # noqa: F401  (registers bfloat16 etc. with numpy)

                np.dtype(self.compute_dtype)
            except TypeError as e:
                raise ValueError(f"unknown compute_dtype {self.compute_dtype!r}") from e
