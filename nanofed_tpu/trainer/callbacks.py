"""Host-side training callbacks.

The reference invokes ``Callback.on_epoch_start/on_epoch_end/on_batch_end`` inline in its
Python batch loop (``nanofed/trainer/base.py:46-51,134-181``; note its Protocol misspells
``on_eopch_start`` — fixed here).  In a jitted trainer there is no host code between
batches, so callbacks are *metric sinks replayed after the fact*: ``local_fit`` returns
per-epoch (and optionally per-batch) metric arrays, and the host ``Trainer`` feeds them to
callbacks in order.  Observable behavior (files written, values seen) matches; the timing
is post-hoc.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Protocol, runtime_checkable


@runtime_checkable
class Callback(Protocol):
    """Parity surface of ``nanofed/trainer/base.py:46-51``."""

    def on_epoch_start(self, epoch: int) -> None: ...

    def on_epoch_end(self, epoch: int, metrics: dict[str, Any]) -> None: ...

    def on_batch_end(self, epoch: int, batch: int, metrics: dict[str, Any]) -> None: ...


class BaseCallback:
    """No-op base so subclasses override only what they need."""

    def on_epoch_start(self, epoch: int) -> None:  # noqa: B027
        pass

    def on_epoch_end(self, epoch: int, metrics: dict[str, Any]) -> None:  # noqa: B027
        pass

    def on_batch_end(self, epoch: int, batch: int, metrics: dict[str, Any]) -> None:  # noqa: B027
        pass


class MetricsLogger(BaseCallback):
    """JSON metrics file sink.

    Parity with ``nanofed/trainer/callback.py:10-53`` (accumulates epoch/batch metrics and
    rewrites one JSON file), but appends atomically once per epoch instead of rewriting on
    every batch.
    """

    def __init__(self, path: str | Path, client_id: str = "client") -> None:
        self._path = Path(path)
        self._client_id = client_id
        self._epochs: list[dict[str, Any]] = []
        self._batches: list[dict[str, Any]] = []

    def on_batch_end(self, epoch: int, batch: int, metrics: dict[str, Any]) -> None:
        self._batches.append({"epoch": epoch, "batch": batch, **metrics})

    def on_epoch_end(self, epoch: int, metrics: dict[str, Any]) -> None:
        self._epochs.append({"epoch": epoch, **metrics})
        self._flush()

    def _flush(self) -> None:
        self._path.parent.mkdir(parents=True, exist_ok=True)
        payload = {
            "client_id": self._client_id,
            "epochs": self._epochs,
            "batches": self._batches,
        }
        tmp = self._path.with_suffix(".tmp")
        tmp.write_text(json.dumps(payload, indent=2))
        tmp.replace(self._path)
