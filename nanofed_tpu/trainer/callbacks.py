"""Host-side training callbacks.

The reference invokes ``Callback.on_epoch_start/on_epoch_end/on_batch_end`` inline in its
Python batch loop (``nanofed/trainer/base.py:46-51,134-181``; note its Protocol misspells
``on_eopch_start`` — fixed here).  In a jitted trainer there is no host code between
batches, so callbacks are *metric sinks replayed after the fact*: ``local_fit`` returns
per-epoch (and optionally per-batch) metric arrays, and the host ``Trainer`` feeds them to
callbacks in order.  Observable behavior (files written, values seen) matches; the timing
is post-hoc.
"""

from __future__ import annotations

import json
import math
from pathlib import Path
from typing import Any, Protocol, runtime_checkable

from nanofed_tpu.observability.registry import MetricsRegistry, get_registry


@runtime_checkable
class Callback(Protocol):
    """Parity surface of ``nanofed/trainer/base.py:46-51``."""

    def on_epoch_start(self, epoch: int) -> None: ...

    def on_epoch_end(self, epoch: int, metrics: dict[str, Any]) -> None: ...

    def on_batch_end(self, epoch: int, batch: int, metrics: dict[str, Any]) -> None: ...


class BaseCallback:
    """No-op base so subclasses override only what they need."""

    def on_epoch_start(self, epoch: int) -> None:  # noqa: B027
        pass

    def on_epoch_end(self, epoch: int, metrics: dict[str, Any]) -> None:  # noqa: B027
        pass

    def on_batch_end(self, epoch: int, batch: int, metrics: dict[str, Any]) -> None:  # noqa: B027
        pass


class TelemetryCallback(BaseCallback):
    """Bridges per-epoch / per-batch local-training metrics into the metrics
    registry (observability subsystem), so client-side training progress shows up
    on ``GET /metrics`` next to the round engine's counters.

    Per-epoch: ``nanofed_local_epochs_total{client=...}`` increments and the last
    loss/accuracy land in ``nanofed_local_last_loss`` / ``_last_accuracy`` gauges,
    with the loss distribution in the ``nanofed_local_epoch_loss`` histogram.
    Per-batch: ``nanofed_local_batches_total{client=...}``.  Non-numeric or
    non-finite metric values are skipped (the callback must never fail training).
    """

    def __init__(self, client_id: str = "client",
                 registry: MetricsRegistry | None = None) -> None:
        self._client_id = client_id
        reg = registry or get_registry()
        self._epochs = reg.counter(
            "nanofed_local_epochs_total", "Local training epochs completed",
            labels=("client",),
        )
        self._batches = reg.counter(
            "nanofed_local_batches_total", "Local training batches completed",
            labels=("client",),
        )
        self._last_loss = reg.gauge(
            "nanofed_local_last_loss", "Last epoch's training loss",
            labels=("client",),
        )
        self._last_accuracy = reg.gauge(
            "nanofed_local_last_accuracy", "Last epoch's training accuracy",
            labels=("client",),
        )
        self._loss_hist = reg.histogram(
            "nanofed_local_epoch_loss", "Per-epoch training loss distribution",
            labels=("client",),
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0),
        )

    @staticmethod
    def _finite(metrics: dict[str, Any], key: str) -> float | None:
        try:
            v = float(metrics.get(key))
        except (TypeError, ValueError):
            return None
        return v if math.isfinite(v) else None

    def on_epoch_end(self, epoch: int, metrics: dict[str, Any]) -> None:
        self._epochs.inc(client=self._client_id)
        loss = self._finite(metrics, "loss")
        if loss is not None:
            self._last_loss.set(loss, client=self._client_id)
            self._loss_hist.observe(loss, client=self._client_id)
        accuracy = self._finite(metrics, "accuracy")
        if accuracy is not None:
            self._last_accuracy.set(accuracy, client=self._client_id)

    def on_batch_end(self, epoch: int, batch: int, metrics: dict[str, Any]) -> None:
        self._batches.inc(client=self._client_id)


class MetricsLogger(BaseCallback):
    """JSON metrics file sink.

    Parity with ``nanofed/trainer/callback.py:10-53`` (accumulates epoch/batch metrics and
    rewrites one JSON file), but appends atomically once per epoch instead of rewriting on
    every batch.
    """

    def __init__(self, path: str | Path, client_id: str = "client") -> None:
        self._path = Path(path)
        self._client_id = client_id
        self._epochs: list[dict[str, Any]] = []
        self._batches: list[dict[str, Any]] = []

    def on_batch_end(self, epoch: int, batch: int, metrics: dict[str, Any]) -> None:
        self._batches.append({"epoch": epoch, "batch": batch, **metrics})

    def on_epoch_end(self, epoch: int, metrics: dict[str, Any]) -> None:
        self._epochs.append({"epoch": epoch, **metrics})
        self._flush()

    def _flush(self) -> None:
        self._path.parent.mkdir(parents=True, exist_ok=True)
        payload = {
            "client_id": self._client_id,
            "epochs": self._epochs,
            "batches": self._batches,
        }
        tmp = self._path.with_suffix(".tmp")
        tmp.write_text(json.dumps(payload, indent=2))
        tmp.replace(self._path)
