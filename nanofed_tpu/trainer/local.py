"""Client-side local training as one jitted pure function.

This replaces the reference's hot loop — ``BaseTrainer.train_epoch`` iterating a torch
DataLoader with per-batch ``zero_grad/forward/backward/step`` (``nanofed/trainer/
base.py:116-198``) — with a ``lax.scan`` over shuffled fixed-shape batches, nested in a
scan over local epochs.  The whole multi-epoch fit compiles to a single XLA program, and
``vmap`` of it over the leading client axis is what turns one client's SGD into a whole
federated round on a TPU mesh.

Padding discipline: every client's data is padded to a common capacity with a {0,1} sample
mask (see ``nanofed_tpu.data.batching``).  Masked samples contribute exactly zero to the
loss, the gradient, and the metrics; a batch that is entirely padding applies a zero
parameter update.  This is how clients with 12k/8k/4k samples (the reference example)
share one SPMD program without biasing FedAvg.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from nanofed_tpu.core.types import ClientData, ClientMetrics, Params, PRNGKey
from nanofed_tpu.trainer.config import TrainingConfig
from nanofed_tpu.utils.trees import tree_scale, tree_sub, tree_where

# grad_fn(params, xb, yb, mb, rng) -> (grads, StepStats)
GradFn = Callable[..., tuple[Params, "StepStats"]]


class StepStats(NamedTuple):
    """Per-batch masked sums (not means): summing across steps stays exact."""

    loss_sum: jax.Array  # sum of per-sample loss over real samples
    correct: jax.Array  # count of correct predictions over real samples
    count: jax.Array  # number of real samples in the batch


class LocalFitResult(NamedTuple):
    params: Params
    metrics: ClientMetrics  # metrics of the FINAL local epoch (what a client reports)
    epoch_loss: jax.Array  # [E] per-epoch mean loss
    epoch_accuracy: jax.Array  # [E] per-epoch accuracy
    batch_loss: jax.Array  # [E, S] per-step mean loss (zeros unless collect_batch_metrics)


def make_grad_fn(
    apply_fn: Callable[..., jax.Array], compute_dtype: str | None = None
) -> GradFn:
    """Standard masked NLL gradient.

    ``apply_fn`` returns log-probabilities (all zoo models end in log_softmax, parity with
    ``nanofed/models/mnist.py:28``); the loss is the masked mean negative log-likelihood —
    what the reference computes with ``F.cross_entropy`` on logits
    (``nanofed/trainer/torch.py:10-14``).

    ``compute_dtype`` enables mixed precision: params and activations are cast (inside
    the differentiated function, so gradients flow back to the float32 masters) and the
    loss/metric reductions stay float32.
    """
    cdt = jnp.dtype(compute_dtype) if compute_dtype is not None else None

    def loss_fn(params, xb, yb, mb, rng):
        if cdt is not None:
            params = jax.tree.map(lambda p: p.astype(cdt), params)
            # Integer inputs (token-id streams) must stay integer: they index an
            # embedding table, and casting ids to bf16 would corrupt the lookup.
            # fedlint: disable=FED002 (branches on xb.dtype — static trace-time metadata, not a traced value; both arms compile into one program)
            if jnp.issubdtype(xb.dtype, jnp.floating):
                xb = xb.astype(cdt)
        logp = apply_fn(params, xb, train=True, rng=rng).astype(jnp.float32)
        nll = -jnp.take_along_axis(logp, yb[:, None], axis=-1)[:, 0]
        count = mb.sum()
        loss = (nll * mb).sum() / jnp.maximum(count, 1.0)
        correct = ((jnp.argmax(logp, -1) == yb) * mb).sum()
        return loss, (correct, count)

    def grad_fn(params, xb, yb, mb, rng):
        (loss, (correct, count)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, xb, yb, mb, rng
        )
        return grads, StepStats(loss_sum=loss * count, correct=correct, count=count)

    return grad_fn


def make_optimizer(config: TrainingConfig) -> optax.GradientTransformation:
    """SGD(+momentum, +decoupled weight decay) — the reference's optimizer family
    (``examples/mnist/run_experiment.py:73``: ``torch.optim.SGD(lr=0.1)``)."""
    parts = []
    if config.weight_decay > 0:
        parts.append(optax.add_decayed_weights(config.weight_decay))
    parts.append(optax.sgd(config.learning_rate, momentum=config.momentum or None))
    return optax.chain(*parts) if len(parts) > 1 else parts[0]


def make_local_fit(
    apply_fn: Callable[..., jax.Array],
    config: TrainingConfig,
    grad_fn: GradFn | None = None,
    optimizer: optax.GradientTransformation | None = None,
) -> Callable[[Params, ClientData, PRNGKey], LocalFitResult]:
    """Build the pure local-training function for one client.

    The returned ``local_fit(global_params, data, rng, lr_scale=None)`` is
    jit-compatible and vmap-compatible over stacked clients.  FedProx: with
    ``config.prox_mu > 0`` the proximal gradient ``mu * (w - w_global)`` is added
    analytically each step.

    ``lr_scale`` (an optional TRACED scalar) multiplies every optimizer step — the
    per-round lr-schedule hook (``trainer.schedules``): scheduling via a traced
    multiplier keeps one compiled round program, where re-baking
    ``config.learning_rate`` per round would re-trace and re-compile.  Scaling the
    post-momentum update is equivalent to running this fit at
    ``learning_rate * lr_scale`` (optax applies lr after the momentum trace);
    FedProx and decoupled weight decay scale with it, exactly as if lr changed.
    """
    if grad_fn is not None and config.compute_dtype is not None:
        # A custom grad_fn owns its own casts; silently ignoring the config would let a
        # user believe bf16 is active when it is not.  make_dp_grad_fn/
        # make_private_local_fit accept compute_dtype directly.
        raise ValueError(
            "compute_dtype is set but a custom grad_fn was supplied; bake the dtype "
            "into the grad_fn (e.g. make_dp_grad_fn(..., compute_dtype=...)) and leave "
            "TrainingConfig.compute_dtype unset"
        )
    grad_fn = grad_fn or make_grad_fn(apply_fn, compute_dtype=config.compute_dtype)
    tx = optimizer or make_optimizer(config)
    bsz = config.batch_size

    def local_fit(
        global_params: Params,
        data: ClientData,
        rng: PRNGKey,
        lr_scale: jax.Array | None = None,
    ) -> LocalFitResult:
        n = data.x.shape[0]
        if n % bsz != 0:
            raise ValueError(
                f"data capacity {n} must be a multiple of batch_size {bsz} "
                "(use data.batching.pack_clients with the same batch_size)"
            )
        steps = n // bsz
        if config.max_batches is not None:
            steps = min(steps, config.max_batches)

        opt_state = tx.init(global_params)

        def epoch_body(carry, ekey):
            params, opt_state = carry
            perm_key, step_key = jax.random.split(ekey)
            perm = jax.random.permutation(perm_key, n)

            def step_body(carry, inp):
                params, opt_state = carry
                sidx, skey = inp
                idx = lax.dynamic_slice(perm, (sidx * bsz,), (bsz,))
                xb, yb, mb = data.x[idx], data.y[idx], data.mask[idx]
                grads, stats = grad_fn(params, xb, yb, mb, skey)
                if config.prox_mu > 0:
                    prox = tree_scale(tree_sub(params, global_params), config.prox_mu)
                    grads = jax.tree.map(jnp.add, grads, prox)
                updates, new_opt_state = tx.update(grads, opt_state, params)
                if lr_scale is not None:
                    updates = tree_scale(updates, lr_scale)
                new_params = optax.apply_updates(params, updates)
                # A batch of pure padding must be a no-op (both params and opt state).
                nonempty = stats.count > 0
                params = tree_where(nonempty, new_params, params)
                opt_state = tree_where(nonempty, new_opt_state, opt_state)
                return (params, opt_state), stats

            step_keys = jax.random.split(step_key, steps)
            (params, opt_state), stats = lax.scan(
                step_body, (params, opt_state), (jnp.arange(steps), step_keys)
            )
            count = jnp.maximum(stats.count.sum(), 1.0)
            e_loss = stats.loss_sum.sum() / count
            e_acc = stats.correct.sum() / count
            if config.collect_batch_metrics:
                b_loss = stats.loss_sum / jnp.maximum(stats.count, 1.0)
            else:
                b_loss = jnp.zeros((steps,))
            return (params, opt_state), (e_loss, e_acc, b_loss)

        epoch_keys = jax.random.split(rng, config.local_epochs)
        (params, _), (e_loss, e_acc, b_loss) = lax.scan(
            epoch_body, (global_params, opt_state), epoch_keys
        )
        metrics = ClientMetrics(loss=e_loss[-1], accuracy=e_acc[-1], samples=data.mask.sum())
        return LocalFitResult(
            params=params,
            metrics=metrics,
            epoch_loss=e_loss,
            epoch_accuracy=e_acc,
            batch_loss=b_loss,
        )

    # Marker for build_round_step: a CUSTOM local_fit override may not accept
    # lr_scale, and a traced value cannot be introspected at call time — the round
    # builder checks this attribute instead of the signature.
    local_fit.supports_lr_scale = True
    return local_fit


def make_evaluator(
    apply_fn: Callable[..., jax.Array], batch_size: int = 256
) -> Callable[[Params, ClientData], dict[str, jax.Array]]:
    """Jitted full-dataset evaluation (masked loss/accuracy), scanning fixed-size batches.

    Replaces host-side test loops; used by the coordinator for the global-accuracy metric
    the baselines target (97% MNIST test accuracy).
    """

    # fedlint: disable=FED004 (eval must NOT donate: params are the live global params, reused for the next round's dispatch)
    @jax.jit
    def evaluate(params: Params, data: ClientData) -> dict[str, jax.Array]:
        n = data.x.shape[0]
        steps = -(-n // batch_size)  # ceil: never truncate real samples
        cap = steps * batch_size
        pad = cap - n
        x = jnp.pad(data.x, [(0, pad)] + [(0, 0)] * (data.x.ndim - 1))
        y = jnp.pad(data.y, (0, pad))
        m = jnp.pad(data.mask, (0, pad))
        xb = x.reshape(steps, batch_size, *data.x.shape[1:])
        yb = y.reshape(steps, batch_size)
        mb = m.reshape(steps, batch_size)

        def body(carry, batch):
            loss_sum, correct, count = carry
            x, y, m = batch
            logp = apply_fn(params, x)
            nll = -jnp.take_along_axis(logp, y[:, None], axis=-1)[:, 0]
            loss_sum = loss_sum + (nll * m).sum()
            correct = correct + ((jnp.argmax(logp, -1) == y) * m).sum()
            return (loss_sum, correct, count + m.sum()), None

        (loss_sum, correct, count), _ = lax.scan(body, (0.0, 0.0, 0.0), (xb, yb, mb))
        count = jnp.maximum(count, 1.0)
        return {"loss": loss_sum / count, "accuracy": correct / count}

    return evaluate


def stack_rngs(rng: PRNGKey, num_clients: int) -> jax.Array:
    """Split an rng into a ``[C]`` batch of per-client keys (one per vmapped client)."""
    return jax.random.split(rng, num_clients)
