"""Personalized evaluation: fine-tune the global model per client, test on the
client's OWN held-out data.

Global accuracy under non-IID data understates what federation delivers to each
participant: a client holding two classes does not need the 10-class decision
boundary — it needs a model that, after a few LOCAL steps from the global
initialization, is excellent on ITS distribution (the "personalization" axis of FL;
Wang et al. 2019's FedAvg-then-fine-tune baseline, which stronger schemes are judged
against).  The reference framework has no notion of this; its only metric is the
global model's aggregate accuracy.

TPU mapping: fine-tuning IS ``make_local_fit`` and per-client evaluation is a masked
scan — so personalized evaluation for the whole population is one
``jit(vmap(fine_tune_then_eval))`` over the stacked client axis, reusing the exact
local-training program the rounds run.  Nothing about the global model changes: this
is a pure measurement.

The per-client train/test split lives here too (``split_client_data``): personalized
metrics are only honest on samples the fine-tune never saw, and the split must
respect the padding mask (padding rows belong to NEITHER side).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from nanofed_tpu.core.types import ClientData, Params
from nanofed_tpu.trainer.config import TrainingConfig
from nanofed_tpu.trainer.local import GradFn, make_local_fit, stack_rngs


def split_client_data(
    data: ClientData, test_fraction: float = 0.2, seed: int = 0
) -> tuple[ClientData, ClientData]:
    """Split each client's REAL samples into disjoint train/test subsets.

    Returns ``(train, test)`` with the same ``[C, N, ...]`` shapes as the input —
    the split moves samples between the two MASKS (a sample is real in exactly one
    side), so both halves stay drop-in compatible with every stacked-pytree
    consumer.  Each client keeps at least one sample on each side (a client with a
    single real sample keeps it on the TRAIN side and contributes no test signal,
    rather than fabricating an empty fine-tune).
    """
    if not 0.0 < test_fraction < 1.0:
        raise ValueError(f"test_fraction must be in (0, 1), got {test_fraction}")
    mask = np.asarray(data.mask)
    if mask.ndim != 2:
        raise ValueError("split_client_data expects stacked [C, N] client data")
    rng = np.random.default_rng(seed)
    train_mask = np.zeros_like(mask)
    test_mask = np.zeros_like(mask)
    for c in range(mask.shape[0]):
        real = np.where(mask[c] > 0)[0]
        if len(real) == 0:
            continue  # padding client (pad_clients): stays empty on both sides
        n_test = int(np.floor(test_fraction * len(real)))
        if len(real) >= 2:
            n_test = min(max(n_test, 1), len(real) - 1)
        else:
            n_test = 0
        chosen = rng.permutation(real)
        test_idx, train_idx = chosen[:n_test], chosen[n_test:]
        train_mask[c, train_idx] = 1.0
        test_mask[c, test_idx] = 1.0
    return (
        data._replace(mask=jnp.asarray(train_mask)),
        data._replace(mask=jnp.asarray(test_mask)),
    )


def make_personalized_evaluator(
    apply_fn: Callable[..., jax.Array],
    training: TrainingConfig,
    grad_fn: GradFn | None = None,
) -> Callable[..., dict[str, jax.Array]]:
    """Build the jitted population-wide personalized evaluator.

    The returned ``evaluate(global_params, train, test, rng)`` fine-tunes the global
    model on every client's train split (``vmap`` of the SAME ``local_fit`` program
    rounds use — ``training`` controls epochs/lr of the fine-tune) and reports, per
    client and population-weighted:

    - ``global_accuracy``    the un-tuned global model on each client's test split
    - ``personal_accuracy``  the fine-tuned model on the same split

    Clients whose test mask is empty (padding rows, single-sample clients) carry
    zero weight in the means.  Pure measurement — no state anywhere changes.
    """
    fit = make_local_fit(apply_fn, training, grad_fn=grad_fn)
    bsz = training.batch_size

    def eval_on(params, test: ClientData) -> tuple[jax.Array, jax.Array]:
        # Scan fixed-size batches (capacity is a batch_size multiple by the same
        # pack_clients contract the fit relies on): under vmap this bounds peak
        # activation memory at [C, bsz, ...] instead of [C, N, ...].
        n = test.x.shape[0]
        steps = n // bsz
        xb = test.x.reshape(steps, bsz, *test.x.shape[1:])
        yb = test.y.reshape(steps, bsz)
        mb = test.mask.reshape(steps, bsz)

        def body(carry, batch):
            correct, count = carry
            x, y, m = batch
            logp = apply_fn(params, x)
            correct = correct + ((jnp.argmax(logp, -1) == y) * m).sum()
            return (correct, count + m.sum()), None

        (correct, count), _ = lax.scan(body, (0.0, 0.0), (xb, yb, mb))
        return correct / jnp.maximum(count, 1.0), count

    def one_client(global_params, train_i, test_i, rng_i):
        g_acc, count = eval_on(global_params, test_i)
        tuned = fit(global_params, train_i, rng_i).params
        p_acc, _ = eval_on(tuned, test_i)
        return g_acc, p_acc, count

    # fedlint: disable=FED004 (eval must NOT donate: the global params are reused by the caller after personalization scoring)
    @jax.jit
    def evaluate(
        global_params: Params, train: ClientData, test: ClientData, rng: jax.Array
    ) -> dict[str, jax.Array]:
        rngs = stack_rngs(rng, train.mask.shape[0])
        g_acc, p_acc, counts = jax.vmap(one_client, in_axes=(None, 0, 0, 0))(
            global_params, train, test, rngs
        )
        w = counts / jnp.maximum(counts.sum(), 1.0)
        return {
            "global_accuracy_per_client": g_acc,
            "personal_accuracy_per_client": p_acc,
            "test_counts": counts,
            "global_accuracy": (g_acc * w).sum(),
            "personal_accuracy": (p_acc * w).sum(),
            "personalization_gain": ((p_acc - g_acc) * w).sum(),
        }

    return evaluate


__all__ = ["make_personalized_evaluator", "split_client_data"]
