"""SCAFFOLD local training: control-variate-corrected SGD (Karimireddy et al. 2020).

FedAvg's local steps follow each client's OWN gradient field; under non-IID data the
clients drift toward their local optima and the averaged model oscillates between them
— the reference framework's only answer is FedProx's proximal pull (and the reference
itself has neither; see ``nanofed/trainer/`` — plain ``TorchTrainer``/``PrivateTrainer``
are its whole algorithm surface).  SCAFFOLD removes the drift at its source: every local
step is corrected by the difference between the estimated GLOBAL gradient direction
(server control ``c``) and the client's own (client control ``c_i``),

    y  <-  y - eta_l * (grad f_i(y) + c - c_i),

so in expectation each client walks the global descent direction even on fully skewed
shards.  After ``K`` effective steps the client re-estimates its control (option II of
the paper — no extra gradient pass):

    c_i+  =  c_i - c + (x - y) / (K * eta_l)          (== the mean of its local grads)
    dc_i  =  c_i+ - c_i

TPU mapping: ``c_i`` for the whole population is a STACKED pytree ``[C, ...]`` sharded
over the client mesh axis (exactly like the training data), and the corrected fit is
``vmap``-ed over ``(data_i, rng_i, c_i)`` — one client's control ride-along costs one
extra vector add per step on the VPU, fused by XLA into the optimizer update.  The fit
returns ``dc_i`` (not ``c_i+``) so the round step can write participants back with a
collision-safe ``scatter-add`` (non-participants contribute an exact zero).

Restrictions are enforced, not documented away: the option-II control estimate equals
the mean local gradient ONLY for plain SGD — momentum or decoupled weight decay would
make ``(x - y)/(K*eta)`` estimate a momentum-filtered direction and silently bias every
future round's correction — and FedProx's proximal term is a different drift remedy
whose gradient would leak into the control estimate; combining them is refused.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from nanofed_tpu.core.types import ClientData, ClientMetrics, Params, PRNGKey
from nanofed_tpu.trainer.config import TrainingConfig
from nanofed_tpu.trainer.local import GradFn, make_grad_fn
from nanofed_tpu.utils.trees import tree_where, tree_zeros_like


class ScaffoldFitResult(NamedTuple):
    params: Params  # the client's final local params y
    metrics: ClientMetrics  # final-epoch metrics (same reporting contract as local_fit)
    delta_c: Params  # dc_i = c_i+ - c_i (zero when the client took no real step)
    epoch_loss: jax.Array  # [E] per-epoch mean loss
    epoch_accuracy: jax.Array  # [E] per-epoch accuracy


def make_scaffold_local_fit(
    apply_fn: Callable[..., jax.Array],
    config: TrainingConfig,
    grad_fn: GradFn | None = None,
) -> Callable[..., ScaffoldFitResult]:
    """Build the SCAFFOLD-corrected local fit for one client.

    The returned ``fit(global_params, data, rng, c_global, c_client, lr_scale=None)``
    is jit/vmap-compatible; the round step vmaps it with
    ``in_axes=(None, 0, 0, None, 0)`` — controls are per-client, the server control is
    replicated round state.  ``lr_scale`` scales the SGD step AND the control estimate's
    effective ``eta`` consistently, so per-round lr schedules compose with SCAFFOLD
    without re-tracing (same traced-scalar contract as ``make_local_fit``).
    """
    if config.momentum != 0.0 or config.weight_decay != 0.0:
        raise ValueError(
            "SCAFFOLD requires plain SGD locally: the option-II control update "
            "(x - y)/(K*eta) equals the mean local gradient only without momentum/"
            "weight decay — set TrainingConfig.momentum=0 and weight_decay=0"
        )
    if config.prox_mu != 0.0:
        raise ValueError(
            "prox_mu > 0 with SCAFFOLD would fold the proximal gradient into the "
            "control estimate — choose ONE drift remedy (FedProx via prox_mu on the "
            "standard path, or SCAFFOLD here)"
        )
    grad_fn = grad_fn or make_grad_fn(apply_fn, compute_dtype=config.compute_dtype)
    bsz = config.batch_size
    base_lr = config.learning_rate

    # NOTE: the epoch/step scan below mirrors make_local_fit's loop (local.py) with
    # the update rule swapped for corrected plain SGD + the effective-step counter.
    # The batching/masking discipline (capacity check, permutation slicing, the
    # pure-padding no-op rule, max_batches clamp) must stay identical in both —
    # test_zero_controls_first_round_is_fedavg pins the two paths to the same float
    # trajectory, so a divergence in the shared discipline fails loudly.
    def scaffold_fit(
        global_params: Params,
        data: ClientData,
        rng: PRNGKey,
        c_global: Params,
        c_client: Params,
        lr_scale: jax.Array | None = None,
    ) -> ScaffoldFitResult:
        n = data.x.shape[0]
        if n % bsz != 0:
            raise ValueError(
                f"data capacity {n} must be a multiple of batch_size {bsz} "
                "(use data.batching.pack_clients with the same batch_size)"
            )
        steps = n // bsz
        if config.max_batches is not None:
            steps = min(steps, config.max_batches)

        # c - c_i is constant over the whole local fit (controls update once per
        # round); hoist it out of the step loop.
        correction = jax.tree.map(lax.sub, c_global, c_client)
        scale = 1.0 if lr_scale is None else lr_scale
        eta = base_lr * scale

        def epoch_body(carry, ekey):
            params, taken = carry
            perm_key, step_key = jax.random.split(ekey)
            perm = jax.random.permutation(perm_key, n)

            def step_body(carry, inp):
                params, taken = carry
                sidx, skey = inp
                idx = lax.dynamic_slice(perm, (sidx * bsz,), (bsz,))
                xb, yb, mb = data.x[idx], data.y[idx], data.mask[idx]
                grads, stats = grad_fn(params, xb, yb, mb, skey)
                corrected = jax.tree.map(jnp.add, grads, correction)
                new_params = jax.tree.map(
                    lambda p, g: p - (eta * g).astype(p.dtype), params, corrected
                )
                # A batch of pure padding is a no-op and does NOT count toward K:
                # the control estimate divides by the number of REAL steps.
                nonempty = stats.count > 0
                params = tree_where(nonempty, new_params, params)
                taken = taken + nonempty.astype(jnp.float32)
                return (params, taken), stats

            step_keys = jax.random.split(step_key, steps)
            (params, taken), stats = lax.scan(
                step_body, (params, taken), (jnp.arange(steps), step_keys)
            )
            count = jnp.maximum(stats.count.sum(), 1.0)
            e_loss = stats.loss_sum.sum() / count
            e_acc = stats.correct.sum() / count
            return (params, taken), (e_loss, e_acc)

        epoch_keys = jax.random.split(rng, config.local_epochs)
        # The step counter's zero is derived from the data so it carries the same
        # varying-axes type as the per-step increments under shard_map (a literal
        # jnp.float32(0.0) is "unvarying" there and fails the scan carry check).
        taken0 = data.mask.sum().astype(jnp.float32) * 0.0
        (params, taken), (e_loss, e_acc) = lax.scan(
            epoch_body, (global_params, taken0), epoch_keys
        )

        # Option II: c_i+ = c_i - c + (x - y)/(K*eta)  =>  dc_i = -c + (x - y)/(K*eta).
        # A client that never took a real step (all-padding cohort slot) has y == x and
        # K == 0; its control must not move.
        k_eta = jnp.maximum(taken, 1.0) * eta
        took_any = taken > 0
        delta_c = jax.tree.map(
            lambda cg, x, y: jnp.where(
                took_any, -cg + (x - y).astype(jnp.float32) / k_eta, 0.0
            ).astype(cg.dtype),
            c_global, global_params, params,
        )
        metrics = ClientMetrics(
            loss=e_loss[-1], accuracy=e_acc[-1], samples=data.mask.sum()
        )
        return ScaffoldFitResult(
            params=params,
            metrics=metrics,
            delta_c=delta_c,
            epoch_loss=e_loss,
            epoch_accuracy=e_acc,
        )

    scaffold_fit.supports_lr_scale = True
    return scaffold_fit


def zero_controls(params: Params) -> Params:
    """Fresh server/client control state: all zeros (the paper's initialization —
    round 1 with zero controls is exactly uniform FedAvg)."""
    return tree_zeros_like(params)


def stack_zero_controls(params: Params, num_clients: int) -> Params:
    """The population's client controls as one stacked ``[C, ...]`` pytree, ready to
    shard over the client mesh axis."""
    return jax.tree.map(
        lambda p: jnp.zeros((num_clients, *p.shape), p.dtype), params
    )


__all__ = [
    "ScaffoldFitResult",
    "make_scaffold_local_fit",
    "stack_zero_controls",
    "zero_controls",
]
