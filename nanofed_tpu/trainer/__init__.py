"""Client-side training (parity: ``nanofed/trainer/__init__.py`` exports BaseTrainer/
TorchTrainer/PrivateTrainer/TrainingConfig/Callback/MetricsLogger)."""

from nanofed_tpu.trainer.api import Trainer
from nanofed_tpu.trainer.callbacks import (
    BaseCallback,
    Callback,
    MetricsLogger,
    TelemetryCallback,
)
from nanofed_tpu.trainer.config import TrainingConfig
from nanofed_tpu.trainer.local import (
    LocalFitResult,
    StepStats,
    make_evaluator,
    make_grad_fn,
    make_local_fit,
    make_optimizer,
    stack_rngs,
)
from nanofed_tpu.trainer.personalization import (
    make_personalized_evaluator,
    split_client_data,
)
from nanofed_tpu.trainer.scaffold import (
    ScaffoldFitResult,
    make_scaffold_local_fit,
    stack_zero_controls,
    zero_controls,
)
from nanofed_tpu.trainer.schedules import SCHEDULES, lr_schedule_scale
from nanofed_tpu.trainer.private import (
    local_fit_noise_events,
    make_dp_grad_fn,
    make_private_local_fit,
    record_local_fit,
    validate_privacy_budget,
)

__all__ = [
    "BaseCallback",
    "Callback",
    "TelemetryCallback",
    "LocalFitResult",
    "MetricsLogger",
    "ScaffoldFitResult",
    "StepStats",
    "Trainer",
    "TrainingConfig",
    "local_fit_noise_events",
    "make_dp_grad_fn",
    "make_evaluator",
    "make_grad_fn",
    "make_local_fit",
    "make_optimizer",
    "make_personalized_evaluator",
    "make_private_local_fit",
    "make_scaffold_local_fit",
    "record_local_fit",
    "split_client_data",
    "stack_zero_controls",
    "zero_controls",
    "SCHEDULES",
    "lr_schedule_scale",
    "stack_rngs",
    "validate_privacy_budget",
]
