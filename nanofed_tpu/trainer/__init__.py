"""Client-side training (parity: ``nanofed/trainer/__init__.py`` exports BaseTrainer/
TorchTrainer/PrivateTrainer/TrainingConfig/Callback/MetricsLogger; the DP trainer lives in
``nanofed_tpu.privacy.dp_trainer``)."""

from nanofed_tpu.trainer.api import Trainer
from nanofed_tpu.trainer.callbacks import BaseCallback, Callback, MetricsLogger
from nanofed_tpu.trainer.config import TrainingConfig
from nanofed_tpu.trainer.local import (
    LocalFitResult,
    StepStats,
    make_evaluator,
    make_grad_fn,
    make_local_fit,
    make_optimizer,
    stack_rngs,
)

__all__ = [
    "BaseCallback",
    "Callback",
    "LocalFitResult",
    "MetricsLogger",
    "StepStats",
    "Trainer",
    "TrainingConfig",
    "make_evaluator",
    "make_grad_fn",
    "make_local_fit",
    "make_optimizer",
    "stack_rngs",
]
