"""Host-facing Trainer: the standalone single-client API.

The reference exposes ``TorchTrainer.train_epoch(model, loader, optimizer, epoch)`` driven
by user code (``nanofed/trainer/base.py:116-198``, ``examples/mnist/run_experiment.py:75-78``).
The equivalent here wraps the jitted ``local_fit``: one call runs all local epochs on
device, then per-epoch/per-batch metric arrays are replayed into callbacks.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

import jax
import numpy as np

from nanofed_tpu.core.types import ClientData, Params, PRNGKey
from nanofed_tpu.trainer.callbacks import Callback
from nanofed_tpu.trainer.config import TrainingConfig
from nanofed_tpu.trainer.local import GradFn, LocalFitResult, make_evaluator, make_local_fit
from nanofed_tpu.utils.logger import Logger, log_exec


class Trainer:
    """Single-client trainer over a functional model.

    >>> trainer = Trainer(model.apply, TrainingConfig(batch_size=64, local_epochs=2))
    >>> params, metrics = trainer.fit(params, client_data, rng)
    """

    def __init__(
        self,
        apply_fn: Callable[..., jax.Array],
        config: TrainingConfig,
        grad_fn: GradFn | None = None,
        callbacks: Sequence[Callback] = (),
    ) -> None:
        self.config = config
        self.callbacks = list(callbacks)
        # collect_batch_metrics feeds on_batch_end; force it on when batch callbacks exist.
        if self.callbacks and not config.collect_batch_metrics:
            config = dataclasses.replace(config, collect_batch_metrics=True)
            self.config = config
        self._local_fit = jax.jit(make_local_fit(apply_fn, config, grad_fn=grad_fn))
        self._evaluate = make_evaluator(apply_fn, batch_size=config.batch_size)

    @log_exec(block=True)
    def fit(
        self, params: Params, data: ClientData, rng: PRNGKey
    ) -> tuple[Params, dict[str, float]]:
        """Run all local epochs; returns (new_params, final-epoch metrics dict)."""
        result: LocalFitResult = self._local_fit(params, data, rng)
        self._replay_callbacks(result)
        m = result.metrics
        return result.params, {
            "loss": float(m.loss),
            "accuracy": float(m.accuracy),
            "samples_processed": int(m.samples),
        }

    def evaluate(self, params: Params, data: ClientData) -> dict[str, float]:
        out = self._evaluate(params, data)
        return {k: float(v) for k, v in out.items()}

    def _replay_callbacks(self, result: LocalFitResult) -> None:
        if not self.callbacks:
            return
        e_loss = np.asarray(result.epoch_loss)
        e_acc = np.asarray(result.epoch_accuracy)
        b_loss = np.asarray(result.batch_loss)
        log = Logger()
        with log.context("trainer"):
            for e in range(len(e_loss)):
                for cb in self.callbacks:
                    cb.on_epoch_start(e)
                if self.config.collect_batch_metrics:
                    for b in range(b_loss.shape[1]):
                        for cb in self.callbacks:
                            cb.on_batch_end(e, b, {"loss": float(b_loss[e, b])})
                for cb in self.callbacks:
                    cb.on_epoch_end(
                        e, {"loss": float(e_loss[e]), "accuracy": float(e_acc[e])}
                    )
                log.debug("epoch %d: loss=%.4f acc=%.4f", e, e_loss[e], e_acc[e])
