"""DP-SGD local training (Abadi et al. 2016).

Replaces ``PrivateTrainer`` (``nanofed/trainer/private.py:16-154``).  The reference clips
the *batch* gradient with ``clip_grad_norm_`` then adds noise (``private.py:54-86``) — a
weaker guarantee than the paper it cites.  Here clipping is **per-example**: per-example
gradients come from ``vmap`` of a single-example grad (free on TPU — it vectorizes into the
same MXU matmuls), each is clipped to C, the noised sum is averaged.  That is the actual
DP-SGD sensitivity argument, and it composes with the framework's client-``vmap``: a whole
DP federated round is a 2-level ``vmap`` inside one ``jit``.

Accounting is host-side: the number of noise events of a local fit is static
(steps × epochs), so the caller records them with ``record_local_fit`` after the compiled
call — the split the reference does stateful-inside-the-step
(``private.py:122`` → ``accountant.add_noise_event``).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from nanofed_tpu.core.types import Params, PyTree
from nanofed_tpu.privacy.accounting import BasePrivacyAccountant, PrivacySpent
from nanofed_tpu.privacy.config import (
    NoiseType,
    PrivacyConfig,
    require_gaussian_accounting,
)
from nanofed_tpu.privacy.noise import get_noise_generator, tree_noise
from nanofed_tpu.trainer.config import TrainingConfig
from nanofed_tpu.trainer.local import GradFn, StepStats, make_local_fit
from nanofed_tpu.utils.trees import tree_sq_norm


def make_dp_grad_fn(
    apply_fn: Callable[..., jax.Array],
    privacy: PrivacyConfig,
    compute_dtype: str | None = None,
) -> GradFn:
    """Per-example clip + noise gradient for ``make_local_fit``.

    For each real example i: g_i = ∇ nll_i, clipped to ``privacy.max_gradient_norm`` (C);
    padded examples are zeroed (their clipped gradient contributes nothing, preserving the
    sensitivity bound).  The update direction is (Σ clip(g_i) + N(0, (σC)² I)) / count —
    the Gaussian mechanism on a sum of L2-bounded terms (``trainer/private.py:54-86``
    capability, done per-example).
    """
    noise_gen = get_noise_generator(privacy.noise_type)
    C = privacy.max_gradient_norm
    sigma = privacy.noise_multiplier
    cdt = jnp.dtype(compute_dtype) if compute_dtype is not None else None

    def example_loss(params, x, y, rng):
        if cdt is not None:  # mixed precision; grads flow back to fp32 masters
            params = jax.tree.map(lambda p: p.astype(cdt), params)
            x = x.astype(cdt)
        logp = apply_fn(params, x[None], train=True, rng=rng)[0].astype(jnp.float32)
        nll = -logp[y]
        return nll, (logp,)

    def grad_fn(params: Params, xb, yb, mb, rng) -> tuple[PyTree, StepStats]:
        drop_rng, noise_rng = jax.random.split(rng)
        B = xb.shape[0]
        # Per-example dropout keys: each example's forward is an independent draw.
        ex_rngs = jax.random.split(drop_rng, B)
        (nll, (logp,)), grads = jax.vmap(
            jax.value_and_grad(example_loss, has_aux=True), in_axes=(None, 0, 0, 0)
        )(params, xb, yb, ex_rngs)

        # Clip each example's gradient to global norm C, then mask out padding.
        sq = jax.vmap(tree_sq_norm)(grads)  # [B]
        coef = jnp.minimum(1.0, C / jnp.maximum(jnp.sqrt(sq), 1e-12)) * mb  # [B]
        clipped_sum = jax.tree.map(
            lambda g: jnp.tensordot(coef.astype(g.dtype), g, axes=1), grads
        )

        noise = tree_noise(noise_rng, clipped_sum, sigma * C, noise_gen)
        count = mb.sum()
        denom = jnp.maximum(count, 1.0)
        noisy_mean = jax.tree.map(lambda s, n: (s + n) / denom, clipped_sum, noise)

        correct = ((jnp.argmax(logp, -1) == yb) * mb).sum()
        return noisy_mean, StepStats(loss_sum=(nll * mb).sum(), correct=correct, count=count)

    return grad_fn


def make_private_local_fit(
    apply_fn: Callable[..., jax.Array],
    config: TrainingConfig,
    privacy: PrivacyConfig,
    optimizer=None,
):
    """DP-SGD variant of ``make_local_fit`` (the ``PrivateTrainer`` equivalent).

    Identical signature/semantics to the non-private fit — drop-in for
    ``build_round_step`` — but every gradient step is privatized.
    """
    import dataclasses

    return make_local_fit(
        apply_fn,
        # The dtype is baked into the DP grad fn; clear it on the config so
        # make_local_fit's custom-grad_fn guard doesn't trip.
        dataclasses.replace(config, compute_dtype=None),
        grad_fn=make_dp_grad_fn(apply_fn, privacy, compute_dtype=config.compute_dtype),
        optimizer=optimizer,
    )


def local_fit_noise_events(config: TrainingConfig, data_capacity: int) -> int:
    """Number of noise events one private local fit performs (static: steps × epochs)."""
    steps = data_capacity // config.batch_size
    if config.max_batches is not None:
        steps = min(steps, config.max_batches)
    return steps * config.local_epochs


def record_local_fit(
    accountant: BasePrivacyAccountant,
    privacy: PrivacyConfig,
    config: TrainingConfig,
    data_capacity: int,
    num_samples: int,
) -> None:
    """Feed one client's local fit into ``accountant``.

    Sampling rate is the true subsampling probability q = batch_size / num_samples
    (clamped to 1), correcting the reference's ``samples / max_gradient_norm`` quirk
    (``accountant/gaussian.py:23-25``).
    """
    require_gaussian_accounting(privacy)
    q = min(1.0, config.batch_size / max(num_samples, 1))
    accountant.add_noise_event(
        privacy.noise_multiplier, q, count=local_fit_noise_events(config, data_capacity)
    )


def get_privacy_spent(accountant: BasePrivacyAccountant, privacy: PrivacyConfig) -> PrivacySpent:
    """Spend at the config's δ (parity: ``PrivateTrainer.get_privacy_spent``,
    ``private.py:136-144``)."""
    return accountant.get_privacy_spent(privacy.delta)


def validate_privacy_budget(
    accountant: BasePrivacyAccountant, privacy: PrivacyConfig
) -> bool:
    """True iff spend fits the configured (ε, δ) budget (parity:
    ``PrivateTrainer.validate_privacy_budget``, ``private.py:146-154``)."""
    return accountant.validate_budget(privacy.epsilon, privacy.delta)


__all__ = [
    "make_dp_grad_fn",
    "make_private_local_fit",
    "local_fit_noise_events",
    "record_local_fit",
    "get_privacy_spent",
    "validate_privacy_budget",
    "NoiseType",
    "PrivacyConfig",
]
