"""Per-round learning-rate schedules for federated training.

The reference has no scheduling at all — its clients run torch SGD at a fixed lr for
the whole federation (``nanofed/trainer/base.py``, ``examples/mnist/run_experiment.py``).
Round-wise decay is standard practice in the FL literature (e.g. Reddi et al. 2021,
"Adaptive Federated Optimization", decays client lr across rounds) and measurably
matters here: the 100-client digits benchmark only crossed the 97% bar once the local
optimizer was tuned (``runs/accuracy_digits_100c_r05.json``).

TPU-first design: the schedule must not recompile the round program.  A naive
per-round ``TrainingConfig(learning_rate=...)`` is a *static* jit argument — every
round would re-trace and re-compile (~20-40 s each on a TPU).  Instead the round step
takes a traced ``lr_scale`` scalar (see ``build_round_step``): one compiled program,
the scale streams in as data.  These helpers compute that scale on the host — pure,
cheap, resume-safe (a function of the round index only, so a resumed run continues
the schedule exactly).

``lr_scale`` multiplies each local SGD *step* (the full optax update, after momentum
accumulation), which is the standard per-round-decay formulation: equivalent to
running that round at ``learning_rate * lr_scale``.
"""

from __future__ import annotations

import math

SCHEDULES = ("constant", "cosine", "linear", "step")


def lr_schedule_scale(
    schedule: str,
    round_id: int,
    total_rounds: int,
    *,
    min_factor: float = 0.0,
    decay_every: int = 10,
    gamma: float = 0.5,
) -> float:
    """The lr multiplier for ``round_id`` (0-based) of ``total_rounds``.

    - ``constant``: 1.0 forever.
    - ``cosine``: half-cosine from 1.0 at round 0 toward ``min_factor``
      (Loshchilov & Hutter 2017, without restarts).
    - ``linear``: straight line from 1.0 toward ``min_factor`` over the run.
    - ``step``: multiply by ``gamma`` every ``decay_every`` rounds (classic staircase);
      never below ``min_factor``.

    Decay progress is ``round_id / total_rounds`` — the LAST trained round sits one
    step above the floor, never on it: with the default ``min_factor=0.0``, landing
    exactly on the floor would make the final round a full-cost silent no-op (every
    client trains, scale 0 zeroes every update).  Rounds past ``total_rounds`` (e.g.
    a resumed run extended beyond its original plan) hold the terminal value rather
    than extrapolating — for every schedule, step included.
    """
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown lr schedule {schedule!r}; choose from {SCHEDULES}")
    if not 0.0 <= min_factor <= 1.0:
        raise ValueError("min_factor must be in [0, 1]")
    if schedule == "constant":
        return 1.0
    if schedule == "step":
        if decay_every < 1:
            raise ValueError("decay_every must be >= 1")
        if not 0.0 < gamma <= 1.0:
            raise ValueError("gamma must be in (0, 1]")
        effective = min(round_id, max(total_rounds - 1, 0))
        return max(min_factor, gamma ** (effective // decay_every))
    # cosine / linear interpolate over the run; a 1-round run has no room to decay.
    if total_rounds <= 1:
        return 1.0
    frac = min(round_id / total_rounds, 1.0)
    if schedule == "cosine":
        return min_factor + (1.0 - min_factor) * 0.5 * (1.0 + math.cos(math.pi * frac))
    return 1.0 + (min_factor - 1.0) * frac  # linear


def lr_schedule_scales(
    schedule: str,
    first_round: int,
    num_rounds: int,
    total_rounds: int,
    *,
    min_factor: float = 0.0,
    decay_every: int = 10,
    gamma: float = 0.5,
) -> list[float]:
    """The ``[R]`` scale vector for rounds ``first_round .. first_round+num_rounds-1``
    — what a fused round block (``parallel.multi_round``) consumes as its traced
    per-round schedule array.  Element r is exactly ``lr_schedule_scale`` of that
    round, so a fused run follows the schedule identically to a single-round run."""
    return [
        lr_schedule_scale(
            schedule, first_round + i, total_rounds,
            min_factor=min_factor, decay_every=decay_every, gamma=gamma,
        )
        for i in range(num_rounds)
    ]
