"""Binary wire format for model parameters.

The reference ships weights as nested Python float lists in JSON — ~9x size inflation and
an O(params) Python encode/decode loop per client per round (``nanofed/communication/http/
server.py:140-149``, ``client.py:147-156``, SURVEY.md §5).  Here the wire format is an
in-memory ``.npz`` archive in the exact checkpoint layout ('/'-joined pytree paths,
dtype-tagged bfloat16/ml_dtypes leaves — see ``persistence.serialization``): binary,
compressed, and a captured payload IS a loadable checkpoint.

With a template, decoding validates leaf names, shapes, AND dtypes — this is the
server's structural-validation barrier for incoming updates.
"""

from __future__ import annotations

import io

import jax
import numpy as np

from nanofed_tpu.core.exceptions import CheckpointError, NanoFedError
from nanofed_tpu.core.types import Params
from nanofed_tpu.persistence.serialization import (
    flatten_to_arrays,
    from_storable,
    unflatten_from_arrays,
)


def encode_params(params: Params) -> bytes:
    """Params pytree -> compressed npz bytes.

    Committed device-sharded leaves (e.g. model-sharded params off a 2-D
    ``clients x model`` mesh) are gathered to host arrays FIRST: ``np.asarray``
    on a sharded ``jax.Array`` either raises or silently assembles per-shard
    copies depending on layout, while ``jax.device_get`` performs the one
    well-defined gather for every leaf of the tree."""
    params = jax.device_get(params)
    try:
        arrays = flatten_to_arrays(params)
    except CheckpointError as e:
        raise NanoFedError(str(e)) from e
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


def decode_params(payload: bytes, like: Params | None = None) -> Params:
    """npz bytes -> params pytree (template-structured + validated when ``like`` given)."""
    with np.load(io.BytesIO(payload)) as data:
        arrays = dict(from_storable(name, data[name]) for name in data.files)
    try:
        return unflatten_from_arrays(arrays, like, source="payload")
    except CheckpointError as e:
        raise NanoFedError(str(e)) from e


# ---------------------------------------------------------------------------
# Quantized update compression (q8-delta wire encoding)
# ---------------------------------------------------------------------------
#
# The dominant federation bandwidth cost is the client -> server update.  Instead of
# shipping full float32 params, the client ships its round DELTA (params - global; the
# client just fetched the global, so both sides hold the base) quantized to int8 with a
# per-leaf absmax scale and STOCHASTIC rounding:
#
#     q = clip(round_stochastic(x / s), -127, 127),   s = max|x| / 127  per leaf
#
# Stochastic rounding makes the dequantized delta an UNBIASED estimator of the true
# delta (E[s*q] = x), so FedAvg over many clients averages the rounding noise away
# instead of accumulating a bias — the standard QSGD-style argument (Alistarh et al.
# 2017).  4x fewer payload bytes before npz deflate; deltas also compress better than
# params (small dynamic range).  The reference has no compression at all (JSON float
# lists, ~9x inflation: ``nanofed/communication/http/server.py:140-149``).

#: Key namespace for quantized-leaf npz entries: "<path>::q8q" holds the int8 payload,
#: "<path>::q8s" its float32 absmax scale.  The "::" pattern cannot occur in '/'-joined
#: pytree paths, so plain and quantized payloads cannot be confused.  Leaf dtypes are
#: NOT encoded on the wire — the decoder casts to the TEMPLATE's dtypes, so a bfloat16
#: model federates with the same payload format as a float32 one.
Q8_QUANT_TAG = "::q8q"
Q8_SCALE_TAG = "::q8s"

#: Wire value for the X-NanoFed-Encoding header selecting this codec.
ENCODING_Q8_DELTA = "q8-delta"


def encode_delta_q8(delta: Params, seed: int | None = None) -> bytes:
    """Round delta pytree -> compressed npz of int8 leaves + per-leaf scales.

    ``seed`` fixes the stochastic-rounding draws (tests, reproducible clients); None
    draws from OS entropy.  All-zero leaves encode with scale 0 and decode exactly.
    """
    from nanofed_tpu.persistence.serialization import tree_flatten_with_names

    named, _ = tree_flatten_with_names(delta)
    rng = np.random.default_rng(seed)
    arrays: dict[str, np.ndarray] = {}
    for name, leaf in named:
        x32 = np.asarray(leaf, dtype=np.float32)
        absmax = float(np.max(np.abs(x32))) if x32.size else 0.0
        scale = absmax / 127.0
        if scale == 0.0:
            q = np.zeros(x32.shape, dtype=np.int8)
        else:
            scaled = x32 / scale
            # Stochastic rounding: floor + Bernoulli(frac) — E[q] = scaled exactly.
            floor = np.floor(scaled)
            frac = scaled - floor
            q = floor + (rng.random(scaled.shape, dtype=np.float32) < frac)
            q = np.clip(q, -127, 127).astype(np.int8)
        arrays[f"{name}{Q8_QUANT_TAG}"] = q
        arrays[f"{name}{Q8_SCALE_TAG}"] = np.float32(scale)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


def decode_delta_q8(payload: bytes, like: Params) -> Params:
    """q8 npz bytes -> dequantized delta pytree in the template's structure/dtypes.

    A template is REQUIRED (unlike :func:`decode_params`): the delta only means
    anything relative to a known global model, the server must never buffer an
    unvalidated quantized payload, and the template supplies each leaf's target dtype
    (dequantization happens in float32; the result is cast to the template — a
    bfloat16 model federates over the same wire format).
    """
    from nanofed_tpu.persistence.serialization import tree_flatten_with_names

    with np.load(io.BytesIO(payload)) as data:
        quants: dict[str, np.ndarray] = {}
        scales: dict[str, np.float32] = {}
        for key in data.files:
            if key.endswith(Q8_QUANT_TAG):
                quants[key[: -len(Q8_QUANT_TAG)]] = data[key].astype(np.float32)
            elif key.endswith(Q8_SCALE_TAG):
                scales[key[: -len(Q8_SCALE_TAG)]] = data[key]
            else:
                raise NanoFedError(
                    f"q8 payload contains non-q8 entry {key!r} — plain and "
                    "quantized encodings must not be mixed in one payload"
                )
    unscaled = set(quants) ^ set(scales)
    if unscaled:
        raise NanoFedError(
            f"q8 payload has mismatched quant/scale entries for {sorted(unscaled)[:5]}"
        )
    template_dtypes = {
        name: np.asarray(leaf).dtype for name, leaf in tree_flatten_with_names(like)[0]
    }
    arrays = {
        name: (q * scales[name]).astype(template_dtypes.get(name, np.float32))
        for name, q in quants.items()
    }
    try:
        return unflatten_from_arrays(arrays, like, source="q8 payload")
    except CheckpointError as e:
        raise NanoFedError(str(e)) from e


def reconstruct_q8(base: Params, payload: bytes) -> Params:
    """q8-delta bytes + base params -> full params, in ONE place.

    Client (signing side) and server (verifying side) must compute the identical
    float32 arithmetic or signature verification breaks for every compressed update —
    this shared helper makes that invariant structural rather than a convention
    spread across two modules.  The result is float32 regardless of the base's dtype
    (both sides upcast identically); callers needing the base's dtype cast after.
    """
    delta = decode_delta_q8(payload, like=base)
    return jax.tree.map(
        lambda g, d: np.asarray(g, np.float32) + np.asarray(d, np.float32),
        base, delta,
    )


# ---------------------------------------------------------------------------
# Top-k sparsification + int8 (topk8-delta wire encoding)
# ---------------------------------------------------------------------------
#
# One step beyond q8: ship only each leaf's top-``fraction`` coordinates by
# magnitude (uint32 indices + int8 values + scale).  Round deltas are heavy-tailed —
# a few coordinates carry most of the mass — so at fraction=0.05 the payload is
# ~25-60x smaller than full float params while the retained mass stays high.  Unlike
# q8's stochastic rounding, top-k selection is BIASED (the dropped tail is always
# lost); the standard fix is ERROR FEEDBACK (Seide et al. 2014; Karimireddy et al.
# 2019): the client accumulates what it didn't send and adds it to the next round's
# delta, so every coordinate eventually ships.  ``HTTPClient`` owns that residual
# state; the codec stays stateless.

Q8_INDEX_TAG = "::tk8i"

#: Wire value for the X-NanoFed-Encoding header selecting top-k + int8.
ENCODING_TOPK8 = "topk8-delta"


def encode_delta_topk8(
    delta: Params, fraction: float = 0.05, seed: int | None = None
) -> bytes:
    """Round delta pytree -> npz of per-leaf (uint32 indices, int8 values, scale).

    ``fraction`` of each leaf's coordinates (by magnitude, at least 1) are kept;
    kept values are stochastically rounded to int8 exactly like ``encode_delta_q8``
    (the scale is the absmax of the KEPT values, so sparsity tightens quantization
    too).  Selection is per leaf — a layer whose delta is globally small still ships
    its locally-largest coordinates, which matters for calibration-sensitive leaves
    like biases.
    """
    if not 0.0 < fraction <= 1.0:
        raise NanoFedError(f"topk fraction must be in (0, 1], got {fraction}")
    from nanofed_tpu.persistence.serialization import tree_flatten_with_names

    named, _ = tree_flatten_with_names(delta)
    rng = np.random.default_rng(seed)
    arrays: dict[str, np.ndarray] = {}
    for name, leaf in named:
        x = np.asarray(leaf, dtype=np.float32).ravel()
        k = max(1, int(np.ceil(fraction * x.size)))
        idx = np.argpartition(np.abs(x), -k)[-k:].astype(np.uint32)
        idx.sort()  # deterministic order + deflate-friendlier index stream
        vals = x[idx]
        absmax = float(np.max(np.abs(vals))) if vals.size else 0.0
        scale = absmax / 127.0
        if scale == 0.0:
            q = np.zeros(vals.shape, dtype=np.int8)
        else:
            scaled = vals / scale
            floor = np.floor(scaled)
            q = floor + (rng.random(scaled.shape, dtype=np.float32)
                         < (scaled - floor))
            q = np.clip(q, -127, 127).astype(np.int8)
        arrays[f"{name}{Q8_INDEX_TAG}"] = idx
        arrays[f"{name}{Q8_QUANT_TAG}"] = q
        arrays[f"{name}{Q8_SCALE_TAG}"] = np.float32(scale)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


def decode_delta_topk8(payload: bytes, like: Params) -> Params:
    """topk8 npz bytes -> DENSE delta pytree (zeros off the shipped coordinates),
    template-structured with the template's dtypes.  Refuses out-of-range indices —
    the server must never scatter an attacker-chosen index past a leaf's extent."""
    from nanofed_tpu.persistence.serialization import tree_flatten_with_names

    with np.load(io.BytesIO(payload)) as data:
        idxs: dict[str, np.ndarray] = {}
        quants: dict[str, np.ndarray] = {}
        scales: dict[str, np.float32] = {}
        for key in data.files:
            if key.endswith(Q8_INDEX_TAG):
                idxs[key[: -len(Q8_INDEX_TAG)]] = data[key]
            elif key.endswith(Q8_QUANT_TAG):
                quants[key[: -len(Q8_QUANT_TAG)]] = data[key].astype(np.float32)
            elif key.endswith(Q8_SCALE_TAG):
                scales[key[: -len(Q8_SCALE_TAG)]] = data[key]
            else:
                raise NanoFedError(
                    f"topk8 payload contains non-topk8 entry {key!r}"
                )
    if not (set(idxs) == set(quants) == set(scales)):
        raise NanoFedError("topk8 payload has mismatched index/quant/scale entries")
    template = dict(tree_flatten_with_names(like)[0])
    arrays: dict[str, np.ndarray] = {}
    for name, idx in idxs.items():
        if name not in template:
            raise NanoFedError(f"topk8 payload leaf '{name}' not in template")
        leaf = np.asarray(template[name])
        if idx.size != quants[name].size:
            raise NanoFedError(f"topk8 leaf '{name}': index/value length mismatch")
        if idx.size and int(idx.max()) >= leaf.size:
            raise NanoFedError(
                f"topk8 leaf '{name}': index {int(idx.max())} out of range for "
                f"size {leaf.size}"
            )
        dense = np.zeros(leaf.size, np.float32)
        dense[idx.astype(np.int64)] = quants[name] * scales[name]
        arrays[name] = dense.reshape(leaf.shape).astype(leaf.dtype)
    try:
        return unflatten_from_arrays(arrays, like, source="topk8 payload")
    except CheckpointError as e:
        raise NanoFedError(str(e)) from e


def reconstruct_topk8(base: Params, payload: bytes) -> Params:
    """topk8 bytes + base -> full params; the signing/verifying counterpart of
    :func:`reconstruct_q8` (same shared-arithmetic invariant)."""
    delta = decode_delta_topk8(payload, like=base)
    return jax.tree.map(
        lambda g, d: np.asarray(g, np.float32) + np.asarray(d, np.float32),
        base, delta,
    )
