"""Binary wire format for model parameters.

The reference ships weights as nested Python float lists in JSON — ~9x size inflation and
an O(params) Python encode/decode loop per client per round (``nanofed/communication/http/
server.py:140-149``, ``client.py:147-156``, SURVEY.md §5).  Here the wire format is an
in-memory ``.npz`` archive keyed by '/'-joined pytree paths: binary, compressed, zero-copy
into numpy on receive, and identical to the checkpoint format so a captured payload IS a
loadable checkpoint.
"""

from __future__ import annotations

import io
from typing import Any

import numpy as np

from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.core.types import Params
from nanofed_tpu.utils.trees import tree_flatten_with_names


#: Separator tagging leaves whose dtype npz cannot represent natively (bfloat16 and the
#: other ml_dtypes register as numpy void kinds and would silently degrade to raw bytes).
_DTYPE_TAG = "::dtype::"


def _to_storable(name: str, arr: np.ndarray) -> tuple[str, np.ndarray]:
    if arr.dtype.kind == "V":  # ml_dtypes (bfloat16, fp8, ...)
        raw = np.frombuffer(arr.tobytes(), dtype=np.uint8).reshape(
            arr.shape + (arr.dtype.itemsize,)
        )
        return f"{name}{_DTYPE_TAG}{arr.dtype.name}", raw
    return name, arr


def _from_storable(name: str, arr: np.ndarray) -> tuple[str, np.ndarray]:
    if _DTYPE_TAG in name:
        name, dtype_name = name.split(_DTYPE_TAG, 1)
        import ml_dtypes  # noqa: F401  (registers the named dtypes with numpy)

        dtype = np.dtype(dtype_name)
        arr = np.frombuffer(arr.tobytes(), dtype=dtype).reshape(arr.shape[:-1])
    return name, arr


def encode_params(params: Params) -> bytes:
    """Params pytree -> compressed npz bytes."""
    named, _ = tree_flatten_with_names(params)
    arrays = dict(_to_storable(name, np.asarray(leaf)) for name, leaf in named)
    if len(arrays) != len(named):
        raise NanoFedError("pytree has duplicate leaf path names; cannot encode")
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


def decode_params(payload: bytes, like: Params | None = None) -> Params:
    """npz bytes -> params pytree (template-structured when ``like`` is given)."""
    import jax

    with np.load(io.BytesIO(payload)) as data:
        arrays = dict(_from_storable(name, data[name]) for name in data.files)
    if like is None:
        return _nest(arrays)
    named, treedef = tree_flatten_with_names(like)
    leaves = []
    for name, leaf in named:
        if name not in arrays:
            raise NanoFedError(f"payload is missing leaf '{name}' for the given template")
        arr = arrays[name]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise NanoFedError(
                f"shape mismatch for '{name}': payload {arr.shape} vs template "
                f"{np.shape(leaf)}"
            )
        leaves.append(arr)
    return jax.tree.unflatten(treedef, leaves)


def _nest(flat: dict[str, np.ndarray]) -> dict[str, Any]:
    out: dict[str, Any] = {}
    for name, arr in flat.items():
        node = out
        parts = name.split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = arr
    return out
