"""Binary wire format for model parameters.

The reference ships weights as nested Python float lists in JSON — ~9x size inflation and
an O(params) Python encode/decode loop per client per round (``nanofed/communication/http/
server.py:140-149``, ``client.py:147-156``, SURVEY.md §5).  Here the wire format is an
in-memory ``.npz`` archive in the exact checkpoint layout ('/'-joined pytree paths,
dtype-tagged bfloat16/ml_dtypes leaves — see ``persistence.serialization``): binary,
compressed, and a captured payload IS a loadable checkpoint.

With a template, decoding validates leaf names, shapes, AND dtypes — this is the
server's structural-validation barrier for incoming updates.
"""

from __future__ import annotations

import io

import numpy as np

from nanofed_tpu.core.exceptions import CheckpointError, NanoFedError
from nanofed_tpu.core.types import Params
from nanofed_tpu.persistence.serialization import (
    flatten_to_arrays,
    from_storable,
    unflatten_from_arrays,
)


def encode_params(params: Params) -> bytes:
    """Params pytree -> compressed npz bytes."""
    try:
        arrays = flatten_to_arrays(params)
    except CheckpointError as e:
        raise NanoFedError(str(e)) from e
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


def decode_params(payload: bytes, like: Params | None = None) -> Params:
    """npz bytes -> params pytree (template-structured + validated when ``like`` given)."""
    with np.load(io.BytesIO(payload)) as data:
        arrays = dict(from_storable(name, data[name]) for name in data.files)
    try:
        return unflatten_from_arrays(arrays, like, source="payload")
    except CheckpointError as e:
        raise NanoFedError(str(e)) from e
