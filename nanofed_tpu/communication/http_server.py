"""HTTP federation server (real-network mode).

Capability parity with ``HTTPServer`` (``nanofed/communication/http/server.py:38-340``):
``GET /model`` serves the current global parameters, ``POST /update`` buffers client
updates for the current round (stale rounds are rejected with 400, ``server.py:260-272``),
``GET /status`` exposes live round/update counts, and ``stop_training`` flips the
termination flag clients poll (``server.py:313-317``).

Differences by design (SURVEY.md §7 stage 9):
* Payloads are binary npz (see ``codec``), not JSON float lists — ~9x smaller, no Python
  per-element loops.
* No ``set_coordinator`` back-pointer / private ``_updates`` reach-in (the reference's
  circular-dependency workaround, ``server.py:123-125``, ``coordinator.py:218-293``): the
  server owns the buffer and exposes ``num_updates`` / ``drain_updates``.
* The simulator path (``nanofed_tpu.parallel``) never touches this module; it exists for
  true cross-device federation.

Since the transport/session split (multi-tenant federation service), this class is
the per-tenant SESSION: routing, tenant resolution, and lifecycle live in
``communication.transport.HTTPTransport``, while everything here — round/version
buffers, admission counters, submit-key dedup windows, secure-aggregation rosters,
chaos application — is per-session state a shared transport multiplexes N of.  A
standalone ``HTTPServer`` (no ``transport=``) owns a private transport and behaves
byte-identically to the pre-split server.
"""

from __future__ import annotations

import asyncio
import json
from collections import deque
from contextlib import nullcontext
from dataclasses import dataclass
from typing import Any, Iterable

import jax
from aiohttp import web

from nanofed_tpu.communication.codec import (
    ENCODING_Q8_DELTA,
    ENCODING_TOPK8,
    decode_params,
    encode_params,
)
from nanofed_tpu.communication.transport import (
    HTTPTransport,
    read_body_bounded,
)
from nanofed_tpu.core.types import ModelUpdate, Params
from nanofed_tpu.observability.registry import MetricsRegistry, get_registry
from nanofed_tpu.observability.tracing import TraceContext, parse_trace
from nanofed_tpu.utils.clock import SYSTEM_CLOCK, Clock
from nanofed_tpu.utils.dates import get_current_time
from nanofed_tpu.utils.logger import Logger

MAX_REQUEST_SIZE = 100 * 1024 * 1024  # parity: 100 MB cap, server.py:72

#: Idempotency keys remembered per client: a retry storm's duplicates must
#: dedupe against a WINDOW of recent submits (a client retries at most a
#: handful of logical submits concurrently), bounded so memory stays O(clients).
SUBMIT_KEY_WINDOW = 16

#: Metadata travels in headers; the body is pure npz bytes.
HEADER_CLIENT = "X-NanoFed-Client"
HEADER_ROUND = "X-NanoFed-Round"
HEADER_METRICS = "X-NanoFed-Metrics"
HEADER_STATUS = "X-NanoFed-Status"
HEADER_SIGNATURE = "X-NanoFed-Signature"  # base64 RSA-PSS signature of the npz params
HEADER_SECAGG = "X-NanoFed-SecAgg"  # "masked" flags a pairwise-masked uint32 payload
HEADER_ENCODING = "X-NanoFed-Encoding"  # absent/"npz" = full params; "q8-delta" = codec
HEADER_SUBMIT = "X-NanoFed-Submit"  # idempotency key: one per LOGICAL submit, rides retries
HEADER_TIER = "X-NanoFed-Tier"  # fleet mode: which DeviceTier this client belongs to
HEADER_TRACE = "X-NanoFed-Trace"  # W3C-style trace context: 00-<trace>-<span>-<flags>


@dataclass(frozen=True)
class ServerEndpoints:
    """Parity: ``ServerEndpoints`` (``server.py:29-35``), plus the secure-aggregation
    roster endpoints (no reference equivalent — its SecAgg never touches the wire)."""

    model: str = "/model"
    update: str = "/update"
    status: str = "/status"
    test: str = "/test"
    metrics: str = "/metrics"
    secagg_register: str = "/secagg/register"
    secagg_roster: str = "/secagg/roster"
    secagg_shares: str = "/secagg/shares"
    secagg_unmask: str = "/secagg/unmask"


class HTTPServer:
    """Serves the global model and buffers client updates for the round engine."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8080,
        endpoints: ServerEndpoints | None = None,
        max_request_size: int = MAX_REQUEST_SIZE,
        client_keys: dict[str, bytes] | None = None,
        require_signatures: bool = False,
        staleness_window: int = 0,
        registry: MetricsRegistry | None = None,
        max_inflight: int | None = None,
        retry_after_s: float = 0.25,
        read_timeout_s: float = 30.0,
        chaos: Any | None = None,
        clock: Clock | None = None,
        ingest: Any | None = None,
        transport: HTTPTransport | None = None,
        tenant: str | None = None,
        fleet: Any | None = None,
        tracer: Any | None = None,
    ) -> None:
        """``client_keys`` maps client_id -> PEM public key.  With
        ``require_signatures=True`` every update must carry a valid RSA-PSS signature
        (``HEADER_SIGNATURE``) from a registered client or it is rejected with 403 —
        this is where the signing capability (``nanofed_tpu.security.signing``, parity
        ``nanofed/server/validation.py:138-212``) is enforced on the wire.

        ``staleness_window=0`` (default) is the strict synchronous protocol: an
        update is accepted only for the CURRENT round.  ``staleness_window=W > 0``
        enables asynchronous federation (FedBuff, Nguyen et al. 2022): updates based
        on any of the last ``W`` published versions are accepted and buffered with
        their base round, the buffer SURVIVES ``publish_model`` (a straggler's
        update for version v stays valid while v >= current - W), and compressed
        deltas reconstruct against the version the client actually fetched.  One
        buffered update per client (latest wins — a fast client's newer update
        supersedes its unaggregated older one).

        ``registry`` (default: the process-wide one) receives this server's wire
        metrics — bytes tx/rx per endpoint, update acceptances/rejections by reason,
        secure-aggregation evictions — and is what ``GET /metrics`` renders in
        Prometheus text format.

        ``max_inflight`` is the admission-control bound: at most that many
        update bodies may be in the read/decode pipeline at once; excess
        submits are answered ``429`` + ``Retry-After: retry_after_s`` WITHOUT
        reading their bodies, so overload degrades to client backoff instead
        of unbounded memory growth and event-loop starvation (None = no bound,
        the pre-admission-control behavior).  ``read_timeout_s`` bounds how
        long any request BODY may take to arrive (``client_max_size`` bounds
        its size): a peer trickling bytes can no longer hold a handler — and
        its admission slot — open forever; a stalled read is answered 408.

        ``chaos`` (a ``nanofed_tpu.faults.ChaosSchedule``, duck-typed to keep
        this module dependency-light) injects wire faults at the server
        boundary: per the seeded plan, an update request is severed before
        handling (``drop``), severed after handling but before its response
        (``ack_drop`` — the lost-ACK case idempotent submit keys exist for),
        or delayed.  ``clock`` injects the time source for those delays.

        ``ingest`` (a ``nanofed_tpu.ingest.IngestConfig``) switches PLAIN
        update submits to the batched device-resident path: decoded deltas
        accumulate into a preallocated FedBuff-style device buffer and ONE
        jit-compiled batched reduce fires per drain instead of one
        aggregation per client; npz decode/verify moves into the pipeline's
        BOUNDED worker pool, and a full buffer answers 429 + Retry-After
        (the same backpressure contract as ``max_inflight``) instead of
        queueing unboundedly.  Masked (secure-aggregation) submits keep
        their own buffer — masked vectors cannot be batch-reduced before
        unmasking — but their CPU-bound decode rides the same bounded pool.
        The idempotent-key, stale-round, and signature contracts are
        identical on both paths.

        ``transport`` mounts this session on a SHARED
        :class:`~nanofed_tpu.communication.transport.HTTPTransport` under the
        given ``tenant`` name (the multi-tenant federation service's shape:
        one listener, N per-tenant sessions; the transport resolves tenant
        identity from the ``/t/<tenant>`` path prefix or the
        ``X-NanoFed-Tenant`` header and this session never sees another
        tenant's requests).  ``transport=None`` (the single-tenant default)
        creates a PRIVATE transport and mounts this session as its default —
        the pre-split wire behavior, byte-identical.  On a shared transport
        the transport's lifecycle and ``client_max_size`` govern;
        ``host``/``port``/``max_request_size`` here are ignored and
        ``start()`` must not be called (the service starts the transport
        once).

        ``fleet`` (a ``nanofed_tpu.fleet.FleetGateway``, duck-typed) turns on
        heterogeneous-fleet mode: ``GET /model`` with an ``X-NanoFed-Tier``
        header serves that tier's low-rank published view instead of the
        dense global, and tier-tagged submits decode by the TIER's codec
        (derived from the profile — a mismatching explicit encoding header is
        a 400) into flat dense-delta rows for the ingest buffer.  Fleet mode
        REQUIRES ``ingest`` (tier rows only exist in the batched flat path)
        and excludes ``require_signatures`` (signatures cover dense-params
        reconstructions, which tier submits never materialize) and masked
        SecAgg submits (rejected 400 per request).  Untagged requests behave
        exactly as without a fleet — mixed cohorts are first-class.

        ``tracer`` (a ``nanofed_tpu.observability.SpanTracer``, duck-typed)
        opens a ``submit-decode`` span around each admitted submit's
        offloaded decode, carrying the request's ``X-NanoFed-Trace`` trace id
        as an attribute — the wire-to-mesh hop of the distributed-tracing
        story.  ``tracer=None`` (default) records nothing; tracing is
        observability, never admission control."""
        if staleness_window < 0:
            raise ValueError("staleness_window must be >= 0")
        if fleet is not None and ingest is None:
            raise ValueError(
                "fleet mode requires ingest= (tier submits decode into the "
                "batched flat ingest buffer; there is no per-update path)"
            )
        if fleet is not None and require_signatures:
            raise ValueError(
                "fleet mode cannot combine with require_signatures: tier "
                "submits never reconstruct the dense params tree a signature "
                "would cover"
            )
        if max_inflight is not None and max_inflight < 0:
            raise ValueError("max_inflight must be >= 0 (0 rejects every submit)")
        if read_timeout_s <= 0:
            raise ValueError("read_timeout_s must be > 0")
        self.host = host
        self.port = port
        self.endpoints = endpoints or ServerEndpoints()
        self.client_keys = dict(client_keys or {})
        self.require_signatures = require_signatures
        self.staleness_window = staleness_window
        self.max_inflight = max_inflight
        self.retry_after_s = retry_after_s
        self.read_timeout_s = read_timeout_s
        self._chaos = chaos
        self._clock = clock or SYSTEM_CLOCK
        self.ingest = ingest
        self.fleet = fleet
        self._tracer = tracer
        # Built lazily at the first publish_model (the params template fixes
        # the buffer's flat size); every mutation happens under self._lock.
        self._ingest_pipeline: Any | None = None
        self._log = Logger()
        self._lock = asyncio.Lock()
        self._inflight = 0  # submits currently in the read/decode pipeline
        # client -> recent (submit key, fingerprint) pairs (see _submit_fingerprint)
        self._seen_submits: dict[str, deque[tuple[str, str]]] = {}
        self._updates: dict[str, ModelUpdate] = {}
        self._params: Params | None = None
        self._params_bytes: bytes | None = None
        self._round = 0
        self._version_params: dict[int, Params] = {}  # async mode: base history
        self._training_active = True
        # Secure-aggregation state: a roster of (X25519 public key, sample count) per
        # client, opened by the round engine, and a separate buffer for masked payloads
        # (they are uniform uint32 vectors, not decodable params).
        self._secagg_expected: int | None = None
        self._secagg_window = False  # exact cohort (legacy) vs min+close window
        self._secagg_max: int | None = None
        self._secagg_threshold_for: Any | None = None  # n -> Shamir threshold, at freeze
        self._secagg_threshold: int | None = None
        self._secagg_closed = False
        self._secagg_session: str = ""
        self._secagg_backend: str | None = None  # pinned by the first enrollment
        self._secagg_roster: dict[str, dict[str, Any]] = {}
        self._masked_updates: dict[str, tuple[Any, dict[str, Any]]] = {}
        # Dropout-tolerant mode (all PER-ROUND, cleared on publish_model — Bonawitz §4
        # is a per-execution protocol, so every round distributes fresh ephemeral mask
        # keys and sealed Shamir share blobs): the server routes blobs it cannot read,
        # collects each participant's round mask public key, and runs the unmask
        # request/reveal exchange.  Clients declared dropped are EVICTED from the
        # active cohort so later rounds stop waiting for them.
        self._secagg_evicted: set[str] = set()
        self._round_share_epks: dict[str, bytes] = {}
        self._round_share_bhs: dict[str, bytes] = {}  # sha256 self-seed commitments
        self._round_share_blobs: dict[str, dict[str, str]] = {}  # recipient -> sender -> blob
        self._round_share_senders: dict[str, dict[str, str]] = {}  # sender -> its deposit
        self._unmask_request: dict[str, Any] | None = None
        self._unmask_reveals: dict[str, dict[str, Any]] = {}
        # Wire metrics (observability subsystem): counted at the handler level so
        # every scrape of /metrics reflects what actually crossed this server's wire.
        self.metrics_registry = registry or get_registry()
        self._m_bytes_rx = self.metrics_registry.counter(
            "nanofed_bytes_received_total",
            "Request body bytes received, by endpoint", labels=("endpoint",),
        )
        self._m_bytes_tx = self.metrics_registry.counter(
            "nanofed_bytes_sent_total",
            "Response body bytes served, by endpoint", labels=("endpoint",),
        )
        self._m_updates = self.metrics_registry.counter(
            "nanofed_updates_total",
            "Client update submissions by kind (plain/masked) and result",
            labels=("kind", "result"),
        )
        self._m_evictions = self.metrics_registry.counter(
            "nanofed_secagg_evictions_total",
            "Clients evicted from the secure-aggregation cohort",
        )
        self._m_429 = self.metrics_registry.counter(
            "nanofed_http_429_total",
            "Requests shed by admission control (429 + Retry-After), by endpoint",
            labels=("endpoint",),
        )
        self._m_read_timeouts = self.metrics_registry.counter(
            "nanofed_read_timeouts_total",
            "Request bodies that failed to arrive within read_timeout_s (408)",
        )
        # Fleet mode: per-tier wire accounting — the aggregate-wire-bytes
        # story of docs/fleet.md is read straight off these.
        self._m_fleet_bytes = self.metrics_registry.counter(
            "nanofed_fleet_bytes_total",
            "Fleet-mode body bytes by tier and direction (rx=submit, tx=model)",
            labels=("tier", "direction"),
        )
        self._m_fleet_updates = self.metrics_registry.counter(
            "nanofed_fleet_updates_total",
            "Fleet-mode tier submits by tier and result",
            labels=("tier", "result"),
        )
        # Logical-path route table: the transport resolves the TENANT and
        # hands this session the endpoint path; everything behind it —
        # admission, dedup windows, chaos, quota state — is session-scoped.
        ep = self.endpoints
        self._routes: dict[tuple[str, str], Any] = {
            ("GET", ep.model): self._handle_get_model,
            ("POST", ep.update): self._handle_submit_update,
            ("GET", ep.status): self._handle_status,
            ("GET", ep.test): self._handle_test,
            ("GET", ep.metrics): self._handle_metrics,
            ("POST", ep.secagg_register): self._handle_secagg_register,
            ("GET", ep.secagg_roster): self._handle_secagg_roster,
            ("POST", ep.secagg_shares): self._handle_secagg_shares_post,
            ("GET", ep.secagg_shares): self._handle_secagg_shares_get,
            ("GET", ep.secagg_unmask): self._handle_unmask_get,
            ("POST", ep.secagg_unmask): self._handle_unmask_post,
        }
        self.tenant = tenant
        self._owns_transport = transport is None
        if transport is None and tenant is not None:
            # A tenant name without a shared transport would silently mount
            # as a private transport's DEFAULT session — /t/<name> requests
            # would 404 while the name LOOKS configured.  Refuse loudly.
            raise ValueError(
                f"tenant={tenant!r} requires a shared transport= to mount "
                "under; a standalone server is the anonymous default session"
            )
        if transport is None:
            transport = HTTPTransport(
                host=host, port=port, max_request_size=max_request_size,
                registry=self.metrics_registry,
            )
            transport.add_session(self)  # default session: pre-split wire shape
        else:
            transport.add_session(self, tenant=tenant)
        self.transport = transport

    # ------------------------------------------------------------------
    # Round-engine API (what the reference's coordinator did via _updates reach-in)
    # ------------------------------------------------------------------

    async def publish_model(self, params: Params, round_number: int) -> None:
        """Set the global params served to clients and advance the round."""
        payload = encode_params(params)
        async with self._lock:
            self._params = params
            self._params_bytes = payload
            self._round = round_number
            if self.ingest is not None:
                if self._ingest_pipeline is None:
                    from nanofed_tpu.ingest import IngestPipeline

                    self._ingest_pipeline = IngestPipeline(
                        params, self.ingest, registry=self.metrics_registry
                    )
                # The pipeline's flat-base cache mirrors the version window
                # EXACTLY (same publish, same pruning rule), so wire
                # acceptance and delta reconstruction can never disagree.
                self._ingest_pipeline.note_version(
                    round_number, params, window=self.staleness_window
                )
                if self.staleness_window == 0:
                    # Sync parity with the _updates.clear() below: a new
                    # round invalidates every unaggregated buffered delta.
                    self._ingest_pipeline.clear()
            if self.fleet is not None:
                # Tier views version with the SAME window rule as the flat
                # base cache above, so tier-delta reconstruction and wire
                # acceptance can never disagree about live rounds.
                self.fleet.publish(
                    round_number, params, window=self.staleness_window
                )
            if self.staleness_window > 0:
                # Async mode: keep the window of base versions for delta
                # reconstruction, and keep buffered updates — a straggler's update
                # for an older version stays aggregatable while it is in-window.
                self._version_params[round_number] = params
                floor = round_number - self.staleness_window
                for old in [r for r in self._version_params if r < floor]:
                    del self._version_params[old]
            else:
                self._updates.clear()
            # A straggler's masked vector from a FAILED secure round must never leak
            # into the next round: its masks are bound to the OLD round number and
            # would not cancel (unmask_sum would silently produce garbage).
            self._masked_updates.clear()
            # Per-round dropout-tolerance state: fresh ephemeral keys and shares are
            # distributed for every round.
            self._round_share_epks.clear()
            self._round_share_bhs.clear()
            self._round_share_blobs.clear()
            self._round_share_senders.clear()
            self._unmask_request = None
            self._unmask_reveals.clear()

    def num_updates(self) -> int:
        # Lock-free read: len() is a single atomic operation and every MUTATION of
        # _updates is under self._lock — an invariant fedlint FED005 enforces on this
        # class, not a GIL hand-wave.  The round engine treats this as a hint and
        # re-checks under the lock via drain_updates()/take_updates().
        if self._ingest_pipeline is not None:
            return self._ingest_pipeline.fill
        return len(self._updates)

    async def drain_updates(self) -> list[ModelUpdate]:
        """Atomically take the buffered updates for aggregation."""
        async with self._lock:
            updates = list(self._updates.values())
            self._updates.clear()
        return updates

    @property
    def published_versions(self) -> dict[int, Params]:
        """Async mode's version window — the SINGLE source of truth for which base
        params are still reconstructable/aggregatable.  The round engine reads this
        for delta computation instead of keeping its own copy (two pruning loops
        that must stay bit-identical is how windows silently diverge)."""
        return dict(self._version_params)

    async def take_updates(self, k: int) -> list[ModelUpdate]:
        """Atomically take up to ``k`` buffered updates in arrival order, LEAVING the
        rest buffered — the async engine aggregates exactly K per step (FedBuff), and
        surplus arrivals must wait for the next aggregation, not inflate this one."""
        async with self._lock:
            keys = list(self._updates.keys())[:k]
            taken = [self._updates.pop(key) for key in keys]
        return taken

    async def drain_ingest_fedavg(self) -> tuple[Any | None, list[Any]]:
        """Sync-round drain of the batched-ingest buffer: ONE jitted reduce of
        every buffered delta against the CURRENT round's base.  Returns
        ``(new_flat_params, slot_metas)`` — ``(None, [])`` when nothing is
        buffered; the round engine unravels the flat result into params."""
        async with self._lock:
            return self._ingest_pipeline.drain_fedavg(self._round)

    async def drain_ingest_fedbuff(
        self, k: int, current_version: int,
        staleness_exponent: float = 0.5, server_lr: float = 1.0,
    ) -> tuple[Any, list[Any], dict[str, Any]]:
        """Async-mode drain: ONE jitted reduce of the K OLDEST buffered deltas
        (staleness-discounted, out-of-window slots skipped) applied to the
        current version — the batched counterpart of ``take_updates(k)`` +
        ``fedbuff_combine``.  Surplus newer slots stay buffered."""
        async with self._lock:
            return self._ingest_pipeline.drain_fedbuff(
                k, current_version,
                staleness_exponent=staleness_exponent, server_lr=server_lr,
            )

    async def drain_ingest_fedavg_partial(self) -> tuple[Any | None, float, list[Any]]:
        """Hierarchical sync-round drain, HOST-LOCAL stage: the batched
        reduce of every buffered delta as the UNNORMALIZED
        ``(Σ w_i δ_i, Σ w_i, slot_metas)`` — the federate mesh worker psums
        the partials over the ``hosts`` axis and applies base + num/den once
        (see ``communication.federation``).  ``(None, 0.0, [])`` when nothing
        is buffered: a zero-mass host still participates in the psum."""
        async with self._lock:
            return self._ingest_pipeline.drain_fedavg_partial()

    async def drain_ingest_fedbuff_partial(
        self, k: int, current_version: int, staleness_exponent: float = 0.5,
    ) -> tuple[Any, list[Any], dict[str, Any]]:
        """Hierarchical async-mode drain, HOST-LOCAL stage: the unnormalized
        discounted sum of this host's K oldest in-window deltas (``server_lr``
        and the global ``1/K`` apply after the cross-host psum)."""
        async with self._lock:
            return self._ingest_pipeline.drain_fedbuff_partial(
                k, current_version, staleness_exponent=staleness_exponent,
            )

    def stop_training(self) -> None:
        """Signal clients to stop polling (parity: ``server.py:313-317``)."""
        self._training_active = False

    # ------------------------------------------------------------------
    # Secure-aggregation round-engine API
    # ------------------------------------------------------------------

    async def open_secagg(
        self,
        expected_clients: int,
        *,
        window: bool = False,
        max_clients: int | None = None,
        threshold_for: Any | None = None,
    ) -> None:
        """Open secure-aggregation enrollment.  Clients register their X25519 public
        key + sample count via POST ``/secagg/register``; the roster endpoint reports
        ``complete`` once the cohort is fixed.  The cohort is fixed for the whole
        training run (masks are re-derived per round from the round number, so one
        enrollment covers every round).

        Two cohort-sizing modes:

        * **exact** (default, ``window=False``): the cohort is exactly
          ``expected_clients`` — registration beyond it is refused and the roster is
          complete the moment the count is reached.  Right for the no-dropout masked
          protocol, where every cohort member must submit every round anyway.
        * **window** (``window=True``): ``expected_clients`` is a MINIMUM.
          Registration stays open — up to ``max_clients`` if given — until
          ``close_secagg()`` freezes the roster (reaching ``max_clients`` freezes it
          implicitly).  Only then does the roster report complete.  This is the
          dropout-tolerant mode's shape: the Shamir ``threshold`` must exceed half the
          cohort that ACTUALLY enrolled (split-view defense,
          ``secure_agg.make_dropout_shares``), so the cohort size must be settled
          before anyone derives a threshold from it — ``threshold_for`` (a callable
          ``n -> int``) is evaluated exactly once, at freeze, and the result is
          published to clients in the roster payload.

        A fresh random session nonce is issued per call; signed enrollments bind to it,
        so captured enrollments from an earlier session cannot be replayed here."""
        import secrets

        if window and max_clients is not None and max_clients < expected_clients:
            # Reaching max freezes the roster, so a cap below the minimum would
            # close enrollment at a size the coordinator then waits on forever —
            # fail fast at configuration time, not after a round timeout.
            raise ValueError(
                f"max_clients ({max_clients}) must be >= the enrollment minimum "
                f"({expected_clients})"
            )
        async with self._lock:
            self._secagg_expected = int(expected_clients)
            self._secagg_window = bool(window)
            self._secagg_max = int(max_clients) if max_clients is not None else None
            self._secagg_threshold_for = threshold_for
            self._secagg_threshold: int | None = None
            self._secagg_closed = False
            self._secagg_session = secrets.token_hex(16)
            self._secagg_backend = None
            self._secagg_roster.clear()
            self._masked_updates.clear()
            self._secagg_evicted.clear()
            self._round_share_epks.clear()
            self._round_share_bhs.clear()
            self._round_share_blobs.clear()
            self._round_share_senders.clear()
            self._unmask_request = None
            self._unmask_reveals.clear()

    async def close_secagg(self) -> int:
        """Freeze a window-mode roster (idempotent): no further registrations, and the
        cohort-derived Shamir threshold becomes available.  Returns the frozen cohort
        size."""
        async with self._lock:
            return self._close_secagg_locked()

    def _close_secagg_locked(self) -> int:
        """Freeze the roster; the CALLER must hold ``self._lock`` (``close_secagg``
        and the register handler's implicit cap-reached freeze both do)."""
        if not self._secagg_closed:
            # fedlint: disable=FED005 (caller holds self._lock: close_secagg and the register handler's locked freeze both enter locked)
            self._secagg_closed = True
            if self._secagg_threshold_for is not None:
                # fedlint: disable=FED005 (caller holds self._lock: close_secagg and the register handler's locked freeze both enter locked)
                self._secagg_threshold = int(
                    self._secagg_threshold_for(len(self._secagg_roster))
                )
        return len(self._secagg_roster)

    def secagg_enrolled(self) -> int:
        return len(self._secagg_roster)

    def secagg_threshold(self) -> int | None:
        """The cohort-derived Shamir threshold for the CURRENT round (window mode,
        after the freeze); None in exact mode or before the freeze.

        Re-derived from the ACTIVE cohort, not frozen at enrollment: per-round fresh
        secrets (Bonawitz §4) mean each round's sharing stands alone, so the
        split-view requirement is t > m/2 of the cohort sharing THIS round.  A
        threshold frozen at the enrollment size n would permanently brick the
        protocol once evictions shrink the active cohort below it (shares can never
        number >= t again), even with the privacy floor still satisfied."""
        if not self._secagg_closed or self._secagg_threshold_for is None:
            return self._secagg_threshold
        return int(self._secagg_threshold_for(len(self.secagg_active_order())))

    def secagg_roster_complete(self) -> bool:
        if self._secagg_expected is None:
            return False
        if self._secagg_window:
            return self._secagg_closed
        return len(self._secagg_roster) >= self._secagg_expected

    def secagg_client_order(self) -> list[str]:
        """Canonical cohort ordering (sorted ids) — mask sign convention depends on
        every party agreeing on it."""
        return sorted(self._secagg_roster)

    def num_masked_updates(self) -> int:
        return len(self._masked_updates)

    async def drain_masked_updates(self) -> dict[str, Any]:
        """Atomically take the buffered masked vectors (client_id -> uint32 array)."""
        async with self._lock:
            taken = {cid: vec for cid, (vec, _) in self._masked_updates.items()}
            self._masked_updates.clear()
        return taken

    def secagg_backend(self) -> str:
        """The cohort's negotiated mask-expansion backend (pinned at first
        enrollment; 'host' for an empty roster)."""
        return self._secagg_backend or "host"

    def secagg_public_keys(self) -> dict[str, bytes]:
        return {c: e["public_key"] for c, e in self._secagg_roster.items()}

    def secagg_weights(self) -> dict[str, float]:
        """Normalized FedAvg weights over the FULL enrolled cohort (what clients
        pre-scale by; dropout renormalization divides by the survivors' mass)."""
        total = sum(e["num_samples"] for e in self._secagg_roster.values())
        return {c: e["num_samples"] / total for c, e in self._secagg_roster.items()}

    def secagg_active_order(self) -> list[str]:
        """This round's active cohort: enrolled minus evicted, canonical order."""
        return sorted(set(self._secagg_roster) - self._secagg_evicted)

    async def evict_secagg_clients(self, client_ids: Iterable[str]) -> None:
        """Remove dropped clients from the active cohort (their round secrets were
        revealed to recover the round; later rounds must not wait for them — a client
        can only rejoin by enrolling in a fresh cohort).

        The current round's share-exchange state is purged with them: shrinking the
        active set would otherwise flip ``secagg_shares_complete()`` true for the
        ROUND IN PROGRESS, serving surviving pollers an epk/inbox view inconsistent
        with the participants list they deposited against."""
        async with self._lock:
            newly = set(client_ids) - self._secagg_evicted
            if newly:
                self._m_evictions.inc(len(newly))
            self._secagg_evicted.update(client_ids)
            self._round_share_epks.clear()
            self._round_share_bhs.clear()
            self._round_share_blobs.clear()
            self._round_share_senders.clear()

    def secagg_shares_complete(self) -> bool:
        """True once every ACTIVE cohort member has deposited this round's ephemeral
        key + sealed share blobs (the per-round share barrier)."""
        active = self.secagg_active_order()
        return bool(active) and set(self._round_share_senders) >= set(active)

    def secagg_round_epks(self) -> dict[str, bytes]:
        """This round's ephemeral mask public keys (what pairwise seeds derive from)."""
        return dict(self._round_share_epks)

    def secagg_round_commitments(self) -> dict[str, bytes]:
        """This round's sha256 self-seed commitments (recovery verifies reconstructed
        seeds against these so a corrupt share fails the round instead of silently
        corrupting the model)."""
        return dict(self._round_share_bhs)

    async def open_unmask(self, round_number: int, dropped: list[str],
                          survivors: list[str]) -> None:
        """Publish the unmask request survivors poll for (dropout-tolerant mode)."""
        async with self._lock:
            self._unmask_request = {
                "round": int(round_number),
                "dropped": sorted(dropped),
                "survivors": sorted(survivors),
            }
            self._unmask_reveals.clear()

    def num_unmask_reveals(self) -> int:
        return len(self._unmask_reveals)

    async def drain_unmask_reveals(self) -> dict[str, dict[str, Any]]:
        """Atomically take the buffered reveals and close the unmask request."""
        async with self._lock:
            taken = dict(self._unmask_reveals)
            self._unmask_reveals.clear()
            self._unmask_request = None
        return taken

    @property
    def current_round(self) -> int:
        return self._round

    @property
    def ingest_pipeline(self) -> Any | None:
        """The batched-ingest pipeline, once the first ``publish_model`` built
        it (None before, and always None without ``ingest=``) — the load
        harness reads decode-pool utilization and buffer stats from here."""
        return self._ingest_pipeline

    # ------------------------------------------------------------------
    # Transport dispatch, fault injection, bounded reads
    # ------------------------------------------------------------------

    async def dispatch(
        self, path: str, request: web.Request
    ) -> web.StreamResponse:
        """Transport entry point: route the LOGICAL endpoint path (tenant
        prefix already stripped by the transport) to this session's handler,
        applying this session's chaos schedule to its update endpoint.  The
        method/path table replaces the pre-split aiohttp router, so custom
        ``ServerEndpoints`` keep working and a missing path 404s here — inside
        the resolved tenant, never across tenants."""
        handler = self._routes.get((request.method, path))
        if handler is None and request.method == "HEAD":
            # Parity with the pre-split aiohttp router's automatic HEAD
            # support on GET routes (load-balancer health probes HEAD
            # /status); the protocol layer suppresses the body.
            handler = self._routes.get(("GET", path))
        if handler is None:
            if any(p == path for _, p in self._routes):
                return web.json_response(
                    {"status": "error",
                     "message": f"method {request.method} not allowed on {path}"},
                    status=405,
                )
            return web.json_response(
                {"status": "error", "message": f"no endpoint {path}"},
                status=404,
            )
        if self._chaos is not None and path == self.endpoints.update:
            return await self._apply_chaos(request, handler)
        return await handler(request)

    async def _apply_chaos(self, request: web.Request, handler: Any) -> Any:
        """Apply the chaos schedule's wire fault to this request, if any.

        Only the update endpoint is faulted (the model/status/secagg paths have
        their own failure modes driven from the client side — ``dispatch``
        gates on the logical path): ``drop`` severs
        the connection BEFORE the handler — the submit never happened;
        ``ack_drop`` runs the handler (the update IS buffered) and severs the
        connection before the response — the lost ACK that makes idempotent
        submit keys necessary; ``delay`` holds the request for its seconds.
        One-shot events are consumed by the schedule, so a retry eventually
        gets through."""
        event = self._chaos.wire_fault(
            request.headers.get(HEADER_CLIENT), request.headers.get(HEADER_ROUND)
        )
        if event is None:
            return await handler(request)
        if event.kind == "delay":
            await self._clock.sleep(event.seconds)
            return await handler(request)
        if event.kind == "drop":
            self._log.warning("chaos: dropping request from %s pre-handler",
                              request.headers.get(HEADER_CLIENT))
            if request.transport is not None:
                request.transport.close()
            return web.Response(status=500)  # never reaches the severed peer
        # ack_drop: the handler's effects are REAL, only the response is lost.
        response = await handler(request)
        self._log.warning("chaos: severing connection from %s before its ACK",
                          request.headers.get(HEADER_CLIENT))
        if request.transport is not None:
            request.transport.close()
        return response

    async def _offload(self, fn: Any, *args: Any, **kwargs: Any) -> Any:
        """Run one CPU-bound submit stage (npz decode, delta reconstruction,
        RSA verify, flatten) off the event loop: on the ingest pipeline's
        BOUNDED worker pool when one exists — ``asyncio.to_thread``'s default
        executor grows with concurrency, so a submit storm (plain OR masked)
        could otherwise fan out unbounded decode threads — else ``to_thread``
        (the pre-ingest behavior, still off-loop)."""
        if self._ingest_pipeline is not None:
            return await self._ingest_pipeline.run_decode(fn, *args, **kwargs)
        return await asyncio.to_thread(fn, *args, **kwargs)

    def _decode_span(
        self, trace: TraceContext | None, client_id: str, encoding: str
    ) -> Any:
        """A ``submit-decode`` span around the offloaded decode when a tracer
        is wired, tagged with the submit's trace id — the hop that links the
        wire header to the decode-pool work.  No tracer -> no-op context."""
        if self._tracer is None:
            return nullcontext()
        attrs: dict[str, Any] = {"client": client_id, "encoding": encoding}
        if trace is not None:
            attrs["trace"] = trace.trace_id
        return self._tracer.span("submit-decode", **attrs)

    async def _read_body(self, request: web.Request) -> bytes:
        """Read the request body via the transport's bounded-read primitive
        (``client_max_size`` bounds the size): a slowloris peer trickling
        bytes must not hold this handler — and its admission slot — open past
        ``read_timeout_s``.  The timeout and its 408 metric are per-session:
        one tenant's slowloris storm counts against that tenant only."""
        try:
            return await read_body_bounded(request, self.read_timeout_s)
        except asyncio.TimeoutError:
            self._m_read_timeouts.inc()
            raise web.HTTPRequestTimeout(
                text=json.dumps({
                    "status": "error",
                    "message": (f"request body not received within "
                                f"{self.read_timeout_s:g}s"),
                }),
                content_type="application/json",
            ) from None

    def _submit_fingerprint(self, request: web.Request) -> str:
        """What a duplicate must MATCH beyond its idempotency key.  On a
        ``require_signatures`` server that is the sha256 of the signature
        header: a retry re-sends the accepted attempt's exact headers, so the
        legitimate client matches for free, while an unauthenticated prober
        who merely guesses the (fully predictable) submit key cannot elicit a
        success-shaped duplicate-200 — the signature gate is preserved even on
        the dedupe fast path.  Unsigned servers have no authentication
        anywhere, so the fingerprint is empty there."""
        if not self.require_signatures:
            return ""
        import hashlib

        return hashlib.sha256(
            request.headers.get(HEADER_SIGNATURE, "").encode()
        ).hexdigest()

    def _duplicate_submit(
        self, client_id: str, submit_id: str | None, fingerprint: str
    ) -> bool:
        """True when this (idempotency key, fingerprint) pair was already
        accepted from this client.  Callers hold ``self._lock`` for the
        authoritative pre-buffer check; the lock-free call at handler entry is
        an optimization (no await has happened yet in that handler, so the
        read is race-free) that skips the body read for obvious duplicates."""
        return (
            submit_id is not None
            and (submit_id, fingerprint) in self._seen_submits.get(client_id, ())
        )

    def _record_submit_locked(
        self, client_id: str, submit_id: str | None, fingerprint: str
    ) -> None:
        """Remember an ACCEPTED submit's idempotency key + fingerprint (caller
        holds the lock).  The per-client window is bounded: dedupe protects
        against retry storms (seconds), not replay (signatures handle that)."""
        if submit_id is None:
            return
        # fedlint: disable=FED005 (every mutation of _seen_submits goes through this helper, whose callers hold self._lock)
        self._seen_submits.setdefault(
            client_id, deque(maxlen=SUBMIT_KEY_WINDOW)
        ).append((submit_id, fingerprint))

    def _duplicate_response(self, client_id: str, kind: str) -> web.StreamResponse:
        self._m_updates.inc(kind=kind, result="duplicate")
        self._log.info("duplicate submit from %s folded at most once", client_id)
        return web.json_response({
            "status": "success",
            "message": "duplicate submit (already accepted; folded at most once)",
            "update_id": client_id,
            "duplicate": True,
        })

    # ------------------------------------------------------------------
    # Handlers
    # ------------------------------------------------------------------

    async def _handle_get_model(self, request: web.Request) -> web.StreamResponse:
        if not self._training_active:
            return web.Response(
                status=200,
                headers={HEADER_STATUS: "terminated", HEADER_ROUND: str(self._round)},
            )
        body = self._params_bytes
        if body is None:
            return web.json_response(
                {"status": "error", "message": "no model published"}, status=503
            )
        tier = request.headers.get(HEADER_TIER)
        if tier is not None:
            if self.fleet is None:
                return web.json_response(
                    {"status": "error",
                     "message": "tier header on a server with no fleet configured"},
                    status=400,
                )
            try:
                body = self.fleet.payload(tier)
            except Exception as e:
                return web.json_response(
                    {"status": "error", "message": f"bad tier: {e}"}, status=400
                )
            self._m_bytes_tx.inc(len(body), endpoint="model")
            self._m_fleet_bytes.inc(len(body), tier=tier, direction="tx")
            return web.Response(
                body=body,
                content_type="application/octet-stream",
                headers={
                    HEADER_STATUS: "training",
                    HEADER_ROUND: str(self._round),
                    HEADER_TIER: tier,
                },
            )
        self._m_bytes_tx.inc(len(body), endpoint="model")
        return web.Response(
            body=body,
            content_type="application/octet-stream",
            headers={HEADER_STATUS: "training", HEADER_ROUND: str(self._round)},
        )

    def _reject_update(self, reason: str, kind: str = "plain") -> None:
        self._m_updates.inc(kind=kind, result=reason)

    async def _handle_submit_update(self, request: web.Request) -> web.StreamResponse:
        client_id = request.headers.get(HEADER_CLIENT)
        round_header = request.headers.get(HEADER_ROUND)
        if not client_id or round_header is None:
            self._reject_update("missing_headers")
            return web.json_response(
                {"status": "error", "message": "missing client/round headers"}, status=400
            )
        try:
            round_number = int(round_header)
        except ValueError:
            self._reject_update("bad_round_header")
            return web.json_response(
                {"status": "error", "message": f"bad round: {round_header!r}"}, status=400
            )
        try:
            metrics: dict[str, Any] = json.loads(request.headers.get(HEADER_METRICS, "{}"))
        except json.JSONDecodeError:
            self._reject_update("bad_metrics_header")
            return web.json_response(
                {"status": "error", "message": "bad metrics header"}, status=400
            )
        if self._params is None:
            # No template yet: decode_params(like=None) would skip shape/structure
            # validation entirely and buffer an arbitrary payload for round 0.
            self._reject_update("no_model")
            return web.json_response(
                {"status": "error", "message": "no model published"}, status=503
            )
        masked = request.headers.get(HEADER_SECAGG) == "masked"
        # Fleet mode: a tier-tagged submit decodes by the TIER's codec — the
        # tier must exist, an explicit encoding header must AGREE with the
        # tier's (a client that disagrees with its own profile is
        # misconfigured, not negotiable), and masked payloads cannot be
        # tier-routed (the mask hides the codec's structure).
        tier = request.headers.get(HEADER_TIER)
        if tier is not None:
            if self.fleet is None:
                self._reject_update("bad_tier")
                return web.json_response(
                    {"status": "error",
                     "message": "tier header on a server with no fleet configured"},
                    status=400,
                )
            if masked:
                self._reject_update("bad_tier", kind="masked")
                return web.json_response(
                    {"status": "error",
                     "message": "tier routing cannot combine with SecAgg "
                                "masked payloads"},
                    status=400,
                )
            try:
                tier_encoding = self.fleet.profile.tier(tier).encoding
            except Exception as e:
                self._reject_update("bad_tier")
                return web.json_response(
                    {"status": "error", "message": f"bad tier: {e}"}, status=400
                )
            explicit = request.headers.get(HEADER_ENCODING)
            if explicit is not None and explicit != tier_encoding:
                self._reject_update("bad_tier")
                self._m_fleet_updates.inc(tier=tier, result="encoding_mismatch")
                return web.json_response(
                    {"status": "error",
                     "message": (f"tier {tier!r} submits {tier_encoding!r}, "
                                 f"not {explicit!r}")},
                    status=400,
                )
        # Idempotent-submit dedupe FIRST — even before the stale-round check: a
        # retry of an ACCEPTED submit may arrive after publish_model advanced
        # the round, and answering it 400-stale would make a topk8 client fold
        # a delta the server already aggregated (the double-count this key
        # exists to prevent).  Lock-free here is race-free (no await yet); the
        # authoritative re-check runs under the buffer lock below.  The
        # fingerprint keeps the fast path authenticated: on a signing server a
        # duplicate only matches when it carries the ACCEPTED attempt's exact
        # signature, so guessing the (predictable) key buys nothing.
        submit_id = request.headers.get(HEADER_SUBMIT)
        fingerprint = self._submit_fingerprint(request)
        if self._duplicate_submit(client_id, submit_id, fingerprint):
            return self._duplicate_response(
                client_id, "masked" if masked else "plain"
            )
        # Cheap stale-round rejection BEFORE reading/decompressing up to 100 MB; the
        # authoritative check re-runs under the lock below.
        if not self._round_acceptable(round_number):
            self._reject_update("stale_round")
            return web.json_response(
                {
                    "status": "error",
                    "message": self._round_rejection_message(round_number),
                },
                status=400,
            )
        encoding = request.headers.get(HEADER_ENCODING, "npz")
        if masked and encoding != "npz":
            # Masked payloads are uint32 fixed-point with their own codec; a
            # client that ALSO asks for q8-delta is misconfigured — refuse
            # rather than silently interpret the body one way or the other.
            self._reject_update("bad_encoding", kind="masked")
            return web.json_response(
                {"status": "error",
                 "message": f"encoding {encoding!r} cannot combine with "
                            "SecAgg masked payloads"},
                status=400,
            )
        # Admission control: bound the submits — PLAIN AND MASKED — that
        # concurrently hold body/decode resources.  Past the cap the answer is
        # an IMMEDIATE 429 + Retry-After — the body is never read — so
        # overload degrades to client backoff (exponential, jittered) instead
        # of unbounded memory growth and event-loop starvation.  (_inflight is
        # mutated only from the event loop with no await between check and
        # increment.)
        if self.max_inflight is not None and self._inflight >= self.max_inflight:
            self._m_429.inc(endpoint="update")
            self._reject_update("admission_reject",
                                kind="masked" if masked else "plain")
            return web.json_response(
                {"status": "error",
                 "message": (f"server at capacity ({self.max_inflight} submits "
                             "in flight); retry after backoff")},
                status=429,
                headers={"Retry-After": f"{self.retry_after_s:g}"},
            )
        # Batched ingest: a FULL buffer is known before any work — shed the
        # submit NOW (body unread, no decode-pool slot burned) rather than
        # after paying the whole decode pipeline for a guaranteed bounce.
        # Lock-free fill read is a hint (no await yet); the authoritative
        # re-check runs at the locked offer.  Clients whose slot would merely
        # be REPLACED (latest-wins resubmit) are not full-rejected.
        if (
            not masked
            and self._ingest_pipeline is not None
            and self._ingest_pipeline.fill >= self.ingest.capacity
            and not self._ingest_pipeline.buffer.has_client(client_id)
        ):
            self._m_429.inc(endpoint="update")
            self._reject_update("ingest_full")
            return web.json_response(
                {"status": "error",
                 "message": (f"ingest buffer full ({self.ingest.capacity} "
                             "slots); retry after backoff")},
                status=429,
                headers={"Retry-After": f"{self.retry_after_s:g}"},
            )
        # Trace context rides along from here: a malformed/absent header is
        # simply an untraced submit (None) — tracing is observability, never
        # admission control.
        trace = parse_trace(request.headers.get(HEADER_TRACE))
        self._inflight += 1
        try:
            if masked:
                return await self._handle_masked_update(
                    request, client_id, round_number, metrics, submit_id,
                    fingerprint,
                )
            return await self._admitted_submit_update(
                request, client_id, round_number, metrics, submit_id, fingerprint,
                tier=tier, trace=trace,
            )
        finally:
            self._inflight -= 1

    async def _admitted_submit_update(
        self, request: web.Request, client_id: str, round_number: int,
        metrics: dict[str, Any], submit_id: str | None, fingerprint: str,
        tier: str | None = None, trace: TraceContext | None = None,
    ) -> web.StreamResponse:
        """The body of a plain-update submit AFTER admission: the caller holds
        one in-flight slot for the duration (read + decode + verify + buffer)."""
        body = await self._read_body(request)
        self._m_bytes_rx.inc(len(body), endpoint="update")
        if tier is not None:
            self._m_fleet_bytes.inc(len(body), tier=tier, direction="rx")
            # The tier fixes the codec (validated against any explicit header
            # at entry); the tier's own decode path runs below.
            encoding = self.fleet.profile.tier(tier).encoding
        else:
            encoding = request.headers.get(HEADER_ENCODING, "npz")
        if encoding not in ("npz", ENCODING_Q8_DELTA, ENCODING_TOPK8):
            self._reject_update("bad_encoding")
            return web.json_response(
                {"status": "error", "message": f"unknown encoding {encoding!r}"},
                status=400,
            )
        # Snapshot the (round, base-params) pair UNDER THE LOCK before dispatching the
        # decode thread: publish_model can advance the round mid-decode, and a decode
        # against the NEW params would hand the signature check a reconstruction the
        # client never signed — a raced straggler would then see a misleading 403
        # signature failure instead of the accurate 400 stale-round rejection.  (The
        # locked re-check after the decode remains the authority on acceptance.)
        async with self._lock:
            if not self._round_acceptable(round_number):
                self._reject_update("stale_round")
                return web.json_response(
                    {
                        "status": "error",
                        "message": self._round_rejection_message(round_number),
                    },
                    status=400,
                )
            base = (
                self._version_params.get(round_number)
                if self.staleness_window > 0
                else self._params
            )
            # Batched ingest: the flat base for the SAME version, from the
            # snapshot the lock guarantees consistent — the worker thread
            # computes (flat(params) - base_flat) against it below.
            base_flat = (
                self._ingest_pipeline.base_flat(round_number)
                if self._ingest_pipeline is not None
                else None
            )
        if base is None:
            # _round_acceptable passed under the lock, so async mode's window held
            # the version; this is unreachable short of state corruption — refuse
            # rather than reconstruct against a guessed base.
            self._reject_update("stale_round")
            return web.json_response(
                {"status": "error",
                 "message": self._round_rejection_message(round_number)},
                status=400,
            )
        def _decode() -> Params:
            # CPU-bound decode (up to 100 MB decompress + structure checks);
            # compressed round deltas reconstruct base + dequantized delta in
            # numpy float32 — bit-identical to the client's signing-side
            # reconstruction, so signature verification composes.
            if encoding in (ENCODING_Q8_DELTA, ENCODING_TOPK8):
                return self._reconstruct_compressed_update(body, encoding, base)
            return decode_params(body, like=base)

        ingest_flat = None
        try:
            # Offloaded so concurrent /model and /status requests aren't
            # stalled behind it.  On the batched-ingest path WITHOUT
            # signatures the flatten fuses into the same pool job — the full
            # params tree never comes back to the handler, and each submit
            # pays ONE pool round trip, not two.
            if tier is not None:
                # Fleet path: the gateway decodes by the tier's codec against
                # the tier's published view for this round and returns the
                # flat dense-delta row directly — the tier submit never
                # materializes a dense params tree.
                def _decode_tier() -> Any:
                    return self.fleet.decode_submit(tier, body, round_number)

                with self._decode_span(trace, client_id, encoding):
                    ingest_flat = await self._offload(_decode_tier)
                params = None
            elif (
                self._ingest_pipeline is not None
                and not self.require_signatures
                and base_flat is not None
            ):

                def _decode_flat() -> Any:
                    from nanofed_tpu.ingest.pipeline import flatten_params

                    # Host float32 [P]: the buffer stages it and flushes the
                    # batch to device in one scatter at drain — no per-submit
                    # device dispatch anywhere on this path.
                    return flatten_params(_decode()) - base_flat

                with self._decode_span(trace, client_id, encoding):
                    ingest_flat = await self._offload(_decode_flat)
                params = None
            else:
                with self._decode_span(trace, client_id, encoding):
                    params = await self._offload(_decode)
        except Exception as e:
            self._reject_update("bad_payload")
            if tier is not None:
                self._m_fleet_updates.inc(tier=tier, result="bad_payload")
            return web.json_response(
                {"status": "error", "message": f"bad payload: {e}"}, status=400
            )
        if self.require_signatures:
            verdict = await self._offload(
                self._verify_update_signature, client_id, round_number, request, params
            )
            if verdict is not None:
                self._reject_update("bad_signature")
                return verdict
        if self._ingest_pipeline is not None:
            return await self._ingest_buffer_update(
                client_id, round_number, metrics, submit_id, fingerprint,
                params, base_flat, ingest_flat, tier=tier,
                trace="" if trace is None else trace.trace_id,
            )
        async with self._lock:
            # Authoritative duplicate re-check: two concurrent attempts of the
            # same retry storm can both pass the lock-free entry check while
            # their bodies read; only the first to reach this lock buffers.
            if self._duplicate_submit(client_id, submit_id, fingerprint):
                return self._duplicate_response(client_id, "plain")
            # Stale-round rejection (parity: server.py:260-272); in async mode the
            # window may have MOVED during the decode, so the authoritative
            # re-check matters for correctness, not just races.
            if not self._round_acceptable(round_number):
                self._reject_update("stale_round")
                return web.json_response(
                    {
                        "status": "error",
                        "message": self._round_rejection_message(round_number),
                    },
                    status=400,
                )
            self._updates[client_id] = ModelUpdate(
                client_id=client_id,
                round_number=round_number,
                params=params,
                metrics=metrics,
                timestamp=get_current_time().isoformat(),
            )
            self._record_submit_locked(client_id, submit_id, fingerprint)
            accepted = len(self._updates)
        self._m_updates.inc(kind="plain", result="accepted")
        self._log.info("update from %s (round %d, %d buffered)", client_id, round_number,
                       accepted)
        return web.json_response(
            {"status": "success", "message": "update accepted", "update_id": client_id}
        )

    async def _ingest_buffer_update(
        self, client_id: str, round_number: int, metrics: dict[str, Any],
        submit_id: str | None, fingerprint: str, params: Params | None,
        base_flat: Any, flat_delta: Any | None = None,
        tier: str | None = None, trace: str = "",
    ) -> web.StreamResponse:
        """Batched-ingest tail of an admitted plain submit: flatten the decoded
        params into a delta against the snapshotted base (worker pool — one
        O(P) subtract, then the device upload, both off the event loop) and
        offer it into the device buffer under the lock.  A FULL buffer is the
        backpressure boundary: 429 + Retry-After, the idempotency key NOT
        recorded — exactly the admission-control contract, so a retrying
        client lands later and a topk8 client that exhausts its retries folds
        the delta into its error-feedback residual exactly once."""
        if base_flat is None:
            # The flat cache mirrors the acceptance window exactly, so an
            # acceptable round always has a base; unreachable short of state
            # corruption — refuse rather than guess (parity with the plain
            # path's base-None refusal).
            self._reject_update("stale_round")
            return web.json_response(
                {"status": "error",
                 "message": self._round_rejection_message(round_number)},
                status=400,
            )
        if flat_delta is None:
            # Signed path: the decode job had to return the full params tree
            # for signature verification, so flattening is its own pool job.
            from nanofed_tpu.ingest.pipeline import flatten_params

            def _flat_delta() -> Any:
                return flatten_params(params) - base_flat

            flat_delta = await self._offload(_flat_delta)
        async with self._lock:
            # Same authoritative re-checks as the per-submit path: duplicate
            # first (a racing retry storm's second body must not double-buffer),
            # then the round (the window may have moved during decode).
            if self._duplicate_submit(client_id, submit_id, fingerprint):
                return self._duplicate_response(client_id, "plain")
            if not self._round_acceptable(round_number):
                self._reject_update("stale_round")
                return web.json_response(
                    {"status": "error",
                     "message": self._round_rejection_message(round_number)},
                    status=400,
                )
            if tier is not None:
                # Tag the slot with its tier so drain-side consumers (fleet
                # telemetry, per-tier round accounting) can group without a
                # side lookup.
                metrics = dict(metrics, tier=tier)
            slot = self._ingest_pipeline.offer(
                flat_delta, client_id=client_id, round_number=round_number,
                metrics=metrics, trace=trace,
            )
            if slot is not None:
                self._record_submit_locked(client_id, submit_id, fingerprint)
                buffered = self._ingest_pipeline.fill
        if slot is None:
            self._m_429.inc(endpoint="update")
            self._reject_update("ingest_full")
            if tier is not None:
                self._m_fleet_updates.inc(tier=tier, result="ingest_full")
            return web.json_response(
                {"status": "error",
                 "message": (f"ingest buffer full ({self.ingest.capacity} "
                             "slots); retry after backoff")},
                status=429,
                headers={"Retry-After": f"{self.retry_after_s:g}"},
            )
        if tier is not None:
            self._m_fleet_updates.inc(tier=tier, result="accepted")
        self._m_updates.inc(kind="plain", result="accepted")
        self._log.info("ingested update from %s (round %d, slot %d, %d buffered)",
                       client_id, round_number, slot, buffered)
        return web.json_response(
            {"status": "success", "message": "update accepted",
             "update_id": client_id}
        )

    def _round_acceptable(self, round_number: int) -> bool:
        """Sync mode: exactly the current round.  Async mode (staleness_window>0):
        a version that was actually PUBLISHED and is still in the window — a
        never-published in-range number (e.g. a negative round while the window
        extends below 0) has no base params and must be refused, not guessed."""
        if round_number == self._round:
            return True
        return self.staleness_window > 0 and round_number in self._version_params

    def _round_rejection_message(self, round_number: int) -> str:
        if self.staleness_window > 0:
            return (
                f"update for round {round_number} is outside the staleness window "
                f"[{self._round - self.staleness_window}, {self._round}]"
            )
        return f"update for round {round_number}, server is on {self._round}"

    def _reconstruct_compressed_update(
        self, body: bytes, encoding: str, base: Params
    ) -> Params:
        """Compressed-delta body -> full params via the SHARED codec helpers (the
        client signs this exact arithmetic).  ``base`` is the params of the version
        the CLIENT fetched, SNAPSHOTTED under the round lock by the caller before
        this runs in a worker thread — in async mode that may be an older in-window
        version from the history dict; sync mode only ever sees the current round.
        Snapshotting (rather than re-reading ``self._params`` here) keeps the
        signature check downstream honest when publish_model races the decode."""
        from nanofed_tpu.communication.codec import reconstruct_q8, reconstruct_topk8

        if encoding == ENCODING_TOPK8:
            return reconstruct_topk8(base, body)
        return reconstruct_q8(base, body)

    def _verify_update_signature(
        self, client_id: str, round_number: int, request: web.Request, params: Params
    ) -> web.StreamResponse | None:
        """Return an error response when the update's signature is missing/invalid,
        None when it verifies (INVALID_SIGNATURE parity:
        ``nanofed/server/validation.py:179-212``).

        The signature covers the update's full wire context — client id, round number,
        the verbatim metrics header, and the params — so a captured signed update cannot
        be replayed into a later round or have its metrics rewritten.

        CPU-bound (canonical serialization + RSA verify): callers run it via
        ``asyncio.to_thread`` to keep the event loop responsive.
        """
        import base64

        from nanofed_tpu.security.signing import verify_update_signature

        pem = self.client_keys.get(client_id)
        if pem is None:
            return web.json_response(
                {"status": "error", "message": f"unknown client {client_id!r}"}, status=403
            )
        try:
            signature = base64.b64decode(request.headers.get(HEADER_SIGNATURE, ""))
        except Exception:
            signature = b""
        metrics_json = request.headers.get(HEADER_METRICS, "{}")
        if not signature or not verify_update_signature(
            params, client_id, round_number, metrics_json, signature, pem
        ):
            self._log.warning("invalid signature from %s", client_id)
            return web.json_response(
                {"status": "error", "message": "invalid signature"}, status=403
            )
        return None

    async def _check_signature(
        self, request: web.Request, client_id: str, verify: Any, *verify_args: Any
    ) -> web.StreamResponse | None:
        """Shared signature-enforcement plumbing: registered-key lookup, tolerant
        base64 decode, threaded RSA verify, warn + 403 on failure.  ``verify`` is the
        module-level verifier whose trailing arguments are ``(signature, pem)``.
        Returns the error response, or None when the signature checks out."""
        import base64

        pem = self.client_keys.get(client_id)
        if pem is None:
            return web.json_response(
                {"status": "error", "message": f"unknown client {client_id!r}"},
                status=403,
            )
        try:
            signature = base64.b64decode(request.headers.get(HEADER_SIGNATURE, ""))
        except Exception:
            signature = b""
        ok = signature and await self._offload(verify, *verify_args, signature, pem)
        if not ok:
            self._log.warning("invalid signature from %s on %s", client_id,
                              request.path)
            return web.json_response(
                {"status": "error", "message": "invalid signature"}, status=403
            )
        return None

    async def _handle_secagg_register(self, request: web.Request) -> web.StreamResponse:
        """Enroll one client in the secure-aggregation cohort: X25519 public key (for
        pairwise mask agreement) + sample count (for server-computed FedAvg weights).

        Re-registration is IDEMPOTENT-ONLY: the identical payload returns 200 (safe
        retry), but a changed key/count for an enrolled id is a 409 — a mid-session
        key swap (including a replayed enrollment from an earlier session) would
        silently break pairwise-mask cancellation for everyone who already fetched
        the roster."""
        import base64
        import math

        client_id = request.headers.get(HEADER_CLIENT)
        if not client_id:
            return web.json_response(
                {"status": "error", "message": "missing client header"}, status=400
            )
        if self._secagg_expected is None:
            return web.json_response(
                {"status": "error", "message": "secure aggregation not open"}, status=403
            )
        raw = await self._read_body(request)
        try:
            body = json.loads(raw)
            public_key = base64.b64decode(body["public_key"])
            num_samples = float(body["num_samples"])
            backend = str(body.get("backend", "host"))
            if len(public_key) != 32:
                raise ValueError("bad key length")
            if not (math.isfinite(num_samples) and num_samples > 0):
                # Infinity would make every honest weight num/inf = 0 at the roster.
                raise ValueError("sample count must be finite and positive")
            if backend not in ("host", "device"):
                raise ValueError(f"unknown mask backend {backend!r}")
        except Exception as e:
            return web.json_response(
                {"status": "error", "message": f"bad registration: {e}"}, status=400
            )
        if self.require_signatures:
            # Enrollment must be as authentic as updates: an unsigned register would
            # let anyone claim a cohort slot (and its mask identity) for a known id.
            # The signature binds this server's session nonce against replay, and the
            # advertised backend against splicing.
            from nanofed_tpu.security.signing import verify_enrollment_signature

            verdict = await self._check_signature(
                request, client_id,
                lambda *a: verify_enrollment_signature(*a, backend=backend),
                client_id, public_key, num_samples, self._secagg_session,
            )
            if verdict is not None:
                return verdict
        async with self._lock:
            # Mask-backend negotiation: host-Philox and device-PRNG expansions are
            # wire-incompatible — a mixed cohort's pairwise masks would NOT cancel and
            # the failure would surface only as garbage aggregates after dequantize.
            # The first enrollment pins the cohort backend; a mismatch is refused HERE,
            # at registration, with the reason in the error.
            if self._secagg_backend is not None and backend != self._secagg_backend:
                return web.json_response(
                    {
                        "status": "error",
                        "message": (
                            f"mask backend {backend!r} conflicts with this cohort's "
                            f"negotiated backend {self._secagg_backend!r}: host and "
                            "device PRG streams are wire-incompatible (mixed masks "
                            "would not cancel); re-enroll with the cohort backend"
                        ),
                    },
                    status=409,
                )
            existing = self._secagg_roster.get(client_id)
            if existing is not None:
                if (existing["public_key"] == public_key
                        and existing["num_samples"] == num_samples):
                    return web.json_response(
                        {"status": "success", "message": "already enrolled"}
                    )
                return web.json_response(
                    {"status": "error",
                     "message": "already enrolled with a different key/count"},
                    status=409,
                )
            if self._secagg_closed:
                # Window mode after the freeze: the cohort (and the threshold derived
                # from its size) is fixed; a late joiner would break every client's
                # view of the mask order.
                return web.json_response(
                    {"status": "error", "message": "cohort closed"}, status=403
                )
            cap = self._secagg_max if self._secagg_window else self._secagg_expected
            if cap is not None and len(self._secagg_roster) >= cap:
                return web.json_response(
                    {"status": "error", "message": "cohort is full"}, status=403
                )
            if self._secagg_backend is None:
                self._secagg_backend = backend
            self._secagg_roster[client_id] = {
                "public_key": public_key, "num_samples": num_samples
            }
            if (
                self._secagg_window
                and self._secagg_max is not None
                and len(self._secagg_roster) >= self._secagg_max
            ):
                self._close_secagg_locked()  # cap reached — freeze implicitly
        self._log.info("secagg enrollment: %s (%d/%d, backend=%s)", client_id,
                       len(self._secagg_roster), self._secagg_expected, backend)
        return web.json_response({"status": "success", "message": "enrolled"})

    async def _handle_secagg_roster(self, request: web.Request) -> web.StreamResponse:
        """The cohort roster every client needs before masking: canonical client order,
        all public keys, and each client's NORMALIZED FedAvg weight.  Clients pre-scale
        their update by their weight so the masked modular sum IS the weighted mean —
        the server never needs (and never sees) any individual update."""
        import base64

        if self._secagg_expected is None:
            return web.json_response(
                {"status": "error", "message": "secure aggregation not open"}, status=403
            )
        complete = self.secagg_roster_complete()
        payload: dict[str, Any] = {
            "status": "success",
            "complete": complete,
            "expected": self._secagg_expected,
            "enrolled": len(self._secagg_roster),
            "session": self._secagg_session,
            "backend": self.secagg_backend(),
        }
        if complete:
            order = self.secagg_client_order()
            total = sum(self._secagg_roster[c]["num_samples"] for c in order)
            payload.update(
                client_order=order,
                public_keys={
                    c: base64.b64encode(self._secagg_roster[c]["public_key"]).decode()
                    for c in order
                },
                weights={
                    c: self._secagg_roster[c]["num_samples"] / total for c in order
                },
            )
            if self._secagg_threshold is not None:
                # Window mode: the Shamir threshold is a property of who actually
                # enrolled (> n/2, split-view defense) — clients take it from here,
                # not from out-of-band config, and make_dropout_shares re-checks the
                # invariant against the roster before sharing any secret.
                payload["threshold"] = self._secagg_threshold
        return web.json_response(payload)

    async def _handle_secagg_shares_post(self, request: web.Request) -> web.StreamResponse:
        """Deposit one active client's ROUND secrets (dropout-tolerant mode, start of
        every round): body ``{"epk": b64, "blobs": {recipient_id: sealed_b64}}`` —
        the round's fresh ephemeral mask public key plus sealed Shamir share blobs
        covering the active cohort exactly.  The server routes the blobs but cannot
        read them (AES-GCM under pairwise identity keys)."""
        client_id = request.headers.get(HEADER_CLIENT)
        round_header = request.headers.get(HEADER_ROUND, "")
        if not client_id:
            return web.json_response(
                {"status": "error", "message": "missing client header"}, status=400
            )
        if not self.secagg_roster_complete():
            return web.json_response(
                {"status": "error",
                 "message": "roster incomplete: shares seal to the final cohort"},
                status=403,
            )
        active = self.secagg_active_order()
        if client_id not in active:
            return web.json_response(
                {"status": "error",
                 "message": f"{client_id!r} not in the active cohort"}, status=403
            )
        if round_header != str(self._round):
            return web.json_response(
                {"status": "error",
                 "message": f"shares for round {round_header!r}, server is on "
                            f"{self._round}"},
                status=400,
            )
        body = await self._read_body(request)
        if self.require_signatures:
            from nanofed_tpu.security.signing import verify_secagg_body_signature

            verdict = await self._check_signature(
                request, client_id, verify_secagg_body_signature,
                "shares", body, client_id, f"{self._secagg_session}:{self._round}",
            )
            if verdict is not None:
                return verdict
        import base64

        try:
            payload = json.loads(body)
            epk = base64.b64decode(payload["epk"])
            bh = base64.b64decode(payload.get("bh", ""))
            blobs = payload["blobs"]
            if len(epk) != 32:
                raise ValueError("bad ephemeral key length")
            if bh and len(bh) != 32:
                raise ValueError("bad self-seed commitment length")
            if set(blobs) != set(active):
                raise ValueError(
                    f"blobs must cover the active cohort exactly "
                    f"(got {len(blobs)}, expected {len(active)})"
                )
            if not all(isinstance(v, str) for v in blobs.values()):
                raise ValueError("each blob must be a base64 string")
        except Exception as e:
            return web.json_response(
                {"status": "error", "message": f"bad share deposit: {e}"}, status=400
            )
        async with self._lock:
            # Re-validate the round under the lock: publish_model may have advanced
            # the round (clearing the per-round state) while we awaited the body read
            # or the threaded signature verify — a stale round's epk/blobs recorded
            # into the new round's maps would derive masks that never cancel.
            if round_header != str(self._round):
                return web.json_response(
                    {"status": "error",
                     "message": f"shares for round {round_header!r}, server moved to "
                                f"{self._round}"},
                    status=409,
                )
            existing = self._round_share_senders.get(client_id)
            if existing is not None:
                if existing == blobs and self._round_share_epks.get(client_id) == epk:
                    return web.json_response(
                        {"status": "success", "message": "already deposited"}
                    )
                # A re-deposit with different content would desynchronize recipients
                # that already fetched their inbox.
                return web.json_response(
                    {"status": "error",
                     "message": "shares already deposited with different content"},
                    status=409,
                )
            self._round_share_senders[client_id] = dict(blobs)
            self._round_share_epks[client_id] = epk
            if bh:
                self._round_share_bhs[client_id] = bh
            for recipient, blob in blobs.items():
                self._round_share_blobs.setdefault(recipient, {})[client_id] = blob
        self._log.info("secagg round-%s shares deposited by %s (%d/%d)",
                       round_header, client_id,
                       len(self._round_share_senders), len(active))
        return web.json_response({"status": "success", "message": "shares deposited"})

    async def _handle_secagg_shares_get(self, request: web.Request) -> web.StreamResponse:
        """This round's share exchange state: the active participant list (what a
        client needs BEFORE depositing), and — once every active member has deposited
        — everyone's ephemeral mask key plus this client's sealed-blob inbox.  The
        all-deposited barrier matters: masking must not start until recovery is
        possible for any dropout pattern."""
        import base64

        client_id = request.headers.get(HEADER_CLIENT)
        if not client_id:
            return web.json_response(
                {"status": "error", "message": "missing client header"}, status=400
            )
        if client_id not in self._secagg_roster:
            return web.json_response(
                {"status": "error", "message": f"{client_id!r} not enrolled"}, status=403
            )
        active = self.secagg_active_order()
        complete = self.secagg_shares_complete()
        payload: dict[str, Any] = {
            "status": "success",
            "round": self._round,
            "participants": active,
            "complete": complete,
            "deposited": len(self._round_share_senders),
            "expected": len(active),
        }
        round_threshold = self.secagg_threshold()
        if round_threshold is not None:
            # Window mode: the threshold tracks the ACTIVE cohort (see
            # secagg_threshold) — clients must share THIS round's secrets at this
            # value, not the enrollment-time one, or a shrunk cohort could never
            # reach the share count again.
            payload["threshold"] = round_threshold
        if complete:
            payload["epks"] = {
                c: base64.b64encode(k).decode()
                for c, k in self._round_share_epks.items()
            }
            payload["inbox"] = dict(self._round_share_blobs.get(client_id, {}))
        return web.json_response(payload)

    async def _handle_unmask_get(self, request: web.Request) -> web.StreamResponse:
        """Survivors poll here after submitting: ``{"status": "none"}`` or the active
        unmask request (round, dropped ids, survivor ids)."""
        if self._unmask_request is None:
            return web.json_response({"status": "none"})
        return web.json_response({"status": "pending", **self._unmask_request})

    async def _handle_unmask_post(self, request: web.Request) -> web.StreamResponse:
        """Buffer one survivor's unmask reveals (Shamir shares of dropped clients'
        X25519 keys and survivors' self-mask seeds)."""
        client_id = request.headers.get(HEADER_CLIENT)
        round_header = request.headers.get(HEADER_ROUND, "")
        if not client_id:
            return web.json_response(
                {"status": "error", "message": "missing client header"}, status=400
            )
        if self._unmask_request is None:
            return web.json_response(
                {"status": "error", "message": "no unmask round active"}, status=403
            )
        if client_id not in self._unmask_request["survivors"]:
            return web.json_response(
                {"status": "error",
                 "message": f"{client_id!r} is not a survivor of this round"},
                status=403,
            )
        # Snapshot the request: every await below can interleave with
        # drain_unmask_reveals clearing it (the under-lock re-validation is the
        # authority; dereferencing self._unmask_request after an await would 500).
        snapshot = self._unmask_request
        try:
            if int(round_header) != snapshot["round"]:
                raise ValueError
        except ValueError:
            return web.json_response(
                {"status": "error",
                 "message": f"reveal for round {round_header!r}, unmask round is "
                            f"{snapshot['round']}"},
                status=400,
            )
        body = await self._read_body(request)
        if self.require_signatures:
            from nanofed_tpu.security.signing import verify_secagg_body_signature

            # Context binds the cohort session nonce AND the round: a reveal captured
            # from an earlier cohort on this server must not verify here (it would
            # carry shares of the OLD cohort's secrets and corrupt recovery).
            verdict = await self._check_signature(
                request, client_id, verify_secagg_body_signature,
                "unmask", body, client_id,
                f"{self._secagg_session}:{snapshot['round']}",
            )
            if verdict is not None:
                return verdict
        try:
            reveals = json.loads(body)
            if not isinstance(reveals.get("sk"), dict) or not isinstance(
                reveals.get("b"), dict
            ):
                raise ValueError("reveals must carry 'sk' and 'b' share maps")
        except Exception as e:
            return web.json_response(
                {"status": "error", "message": f"bad reveals: {e}"}, status=400
            )
        async with self._lock:
            active = self._unmask_request
            # Re-validate EVERYTHING the pre-read checks covered: the request may have
            # been drained and a NEW round's request opened while we awaited the body
            # read / threaded signature verify — a stale round's reveal must not be
            # buffered into the new round (it was validated against a different
            # request).
            if (
                active is None
                or int(round_header) != active["round"]
                or client_id not in active["survivors"]
            ):
                return web.json_response(
                    {"status": "error",
                     "message": "unmask round changed while processing this reveal"},
                    status=409,
                )
            self._unmask_reveals[client_id] = reveals
            count, expected = len(self._unmask_reveals), len(active["survivors"])
        self._log.info("unmask reveals from %s (%d/%d survivors)", client_id, count,
                       expected)
        return web.json_response({"status": "success", "message": "reveals accepted"})

    async def _handle_masked_update(
        self, request: web.Request, client_id: str, round_number: int,
        metrics: dict[str, Any], submit_id: str | None = None,
        fingerprint: str = "",
    ) -> web.StreamResponse:
        """Buffer a pairwise-masked uint32 vector (flagged via ``HEADER_SECAGG``).

        Masked payloads are indistinguishable from uniform noise, so the only possible
        content validation is structural: enrollment, dtype, and exact length (= total
        param count of the published model).  AUTHENTICITY is still enforced: with
        ``require_signatures=True`` the masked body must carry a valid RSA-PSS
        signature over the verbatim bytes + wire context, same policy as the plain
        path (an unsigned forged vector would otherwise corrupt the unmasked sum)."""
        import io

        import numpy as np

        if client_id not in self._secagg_roster:
            self._reject_update("not_enrolled", kind="masked")
            return web.json_response(
                {"status": "error", "message": f"{client_id!r} not enrolled"}, status=403
            )
        if client_id in self._secagg_evicted:
            # An evicted client's round secrets were revealed (its masks are
            # compromised) and the active cohort no longer includes it — accepting
            # its vector would inflate the masked-update count and let it push a
            # slow-but-alive member past the round barrier into eviction.
            self._reject_update("evicted", kind="masked")
            return web.json_response(
                {"status": "error",
                 "message": f"{client_id!r} was evicted from this cohort"}, status=403
            )
        body = await self._read_body(request)
        self._m_bytes_rx.inc(len(body), endpoint="update")
        if self.require_signatures:
            from nanofed_tpu.security.signing import verify_masked_signature

            verdict = await self._check_signature(
                request, client_id, verify_masked_signature,
                body, client_id, round_number, request.headers.get(HEADER_METRICS, "{}"),
            )
            if verdict is not None:
                self._reject_update("bad_signature", kind="masked")
                return verdict
        def _decode_masked() -> np.ndarray:
            # CPU-bound npz decompress + structural check: a masked-submit
            # storm must not starve the event loop, so this runs on the SAME
            # bounded pool as plain-update decodes (``_offload``) — not inline
            # in the handler, and not on to_thread's unbounded default pool.
            with np.load(io.BytesIO(body)) as z:
                vec = z["masked"]
            expected_size = int(
                sum(np.prod(leaf.shape) for leaf in jax.tree.leaves(self._params))
            )
            if vec.dtype != np.uint32 or vec.shape != (expected_size,):
                raise ValueError(
                    f"expected uint32[{expected_size}], got {vec.dtype}{vec.shape}"
                )
            return vec

        try:
            masked = await self._offload(_decode_masked)
        except Exception as e:
            self._reject_update("bad_payload", kind="masked")
            return web.json_response(
                {"status": "error", "message": f"bad masked payload: {e}"}, status=400
            )
        async with self._lock:
            if self._duplicate_submit(client_id, submit_id, fingerprint):
                return self._duplicate_response(client_id, "masked")
            if round_number != self._round:
                self._reject_update("stale_round", kind="masked")
                return web.json_response(
                    {"status": "error",
                     "message": f"update for round {round_number}, server is on {self._round}"},
                    status=400,
                )
            self._masked_updates[client_id] = (masked, metrics)
            self._record_submit_locked(client_id, submit_id, fingerprint)
            accepted = len(self._masked_updates)
        self._m_updates.inc(kind="masked", result="accepted")
        self._log.info("masked update from %s (round %d, %d buffered)", client_id,
                       round_number, accepted)
        return web.json_response(
            {"status": "success", "message": "masked update accepted",
             "update_id": client_id}
        )

    async def _handle_status(self, request: web.Request) -> web.StreamResponse:
        return web.json_response(
            {
                "status": "success",
                "round": self._round,
                "num_updates": len(self._updates),
                "training_active": self._training_active,
            }
        )

    async def _handle_test(self, request: web.Request) -> web.StreamResponse:
        return web.json_response({"status": "success", "message": "server is running"})

    async def _handle_metrics(self, request: web.Request) -> web.StreamResponse:
        """Prometheus text exposition of the attached registry — the whole process's
        instruments, not just this server's (one scrape sees coordinator round/phase
        metrics alongside the wire counters)."""
        text = self.metrics_registry.render_prometheus()
        return web.Response(
            body=text.encode("utf-8"),
            headers={"Content-Type": "text/plain; version=0.0.4; charset=utf-8"},
        )

    # ------------------------------------------------------------------
    # Lifecycle (parity: server.py:319-340)
    # ------------------------------------------------------------------

    @property
    def _app(self) -> web.Application:
        """The underlying aiohttp application (owned by the transport since
        the transport/session split); kept for in-process test harnesses
        (``aiohttp.test_utils.TestServer(server._app)``)."""
        return self.transport.app

    async def start(self) -> None:
        """Start listening.  Only valid on a session that OWNS its transport
        (the single-tenant shape); sessions mounted on a shared transport are
        started once, by the service, via ``transport.start()``."""
        if not self._owns_transport:
            raise RuntimeError(
                "this session rides a shared transport; start the transport "
                "(once) instead of each session"
            )
        await self.transport.start()

    async def stop(self) -> None:
        """Release this session's resources; stops the transport too when this
        session owns it (shared transports are stopped by the service)."""
        if self._owns_transport:
            await self.transport.stop()
        if self._ingest_pipeline is not None:
            self._ingest_pipeline.close()
