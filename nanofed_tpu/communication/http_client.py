"""HTTP federation client (real-network mode).

Capability parity with ``HTTPClient`` (``nanofed/communication/http/client.py:33-242``):
an async context manager that fetches the global model, submits local updates, and polls
server status until termination — with binary npz payloads instead of JSON float lists.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass
from typing import Any, Callable

import aiohttp
import jax

from nanofed_tpu.communication.codec import (
    ENCODING_Q8_DELTA,
    ENCODING_TOPK8,
    decode_delta_topk8,
    decode_params,
    encode_delta_q8,
    encode_delta_topk8,
    encode_params,
    reconstruct_q8,
)
from nanofed_tpu.communication.http_server import (
    HEADER_CLIENT,
    HEADER_ENCODING,
    HEADER_METRICS,
    HEADER_ROUND,
    HEADER_SECAGG,
    HEADER_SIGNATURE,
    HEADER_STATUS,
    HEADER_SUBMIT,
    HEADER_TRACE,
)
from nanofed_tpu.communication.retry import (
    RETRYABLE_STATUSES,
    RetryPolicy,
    parse_retry_after,
)
from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.core.types import Params
from nanofed_tpu.observability.registry import MetricsRegistry, get_registry
from nanofed_tpu.observability.tracing import new_trace
from nanofed_tpu.utils.clock import SYSTEM_CLOCK, Clock
from nanofed_tpu.utils.logger import Logger

#: Connection-level failures a retry can fix (the server restarted, the
#: connection was severed mid-flight, the request timed out in transit).
_RETRYABLE_EXCEPTIONS = (aiohttp.ClientConnectionError, asyncio.TimeoutError)


@dataclass(frozen=True)
class ClientEndpoints:
    """Parity: ``ClientEndpoints`` (``client.py:24-30``) + secure-aggregation routes."""

    model: str = "/model"
    update: str = "/update"
    status: str = "/status"
    secagg_register: str = "/secagg/register"
    secagg_roster: str = "/secagg/roster"
    secagg_shares: str = "/secagg/shares"
    secagg_unmask: str = "/secagg/unmask"


@dataclass(frozen=True)
class SecAggRoster:
    """The completed cohort roster a client needs to mask its update: canonical client
    order (mask sign convention), everyone's X25519 public key, the cohort's negotiated
    mask backend, and this framework's twist — server-computed NORMALIZED FedAvg
    weights, so the masked modular sum IS the weighted mean and no per-client weight
    ever reaches the server next to a payload."""

    client_order: list[str]
    public_keys: dict[str, bytes]
    weights: dict[str, float]
    backend: str = "host"
    # Cohort-derived Shamir threshold (dropout-tolerant window enrollment): the server
    # announces the threshold it froze with the roster (> n/2 of who actually
    # enrolled).  None on exact-cohort rosters — clients then use their configured
    # value.  Either way make_dropout_shares re-validates t > n/2 before any secret
    # is shared, so a server announcing a too-small threshold is refused client-side.
    threshold: int | None = None

    def index_of(self, client_id: str) -> int:
        return self.client_order.index(client_id)

    def ordered_keys(self) -> list[bytes]:
        return [self.public_keys[c] for c in self.client_order]


class HTTPClient:
    """One federated client's connection to the server.

    Usage parity with ``client.py:83-98``::

        async with HTTPClient(url, "client_1") as client:
            params, rnd, active = await client.fetch_global_model(template)
            ...train...
            await client.submit_update(params, metrics)
    """

    def __init__(
        self,
        server_url: str,
        client_id: str,
        endpoints: ClientEndpoints | None = None,
        timeout_s: float = 300.0,
        security_manager: Any | None = None,
        update_encoding: str = "npz",
        topk_fraction: float = 0.05,
        registry: MetricsRegistry | None = None,
        retry: RetryPolicy | None = None,
        clock: Clock | None = None,
        wire_filter: Callable[[str, bytes], bytes] | None = None,
    ) -> None:
        """``security_manager`` (a ``nanofed_tpu.security.SecurityManager``) makes every
        submitted update carry an RSA-PSS signature header; pair it with a server
        configured with ``require_signatures=True`` and this client's public key.

        ``update_encoding="q8-delta"`` ships each update as its stochastically-rounded
        int8 round DELTA instead of full float params — ~4x fewer bytes on the
        client->server wire (see ``codec.encode_delta_q8``).
        ``update_encoding="topk8-delta"`` additionally keeps only the top
        ``topk_fraction`` of each leaf's coordinates by magnitude, with ERROR
        FEEDBACK: the un-sent tail accumulates in this client and rides the next
        round's delta, so the bias of top-k selection cancels over rounds
        (Seide et al. 2014).  Both require fetching the global model through THIS
        client each round (the delta's base); signatures are computed over the
        server's exact reconstruction, so signing composes.

        ``retry`` (a ``RetryPolicy``) makes model fetches and update submits
        survive transient failures: connection errors, server restarts, and
        admission-control 429s are retried with exponential backoff + jitter
        (429 ``Retry-After`` is honored as a floor).  Every logical submit
        carries an idempotency key (``X-NanoFed-Submit``), so a retry after a
        lost ACK is folded by the server AT MOST once — the retry policy
        composes with the topk8 ``_pending_base`` error-feedback contract
        instead of double-counting deltas.  Protocol rejections (400 stale
        round, 403 signature, 413) stay final: retrying them verbatim cannot
        succeed.

        ``clock`` injects the time source for backoff sleeps and poll
        deadlines (default: the real event-loop clock); ``wire_filter``
        — ``(endpoint, body) -> body`` — is a fault-injection hook applied to
        outgoing update bodies at the wire boundary (see
        ``nanofed_tpu.faults``), simulating in-flight corruption AFTER
        signing, exactly like a flipped bit on the network."""
        if update_encoding not in ("npz", ENCODING_Q8_DELTA, ENCODING_TOPK8):
            raise NanoFedError(
                f"unknown update_encoding {update_encoding!r} (choose 'npz', "
                f"'{ENCODING_Q8_DELTA}', or '{ENCODING_TOPK8}')"
            )
        if not 0.0 < topk_fraction <= 1.0:
            raise NanoFedError("topk_fraction must be in (0, 1]")
        self.server_url = server_url.rstrip("/")
        self.client_id = client_id
        self.endpoints = endpoints or ClientEndpoints()
        self.security_manager = security_manager
        self.update_encoding = update_encoding
        self.topk_fraction = topk_fraction
        self.retry = retry
        self.wire_filter = wire_filter
        self._clock = clock or SYSTEM_CLOCK
        self._retry_rng = retry.rng_for(client_id) if retry is not None else None
        self._timeout = aiohttp.ClientTimeout(total=timeout_s)
        self._session: aiohttp.ClientSession | None = None
        self._log = Logger()
        self.current_round = 0
        self._submit_seq = 0  # idempotency-key counter (one per LOGICAL submit)
        self._last_update_post: tuple[str, bytes, dict[str, str]] | None = None
        self._secagg_session = ""  # cohort session nonce, cached from the roster
        self._last_global: Params | None = None  # compressed-delta base, set by fetch
        self._residual: Params | None = None  # topk8 error-feedback accumulator
        # After a REJECTED topk8 submit the whole un-sent delta is folded into
        # _residual; _pending_base remembers the local params that fold covered, so
        # an immediate retry measures only the training since the fold (zero on an
        # identical retry) instead of double-counting the round's delta.
        self._pending_base: Params | None = None
        # Client-side wire metrics (observability subsystem).
        reg = registry or get_registry()
        self._m_bytes_tx = reg.counter(
            "nanofed_client_bytes_sent_total",
            "Request body bytes sent by HTTP clients, by endpoint",
            labels=("endpoint",),
        )
        self._m_bytes_rx = reg.counter(
            "nanofed_client_bytes_received_total",
            "Response body bytes fetched by HTTP clients, by endpoint",
            labels=("endpoint",),
        )
        self._m_submissions = reg.counter(
            "nanofed_client_submissions_total",
            "Update submissions by result (accepted / rejected)",
            labels=("result",),
        )
        self._m_codec_ratio = reg.gauge(
            "nanofed_client_codec_ratio",
            "Last update's wire bytes / raw float32 bytes, by encoding",
            labels=("encoding",),
        )
        self._m_retries = reg.counter(
            "nanofed_client_retries_total",
            "Request retries by endpoint and failure reason",
            labels=("endpoint", "reason"),
        )

    @property
    def secagg_session(self) -> str:
        """The cohort session nonce (set by ``fetch_secagg_roster``) — the context
        share-blob AADs and auxiliary-POST signatures bind to."""
        return self._secagg_session

    async def __aenter__(self) -> "HTTPClient":
        self._session = aiohttp.ClientSession(timeout=self._timeout)
        return self

    async def __aexit__(self, *exc: Any) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    def _require_session(self) -> aiohttp.ClientSession:
        if self._session is None:
            raise NanoFedError("HTTPClient must be used as an async context manager")
        return self._session

    async def _request_with_retries(
        self,
        method: str,
        url: str,
        *,
        data: bytes | None = None,
        headers: dict[str, str] | None = None,
        endpoint: str = "",
    ) -> tuple[int, dict[str, str], bytes | None, str | None]:
        """One LOGICAL request under the retry policy (a single plain request
        when no policy is configured).

        Retries connection-level failures and the retryable statuses (429 with
        its ``Retry-After`` honored as a backoff floor, 502/503/504) with
        exponential backoff + jitter, inside the policy's attempt and budget
        limits; protocol rejections (400/403/413/...) return immediately.
        Returns ``(status, response_headers, body, error_message)`` — body is
        the response bytes on 200, error_message the server's explanation (or
        the exception) otherwise; connection-level failure is status ``-1``.
        The SAME bytes and headers ride every attempt, so a retried submit
        keeps its idempotency key and its signature."""
        session = self._require_session()
        policy = self.retry
        deadline = (
            self._clock.time() + policy.budget_s
            if policy is not None and policy.budget_s is not None
            else None
        )
        attempt = 1
        while True:
            retry_after: float | None = None
            message: str | None = None
            try:
                async with session.request(
                    method, url, data=data, headers=headers
                ) as resp:
                    status = resp.status
                    if status == 200:
                        return status, dict(resp.headers), await resp.read(), None
                    retry_after = parse_retry_after(resp.headers.get("Retry-After"))
                    # Framework error pages (413 too-large, 500) are text, not
                    # JSON.
                    try:
                        message = (await resp.json()).get("message")
                    except Exception:
                        message = (await resp.text())[:200]
                retryable = status in RETRYABLE_STATUSES
                reason = f"http_{status}"
            except _RETRYABLE_EXCEPTIONS as e:
                status = -1
                message = f"{type(e).__name__}: {e}"
                retryable, reason = True, type(e).__name__
            if policy is None or not retryable or attempt >= policy.max_attempts:
                return status, {}, None, message
            delay = policy.backoff_s(attempt, self._retry_rng, retry_after)
            if deadline is not None and self._clock.time() + delay > deadline:
                return status, {}, None, f"{message} (retry budget exhausted)"
            self._m_retries.inc(endpoint=endpoint, reason=reason)
            self._log.warning(
                "%s %s failed (%s); retry %d/%d in %.3fs",
                method, endpoint, reason, attempt, policy.max_attempts - 1, delay,
            )
            await self._clock.sleep(delay)
            attempt += 1

    async def fetch_global_model(
        self, like: Params | None = None
    ) -> tuple[Params | None, int, bool]:
        """GET the current global model.

        Returns ``(params, round_number, training_active)``; params is None when the
        server has terminated training (parity: ``client.py:104-145``).  With a
        ``retry`` policy the fetch rides out transient connection failures —
        including a server restarting mid-round — before raising.
        """
        url = self.server_url + self.endpoints.model
        status, resp_headers, payload, message = await self._request_with_retries(
            "GET", url, endpoint="model"
        )
        if status != 200 or payload is None:
            raise NanoFedError(f"fetch_global_model: HTTP {status} ({message})")
        round_number = int(resp_headers.get(HEADER_ROUND, "0"))
        self.current_round = round_number
        if resp_headers.get(HEADER_STATUS) == "terminated":
            return None, round_number, False
        self._m_bytes_rx.inc(len(payload), endpoint="model")
        params = decode_params(payload, like=like)
        if self.update_encoding in (ENCODING_Q8_DELTA, ENCODING_TOPK8):
            # Pin the delta base.  Not kept for plain npz — it would hold a full
            # extra model copy per client process for nothing.
            self._last_global = params
            # A fresh base resets the retry bookkeeping: the next delta is measured
            # against THIS global (any mass a rejected submit left behind is already
            # accumulated in _residual, which rides the next delta as usual).
            self._pending_base = None
        return params, round_number, True

    async def submit_update(self, params: Params, metrics: dict[str, Any]) -> bool:
        """POST local training results for the current round (parity:
        ``client.py:158-211``).

        Under ``update_encoding="q8-delta"`` the body is the quantized round delta and
        the signature covers the server's exact reconstruction (base + dequantized
        delta — recomputed locally with the same numpy float32 arithmetic), so a
        verifying server accepts precisely what it will aggregate.

        Every call is one LOGICAL submit with a fresh idempotency key; with a
        ``retry`` policy the same bytes + key are re-POSTed through transient
        failures, and the server folds the key at most once (a retry after a
        lost ACK returns its cached acceptance).  If every attempt fails, the
        client assumes the update was NOT applied (topk8 folds the whole delta
        into the error-feedback residual) — the idempotency key is what keeps
        that assumption safe: should the server actually have buffered a lost-
        ACK attempt, a later identical retry would be answered as a duplicate
        rather than double-counted."""
        self._require_session()
        url = self.server_url + self.endpoints.update
        self._submit_seq += 1
        headers = {
            HEADER_CLIENT: self.client_id,
            HEADER_ROUND: str(self.current_round),
            HEADER_METRICS: json.dumps(metrics),
            HEADER_SUBMIT: f"{self.client_id}:{self.current_round}:{self._submit_seq}",
            # Trace context, derived from the same identity as the idempotency
            # key: retries of this logical submit ride ONE trace, so the round
            # that finally consumes it resolves every wire attempt at once.
            HEADER_TRACE: new_trace(
                self.client_id, self.current_round, self._submit_seq
            ).header(),
        }
        staged_residual: Params | None = None
        if self.update_encoding in (ENCODING_Q8_DELTA, ENCODING_TOPK8):
            import numpy as np

            if self._last_global is None:
                raise NanoFedError(
                    f"{self.update_encoding} encoding needs the round's global model "
                    "as its base — call fetch_global_model on this client before "
                    "submit_update"
                )
            # After a rejected topk8 submit, _residual already holds everything up
            # to _pending_base — measure only the training SINCE the fold, or an
            # immediate retry would double-count the round's delta.
            delta_base = (
                self._pending_base
                if self._pending_base is not None
                else self._last_global
            )
            delta = jax.tree.map(
                lambda p, g: np.asarray(p, np.float32) - np.asarray(g, np.float32),
                params, delta_base,
            )
            if self.update_encoding == ENCODING_TOPK8:
                # Error feedback: last round's un-sent tail rides this delta, and
                # this round's un-sent tail (selection AND quantization error) is
                # kept for the next — the top-k bias cancels over rounds.
                if self._residual is not None:
                    delta = jax.tree.map(np.add, delta, self._residual)
                body = encode_delta_topk8(delta, self.topk_fraction)
                sent = decode_delta_topk8(body, like=self._last_global)
                # STAGED, not committed: the sent mass only leaves the residual
                # once the server ACCEPTS (a rejected submit must keep the whole
                # delta accumulated or that mass is lost from both sides forever).
                staged_residual = jax.tree.map(
                    lambda d, s: d - np.asarray(s, np.float32), delta, sent
                )
                # Same float32 arithmetic as the server's reconstruct_topk8 —
                # reusing the decode above instead of decoding the payload twice.
                signed_params = jax.tree.map(
                    lambda g, s: np.asarray(g, np.float32)
                    + np.asarray(s, np.float32),
                    self._last_global, sent,
                )
                headers[HEADER_ENCODING] = ENCODING_TOPK8
            else:
                body = encode_delta_q8(delta)
                # What the SERVER will reconstruct (dequantization is lossy; sign
                # that, not the local pre-quantization params) — via the SHARED
                # helper, so client and server arithmetic cannot drift apart.
                signed_params = reconstruct_q8(self._last_global, body)
                headers[HEADER_ENCODING] = ENCODING_Q8_DELTA
        else:
            body = encode_params(params)
            signed_params = params
        raw_bytes = sum(int(leaf.size) * 4 for leaf in jax.tree.leaves(params))
        if raw_bytes:
            self._m_codec_ratio.set(
                len(body) / raw_bytes, encoding=self.update_encoding
            )
        if self.security_manager is not None:
            import base64

            # Sign the exact wire context (client, round, verbatim metrics header) plus
            # the params, so a captured update cannot be replayed into a later round or
            # have its metrics (aggregation weight) rewritten.
            signature = self.security_manager.sign_update(
                signed_params, self.client_id, self.current_round,
                headers[HEADER_METRICS],
            )
            headers[HEADER_SIGNATURE] = base64.b64encode(signature).decode()
        if self.wire_filter is not None:
            # Fault-injection hook AFTER signing: a corrupted body is what a
            # flipped bit in transit looks like — the server must reject it
            # (bad payload / bad signature), never aggregate it.
            body = self.wire_filter("update", body)
        self._m_bytes_tx.inc(len(body), endpoint="update")
        self._last_update_post = (url, bytes(body), dict(headers))
        status, _, _, message = await self._request_with_retries(
            "POST", url, data=body, headers=headers, endpoint="update"
        )
        if status != 200:
            self._log.warning("update rejected (HTTP %d): %s", status, message)
            self._m_submissions.inc(result="rejected")
            if self.update_encoding == ENCODING_TOPK8:
                # A rejected submit applied NOTHING server-side: fold the WHOLE
                # combined delta (this round's progress + all accumulated tail)
                # into the accumulator so true error-feedback semantics hold
                # across a dropped round — the mass rides the next round's
                # delta instead of vanishing from both sides forever.
                # _pending_base pins where the fold stopped, so an immediate
                # retry contributes only post-fold training (see submit above).
                # A lost-ACK attempt whose retries ALL fail leaves genuine
                # at-most-once ambiguity (the server may have buffered attempt
                # 1); the retry policy makes that window small, and the next
                # fetch_global_model resets the base either way.
                self._residual = delta
                self._pending_base = params
            return False
        if staged_residual is not None:
            self._residual = staged_residual
            self._pending_base = None
        self._m_submissions.inc(result="accepted")
        return True

    async def resend_last_update(self) -> bool:
        """Re-POST the EXACT bytes + headers (same idempotency key) of the last
        ``submit_update`` — the duplicate a retry storm produces after a lost
        ACK, exposed directly so the chaos harness can drive N duplicates
        deterministically.  The server must fold the key at most once; error-
        feedback state is deliberately untouched (the logical submit already
        settled it)."""
        if self._last_update_post is None:
            raise NanoFedError("no update has been submitted yet")
        url, body, headers = self._last_update_post
        status, _, _, message = await self._request_with_retries(
            "POST", url, data=body, headers=headers, endpoint="update"
        )
        if status != 200:
            self._log.warning("duplicate update rejected (HTTP %d): %s", status, message)
            return False
        return True

    # ------------------------------------------------------------------
    # Secure aggregation (Bonawitz pairwise masking over the wire)
    # ------------------------------------------------------------------

    async def register_secagg(
        self, public_key: bytes, num_samples: float, backend: str = "host"
    ) -> bool:
        """Enroll in the secure-aggregation cohort with this client's X25519 public key,
        its FedAvg sample count, and its mask-expansion ``backend`` ('host' numpy-Philox
        or 'device' TPU-kernel — wire-incompatible streams, so the server pins the first
        enrollment's backend and refuses mixed cohorts at registration).  With a
        ``security_manager``, the enrollment is RSA-PSS-signed over the server's
        per-cohort session nonce (fetched from the roster endpoint first) — required by
        ``require_signatures=True`` servers, and what makes a captured enrollment
        unreplayable into a later cohort."""
        import base64

        session = self._require_session()
        url = self.server_url + self.endpoints.secagg_register
        headers = {HEADER_CLIENT: self.client_id}
        if self.security_manager is not None:
            async with session.get(
                self.server_url + self.endpoints.secagg_roster
            ) as resp:
                if resp.status != 200:
                    self._log.warning(
                        "secagg session fetch rejected (HTTP %d)", resp.status
                    )
                    return False
                cohort_session = (await resp.json()).get("session", "")
            signature = self.security_manager.sign_enrollment(
                self.client_id, public_key, num_samples, cohort_session, backend
            )
            headers[HEADER_SIGNATURE] = base64.b64encode(signature).decode()
        async with session.post(
            url,
            json={"public_key": base64.b64encode(public_key).decode(),
                  "num_samples": num_samples, "backend": backend},
            headers=headers,
        ) as resp:
            if resp.status != 200:
                try:
                    message = (await resp.json()).get("message")
                except Exception:
                    message = ""
                self._log.warning("secagg registration rejected (HTTP %d): %s",
                                  resp.status, message)
                return False
        return True

    async def fetch_secagg_roster(
        self, poll_interval_s: float = 0.05, timeout_s: float = 30.0
    ) -> SecAggRoster:
        """Poll the roster endpoint until the cohort is complete."""
        import base64

        session = self._require_session()
        url = self.server_url + self.endpoints.secagg_roster
        deadline = self._clock.time() + timeout_s
        while True:
            async with session.get(url) as resp:
                if resp.status != 200:
                    raise NanoFedError(f"fetch_secagg_roster: HTTP {resp.status}")
                payload = await resp.json()
            self._secagg_session = str(payload.get("session", ""))
            if payload.get("complete"):
                raw_t = payload.get("threshold")
                return SecAggRoster(
                    client_order=list(payload["client_order"]),
                    public_keys={c: base64.b64decode(k)
                                 for c, k in payload["public_keys"].items()},
                    weights={c: float(w) for c, w in payload["weights"].items()},
                    backend=str(payload.get("backend", "host")),
                    threshold=int(raw_t) if raw_t is not None else None,
                )
            if self._clock.time() > deadline:
                raise NanoFedError(
                    f"secagg roster incomplete after {timeout_s}s "
                    f"({payload.get('enrolled')}/{payload.get('expected')})"
                )
            await self._clock.sleep(poll_interval_s)

    async def fetch_secagg_participants(self) -> list[str]:
        """This round's ACTIVE cohort (enrolled minus evicted) — what the per-round
        shares must cover."""
        participants, _ = await self.fetch_secagg_round_info()
        return participants

    async def fetch_secagg_round_info(self) -> tuple[list[str], int | None]:
        """This round's ACTIVE cohort plus the server-announced Shamir threshold for
        the round (window enrollment re-derives it from the active cohort as
        evictions shrink it; None on exact-cohort servers — use the shared config).
        ``make_dropout_shares`` re-validates t > m/2 client-side either way."""
        session = self._require_session()
        url = self.server_url + self.endpoints.secagg_shares
        async with session.get(url, headers={HEADER_CLIENT: self.client_id}) as resp:
            if resp.status != 200:
                raise NanoFedError(f"fetch_secagg_round_info: HTTP {resp.status}")
            payload = await resp.json()
        raw_t = payload.get("threshold")
        return list(payload["participants"]), (int(raw_t) if raw_t is not None else None)

    async def deposit_secagg_shares(
        self, round_number: int, ephemeral_public_key: bytes, blobs: dict[str, str],
        self_seed_commitment: bytes | None = None,
    ) -> bool:
        """Deposit this client's ROUND secrets (dropout-tolerant mode, start of each
        round): the fresh ephemeral mask public key, the sealed Shamir share blobs
        covering the active cohort (see ``security.secure_agg.make_dropout_shares``),
        and the sha256 commitment to the self-mask seed (lets recovery detect corrupt
        shares instead of silently corrupting the model)."""
        import base64

        session = self._require_session()
        payload: dict[str, Any] = {
            "epk": base64.b64encode(ephemeral_public_key).decode(),
            "blobs": blobs,
        }
        if self_seed_commitment is not None:
            payload["bh"] = base64.b64encode(self_seed_commitment).decode()
        body = json.dumps(payload).encode()
        headers = {HEADER_CLIENT: self.client_id,
                   HEADER_ROUND: str(round_number),
                   "Content-Type": "application/json"}
        if self.security_manager is not None:
            signature = self.security_manager.sign_secagg_body(
                "shares", body, self.client_id,
                f"{self._secagg_session}:{round_number}",
            )
            headers[HEADER_SIGNATURE] = base64.b64encode(signature).decode()
        url = self.server_url + self.endpoints.secagg_shares
        async with session.post(url, data=body, headers=headers) as resp:
            if resp.status != 200:
                try:
                    message = (await resp.json()).get("message")
                except Exception:
                    message = (await resp.text())[:200]
                self._log.warning("share deposit rejected (HTTP %d): %s",
                                  resp.status, message)
                return False
        return True

    async def fetch_secagg_inbox(
        self, round_number: int | None = None,
        poll_interval_s: float = 0.05, timeout_s: float = 30.0,
    ) -> tuple[dict[str, bytes], dict[str, str]]:
        """Poll the round's share exchange until every active member has deposited;
        returns ``(ephemeral_public_keys, inbox)`` — everyone's round mask key and
        this client's sealed blobs (open with ``open_share_inbox``).

        ``round_number`` pins the exchange to the round this client deposited for: if
        the server advances mid-poll (e.g. the round FAILED and evictions reset the
        share state), the stale wait is cut short with an error the caller can treat
        as "re-fetch the model and start the next round"."""
        import base64

        session = self._require_session()
        url = self.server_url + self.endpoints.secagg_shares
        deadline = self._clock.time() + timeout_s
        while True:
            async with session.get(url, headers={HEADER_CLIENT: self.client_id}) as resp:
                if resp.status != 200:
                    raise NanoFedError(f"fetch_secagg_inbox: HTTP {resp.status}")
                payload = await resp.json()
            if round_number is not None and payload.get("round") != round_number:
                raise NanoFedError(
                    f"share exchange moved to round {payload.get('round')} while "
                    f"waiting on round {round_number}"
                )
            if payload.get("complete"):
                epks = {c: base64.b64decode(k)
                        for c, k in payload["epks"].items()}
                return epks, dict(payload["inbox"])
            if self._clock.time() > deadline:
                raise NanoFedError(
                    f"share deposits incomplete after {timeout_s}s "
                    f"({payload.get('deposited')}/{payload.get('expected')})"
                )
            await self._clock.sleep(poll_interval_s)

    async def poll_unmask_request(self) -> dict[str, Any] | None:
        """One poll of the unmask endpoint: the active request dict (round / dropped /
        survivors) or None."""
        session = self._require_session()
        async with session.get(
            self.server_url + self.endpoints.secagg_unmask
        ) as resp:
            if resp.status != 200:
                raise NanoFedError(f"poll_unmask_request: HTTP {resp.status}")
            payload = await resp.json()
        return payload if payload.get("status") == "pending" else None

    async def submit_unmask_reveals(
        self, round_number: int, reveals: dict[str, Any]
    ) -> bool:
        """POST this survivor's unmask reveals (built with
        ``security.secure_agg.build_unmask_reveals`` — which enforces the
        never-both-secrets refusals client-side)."""
        import base64

        session = self._require_session()
        body = json.dumps(reveals).encode()
        headers = {HEADER_CLIENT: self.client_id,
                   HEADER_ROUND: str(round_number),
                   "Content-Type": "application/json"}
        if self.security_manager is not None:
            # Bound to the cohort session nonce + round: a captured reveal cannot be
            # replayed into a later cohort on the same server.
            signature = self.security_manager.sign_secagg_body(
                "unmask", body, self.client_id,
                f"{self._secagg_session}:{round_number}",
            )
            headers[HEADER_SIGNATURE] = base64.b64encode(signature).decode()
        url = self.server_url + self.endpoints.secagg_unmask
        async with session.post(url, data=body, headers=headers) as resp:
            if resp.status != 200:
                try:
                    message = (await resp.json()).get("message")
                except Exception:
                    message = (await resp.text())[:200]
                self._log.warning("unmask reveals rejected (HTTP %d): %s",
                                  resp.status, message)
                return False
        return True

    async def submit_masked_update(
        self, masked: Any, metrics: dict[str, Any]
    ) -> bool:
        """POST a pairwise-masked uint32 vector (see ``security.secure_agg.mask_update``)
        for the current round.  The server can only ever recover the cohort SUM."""
        import io

        import numpy as np

        session = self._require_session()
        buf = io.BytesIO()
        np.savez_compressed(buf, masked=np.asarray(masked, np.uint32))
        body = buf.getvalue()
        self._submit_seq += 1
        headers = {
            HEADER_CLIENT: self.client_id,
            HEADER_ROUND: str(self.current_round),
            HEADER_METRICS: json.dumps(metrics),
            HEADER_SECAGG: "masked",
            HEADER_SUBMIT: f"{self.client_id}:{self.current_round}:{self._submit_seq}",
        }
        if self.security_manager is not None:
            import base64

            signature = self.security_manager.sign_masked_update(
                body, self.client_id, self.current_round, headers[HEADER_METRICS]
            )
            headers[HEADER_SIGNATURE] = base64.b64encode(signature).decode()
        url = self.server_url + self.endpoints.update
        async with session.post(url, data=body, headers=headers) as resp:
            if resp.status != 200:
                try:
                    message = (await resp.json()).get("message")
                except Exception:
                    message = (await resp.text())[:200]
                self._log.warning("masked update rejected (HTTP %d): %s",
                                  resp.status, message)
                return False
        return True

    async def check_server_status(self) -> dict[str, Any]:
        """GET /status (parity: ``client.py:213-229``)."""
        session = self._require_session()
        async with session.get(self.server_url + self.endpoints.status) as resp:
            if resp.status != 200:
                raise NanoFedError(f"check_server_status: HTTP {resp.status}")
            return await resp.json()

    async def wait_for_completion(self, poll_interval_s: float = 1.0) -> None:
        """Poll status until the server stops training (parity: ``client.py:234-242``,
        which polls at 10 s)."""
        while True:
            status = await self.check_server_status()
            if not status.get("training_active", False):
                return
            await self._clock.sleep(poll_interval_s)
