"""Wire → mesh bridge: drain HTTP ingest buffers into the hierarchical reduce.

ROADMAP item 1's fusion.  Before this module the repo had two disjoint
serving stacks: the batched wire tier (``HTTPTransport`` + ``HTTPServer`` +
``DeviceIngestBuffer``, proven at 10k clients single-host) and the 3-axis
``(hosts, clients, model)`` mesh (proven at 100k *simulated* clients with no
wire).  Here they become one aggregation hierarchy:

* Each mesh host runs a listener + ingest buffer front end.  The buffer's
  batched ``coefs @ buffer`` reduce IS the host-local aggregation stage —
  but drained UNNORMALIZED (``DeviceIngestBuffer.drain_fedavg_partial``:
  ``Σ w_i δ_i`` and the weight mass, not ``Σ (w_i/Σw) δ_i``), because the
  FedAvg normalizer is a global quantity.
* ONE cross-host psum over the ``hosts`` axis then moves exactly one
  model-sized tensor per round — each host's ``[P+1]`` partial row
  (numerator ‖ weight mass) — and the apply ``base + num/den`` lands
  replicated on every host.  This is the same client → host → global
  hierarchy :func:`~nanofed_tpu.parallel.mesh.hierarchical_psum` gives the
  simulated path, with wire clients as the leaves.

Two program builders cover the two dispatch shapes:

* :func:`build_cross_host_reduce` — the RUNTIME program of the federate
  harness's two-stage path: host-local drains happen in the ingest buffers
  (outside jit, per arrival), and this program is the round's single
  cross-host collective.
* :func:`build_drained_ingest_reduce` — the FUSED single-program form
  (per-device ingest slabs → host-local reduce → one hosts psum → apply),
  dispatch-shaped for the program auditor's reference catalog: the
  mesh-discipline check (clients reduce before hosts; one model-sized
  cross-host tensor per round) machine-checks the fusion invariant.

Parity contract (tested in ``tests/integration/test_ingest_parity.py``):
host-local partial drains + cross-host sum ≡ a single host draining the
union of the buffers — exactly, for FedAvg trajectories and FedBuff
staleness accounting, because ``Σ_h Σ_{i∈h} w_i δ_i / Σ_h Σ_{i∈h} w_i`` is
the union's weighted mean under any partition of clients into hosts.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from nanofed_tpu.parallel.mesh import (
    CLIENT_AXIS,
    HOST_AXIS,
    hierarchical_psum,
    multi_axis_shard_map_kwargs,
    replicated_sharding,
)

__all__ = [
    "MASS_LANE",
    "apply_summed_row",
    "assemble_host_rows",
    "build_cross_host_reduce",
    "build_cross_host_row_psum",
    "build_drained_ingest_reduce",
    "host_partial_row",
]

#: Trailing lanes of a host partial row beyond the P model lanes: the weight
#: mass (FedAvg) or live count (FedBuff) that makes the partial composable.
MASS_LANE = 1

#: Division floor for the global weight mass: a round where EVERY host drained
#: an empty buffer divides zero by this instead of NaN-ing the model — the
#: caller detects the failure from the returned mass, not from the params.
_MASS_FLOOR = 1e-12


def _require_hosts(mesh: Mesh) -> None:
    if HOST_AXIS not in mesh.axis_names:
        raise ValueError(
            f"the wire→mesh bridge needs a mesh with a {HOST_AXIS!r} axis "
            f"(got axes {mesh.axis_names}); build one with "
            "make_mesh(shape=(hosts, clients, model))"
        )


def host_partial_row(
    partial: Any | None,
    mass: float,
    flat_size: int,
    extra: tuple[float, ...] = (),
) -> np.ndarray:
    """One host's ``[P+1+E]`` contribution to the cross-host reduce: the
    unnormalized drain numerator ‖ its weight mass ‖ optional control lanes.
    An empty drain (``partial is None``) contributes exact zeros in the model
    and mass lanes — the host still participates in the psum (collectives
    admit no absentees), it just adds nothing.  ``extra`` lanes are summed
    across hosts like everything else; the federate harness uses one as a
    stop vote so workers reach round-count consensus THROUGH the collective
    they already run, instead of diverging and deadlocking the next psum."""
    row = np.zeros(flat_size + MASS_LANE + len(extra), np.float32)
    if partial is not None:
        row[:flat_size] = np.asarray(partial, np.float32)
        row[flat_size] = float(mass)
    for i, v in enumerate(extra):
        row[flat_size + MASS_LANE + i] = float(v)
    return row


def assemble_host_rows(mesh: Mesh, local_rows: Any) -> jax.Array:
    """The global ``[H, P+1]`` rows array, hosts-axis sharded, from each
    process's local row block — ``make_array_from_process_local_data`` on a
    real multi-process mesh (no host ever materializes another host's row),
    a plain sharded ``device_put`` on a single-process virtual-hosts mesh
    (where the caller holds all rows)."""
    _require_hosts(mesh)
    sharding = NamedSharding(mesh, P(HOST_AXIS))
    rows = np.atleast_2d(np.asarray(local_rows, np.float32))
    n_hosts = int(mesh.shape[HOST_AXIS])
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(
            sharding, rows, (n_hosts, rows.shape[1])
        )
    if rows.shape[0] != n_hosts:
        raise ValueError(
            f"single-process assembly needs all {n_hosts} host rows, "
            f"got {rows.shape[0]}"
        )
    return jax.device_put(rows, sharding)


def build_cross_host_reduce(
    mesh: Mesh, flat_size: int
) -> Callable[[jax.Array, jax.Array], tuple[jax.Array, jax.Array]]:
    """The ONE cross-host collective of a federated round (two-stage runtime
    path): psum the ``[H, P+1+E]`` host partial rows over ``hosts`` and apply
    ``base + num / den`` once.

    Returns a jitted ``fn(rows, base) -> (new_flat, tail)`` with both outputs
    replicated.  ``tail`` is the psum'd trailing lanes of the row —
    ``tail[0]`` is the global weight mass, ``tail[1:]`` any extra control
    lanes the caller packed via :func:`host_partial_row`.  ``tail[0] == 0``
    means every host drained empty — the round FAILED and ``new_flat == base``
    (the division floor keeps the params finite; the caller decides the
    outcome from the mass).  No buffers are donated: the output aliases
    nothing (``rows`` is consumed, ``base`` may be republished on failure)."""
    _require_hosts(mesh)

    def body(rows: jax.Array, base: jax.Array) -> tuple[jax.Array, jax.Array]:
        # rows block: this host's [H/H, P+1+E] slice — sum collapses the
        # block dim so the psum moves exactly one model-sized row per host.
        total = jax.lax.psum(jnp.sum(rows, axis=0), HOST_AXIS)
        num, den = total[:flat_size], total[flat_size]
        return base + num / jnp.maximum(den, _MASS_FLOOR), total[flat_size:]

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(HOST_AXIS), P()),
        out_specs=(P(), P()),
        **multi_axis_shard_map_kwargs(mesh),
    )
    repl = replicated_sharding(mesh)
    return jax.jit(mapped, out_shardings=(repl, repl))


def build_cross_host_row_psum(
    mesh: Mesh,
) -> Callable[[jax.Array], jax.Array]:
    """The single-collective runtime path: psum the ``[H, P+1+E]`` host rows
    over ``hosts`` and return ONLY the summed row — the apply stays on the
    host (:func:`apply_summed_row`).

    This exists because of a CPU/gloo failure mode the federate harness hit
    at 4 processes: any round whose dispatch carries MORE than one in-flight
    gloo stream (a psum with several replica groups because the mesh has a
    populated clients axis, a ``device_put`` broadcast of the base, a
    replicated-output materialization) can cross transfers between streams in
    gloo's async slot sequencing — ``op.preamble.length <= op.nbytes``
    aborts.  Callers should hand this builder a HOSTS-ONLY mesh (one device
    per process, ``make_mesh(devices=[one per process], shape=(H, 1, 1))``)
    so the compiled program contains exactly one all-reduce with exactly one
    replica group: one gloo stream per round, nothing to cross.  The output
    is each device's local psum result (replicated by the all-reduce itself —
    ring results are bitwise identical on every rank), so no gather/broadcast
    follows it."""
    _require_hosts(mesh)

    def body(rows: jax.Array) -> jax.Array:
        return jax.lax.psum(jnp.sum(rows, axis=0), HOST_AXIS)

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(HOST_AXIS),),
        out_specs=P(),
        **multi_axis_shard_map_kwargs(mesh),
    )
    return jax.jit(mapped, out_shardings=replicated_sharding(mesh))


def apply_summed_row(
    base: np.ndarray, total: np.ndarray, flat_size: int
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side FedAvg apply for :func:`build_cross_host_row_psum`:
    ``(base + num / max(mass, floor), tail)`` in float32 numpy.  Every host
    computes this from the SAME psum'd row and the SAME base (identical by
    induction), so the new params are bitwise identical across hosts without
    a second collective.  ``tail[0] == 0`` means every host drained empty —
    the division floor keeps ``new == base`` exactly."""
    total = np.asarray(total, np.float32)
    base = np.asarray(base, np.float32)
    num, den = total[:flat_size], total[flat_size]
    new = base + num / np.maximum(den, np.float32(_MASS_FLOOR))
    return new.astype(np.float32), total[flat_size:]


def build_drained_ingest_reduce(
    mesh: Mesh, capacity: int, flat_size: int
) -> Callable[[jax.Array, jax.Array, jax.Array], jax.Array]:
    """The fused wire→mesh round reduce as ONE program, for the audit
    catalog's mesh-discipline check and the single-dispatch parity path.

    Per-device inputs (global shapes; sharded jointly over
    ``(hosts, clients)``): the ingest slab ``buf[H·C, capacity, P]`` and raw
    FedAvg weights ``coefs[H·C, capacity]`` (unused slots exactly 0.0, the
    buffer's own convention), plus the replicated flat base.  The body is the
    hierarchy in three lines: the drain's batched ``coefs @ buf`` produces
    each shard's partial, ``psum`` over ``clients`` closes the host-local
    stage on ICI, and ONE ``psum`` over ``hosts`` moves the single
    model-sized ``[P+1]`` row per round that the auditor's cross-host byte
    budget enforces.  The FedAvg apply lands replicated."""
    _require_hosts(mesh)
    data_spec = P((HOST_AXIS, CLIENT_AXIS))

    def body(buf: jax.Array, coefs: jax.Array, base: jax.Array) -> jax.Array:
        # buf block [1, capacity, P]; coefs block [1, capacity].
        num = coefs[0] @ buf[0]  # the DeviceIngestBuffer drain reduce
        row = jnp.concatenate([num, jnp.sum(coefs[0])[None]])
        # Innermost first: clients (host-local) then ONE hosts psum.
        total = hierarchical_psum(row, (HOST_AXIS, CLIENT_AXIS))
        return base + total[:flat_size] / jnp.maximum(total[flat_size], _MASS_FLOOR)

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(data_spec, data_spec, P()),
        out_specs=P(),
        **multi_axis_shard_map_kwargs(mesh),
    )
    return jax.jit(mapped, out_shardings=replicated_sharding(mesh))
