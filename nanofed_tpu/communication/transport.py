"""HTTP transport layer: routing, tenant resolution, and lifecycle.

Split out of ``http_server.py`` (the multi-tenant federation service needs N
per-tenant sessions behind ONE listener): this module owns everything about
the wire that is NOT per-tenant state — the aiohttp application, the route
table, tenant resolution, and the bounded body-read primitive — while
:class:`~nanofed_tpu.communication.http_server.HTTPServer` keeps exactly the
per-session state and handlers (round/version buffers, quotas, admission
counters, secure-aggregation rosters).

Tenant identity travels on the wire two equivalent ways:

* **path prefix** — ``/t/<tenant>/update`` routes to tenant ``<tenant>``'s
  session; this is what multi-tenant swarm clients use (a base URL of
  ``http://host:port/t/<tenant>`` makes every existing client tenant-aware
  without code changes);
* **header** — ``X-NanoFed-Tenant: <tenant>`` on an unprefixed path routes
  the same way (reverse proxies that rewrite paths keep working).

An unknown tenant is a **404** (never a 403: tenant names are not secrets,
and a deleted tenant's stragglers must see a terminal answer, not a retryable
one).  Everything past resolution — admission 429s, quota state, submit-key
dedup windows, chaos injection — happens inside the resolved session, so one
tenant's overload or chaos plan is structurally invisible to every other
tenant's requests.

A single-tenant ``HTTPServer`` (the pre-service shape every existing test and
CLI path constructs) owns a private transport and registers itself as the
DEFAULT session: unprefixed, headerless requests route to it and the wire
protocol is byte-identical to before the split.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Protocol

from aiohttp import web

from nanofed_tpu.observability.registry import MetricsRegistry, get_registry
from nanofed_tpu.utils.logger import Logger

__all__ = [
    "HEADER_TENANT",
    "HTTPTransport",
    "TENANT_PATH_PREFIX",
    "free_port",
    "read_body_bounded",
    "tenant_base_url",
]

#: Tenant identity header (the path-prefix form is ``/t/<tenant>/...``).
HEADER_TENANT = "X-NanoFed-Tenant"

#: Path prefix for tenant-addressed requests: ``/t/<tenant>/<endpoint>``.
TENANT_PATH_PREFIX = "/t"

MAX_REQUEST_SIZE = 100 * 1024 * 1024  # parity with the pre-split server cap

Handler = Callable[[web.Request], Awaitable[web.StreamResponse]]


class TransportSession(Protocol):
    """What the transport needs from a session: logical-path dispatch.

    ``dispatch`` receives the LOGICAL endpoint path (tenant prefix already
    stripped) and the raw request; the session applies its own chaos
    schedule, admission control, and handler."""

    async def dispatch(
        self, path: str, request: web.Request
    ) -> web.StreamResponse: ...


async def read_body_bounded(
    request: web.Request, timeout_s: float
) -> bytes:
    """Read a request body with a TIME bound (``client_max_size`` bounds the
    size): a slowloris peer trickling bytes must not hold a handler — and its
    admission slot — open past ``timeout_s``.  Raises
    ``asyncio.TimeoutError``; the caller owns the 408 answer and its metric
    (each session counts its own read timeouts)."""
    return await asyncio.wait_for(request.read(), timeout=timeout_s)


def _json_error(message: str, status: int) -> web.Response:
    return web.json_response({"status": "error", "message": message},
                             status=status)


class HTTPTransport:
    """One listener multiplexing N tenant sessions (plus an optional default).

    Routing is a catch-all pair — ``/t/{tenant}/{tail}`` and ``/{tail}`` —
    resolved here and dispatched to the session's logical-path table, so
    adding a tenant is a dict insert, not a router mutation (aiohttp routers
    freeze at startup; a live service must admit tenants after ``start``)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8080,
        max_request_size: int = MAX_REQUEST_SIZE,
        registry: MetricsRegistry | None = None,
    ) -> None:
        self.host = host
        self.port = port
        self._log = Logger()
        self._sessions: dict[str, TransportSession] = {}
        self._default: TransportSession | None = None
        self.metrics_registry = registry or get_registry()
        self._m_unknown_tenant = self.metrics_registry.counter(
            "nanofed_unknown_tenant_total",
            "Requests addressed to a tenant this transport does not host (404)",
        )
        self._app = web.Application(client_max_size=max_request_size)
        self._app.router.add_route(
            "*", TENANT_PATH_PREFIX + "/{tenant}/{tail:.+}",
            self._dispatch_tenant_path,
        )
        self._app.router.add_route("*", "/{tail:.+}", self._dispatch_root_path)
        self._runner: web.AppRunner | None = None

    @property
    def app(self) -> web.Application:
        return self._app

    # -- session registry -------------------------------------------------

    def add_session(
        self, session: TransportSession, tenant: str | None = None
    ) -> None:
        """Mount a session.  ``tenant=None`` mounts it as the DEFAULT (the
        single-tenant shape: unprefixed, headerless requests); a named tenant
        answers under ``/t/<tenant>/...`` and the tenant header.  Replacing a
        live name is refused — a tenant is removed first, never silently
        swapped under in-flight requests."""
        if tenant is None:
            if self._default is not None and self._default is not session:
                raise ValueError("a default session is already mounted")
            self._default = session
            return
        if not tenant or "/" in tenant:
            raise ValueError(f"invalid tenant name {tenant!r}")
        if self._sessions.get(tenant) not in (None, session):
            raise ValueError(f"tenant {tenant!r} is already mounted")
        self._sessions[tenant] = session

    def remove_session(self, tenant: str) -> None:
        """Unmount a tenant; its in-flight handlers finish, later requests
        404.  Unknown names are a no-op (removal must be idempotent for a
        supervisor retrying a teardown)."""
        self._sessions.pop(tenant, None)

    def tenants(self) -> list[str]:
        return sorted(self._sessions)

    # -- dispatch ----------------------------------------------------------

    async def _dispatch_tenant_path(
        self, request: web.Request
    ) -> web.StreamResponse:
        tenant = request.match_info["tenant"]
        session = self._sessions.get(tenant)
        if session is None:
            self._m_unknown_tenant.inc()
            return _json_error(f"unknown tenant {tenant!r}", 404)
        return await session.dispatch(
            "/" + request.match_info["tail"], request
        )

    async def _dispatch_root_path(
        self, request: web.Request
    ) -> web.StreamResponse:
        tenant = request.headers.get(HEADER_TENANT)
        if tenant is not None:
            session = self._sessions.get(tenant)
            if session is None:
                self._m_unknown_tenant.inc()
                return _json_error(f"unknown tenant {tenant!r}", 404)
        else:
            session = self._default
            if session is None:
                # A tenant-only transport has no anonymous surface: the
                # caller forgot its tenant identity, say so.
                self._m_unknown_tenant.inc()
                return _json_error(
                    "no default session: address a tenant via "
                    f"{TENANT_PATH_PREFIX}/<tenant>/... or the "
                    f"{HEADER_TENANT} header",
                    404,
                )
        return await session.dispatch("/" + request.match_info["tail"], request)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._runner = web.AppRunner(self._app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self._log.info(
            "HTTP transport on %s:%d (%d tenant sessions%s)",
            self.host, self.port, len(self._sessions),
            ", default mounted" if self._default is not None else "",
        )

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None


def tenant_base_url(base: str, tenant: str) -> str:
    """The tenant-prefixed base URL swarm/HTTP clients point at:
    ``http://host:port`` + tenant -> ``http://host:port/t/<tenant>``."""
    return base.rstrip("/") + f"{TENANT_PATH_PREFIX}/{tenant}"


def free_port() -> int:
    """An ephemeral localhost port (in-process harnesses; the canonical copy —
    loadgen and the federation service both import it)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
