"""Real-network transport (stage 9 of SURVEY.md §7).

Replaces ``nanofed/communication/http/`` with binary-payload HTTP federation.  The SPMD
simulator never imports this package; it exists for true cross-device runs.  Requires the
``[net]`` extra (aiohttp); the codec itself is dependency-free.
"""

from nanofed_tpu.communication.codec import (
    ENCODING_Q8_DELTA,
    ENCODING_TOPK8,
    decode_delta_q8,
    decode_delta_topk8,
    decode_params,
    encode_delta_q8,
    encode_delta_topk8,
    encode_params,
    reconstruct_q8,
    reconstruct_topk8,
)

_NET_EXPORTS = {
    "HTTPServer": "http_server",
    "ServerEndpoints": "http_server",
    "HTTPTransport": "transport",
    "HEADER_TENANT": "transport",
    "tenant_base_url": "transport",
    "HTTPClient": "http_client",
    "ClientEndpoints": "http_client",
    "NetworkCoordinator": "network_coordinator",
    "NetworkRoundConfig": "network_coordinator",
    "fedbuff_combine": "network_coordinator",
    "stack_model_updates": "network_coordinator",
    "SecAggRoster": "http_client",
    "RetryPolicy": "retry",
    "RETRYABLE_STATUSES": "retry",
    "parse_retry_after": "retry",
}


def __getattr__(name: str):
    if name in _NET_EXPORTS:
        import importlib

        mod = importlib.import_module(f"nanofed_tpu.communication.{_NET_EXPORTS[name]}")
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "ClientEndpoints",
    "ENCODING_Q8_DELTA",
    "ENCODING_TOPK8",
    "HTTPClient",
    "HTTPServer",
    "HTTPTransport",
    "HEADER_TENANT",
    "tenant_base_url",
    "NetworkCoordinator",
    "NetworkRoundConfig",
    "decode_delta_q8",
    "decode_delta_topk8",
    "encode_delta_q8",
    "encode_delta_topk8",
    "fedbuff_combine",
    "reconstruct_q8",
    "reconstruct_topk8",
    "RetryPolicy",
    "RETRYABLE_STATUSES",
    "parse_retry_after",
    "SecAggRoster",
    "ServerEndpoints",
    "decode_params",
    "encode_params",
    "stack_model_updates",
]
