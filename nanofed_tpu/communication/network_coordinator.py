"""Round engine for the real-network mode.

Parity with the reference's HTTP-driven round loop (``nanofed/orchestration/
coordinator.py:282-382``): publish the global model, wait for
``ceil(min_clients * min_completion_rate)`` updates or time out, aggregate, repeat.  The
wait is an asyncio poll like the reference's (``coordinator.py:216-238``), but at 50 ms
granularity instead of 1 s, and the FedAvg reduce itself runs on-device: buffered updates
are stacked into one ``ClientUpdates`` batch and pushed through ``fedavg_combine`` (a
jitted weighted tree-mean), not a per-key Python loop.

The SPMD simulator (``nanofed_tpu.orchestration.Coordinator``) is the primary engine; this
exists for true cross-device federation where clients are separate processes/machines.
"""

from __future__ import annotations

import asyncio
import math
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from nanofed_tpu.aggregation.fedavg import fedavg_combine
from nanofed_tpu.communication.http_server import HTTPServer
from nanofed_tpu.core.types import ClientMetrics, ClientUpdates, ModelUpdate, Params
from nanofed_tpu.utils.logger import Logger


@dataclass(frozen=True)
class NetworkRoundConfig:
    """Parity surface of ``CoordinatorConfig`` (``coordinator.py:26-49``) for the
    network path: wall-clock timeouts are meaningful again here."""

    num_rounds: int = 1
    min_clients: int = 1
    min_completion_rate: float = 1.0
    round_timeout_s: float = 300.0
    poll_interval_s: float = 0.05


def _metric(
    metrics: dict, key: str, default: float, *alt_keys: str, positive: bool = False
) -> float:
    """Defensive float coercion of a client-supplied metric value.

    Clients control the metrics JSON: the server validates the params payload strictly
    but metrics only as parseable JSON, so a single client sending ``"loss": "oops"``
    must not raise inside ``train_round`` and kill the round for everyone.  Non-numeric
    or non-finite values fall back to ``default``; ``positive=True`` additionally
    rejects values <= 0 (a negative ``num_samples`` could zero the cohort's weight sum
    and blow up the weighted mean).
    """
    for k in (key, *alt_keys):
        if k in metrics:
            try:
                v = float(metrics[k])
            except (TypeError, ValueError):
                continue
            if math.isfinite(v) and not (positive and v <= 0):
                return v
    return default


def stack_model_updates(updates: list[ModelUpdate]) -> ClientUpdates:
    """Stack host-path ``ModelUpdate`` records into one device batch for aggregation."""
    params = jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
                          *[u.params for u in updates])
    weights = jnp.asarray(
        [_metric(u.metrics, "num_samples", 1.0, "samples_processed", positive=True)
         for u in updates],
        jnp.float32,
    )
    metrics = ClientMetrics(
        loss=jnp.asarray([_metric(u.metrics, "loss", 0.0) for u in updates]),
        accuracy=jnp.asarray([_metric(u.metrics, "accuracy", 0.0) for u in updates]),
        samples=weights,
    )
    return ClientUpdates(params=params, weights=weights, metrics=metrics)


class NetworkCoordinator:
    """Drives federated rounds over an ``HTTPServer``."""

    def __init__(self, server: HTTPServer, params: Params, config: NetworkRoundConfig):
        self.server = server
        self.params = params
        self.config = config
        self.history: list[dict[str, Any]] = []
        self._log = Logger()

    async def _wait_for_clients(self, required: int) -> bool:
        """Poll the update buffer until ``required`` updates arrive or timeout
        (parity: ``coordinator.py:205-245``)."""
        deadline = asyncio.get_event_loop().time() + self.config.round_timeout_s
        while asyncio.get_event_loop().time() < deadline:
            if self.server.num_updates() >= required:
                return True
            await asyncio.sleep(self.config.poll_interval_s)
        return self.server.num_updates() >= required

    async def train_round(self, round_number: int) -> dict[str, Any]:
        await self.server.publish_model(self.params, round_number)
        required = max(1, math.ceil(self.config.min_clients * self.config.min_completion_rate))
        ok = await self._wait_for_clients(required)
        updates = await self.server.drain_updates()
        if not ok or len(updates) < required:
            self._log.warning(
                "round %d FAILED: %d/%d updates", round_number, len(updates), required
            )
            record = {"round": round_number, "status": "FAILED", "num_clients": len(updates)}
            self.history.append(record)
            return record
        stacked = stack_model_updates(updates)
        self.params = fedavg_combine(stacked)
        record = {
            "round": round_number,
            "status": "COMPLETED",
            "num_clients": len(updates),
            "metrics": {
                "loss": float((stacked.metrics.loss * stacked.weights).sum()
                              / stacked.weights.sum()),
                "accuracy": float((stacked.metrics.accuracy * stacked.weights).sum()
                                  / stacked.weights.sum()),
            },
        }
        self.history.append(record)
        self._log.info("round %d: %s", round_number, record["metrics"])
        return record

    async def run(self) -> list[dict[str, Any]]:
        """All rounds, then signal termination to polling clients."""
        for r in range(self.config.num_rounds):
            await self.train_round(r)
        self.server.stop_training()
        return self.history
