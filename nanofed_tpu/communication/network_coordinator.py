"""Round engine for the real-network mode.

Parity with the reference's HTTP-driven round loop (``nanofed/orchestration/
coordinator.py:282-382``): publish the global model, wait for
``ceil(min_clients * min_completion_rate)`` updates or time out, aggregate, repeat.  The
wait is an asyncio poll like the reference's (``coordinator.py:216-238``), but at 50 ms
granularity instead of 1 s, and the FedAvg reduce itself runs on-device: buffered updates
are stacked into one ``ClientUpdates`` batch and pushed through ``fedavg_combine`` (a
jitted weighted tree-mean), not a per-key Python loop.

The SPMD simulator (``nanofed_tpu.orchestration.Coordinator``) is the primary engine; this
exists for true cross-device federation where clients are separate processes/machines.
"""

from __future__ import annotations

import asyncio
import math
import time
from contextlib import asynccontextmanager
from dataclasses import dataclass, replace
from pathlib import Path
from typing import TYPE_CHECKING, Any

import jax
import jax.numpy as jnp
import numpy as np

from nanofed_tpu.aggregation.fedavg import fedavg_combine
from nanofed_tpu.aggregation.robust import (
    RobustAggregationConfig,
    robust_aggregate,
    robust_floor,
)
from nanofed_tpu.communication.http_server import HTTPServer
from nanofed_tpu.core.types import ClientMetrics, ClientUpdates, ModelUpdate, Params
from nanofed_tpu.faults.plan import InjectedServerCrash
from nanofed_tpu.observability.registry import MetricsRegistry
from nanofed_tpu.observability.spans import SpanTracer
from nanofed_tpu.observability.telemetry import RunTelemetry
from nanofed_tpu.orchestration.engine import RoundLedger, completion_required
from nanofed_tpu.security.validation import (
    ValidationConfig,
    ValidationResult,
    loo_zscore,
    reference_shapes,
    update_flat_norm,
    validate_range,
    validate_shape,
)
from nanofed_tpu.utils.clock import SYSTEM_CLOCK, Clock
from nanofed_tpu.utils.logger import Logger

if TYPE_CHECKING:
    # Imported lazily at runtime: secure_agg needs the ``cryptography`` package,
    # which the plain (non-secure) network path must not require just to import
    # this module.
    from nanofed_tpu.persistence.state_store import FileStateStore
    from nanofed_tpu.security.secure_agg import SecureAggregationConfig


@dataclass(frozen=True)
class NetworkRoundConfig:
    """Parity surface of ``CoordinatorConfig`` (``coordinator.py:26-49``) for the
    network path: wall-clock timeouts are meaningful again here."""

    num_rounds: int = 1
    min_clients: int = 1
    min_completion_rate: float = 1.0
    round_timeout_s: float = 300.0
    poll_interval_s: float = 0.05
    # Dropout-tolerant enrollment window: min_clients is a true MINIMUM — enrollment
    # stays open (up to max_clients, None = unbounded) until the count has been quiet
    # for enrollment_grace_s, then the roster freezes and the Shamir threshold is
    # derived from who actually enrolled (> n/2; see run()).
    max_clients: int | None = None
    enrollment_grace_s: float = 1.0
    # Straggler eviction (sync, non-secure rounds): a client that has been seen
    # before but misses this many CONSECUTIVE rounds is evicted from the
    # expected population, and the round barrier degrades gracefully —
    # ``required`` is recomputed as ceil((min_clients - evicted) *
    # min_completion_rate) — so one dead client stops costing every later
    # round a full timeout.  0 disables (the pre-PR-6 behavior).  Evicted
    # clients' submits are still ACCEPTED if they return (eviction shrinks the
    # barrier, it is not a ban); a returning client rejoins the expected set.
    straggler_evict_after: int = 0
    # Asynchronous buffered aggregation (FedBuff, Nguyen et al. 2022): aggregate as
    # soon as async_buffer_k updates are buffered instead of waiting for a
    # synchronized cohort; updates based on any of the last staleness_window
    # published versions are accepted, discounted by (1 + staleness)^-alpha.
    # num_rounds then counts AGGREGATIONS (model versions), not cohort rounds.
    async_buffer_k: int | None = None
    staleness_window: int = 4
    staleness_exponent: float = 0.5
    async_server_lr: float = 1.0

    def __post_init__(self) -> None:
        if self.async_buffer_k is not None:
            if self.async_buffer_k < 1:
                raise ValueError("async_buffer_k must be >= 1")
            if self.staleness_window < 1:
                raise ValueError("async mode needs staleness_window >= 1")
            if self.staleness_exponent < 0:
                raise ValueError("staleness_exponent must be >= 0")
            if self.async_server_lr <= 0:
                raise ValueError("async_server_lr must be > 0")


def _metric(
    metrics: dict, key: str, default: float, *alt_keys: str, positive: bool = False
) -> float:
    """Defensive float coercion of a client-supplied metric value.

    Clients control the metrics JSON: the server validates the params payload strictly
    but metrics only as parseable JSON, so a single client sending ``"loss": "oops"``
    must not raise inside ``train_round`` and kill the round for everyone.  Non-numeric
    or non-finite values fall back to ``default``; ``positive=True`` additionally
    rejects values <= 0 (a negative ``num_samples`` could zero the cohort's weight sum
    and blow up the weighted mean).
    """
    for k in (key, *alt_keys):
        if k in metrics:
            try:
                v = float(metrics[k])
            except (TypeError, ValueError):
                continue
            if math.isfinite(v) and not (positive and v <= 0):
                return v
    return default


def stack_model_updates(updates: list[ModelUpdate]) -> ClientUpdates:
    """Stack host-path ``ModelUpdate`` records into one device batch for aggregation."""
    params = jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
                          *[u.params for u in updates])
    weights = jnp.asarray(
        [_metric(u.metrics, "num_samples", 1.0, "samples_processed", positive=True)
         for u in updates],
        jnp.float32,
    )
    metrics = ClientMetrics(
        loss=jnp.asarray([_metric(u.metrics, "loss", 0.0) for u in updates]),
        accuracy=jnp.asarray([_metric(u.metrics, "accuracy", 0.0) for u in updates]),
        samples=weights,
    )
    return ClientUpdates(params=params, weights=weights, metrics=metrics)


def fedbuff_combine(
    global_params: Params,
    updates: list[ModelUpdate],
    version_params: dict[int, Params],
    current_version: int,
    staleness_exponent: float = 0.5,
    server_lr: float = 1.0,
) -> tuple[Params, dict[str, Any]]:
    """FedBuff aggregation (Nguyen et al. 2022), pure: new params from a buffer of
    possibly-stale updates.

    Each update's DELTA is computed against the version the client actually trained
    from (``version_params[update.round_number]``), discounted by
    ``(1 + staleness)^-alpha``, and the DISCOUNTED deltas are averaged uniformly:
    ``(1/K) * sum_i s(tau_i) * delta_i`` — the paper's unnormalized form, so an
    all-stale buffer takes a genuinely SMALLER step (normalizing by the discount sum
    would cancel a homogeneous discount and let outdated bases drag the model with
    full force).  No sample-count weighting: it composes badly with staleness (a
    slow hoarding client would dominate exactly when its information is oldest).
    ``server_lr`` scales the applied step.

    Updates whose base version has left ``version_params`` are skipped (reported in
    the stats) — their delta is uncomputable.  Raises if nothing is aggregatable.
    """
    deltas, discounts, staleness_list, skipped = [], [], [], 0
    for u in updates:
        base = version_params.get(u.round_number)
        if base is None:
            skipped += 1
            continue
        s = current_version - u.round_number
        deltas.append(jax.tree.map(
            lambda p, g: np.asarray(p, np.float32) - np.asarray(g, np.float32),
            u.params, base,
        ))
        discounts.append((1.0 + s) ** (-staleness_exponent))
        staleness_list.append(s)
    if not deltas:
        raise ValueError(
            f"no aggregatable updates: all {skipped} buffered bases have left the "
            "version window"
        )
    k = len(deltas)
    agg = None
    for d, w in zip(deltas, discounts):
        contrib = jax.tree.map(lambda x, w=w: (w / k) * x, d)
        agg = contrib if agg is None else jax.tree.map(np.add, agg, contrib)
    new_params = jax.tree.map(
        lambda g, a: (np.asarray(g, np.float32) + server_lr * a).astype(
            np.asarray(g).dtype
        ),
        global_params, agg,
    )
    stats = {
        "num_aggregated": len(deltas),
        "num_skipped_out_of_window": skipped,
        "staleness": staleness_list,
        "mean_staleness": float(np.mean(staleness_list)),
        "discounts": [round(float(d), 4) for d in discounts],
    }
    return new_params, stats


class NetworkCoordinator:
    """Drives federated rounds over an ``HTTPServer``.

    ``validation`` enables the host-path update checks on every drained update —
    shape, finiteness/norm range, and cohort z-score anomaly detection (parity:
    ``nanofed/server/validation.py:53-135``, which the reference implements but never
    calls from its round loop).  Invalid clients are dropped from the round with a
    logged reason; a NaN or oversized networked update cannot reach the aggregate.

    ``secure`` switches the round to honest Bonawitz secure aggregation: clients
    enroll (X25519 keys + sample counts) via ``/secagg/register``, pre-scale their
    update by the server-published normalized weight, mask with pairwise PRG streams,
    and the coordinator modular-sums + dequantizes — it only ever observes uniformly
    masked vectors and the cohort's weighted mean.  By default this is the
    single-round no-dropout SecAgg variant: every enrolled client must report or the
    round FAILS (a missing client's pairwise masks would not cancel).  With
    ``secure.dropout_tolerant=True`` the double-masking variant runs instead
    (Bonawitz §4): clients Shamir-share fresh per-round secrets and an unmask
    round reconstructs orphaned masks, so a round with dropouts completes as the
    weighted FedAvg of the survivors (see ``_tolerant_secure_round``).  Per-update
    validation is impossible by construction in either mode — masked vectors are
    indistinguishable from noise; range enforcement must come from quantization
    bounds and DP clipping client-side.
    """

    def __init__(
        self,
        server: HTTPServer,
        params: Params,
        config: NetworkRoundConfig,
        validation: ValidationConfig | None = None,
        secure: SecureAggregationConfig | None = None,
        robust: RobustAggregationConfig | None = None,
        telemetry_dir: str | Path | None = None,
        registry: MetricsRegistry | None = None,
        state_store: "FileStateStore | None" = None,
        chaos: Any | None = None,
        clock: Clock | None = None,
        device_gate: Any | None = None,
    ):
        """``robust`` (a ``RobustAggregationConfig``) swaps the weighted FedAvg of
        drained updates for the coordinate-wise trimmed mean — the network path is
        where actual Byzantine clients live (the simulator's clients are our own
        code).  Incompatible with ``secure``: masked vectors are uniformly random,
        so per-coordinate order statistics are meaningless until after unmasking,
        and the server never sees unmasked individuals by design.

        ``telemetry_dir`` enables the per-run telemetry artifact: every round's
        phase spans and outcome stream into ``<telemetry_dir>/telemetry.jsonl``
        (plus a final registry snapshot on ``run()`` exit).  Round metrics and span
        durations always flow into ``registry`` (default: the server's, so one
        ``GET /metrics`` scrape covers the wire counters AND the round engine).

        ``state_store`` (a ``persistence.FileStateStore``) makes the engine
        crash-recoverable: every COMPLETED round/aggregation checkpoints the
        global params + engine state (off the event loop, via
        ``asyncio.to_thread``), and a coordinator CONSTRUCTED over a non-empty
        store resumes from the latest checkpoint — params, round number, and
        the straggler-eviction set all restore, so a server kill-restart
        re-publishes the last completed round's model and continues.  Clients
        re-sync through their normal loop: fetches retry until the new server
        answers, and in-flight submits for torn rounds land on the stale-round
        400 path (or dedupe, for retries of already-accepted submits).

        ``chaos`` (a ``nanofed_tpu.faults.ChaosSchedule``) injects round-loop
        faults: a planned ``server_kill`` raises ``InjectedServerCrash``
        mid-round (after publish, before aggregation), which
        ``persistence.is_recoverable`` classifies as recoverable — the chaos
        harness rebuilds server + coordinator from ``state_store`` exactly as
        an operator's process supervisor would.  ``clock`` injects the time
        source for every deadline and poll sleep (tests pass a
        ``VirtualClock`` so timeout behavior is load-independent).

        ``device_gate`` (a zero-arg factory returning an async context
        manager) brackets every DEVICE-dispatching aggregation section.  The
        multi-tenant federation service passes its
        :class:`~nanofed_tpu.service.RoundScheduler`'s lease here, so N
        tenants sharing one device pool serialize their device steps in
        weighted-fair order while each tenant's host-side waiting, decode and
        publish overlap the others' device time.  None (the default) is the
        single-tenant behavior: no gate, no overhead."""
        if robust is not None and secure is not None:
            raise ValueError(
                "robust= cannot be combined with secure=: the server only ever "
                "sees masked (uniformly random) vectors, so it cannot compute "
                "order statistics over individual updates — that blindness is the "
                "point of secure aggregation"
            )
        if getattr(server, "ingest", None) is not None:
            # Batched ingest folds every delta into a device buffer at submit
            # time; individual update trees never exist server-side, so the
            # per-update mechanisms cannot run.  (secure= composes fine: the
            # masked path keeps its own buffer and only borrows the ingest
            # pipeline's bounded decode pool.)
            bad = [name for name, v in (("validation", validation),
                                        ("robust", robust)) if v is not None]
            if bad:
                raise ValueError(
                    f"batched ingest (server ingest=) cannot be combined with "
                    f"{', '.join(bad)} — these inspect INDIVIDUAL updates, "
                    "which the device-resident buffer folds away at submit "
                    "time; disable ingest or drop the per-update mechanism"
                )
        if config.async_buffer_k is not None:
            # Async federation composes with neither round-locked protocol:
            # SecAgg masks are bound to ONE round's cohort (a stale masked vector
            # cannot unmask against a moved-on roster), and the robust order
            # statistics assume one cohort's comparable deltas — mixing staleness
            # levels would let an attacker hide behind legitimately-stale honest
            # updates.  Validation (per-update, stateless) would be fine but is
            # deferred until someone needs it; refuse loudly rather than half-run.
            bad = [name for name, v in (("secure", secure), ("robust", robust),
                                        ("validation", validation)) if v is not None]
            if bad:
                raise ValueError(
                    f"async_buffer_k cannot be combined with {', '.join(bad)} — "
                    "asynchronous aggregation mixes staleness levels that these "
                    "round-locked mechanisms assume away"
                )
            # The server enforces the window; wire it so users configure ONE place.
            server.staleness_window = config.staleness_window
        elif server.staleness_window > 0:
            # A windowed server under the SYNC protocol would re-admit the exact
            # cross-round contamination the sync buffer clear exists to prevent
            # (a just-drained round's straggler counting toward the next round's
            # barrier at full, undiscounted weight).
            raise ValueError(
                "server was built with staleness_window > 0 but the coordinator "
                "is synchronous — set NetworkRoundConfig(async_buffer_k=...) or "
                "use a sync server (staleness_window=0)"
            )
        self.server = server
        self.params = params
        self.config = config
        self._ingest_mode = getattr(server, "ingest", None) is not None
        if self._ingest_mode:
            # The buffer's drains return FLAT [P] params; the unravel (built
            # once — the tree structure never changes across rounds) restores
            # the pytree.  Same tree_ravel layout the pipeline flattens with.
            from nanofed_tpu.utils.trees import tree_ravel

            _, self._flat_unravel = tree_ravel(params)
        self.validation = validation
        self.secure = secure
        self.robust = robust
        self.state_store = state_store
        self.chaos = chaos
        self._device_gate = device_gate
        self.history: list[dict[str, Any]] = []
        self._clock = clock or SYSTEM_CLOCK
        self._log = Logger()
        # Straggler accounting (sync rounds): consecutive missed rounds per
        # ever-seen client, and the evicted set the round barrier excludes.
        self._known_clients: set[str] = set()
        self._absence: dict[str, int] = {}
        self._evicted_stragglers: set[str] = set()
        # Crash recovery: resume from the latest COMPLETED checkpoint.  The
        # restored round number is where the CRASHED run got to; this engine
        # starts at the round after it, publishing the restored params.
        self.start_round = 0
        if state_store is not None:
            restored = state_store.restore_latest()
            if restored is not None:
                self.params = restored.params
                self.start_round = restored.round_number + 1
                engine_state = restored.server_state or {}
                if isinstance(engine_state, dict):
                    self._evicted_stragglers = set(
                        engine_state.get("evicted_stragglers", ())
                    )
                    self._known_clients = set(self._evicted_stragglers)
                self._log.info(
                    "resumed from checkpoint: round %d (restarting at %d, "
                    "%d evicted stragglers restored)",
                    restored.round_number, self.start_round,
                    len(self._evicted_stragglers),
                )
        self.metrics_registry = registry or server.metrics_registry
        self.telemetry = (
            RunTelemetry(telemetry_dir, registry=self.metrics_registry)
            if telemetry_dir is not None
            else None
        )
        self._tracer = (
            self.telemetry.tracer
            if self.telemetry is not None
            # keep_records=False: only the histogram consumes these spans — a
            # long-lived engine must not accumulate every round's records.
            else SpanTracer(registry=self.metrics_registry, keep_records=False)
        )
        # Round-outcome accounting delegates to the shared engine: this wire
        # front, the SPMD coordinator, and the federate mesh workers all
        # charge the same ledger (same instruments, same `round` record).
        self._ledger = RoundLedger(self.metrics_registry, telemetry=self.telemetry)
        self._m_validation_rejects = self.metrics_registry.counter(
            "nanofed_validation_rejections_total",
            "Drained updates rejected by host-path validation",
        )
        self._m_straggler_evictions = self.metrics_registry.counter(
            "nanofed_straggler_evictions_total",
            "Clients evicted from the sync round barrier after consecutive misses",
        )

    @asynccontextmanager
    async def _device_section(self):
        """The device-step critical section: a no-op without a gate; under the
        service scheduler, waits for the weighted-fair device lease."""
        if self._device_gate is None:
            yield
            return
        async with self._device_gate():
            yield

    async def _wait_for_clients(self, required: int) -> bool:
        """Poll the update buffer until ``required`` updates arrive or timeout
        (parity: ``coordinator.py:205-245``)."""
        deadline = self._clock.time() + self.config.round_timeout_s
        while self._clock.time() < deadline:
            if self.server.num_updates() >= required:
                return True
            await self._clock.sleep(self.config.poll_interval_s)
        return self.server.num_updates() >= required

    def _required_clients(self) -> int:
        """This round's barrier: completion-rate over the LIVE expected
        population (min_clients minus evicted stragglers) — graceful
        degradation, so a permanently-dead client costs ``straggler_evict_after``
        timed-out rounds and then stops failing the federation."""
        return completion_required(
            self.config.min_clients - len(self._evicted_stragglers),
            self.config.min_completion_rate,
        )

    def _note_participation(self, reported: set[str]) -> list[str]:
        """Track per-client absences after a sync round's drain; returns the
        clients newly evicted this round.  Only ever-seen clients accrue
        absence (an expected-but-never-connected population is a configuration
        problem the timeout already surfaces), and a returning evictee rejoins
        the expected set — eviction shrinks the barrier, it is not a ban."""
        if self.config.straggler_evict_after <= 0:
            return []
        returned = reported & self._evicted_stragglers
        if returned:
            self._log.info("stragglers returned, rejoining the barrier: %s",
                           sorted(returned))
            self._evicted_stragglers -= returned
        self._known_clients |= reported
        newly_evicted: list[str] = []
        for cid in reported:
            self._absence[cid] = 0
        for cid in sorted(self._known_clients - reported - self._evicted_stragglers):
            self._absence[cid] = self._absence.get(cid, 0) + 1
            if self._absence[cid] >= self.config.straggler_evict_after:
                self._evicted_stragglers.add(cid)
                newly_evicted.append(cid)
        if newly_evicted:
            self._m_straggler_evictions.inc(len(newly_evicted))
            self._log.warning(
                "evicting stragglers after %d consecutive missed rounds: %s "
                "(barrier degrades to %d required)",
                self.config.straggler_evict_after, newly_evicted,
                self._required_clients(),
            )
        return newly_evicted

    async def _checkpoint_round(
        self, round_number: int, record: dict[str, Any]
    ) -> None:
        """Persist a COMPLETED round's state (params + engine state) off the
        event loop.  This is the recovery point a restarted coordinator
        resumes from; FAILED rounds are not checkpointed (the params did not
        change, and restore_latest skips non-COMPLETED checkpoints anyway)."""
        if self.state_store is None or record.get("status") != "COMPLETED":
            return
        await asyncio.to_thread(
            self.state_store.checkpoint,
            round_number,
            self.params,
            {"evicted_stragglers": sorted(self._evicted_stragglers)},
            {k: v for k, v in (record.get("metrics") or {}).items()},
        )

    def _validate_updates(self, updates: list[ModelUpdate]) -> list[ModelUpdate]:
        """Drop invalid updates (wrong shape / non-finite / norm cap / cohort anomaly)
        before they can touch the aggregate; each rejection is logged with its reason."""
        shapes = reference_shapes(self.params)
        survivors = []
        for u in updates:
            verdict = validate_shape(u, shapes)
            if verdict is ValidationResult.VALID:
                verdict = validate_range(u, self.validation)
            if verdict is not ValidationResult.VALID:
                self._log.warning("rejecting update from %s: %s", u.client_id, verdict.name)
                continue
            survivors.append(u)
        # Cohort anomaly detection over the range-valid survivors only (a NaN norm
        # would poison the z-scores).  Same leave-one-out math as the in-mesh path
        # (each norm computed ONCE — not the O(n^2) pairwise re-derivation the enum
        # API would imply); loo_zscore itself gates on min_clients_for_stats.
        if len(survivors) > 1:
            norms = jnp.asarray([update_flat_norm(u) for u in survivors])
            _, anomalous = loo_zscore(
                norms,
                jnp.ones_like(norms),
                self.validation.z_score_threshold,
                float(self.validation.min_clients_for_stats),
            )
            kept = []
            for u, bad in zip(survivors, np.asarray(anomalous)):
                if bad:
                    self._log.warning("rejecting update from %s: ANOMALOUS", u.client_id)
                else:
                    kept.append(u)
            survivors = kept
        return survivors

    async def _tolerant_secure_round(
        self, round_number: int, required: int
    ) -> dict[str, Any]:
        """One dropout-tolerant masked round (Bonawitz §4 double masking): wait for the
        cohort until the timeout, then run the UNMASK round — survivors reveal Shamir
        shares of dropped clients' pair keys and survivors' self-mask seeds, the
        coordinator reconstructs and removes the orphaned masks, and the round
        completes as the weighted FedAvg of the survivors."""
        from nanofed_tpu.security.secure_agg import recover_unmasked_sum
        from nanofed_tpu.utils.trees import tree_ravel

        cohort = self.server.secagg_active_order()
        expected = len(cohort)
        # The effective threshold is the server's per-round derivation over the
        # ACTIVE cohort (window enrollment — the same value clients read alongside
        # the participants list and share at); library users driving the server
        # directly without a window fall back to the static config value.
        threshold = self.server.secagg_threshold() or self.secure.threshold
        if threshold > expected:
            # No m-client cohort can deposit >= t > m shares: every client's
            # make_dropout_shares refuses, so waiting out the round timeout for
            # masked updates that can never come would only hide the real cause.
            self._log.warning(
                "secure round %d FAILED: threshold %d exceeds active cohort %d",
                round_number, threshold, expected,
            )
            record = {"round": round_number, "status": "FAILED",
                      "num_clients": 0, "num_dropped": 0, "secure": True,
                      "reason": (f"threshold {threshold} exceeds the {expected}-"
                                 "client active cohort (unsatisfiable)")}
            self.history.append(record)
            return record
        deadline = self._clock.time() + self.config.round_timeout_s
        while (
            self.server.num_masked_updates() < expected
            and self._clock.time() < deadline
        ):
            await self._clock.sleep(self.config.poll_interval_s)
        masked = await self.server.drain_masked_updates()
        survivors = [c for c in cohort if c in masked]
        dropped = [c for c in cohort if c not in masked]

        def fail(reason: str) -> dict[str, Any]:
            self._log.warning("secure round %d FAILED: %s", round_number, reason)
            record = {"round": round_number, "status": "FAILED",
                      "num_clients": len(survivors), "num_dropped": len(dropped),
                      "secure": True, "reason": reason}
            self.history.append(record)
            return record

        # Gate BEFORE the unmask phase: min_clients is the privacy floor (a smaller
        # revealed sum would expose updates below the crowd size clients consented
        # to), and reveals must not be solicited for a round that cannot complete.
        floor = self.secure.min_clients
        if len(survivors) < max(required, threshold, floor, 1):
            reason = (
                f"{len(survivors)}/{expected} masked updates (need "
                f"max(required={required}, threshold={threshold}, "
                f"min_clients={floor}))"
            )
            # Evict clients known dead — FAILED rounds must shed them too, or every
            # subsequent round stalls a full timeout waiting for a corpse:
            # * shares incomplete: the non-depositors stalled the share barrier
            #   (nobody could mask; the depositors are alive and blameless);
            # * shares complete: the non-submitters went silent after depositing.
            # Never evict everyone — a total stall is systemic (e.g. clients cannot
            # reach us), and emptying the cohort would end recovery for good.
            if not self.server.secagg_shares_complete():
                alive = set(self.server.secagg_round_epks())
                gone = [c for c in cohort if c not in alive]
            else:
                gone = dropped
            if gone and len(gone) < len(cohort):
                await self.server.evict_secagg_clients(gone)
                reason += f"; evicted unresponsive clients {gone}"
            return fail(reason)
        # This round's ephemeral mask keys (pairwise seeds derive from these; a
        # survivor could only have masked after the share barrier, so the epk map
        # covers everyone who matters).
        epks = self.server.secagg_round_epks()
        missing_epks = [c for c in cohort if c not in epks]
        if any(c in survivors for c in missing_epks):
            return fail(f"survivors without ephemeral keys: {missing_epks}")
        # A client that dropped BEFORE depositing its round shares left nothing to
        # recover — but also added no masks anywhere (nobody could mask before the
        # share barrier), so it is simply excluded.
        dropped_after_shares = [c for c in dropped if c in epks]
        # Unmask round: even with zero dropouts the survivors' SELF masks must be
        # removed, so this phase always runs in tolerant mode.
        await self.server.open_unmask(round_number, dropped_after_shares, survivors)
        deadline = self._clock.time() + self.config.round_timeout_s
        while (
            self.server.num_unmask_reveals() < len(survivors)
            and self._clock.time() < deadline
        ):
            await self._clock.sleep(self.config.poll_interval_s)
        reveals = await self.server.drain_unmask_reveals()
        if len(reveals) < threshold:
            # The non-submitters are known dead either way; shed them so the next
            # round's barrier stops waiting (non-REVEALING survivors stay — they are
            # provably alive, their reveal may just be late).
            if dropped and len(dropped) < len(cohort):
                await self.server.evict_secagg_clients(dropped)
            return fail(
                f"only {len(reveals)}/{len(survivors)} unmask reveals "
                f"(threshold {threshold})"
            )
        try:
            total = recover_unmasked_sum(
                masked,
                [c for c in cohort if c in epks],
                epks,
                round_number,
                reveals,
                replace(self.secure, threshold=threshold),
                backend=self.server.secagg_backend(),
                self_seed_commitments=self.server.secagg_round_commitments(),
            )
        except Exception as e:
            return fail(f"mask recovery failed: {e}")
        # Clients pre-scaled by full-cohort enrollment weights; renormalize to the
        # survivors' weight mass so the result is the weighted mean of who reported.
        from nanofed_tpu.security.secure_agg import dequantize

        weights = self.server.secagg_weights()
        survivor_mass = sum(weights[s] for s in survivors)
        flat = dequantize(total, self.secure.frac_bits) / survivor_mass
        _, unravel = tree_ravel(self.params)
        self.params = unravel(jnp.asarray(flat, jnp.float32))
        if dropped:
            # Their round secrets were revealed; evict so later rounds neither wait
            # for them nor accept a compromised-mask submission.  Rejoining requires
            # a fresh cohort.
            await self.server.evict_secagg_clients(dropped)
        record = {
            "round": round_number,
            "status": "COMPLETED",
            "num_clients": len(survivors),
            "num_dropped": len(dropped),
            "secure": True,
        }
        self.history.append(record)
        self._log.info(
            "secure round %d: recovered aggregate from %d survivors (%d dropped)",
            round_number, len(survivors), len(dropped),
        )
        return record

    async def _secure_round(self, round_number: int, required: int) -> dict[str, Any]:
        """One masked round: wait for the FULL cohort, modular-sum, unmask."""
        if self.secure.dropout_tolerant:
            return await self._tolerant_secure_round(round_number, required)
        cohort = self.server.secagg_client_order()
        expected = len(cohort)
        deadline = self._clock.time() + self.config.round_timeout_s
        while (
            self.server.num_masked_updates() < expected
            and self._clock.time() < deadline
        ):
            await self._clock.sleep(self.config.poll_interval_s)
        masked = await self.server.drain_masked_updates()
        if len(masked) < expected or expected < required:
            # Any missing cohort member leaves uncancelled pairwise masks in the sum.
            self._log.warning(
                "secure round %d FAILED: %d/%d masked updates",
                round_number, len(masked), expected,
            )
            record = {"round": round_number, "status": "FAILED",
                      "num_clients": len(masked), "secure": True}
            self.history.append(record)
            return record
        # Clients pre-scaled by their published normalized weight, so the masked
        # modular sum IS the weighted mean once the pairwise masks cancel.
        from nanofed_tpu.security.secure_agg import unmask_sum

        self.params = unmask_sum(
            [masked[c] for c in cohort], self.params, self.secure
        )
        record = {
            "round": round_number,
            "status": "COMPLETED",
            "num_clients": len(masked),
            "secure": True,
        }
        self.history.append(record)
        self._log.info("secure round %d: aggregated %d masked updates",
                       round_number, len(masked))
        return record

    async def train_round(self, round_number: int) -> dict[str, Any]:
        """One federation round, instrumented: the round and its phases (publish →
        cohort-sample → aggregate) are recorded as spans, the outcome lands in
        ``nanofed_rounds_total`` / ``nanofed_round_duration_seconds``, and — with a
        ``telemetry_dir`` — the round record is appended to ``telemetry.jsonl``."""
        t0 = time.perf_counter()
        with self._tracer.span("round", round=round_number):
            record = await self._train_round_inner(round_number)
        duration = time.perf_counter() - t0
        self._ledger.charge(
            status=str(record.get("status", "?")),
            num_clients=record.get("num_clients", 0), duration_s=duration,
            telemetry_fields={"duration_s": round(duration, 6), **record},
        )
        await self._checkpoint_round(round_number, record)
        return record

    async def _train_round_inner(self, round_number: int) -> dict[str, Any]:
        with self._tracer.span("publish", round=round_number):
            await self.server.publish_model(self.params, round_number)
        if self.chaos is not None and self.chaos.take_server_kill(round_number):
            # Mid-round crash: the model for this round IS published (clients
            # may have fetched, trained, submitted) but aggregation never
            # happens.  Recovery: rebuild from the state store; this round
            # re-runs from scratch on the restored params.
            raise InjectedServerCrash(
                f"chaos plan (seed {getattr(self.chaos.plan, 'seed', '?')}): "
                f"server killed mid-round {round_number}"
            )
        required = self._required_clients()
        if self.secure is not None:
            with self._tracer.span("secure-aggregate", round=round_number):
                return await self._secure_round(round_number, required)
        with self._tracer.span("cohort-sample", round=round_number):
            ok = await self._wait_for_clients(required)
            if self._ingest_mode:
                updates = []
            else:
                updates = await self.server.drain_updates()
        if self._ingest_mode:
            return await self._ingest_round_tail(round_number, required, ok)
        num_received = len(updates)
        num_rejected = 0
        if self.validation is not None and updates:
            updates = self._validate_updates(updates)
            num_rejected = num_received - len(updates)
            if num_rejected:
                self._m_validation_rejects.inc(num_rejected)
        newly_evicted = self._note_participation({u.client_id for u in updates})
        if not ok or len(updates) < required:
            self._log.warning(
                "round %d FAILED: %d/%d updates (%d rejected)",
                round_number, len(updates), required, num_rejected,
            )
            record = {"round": round_number, "status": "FAILED",
                      "num_clients": len(updates), "num_rejected": num_rejected,
                      "required": required}
            if newly_evicted:
                record["evicted_stragglers"] = newly_evicted
            self.history.append(record)
            return record
        async with self._device_section():
            with self._tracer.span("aggregate", round=round_number,
                                   num_clients=len(updates)):
                record = self._aggregate_round(round_number, updates, num_rejected)
        record["required"] = required
        if newly_evicted:
            record["evicted_stragglers"] = newly_evicted
        if record["status"] == "COMPLETED":
            self._log.info("round %d: %s", round_number, record["metrics"])
        self.history.append(record)
        return record

    async def _ingest_round_tail(
        self, round_number: int, required: int, ok: bool
    ) -> dict[str, Any]:
        """Sync-round completion on the batched-ingest path: ONE jitted reduce
        over the device buffer replaces drain + host stack + per-leaf mean.
        Weighted FedAvg semantics are identical (the weighted mean of deltas
        against the round's shared base IS the weighted mean of params); the
        round record keeps the per-submit shape so telemetry consumers and the
        straggler-eviction accounting see no difference."""
        async with self._device_section():
            with self._tracer.span("aggregate", round=round_number, ingest=True):
                new_flat, metas = await self.server.drain_ingest_fedavg()
        newly_evicted = self._note_participation({m.client_id for m in metas})
        if not ok or len(metas) < required:
            self._log.warning(
                "round %d FAILED: %d/%d batched updates",
                round_number, len(metas), required,
            )
            record: dict[str, Any] = {
                "round": round_number, "status": "FAILED",
                "num_clients": len(metas), "num_rejected": 0,
                "required": required, "ingest": True,
            }
            if newly_evicted:
                record["evicted_stragglers"] = newly_evicted
            self.history.append(record)
            return record
        self.params = self._flat_unravel(new_flat)
        wsum = sum(m.weight for m in metas)
        round_metrics = {
            "loss": sum(_metric(m.metrics, "loss", 0.0) * m.weight
                        for m in metas) / wsum,
            "accuracy": sum(_metric(m.metrics, "accuracy", 0.0) * m.weight
                            for m in metas) / wsum,
        }
        record = {
            "round": round_number, "status": "COMPLETED",
            "num_clients": len(metas), "num_rejected": 0,
            "metrics": round_metrics, "required": required, "ingest": True,
        }
        if newly_evicted:
            record["evicted_stragglers"] = newly_evicted
        self._log.info("round %d (batched ingest): %s", round_number, round_metrics)
        self.history.append(record)
        return record

    def _aggregate_round(
        self, round_number: int, updates: list[ModelUpdate], num_rejected: int
    ) -> dict[str, Any]:
        """Stack the drained updates and fold them into the global params (plain
        weighted FedAvg, or the robust estimator when configured); pure aggregation,
        split out so the ``aggregate`` span covers exactly the on-device reduce."""
        stacked = stack_model_updates(updates)
        if self.robust is not None:
            # FedAvg over params IS a mean of client params, so the trimmed mean
            # drops straight in: coordinate-wise, unweighted over kept ranks (a
            # Byzantine client claiming a huge num_samples must not amplify
            # itself), every drained update participating.  The round's reported
            # loss/accuracy ride the SAME estimator in the same call — a
            # huge-but-finite claimed loss (the host _metric coercion only catches
            # non-finite values) must not corrupt the round record either.
            out, trim_ok, _ = robust_aggregate(
                self.robust,
                {"params": stacked.params,
                 "loss": stacked.metrics.loss,
                 "accuracy": stacked.metrics.accuracy},
                jnp.ones(len(updates), jnp.float32),
            )
            if not bool(trim_ok):
                self._log.warning(
                    "round %d FAILED: %d updates < robust floor %d",
                    round_number, len(updates), robust_floor(self.robust),
                )
                return {"round": round_number, "status": "FAILED",
                        "num_clients": len(updates),
                        "num_rejected": num_rejected,
                        "reason": (f"{len(updates)} updates below the robust "
                                   f"floor {robust_floor(self.robust)}")}
            self.params = out["params"]
            round_metrics = {"loss": float(out["loss"]),
                             "accuracy": float(out["accuracy"])}
        else:
            self.params = fedavg_combine(stacked)
            round_metrics = {
                "loss": float((stacked.metrics.loss * stacked.weights).sum()
                              / stacked.weights.sum()),
                "accuracy": float((stacked.metrics.accuracy * stacked.weights).sum()
                                  / stacked.weights.sum()),
            }
        return {
            "round": round_number,
            "status": "COMPLETED",
            "num_clients": len(updates),
            "num_rejected": num_rejected,
            "metrics": round_metrics,
        }

    async def _wait_for_buffer(self, k: int) -> int:
        """Async mode: poll until >= k updates are buffered or the timeout expires;
        returns the buffered count at exit."""
        deadline = self._clock.time() + self.config.round_timeout_s
        while self._clock.time() < deadline:
            n = self.server.num_updates()
            if n >= k:
                return n
            await self._clock.sleep(self.config.poll_interval_s)
        return self.server.num_updates()

    async def _run_async(self) -> list[dict[str, Any]]:
        """FedBuff loop: each iteration publishes the current version, waits for
        ``async_buffer_k`` buffered updates (of ANY in-window staleness — no cohort
        barrier), and applies the staleness-discounted buffer aggregate.

        ``num_rounds`` counts aggregations.  A timeout with a non-empty buffer
        aggregates what arrived (a slow federation still makes progress); a timeout
        with an empty buffer records a FAILED aggregation and re-publishes the same
        version.  Deltas are computed against the server's published-version window
        (``server.published_versions``) — the same map the wire acceptance and
        compressed-delta reconstruction use, so the three can never disagree.
        """
        k = self.config.async_buffer_k
        # Crash recovery: resume at the checkpointed VERSION (checkpoints are
        # written per completed aggregation, keyed by the version they
        # produced); already-spent aggregations stay spent.
        version = self.start_round
        for agg_i in range(self.start_round, self.config.num_rounds):
            t0 = time.perf_counter()
            with self._tracer.span("round", aggregation=agg_i, version=version):
                with self._tracer.span("publish", aggregation=agg_i):
                    await self.server.publish_model(self.params, version)
                with self._tracer.span("cohort-sample", aggregation=agg_i):
                    got = await self._wait_for_buffer(k)
                    # Exactly K per aggregation (surplus stays buffered for the next
                    # one) — "buffer of K" means K, or the update-budget accounting
                    # lies.  The batched-ingest drain enforces the same K below.
                    updates = (
                        [] if self._ingest_mode
                        else await self.server.take_updates(k)
                    )
                if not updates and not (self._ingest_mode and got):
                    record = {"aggregation": agg_i, "version": version,
                              "status": "FAILED", "num_clients": 0,
                              "reason": f"timeout with an empty buffer (wanted {k})"}
                    self._log.warning("aggregation %d FAILED: empty buffer", agg_i)
                elif self._ingest_mode:
                    # Batched path: ONE jitted reduce of the K oldest buffered
                    # deltas, staleness-discounted — numerically
                    # fedbuff_combine to float tolerance, without K host-side
                    # tree traversals per aggregation.
                    try:
                        async with self._device_section():
                            with self._tracer.span("aggregate", aggregation=agg_i,
                                                   num_clients=got, ingest=True):
                                new_flat, live, stats = (
                                    await self.server.drain_ingest_fedbuff(
                                        k, version,
                                        staleness_exponent=self.config.staleness_exponent,
                                        server_lr=self.config.async_server_lr,
                                    )
                                )
                    except ValueError as e:
                        record = self._async_stale_drain_record(agg_i, version, e)
                    else:
                        self.params = self._flat_unravel(new_flat)
                        version += 1
                        losses = [_metric(m.metrics, "loss", float("nan"))
                                  for m in live]
                        finite = [v for v in losses if math.isfinite(v)]
                        record = {
                            "aggregation": agg_i, "version": version,
                            "status": "COMPLETED",
                            "num_clients": stats["num_aggregated"],
                            "buffered_at_drain": got, "ingest": True,
                            "metrics": {"loss": float(np.mean(finite)) if finite
                                        else None},
                            **stats,
                        }
                        self._log.info(
                            "aggregation %d -> version %d (batched ingest): %d "
                            "updates, staleness %s",
                            agg_i, version, stats["num_aggregated"],
                            stats["staleness"],
                        )
                else:
                    # The server's published-version window is the single source of
                    # truth for which bases are still reconstructable — no
                    # coordinator-side copy whose pruning could silently diverge.
                    try:
                        async with self._device_section():
                            with self._tracer.span("aggregate", aggregation=agg_i,
                                                   num_clients=len(updates)):
                                new_params, stats = fedbuff_combine(
                                    self.params, updates,
                                    self.server.published_versions, version,
                                    staleness_exponent=self.config.staleness_exponent,
                                    server_lr=self.config.async_server_lr,
                                )
                    except ValueError as e:
                        record = self._async_stale_drain_record(agg_i, version, e)
                    else:
                        self.params = new_params
                        version += 1
                        losses = [_metric(u.metrics, "loss", float("nan")) for u in updates]
                        finite = [v for v in losses if math.isfinite(v)]
                        record = {
                            "aggregation": agg_i, "version": version,
                            "status": "COMPLETED",
                            "num_clients": stats["num_aggregated"],
                            "buffered_at_drain": got,
                            "metrics": {"loss": float(np.mean(finite)) if finite else None},
                            **stats,
                        }
                        self._log.info(
                            "aggregation %d -> version %d: %d updates, staleness %s",
                            agg_i, version, stats["num_aggregated"], stats["staleness"],
                        )
            self.history.append(record)
            duration = time.perf_counter() - t0
            self._ledger.charge(
                status=record["status"], num_clients=record["num_clients"],
                duration_s=duration,
                telemetry_fields={
                    "duration_s": round(duration, 6),
                    **{key: v for key, v in record.items() if key != "discounts"},
                },
            )
            if record["status"] == "COMPLETED":
                # Keyed by the PRODUCED version: a resumed engine starts its
                # next aggregation from exactly this model.
                await self._checkpoint_round(version - 1, record)
        await self.server.publish_model(self.params, version)
        self.server.stop_training()
        return self.history

    def _async_stale_drain_record(
        self, agg_i: int, version: int, e: ValueError
    ) -> dict[str, Any]:
        """A drain whose every update's base left the version window (the
        engine outran its clients) is a FAILED AGGREGATION, not a crashed
        federation: the drained slots were consumed, the version does not
        advance, and the next drain sees strictly newer arrivals — under
        sustained overload this degrades to dropped stale work instead of
        killing the round loop (the load harness routinely provokes it)."""
        self._log.warning("aggregation %d FAILED: %s", agg_i, e)
        return {"aggregation": agg_i, "version": version, "status": "FAILED",
                "num_clients": 0, "reason": str(e)}

    async def run(self) -> list[dict[str, Any]]:
        """All rounds, then signal termination to polling clients.

        In secure mode, opens secure-aggregation enrollment for ``min_clients`` and
        waits for the cohort to complete before round 0.

        With ``async_buffer_k`` set, runs the FedBuff loop instead (see
        ``_run_async``): no cohort barrier, aggregations fire on buffer fill.
        """
        try:
            return await self._run_all_rounds()
        finally:
            # Final metrics snapshot + handle release; a raised enrollment timeout
            # still leaves every completed round's telemetry on disk.
            if self.telemetry is not None:
                self.telemetry.close()

    async def _run_all_rounds(self) -> list[dict[str, Any]]:
        if self.config.async_buffer_k is not None:
            return await self._run_async()
        if self.secure is not None:
            tolerant = self.secure.dropout_tolerant
            if tolerant:
                # min_clients is a true MINIMUM here: the Shamir threshold must
                # exceed half the cohort that ACTUALLY enrolls (split-view defense,
                # secure_agg.make_dropout_shares), so a static threshold wired from
                # min_clients would be wrong for any larger roster.  Enrollment stays
                # open; once >= min_clients are in and the count has been quiet for
                # enrollment_grace_s (or max_clients is reached), the roster freezes
                # and the threshold is derived from its real size — never below an
                # operator-configured one.
                await self.server.open_secagg(
                    self.config.min_clients,
                    window=True,
                    max_clients=self.config.max_clients,
                    threshold_for=lambda n: max(self.secure.threshold, n // 2 + 1),
                )
            else:
                await self.server.open_secagg(self.config.min_clients)
            deadline = self._clock.time() + self.config.round_timeout_s
            while (
                self.server.secagg_enrolled() < self.config.min_clients
                and self._clock.time() < deadline
            ):
                await self._clock.sleep(self.config.poll_interval_s)
            if self.server.secagg_enrolled() < self.config.min_clients:
                self.server.stop_training()
                raise TimeoutError(
                    "secure-aggregation cohort incomplete before round 0"
                )
            if tolerant:
                if not self.server.secagg_roster_complete():
                    # Straggler window: admit whoever else shows up until the
                    # roster has been quiet for the grace period, then freeze.
                    last_n, last_t = self.server.secagg_enrolled(), self._clock.time()
                    while self._clock.time() < deadline:
                        n = self.server.secagg_enrolled()
                        if n != last_n:
                            last_n, last_t = n, self._clock.time()
                        elif self._clock.time() - last_t >= self.config.enrollment_grace_s:
                            break
                        if self.server.secagg_roster_complete():
                            break  # max_clients froze it implicitly
                        await self._clock.sleep(self.config.poll_interval_s)
                # Idempotent: a no-op when max_clients already froze the roster —
                # the validation below must run on BOTH freeze paths.
                n = await self.server.close_secagg()
                frozen_t = self.server.secagg_threshold()
                if frozen_t is not None and frozen_t > n:
                    # A configured threshold above the cohort size can never be
                    # shared or reconstructed — every client's make_dropout_shares
                    # would raise and every round would time out empty.  Surface
                    # the misconfiguration at startup instead.
                    self.server.stop_training()
                    raise ValueError(
                        f"secure-aggregation threshold {frozen_t} exceeds the "
                        f"{n}-client cohort that enrolled; lower the configured "
                        "threshold or raise min_clients"
                    )
                self._log.info(
                    "secagg cohort frozen: %d enrolled (min %d), threshold %s",
                    n, self.config.min_clients, frozen_t,
                )
            # (Dropout-tolerant share distribution is PER-ROUND — fresh ephemeral
            # secrets every round, see _tolerant_secure_round — so there is no
            # enrollment-time share barrier.)
        # start_round > 0 after a state-store resume: completed rounds are not
        # re-run, the restored params are simply re-published at the next one.
        for r in range(self.start_round, self.config.num_rounds):
            await self.train_round(r)
        self.server.stop_training()
        return self.history
