"""Client-side retry policy: exponential backoff + jitter, Retry-After, budgets.

``HTTPClient`` had zero retry policy — any transient failure (a dropped
connection, a server restart, an admission-control 429) surfaced as a failed
round for that client.  Production federations are built on flaky clients and
servers that shed load; the client's half of that contract is:

* **exponential backoff with jitter** so ten thousand rejected clients do not
  re-arrive in lockstep (the retry storm that turns one overload into many);
* **honor 429 ``Retry-After``** — the server KNOWS when capacity frees up;
  the client's own schedule is only a floor under that answer;
* **a per-call budget** so retries stop burning time the round no longer has
  (wire it to a share of the round timeout);
* **idempotent submit keys** (``HTTPClient`` attaches one per logical submit)
  so a retry after a lost ACK cannot double-count — the server folds each key
  at most once, whatever the retry policy re-sends.

The policy object is pure and seedable (chaos tests need the backoff schedule
deterministic); the retry LOOP lives in ``HTTPClient`` where the aiohttp
exception taxonomy is.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

__all__ = ["RETRYABLE_STATUSES", "RetryPolicy", "parse_retry_after"]

#: HTTP statuses a retry can fix: admission-control backpressure (429) and the
#: transient-unavailability family.  4xx protocol rejections (stale round, bad
#: payload, bad signature) are FINAL — retrying them verbatim cannot succeed,
#: and the topk8 error-feedback fold must run instead.
RETRYABLE_STATUSES = frozenset({429, 502, 503, 504})


def parse_retry_after(value: str | None) -> float | None:
    """A ``Retry-After`` header as seconds, or None when absent/unparseable.
    Only the delta-seconds form is supported (what this server emits); an
    HTTP-date here would need a wall clock, which the communication stack
    deliberately never reads."""
    if value is None:
        return None
    try:
        seconds = float(value)
    except ValueError:
        return None
    return seconds if seconds >= 0 else None


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule for retryable submit/fetch failures.

    ``max_attempts`` counts every try including the first.  The delay before
    retry ``attempt`` (1-based: after the attempt-1 failure) is::

        raw   = min(max_backoff_s, base_backoff_s * multiplier ** (attempt-1))
        delay = raw * (1 - jitter_fraction * U[0,1))     # decorrelating jitter
        delay = max(delay, retry_after)                  # the server knows best

    ``budget_s`` bounds the TOTAL time a single logical call may spend
    retrying (first attempt included) — size it to the slice of the round
    timeout this client can afford.  ``seed`` makes the jitter stream
    deterministic for chaos tests; leave None in production (each client then
    jitters independently, which is the point of jitter).
    """

    max_attempts: int = 5
    base_backoff_s: float = 0.1
    max_backoff_s: float = 5.0
    multiplier: float = 2.0
    jitter_fraction: float = 0.5
    budget_s: float | None = None
    seed: int | None = None

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.base_backoff_s < 0 or self.max_backoff_s < self.base_backoff_s:
            raise ValueError("need 0 <= base_backoff_s <= max_backoff_s")
        if self.multiplier < 1.0:
            raise ValueError("multiplier must be >= 1")
        if not 0.0 <= self.jitter_fraction <= 1.0:
            raise ValueError("jitter_fraction must be in [0, 1]")
        if self.budget_s is not None and self.budget_s <= 0:
            raise ValueError("budget_s must be > 0")

    def rng_for(self, client_id: str) -> random.Random:
        """The jitter stream for one client: seeded -> deterministic per
        (seed, client) so chaos runs replay exactly; unseeded -> OS entropy."""
        if self.seed is None:
            return random.Random()
        return random.Random(f"{self.seed}:{client_id}")

    def backoff_s(
        self,
        attempt: int,
        rng: random.Random,
        retry_after_s: float | None = None,
    ) -> float:
        """Seconds to wait before retry ``attempt`` (1-based)."""
        if attempt < 1:
            raise ValueError("attempt is 1-based")
        raw = min(
            self.max_backoff_s,
            self.base_backoff_s * self.multiplier ** (attempt - 1),
        )
        delay = raw * (1.0 - self.jitter_fraction * rng.random())
        if retry_after_s is not None:
            delay = max(delay, retry_after_s)
        return delay
