"""Named benchmark configurations (the BASELINE.json suite).

Each entry maps a benchmark the driver cares about onto ``run_experiment`` kwargs.  The
reference ships no benchmark harness at all (SURVEY.md §6); these configs are the five
workloads named in BASELINE.json:

1. ``mnist_iid``        — examples/mnist parity: 10 clients, IID, MNIST CNN, sync FedAvg.
2. ``mnist_labelskew``  — 100 clients, non-IID label-skew, partial participation C=0.1.
3. ``fedprox_cifar10``  — FedProx (proximal local loss) on CIFAR-10 ResNet-8, 100 clients.
4. ``dp_fedavg_mnist``  — DP-FedAvg: per-client update clipping + Gaussian noise.
5. ``cross_silo``       — 8 clients, ResNet-18 on CIFAR-100, full participation.
6. ``mnist_1000``       — the north-star flagship: 1000 clients >> chips, MNIST CNN,
   sequential ``client_chunk`` training per device, bf16 compute on the MXU.

``run_benchmark`` returns the experiment summary augmented with rounds/sec — the
north-star metric (1000-client MNIST round < 1 s on v5e-8).
"""

from __future__ import annotations

from typing import Any

import numpy as np

BENCHMARKS: dict[str, dict[str, Any]] = {
    "mnist_iid": dict(
        model="mnist_cnn", num_clients=10, num_rounds=5, local_epochs=2,
        batch_size=64, learning_rate=0.1, scheme="iid", participation=1.0,
    ),
    "mnist_labelskew": dict(
        model="mnist_cnn", num_clients=100, num_rounds=5, local_epochs=1,
        batch_size=32, learning_rate=0.1, scheme="label_skew", participation=0.1,
        shards_per_client=2,
    ),
    "fedprox_cifar10": dict(
        model="resnet8", num_clients=100, num_rounds=3, local_epochs=1,
        batch_size=32, learning_rate=0.05, scheme="dirichlet", participation=0.1,
        alpha=0.5, prox_mu=0.01,
    ),
    "dp_fedavg_mnist": dict(
        model="mnist_cnn", num_clients=10, num_rounds=3, local_epochs=1,
        batch_size=64, learning_rate=0.1, scheme="iid", participation=1.0,
        dp=True,
    ),
    "cross_silo": dict(
        model="resnet18", num_clients=8, num_rounds=2, local_epochs=1,
        batch_size=32, learning_rate=0.05, scheme="iid", participation=1.0,
    ),
    # Flagship clients>>chips configuration (BASELINE.md north star: 1000-client MNIST
    # FedAvg round < 1 s).  60k MNIST / 1000 clients = 60 samples each; client_chunk
    # bounds per-device live memory while vmap batches the resident clients.
    "mnist_1000": dict(
        model="mnist_cnn", num_clients=1000, num_rounds=3, local_epochs=2,
        batch_size=64, learning_rate=0.1, scheme="iid", participation=1.0,
        client_chunk=125, compute_dtype="bfloat16",
    ),
}


def run_benchmark(
    name: str, out_dir: str = "runs/bench", **overrides: Any
) -> dict[str, Any]:
    """Run one named benchmark; ``overrides`` adjust any run_experiment kwarg
    (e.g. ``train_size=`` for a quick synthetic-data smoke run)."""
    if name not in BENCHMARKS:
        raise KeyError(f"unknown benchmark {name!r}; have {sorted(BENCHMARKS)}")
    from nanofed_tpu.experiments import run_experiment

    config = dict(BENCHMARKS[name])
    config.update(overrides)
    if config.pop("dp", False):
        from nanofed_tpu.aggregation.privacy import PrivacyAwareAggregationConfig
        from nanofed_tpu.orchestration import cohort_size
        from nanofed_tpu.privacy import PrivacyConfig
        from nanofed_tpu.privacy.accounting import noise_multiplier_for_budget

        # Calibrate σ so the whole run spends exactly the (ε=8, δ=1e-5) budget at the
        # realized cohort rate — a fixed σ would either blow the budget or waste it.
        q = cohort_size(config["num_clients"], config["participation"]) / config["num_clients"]
        sigma = noise_multiplier_for_budget(
            8.0, 1e-5, sampling_rate=q, num_events=config["num_rounds"]
        )
        config["central_privacy"] = PrivacyAwareAggregationConfig(
            privacy=PrivacyConfig(
                epsilon=8.0, delta=1e-5, max_gradient_norm=1.0, noise_multiplier=sigma
            )
        )
    summary = run_experiment(out_dir=out_dir, **config)
    durations = summary.get("round_durations_s", [])
    steady = durations[1:] or durations  # first round pays the XLA compile
    if steady:
        summary["rounds_per_sec"] = float(1.0 / np.median(steady))
    summary["benchmark"] = name
    return summary
