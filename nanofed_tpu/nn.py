"""Minimal functional neural-net layer library.

The reference builds models on ``torch.nn`` (``nanofed/models/mnist.py:6-28``).  Here models
are pure ``(init, apply)`` functions over explicit parameter pytrees — no module objects, no
mutable state — which is what lets a whole client population train under one
``vmap``/``shard_map`` program.  Layout is NHWC (channels-last), the native layout for TPU
convolutions; matmuls/convs stay large and batched so XLA tiles them onto the MXU.

Normalization is GroupNorm rather than BatchNorm: batch statistics are both mutable state
(breaking pure-function training) and statistically wrong under non-IID federated clients,
so GroupNorm is the standard choice in FL (cf. FedProx/LEAF practice).
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from nanofed_tpu.core.types import Params, PRNGKey

# ---------------------------------------------------------------------------
# Initializers
# ---------------------------------------------------------------------------


def _fan_in_out(shape: Sequence[int]) -> tuple[int, int]:
    if len(shape) == 2:  # dense [in, out]
        return shape[0], shape[1]
    # conv [kh, kw, cin, cout]
    receptive = math.prod(shape[:-2])
    return shape[-2] * receptive, shape[-1] * receptive


def kaiming_uniform(rng: PRNGKey, shape: Sequence[int], dtype=jnp.float32) -> jax.Array:
    """Kaiming uniform with torch's default bound: torch initializes Conv2d/Linear with
    ``kaiming_uniform_(a=sqrt(5))`` which reduces to U(-1/sqrt(fan_in), 1/sqrt(fan_in)),
    so training dynamics are comparable to the reference CNN."""
    fan_in, _ = _fan_in_out(shape)
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(rng, shape, dtype, -bound, bound)


def uniform_bias(rng: PRNGKey, fan_in: int, shape: Sequence[int], dtype=jnp.float32) -> jax.Array:
    bound = 1.0 / math.sqrt(fan_in) if fan_in > 0 else 0.0
    return jax.random.uniform(rng, shape, dtype, -bound, bound)


# ---------------------------------------------------------------------------
# Dense
# ---------------------------------------------------------------------------


def dense_init(rng: PRNGKey, in_features: int, out_features: int, dtype=jnp.float32) -> Params:
    k_w, k_b = jax.random.split(rng)
    return {
        "kernel": kaiming_uniform(k_w, (in_features, out_features), dtype),
        "bias": uniform_bias(k_b, in_features, (out_features,), dtype),
    }


def dense(params: Params, x: jax.Array) -> jax.Array:
    return x @ params["kernel"] + params["bias"]


# ---------------------------------------------------------------------------
# Conv2d (NHWC, HWIO kernels)
# ---------------------------------------------------------------------------


def conv2d_init(
    rng: PRNGKey,
    in_channels: int,
    out_channels: int,
    kernel_size: int | tuple[int, int],
    dtype=jnp.float32,
    use_bias: bool = True,
) -> Params:
    kh, kw = (kernel_size, kernel_size) if isinstance(kernel_size, int) else kernel_size
    k_w, k_b = jax.random.split(rng)
    params = {"kernel": kaiming_uniform(k_w, (kh, kw, in_channels, out_channels), dtype)}
    if use_bias:
        params["bias"] = uniform_bias(k_b, in_channels * kh * kw, (out_channels,), dtype)
    return params


def conv2d(
    params: Params,
    x: jax.Array,
    *,
    stride: int | tuple[int, int] = 1,
    padding: str = "VALID",
) -> jax.Array:
    """NHWC convolution via ``lax.conv_general_dilated`` — lowers straight to the MXU."""
    strides = (stride, stride) if isinstance(stride, int) else stride
    out = lax.conv_general_dilated(
        x,
        params["kernel"],
        window_strides=strides,
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if "bias" in params:
        out = out + params["bias"]
    return out


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------


def max_pool(x: jax.Array, window: int = 2, stride: int | None = None) -> jax.Array:
    stride = window if stride is None else stride
    return lax.reduce_window(
        x,
        -jnp.inf,
        lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding="VALID",
    )


def avg_pool(x: jax.Array, window: int = 2, stride: int | None = None) -> jax.Array:
    stride = window if stride is None else stride
    summed = lax.reduce_window(
        x,
        0.0,
        lax.add,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding="VALID",
    )
    return summed / (window * window)


def global_avg_pool(x: jax.Array) -> jax.Array:
    """[N, H, W, C] -> [N, C]."""
    return jnp.mean(x, axis=(1, 2))


# ---------------------------------------------------------------------------
# Dropout (functional — rng passed in, no state)
# ---------------------------------------------------------------------------


def dropout(rng: PRNGKey | None, x: jax.Array, rate: float, train: bool) -> jax.Array:
    """Inverted dropout; identity when ``train`` is False or rate == 0.

    The reference model uses rates .25/.5 (``nanofed/models/mnist.py:12-13``).
    """
    if not train or rate == 0.0:
        return x
    if rng is None:
        raise ValueError("dropout in train mode requires an rng key")
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


# ---------------------------------------------------------------------------
# GroupNorm
# ---------------------------------------------------------------------------


def group_norm_init(num_channels: int, dtype=jnp.float32) -> Params:
    return {"scale": jnp.ones((num_channels,), dtype), "bias": jnp.zeros((num_channels,), dtype)}


def group_norm(params: Params, x: jax.Array, num_groups: int = 8, eps: float = 1e-5) -> jax.Array:
    """GroupNorm over NHWC input."""
    n, h, w, c = x.shape
    g = min(num_groups, c)
    while c % g != 0:  # pragma: no cover - configs keep c % g == 0
        g -= 1
    xg = x.reshape(n, h, w, g, c // g)
    mean = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    xg = (xg - mean) * lax.rsqrt(var + eps)
    return xg.reshape(n, h, w, c) * params["scale"] + params["bias"]


# ---------------------------------------------------------------------------
# Activations / outputs
# ---------------------------------------------------------------------------

relu = jax.nn.relu
log_softmax = jax.nn.log_softmax


def flatten(x: jax.Array) -> jax.Array:
    """[N, ...] -> [N, prod(...)]."""
    return x.reshape(x.shape[0], -1)
