"""Per-tier wire paths: one adapter tree, three codecs, isolated residuals.

In a mixed fleet, the SAME round sees the same logical object — a tier's
adapter tree — cross the wire three different ways: a silo ships the full tree
as plain npz (``f32``), an edge box ships its factor-space delta through the
q8 quantizer, a phone ships the top-k sparsified delta.  This module owns the
two halves of that contract:

* :func:`decode_tier_submit` — the server side: given the tier's codec, the
  tier's structural template, and the tier's last PUBLISHED tree (the delta
  base), turn a payload into the full adapter tree the client now holds.  All
  three codecs land in the same place, so downstream aggregation
  (``fleet.aggregate``) never sees the wire.
* :class:`TierClientState` — the client side, transport-free: the delta-base
  pinning and topk8 error-feedback bookkeeping that ``communication.
  http_client.HTTPClient`` implements for homogeneous clients, replicated per
  tier so the staged-residual contract (fold-before-encode, commit-on-accept)
  is unit-testable without a server.  Each client owns its OWN state object:
  a phone's residual is its private unsent tail and must never leak into
  another client's — or another tier's — accounting (the mixed-tier round-trip
  tests assert this isolation).

The q8 codec needs no residual: stochastic rounding is unbiased, so FedAvg
averages its noise away (Alistarh et al. 2017).  topk8's dropped tail is
biased and DOES need error feedback (Seide et al. 2014; Karimireddy et al.
2019) — the residual accumulates what a submit didn't ship and rides the next
delta, staged (not committed) until the server accepts.
"""

from __future__ import annotations

import jax
import numpy as np

from nanofed_tpu.adapters.lora import AdapterSpec
from nanofed_tpu.communication.codec import (
    decode_delta_topk8,
    decode_params,
    encode_delta_q8,
    encode_delta_topk8,
    encode_params,
    reconstruct_q8,
    reconstruct_topk8,
)
from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.core.types import Params
from nanofed_tpu.fleet.profile import CODEC_ENCODINGS, DeviceTier

__all__ = [
    "TierClientState",
    "decode_tier_submit",
]


def decode_tier_submit(
    tier: DeviceTier,
    body: bytes,
    template: Params,
    published: Params,
) -> Params:
    """Payload -> the FULL adapter tree the client holds, by the tier's codec.

    ``template`` is the tier's structural template (shapes/dtypes validated
    against it); ``published`` is the tier tree the server last served this
    tier — the base both delta codecs measure against.  f32 payloads ARE the
    full tree; q8/topk8 payloads are deltas reconstructed onto ``published``
    in the shared float32 arithmetic of ``codec.reconstruct_*`` (the same
    invariant signature verification relies on)."""
    if tier.codec == "f32":
        return decode_params(body, like=template)
    if tier.codec == "q8":
        return reconstruct_q8(published, body)
    if tier.codec == "topk8":
        return reconstruct_topk8(published, body)
    raise NanoFedError(f"tier {tier.name!r}: unknown codec {tier.codec!r}")


def _f32_delta(new: Params, base: Params) -> Params:
    return jax.tree.map(
        lambda p, g: np.asarray(p, np.float32) - np.asarray(g, np.float32),
        new, base,
    )


class TierClientState:
    """One client's wire-side state for one tier (see module doc).

    Lifecycle per round: ``payload = encode(trained_tree)`` -> POST ->
    ``commit()`` on 200 or ``reject(trained_tree)`` on anything else; a fresh
    server publish arrives via ``set_base(tree)``.  For the ``f32``/``q8``
    codecs commit/reject are cheap bookkeeping; for ``topk8`` they implement
    the staged-residual contract of ``HTTPClient.submit_update``."""

    def __init__(self, tier: DeviceTier, spec: AdapterSpec, base: Params):
        if spec.rank != tier.adapter_rank:
            raise NanoFedError(
                f"tier {tier.name!r} trains rank {tier.adapter_rank} but the "
                f"spec says rank {spec.rank}"
            )
        self.tier = tier
        self.spec = spec
        self.base = base  # the tier tree the server last published to us
        self._residual: Params | None = None  # topk8 error-feedback accumulator
        # After a REJECTED topk8 submit the whole un-sent delta is folded into
        # _residual; _pending_base remembers the local tree that fold covered,
        # so a retry measures only post-fold training (HTTPClient's contract).
        self._pending_base: Params | None = None
        self._staged_residual: Params | None = None
        self.bytes_sent = 0
        self.submits = 0

    @property
    def encoding(self) -> str:
        return CODEC_ENCODINGS[self.tier.codec]

    def set_base(self, base: Params) -> None:
        """A fresh published tier tree: future deltas measure against it.  Any
        accumulated residual stays — it rides the next delta as usual — but
        retry bookkeeping resets (mass from a rejected submit is already in
        the residual)."""
        self.base = base
        self._pending_base = None
        self._staged_residual = None

    def encode(self, new_tree: Params, seed: int | None = None) -> bytes:
        """The wire bytes for this client's current local tree.  topk8 folds
        the residual in BEFORE encoding and stages (does not commit) the new
        unsent tail; nothing is mutated until :meth:`commit`/:meth:`reject`."""
        if self.tier.codec == "f32":
            body = encode_params(new_tree)
        else:
            delta_base = (
                self._pending_base if self._pending_base is not None else self.base
            )
            delta = _f32_delta(new_tree, delta_base)
            if self.tier.codec == "q8":
                body = encode_delta_q8(delta, seed=seed)
            else:
                if self._residual is not None:
                    delta = jax.tree.map(np.add, delta, self._residual)
                body = encode_delta_topk8(
                    delta, fraction=self.tier.topk_fraction, seed=seed
                )
                sent = decode_delta_topk8(body, like=self.base)
                # STAGED, not committed: the sent mass leaves the residual only
                # once the server accepts, or a rejected submit would lose it
                # from both sides forever.
                self._staged_residual = jax.tree.map(
                    lambda d, s: d - np.asarray(s, np.float32), delta, sent
                )
                self._pending_delta = delta
        self._last_body_len = len(body)
        return body

    def commit(self) -> None:
        """Server accepted: the staged residual becomes THE residual, retry
        bookkeeping clears, byte accounting advances."""
        if self._staged_residual is not None:
            self._residual = self._staged_residual
            self._staged_residual = None
        self._pending_base = None
        self.bytes_sent += getattr(self, "_last_body_len", 0)
        self.submits += 1

    def reject(self, new_tree: Params) -> None:
        """Server rejected: nothing was applied server-side.  topk8 folds the
        WHOLE combined delta (round progress + accumulated tail) into the
        residual and pins ``_pending_base`` at the local tree, so a retry
        contributes only post-fold training instead of double-counting."""
        if self.tier.codec == "topk8" and self._staged_residual is not None:
            self._residual = self._pending_delta
            self._pending_base = new_tree
            self._staged_residual = None

    def residual_norm(self) -> float:
        """l2 norm of the accumulated unsent tail (0 when no residual) — what
        the isolation tests compare across tiers."""
        if self._residual is None:
            return 0.0
        sq = jax.tree.map(
            lambda x: float(np.sum(np.square(np.asarray(x, np.float64)))),
            self._residual,
        )
        return float(np.sqrt(sum(jax.tree.leaves(sq))))
