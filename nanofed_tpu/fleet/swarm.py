"""Fleet load generation: per-tier sub-swarms on one clock, one server.

A homogeneous swarm (``loadgen.swarm``) is one arrival process over one body
pool.  A FLEET is several at once: the phone tier's bursty poisson trickle of
tiny topk8 bodies lands on the same ``/update`` endpoint as the silo tier's
burst of full f32 trees, and the interesting server behaviors — per-tier
decode routing, admission control under mixed body sizes, ingest backpressure
hitting the chatty tier first — only show up when the sub-swarms actually
interleave.  :func:`run_fleet_swarm` builds one ``SwarmConfig`` per tier from
the profile (population via ``population_split`` x availability, arrival and
skew from the tier, codec-correct canned bodies against the tier's PUBLISHED
view) and drives them concurrently on one injected clock, so the whole mixed
schedule runs on a ``VirtualClock`` in milliseconds exactly like the
single-tier smoke tests.
"""

from __future__ import annotations

import asyncio
from typing import Any

from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.core.types import Params
from nanofed_tpu.fleet.profile import FleetProfile
from nanofed_tpu.loadgen.swarm import (
    SwarmConfig,
    SwarmResult,
    latency_digest,
    run_swarm,
)
from nanofed_tpu.utils.clock import Clock

__all__ = ["fleet_swarm_digest", "run_fleet_swarm", "tier_swarm_configs"]


def tier_swarm_configs(
    profile: FleetProfile,
    num_clients: int,
    submits_per_client: int = 1,
    seed: int = 0,
    delta_scale: float = 1e-3,
    apply_availability: bool = True,
    **overrides: Any,
) -> dict[str, SwarmConfig]:
    """One ``SwarmConfig`` per tier: population from the profile's
    largest-remainder split (scaled by availability — the clients who actually
    show up this round), arrival/skew/codec from the tier, disjoint client-id
    spaces, per-tier seeds.  ``overrides`` pass through to every tier's config
    (retry policy, connector limit, ...)."""
    split = profile.population_split(num_clients)
    configs: dict[str, SwarmConfig] = {}
    for i, tier in enumerate(profile.tiers):
        participants = split[tier.name]
        if apply_availability:
            participants = max(1, int(round(participants * tier.availability)))
        configs[tier.name] = SwarmConfig(
            num_clients=participants,
            submits_per_client=submits_per_client,
            arrival=tier.arrival,
            arrival_rate=tier.arrival_rate,
            weight_skew=tier.weight_skew,
            delta_scale=delta_scale,
            seed=seed + 101 * i,
            encoding=tier.encoding,
            topk_fraction=tier.topk_fraction,
            tier=tier.name,
            client_prefix=f"fleet_{tier.name}",
            **overrides,
        )
    return configs


async def run_fleet_swarm(
    server_url: str,
    profile: FleetProfile,
    tier_bases: dict[str, Params],
    num_clients: int,
    submits_per_client: int = 1,
    seed: int = 0,
    clock: Clock | None = None,
    registry: Any | None = None,
    **overrides: Any,
) -> dict[str, SwarmResult]:
    """Drive every tier's sub-swarm concurrently against one live server.

    ``tier_bases`` maps tier name -> the tier's PUBLISHED adapter tree (a
    fleet server's ``FleetGateway.view(tier).tree``): the f32 tier's canned
    bodies are noisy variants of it, the delta tiers' bodies are noise deltas
    the server reconstructs against it.  Returns per-tier raw results —
    :func:`fleet_swarm_digest` folds them into the artifact block."""
    missing = [t for t in profile.tier_names() if t not in tier_bases]
    if missing:
        raise NanoFedError(f"tier_bases missing entries for tiers: {missing}")
    configs = tier_swarm_configs(
        profile, num_clients, submits_per_client=submits_per_client,
        seed=seed, **overrides,
    )
    names = list(configs)
    results = await asyncio.gather(*(
        run_swarm(
            server_url, tier_bases[name], configs[name],
            clock=clock, registry=registry,
        )
        for name in names
    ))
    return dict(zip(names, results))


def fleet_swarm_digest(
    results: dict[str, SwarmResult], profile: FleetProfile
) -> dict[str, Any]:
    """Per-tier submit outcome + latency digest, plus fleet-wide totals — the
    shape the fleet telemetry record and the runs/ artifact carry."""
    out: dict[str, Any] = {"tiers": {}, "profile": profile.name}
    tot_accepted = tot_failed = tot_429 = 0
    for name, r in results.items():
        tier = profile.tier(name)
        out["tiers"][name] = {
            "codec": tier.codec,
            "rank": tier.adapter_rank,
            "logical_submits": (
                r.accepted + r.duplicates + r.failed + r.terminated_early
            ),
            "accepted": r.accepted,
            "duplicates": r.duplicates,
            "rejected_429": r.rejected_429,
            "retries": r.retries,
            "stale_refreshes": r.stale_refreshes,
            "failed": r.failed,
            "terminated_early": r.terminated_early,
            "latency": latency_digest(r.latencies_s),
        }
        tot_accepted += r.accepted
        tot_failed += r.failed
        tot_429 += r.rejected_429
    out["accepted_total"] = tot_accepted
    out["failed_total"] = tot_failed
    out["rejected_429_total"] = tot_429
    return out
