"""Fleet-mix tuning: sweep per-tier ranks analytically, price ranks once.

The homogeneous autotuner sweeps ONE adapter rank through the {r/2, r, 2r}
ladder, paying an XLA compile per candidate.  A fleet has a rank PER TIER, so
the naive compiled sweep is exponential in tiers — and unnecessary: for a
fixed mix (tier fractions and codecs don't move during a rank sweep), the two
things a mix candidate changes are analytic.  Aggregate wire bytes per round
follow from parameter counts x codec bytes x expected participants
(``FleetProfile.wire_bytes_per_round``), and device-memory feasibility
follows from the max-rank tier (``TenantFootprint.for_fleet`` — the dense
ingest path makes everything else rank-independent).  So:

* :func:`mix_candidates` — the cross product of per-tier ``{r/2, r, 2r}``
  ladders (the homogeneous ladder rule, applied per tier with the mix fixed).
* :func:`sweep_fleet_mix` — score every candidate WITHOUT compiling: filter
  by HBM budget, then rank by wire bytes per unit of fleet capacity (the
  availability-weighted mean rank — the analytic stand-in for "how much
  model the round actually trains").  Deterministic: equal scores fall back
  to the candidate key.

Per-rank COMPILED costs still matter for step-time feasibility — that is what
``TuningSpace.for_fleet`` exists for: it prices the UNION of every ladder
rank through the normal compiled sweep (linear in distinct ranks, not
exponential in tiers), and its measured per-rank costs can be fed back here
via ``step_costs`` to annotate the analytic ranking with real seconds.  The
final authority on quality stays with measured convergence
(``fleet.evidence``); this sweep chooses which few mixes are worth measuring.
"""

from __future__ import annotations

import dataclasses
import itertools
from dataclasses import dataclass, field
from typing import Any, Mapping

from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.core.types import Params
from nanofed_tpu.fleet.profile import FleetProfile

__all__ = [
    "FleetMixCandidate",
    "FleetMixOutcome",
    "mix_candidates",
    "profile_with_ranks",
    "sweep_fleet_mix",
]


def _ladder(rank: int) -> tuple[int, ...]:
    """The homogeneous autotuner's rank ladder, per tier."""
    return tuple(sorted({max(1, rank // 2), rank, 2 * rank}))


@dataclass(frozen=True, order=True)
class FleetMixCandidate:
    """One per-tier rank assignment, tiers in profile order.  Ordered, so the
    dataclass ordering is the deterministic last-resort tie-break."""

    ranks: tuple[tuple[str, int], ...]  # ((tier_name, rank), ...)

    def rank_for(self, tier_name: str) -> int:
        for name, r in self.ranks:
            if name == tier_name:
                return r
        raise NanoFedError(f"mix candidate has no tier {tier_name!r}")

    def to_dict(self) -> dict[str, int]:
        return dict(self.ranks)


def mix_candidates(profile: FleetProfile) -> list[FleetMixCandidate]:
    """Cross product of every tier's ladder — ``3^tiers`` candidates minus
    ladder collisions, each a full per-tier rank assignment."""
    names = profile.tier_names()
    ladders = [_ladder(profile.tier(n).adapter_rank) for n in names]
    return [
        FleetMixCandidate(ranks=tuple(zip(names, combo)))
        for combo in itertools.product(*ladders)
    ]


def profile_with_ranks(
    profile: FleetProfile, candidate: FleetMixCandidate
) -> FleetProfile:
    """The profile re-ranked to the candidate (fractions, codecs, arrivals
    untouched — the mix is fixed, only ranks move)."""
    tiers = tuple(
        dataclasses.replace(t, adapter_rank=candidate.rank_for(t.name))
        for t in profile.tiers
    )
    return dataclasses.replace(profile, tiers=tiers)


@dataclass
class FleetMixOutcome:
    """One candidate's analytic fate: wire/memory numbers, feasibility, and
    the score the ranking sorts by (lower is better)."""

    candidate: FleetMixCandidate
    feasible: bool
    reject_reason: str | None = None
    wire_bytes_per_round: int = 0
    capacity: float = 0.0  # availability-weighted mean rank
    hbm_resident_bytes: int = 0
    hbm_peak_bytes: int = 0
    score: float | None = None
    step_cost_s: float | None = None  # from measured per-rank costs, if given
    detail: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {
            "ranks": self.candidate.to_dict(),
            "feasible": self.feasible,
            **({"reject_reason": self.reject_reason}
               if self.reject_reason else {}),
            "wire_bytes_per_round": self.wire_bytes_per_round,
            "capacity": round(self.capacity, 3),
            "hbm_resident_bytes": self.hbm_resident_bytes,
            "hbm_peak_bytes": self.hbm_peak_bytes,
            **({"score": round(self.score, 2)} if self.score is not None else {}),
            **({"step_cost_s": self.step_cost_s}
               if self.step_cost_s is not None else {}),
        }


def sweep_fleet_mix(
    profile: FleetProfile,
    base_like: Params,
    num_clients: int,
    hbm_budget_bytes: int | None = None,
    ingest_capacity: int = 64,
    agg_k: int = 8,
    step_costs: Mapping[int, float] | None = None,
) -> list[FleetMixOutcome]:
    """Score every mix candidate analytically; returns outcomes sorted best
    first (feasible before infeasible, then ascending score, then candidate
    order).  Score = wire bytes per round / fleet capacity — bytes paid per
    unit of availability-weighted rank, so a candidate that halves the
    phone tier's rank only wins if the byte saving beats the capacity loss.
    ``step_costs`` (rank -> measured seconds, from the compiled
    ``TuningSpace.for_fleet`` sweep) annotates each outcome with the max-rank
    tier's measured step cost; it does not change the ranking — wall-clock
    feasibility is the compiled sweep's verdict, not this one's."""
    outcomes: list[FleetMixOutcome] = []
    from nanofed_tpu.service.scheduler import TenantFootprint

    for cand in mix_candidates(profile):
        p = profile_with_ranks(profile, cand)
        wire = p.wire_bytes_per_round(base_like, num_clients)
        capacity = sum(
            t.fraction * t.availability * t.adapter_rank for t in p.tiers
        )
        fp = TenantFootprint.for_fleet(
            p, base_like, ingest_capacity=ingest_capacity, agg_k=agg_k
        )
        out = FleetMixOutcome(
            candidate=cand,
            feasible=True,
            wire_bytes_per_round=int(wire["total_bytes_per_round"]),
            capacity=capacity,
            hbm_resident_bytes=fp.resident_bytes,
            hbm_peak_bytes=fp.peak_extra_bytes,
            detail={"wire": wire, "footprint_basis": fp.basis},
        )
        if step_costs is not None:
            out.step_cost_s = step_costs.get(p.max_rank)
        if (
            hbm_budget_bytes is not None
            and fp.resident_bytes + fp.peak_extra_bytes > hbm_budget_bytes
        ):
            out.feasible = False
            out.reject_reason = (
                f"hbm: resident {fp.resident_bytes} + peak "
                f"{fp.peak_extra_bytes} > budget {hbm_budget_bytes}"
            )
        else:
            out.score = out.wire_bytes_per_round / max(capacity, 1e-9)
        outcomes.append(out)
    outcomes.sort(
        key=lambda o: (
            not o.feasible,
            o.score if o.score is not None else float("inf"),
            o.candidate,
        )
    )
    return outcomes
