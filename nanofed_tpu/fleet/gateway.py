"""Server-side fleet state: per-tier published views over one dense global.

In fleet mode the server's global model stays exactly what it always was — a
dense base-shaped params tree, published each round, aggregated through the
batched device ingest buffer (``ingest.buffer``) in flat dense-delta space.
What changes is the EDGE of the server: each tier sees the global through its
own low-rank window.  The :class:`FleetGateway` owns that edge:

* :meth:`publish` — at every ``publish_model`` the gateway takes the new
  global params, forms the dense delta vs the frozen round-0 base, and
  projects it onto EVERY tier's rank via truncated SVD
  (``fleet.aggregate.project_to_rank``); each tier's published view is the
  projected adapter tree, its npz payload (what ``GET /model`` with a tier
  header serves), and its dense-flat image (the delta base tier submits are
  measured against).  Zero-padded SVD columns are revived with the LoRA init
  draw (:func:`~nanofed_tpu.fleet.aggregate.revive_adapters`) so a tier whose
  view is rank-deficient — every tier, at round 0 — still has gradient flow.
* :meth:`decode_submit` — a tier submit (any codec) decodes into the full
  adapter tree the client now holds, densifies through ``adapter_delta``, and
  returns the flat dense delta vs the tier's published view.  That row drops
  straight into the existing ingest buffer: ``drain`` then computes
  ``published + weighted-mean(per-client training progress)``, the same
  FedAvg-on-deltas semantics as a homogeneous cohort — the buffer never
  learns tiers exist.

Views are versioned with the SAME window rule as the ingest pipeline's flat
base cache, so wire acceptance and tier-delta reconstruction can never
disagree about which rounds are alive.

The per-publish cost is one truncated SVD per targeted leaf per tier — fine
for the adapter-scale models this subsystem targets (docs/fleet.md quantifies
it); the projections happen once per round on the server, not per client.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import numpy as np

from nanofed_tpu.adapters.lora import AdapterSpec, adapter_delta
from nanofed_tpu.communication.codec import encode_params
from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.core.types import Params
from nanofed_tpu.fleet.aggregate import project_to_rank, revive_adapters
from nanofed_tpu.fleet.profile import FleetProfile
from nanofed_tpu.fleet.wire import decode_tier_submit

__all__ = ["FleetGateway", "TierView"]


@dataclass(frozen=True)
class TierView:
    """One tier's published window onto one round's global model."""

    tree: Params  # the tier-rank adapter tree (what the tier fetches)
    flat_dense: np.ndarray  # flat dense image of ``tree`` (delta base, [P] f32)
    payload: bytes  # npz of ``tree`` — the GET /model body for this tier


class FleetGateway:
    """Per-tier publish/decode state for an :class:`~nanofed_tpu.communication.
    http_server.HTTPServer` running a heterogeneous fleet (see module doc).

    ``base_like`` is the FROZEN round-0 base the whole fleet adapts; every
    dense delta — published or submitted — is measured against it.
    ``spec_kwargs`` (targets, min_dim, ...) are shared across tiers exactly as
    ``FleetProfile.specs`` shares them; ranks come from the tiers."""

    def __init__(
        self,
        profile: FleetProfile,
        base_like: Params,
        spec_kwargs: dict[str, Any] | None = None,
        revive_seed: int = 0,
    ) -> None:
        self.profile = profile
        self.base_like = jax.device_get(base_like)
        self.specs: dict[str, AdapterSpec] = profile.specs(**(spec_kwargs or {}))
        self.revive_seed = revive_seed
        self.current_round: int | None = None
        self._views: dict[int, dict[str, TierView]] = {}  # round -> tier -> view

    def spec(self, tier_name: str) -> AdapterSpec:
        try:
            return self.specs[tier_name]
        except KeyError:
            raise NanoFedError(
                f"fleet profile {self.profile.name!r} has no tier {tier_name!r}"
            ) from None

    # ------------------------------------------------------------------
    # Publish side
    # ------------------------------------------------------------------

    def publish(self, round_number: int, params: Params, window: int = 0) -> None:
        """Project the new global onto every tier and version the views with
        the ingest pipeline's pruning rule (keep ``[round - window, round]``;
        ``window=0`` keeps only the current round)."""
        from nanofed_tpu.ingest.pipeline import flatten_params

        params = jax.device_get(params)
        dense = jax.tree.map(
            lambda p, b: np.asarray(p, np.float32) - np.asarray(b, np.float32),
            params, self.base_like,
        )
        views: dict[str, TierView] = {}
        for name, spec in self.specs.items():
            tree = project_to_rank(dense, spec, self.base_like)
            tree = revive_adapters(
                tree, spec, seed=self.revive_seed + round_number
            )
            flat = flatten_params(
                adapter_delta(spec, self.base_like, tree)
            ).astype(np.float32)
            views[name] = TierView(
                tree=tree, flat_dense=flat, payload=encode_params(tree)
            )
        self._views[round_number] = views
        self.current_round = round_number
        floor = round_number - max(0, window)
        for old in [r for r in self._views if r < floor]:
            del self._views[old]

    def view(self, tier_name: str, round_number: int | None = None) -> TierView:
        """The tier's published view for ``round_number`` (default: current).
        Raises when the round is outside the live window — the server maps
        that onto its stale-round rejection."""
        rnd = self.current_round if round_number is None else round_number
        views = self._views.get(rnd)
        if views is None or tier_name not in views:
            raise NanoFedError(
                f"no published fleet view for tier {tier_name!r} at round {rnd}"
            )
        return views[tier_name]

    def payload(self, tier_name: str, round_number: int | None = None) -> bytes:
        """The npz body ``GET /model`` serves a client of this tier."""
        return self.view(tier_name, round_number).payload

    # ------------------------------------------------------------------
    # Submit side
    # ------------------------------------------------------------------

    def decode_submit(
        self, tier_name: str, body: bytes, round_number: int
    ) -> np.ndarray:
        """Tier payload -> flat dense-delta row for the ingest buffer: decode
        by the tier's codec against the tier's published view for the
        client's round, densify through ``adapter_delta``, subtract the
        view's dense image.  CPU-bound (npz decompress + matmuls + O(P)
        subtract) — the server runs it in the decode worker pool."""
        from nanofed_tpu.ingest.pipeline import flatten_params

        tier = self.profile.tier(tier_name)
        view = self.view(tier_name, round_number)
        new_tree = decode_tier_submit(
            tier, body, template=view.tree, published=view.tree
        )
        flat = flatten_params(
            adapter_delta(self.spec(tier_name), self.base_like, new_tree)
        ).astype(np.float32)
        return flat - view.flat_dense

    # ------------------------------------------------------------------
    # Introspection
    # ------------------------------------------------------------------

    def stats(self) -> dict[str, Any]:
        """Per-tier shape of the CURRENT views — rank, payload bytes, live
        rounds — for /status surfaces and the fleet telemetry record."""
        out: dict[str, Any] = {
            "profile": self.profile.name,
            "round": self.current_round,
            "live_rounds": sorted(self._views),
            "tiers": {},
        }
        if self.current_round is not None:
            for name, v in self._views[self.current_round].items():
                out["tiers"][name] = {
                    "rank": self.spec(name).rank,
                    "codec": self.profile.tier(name).codec,
                    "payload_bytes": len(v.payload),
                }
        return out
