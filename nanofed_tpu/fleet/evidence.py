"""Evidence harness for heterogeneous fleet federation (ISSUE 16).

Produces the two committed ``runs/`` artifacts:

* ``fleet_<tag>_*.json`` (:func:`generate_fleet_evidence`) — the headline
  artifact: a ≥3-tier fleet (rank-4 topk8 phones, rank-8 q8 edge boxes,
  rank-32 f32 silos) trained IN PROCESS with every submit crossing the real
  wire codecs and both aggregation routes (dense reference vs padded einsum)
  parity-asserted per round, against a homogeneous max-rank/f32 baseline on
  the identical population and arrival pattern — the claim is comparable loss
  at a FRACTION of the aggregate wire bytes.  A second leg drives the
  per-tier sub-swarms over live HTTP on the VirtualClock for the measured
  per-tier p99 submit latency with zero lost submits.
* ``fedbuff_staleness_<tag>.json``
  (:func:`generate_fedbuff_staleness_ablation`) — the staleness-exponent
  ablation over the ``runs/fedbuff_adapter_r15_*`` scenario: the same
  poisson-arrival x lognormal-delay distribution, replayed through
  ``DeviceIngestBuffer.drain_fedbuff`` at α ∈ {0, 0.25, 0.5, 1, 2} with
  EVERYTHING else (seeds, delays, cohort) held fixed, converging a real
  adapter federation per α — where the discount-free (α=0) and
  over-discounted (α=2) corners land is the artifact's finding.

Every number states its basis; runs are deterministic in their seeds.  Run
both via ``python -m nanofed_tpu.fleet.evidence`` (a few minutes on CPU).
"""

from __future__ import annotations

import asyncio
import heapq
import json
from pathlib import Path
from typing import Any

import numpy as np

from nanofed_tpu.utils.logger import Logger

_LOG = Logger()


def _stamp() -> str:
    from nanofed_tpu.utils.dates import get_current_time

    return get_current_time().strftime("%Y%m%dT%H%M%S")


def _max_abs_diff(t1: Any, t2: Any) -> float:
    import jax

    diffs = jax.tree.map(
        lambda a, b: float(
            np.max(np.abs(np.asarray(a, np.float32) - np.asarray(b, np.float32)))
        ),
        t1, t2,
    )
    return max(jax.tree.leaves(diffs))


def homogenize(profile: Any, codec: str = "f32") -> Any:
    """The baseline mix: same tiers, fractions, arrivals, and availability —
    but every tier at the profile's MAX rank on the ``codec`` wire.  What the
    fleet run is judged against: heterogeneity changes only what it claims to
    change."""
    import dataclasses

    from nanofed_tpu.fleet.profile import FleetProfile

    tiers = tuple(
        dataclasses.replace(t, adapter_rank=profile.max_rank, codec=codec)
        for t in profile.tiers
    )
    return FleetProfile(name=f"{profile.name}_homogeneous", tiers=tiers)


def run_fleet_convergence(
    profile: Any,
    num_clients: int = 30,
    num_rounds: int = 20,
    local_steps: int = 8,
    learning_rate: float = 0.5,
    seed: int = 0,
) -> dict[str, Any]:
    """One in-process fleet federation: every participant fetches its tier's
    published view (truncated-SVD projection of the global, dead directions
    revived), trains its tier-rank adapters locally, and submits through its
    tier's REAL wire codec (the server decodes what actually crossed the
    wire — q8 noise and the topk8 tail are in the trajectory, with per-client
    error feedback riding between rounds).  Both aggregation routes run every
    round and their parity is the returned ``parity_max_abs_diff``."""
    import jax
    import jax.numpy as jnp

    from nanofed_tpu.adapters import make_adapter_apply
    from nanofed_tpu.data import federate, pack_eval, synthetic_classification
    from nanofed_tpu.fleet.aggregate import (
        AdapterUpdate,
        aggregate_dense,
        aggregate_padded,
    )
    from nanofed_tpu.fleet.gateway import FleetGateway
    from nanofed_tpu.fleet.wire import TierClientState, decode_tier_submit
    from nanofed_tpu.models import get_model

    in_features, hidden, num_classes = 64, 128, 10
    model = get_model(
        "mlp", in_features=in_features, hidden=hidden, num_classes=num_classes
    )
    base = jax.device_get(model.init(jax.random.key(seed)))
    train = synthetic_classification(
        64 * num_clients, num_classes=num_classes, shape=(in_features,),
        seed=seed,
    )
    test = synthetic_classification(
        1024, num_classes=num_classes, shape=(in_features,), seed=seed + 1
    )
    data = federate(train, num_clients=num_clients, batch_size=32, seed=seed)
    eval_pack = pack_eval(test, batch_size=256)

    gateway = FleetGateway(profile, base, revive_seed=seed)
    split = profile.population_split(num_clients)
    # contiguous client-index ranges per tier, in profile order
    ranges: dict[str, np.ndarray] = {}
    lo = 0
    for t in profile.tiers:
        ranges[t.name] = np.arange(lo, lo + split[t.name])
        lo += split[t.name]

    def make_fit(spec):
        apply = make_adapter_apply(model.apply, spec, base)
        # the common-alpha convention scales a tier's delta by alpha/rank, and
        # a gradient step moves the delta by that factor SQUARED — normalize
        # the local lr so every tier takes comparable delta-space steps
        scale = (spec.alpha if spec.alpha is not None else spec.rank) / spec.rank
        lr = learning_rate / scale**2

        def loss_fn(ad, x, y, m):
            logp = apply(ad, x)
            nll = -jnp.take_along_axis(logp, y[:, None], axis=-1)[:, 0]
            return (nll * m).sum() / jnp.maximum(m.sum(), 1.0)

        @jax.jit
        def fit(ad, x, y, m):
            def step(a, _):
                g = jax.grad(loss_fn)(a, x, y, m)
                return jax.tree.map(lambda p, q: p - lr * q, a, g), None

            out, _ = jax.lax.scan(step, ad, None, length=local_steps)
            return out

        return fit

    fits = {name: make_fit(spec) for name, spec in gateway.specs.items()}

    # fedlint: disable=FED004 (eval must NOT donate: the merged global params are re-evaluated and re-published every round)
    @jax.jit
    def eval_loss(params):
        logp = model.apply(params, jnp.asarray(eval_pack.x))
        nll = -jnp.take_along_axis(
            logp, jnp.asarray(eval_pack.y)[:, None], axis=-1
        )[:, 0]
        m = jnp.asarray(eval_pack.mask)
        return (nll * m).sum() / m.sum()

    rng = np.random.default_rng(seed)
    global_params = jax.tree.map(
        lambda x: np.asarray(x, np.float32), base
    )
    states: dict[int, TierClientState] = {}
    wire_bytes = {t.name: 0 for t in profile.tiers}
    submit_counts = {t.name: 0 for t in profile.tiers}
    losses: list[float] = []
    parity_max = 0.0
    for r in range(num_rounds):
        gateway.publish(r, global_params)
        updates = []
        for tier in profile.tiers:
            view = gateway.view(tier.name, r)
            spec = gateway.spec(tier.name)
            pool = ranges[tier.name]
            k = max(1, int(round(len(pool) * tier.availability)))
            chosen = rng.choice(pool, size=min(k, len(pool)), replace=False)
            for ci in chosen:
                ci = int(ci)
                st = states.get(ci)
                if st is None:
                    st = states[ci] = TierClientState(tier, spec, view.tree)
                st.set_base(view.tree)
                trained = jax.device_get(
                    fits[tier.name](
                        view.tree,
                        jnp.asarray(data.x[ci]),
                        jnp.asarray(data.y[ci]),
                        jnp.asarray(data.mask[ci]),
                    )
                )
                body = st.encode(trained, seed=seed + 7919 * r + ci)
                st.commit()
                wire_bytes[tier.name] += len(body)
                submit_counts[tier.name] += 1
                # the server sees what the CODEC delivered, not the raw tree
                on_server = decode_tier_submit(
                    tier, body, template=view.tree, published=view.tree
                )
                updates.append(AdapterUpdate(
                    spec=spec, adapters=on_server,
                    weight=float(data.mask[ci].sum()), tier=tier.name,
                ))
        dense_agg = aggregate_dense(updates, base)
        padded_agg = aggregate_padded(updates, base)
        parity_max = max(parity_max, _max_abs_diff(dense_agg, padded_agg))
        global_params = jax.tree.map(
            lambda b, d: np.asarray(b, np.float32) + np.asarray(d, np.float32),
            base, jax.device_get(padded_agg),
        )
        losses.append(round(float(eval_loss(global_params)), 4))
    total = int(sum(wire_bytes.values()))
    return {
        "profile": profile.name,
        "tiers": {
            t.name: {
                "rank": t.adapter_rank,
                "codec": t.codec,
                "clients": int(split[t.name]),
                "availability": t.availability,
                "submits": submit_counts[t.name],
                "wire_bytes": int(wire_bytes[t.name]),
                "bytes_per_submit": int(
                    wire_bytes[t.name] / max(submit_counts[t.name], 1)
                ),
            }
            for t in profile.tiers
        },
        "rounds": num_rounds,
        "losses": losses,
        "final_loss": losses[-1],
        "loss_descending": bool(losses[-1] < losses[0]),
        "wire_bytes_total": total,
        "parity_max_abs_diff": parity_max,
        "basis": (
            "in-process fleet FedAvg on synthetic_classification: per-tier "
            "truncated-SVD views, local SGD on tier-rank adapters, submits "
            "decoded from the REAL codec payloads (len() of those payloads "
            "is the wire accounting), dense and padded aggregation routes "
            "both computed every round"
        ),
    }


async def _swarm_leg(
    profile: Any,
    num_clients: int = 60,
    submits_per_client: int = 2,
    seed: int = 0,
) -> dict[str, Any]:
    """Per-tier sub-swarms against a LIVE fleet server on the VirtualClock:
    mixed codec payloads on one /update endpoint, per-tier submit latency
    digests, per-tier rx/tx byte counters from the server's own registry."""
    import jax

    from nanofed_tpu.communication.http_server import HTTPServer
    from nanofed_tpu.communication.transport import free_port
    from nanofed_tpu.fleet.gateway import FleetGateway
    from nanofed_tpu.fleet.swarm import fleet_swarm_digest, run_fleet_swarm
    from nanofed_tpu.ingest import IngestConfig
    from nanofed_tpu.models import get_model
    from nanofed_tpu.observability.registry import MetricsRegistry
    from nanofed_tpu.utils.clock import VirtualClock

    model = get_model("mlp", in_features=64, hidden=128, num_classes=10)
    base = jax.device_get(model.init(jax.random.key(seed)))
    clock = VirtualClock()
    registry = MetricsRegistry()
    gateway = FleetGateway(profile, base, revive_seed=seed)
    port = free_port()
    server = HTTPServer(
        port=port,
        registry=registry,
        max_inflight=128,
        clock=clock,
        ingest=IngestConfig(capacity=4 * num_clients, decode_workers=4),
        fleet=gateway,
    )
    await server.start()
    try:
        await server.publish_model(params=base, round_number=0)
        tier_bases = {
            name: gateway.view(name).tree for name in profile.tier_names()
        }
        results = await run_fleet_swarm(
            f"http://127.0.0.1:{port}", profile, tier_bases, num_clients,
            submits_per_client=submits_per_client, seed=seed,
            clock=clock, registry=registry,
        )
    finally:
        await server.stop()
    digest = fleet_swarm_digest(results, profile)
    snapshot = registry.snapshot()
    fleet_bytes = snapshot.get("nanofed_fleet_bytes_total", {}).get("values", {})
    digest["server_bytes_by_tier"] = {
        k: int(v) for k, v in sorted(fleet_bytes.items())
    }
    digest["clock"] = "virtual"
    digest["population"] = num_clients
    digest["submits_per_client"] = submits_per_client
    digest["basis"] = (
        "per-tier sub-swarms over live HTTP on the VirtualClock: latency "
        "digests from the swarm harness, byte counts from the server's "
        "nanofed_fleet_bytes_total counter (tier,direction)"
    )
    return digest


def generate_fleet_evidence(
    out_dir: str | Path = "runs",
    tag: str = "r16",
    num_clients: int = 30,
    num_rounds: int = 20,
    swarm_clients: int = 60,
    seed: int = 0,
) -> dict[str, Any]:
    """The headline fleet artifact (see module doc).  Writes
    ``<out_dir>/fleet_<tag>_<stamp>.json`` and a ``fleet`` telemetry record
    that ``nanofed-tpu metrics-summary`` digests into its ``fleets`` block."""
    import jax

    from nanofed_tpu.fleet.profile import reference_fleet
    from nanofed_tpu.observability.telemetry import RunTelemetry

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    profile = reference_fleet()
    _LOG.info("fleet evidence: mixed %s convergence ...", profile.name)
    mixed = run_fleet_convergence(
        profile, num_clients=num_clients, num_rounds=num_rounds, seed=seed
    )
    baseline_profile = homogenize(profile)
    _LOG.info("fleet evidence: homogeneous baseline convergence ...")
    baseline = run_fleet_convergence(
        baseline_profile, num_clients=num_clients, num_rounds=num_rounds,
        seed=seed,
    )
    _LOG.info("fleet evidence: live-server swarm leg ...")
    swarm = asyncio.run(_swarm_leg(profile, num_clients=swarm_clients, seed=seed))

    wire_ratio = round(
        baseline["wire_bytes_total"] / max(mixed["wire_bytes_total"], 1), 2
    )
    loss_gap = round(mixed["final_loss"] - baseline["final_loss"], 4)
    p99_by_tier = {
        name: rec["latency"].get("p99_s")
        for name, rec in swarm["tiers"].items()
    }
    # "comparable loss": within 25% relative OR 0.05 absolute — the relative
    # bound alone is meaningless once both runs sit near zero loss
    comparable = mixed["final_loss"] <= max(
        baseline["final_loss"] * 1.25, baseline["final_loss"] + 0.05
    )
    reached = bool(
        len(profile.tiers) >= 3
        and mixed["loss_descending"]
        and baseline["loss_descending"]
        and mixed["parity_max_abs_diff"] < 1e-5
        and comparable
        and mixed["wire_bytes_total"] * 2 <= baseline["wire_bytes_total"]
        and swarm["failed_total"] == 0
    )
    artifact = {
        "record_type": "fleet",
        "tag": tag,
        "created": _stamp(),
        "env": {
            "jax": jax.__version__,
            "backend": jax.default_backend(),
            "device_count": jax.device_count(),
            "basis": (
                "CPU host run — trajectories, payload bytes, and VirtualClock "
                "latencies are platform-independent"
            ),
        },
        "profile": profile.to_dict(),
        "mixed": mixed,
        "homogeneous_baseline": baseline,
        "comparison": {
            "wire_reduction_vs_homogeneous": wire_ratio,
            "final_loss_gap": loss_gap,
            "basis": (
                "identical population, arrival pattern, rounds, and seeds; "
                "only ranks and codecs differ"
            ),
        },
        "swarm": swarm,
        "reached": reached,
        "conclusion": (
            f"{len(profile.tiers)}-tier fleet (ranks "
            f"{[t.adapter_rank for t in profile.tiers]}, codecs "
            f"{[t.codec for t in profile.tiers]}): loss "
            f"{mixed['losses'][0]:.3f} -> {mixed['final_loss']:.3f} vs "
            f"homogeneous rank-{profile.max_rank} baseline "
            f"{baseline['final_loss']:.3f} at {wire_ratio}x fewer aggregate "
            f"wire bytes; dense/padded aggregation parity "
            f"{mixed['parity_max_abs_diff']:.2e}; live-server swarm: "
            f"{swarm['accepted_total']} accepted, {swarm['failed_total']} "
            "lost submits"
        ),
    }
    tel = RunTelemetry(out_dir / f"fleet_{tag}_telemetry")
    tel.record(
        "fleet",
        profile=profile.name,
        tiers=len(profile.tiers),
        population=num_clients,
        max_rank=profile.max_rank,
        rounds=num_rounds,
        accepted_total=swarm["accepted_total"],
        failed_total=swarm["failed_total"],
        rejected_429_total=swarm["rejected_429_total"],
        wire_bytes_by_tier={
            name: rec["wire_bytes"] for name, rec in mixed["tiers"].items()
        },
        p99_s_by_tier=p99_by_tier,
        parity_max_abs_diff=mixed["parity_max_abs_diff"],
    )
    tel.close()
    path = out_dir / f"fleet_{tag}_{_stamp()}.json"
    path.write_text(json.dumps(artifact, indent=2) + "\n")
    artifact["artifact_path"] = str(path)
    _LOG.info("fleet evidence artifact: %s", path)
    return artifact


# ---------------------------------------------------------------------------
# FedBuff staleness-exponent ablation (ISSUE 16 satellite)
# ---------------------------------------------------------------------------


def _fedbuff_sim(
    alpha: float,
    num_clients: int = 40,
    buffer_k: int = 8,
    num_aggregations: int = 30,
    staleness_window: int = 10,
    arrival_rate: float = 200.0,
    delay_sigma: float = 1.0,
    adapter_rank: int = 8,
    local_steps: int = 8,
    learning_rate: float = 0.5,
    seed: int = 7,
) -> dict[str, Any]:
    """One asynchronous FedBuff federation at staleness exponent ``alpha``:
    an event-driven replay of the r15 delay distribution (poisson arrival
    gaps x lognormal service times, so slow clients submit STALE deltas)
    through the real ``DeviceIngestBuffer.drain_fedbuff``.  Everything except
    ``alpha`` — the delay schedule, the cohort, the data, the init — is
    deterministic in ``seed``, so the α axis is the only thing that moves."""
    import jax
    import jax.numpy as jnp

    from nanofed_tpu.adapters import AdapterSpec, init_adapters, make_adapter_apply
    from nanofed_tpu.data import federate, pack_eval, synthetic_classification
    from nanofed_tpu.ingest.buffer import DeviceIngestBuffer
    from nanofed_tpu.models import get_model

    in_features, hidden, num_classes = 64, 128, 10
    model = get_model(
        "mlp", in_features=in_features, hidden=hidden, num_classes=num_classes
    )
    base = jax.device_get(model.init(jax.random.key(seed)))
    spec = AdapterSpec(rank=adapter_rank)
    train = synthetic_classification(
        64 * num_clients, num_classes=num_classes, shape=(in_features,),
        seed=seed,
    )
    test = synthetic_classification(
        1024, num_classes=num_classes, shape=(in_features,), seed=seed + 1
    )
    data = federate(train, num_clients=num_clients, batch_size=32, seed=seed)
    eval_pack = pack_eval(test, batch_size=256)

    apply = make_adapter_apply(model.apply, spec, base)

    def loss_fn(ad, x, y, m):
        logp = apply(ad, x)
        nll = -jnp.take_along_axis(logp, y[:, None], axis=-1)[:, 0]
        return (nll * m).sum() / jnp.maximum(m.sum(), 1.0)

    @jax.jit
    def fit(ad, x, y, m):
        def step(a, _):
            g = jax.grad(loss_fn)(a, x, y, m)
            return jax.tree.map(lambda p, q: p - learning_rate * q, a, g), None

        out, _ = jax.lax.scan(step, ad, None, length=local_steps)
        return out

    @jax.jit
    def eval_loss(ad):
        return loss_fn(
            ad,
            jnp.asarray(eval_pack.x),
            jnp.asarray(eval_pack.y),
            jnp.asarray(eval_pack.mask),
        )

    from nanofed_tpu.utils.trees import tree_ravel

    adapters0 = init_adapters(spec, base, rng=seed)
    buf = DeviceIngestBuffer(adapters0, capacity=4 * buffer_k, warm_batch=8)
    flat0 = np.asarray(tree_ravel(adapters0)[0], np.float32)

    # published adapter trees by version (the staleness window's live set)
    published = {0: jax.device_get(adapters0)}
    published_flat = {0: flat0}
    version = 0
    rng = np.random.default_rng(seed)
    # event queue: (completion_time, tiebreak, client, version_fetched)
    events: list[tuple[float, int, int, int]] = []
    tiebreak = 0
    now = 0.0
    for c in range(num_clients):
        now += rng.exponential(1.0 / arrival_rate)
        service = rng.lognormal(mean=0.0, sigma=delay_sigma) / arrival_rate
        heapq.heappush(events, (now + service, tiebreak, c, version))
        tiebreak += 1

    losses: list[float] = []
    staleness_all: list[int] = []
    skipped_total = 0
    while len(losses) < num_aggregations and events:
        t, _, client, v_fetched = heapq.heappop(events)
        if v_fetched in published:
            start = published[v_fetched]
            trained = jax.device_get(fit(
                start,
                jnp.asarray(data.x[client]),
                jnp.asarray(data.y[client]),
                jnp.asarray(data.mask[client]),
            ))
            delta = np.concatenate([
                (np.asarray(b, np.float32) - np.asarray(a, np.float32)).ravel()
                for a, b in zip(
                    jax.tree.leaves(start), jax.tree.leaves(trained)
                )
            ])
            buf.offer(
                delta, client_id=f"c{client}", round_number=v_fetched,
                weight=float(data.mask[client].sum()),
            )
        # the client immediately fetches the CURRENT version and goes again
        service = rng.lognormal(mean=0.0, sigma=delay_sigma) / arrival_rate
        gap = rng.exponential(1.0 / arrival_rate)
        heapq.heappush(events, (t + gap + service, tiebreak, client, version))
        tiebreak += 1

        if buf.fill >= buffer_k:
            window = range(max(0, version - staleness_window), version + 1)
            try:
                out, live, stats = buf.drain_fedbuff(
                    buffer_k, version, window,
                    published_flat[version],
                    staleness_exponent=alpha,
                )
            except ValueError:
                skipped_total += buffer_k
                continue
            staleness_all.extend(stats["staleness"])
            skipped_total += stats["num_skipped_out_of_window"]
            version += 1
            new_flat = np.asarray(out, np.float32)
            published_flat[version] = new_flat
            published[version] = jax.device_get(buf.unravel(new_flat))
            floor = version - staleness_window
            for old in [v for v in published if v < floor]:
                del published[old]
                del published_flat[old]
            losses.append(round(float(eval_loss(published[version])), 4))

    # a divergent run's losses go non-finite — sanitize to None so the
    # artifact stays strict JSON (NaN is not JSON)
    final = losses[-1] if losses else float("nan")
    diverged = bool(
        not losses or not np.isfinite(final) or final > 3 * losses[0]
    )
    fin = lambda x: round(float(x), 4) if np.isfinite(x) else None  # noqa: E731
    return {
        "staleness_exponent": alpha,
        "aggregations": len(losses),
        "final_loss": fin(final) if losses else None,
        "min_loss": fin(min(losses)) if losses else None,
        "losses": [fin(x) for x in losses],
        "mean_staleness": (
            round(float(np.mean(staleness_all)), 3) if staleness_all else 0.0
        ),
        "max_staleness": int(max(staleness_all)) if staleness_all else 0,
        "skipped_out_of_window": int(skipped_total),
        "diverged": diverged,
    }


def generate_fedbuff_staleness_ablation(
    out_dir: str | Path = "runs",
    tag: str = "r16",
    alphas: tuple[float, ...] = (0.0, 0.25, 0.5, 1.0, 2.0),
    seed: int = 7,
    **sim_kwargs: Any,
) -> dict[str, Any]:
    """Sweep the FedBuff staleness exponent over the r15 scenario's delay
    distribution (see :func:`_fedbuff_sim`) and write
    ``<out_dir>/fedbuff_staleness_<tag>.json`` ranking the exponents by final
    loss.  The r15 artifact fixed α=0.5 by citation; this measures the axis."""
    import jax

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    sweep: dict[str, Any] = {}
    for alpha in alphas:
        _LOG.info("fedbuff staleness ablation: alpha=%s ...", alpha)
        sweep[str(alpha)] = _fedbuff_sim(alpha, seed=seed, **sim_kwargs)
    ranked = sorted(
        (rec["final_loss"], a) for a, rec in sweep.items()
        if not rec["diverged"]
    )
    best_alpha = ranked[0][1] if ranked else None
    exercised = all(rec["mean_staleness"] > 0 for rec in sweep.values())
    spread = (
        round(max(r[0] for r in ranked) - min(r[0] for r in ranked), 4)
        if len(ranked) >= 2 else None
    )
    reached = bool(
        len(sweep) == len(alphas)
        and exercised
        and best_alpha is not None
        and all(rec["aggregations"] > 0 for rec in sweep.values())
    )
    artifact = {
        "record_type": "fedbuff_staleness",
        "tag": tag,
        "created": _stamp(),
        "env": {
            "jax": jax.__version__,
            "backend": jax.default_backend(),
            "device_count": jax.device_count(),
        },
        "scenario": {
            "reference": "runs/fedbuff_adapter_r15_*.json",
            "arrival": "poisson",
            "delay": "lognormal service times (sigma=1.0) — slow clients "
                     "submit stale deltas",
            "aggregator": "DeviceIngestBuffer.drain_fedbuff "
                          "(lr·(1+s)^-α/K, Nguyen et al. 2022)",
            "basis": (
                "event-driven replay: identical seeds, delays, cohort, and "
                "data across every α — the exponent is the only moving part"
            ),
        },
        "sweep": sweep,
        "best_alpha": best_alpha,
        "final_loss_spread": spread,
        "reached": reached,
        "conclusion": (
            "staleness-exponent ablation over the r15 FedBuff scenario: "
            + ", ".join(
                f"α={a} -> "
                + ("DIVERGED" if rec["diverged"] else f"{rec['final_loss']}")
                for a, rec in sweep.items()
            )
            + (
                f"; best α={best_alpha}"
                f" (mean staleness "
                f"{sweep[str(alphas[0])]['mean_staleness']}, "
                f"spread {spread})"
                if best_alpha is not None else "; every exponent diverged"
            )
        ),
    }
    path = out_dir / f"fedbuff_staleness_{tag}.json"
    path.write_text(json.dumps(artifact, indent=2) + "\n")
    artifact["artifact_path"] = str(path)
    _LOG.info("fedbuff staleness artifact: %s", path)
    return artifact


def main() -> int:
    fleet = generate_fleet_evidence()
    stale = generate_fedbuff_staleness_ablation()
    print(json.dumps({
        "fleet": {
            k: fleet[k] for k in ("reached", "conclusion", "artifact_path")
        },
        "fedbuff_staleness": {
            k: stale[k] for k in ("reached", "conclusion", "artifact_path")
        },
    }, indent=2))
    return 0 if (fleet["reached"] and stale["reached"]) else 1


if __name__ == "__main__":
    raise SystemExit(main())
