"""Heterogeneous-rank adapter aggregation: rank-4 phones and rank-32 silos in
one global update.

LoRA factors of DIFFERENT ranks cannot be averaged factor-wise — a mean of
``A`` matrices followed by a product is not the mean of the products
(``mean(A_i @ B_i) != mean(A_i) @ mean(B_i)``), and the factors do not even
share shapes across tiers.  What IS well-defined across ranks is the DENSE
delta each client's adapters represent: ``scaling * A @ B`` is base-shaped for
every rank (``adapters.lora.adapter_delta``).  So the fleet's global update
lives in dense-delta space, and this module provides two routes into it:

* :func:`aggregate_dense` — the REFERENCE route: weighted mean of per-client
  dense deltas.  Obviously correct, materializes one ``[d_in, d_out]``
  temporary per client per leaf.
* :func:`aggregate_padded` — the fast path: zero-pad every client's factors
  into a common max-rank bucket (``A [d_in, r] -> [d_in, R]``, ``B [r, d_out]
  -> [R, d_out]``), fold the client's ``weight * scaling / total_weight`` into
  its ``A``, and contract the whole cohort in ONE stacked einsum per leaf
  (``'cir,cro->io'``).  Padded rows/columns are zero, so the result is EXACTLY
  the dense route (to float tolerance — the parity tests assert it); the
  cohort-sized temporaries are factor-shaped ``[C, d_in, R] / [C, R, d_out]``
  instead of C dense ``[d_in, d_out]`` products, which is the in-device win
  whenever ``C * R << d_out`` (see docs/fleet.md for the crossover).

Redistribution closes the loop: :func:`project_to_rank` compresses the
aggregated dense delta back onto one tier's rank via truncated SVD (the
rank-r Frobenius-optimal factorization, Eckart–Young), and
:func:`redistribute` does it for every tier of a profile.  Low-rank tiers
receive the best rank-r view of the fleet's update; the SVD tail they drop is
reported by :func:`projection_error` so the evidence can show what
heterogeneity costs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence

import jax.numpy as jnp
import numpy as np

from nanofed_tpu.adapters.lora import AdapterSpec, adapter_delta, target_paths
from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.core.types import Params

__all__ = [
    "AdapterUpdate",
    "aggregate_dense",
    "aggregate_padded",
    "pad_adapters_to_rank",
    "project_to_rank",
    "projection_error",
    "redistribute",
    "revive_adapters",
]


@dataclass(frozen=True)
class AdapterUpdate:
    """One client's contribution to a heterogeneous round: its tier's spec,
    its trained adapter tree, and its FedAvg weight (sample count)."""

    spec: AdapterSpec
    adapters: Params
    weight: float = 1.0
    tier: str = ""

    def __post_init__(self) -> None:
        if self.weight <= 0:
            raise NanoFedError(f"update weight must be > 0, got {self.weight}")


def _named_leaves(tree: Params) -> list[tuple[str, Any]]:
    from nanofed_tpu.persistence.serialization import tree_flatten_with_names

    return tree_flatten_with_names(tree)[0]


def _unflatten(arrays: dict[str, Any], source: str) -> Params:
    from nanofed_tpu.persistence.serialization import unflatten_from_arrays

    return unflatten_from_arrays(arrays, like=None, source=source)


def _check_compatible(updates: Sequence[AdapterUpdate]) -> None:
    if not updates:
        raise NanoFedError("cannot aggregate an empty update set")
    t0, m0 = updates[0].spec.targets, updates[0].spec.min_dim
    for u in updates[1:]:
        if u.spec.targets != t0 or u.spec.min_dim != m0:
            raise NanoFedError(
                "heterogeneous-rank aggregation requires every tier to target "
                f"the same leaves: {u.spec.targets}/{u.spec.min_dim} vs "
                f"{t0}/{m0} — ranks may differ, target sets may not"
            )


def aggregate_dense(
    updates: Sequence[AdapterUpdate], base_like: Params
) -> Params:
    """REFERENCE route: the weighted mean of per-client dense deltas,
    ``sum_i (w_i / sum w) * scaling_i * (A_i @ B_i)`` per targeted leaf.
    Base-shaped output; works even when tiers target different leaf sets."""
    if not updates:
        raise NanoFedError("cannot aggregate an empty update set")
    total_w = float(sum(u.weight for u in updates))
    acc: dict[str, Any] = {
        name: jnp.zeros(np.shape(leaf), jnp.float32)
        for name, leaf in _named_leaves(base_like)
    }
    for u in updates:
        delta = adapter_delta(u.spec, base_like, u.adapters)
        coef = u.weight / total_w
        for name, leaf in _named_leaves(delta):
            acc[name] = acc[name] + coef * jnp.asarray(leaf)
    return _unflatten(acc, "dense fleet delta")


def aggregate_padded(
    updates: Sequence[AdapterUpdate],
    base_like: Params,
    pad_rank: int | None = None,
) -> Params:
    """Fast path: pad every client's factors into a common ``pad_rank``
    bucket (default: the cohort max rank), fold ``w_i * scaling_i / sum w``
    into ``A_i``, and contract each leaf's whole cohort in one stacked einsum.
    Exactly the dense route — padded rows/columns are zero and contribute
    nothing to the contraction (the parity tests hold this to float32
    tolerance).  Requires a shared target set across tiers."""
    _check_compatible(updates)
    ranks = [u.spec.rank for u in updates]
    bucket = max(ranks) if pad_rank is None else int(pad_rank)
    if bucket < max(ranks):
        raise NanoFedError(
            f"pad_rank {bucket} smaller than the cohort's max rank {max(ranks)}"
        )
    total_w = float(sum(u.weight for u in updates))
    paths = set(target_paths(updates[0].spec, base_like))

    named_per_update = [dict(_named_leaves(u.adapters)) for u in updates]
    arrays: dict[str, Any] = {}
    for name, leaf in _named_leaves(base_like):
        if name not in paths:
            arrays[name] = jnp.zeros(np.shape(leaf), jnp.float32)
            continue
        d_in, d_out = (int(s) for s in np.shape(leaf))
        a_stack = np.zeros((len(updates), d_in, bucket), np.float32)
        b_stack = np.zeros((len(updates), bucket, d_out), np.float32)
        for c, (u, named_ad) in enumerate(zip(updates, named_per_update)):
            r = u.spec.rank
            coef = u.weight * u.spec.scaling / total_w
            a_stack[c, :, :r] = coef * np.asarray(named_ad[f"{name}/A"])
            b_stack[c, :r, :] = np.asarray(named_ad[f"{name}/B"])
        arrays[name] = jnp.einsum(
            "cir,cro->io", jnp.asarray(a_stack), jnp.asarray(b_stack)
        )
    return _unflatten(arrays, "padded fleet delta")


def pad_adapters_to_rank(
    adapters: Params, from_spec: AdapterSpec, to_spec: AdapterSpec
) -> Params:
    """Re-express a low-rank tier's adapters at a higher rank WITHOUT changing
    the delta they represent: zero-pad ``A``'s columns and ``B``'s rows to
    ``to_spec.rank``, and rescale ``A`` by ``from_spec.scaling /
    to_spec.scaling`` so ``adapter_delta(to_spec, base, padded) ==
    adapter_delta(from_spec, base, original)`` exactly.  This is how a phone's
    rank-4 update enters a rank-32 bucket as a first-class citizen."""
    if to_spec.rank < from_spec.rank:
        raise NanoFedError(
            f"cannot pad rank {from_spec.rank} down to {to_spec.rank} — "
            "use project_to_rank for compression"
        )
    if (from_spec.targets, from_spec.min_dim) != (to_spec.targets, to_spec.min_dim):
        raise NanoFedError(
            "pad_adapters_to_rank requires matching target sets between specs"
        )
    rescale = from_spec.scaling / to_spec.scaling
    grow = to_spec.rank - from_spec.rank
    arrays: dict[str, Any] = {}
    for name, leaf in _named_leaves(adapters):
        x = np.asarray(leaf, np.float32)
        if name.endswith("/A"):
            arrays[name] = np.pad(rescale * x, ((0, 0), (0, grow)))
        elif name.endswith("/B"):
            arrays[name] = np.pad(x, ((0, grow), (0, 0)))
        else:  # pragma: no cover - adapter trees only hold /A and /B leaves
            raise NanoFedError(f"unexpected adapter leaf {name!r}")
    return _unflatten(arrays, "padded adapters")


def project_to_rank(
    dense_delta: Params, spec: AdapterSpec, base_like: Params
) -> Params:
    """Compress a base-shaped dense delta onto ``spec``'s rank: per targeted
    leaf, the truncated SVD ``U_r S_r V_r^T`` (the Frobenius-optimal rank-r
    approximation), split symmetrically as ``A = U_r sqrt(S_r)``, ``B =
    sqrt(S_r) V_r^T / scaling`` so ``scaling * A @ B`` reproduces the
    truncation.  Leaves whose true rank is below ``spec.rank`` pad with zeros
    (exact representation).  This is the redistribution direction: the fleet's
    aggregated update flowing back DOWN to a low-rank tier."""
    paths = target_paths(spec, base_like)
    named = dict(_named_leaves(dense_delta))
    arrays: dict[str, Any] = {}
    for name in paths:
        m = np.asarray(named[name], np.float64)
        u, s, vt = np.linalg.svd(m, full_matrices=False)
        r = min(spec.rank, s.shape[0])
        root = np.sqrt(s[:r])
        a = (u[:, :r] * root).astype(np.float32)
        b = ((root[:, None] * vt[:r]) / spec.scaling).astype(np.float32)
        if r < spec.rank:
            a = np.pad(a, ((0, 0), (0, spec.rank - r)))
            b = np.pad(b, ((0, spec.rank - r), (0, 0)))
        arrays[f"{name}/A"] = a
        arrays[f"{name}/B"] = b
    return _unflatten(arrays, "projected adapters")


def projection_error(
    dense_delta: Params, spec: AdapterSpec, base_like: Params
) -> dict[str, float]:
    """Relative Frobenius error per targeted leaf of the rank-``spec.rank``
    truncation (what :func:`project_to_rank` drops), plus an ``__overall__``
    aggregate — the number docs/fleet.md and the evidence artifact report as
    the cost of redistributing to a thin tier."""
    named = dict(_named_leaves(dense_delta))
    out: dict[str, float] = {}
    num = den = 0.0
    for name in target_paths(spec, base_like):
        m = np.asarray(named[name], np.float64)
        s = np.linalg.svd(m, compute_uv=False)
        tail = float(np.sum(s[spec.rank:] ** 2))
        total = float(np.sum(s**2))
        out[name] = float(np.sqrt(tail / total)) if total > 0 else 0.0
        num += tail
        den += total
    out["__overall__"] = float(np.sqrt(num / den)) if den > 0 else 0.0
    return out


def revive_adapters(
    adapters: Params, spec: AdapterSpec, seed: int = 0
) -> Params:
    """Give DEAD adapter directions gradient flow without changing the delta
    they represent.  A direction ``j`` is dead when ``A[:, j]`` and ``B[j, :]``
    are both zero — true of every direction a truncated SVD zero-padded, and
    of EVERY direction at round 0 (the global delta is zero) — and LoRA
    gradients through a dead pair are identically zero, so a client fetching
    such a tree could never train it.  The fix is the LoRA identity-init move:
    redraw those ``A`` columns as ``U(-s, s) / sqrt(rank)`` while ``B``'s rows
    stay zero — ``scaling * A @ B`` is untouched (the zero ``B`` rows
    annihilate the new columns), but dL/dB is now nonzero.  Deterministic in
    ``seed`` so server replicas publish identical views."""
    host = np.random.default_rng(int(seed))
    s = spec.init_scale / np.sqrt(spec.rank)
    arrays: dict[str, Any] = {}
    named = dict(_named_leaves(adapters))
    for name, leaf in named.items():
        if not name.endswith("/A"):
            arrays[name] = np.asarray(leaf, np.float32)
            continue
        a = np.asarray(leaf, np.float32).copy()
        b = np.asarray(named[name[:-2] + "/B"], np.float32)
        dead = (np.abs(a).sum(axis=0) == 0) & (np.abs(b).sum(axis=1) == 0)
        if dead.any():
            fresh = host.uniform(
                -s, s, size=(a.shape[0], int(dead.sum()))
            ).astype(np.float32)
            a[:, dead] = fresh
        arrays[name] = a
    return _unflatten(arrays, "revived adapters")


def redistribute(
    dense_delta: Params,
    profile: Any,
    base_like: Params,
    specs: dict[str, AdapterSpec] | None = None,
) -> dict[str, Params]:
    """Project one aggregated dense delta onto EVERY tier of ``profile``:
    ``{tier_name: adapter_tree}`` at each tier's rank, via
    :func:`project_to_rank`.  ``specs`` defaults to ``profile.specs()`` (the
    common-alpha convention); pass explicit ones to match a running fleet's
    spec set."""
    tier_specs = specs if specs is not None else profile.specs()
    return {
        name: project_to_rank(dense_delta, tier_specs[name], base_like)
        for name in profile.tier_names()
    }
