"""Heterogeneous fleet federation: tiered devices, tiered ranks, tiered wires.

``fleet.profile`` declares the tier mix (:class:`DeviceTier` /
:class:`FleetProfile`); ``fleet.aggregate`` aggregates adapters of DIFFERENT
ranks into one dense global update (reference dense route, exactly-equal
padded fast path, truncated-SVD redistribution); ``fleet.wire`` owns the
per-tier codec paths and the topk8 error-feedback client state;
``fleet.gateway`` is the server edge (per-tier published views + submit
decode into the ingest buffer); ``fleet.swarm`` drives per-tier sub-swarms on
one VirtualClock; ``fleet.tuning`` sweeps the mix; ``fleet.evidence``
produces the committed runs/ artifacts.  See docs/fleet.md.
"""

from nanofed_tpu.fleet.aggregate import (
    AdapterUpdate,
    aggregate_dense,
    aggregate_padded,
    pad_adapters_to_rank,
    project_to_rank,
    projection_error,
    redistribute,
    revive_adapters,
)
from nanofed_tpu.fleet.gateway import FleetGateway, TierView
from nanofed_tpu.fleet.profile import (
    CODEC_ENCODINGS,
    DeviceTier,
    FleetProfile,
    reference_fleet,
)
from nanofed_tpu.fleet.swarm import (
    fleet_swarm_digest,
    run_fleet_swarm,
    tier_swarm_configs,
)
from nanofed_tpu.fleet.tuning import (
    FleetMixCandidate,
    FleetMixOutcome,
    mix_candidates,
    profile_with_ranks,
    sweep_fleet_mix,
)
from nanofed_tpu.fleet.wire import TierClientState, decode_tier_submit

__all__ = [
    "AdapterUpdate",
    "CODEC_ENCODINGS",
    "DeviceTier",
    "FleetGateway",
    "FleetMixCandidate",
    "FleetMixOutcome",
    "FleetProfile",
    "TierClientState",
    "TierView",
    "aggregate_dense",
    "aggregate_padded",
    "decode_tier_submit",
    "fleet_swarm_digest",
    "mix_candidates",
    "pad_adapters_to_rank",
    "profile_with_ranks",
    "project_to_rank",
    "projection_error",
    "redistribute",
    "reference_fleet",
    "revive_adapters",
    "run_fleet_swarm",
    "sweep_fleet_mix",
    "tier_swarm_configs",
]
