"""Fleet profiles: the tier mix as a first-class dimension of a federation run.

Every cohort this framework federated before this module was homogeneous — one
adapter rank, one codec, one batch size, one arrival process.  Production
cross-device populations are not: phones, edge boxes, and datacenter silos span
orders of magnitude in compute, bandwidth, and availability, and the
communication survey (arXiv:2405.20431) names exactly this device/payload
heterogeneity as cross-device FL's binding constraint.  FL_PyTorch
(arXiv:2202.03099) treats client-arrival simulation as a first-class knob for
the same reason.

A :class:`DeviceTier` declares what ONE device class trains and ships:

* ``adapter_rank`` — the LoRA rank its compute budget affords (a phone trains
  rank 4, a silo rank 32; see ``nanofed_tpu.adapters``),
* ``codec`` — the wire encoding its bandwidth affords (``topk8`` for the thin
  wire, ``q8`` for edge, full ``f32`` for silos; ``communication.codec``),
* ``batch_size`` and the ``arrival``/``arrival_rate``/``availability`` process
  its duty cycle affords (the ``loadgen`` arrival machinery).

A :class:`FleetProfile` is a NAMED mix of tiers with per-tier population
fractions, validated at construction — fractions must sum to 1, names must be
unique, ranks positive — so every consumer (the fleet aggregator, the swarm,
the autotuner, the scheduler) reads one vetted object instead of re-validating
ad-hoc dicts.  ``population_split`` turns a fraction mix into exact client
counts deterministically (largest-remainder), so two processes splitting the
same population always agree.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping

import numpy as np

from nanofed_tpu.core.exceptions import NanoFedError

__all__ = [
    "CODEC_ENCODINGS",
    "DeviceTier",
    "FleetProfile",
    "reference_fleet",
]

#: Tier codec name -> X-NanoFed-Encoding wire value (``communication.codec``).
#: ``f32`` ships the full federated tree as plain npz; ``q8``/``topk8`` ship
#: the factor-space delta through the quantized codecs.
CODEC_ENCODINGS: dict[str, str] = {
    "f32": "npz",
    "q8": "q8-delta",
    "topk8": "topk8-delta",
}


@dataclass(frozen=True)
class DeviceTier:
    """One device class's training/wire/arrival shape (see module doc).

    ``fraction`` is this tier's share of the fleet population (all tiers in a
    profile sum to 1).  ``availability`` is the per-round participation
    probability — a phone tier at 0.3 contributes ~30% of its population per
    round, a silo at 1.0 shows up every round.  ``topk_fraction`` only applies
    to the ``topk8`` codec (kept coordinates per leaf).  ``weight_skew`` is
    the lognormal sigma over reported sample counts (the loadgen knob)."""

    name: str
    fraction: float
    adapter_rank: int = 8
    codec: str = "q8"
    batch_size: int = 16
    arrival: str = "poisson"
    arrival_rate: float = 100.0
    availability: float = 1.0
    local_steps: int = 1
    weight_skew: float = 0.0
    topk_fraction: float = 0.05

    def __post_init__(self) -> None:
        if not self.name or "/" in self.name:
            raise NanoFedError(f"tier name must be non-empty, '/'-free: {self.name!r}")
        if not 0.0 < self.fraction <= 1.0:
            raise NanoFedError(
                f"tier {self.name!r}: fraction must be in (0, 1], got {self.fraction}"
            )
        if self.adapter_rank < 1:
            raise NanoFedError(
                f"tier {self.name!r}: adapter_rank must be >= 1, got {self.adapter_rank}"
            )
        if self.codec not in CODEC_ENCODINGS:
            raise NanoFedError(
                f"tier {self.name!r}: unknown codec {self.codec!r} "
                f"(one of {sorted(CODEC_ENCODINGS)})"
            )
        if self.batch_size < 1:
            raise NanoFedError(f"tier {self.name!r}: batch_size must be >= 1")
        if self.arrival not in ("poisson", "uniform", "burst"):
            raise NanoFedError(
                f"tier {self.name!r}: unknown arrival process {self.arrival!r}"
            )
        if self.arrival_rate <= 0:
            raise NanoFedError(f"tier {self.name!r}: arrival_rate must be > 0")
        if not 0.0 < self.availability <= 1.0:
            raise NanoFedError(
                f"tier {self.name!r}: availability must be in (0, 1], "
                f"got {self.availability}"
            )
        if self.local_steps < 1:
            raise NanoFedError(f"tier {self.name!r}: local_steps must be >= 1")
        if not 0.0 < self.topk_fraction <= 1.0:
            raise NanoFedError(
                f"tier {self.name!r}: topk_fraction must be in (0, 1]"
            )

    @property
    def encoding(self) -> str:
        """The X-NanoFed-Encoding wire value this tier's submits carry."""
        return CODEC_ENCODINGS[self.codec]

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "fraction": self.fraction,
            "adapter_rank": self.adapter_rank,
            "codec": self.codec,
            "batch_size": self.batch_size,
            "arrival": self.arrival,
            "arrival_rate": self.arrival_rate,
            "availability": self.availability,
            "local_steps": self.local_steps,
            "weight_skew": self.weight_skew,
            "topk_fraction": self.topk_fraction,
        }

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "DeviceTier":
        return cls(**{k: d[k] for k in cls.__dataclass_fields__ if k in d})


@dataclass(frozen=True)
class FleetProfile:
    """A named tier mix, validated at construction (see module doc)."""

    name: str
    tiers: tuple[DeviceTier, ...] = field(default_factory=tuple)

    def __post_init__(self) -> None:
        if not self.name:
            raise NanoFedError("fleet profile needs a name")
        if not self.tiers:
            raise NanoFedError(f"fleet profile {self.name!r} needs at least one tier")
        names = [t.name for t in self.tiers]
        if len(set(names)) != len(names):
            raise NanoFedError(
                f"fleet profile {self.name!r}: duplicate tier names in {names}"
            )
        total = sum(t.fraction for t in self.tiers)
        if abs(total - 1.0) > 1e-6:
            raise NanoFedError(
                f"fleet profile {self.name!r}: tier fractions sum to {total:.6f}, "
                "must sum to 1"
            )

    # -- lookups -----------------------------------------------------------

    def tier(self, name: str) -> DeviceTier:
        for t in self.tiers:
            if t.name == name:
                return t
        raise NanoFedError(
            f"fleet profile {self.name!r} has no tier {name!r} "
            f"(tiers: {[t.name for t in self.tiers]})"
        )

    def tier_names(self) -> list[str]:
        return [t.name for t in self.tiers]

    @property
    def max_rank(self) -> int:
        """The largest tier rank — what sizes the padded aggregation buckets
        and the scheduler's device-memory footprint."""
        return max(t.adapter_rank for t in self.tiers)

    @property
    def max_rank_tier(self) -> DeviceTier:
        return max(self.tiers, key=lambda t: t.adapter_rank)

    # -- derived shapes ----------------------------------------------------

    def population_split(self, num_clients: int) -> dict[str, int]:
        """Exact per-tier client counts for a population of ``num_clients``:
        largest-remainder apportionment (deterministic, order-stable), every
        tier gets at least one client when the population allows."""
        if num_clients < len(self.tiers):
            raise NanoFedError(
                f"population {num_clients} smaller than the tier count "
                f"{len(self.tiers)} of profile {self.name!r}"
            )
        exact = {t.name: num_clients * t.fraction for t in self.tiers}
        counts = {name: int(np.floor(v)) for name, v in exact.items()}
        # Give starved tiers their guaranteed seat before remainder ordering.
        for name in counts:
            if counts[name] == 0:
                counts[name] = 1
        leftover = num_clients - sum(counts.values())
        remainders = sorted(
            counts, key=lambda n: (-(exact[n] - int(np.floor(exact[n]))), n)
        )
        i = 0
        while leftover != 0:
            name = remainders[i % len(remainders)]
            if leftover > 0:
                counts[name] += 1
                leftover -= 1
            elif counts[name] > 1:  # never starve a tier back to zero
                counts[name] -= 1
                leftover += 1
            i += 1
        return counts

    def specs(self, **spec_kwargs: Any) -> dict[str, Any]:
        """Per-tier :class:`~nanofed_tpu.adapters.AdapterSpec` at each tier's
        rank (extra kwargs — targets, alpha, min_dim — shared across tiers).
        ``alpha`` defaults to the profile's max rank so every tier's effective
        delta scale ``alpha/rank`` is computed on a COMMON alpha: padding a
        tier's factors into the max-rank bucket then needs only a scalar
        rescale (see ``fleet.aggregate.pad_adapters_to_rank``)."""
        from nanofed_tpu.adapters import AdapterSpec

        spec_kwargs.setdefault("alpha", float(self.max_rank))
        return {
            t.name: AdapterSpec(rank=t.adapter_rank, **spec_kwargs)
            for t in self.tiers
        }

    def wire_bytes_per_round(
        self, base_like: Any, num_clients: int
    ) -> dict[str, Any]:
        """ANALYTIC per-round client->server wire bytes by tier: adapter
        parameter count at the tier's rank x the codec's bytes/parameter
        (f32: 4, q8: ~1 + scale overhead, topk8: ~5 x kept fraction — int8
        value + uint32 index per kept coordinate) x expected participants.
        The sizing guide only — evidence artifacts measure the real payloads
        through the codecs (``fleet.evidence``)."""
        from nanofed_tpu.adapters import AdapterSpec, adapter_param_count

        split = self.population_split(num_clients)
        out: dict[str, Any] = {}
        total = 0.0
        for t in self.tiers:
            counts = adapter_param_count(AdapterSpec(rank=t.adapter_rank), base_like)
            p = counts["adapter_params"]
            per_update = {
                "f32": 4.0 * p,
                "q8": 1.0 * p,
                "topk8": 5.0 * t.topk_fraction * p,
            }[t.codec]
            participants = split[t.name] * t.availability
            tier_total = per_update * participants
            out[t.name] = {
                "clients": split[t.name],
                "expected_participants_per_round": round(participants, 2),
                "adapter_params": p,
                "bytes_per_update": int(per_update),
                "bytes_per_round": int(tier_total),
            }
            total += tier_total
        out["total_bytes_per_round"] = int(total)
        out["basis"] = (
            "analytic pre-deflate sizing: params(rank) x codec bytes/param x "
            "expected participants; measured payloads live in fleet.evidence"
        )
        return out

    def to_dict(self) -> dict[str, Any]:
        return {"name": self.name, "tiers": [t.to_dict() for t in self.tiers]}

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "FleetProfile":
        return cls(
            name=str(d["name"]),
            tiers=tuple(DeviceTier.from_dict(t) for t in d["tiers"]),
        )


def reference_fleet(
    name: str = "phone_edge_silo",
    phone_rank: int = 4,
    edge_rank: int = 8,
    silo_rank: int = 32,
) -> FleetProfile:
    """The canonical 3-tier mix the evidence artifacts and smoke tests use:
    a thin-wire phone majority (topk8, low availability, bursty poisson), an
    edge-box middle (q8), and a small always-on datacenter-silo tail (full
    f32).  Fractions follow the cross-device shape the communication survey
    describes: population mass at the thin edge, byte mass at the silos."""
    return FleetProfile(
        name=name,
        tiers=(
            DeviceTier(
                name="phone", fraction=0.70, adapter_rank=phone_rank,
                codec="topk8", batch_size=8, arrival="poisson",
                arrival_rate=200.0, availability=0.4, weight_skew=1.0,
            ),
            DeviceTier(
                name="edge", fraction=0.25, adapter_rank=edge_rank,
                codec="q8", batch_size=16, arrival="uniform",
                arrival_rate=60.0, availability=0.8, weight_skew=0.5,
            ),
            DeviceTier(
                name="silo", fraction=0.05, adapter_rank=silo_rank,
                codec="f32", batch_size=64, arrival="burst",
                arrival_rate=10.0, availability=1.0,
            ),
        ),
    )
