"""The compressed-aggregation epilogues as catalogued, cost-profiled programs.

The q8/topk serving path aggregates in two separate programs today: dequantize
the int8 client stack to a materialized ``[C, P]`` float32 array, then
weighted-reduce it onto the published base.  ``ops.quantize.
dequant_accumulate_flat`` fuses the two (the per-client scale folds into the
reduce coefficients, so the int8 stack is read once and the float intermediate
never exists); ``ops.reduce.masked_weighted_mean_flat`` does the same for the
validated path's sanitize-then-reduce epilogue.

This module registers BOTH forms of each epilogue in a
:class:`~nanofed_tpu.observability.profiling.ProgramCatalog` and profiles them,
so the bytes-accessed drop is a measured row in the tuner's cost table rather
than a claim.  Everything is lowered with abstract arguments — no data, no
execution, one small XLA compile per program.

Basis honesty: on CPU the fused kernels run under the Pallas INTERPRETER, whose
cost accounting materializes every VMEM block copy.  The q8 fusion's win (int8
read once vs int8-read + float-write + float-read) is large enough to survive
that overhead, so the CPU table still shows a real reduction; the validated
fusion's win (one read vs read+write+read of the SAME dtype) is smaller than
interpreter overhead, so its reduction only appears on TPU where the kernel is
real — the returned record labels each comparison with this basis.
"""

from __future__ import annotations

from typing import Any

from nanofed_tpu.observability.profiling import ProgramCatalog

__all__ = ["profile_aggregation_epilogues", "register_epilogue_programs"]

#: Default stacked-client count the epilogues are profiled at — the ingest
#: pipeline's default drain batch (``IngestConfig.drain_batch``).
DEFAULT_EPILOGUE_CLIENTS = 64


def register_epilogue_programs(
    catalog: ProgramCatalog, flat_size: int, clients: int = DEFAULT_EPILOGUE_CLIENTS
) -> None:
    """Register the fused epilogues next to their unfused counterparts.

    Unfused entries mirror the CURRENT serving path as the separate programs it
    actually runs (``q8_epilogue_dequant`` then ``q8_epilogue_reduce``;
    ``validated_epilogue_sanitize`` then ``validated_epilogue_reduce``) — their
    bytes-accessed SUM is the honest baseline a single fused program competes
    against.  Registration is free; ``catalog.profile()`` pays the compiles.
    """
    import jax
    import jax.numpy as jnp

    from nanofed_tpu.ops import dequant_accumulate_flat, masked_weighted_mean_flat

    c, p = int(clients), int(flat_size)
    q_sds = jax.ShapeDtypeStruct((c, p), jnp.int8)
    vec_sds = jax.ShapeDtypeStruct((c,), jnp.float32)
    base_sds = jax.ShapeDtypeStruct((p,), jnp.float32)
    stack_sds = jax.ShapeDtypeStruct((c, p), jnp.float32)
    attrs = {"clients": c, "flat_size": p}

    # --- q8/topk path: dequant (materializing) then reduce, vs fused ----------
    # fedlint: disable=FED004 (profiling-only programs: registered for AOT cost analysis, never executed — donation is irrelevant)
    dequant = jax.jit(lambda q, s: q.astype(jnp.float32) * s[:, None])
    # fedlint: disable=FED004 (profiling-only program, never executed)
    reduce_ = jax.jit(lambda x, w, base: base + (w / w.sum()) @ x)
    catalog.register(
        "q8_epilogue_dequant", dequant,
        args_factory=lambda: ((q_sds, vec_sds), {}),
        attrs={**attrs, "stage": "unfused 1/2: int8 -> materialized f32 stack"},
    )
    catalog.register(
        "q8_epilogue_reduce", reduce_,
        args_factory=lambda: ((stack_sds, vec_sds, base_sds), {}),
        attrs={**attrs, "stage": "unfused 2/2: weighted reduce of the f32 stack"},
    )
    catalog.register(
        "q8_epilogue_fused", dequant_accumulate_flat,
        args_factory=lambda: ((q_sds, vec_sds, vec_sds, base_sds), {}),
        attrs={**attrs, "stage": "fused: dequant folded into reduce coefficients"},
    )

    # --- validated path: sanitize (materializing) then reduce, vs fused -------
    # fedlint: disable=FED004 (profiling-only program, never executed)
    sanitize = jax.jit(lambda x: jnp.where(jnp.isfinite(x), x, jnp.zeros_like(x)))
    # fedlint: disable=FED004 (profiling-only program, never executed)
    masked_reduce = jax.jit(
        lambda x, w, valid: (
            (w * valid) / jnp.maximum((w * valid).sum(), 1e-12)
        ) @ x
    )
    catalog.register(
        "validated_epilogue_sanitize", sanitize,
        args_factory=lambda: ((stack_sds,), {}),
        attrs={**attrs, "stage": "unfused 1/2: non-finite -> 0, materialized"},
    )
    catalog.register(
        "validated_epilogue_reduce", masked_reduce,
        args_factory=lambda: ((stack_sds, vec_sds, vec_sds), {}),
        attrs={**attrs, "stage": "unfused 2/2: mask-weighted reduce"},
    )
    catalog.register(
        "validated_epilogue_fused", masked_weighted_mean_flat,
        args_factory=lambda: ((stack_sds, vec_sds, vec_sds), {}),
        attrs={**attrs, "stage": "fused: sanitize in-register + reduce, one pass"},
    )


def profile_aggregation_epilogues(
    flat_size: int,
    clients: int = DEFAULT_EPILOGUE_CLIENTS,
    catalog: ProgramCatalog | None = None,
) -> dict[str, Any]:
    """Profile both forms of both epilogues and return the comparison record the
    autotune artifact embeds: per-program reports plus the measured
    bytes-accessed reduction of each fused kernel vs its unfused two-program sum.
    """
    import jax

    catalog = catalog or ProgramCatalog()
    register_epilogue_programs(catalog, flat_size=flat_size, clients=clients)
    reports = {name: catalog.profile(name) for name in catalog.names()}

    def _compare(fused: str, unfused: tuple[str, ...]) -> dict[str, Any]:
        fused_bytes = reports[fused].bytes_accessed
        unfused_bytes = sum(reports[n].bytes_accessed for n in unfused)
        out: dict[str, Any] = {
            "fused_bytes_accessed": fused_bytes,
            "unfused_bytes_accessed": unfused_bytes,
            "unfused_programs": list(unfused),
        }
        if unfused_bytes > 0:
            out["bytes_accessed_reduction_pct"] = round(
                100.0 * (1.0 - fused_bytes / unfused_bytes), 2
            )
        return out

    platform = str(jax.devices()[0].platform)
    return {
        "flat_size": int(flat_size),
        "clients": int(clients),
        "platform": platform,
        "q8": _compare(
            "q8_epilogue_fused", ("q8_epilogue_dequant", "q8_epilogue_reduce")
        ),
        "validated": _compare(
            "validated_epilogue_fused",
            ("validated_epilogue_sanitize", "validated_epilogue_reduce"),
        ),
        "reports": {name: r.to_dict() for name, r in reports.items()},
        "basis": (
            "compiler cost_analysis bytes accessed: one fused program vs the "
            "SUM of the two separate programs the current serving path runs. "
            + ("On CPU the fused kernels run under the Pallas interpreter, "
               "whose accounting charges every VMEM block copy — the q8 drop "
               "survives that overhead (int8 read once vs int8-read + "
               "f32-write + f32-read); the validated fusion's smaller win "
               "(same-dtype read-write-read -> one read) appears only on TPU "
               "where the kernel is real."
               if platform != "tpu" else
               "Real Mosaic kernels on this platform.")
        ),
    }
