"""Compile-only autotuning: the compiler's cost model picks the round-program
configuration (``client_chunk`` x ``rounds_per_block`` x ``mesh_shape`` x batch
size) with ZERO round executions — see ``tuning.autotuner`` for the scoring
bases and ``tuning.epilogues`` for the fused-aggregation cost comparison."""

from nanofed_tpu.tuning.autotuner import (
    AutotuneError,
    AutotuneResult,
    CandidateConfig,
    CandidateOutcome,
    PopulationSpec,
    TuningSpace,
    autotune,
    candidate_program_name,
    format_candidate_table,
    order_by_predicted_compile_cost,
    predicted_compile_cost,
    rank_candidates,
    resolve_hbm_budget,
)
from nanofed_tpu.tuning.compile_cache import (
    WarmResult,
    build_manifest,
    install_compile_cache_metrics,
    verify_manifest,
    warm,
    write_manifest,
)
from nanofed_tpu.tuning.epilogues import (
    profile_aggregation_epilogues,
    register_epilogue_programs,
)
from nanofed_tpu.tuning.retuner import OnlineRetuner, RetuneDecision

__all__ = [
    "AutotuneError",
    "AutotuneResult",
    "CandidateConfig",
    "CandidateOutcome",
    "OnlineRetuner",
    "PopulationSpec",
    "RetuneDecision",
    "TuningSpace",
    "WarmResult",
    "autotune",
    "build_manifest",
    "candidate_program_name",
    "format_candidate_table",
    "install_compile_cache_metrics",
    "order_by_predicted_compile_cost",
    "predicted_compile_cost",
    "profile_aggregation_epilogues",
    "rank_candidates",
    "register_epilogue_programs",
    "resolve_hbm_budget",
    "verify_manifest",
    "warm",
    "write_manifest",
]
