"""Compile-only autotuning: the compiler's cost model picks the round-program
configuration (``client_chunk`` x ``rounds_per_block`` x ``mesh_shape`` x batch
size) with ZERO round executions — see ``tuning.autotuner`` for the scoring
bases and ``tuning.epilogues`` for the fused-aggregation cost comparison."""

from nanofed_tpu.tuning.autotuner import (
    AutotuneError,
    AutotuneResult,
    CandidateConfig,
    CandidateOutcome,
    PopulationSpec,
    TuningSpace,
    autotune,
    format_candidate_table,
    rank_candidates,
    resolve_hbm_budget,
)
from nanofed_tpu.tuning.epilogues import (
    profile_aggregation_epilogues,
    register_epilogue_programs,
)

__all__ = [
    "AutotuneError",
    "AutotuneResult",
    "CandidateConfig",
    "CandidateOutcome",
    "PopulationSpec",
    "TuningSpace",
    "autotune",
    "format_candidate_table",
    "profile_aggregation_epilogues",
    "rank_candidates",
    "register_epilogue_programs",
    "resolve_hbm_budget",
]
