"""The persistent compilation cache as a managed subsystem, not an ambient
side effect.

``utils.platform.enable_compilation_cache`` points JAX's persistent cache at a
directory and walks away; until now nothing owned what lands there, whether a
run actually hit it, or how a cache built on one host could be trusted on
another.  Both failed accel windows (r05, r14) burned their whole slot inside
XLA compiles that a pre-warmed, shipped cache would have skipped — FedJAX
(arXiv:2108.02117) amortizes jit compilation across rounds, but amortization
starts at zero every time the cache is cold.  This module closes that gap:

* :func:`install_compile_cache_metrics` — bridges JAX's compilation-cache
  ``jax.monitoring`` events into ``nanofed_compile_cache_hits_total`` /
  ``nanofed_compile_cache_misses_total`` counters, so a scrape (or the final
  telemetry snapshot) states whether the run compiled or replayed.
* :func:`warm` — pre-compiles a program set (an :func:`~nanofed_tpu.tuning.
  autotuner.autotune` sweep: every candidate the coordinator could dispatch)
  into the cache directory OFF the critical path, emitting one ``compile``
  telemetry record per program, then stamps a :func:`manifest <build_manifest>`.
* :func:`build_manifest` / :func:`verify_manifest` — the cache-key manifest:
  what toolchain (jax/jaxlib/platform) produced the entries, how many, how
  large.  ``verify_manifest`` is the receiving side of the warm-ship workflow —
  a cache built under a different jaxlib is DEAD WEIGHT (XLA keys miss), and
  the manifest says so before the accel window finds out the slow way.

The cache directory is shippable: ``tar`` it, move it to the accel host, point
``NANOFED_CACHE_DIR`` (or ``--cache-dir``) at it, and verify the manifest.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from nanofed_tpu.utils.logger import Logger

__all__ = [
    "CACHE_HIT_EVENT",
    "CACHE_MISS_EVENT",
    "COMPILE_CACHE_HITS",
    "COMPILE_CACHE_MISSES",
    "MANIFEST_NAME",
    "WarmResult",
    "build_manifest",
    "install_compile_cache_metrics",
    "verify_manifest",
    "warm",
    "write_manifest",
]

_log = Logger()

#: The jax.monitoring occurrence events the XLA persistent cache emits.
CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

COMPILE_CACHE_HITS = "nanofed_compile_cache_hits_total"
COMPILE_CACHE_MISSES = "nanofed_compile_cache_misses_total"

MANIFEST_NAME = "manifest.json"

_metrics_installed = False
#: The registry the FIRST install adopted — later callers' registries are NOT
#: wired (jax.monitoring keeps listeners forever); read this to find where the
#: counters actually land.
_metrics_registry: Any = None
_metrics_lock = threading.Lock()


def install_compile_cache_metrics(registry: Any = None) -> bool:
    """Count persistent-compilation-cache hits and misses as first-class
    metrics (idempotent, process-wide, same one-registry rule as
    ``install_jax_event_bridge``: jax.monitoring keeps listeners forever, so
    only the FIRST caller's registry receives the counters).

    Distinct from the generic ``nanofed_jax_events_total{event=...}`` bridge:
    these two counters are the warm-ship workflow's acceptance test — a warmed
    run shows hits ≈ programs and misses ≈ 0.

    Returns False when jax.monitoring is unavailable."""
    global _metrics_installed, _metrics_registry
    with _metrics_lock:
        if _metrics_installed:
            return True
        try:
            from jax import monitoring
        except Exception:
            return False
        from nanofed_tpu.observability.registry import get_registry

        reg = registry if registry is not None else get_registry()
        hits = reg.counter(
            COMPILE_CACHE_HITS,
            "XLA persistent compilation cache hits (program replayed, no compile)",
        )
        misses = reg.counter(
            COMPILE_CACHE_MISSES,
            "XLA persistent compilation cache misses (program compiled from scratch)",
        )

        def _on_event(event: str, **kwargs: Any) -> None:
            if event == CACHE_HIT_EVENT:
                hits.inc()
            elif event == CACHE_MISS_EVENT:
                misses.inc()

        try:
            monitoring.register_event_listener(_on_event)
        except Exception:
            return False
        _metrics_installed = True
        _metrics_registry = reg
        return True


def _toolchain() -> dict[str, str]:
    import jax
    import jaxlib

    devices = jax.devices()
    return {
        "jax": str(jax.__version__),
        "jaxlib": str(getattr(jaxlib, "__version__", jax.__version__)),
        "platform": str(devices[0].platform),
        "device_kind": str(
            getattr(devices[0], "device_kind", devices[0].platform)
        ),
        "num_devices": str(len(devices)),
    }


def build_manifest(cache_dir: str | os.PathLike) -> dict[str, Any]:
    """Inventory a cache directory: the producing toolchain plus what is in it
    (XLA cache entries, autotune tables).  Pure read — writes nothing."""
    root = Path(cache_dir)
    xla_entries = 0
    xla_bytes = 0
    autotune_entries: list[dict[str, Any]] = []
    if root.is_dir():
        for p in sorted(root.iterdir()):
            if not p.is_file() or p.name == MANIFEST_NAME:
                continue
            if p.name.startswith("autotune_") and p.suffix == ".json":
                entry: dict[str, Any] = {"file": p.name}
                try:
                    d = json.loads(p.read_text())
                    entry["cache_key"] = d.get("cache_key", "?")[:16]
                    entry["winner"] = d.get("winner")
                except (OSError, json.JSONDecodeError):
                    entry["error"] = "unreadable"
                autotune_entries.append(entry)
            else:
                xla_entries += 1
                xla_bytes += p.stat().st_size
    return {
        "version": 1,
        "created_unix": round(time.time(), 3),
        "cache_dir": str(root),
        "toolchain": _toolchain(),
        "xla_entries": xla_entries,
        "xla_bytes": xla_bytes,
        "autotune_entries": autotune_entries,
    }


def write_manifest(
    cache_dir: str | os.PathLike, extra: dict[str, Any] | None = None,
) -> Path:
    """Stamp ``manifest.json`` into the cache directory (atomic rename)."""
    root = Path(cache_dir)
    root.mkdir(parents=True, exist_ok=True)
    manifest = build_manifest(root)
    if extra:
        manifest.update(extra)
    path = root / MANIFEST_NAME
    tmp = path.with_suffix(".tmp")
    tmp.write_text(json.dumps(manifest, indent=2, sort_keys=True) + "\n")
    tmp.replace(path)
    return path


def verify_manifest(cache_dir: str | os.PathLike) -> dict[str, Any]:
    """The receiving end of a shipped cache: does the manifest's toolchain
    match THIS host?  Returns ``{"compatible": bool, "reasons": [...],
    "manifest": ...}`` — never raises on a missing/corrupt manifest (that is
    itself a stated reason).  XLA would key-miss a foreign cache silently and
    recompile everything; this says so up front."""
    path = Path(cache_dir) / MANIFEST_NAME
    reasons: list[str] = []
    manifest: dict[str, Any] | None = None
    try:
        manifest = json.loads(path.read_text())
    except OSError:
        reasons.append(f"no manifest at {path} (cache never warmed, or not shipped)")
    except json.JSONDecodeError as e:
        reasons.append(f"manifest unreadable: {e}")
    if manifest is not None:
        shipped = manifest.get("toolchain", {})
        here = _toolchain()
        for dim in ("jax", "jaxlib", "platform"):
            if shipped.get(dim) != here[dim]:
                reasons.append(
                    f"{dim} mismatch: cache built under {shipped.get(dim)!r}, "
                    f"this host runs {here[dim]!r} — XLA entries will miss"
                )
        if shipped.get("device_kind") != here["device_kind"]:
            reasons.append(
                f"device_kind differs: {shipped.get('device_kind')!r} vs "
                f"{here['device_kind']!r} — autotune tables keyed elsewhere"
            )
    return {
        "compatible": not reasons,
        "reasons": reasons,
        "manifest": manifest,
    }


@dataclass
class WarmResult:
    """What :func:`warm` did: where the cache lives, what was compiled, and
    the stamped manifest."""

    cache_dir: str
    manifest_path: str
    manifest: dict[str, Any]
    autotune: Any = None
    programs: list[dict[str, Any]] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        return {
            "cache_dir": self.cache_dir,
            "manifest_path": self.manifest_path,
            "manifest": self.manifest,
            "programs": self.programs,
            **(
                {"autotune": self.autotune.telemetry_payload()}
                if self.autotune is not None else {}
            ),
        }


def warm(
    model: Any,
    population: Any,
    training: Any = None,
    *,
    num_rounds: int,
    participation: float = 1.0,
    eval_every: int = 0,
    space: Any = None,
    adapter: Any = None,
    cache_dir: str | os.PathLike | None = None,
    telemetry: Any = None,
    force: bool = False,
    compile_budget_s: float | None = None,
    candidate_deadline_s: float | None = None,
) -> WarmResult:
    """Pre-compile the coordinator/autotuner program set into the persistent
    cache, off the critical path.

    Runs the full :func:`~nanofed_tpu.tuning.autotuner.autotune` sweep with
    the persistent compilation cache enabled at ``cache_dir`` — every
    candidate round program the coordinator could dispatch gets lowered,
    compiled, and serialized into the cache (the sweep result itself lands as
    an ``autotune_*.json`` table beside the XLA entries).  One ``compile``
    telemetry record is emitted per compiled program when ``telemetry`` is
    given, the hit/miss counters are installed, and the directory is stamped
    with a manifest so the receiving host can :func:`verify_manifest` before
    trusting it.  ``force=True`` re-sweeps over a warm autotune table (the
    XLA entries still hit, so a forced re-warm is cheap)."""
    from nanofed_tpu.tuning.autotuner import autotune
    from nanofed_tpu.utils.platform import enable_compilation_cache

    path = enable_compilation_cache(cache_dir)
    install_compile_cache_metrics()
    t0 = time.perf_counter()
    result = autotune(
        model, population, training,
        num_rounds=num_rounds, participation=participation,
        eval_every=eval_every, space=space, adapter=adapter,
        cache_dir=path, out_dir=None, telemetry=telemetry, force=force,
        include_epilogues=False,
        compile_budget_s=compile_budget_s,
        candidate_deadline_s=candidate_deadline_s,
    )
    # On an autotune cache hit nothing compiled THIS pass — the outcomes'
    # compile_seconds describe the original sweep, not this warm.
    programs = [] if result.cache_hit else [
        {
            "program": _cand_name(o.config),
            "compile_seconds": o.cost["compile_seconds"],
            "feasible": o.feasible,
        }
        for o in result.outcomes
        if o.cost.get("compile_seconds") is not None
    ]
    manifest_path = write_manifest(path, extra={
        "warmed": {
            "model": getattr(model, "name", type(model).__name__),
            "cache_key": result.cache_key[:16],
            "programs": programs,
            "compiles": result.compiles,
            "compile_seconds_total": round(result.compile_seconds_total, 4),
            "cache_hit": result.cache_hit,
            "warm_seconds": round(time.perf_counter() - t0, 4),
        },
    })
    _log.info(
        "compile cache warmed at %s: %d programs, %.1fs compile (%s)",
        path, result.compiles, result.compile_seconds_total,
        "autotune cache hit" if result.cache_hit else "fresh sweep",
    )
    return WarmResult(
        cache_dir=str(path),
        manifest_path=str(manifest_path),
        manifest=json.loads(Path(manifest_path).read_text()),
        autotune=result,
        programs=programs,
    )


def _cand_name(config: Any) -> str:
    from nanofed_tpu.tuning.autotuner import candidate_program_name

    return candidate_program_name(config)
