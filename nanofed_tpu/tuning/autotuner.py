"""Cost-model-driven autotuning of the round-program configuration.

PR 5 built the instrument — AOT ``cost_analysis``/``memory_analysis``, roofline
verdicts, achievable lower-bound walltimes per :class:`~nanofed_tpu.observability.
profiling.ProgramCostReport` — and until now nothing used it: ``client_chunk``,
``rounds_per_block``, ``mesh_shape`` and the per-client batch size were hand-picked
knobs.  FedJAX (arXiv:2108.02117) leaves them to the experimenter; FL_PyTorch
(arXiv:2202.03099) treats simulator configuration as a first-class research knob.
This module closes the instrument-to-actuator loop: the COMPILER's own cost model
chooses the configuration, with zero round executions.

The sweep lowers every candidate through the same ``build_round_step`` /
``build_round_block`` builders the ``Coordinator`` dispatches — arguments are
``jax.ShapeDtypeStruct``s carrying the dispatch shardings, so nothing
materializes and nothing runs; the only cost is one XLA compile per candidate
(cheap under the persistent compilation cache, and the sweep result itself is
cached under ``.jax_cache/autotune_*.json`` keyed by model fingerprint,
population, and device kind/count, so repeat runs compile NOTHING).

Scoring is honest about its basis and never fabricates a peak:

* **TPU** (a published peaks row exists): candidates are ranked by the roofline
  **achievable walltime per round** — ``max(flops/peak_flops,
  bytes/peak_bandwidth)`` of the per-device program, divided by the rounds the
  program covers.
* **CPU / unknown chips** (no peaks basis): candidates are ranked by **compiler
  bytes accessed per round** — a relative ordering, NOT a walltime; the artifact
  says so in its ``scoring_basis`` field.

Candidates whose ``memory_analysis`` peak exceeds the device HBM budget are
rejected (never ranked), with the budget's provenance stated.  The AOT cost model
cannot see the per-round HOST tax (dispatch, ``block_until_ready``, metrics
transfer) that ``rounds_per_block`` exists to amortize, so exact score ties break
toward the larger block — the tie-break is stated in the artifact, deterministic,
and last-resorts to the candidate key so equal sweeps rank identically.

Every sweep emits a ranked candidate table as ``runs/autotune_*.json`` (the full
table, rejected candidates included with their reasons) and, when telemetry is
wired, an ``autotune`` record that ``nanofed-tpu metrics-summary`` digests.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Iterable

from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.utils.logger import Logger

__all__ = [
    "AutotuneError",
    "AutotuneResult",
    "CandidateConfig",
    "CandidateOutcome",
    "PopulationSpec",
    "TuningSpace",
    "autotune",
    "candidate_program_name",
    "order_by_predicted_compile_cost",
    "predicted_compile_cost",
    "rank_candidates",
    "resolve_hbm_budget",
]

_log = Logger()

#: Published per-chip HBM capacities, matched like ``profiling.TPU_PEAKS`` (most
#: specific substring first).  Used only when the runtime does not report a
#: ``bytes_limit`` — CPU and unknown chips get NO budget rather than a made-up one.
TPU_HBM_BYTES: tuple[tuple[str, int, str], ...] = (
    ("v5 lite", 16 * 1024**3, "TPU v5e: 16 GiB HBM"),
    ("v5e", 16 * 1024**3, "TPU v5e: 16 GiB HBM"),
    ("v6 lite", 32 * 1024**3, "TPU v6e: 32 GiB HBM"),
    ("v6e", 32 * 1024**3, "TPU v6e: 32 GiB HBM"),
    ("v5p", 95 * 1024**3, "TPU v5p: 95 GiB HBM"),
    ("v4", 32 * 1024**3, "TPU v4: 32 GiB HBM"),
)


class AutotuneError(NanoFedError):
    """No feasible candidate survived the sweep (every configuration was
    rejected); the artifact still records the full table with reasons."""


@dataclass(frozen=True)
class PopulationSpec:
    """The client population's SHAPES — all the tuner needs to lower programs.

    ``capacity`` is the packed per-client sample capacity (the ``[C, N, ...]``
    second dim of ``ClientData``); candidate batch sizes must divide it, which is
    exactly the constraint ``trainer.local`` enforces at dispatch."""

    num_clients: int
    capacity: int
    sample_shape: tuple[int, ...]
    x_dtype: str = "float32"
    y_dtype: str = "int32"
    mask_dtype: str = "float32"

    @classmethod
    def from_client_data(cls, data: Any) -> "PopulationSpec":
        import numpy as np

        x = data.x
        return cls(
            num_clients=int(x.shape[0]),
            capacity=int(x.shape[1]),
            sample_shape=tuple(int(d) for d in x.shape[2:]),
            x_dtype=str(np.asarray(x[:1, :1]).dtype) if hasattr(x, "__getitem__")
            else str(x.dtype),
            y_dtype=str(np.asarray(data.y[:1, :1]).dtype)
            if hasattr(data.y, "__getitem__") else str(data.y.dtype),
            mask_dtype=str(np.asarray(data.mask[:1, :1]).dtype)
            if hasattr(data.mask, "__getitem__") else str(data.mask.dtype),
        )

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


@dataclass(frozen=True, order=True)
class CandidateConfig:
    """One point of the swept configuration space.  Ordered (field order) so the
    deterministic last-resort tie-break is the dataclass ordering itself.
    ``hosts`` (default 1: every pre-multi-host candidate) is the hosts-axis
    size of the mesh the candidate lowers on — >1 builds the 3-axis
    ``hosts x clients x model`` mesh with hierarchical aggregation.
    ``adapter_rank`` (default None: dense full fine-tune) lowers the
    parameter-efficient frozen-base round program at that LoRA rank — the
    federated/aggregated tree is the adapter tree, the base crosses as a
    read-only model-sharded input (``nanofed_tpu.adapters``)."""

    client_chunk: int | None
    rounds_per_block: int
    model_shards: int
    batch_size: int
    hosts: int = 1
    adapter_rank: int | None = None

    @property
    def key(self) -> tuple[int, int, int, int, int, int]:
        """Stable sort key (``None`` chunk/rank order first as 0)."""
        return (
            self.client_chunk or 0, self.rounds_per_block,
            self.model_shards, self.batch_size, self.hosts,
            self.adapter_rank or 0,
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "client_chunk": self.client_chunk,
            "rounds_per_block": self.rounds_per_block,
            "model_shards": self.model_shards,
            "batch_size": self.batch_size,
            "hosts": self.hosts,
            "adapter_rank": self.adapter_rank,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "CandidateConfig":
        return cls(
            client_chunk=d.get("client_chunk"),
            rounds_per_block=int(d["rounds_per_block"]),
            model_shards=int(d["model_shards"]),
            batch_size=int(d["batch_size"]),
            hosts=int(d.get("hosts", 1)),
            adapter_rank=d.get("adapter_rank"),
        )


def _divisor_ladder(n: int, limit: int = 3) -> list[int]:
    """Up to ``limit`` proper divisors of ``n``, spread across its range (small,
    ~sqrt, large) — the interesting chunk sizes without a full divisor sweep."""
    divs = [d for d in range(1, n) if n % d == 0]
    if not divs:
        return []
    if len(divs) <= limit:
        return divs
    picks = {divs[0], divs[len(divs) // 2], divs[-1]}
    return sorted(picks)[:limit]


@dataclass(frozen=True)
class TuningSpace:
    """The candidate grid.  Build one explicitly, or derive a modest default from
    the population/device geometry with :meth:`default` — the default keeps the
    cross product small (a sweep pays one XLA compile per candidate)."""

    client_chunks: tuple[int | None, ...]
    rounds_per_blocks: tuple[int, ...]
    model_shards: tuple[int, ...]
    batch_sizes: tuple[int, ...]
    #: Hosts-axis sizes to sweep; (1,) = single-host meshes only.  On a
    #: multi-process run :func:`autotune` defaults this to the process count —
    #: a flat mesh across processes would pay one DCN reduce per client shard,
    #: so the hierarchical topology is the only sensible default there.
    hosts: tuple[int, ...] = (1,)
    #: LoRA ranks to sweep (the parameter-efficient axis); (None,) = dense
    #: full fine-tune only.  Engaged when :func:`autotune` is given an
    #: ``adapter=`` spec: the default becomes a ladder around the spec's rank
    #: (rank/2, rank, 2*rank), every candidate frozen-base.
    adapter_ranks: tuple[int | None, ...] = (None,)

    @classmethod
    def default(
        cls,
        population: PopulationSpec,
        n_devices: int,
        batch_size: int,
        num_rounds: int,
        hosts: tuple[int, ...] | None = None,
        adapter_rank: int | None = None,
    ) -> "TuningSpace":
        from nanofed_tpu.parallel.mesh import pad_client_count

        if hosts is None:
            import jax

            # THE one home of the multi-process space rule (cli.py and
            # autotune() both rely on it): multi-process runs sweep the
            # hierarchical hosts=(process_count,) topology — a flat client
            # axis across processes would pay one cross-host (DCN) reduce
            # per client shard instead of one per round.
            pc = jax.process_count()
            hosts = (pc,) if pc > 1 else (1,)

        per_dev = pad_client_count(population.num_clients, n_devices) // n_devices
        chunks: list[int | None] = [None] + [
            d for d in _divisor_ladder(per_dev, limit=2)
        ]
        rpbs = tuple(sorted({1, min(4, num_rounds), min(8, num_rounds)}))
        shards = (1, 2) if n_devices % 2 == 0 and n_devices > 1 else (1,)
        batches = tuple(sorted({
            b for b in (batch_size // 2, batch_size, batch_size * 2)
            if 1 <= b <= population.capacity and population.capacity % b == 0
        })) or (batch_size,)
        # THE one home of the adapter-rank space rule: with a spec'd rank r the
        # sweep covers the ladder {max(1, r//2), r, 2r} — enough to show where
        # rank stops paying without exploding the cross product.
        ranks: tuple[int | None, ...] = (None,)
        if adapter_rank is not None:
            ranks = tuple(sorted({max(1, adapter_rank // 2), adapter_rank,
                                  2 * adapter_rank}))
        return cls(
            client_chunks=tuple(chunks),
            rounds_per_blocks=rpbs,
            model_shards=shards,
            batch_sizes=batches,
            hosts=tuple(hosts),
            adapter_ranks=ranks,
        )

    @classmethod
    def for_fleet(
        cls,
        profile: Any,
        population: PopulationSpec,
        n_devices: int,
        batch_size: int,
        num_rounds: int,
        hosts: tuple[int, ...] | None = None,
    ) -> "TuningSpace":
        """The compiled-cost space for a heterogeneous fleet
        (``nanofed_tpu.fleet.FleetProfile``): identical to :meth:`default`
        except the adapter-rank axis is the sorted UNION of every tier's
        ``{max(1, r//2), r, 2r}`` ladder — the mix itself is swept analytically
        by ``nanofed_tpu.fleet.tuning`` (no compile per mix), but every rank
        any mix candidate could assign to a tier needs a measured per-rank
        cost here, so the two sweeps compose: this space prices the ranks,
        the mix sweep shops from those prices."""
        base = cls.default(
            population, n_devices, batch_size, num_rounds, hosts=hosts,
        )
        ranks: set[int] = set()
        for t in profile.tiers:
            r = int(t.adapter_rank)
            ranks.update({max(1, r // 2), r, 2 * r})
        return dataclasses.replace(base, adapter_ranks=tuple(sorted(ranks)))

    def candidates(self) -> list[CandidateConfig]:
        out = []
        for chunk in self.client_chunks:
            for rpb in self.rounds_per_blocks:
                for shards in self.model_shards:
                    for b in self.batch_sizes:
                        for h in self.hosts:
                            for r in self.adapter_ranks:
                                out.append(
                                    CandidateConfig(chunk, rpb, shards, b, h, r)
                                )
        return sorted(set(out), key=lambda c: c.key)

    def to_dict(self) -> dict[str, Any]:
        return {
            "client_chunks": list(self.client_chunks),
            "rounds_per_blocks": list(self.rounds_per_blocks),
            "model_shards": list(self.model_shards),
            "batch_sizes": list(self.batch_sizes),
            "hosts": list(self.hosts),
            "adapter_ranks": list(self.adapter_ranks),
        }


@dataclass
class CandidateOutcome:
    """One candidate's fate: a score (feasible) or a rejection reason, plus the
    per-round cost summary the ranked table prints."""

    config: CandidateConfig
    feasible: bool
    reject_reason: str | None = None
    score: float | None = None
    cost: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {
            "config": self.config.to_dict(),
            "feasible": self.feasible,
            **({"reject_reason": self.reject_reason}
               if self.reject_reason else {}),
            **({"score": self.score} if self.score is not None else {}),
            **({"cost": self.cost} if self.cost else {}),
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "CandidateOutcome":
        return cls(
            config=CandidateConfig.from_dict(d["config"]),
            feasible=bool(d["feasible"]),
            reject_reason=d.get("reject_reason"),
            score=d.get("score"),
            cost=d.get("cost", {}),
        )


def rank_candidates(outcomes: Iterable[CandidateOutcome]) -> list[CandidateOutcome]:
    """Deterministic ranking: feasible candidates by ascending score, exact ties
    broken toward the LARGER ``rounds_per_block`` (the AOT cost model cannot see
    the per-round host tax fused blocks amortize), then the smaller device-memory
    peak, then the stable candidate key; rejected candidates follow in key order.

    Pure — unit-testable without a single compile."""
    outcomes = list(outcomes)
    feasible = [o for o in outcomes if o.feasible]
    rejected = [o for o in outcomes if not o.feasible]
    feasible.sort(key=lambda o: (
        o.score,
        -o.config.rounds_per_block,
        o.cost.get("peak_bytes", 0),
        o.config.key,
    ))
    rejected.sort(key=lambda o: o.config.key)
    return feasible + rejected


def predicted_compile_cost(cand: CandidateConfig) -> float:
    """A dimensionless predictor of how long a candidate's XLA compile takes,
    for SWEEP ORDERING only (never for scoring): fused multi-round blocks trace
    ``rounds_per_block`` bodies plus the cohort plumbing, client chunking adds
    an inner scan, every extra mesh axis cell multiplies the SPMD partitioning
    work, and the frozen-base adapter path adds the bind/merge prologue.  The
    weights are coarse on purpose — the point is that a budget-killed sweep
    dies in the expensive tail, not before the cheap feasible head compiled."""
    return (
        (1.0 if cand.rounds_per_block > 1 else 0.0)
        + (0.5 if cand.client_chunk is not None else 0.0)
        + float(cand.hosts * cand.model_shards - 1)
        + (0.25 if cand.adapter_rank is not None else 0.0)
    )


def order_by_predicted_compile_cost(
    candidates: Iterable[CandidateConfig],
) -> list[CandidateConfig]:
    """Cheapest-compile-first sweep order (stable: ties fall back to the
    candidate key, so equal spaces sweep identically).  This is THE sweep
    order of :func:`autotune` — under a compile budget the cheap single-round
    candidates land first, so a budget- or wedge-killed sweep still holds a
    feasible winner instead of dying inside the most expensive lowering (the
    r14 failure mode, and the ``for_fleet`` rank-union sweep's worst case)."""
    return sorted(candidates, key=lambda c: (predicted_compile_cost(c), c.key))


def candidate_program_name(cand: CandidateConfig) -> str:
    """The ``ProgramCatalog``/telemetry name a candidate's lowered round
    program is registered and recorded under."""
    return (
        f"cand_chunk{cand.client_chunk or 0}_rpb{cand.rounds_per_block}"
        f"_m{cand.model_shards}_b{cand.batch_size}_h{cand.hosts}"
        + (f"_r{cand.adapter_rank}" if cand.adapter_rank is not None else "")
    )


def resolve_hbm_budget(
    explicit: int | None = None, devices: list | None = None
) -> tuple[int | None, str]:
    """The per-device memory budget candidates must fit, with its provenance:
    explicit argument > ``NANOFED_AUTOTUNE_HBM_BUDGET`` env > the runtime's
    ``memory_stats()['bytes_limit']`` > the published per-chip HBM table > None
    (no rejection — stated as unbounded, never a fabricated limit)."""
    if explicit is not None:
        return int(explicit), "explicit hbm_budget_bytes argument"
    env = os.environ.get("NANOFED_AUTOTUNE_HBM_BUDGET")
    if env:
        return int(float(env)), "NANOFED_AUTOTUNE_HBM_BUDGET environment variable"
    import jax

    dev = (devices or jax.devices())[0]
    try:
        stats = dev.memory_stats() or {}
    except Exception:
        stats = {}
    limit = stats.get("bytes_limit")
    if isinstance(limit, (int, float)) and limit > 0:
        return int(limit), f"runtime memory_stats bytes_limit ({dev.device_kind})"
    kind = str(getattr(dev, "device_kind", "")).lower()
    for needle, cap, basis in TPU_HBM_BYTES:
        if needle in kind:
            return cap, basis
    return None, (
        f"unbounded — no device memory limit known for platform="
        f"{dev.platform!r} ({dev.device_kind}); pass hbm_budget_bytes= or set "
        "NANOFED_AUTOTUNE_HBM_BUDGET to enable rejection"
    )


@dataclass
class AutotuneResult:
    """The sweep's outcome: the winner, the full ranked table, and enough basis
    fields that a reader of the artifact alone can audit the choice."""

    winner: CandidateConfig | None
    outcomes: list[CandidateOutcome]
    scoring_basis: str
    platform: str
    device_kind: str
    num_devices: int
    hbm_budget_bytes: int | None
    budget_basis: str
    cache_key: str
    cache_hit: bool = False
    compiles: int = 0
    compile_seconds_total: float = 0.0
    #: The sweep's compile budget (seconds), when one was set — candidates
    #: beyond the budget are in ``outcomes`` with ``skipped: compile_budget``.
    compile_budget_s: float | None = None
    #: Candidates never compiled because the budget ran out or the sweep
    #: wedged (counted so the artifact states its own incompleteness).
    skipped: int = 0
    #: Program name of the candidate whose compile blew the per-candidate
    #: deadline, when one did — the r14 postmortem field.
    wedged_at: str | None = None
    space: dict[str, Any] = field(default_factory=dict)
    population: dict[str, Any] = field(default_factory=dict)
    epilogues: dict[str, Any] = field(default_factory=dict)
    artifact_path: str | None = None

    def to_dict(self) -> dict[str, Any]:
        return {
            "winner": self.winner.to_dict() if self.winner else None,
            "candidates": [o.to_dict() for o in self.outcomes],
            "scoring_basis": self.scoring_basis,
            "tie_break": (
                "exact score ties prefer larger rounds_per_block (AOT cost "
                "cannot see the per-round host dispatch tax fused blocks "
                "amortize), then smaller peak_bytes, then the candidate key"
            ),
            "platform": self.platform,
            "device_kind": self.device_kind,
            "num_devices": self.num_devices,
            "hbm_budget_bytes": self.hbm_budget_bytes,
            "budget_basis": self.budget_basis,
            "cache_key": self.cache_key,
            "cache_hit": self.cache_hit,
            "compiles": self.compiles,
            "compile_seconds_total": round(self.compile_seconds_total, 4),
            **({"compile_budget_s": self.compile_budget_s}
               if self.compile_budget_s is not None else {}),
            **({"skipped": self.skipped} if self.skipped else {}),
            **({"wedged_at": self.wedged_at} if self.wedged_at else {}),
            "space": self.space,
            "population": self.population,
            **({"epilogues": self.epilogues} if self.epilogues else {}),
        }

    def telemetry_payload(self) -> dict[str, Any]:
        """The ``autotune`` telemetry-record fields (what ``metrics-summary``
        digests into its ``autotunes`` block)."""
        feasible = [o for o in self.outcomes if o.feasible]
        return {
            "winner": self.winner.to_dict() if self.winner else None,
            "scoring_basis": self.scoring_basis,
            "platform": self.platform,
            "device_kind": self.device_kind,
            "num_devices": self.num_devices,
            "candidates_total": len(self.outcomes),
            "candidates_feasible": len(feasible),
            "cache_key": self.cache_key,
            "cache_hit": self.cache_hit,
            "compiles": self.compiles,
            "compile_seconds_total": round(self.compile_seconds_total, 4),
            **({"skipped": self.skipped} if self.skipped else {}),
            **({"wedged_at": self.wedged_at} if self.wedged_at else {}),
            **({"best_score": feasible[0].score} if feasible else {}),
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "AutotuneResult":
        return cls(
            winner=(
                CandidateConfig.from_dict(d["winner"])
                if d.get("winner") else None
            ),
            outcomes=[CandidateOutcome.from_dict(o) for o in d.get("candidates", [])],
            scoring_basis=d.get("scoring_basis", "?"),
            platform=d.get("platform", "?"),
            device_kind=d.get("device_kind", "?"),
            num_devices=int(d.get("num_devices", 0)),
            hbm_budget_bytes=d.get("hbm_budget_bytes"),
            budget_basis=d.get("budget_basis", "?"),
            cache_key=d.get("cache_key", "?"),
            cache_hit=bool(d.get("cache_hit", False)),
            compiles=int(d.get("compiles", 0)),
            compile_seconds_total=float(d.get("compile_seconds_total", 0.0)),
            compile_budget_s=d.get("compile_budget_s"),
            skipped=int(d.get("skipped", 0)),
            wedged_at=d.get("wedged_at"),
            space=d.get("space", {}),
            population=d.get("population", {}),
            epilogues=d.get("epilogues", {}),
        )


def _model_fingerprint(model: Any) -> dict[str, Any]:
    """Shape/dtype identity of the model's parameter tree (the cache-key
    component): abstract init only, nothing materializes."""
    import jax

    from nanofed_tpu.persistence.serialization import tree_flatten_with_names

    params_abs = jax.eval_shape(lambda: model.init(jax.random.key(0)))
    named, _ = tree_flatten_with_names(params_abs)
    return {
        "model": getattr(model, "name", type(model).__name__),
        "leaves": [
            [name, list(leaf.shape), str(leaf.dtype)] for name, leaf in named
        ],
    }


def compute_cache_key(
    model: Any,
    population: PopulationSpec,
    training: Any,
    space: TuningSpace,
    participation: float,
    num_rounds: int,
    eval_every: int,
    device_kind: str,
    num_devices: int,
    hbm_budget: int | None = None,
    adapter: Any = None,
) -> str:
    """SHA-256 over everything that changes a sweep's outcome: model fingerprint,
    population shapes, the swept space, the non-swept training dims that shape
    the program (epochs, dtype, prox), participation/rounds geometry, the device
    kind/count, and the RESOLVED memory budget (the budget changes which
    candidates are rejected, hence the winner).  Learning RATE is deliberately
    excluded — it never changes the compiled program's cost."""
    import jax
    import jaxlib

    payload = {
        # v5: jax/jaxlib versions and the backend platform join the key — a
        # jaxlib upgrade changes compiled-program cost analysis, so it must
        # not silently serve a stale tuned config.  (v4 grew the adapter-rank
        # axis; v3 added the hosts axis.)
        "v": 5,
        "jax": str(jax.__version__),
        "jaxlib": str(getattr(jaxlib, "__version__", jax.__version__)),
        "platform": str(jax.devices()[0].platform),
        "adapter": adapter.to_dict() if adapter is not None else None,
        "hbm_budget": hbm_budget,
        "model": _model_fingerprint(model),
        "population": population.to_dict(),
        "space": space.to_dict(),
        "training": {
            "local_epochs": getattr(training, "local_epochs", 1),
            "compute_dtype": getattr(training, "compute_dtype", None),
            "prox_mu": getattr(training, "prox_mu", 0.0),
        },
        "participation": participation,
        "num_rounds": num_rounds,
        "eval_every": eval_every,
        "device_kind": device_kind,
        "num_devices": num_devices,
    }
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()
    ).hexdigest()


def _plan_layout(
    num_clients: int,
    n_client_shards: int,
    participation: float,
    client_chunk: int | None,
) -> tuple[int, int, int, bool]:
    """Mirror the ``Coordinator``'s step-layout rules exactly (padding, cohort
    gathering, the chunk-divisibility fallback) so the lowered candidate IS the
    program the coordinator would dispatch.  Returns ``(padded, step_clients,
    cohort, cohort_mode)``."""
    from nanofed_tpu.orchestration.types import cohort_size
    from nanofed_tpu.parallel.mesh import pad_client_count

    padded = pad_client_count(num_clients, n_client_shards)
    cohort = cohort_size(num_clients, participation)
    cohort_mode = cohort < num_clients
    if cohort_mode and client_chunk is not None:
        per_dev = pad_client_count(cohort, n_client_shards) // n_client_shards
        if client_chunk < per_dev and per_dev % client_chunk != 0:
            cohort_mode = False
    step_clients = (
        pad_client_count(cohort, n_client_shards) if cohort_mode else padded
    )
    return padded, step_clients, cohort, cohort_mode


def _evaluate_candidate(
    cand: CandidateConfig,
    model: Any,
    population: PopulationSpec,
    training: Any,
    participation: float,
    num_rounds: int,
    eval_every: int,
    n_devices: int,
    budget: int | None,
    adapter: Any = None,
) -> CandidateOutcome:
    """Lower + compile ONE candidate's round program with fully abstract
    (ShapeDtypeStruct) arguments in the dispatch shardings and score its cost
    report.  Zero materialization, zero execution."""
    import dataclasses as dc

    import jax
    import jax.numpy as jnp

    from nanofed_tpu.aggregation.base import fedavg_strategy
    from nanofed_tpu.core.types import ClientData
    from nanofed_tpu.observability.profiling import profile_program
    from nanofed_tpu.parallel.mesh import (
        client_sharding,
        make_mesh,
        param_sharding,
    )
    from nanofed_tpu.parallel.multi_round import (
        build_round_block,
        stack_round_keys,
    )
    from nanofed_tpu.parallel.round_step import build_round_step, init_server_state
    from nanofed_tpu.trainer.local import stack_rngs

    C, cap = population.num_clients, population.capacity

    # --- Static feasibility (no compile) -------------------------------------
    if cand.batch_size < 1 or cap % cand.batch_size != 0:
        return CandidateOutcome(cand, False, reject_reason=(
            f"batch_size {cand.batch_size} does not divide the packed "
            f"per-client capacity {cap}"
        ))
    if cand.rounds_per_block > num_rounds:
        return CandidateOutcome(cand, False, reject_reason=(
            f"rounds_per_block {cand.rounds_per_block} exceeds num_rounds "
            f"{num_rounds}"
        ))
    if (
        cand.rounds_per_block > 1
        and 0 < eval_every < cand.rounds_per_block
    ):
        return CandidateOutcome(cand, False, reject_reason=(
            f"rounds_per_block {cand.rounds_per_block} > eval_every "
            f"{eval_every}: the coordinator would fall back to single rounds "
            "(blocks are cut at eval boundaries)"
        ))
    if cand.model_shards < 1 or n_devices % cand.model_shards != 0:
        return CandidateOutcome(cand, False, reject_reason=(
            f"model_shards {cand.model_shards} does not divide the "
            f"{n_devices} available devices"
        ))
    if cand.hosts < 1 or n_devices % (cand.hosts * cand.model_shards) != 0:
        return CandidateOutcome(cand, False, reject_reason=(
            f"hosts {cand.hosts} x model_shards {cand.model_shards} does not "
            f"divide the {n_devices} available devices — the 3-axis mesh "
            "needs a full (hosts, clients, model) grid"
        ))
    n_cs = n_devices // (cand.hosts * cand.model_shards)
    n_client_shards = cand.hosts * n_cs
    padded, step_clients, cohort, cohort_mode = _plan_layout(
        C, n_client_shards, participation, cand.client_chunk
    )
    c_local = step_clients // n_client_shards
    if (
        cand.client_chunk is not None
        and cand.client_chunk < c_local
        and c_local % cand.client_chunk != 0
    ):
        return CandidateOutcome(cand, False, reject_reason=(
            f"client_chunk {cand.client_chunk} does not divide the "
            f"per-device client count {c_local}"
        ))
    if (
        cand.hosts > 1
        and cand.client_chunk is not None
        and cand.client_chunk > c_local
    ):
        # Single-host, an oversized chunk silently degrades to the full vmap
        # (the coordinator's documented fallback, mirrored by _plan_layout);
        # on a multi-host TOPOLOGY that silence would hide a real sizing
        # error — the chunk exceeds the per-device slice of the per-host
        # client shard, so the knob the operator asked for cannot engage
        # anywhere.  Reject, stated with both quantities.
        return CandidateOutcome(cand, False, reject_reason=(
            f"client_chunk {cand.client_chunk} exceeds the per-device client "
            f"count ({c_local} of the {c_local * n_cs}-client per-host client "
            f"shard on the hosts={cand.hosts} topology) — chunking would "
            "silently no-op; shrink the chunk or the hosts axis"
        ))

    if cand.adapter_rank is not None and adapter is None:
        return CandidateOutcome(cand, False, reject_reason=(
            f"adapter_rank {cand.adapter_rank} swept without an adapter= spec "
            "— the tuner needs the target patterns to build the adapter tree"
        ))

    # --- Build + lower (compile; nothing executes) ---------------------------
    training_c = dc.replace(training, batch_size=cand.batch_size)
    if cand.hosts > 1:
        mesh = make_mesh(shape=(cand.hosts, n_cs, cand.model_shards))
    elif cand.model_shards > 1:
        mesh = make_mesh(shape=(n_cs, cand.model_shards))
    else:
        mesh = make_mesh()
    base_abs = jax.eval_shape(lambda: model.init(jax.random.key(0)))
    frozen_base = None
    base_sds = None
    if cand.adapter_rank is not None:
        from nanofed_tpu.adapters import (
            init_adapters,
            make_adapter_apply,
        )
        from nanofed_tpu.parallel.round_step import FrozenBase

        spec_r = dc.replace(adapter, rank=cand.adapter_rank)
        # The federated tree IS the adapter tree at this rank; the base enters
        # the lowered signature as the read-only frozen input, model-sharded in
        # the candidate's layout — the costed program is the dispatched one.
        # init_adapters only reads shapes from the base tree, so it accepts the
        # abstract base directly; the (tiny) concrete A/B arrays it returns are
        # reduced to ShapeDtypeStructs below like every other lowering input.
        params_abs = init_adapters(spec_r, base_abs, rng=0)
        frozen_base = FrozenBase(
            base_like=base_abs,
            bind=lambda bf: make_adapter_apply(model.apply, spec_r, bf),
        )
    else:
        params_abs = base_abs
    strategy = fedavg_strategy()
    sos_abs = jax.eval_shape(lambda p: init_server_state(strategy, p), params_abs)

    def _sharded_sds(tree, sharding_tree):
        return jax.tree.map(
            lambda leaf, sh: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh),
            tree, sharding_tree,
        )

    params_sds = _sharded_sds(params_abs, param_sharding(mesh, params_abs))
    sos_sds = _sharded_sds(sos_abs, param_sharding(mesh, sos_abs))
    if frozen_base is not None:
        base_sds = _sharded_sds(base_abs, param_sharding(mesh, base_abs))
    csh = client_sharding(mesh)

    def _data_sds(rows: int) -> ClientData:
        return ClientData(
            x=jax.ShapeDtypeStruct(
                (rows, cap, *population.sample_shape),
                jnp.dtype(population.x_dtype), sharding=csh,
            ),
            y=jax.ShapeDtypeStruct(
                (rows, cap), jnp.dtype(population.y_dtype), sharding=csh
            ),
            mask=jax.ShapeDtypeStruct(
                (rows, cap), jnp.dtype(population.mask_dtype), sharding=csh
            ),
        )

    name = candidate_program_name(cand)
    try:
        if cand.rounds_per_block == 1:
            fn = build_round_step(
                model.apply, training_c, mesh, strategy,
                client_chunk=cand.client_chunk, params_like=params_abs,
                donate=True, frozen_base=frozen_base,
            )
            rngs_sds = jax.eval_shape(
                lambda: stack_rngs(jax.random.key(0), step_clients)
            )
            args = (
                params_sds, sos_sds,
                *((base_sds,) if frozen_base is not None else ()),
                _data_sds(step_clients),
                jax.ShapeDtypeStruct((step_clients,), jnp.float32),
                rngs_sds, jax.ShapeDtypeStruct((), jnp.float32),
            )
        else:
            rpb = cand.rounds_per_block
            fn = build_round_block(
                model.apply, training_c, mesh, strategy,
                num_clients=C, padded_clients=padded,
                step_clients=step_clients, cohort_size=cohort,
                client_chunk=cand.client_chunk, params_like=params_abs,
                collect_client_detail=False, cohort_mode=cohort_mode,
                donate=True, frozen_base=frozen_base,
            )
            keys_sds = jax.eval_shape(
                lambda: stack_round_keys(0, list(range(rpb)))
            )
            idx_sds = (
                jax.ShapeDtypeStruct((rpb, step_clients), jnp.int32)
                if cohort_mode else None
            )
            args = (
                params_sds, sos_sds, _data_sds(padded),
                jax.ShapeDtypeStruct((padded,), jnp.float32),
                keys_sds, jax.ShapeDtypeStruct((rpb,), jnp.float32),
                idx_sds,
                jax.ShapeDtypeStruct((rpb, step_clients), jnp.float32),
                # The inner jit's last positional: the frozen base (None on
                # dense candidates — an empty pytree to the lowering).
                base_sds,
            )
        report = profile_program(
            name, fn, *args, rounds=cand.rounds_per_block,
            attrs=cand.to_dict(),
        )
    except Exception as e:  # a candidate that cannot lower is rejected, not fatal
        return CandidateOutcome(
            cand, False, reject_reason=f"lowering/compile failed: {e}"
        )

    rounds = report.rounds
    cost = {
        "flops_per_round": report.flops / rounds,
        "bytes_accessed_per_round": report.bytes_accessed / rounds,
        "peak_bytes": report.peak_bytes,
        "arithmetic_intensity": round(report.arithmetic_intensity, 4),
        "verdict": report.verdict,
        "compile_seconds": round(report.compile_seconds, 4),
        "step_clients": step_clients,
        "cohort_mode": cohort_mode,
    }
    if report.lower_bound_s is not None:
        cost["lower_bound_s_per_round"] = report.lower_bound_s / rounds

    if budget is not None and report.peak_bytes > budget:
        return CandidateOutcome(cand, False, reject_reason=(
            f"memory_analysis peak {report.peak_bytes:,} bytes exceeds the "
            f"device HBM budget {budget:,} bytes"
        ), cost=cost)

    if report.peaks is not None:
        score = report.lower_bound_s / rounds
    else:
        score = report.bytes_accessed / rounds
    return CandidateOutcome(cand, True, score=score, cost=cost)


def _scoring_basis(platform: str, has_peaks: bool, peaks_basis: str | None) -> str:
    if has_peaks:
        return (
            "achievable walltime per round: the roofline lower bound "
            "max(flops/peak_flops, bytes_accessed/peak_bandwidth) of the "
            f"per-device program, divided by its rounds ({peaks_basis})"
        )
    return (
        "bytes-accessed ordering: compiler cost_analysis bytes accessed per "
        f"round, lower is better — platform={platform!r} has no published "
        "peaks, so this is a relative ordering, NOT a predicted walltime"
    )


def autotune(
    model: Any,
    population: PopulationSpec | Any,
    training: Any = None,
    *,
    participation: float = 1.0,
    num_rounds: int = 1,
    eval_every: int = 0,
    space: TuningSpace | None = None,
    hbm_budget_bytes: int | None = None,
    cache_dir: str | Path | None = ".jax_cache",
    out_dir: str | Path | None = "runs",
    telemetry: Any = None,
    force: bool = False,
    include_epilogues: bool = True,
    adapter: Any = None,
    compile_budget_s: float | None = None,
    candidate_deadline_s: float | None = None,
) -> AutotuneResult:
    """Sweep the round-program configuration space with the compiler's cost
    model; returns the ranked :class:`AutotuneResult` (winner first).

    ``population`` is a :class:`PopulationSpec` or a ``ClientData`` (shapes are
    taken, data is never touched).  Zero round programs execute: every candidate
    is lowered AOT with abstract arguments.  Results are cached under
    ``cache_dir`` keyed by (model fingerprint, population, space, training dims,
    device kind/count) — a cache hit compiles nothing; ``force=True`` re-sweeps.
    Raises :class:`AutotuneError` when every candidate is rejected (the artifact
    is still written first).

    ``adapter`` (an :class:`~nanofed_tpu.adapters.AdapterSpec`) engages the
    parameter-efficient axis: the default space sweeps LoRA rank over the
    ladder {rank/2, rank, 2*rank}, every candidate lowers the frozen-base
    round program (the federated tree is the adapter tree, the base a
    read-only model-sharded input), and the epilogue cost table is sized to
    the ADAPTER payload (the flattened client stack the q8 dequant-accumulate
    epilogue would actually reduce in adapter mode).

    The sweep is compile-budget aware (the r14 wedge postmortem): candidates
    compile in :func:`order_by_predicted_compile_cost` order (cheapest first);
    ``compile_budget_s`` (env ``NANOFED_AUTOTUNE_COMPILE_BUDGET``) caps the
    RUNNING compile-seconds total — once spent, remaining candidates are
    recorded ``skipped: compile_budget`` instead of compiled; and
    ``candidate_deadline_s`` (env ``NANOFED_AUTOTUNE_CANDIDATE_DEADLINE``)
    bounds each single compile — a candidate that blows it is recorded as the
    sweep's ``wedged_at`` and the rest are skipped (XLA compiles cannot be
    preempted, so the wedged compile finishes in a daemon thread while the
    sweep returns what it has).  Both default to unbounded.
    """
    import jax

    from nanofed_tpu.trainer.config import TrainingConfig

    training = training or TrainingConfig()
    if not isinstance(population, PopulationSpec):
        population = PopulationSpec.from_client_data(population)
    devices = jax.devices()
    platform = str(devices[0].platform)
    device_kind = str(getattr(devices[0], "device_kind", platform))
    n_devices = len(devices)
    if space is None:
        # TuningSpace.default owns the multi-process hosts-axis rule AND the
        # adapter-rank ladder rule.
        space = TuningSpace.default(
            population, n_devices, training.batch_size, num_rounds,
            adapter_rank=adapter.rank if adapter is not None else None,
        )
    budget, budget_basis = resolve_hbm_budget(hbm_budget_bytes, devices)
    key = compute_cache_key(
        model, population, training, space, participation, num_rounds,
        eval_every, device_kind, n_devices, hbm_budget=budget,
        adapter=adapter,
    )

    cache_path = (
        Path(cache_dir) / f"autotune_{key[:16]}.json"
        if cache_dir is not None else None
    )
    if cache_path is not None and not force:
        cached = _read_cache(cache_path, key)
        # A winnerless entry is never written (below), but guard anyway: a
        # cache hit must not short-circuit the all-rejected AutotuneError.
        if cached is not None and cached.winner is not None:
            cached.cache_hit = True
            cached.compiles = 0
            _log.info(
                "autotune cache hit (%s): winner %s, zero compiles",
                cache_path, cached.winner.to_dict(),
            )
            _finish(cached, out_dir, telemetry)
            return cached
    if compile_budget_s is None:
        env_budget = os.environ.get("NANOFED_AUTOTUNE_COMPILE_BUDGET")
        compile_budget_s = float(env_budget) if env_budget else None
    if candidate_deadline_s is None:
        env_deadline = os.environ.get("NANOFED_AUTOTUNE_CANDIDATE_DEADLINE")
        candidate_deadline_s = float(env_deadline) if env_deadline else None

    outcomes: list[CandidateOutcome] = []
    compiles = 0
    skipped = 0
    spent = 0.0
    wedged_at: str | None = None
    for cand in order_by_predicted_compile_cost(space.candidates()):
        if wedged_at is not None:
            skipped += 1
            outcomes.append(CandidateOutcome(cand, False, reject_reason=(
                f"skipped: compile_budget (sweep wedged at {wedged_at}, "
                f"{spent:.1f}s compile spent over {compiles} compiles)"
            )))
            continue
        if compile_budget_s is not None and spent >= compile_budget_s:
            skipped += 1
            outcomes.append(CandidateOutcome(cand, False, reject_reason=(
                f"skipped: compile_budget ({spent:.1f}s of the "
                f"{compile_budget_s:.1f}s compile budget spent over "
                f"{compiles} compiles)"
            )))
            continue
        if candidate_deadline_s is not None:
            # XLA compiles cannot be preempted: run the evaluation in a daemon
            # worker and give up waiting at the deadline.  A wedged compile
            # keeps burning its core in the background, but the SWEEP survives
            # with the candidates it already priced — the never-silent answer
            # to the r14 watchdog kill.
            import threading as _threading

            box: list[CandidateOutcome] = []

            def _work(cand=cand, box=box):
                box.append(_evaluate_candidate(
                    cand, model, population, training, participation,
                    num_rounds, eval_every, n_devices, budget, adapter=adapter,
                ))

            worker = _threading.Thread(target=_work, daemon=True)
            worker.start()
            worker.join(candidate_deadline_s)
            if not box:
                wedged_at = candidate_program_name(cand)
                outcome = CandidateOutcome(cand, False, reject_reason=(
                    f"wedged: compile exceeded the {candidate_deadline_s:.1f}s "
                    "candidate deadline"
                ), cost={"wedged_at": round(float(candidate_deadline_s), 4)})
            else:
                outcome = box[0]
        else:
            outcome = _evaluate_candidate(
                cand, model, population, training, participation, num_rounds,
                eval_every, n_devices, budget, adapter=adapter,
            )
        cand_compile_s = outcome.cost.get("compile_seconds")
        if cand_compile_s is not None:
            compiles += 1
            spent += float(cand_compile_s)
            if telemetry is not None:
                telemetry.record(
                    "compile", program=candidate_program_name(cand),
                    seconds=round(float(cand_compile_s), 4),
                    cache_key=key[:16],
                )
        outcomes.append(outcome)
        _log.info(
            "autotune candidate %s: %s",
            cand.to_dict(),
            (f"score {outcome.score:.4g}" if outcome.feasible
             else f"rejected ({outcome.reject_reason})"),
        )

    ranked = rank_candidates(outcomes)
    feasible = [o for o in ranked if o.feasible]
    has_peaks = any("lower_bound_s_per_round" in o.cost for o in feasible)
    peaks_basis = None
    if has_peaks:
        from nanofed_tpu.observability.profiling import peaks_for_device_kind

        peaks = peaks_for_device_kind(device_kind, platform)
        peaks_basis = peaks.basis if peaks is not None else None
    result = AutotuneResult(
        winner=feasible[0].config if feasible else None,
        outcomes=ranked,
        scoring_basis=_scoring_basis(platform, has_peaks, peaks_basis),
        platform=platform,
        device_kind=device_kind,
        num_devices=n_devices,
        hbm_budget_bytes=budget,
        budget_basis=budget_basis,
        cache_key=key,
        compiles=compiles,
        compile_seconds_total=math.fsum(
            o.cost.get("compile_seconds", 0.0) for o in outcomes
        ),
        compile_budget_s=compile_budget_s,
        skipped=skipped,
        wedged_at=wedged_at,
        space=space.to_dict(),
        population=population.to_dict(),
    )
    if include_epilogues:
        try:
            from nanofed_tpu.tuning.epilogues import profile_aggregation_epilogues

            base_abs = jax.eval_shape(lambda: model.init(jax.random.key(0)))
            if adapter is not None:
                # The epilogue's client stack in adapter mode is the ADAPTER
                # payload — the q8 dequant-accumulate row must be costed at
                # the bytes that actually cross the serving tier.
                from nanofed_tpu.adapters import init_adapters

                epilogue_tree = init_adapters(adapter, base_abs, rng=0)
            else:
                epilogue_tree = base_abs
            flat = sum(
                int(math.prod(leaf.shape) or 1)
                for leaf in jax.tree.leaves(epilogue_tree)
            )
            result.epilogues = profile_aggregation_epilogues(flat_size=flat)
        except Exception as e:  # the sweep result must not die on the side table
            result.epilogues = {"error": f"epilogue profiling failed: {e}"}

    if cache_path is not None and result.winner is not None and skipped == 0:
        # Failed (all-rejected) sweeps are never cached: a later invocation
        # must re-reject — and re-raise — rather than return winner=None.
        # Budget-truncated/wedged sweeps are not cached either — their winner
        # is the best of an INCOMPLETE table, and the re-sweep is cheap: the
        # already-compiled candidates hit the persistent XLA cache.
        _write_cache(cache_path, result)
    _finish(result, out_dir, telemetry)
    if result.winner is None:
        raise AutotuneError(
            "autotune found no feasible candidate: " + "; ".join(
                f"{o.config.to_dict()} -> {o.reject_reason}" for o in ranked
            )
        )
    _log.info(
        "autotune winner: %s (%s)", result.winner.to_dict(), result.scoring_basis
    )
    return result


def _read_cache(path: Path, key: str) -> AutotuneResult | None:
    try:
        with path.open() as f:
            d = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if d.get("cache_key") != key:
        return None
    try:
        return AutotuneResult.from_dict(d)
    except (KeyError, TypeError, ValueError):
        return None


def _write_cache(path: Path, result: AutotuneResult) -> None:
    """Best-effort (an unwritable cache dir must not fail the sweep)."""
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(result.to_dict(), indent=2))
        tmp.replace(path)
    except OSError as e:
        _log.warning("could not write autotune cache %s: %s", path, e)


def _finish(
    result: AutotuneResult, out_dir: str | Path | None, telemetry: Any
) -> None:
    """Emit the ranked-table artifact + the telemetry record (also on cache hits,
    so every invocation leaves a fresh auditable table under runs/)."""
    if out_dir is not None:
        from nanofed_tpu.utils.dates import get_current_time

        stamp = get_current_time().strftime("%Y%m%dT%H%M%S")
        path = Path(out_dir) / f"autotune_{stamp}_{result.cache_key[:8]}.json"
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(json.dumps(result.to_dict(), indent=2))
            result.artifact_path = str(path)
        except OSError as e:
            _log.warning("could not write autotune artifact %s: %s", path, e)
    if telemetry is not None:
        telemetry.record("autotune", **result.telemetry_payload())


def format_candidate_table(result: AutotuneResult) -> str:
    """Human-readable ranked table (what ``nanofed-tpu profile --sweep`` prints).
    The ``lora`` column is the adapter rank ("-" = dense full fine-tune)."""
    rows = [(
        "rank", "chunk", "rpb", "shards", "batch", "hosts", "lora", "score",
        "peak bytes", "verdict",
    )]
    for i, o in enumerate(result.outcomes):
        c = o.config
        rows.append((
            str(i + 1) if o.feasible else "-",
            str(c.client_chunk or "-"), str(c.rounds_per_block),
            str(c.model_shards), str(c.batch_size), str(c.hosts),
            str(c.adapter_rank or "-"),
            f"{o.score:.4g}" if o.score is not None else "-",
            f"{o.cost.get('peak_bytes', 0):,}" if o.cost else "-",
            o.cost.get("verdict", o.reject_reason or "-")
            if not o.feasible else o.cost.get("verdict", "-"),
        ))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines = []
    for j, row in enumerate(rows):
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip())
        if j == 0:
            lines.append("  ".join("-" * w for w in widths))
    lines.append("")
    lines.append(f"scoring basis: {result.scoring_basis}")
    lines.append(
        f"memory budget: "
        + (f"{result.hbm_budget_bytes:,} bytes" if result.hbm_budget_bytes
           else "none")
        + f" ({result.budget_basis})"
    )
    if result.winner is not None:
        lines.append(f"winner: {result.winner.to_dict()}")
    rejected = [o for o in result.outcomes if not o.feasible]
    for o in rejected:
        lines.append(f"rejected {o.config.to_dict()}: {o.reject_reason}")
    return "\n".join(lines)
