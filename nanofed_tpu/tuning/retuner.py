"""Closed-loop online retuning: measured round walltimes re-rank the AOT table.

The autotuner's scoring is honest about its own blindness (``autotuner.py``):
the AOT cost model cannot see the per-round HOST tax — dispatch, metrics
transfer, ``block_until_ready`` — so it breaks exact ties toward larger fused
blocks and hopes.  FL_PyTorch (arXiv:2202.03099) showed simulator
configuration is worth tuning at all; this module makes the tuning LEARN: an
:class:`OnlineRetuner` consumes the walltimes the coordinator actually
realizes per block (plus the ``nanofed_device_occupancy_ratio`` gauge), keeps
a measured seconds-per-round table alongside the AOT scores, and at
block boundaries proposes swapping the live round program for a candidate the
measurements rank higher.  Swap mechanics stay in the coordinator (the
existing ``ProgramCatalog`` register-replaces machinery); the retuner is pure
bookkeeping + decision, so every line of the control loop is unit-testable
without a single compile.

Calibration: with only the incumbent measured, an alternative's expected
walltime is estimated by scaling the incumbent's measured seconds-per-round by
the AOT score ratio (``est(c) = measured(cur) * score(c)/score(cur)``) — the
AOT model prices the DEVICE work it can see, the measurement supplies the
host tax it cannot.  Once a swap lands, the new incumbent's real measurements
replace the estimate.  A swap needs a :attr:`~OnlineRetuner.hysteresis`
relative win so measurement noise cannot flap programs (every swap costs one
compile unless the persistent cache holds the alternative).

Scope rule: only ``client_chunk``/``rounds_per_block`` are hot-swappable — the
mesh shape (hosts x model_shards), batch size, and adapter rank define the
sharded layouts of the params/data already resident on device; changing those
mid-run would reshard the world.  Ineligible candidates are recorded as such
in the decision's ``considered`` table, never silently dropped.

``write_back()`` stamps the measured numbers into the autotune cache entry
(``.jax_cache/autotune_<key16>.json``), so the NEXT run's cache hit starts
from reality instead of the roofline.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from nanofed_tpu.tuning.autotuner import (
    AutotuneResult,
    CandidateConfig,
    CandidateOutcome,
    candidate_program_name,
)
from nanofed_tpu.utils.logger import Logger

__all__ = ["OnlineRetuner", "RetuneDecision"]

_log = Logger()


@dataclass
class RetuneDecision:
    """One retune verdict: swap (``new is not None``) or hold, with the full
    measured/estimated basis so the telemetry record audits itself."""

    old: CandidateConfig
    new: CandidateConfig | None
    #: The incumbent's measured seconds per round (the basis everything else
    #: is compared against).
    measured_s_per_round: float
    #: The winner's estimated (or measured) seconds per round.
    candidate_s_per_round: float | None
    #: Fractional improvement the winner promises ((old-new)/old); None on hold.
    delta: float | None
    #: "measured" when the winner has its own measurements, "estimated (aot
    #: score x measured calibration)" otherwise.
    basis: str
    #: Why a hold held, stated ("no eligible alternative", "hysteresis", ...).
    reason: str | None = None
    #: Every candidate looked at: config, eligibility, estimate.
    considered: list[dict[str, Any]] = field(default_factory=list)

    @property
    def swap(self) -> bool:
        return self.new is not None

    def to_dict(self) -> dict[str, Any]:
        return {
            "swap": self.swap,
            "old": self.old.to_dict(),
            "new": self.new.to_dict() if self.new is not None else None,
            "old_program": candidate_program_name(self.old),
            **(
                {"new_program": candidate_program_name(self.new)}
                if self.new is not None else {}
            ),
            "measured_s_per_round": round(self.measured_s_per_round, 6),
            **(
                {"candidate_s_per_round": round(self.candidate_s_per_round, 6)}
                if self.candidate_s_per_round is not None else {}
            ),
            **({"delta": round(self.delta, 4)} if self.delta is not None else {}),
            "basis": self.basis,
            **({"reason": self.reason} if self.reason else {}),
            "considered": self.considered,
        }


@dataclass
class _Measurement:
    rounds: int = 0
    walltime_s: float = 0.0
    occupancy_sum: float = 0.0
    occupancy_n: int = 0

    @property
    def s_per_round(self) -> float | None:
        if self.rounds <= 0:
            return None
        return self.walltime_s / self.rounds

    @property
    def occupancy_mean(self) -> float | None:
        if self.occupancy_n <= 0:
            return None
        return self.occupancy_sum / self.occupancy_n

    def to_dict(self) -> dict[str, Any]:
        return {
            "rounds": self.rounds,
            "walltime_s": round(self.walltime_s, 6),
            "s_per_round": round(self.s_per_round, 6),
            **(
                {"occupancy_mean": round(self.occupancy_mean, 4)}
                if self.occupancy_mean is not None else {}
            ),
        }


class OnlineRetuner:
    """Measured-walltime re-ranking over an :class:`AutotuneResult`'s
    candidate table.

    The coordinator feeds :meth:`observe` one call per completed block (or
    single round) and asks :meth:`propose` at swap-safe boundaries; everything
    in between is arithmetic.  ``min_rounds`` guards against deciding off a
    single block's noise; ``hysteresis`` is the relative win an alternative
    must promise before a swap fires."""

    def __init__(
        self,
        result: AutotuneResult,
        *,
        hysteresis: float = 0.05,
        min_rounds: int = 2,
        cache_dir: str | Path | None = ".jax_cache",
    ) -> None:
        if not 0.0 <= hysteresis < 1.0:
            raise ValueError(f"hysteresis must be in [0, 1), got {hysteresis}")
        self.result = result
        self.hysteresis = float(hysteresis)
        self.min_rounds = int(min_rounds)
        self.cache_dir = Path(cache_dir) if cache_dir is not None else None
        self._measured: dict[CandidateConfig, _Measurement] = {}
        self.decisions: list[RetuneDecision] = []
        self._score: dict[CandidateConfig, float] = {
            o.config: float(o.score)
            for o in result.outcomes
            if o.feasible and o.score is not None
        }

    # ------------------------------------------------------------------ feed

    def observe(
        self,
        config: CandidateConfig,
        rounds: int,
        walltime_s: float,
        occupancy: float | None = None,
    ) -> None:
        """Accumulate one realized block: ``rounds`` rounds took
        ``walltime_s`` seconds under ``config`` (occupancy: the
        ``nanofed_device_occupancy_ratio`` gauge at the block boundary)."""
        if rounds <= 0 or not math.isfinite(walltime_s) or walltime_s < 0:
            return
        m = self._measured.setdefault(config, _Measurement())
        m.rounds += int(rounds)
        m.walltime_s += float(walltime_s)
        if occupancy is not None and math.isfinite(occupancy):
            m.occupancy_sum += float(occupancy)
            m.occupancy_n += 1

    def measured_s_per_round(self, config: CandidateConfig) -> float | None:
        m = self._measured.get(config)
        return m.s_per_round if m is not None else None

    # -------------------------------------------------------------- decision

    def _eligible(self, current: CandidateConfig, cand: CandidateConfig) -> str | None:
        """None when ``cand`` is hot-swappable from ``current``; otherwise the
        stated reason it is not."""
        if cand == current:
            return "incumbent"
        if (cand.hosts, cand.model_shards) != (current.hosts, current.model_shards):
            return "mesh shape differs (would reshard resident params/data)"
        if cand.batch_size != current.batch_size:
            return "batch size differs (would reshape the resident client data)"
        if cand.adapter_rank != current.adapter_rank:
            return "adapter rank differs (would rebuild the federated tree)"
        return None

    def _estimate(
        self, current: CandidateConfig, cand: CandidateConfig, cur_s: float,
    ) -> tuple[float, str] | None:
        """(seconds-per-round estimate, basis) for ``cand``, or None when the
        table holds nothing to estimate from."""
        own = self.measured_s_per_round(cand)
        if own is not None:
            return own, "measured"
        cur_score = self._score.get(current)
        cand_score = self._score.get(cand)
        if cur_score is None or cand_score is None or cur_score <= 0:
            return None
        return (
            cur_s * (cand_score / cur_score),
            "estimated (aot score x measured calibration)",
        )

    def propose(self, current: CandidateConfig) -> RetuneDecision:
        """The retune verdict for the incumbent ``current``, given everything
        observed so far.  Pure — recording/acting on the decision is the
        caller's job (the coordinator swaps at the next safe boundary)."""
        m = self._measured.get(current)
        cur_s = m.s_per_round if m is not None else None
        if cur_s is None or m.rounds < self.min_rounds:
            decision = RetuneDecision(
                old=current, new=None,
                measured_s_per_round=cur_s if cur_s is not None else float("nan"),
                candidate_s_per_round=None, delta=None, basis="measured",
                reason=(
                    f"insufficient measurements ({m.rounds if m else 0} rounds "
                    f"< min_rounds {self.min_rounds})"
                ),
            )
            self.decisions.append(decision)
            return decision

        considered: list[dict[str, Any]] = []
        best: tuple[float, str, CandidateConfig] | None = None
        for cand in sorted(self._score, key=lambda c: c.key):
            why_not = self._eligible(current, cand)
            row: dict[str, Any] = {"config": cand.to_dict()}
            if why_not is not None:
                row["ineligible"] = why_not
                considered.append(row)
                continue
            est = self._estimate(current, cand, cur_s)
            if est is None:
                row["ineligible"] = "no basis to estimate (unscored candidate)"
                considered.append(row)
                continue
            s, basis = est
            row["s_per_round"] = round(s, 6)
            row["basis"] = basis
            considered.append(row)
            if best is None or s < best[0]:
                best = (s, basis, cand)

        if best is None:
            decision = RetuneDecision(
                old=current, new=None, measured_s_per_round=cur_s,
                candidate_s_per_round=None, delta=None, basis="measured",
                reason="no eligible alternative", considered=considered,
            )
        else:
            s, basis, cand = best
            delta = (cur_s - s) / cur_s
            if s < cur_s * (1.0 - self.hysteresis):
                decision = RetuneDecision(
                    old=current, new=cand, measured_s_per_round=cur_s,
                    candidate_s_per_round=s, delta=delta, basis=basis,
                    considered=considered,
                )
            else:
                decision = RetuneDecision(
                    old=current, new=None, measured_s_per_round=cur_s,
                    candidate_s_per_round=s, delta=delta, basis=basis,
                    reason=(
                        f"hysteresis: best alternative wins {delta:+.1%}, "
                        f"needs > {self.hysteresis:.1%}"
                    ),
                    considered=considered,
                )
        self.decisions.append(decision)
        _log.info(
            "retune %s: %s",
            "SWAP" if decision.swap else "hold",
            (f"{candidate_program_name(decision.old)} -> "
             f"{candidate_program_name(decision.new)} ({decision.delta:+.1%})"
             if decision.swap else decision.reason),
        )
        return decision

    # ------------------------------------------------------------ write-back

    def measured_table(self) -> dict[str, dict[str, Any]]:
        """Program-name-keyed measured numbers (what lands in the cache entry
        and the run summary)."""
        return {
            candidate_program_name(c): m.to_dict()
            for c, m in sorted(
                self._measured.items(), key=lambda kv: kv[0].key
            )
            if m.rounds > 0
        }

    def write_back(self) -> Path | None:
        """Stamp measured seconds-per-round into the autotune cache entry so
        the next run's cache hit starts from measurements.  Each measured
        candidate's ``cost`` gains ``measured_s_per_round`` /
        ``measured_rounds`` (and occupancy); the entry gains a top-level
        ``measured`` block with the swap history.  Best-effort: returns the
        path written, or None (no cache dir / no entry / nothing measured —
        a foreign cache entry is never half-written)."""
        if self.cache_dir is None or not self._measured:
            return None
        path = self.cache_dir / f"autotune_{self.result.cache_key[:16]}.json"
        try:
            d = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            return None
        if d.get("cache_key") != self.result.cache_key:
            return None
        by_key = {
            CandidateConfig.from_dict(o["config"]): o
            for o in d.get("candidates", [])
        }
        for config, m in self._measured.items():
            row = by_key.get(config)
            if row is None or m.rounds <= 0:
                continue
            cost = row.setdefault("cost", {})
            cost["measured_s_per_round"] = round(m.s_per_round, 6)
            cost["measured_rounds"] = m.rounds
            if m.occupancy_mean is not None:
                cost["measured_occupancy_mean"] = round(m.occupancy_mean, 4)
        d["measured"] = {
            "basis": (
                "realized per-block round walltimes (host tax included), "
                "written back by OnlineRetuner"
            ),
            "table": self.measured_table(),
            "swaps": [
                dec.to_dict() for dec in self.decisions if dec.swap
            ],
        }
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(d, indent=2, sort_keys=True) + "\n")
        tmp.replace(path)
        return path

    # ------------------------------------------------------------- reporting

    def summary(self) -> dict[str, Any]:
        """The run-summary block: measurements, decisions, swap count."""
        swaps = [d for d in self.decisions if d.swap]
        return {
            "decisions": len(self.decisions),
            "swaps": len(swaps),
            "hysteresis": self.hysteresis,
            "measured": self.measured_table(),
            **(
                {"swap_history": [d.to_dict() for d in swaps]} if swaps else {}
            ),
        }


def outcome_for(result: AutotuneResult, config: CandidateConfig) -> CandidateOutcome | None:
    """The table row for ``config`` in ``result`` (None when absent)."""
    for o in result.outcomes:
        if o.config == config:
            return o
    return None
