"""Seedable noise generators for DP mechanisms.

Parity with the reference's generators (``nanofed/privacy/noise/generators.py:49-67``):
Gaussian and Laplacian noise with explicit seeds and input validation
(``validate_noise_input``, ``generators.py:14-46``).  The torch ``Generator`` seed becomes a
JAX PRNG key — callers thread keys explicitly, which is what makes per-client, per-step
noise independence auditable (``jax.random.split`` trees instead of a shared stateful RNG).

All generators work on whole pytrees, not single tensors: one call noises every leaf of a
model update with independent noise, deriving one subkey per leaf via ``jax.random.fold_in``.
"""

from __future__ import annotations

from typing import Protocol, Sequence

import jax
import jax.numpy as jnp

from nanofed_tpu.core.types import PRNGKey, PyTree


def validate_noise_input(shape: Sequence[int], scale: float | jax.Array) -> None:
    """Reject invalid shapes/scales (parity: ``generators.py:14-46``).

    Only host-side (concrete) scales are range-checked; traced scales are the caller's
    responsibility.
    """
    if any(int(d) < 0 for d in shape):
        raise ValueError(f"noise shape must be non-negative, got {tuple(shape)}")
    if isinstance(scale, (int, float)) and scale < 0:
        raise ValueError(f"noise scale must be >= 0, got {scale}")


class NoiseGenerator(Protocol):
    """Structural type of a noise source (parity: ``NoiseGenerator`` Protocol,
    ``nanofed/privacy/noise/base.py:9-31``)."""

    def sample(self, rng: PRNGKey, shape: Sequence[int], scale: float | jax.Array) -> jax.Array:
        """Draw noise of the given shape with standard deviation / scale ``scale``."""
        ...


class GaussianNoiseGenerator:
    """N(0, scale²) noise (parity: ``GaussianNoiseGenerator``, ``generators.py:49-54``)."""

    def sample(self, rng: PRNGKey, shape: Sequence[int], scale: float | jax.Array) -> jax.Array:
        validate_noise_input(shape, scale)
        return scale * jax.random.normal(rng, tuple(shape))


class LaplacianNoiseGenerator:
    """Laplace(0, scale) noise (parity: ``LaplacianNoiseGenerator``,
    ``generators.py:57-67``, which inverse-CDF-samples; ``jax.random.laplace`` is the
    native equivalent)."""

    def sample(self, rng: PRNGKey, shape: Sequence[int], scale: float | jax.Array) -> jax.Array:
        validate_noise_input(shape, scale)
        return scale * jax.random.laplace(rng, tuple(shape))


def get_noise_generator(noise_type) -> NoiseGenerator:
    """Factory keyed on ``NoiseType`` (or its string value)."""
    from nanofed_tpu.privacy.config import NoiseType

    key = NoiseType(noise_type) if not isinstance(noise_type, NoiseType) else noise_type
    if key is NoiseType.GAUSSIAN:
        return GaussianNoiseGenerator()
    return LaplacianNoiseGenerator()


def tree_noise(
    rng: PRNGKey, tree: PyTree, scale: float | jax.Array, generator: NoiseGenerator | None = None
) -> PyTree:
    """Independent noise matching each leaf of ``tree`` (std/scale = ``scale``).

    Derives one subkey per leaf with ``fold_in`` so the same ``rng`` never produces
    correlated noise across leaves.  Jit-compatible.
    """
    gen = generator or GaussianNoiseGenerator()
    leaves, treedef = jax.tree.flatten(tree)
    noised = [
        gen.sample(jax.random.fold_in(rng, i), leaf.shape, scale).astype(leaf.dtype)
        for i, leaf in enumerate(leaves)
    ]
    return jax.tree.unflatten(treedef, noised)


def tree_add_noise(
    rng: PRNGKey, tree: PyTree, scale: float | jax.Array, generator: NoiseGenerator | None = None
) -> PyTree:
    """``tree + noise`` in one call (the mechanism hot path)."""
    return jax.tree.map(jnp.add, tree, tree_noise(rng, tree, scale, generator))
