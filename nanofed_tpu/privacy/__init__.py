"""Differential-privacy subsystem: config, noise, accounting, mechanisms.

TPU-native re-design of ``nanofed/privacy/``: noise generation and clip+noise mechanisms
are pure jit/vmap-compatible functions on pytrees keyed by explicit PRNG keys; budget
accounting is host-side NumPy fed by event counts returned from compiled code.  DP-SGD
itself lives in ``nanofed_tpu.trainer.private``; privacy-aware aggregation in
``nanofed_tpu.aggregation.privacy``.
"""

from nanofed_tpu.privacy.accounting import (
    DEFAULT_RDP_ORDERS,
    BasePrivacyAccountant,
    GaussianAccountant,
    PrivacyAccountant,
    PrivacySpent,
    RDPAccountant,
    noise_multiplier_for_budget,
)
from nanofed_tpu.privacy.config import (
    MAX_DELTA,
    MAX_EPSILON,
    MIN_DELTA,
    MIN_EPSILON,
    NoiseType,
    PrivacyConfig,
    require_gaussian_accounting,
)
from nanofed_tpu.privacy.mechanisms import (
    PrivacyMechanism,
    PrivacyType,
    make_privacy_mechanism,
    privatize_stacked_updates,
)
from nanofed_tpu.privacy.noise import (
    GaussianNoiseGenerator,
    LaplacianNoiseGenerator,
    NoiseGenerator,
    get_noise_generator,
    tree_add_noise,
    tree_noise,
    validate_noise_input,
)

__all__ = [
    "DEFAULT_RDP_ORDERS",
    "MAX_DELTA",
    "MAX_EPSILON",
    "MIN_DELTA",
    "MIN_EPSILON",
    "BasePrivacyAccountant",
    "GaussianAccountant",
    "GaussianNoiseGenerator",
    "LaplacianNoiseGenerator",
    "NoiseGenerator",
    "NoiseType",
    "PrivacyAccountant",
    "PrivacyConfig",
    "PrivacyMechanism",
    "PrivacySpent",
    "PrivacyType",
    "RDPAccountant",
    "get_noise_generator",
    "make_privacy_mechanism",
    "noise_multiplier_for_budget",
    "privatize_stacked_updates",
    "tree_add_noise",
    "tree_noise",
    "validate_noise_input",
]
