"""Privacy budget accounting — pure host-side NumPy, off the jitted path.

Parity with the reference's accountant subsystem (``nanofed/privacy/accountant/``):

* ``PrivacySpent`` — frozen (ε, δ) record with validation (``accountant/base.py:8-20``).
* ``GaussianAccountant`` — per-event ε via the classic Gaussian-mechanism bound with
  sampling amplification, composed linearly (``accountant/gaussian.py:14-48``).
* ``RDPAccountant`` — Rényi DP accounting (Mironov 2017): per-event RDP over a grid of
  orders, additive composition, optimal RDP→(ε, δ) conversion
  (``accountant/rdp.py:41-115``).

The reference computes the sampling rate as ``samples / max_gradient_norm``
(``gaussian.py:23-25``, ``rdp.py:79-81``) — a quirk SURVEY.md flags as not-to-copy.  Here
``sampling_rate`` is the true subsampling probability q = batch_size / dataset_size,
supplied by the caller (the DP trainer knows both).

Accounting sits on the host because it is O(events) scalar math that must persist across
rounds — exactly what should NOT live in a compiled round step.  The jitted DP trainer
returns the *count* of noise events; the accountant ingests them afterwards.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Protocol, Sequence

import numpy as np

DEFAULT_RDP_ORDERS: tuple[float, ...] = tuple(
    [1.25, 1.5, 1.75, 2.0, 2.25, 2.5, 3.0, 3.5, 4.0, 4.5]
    + list(range(5, 64))
    + [128.0, 256.0, 512.0]
)


@dataclass(frozen=True, slots=True)
class PrivacySpent:
    """Cumulative privacy expenditure (parity: ``PrivacySpent``,
    ``nanofed/privacy/accountant/base.py:8-20``)."""

    epsilon_spent: float
    delta_spent: float

    def __post_init__(self) -> None:
        if self.epsilon_spent < 0:
            raise ValueError(f"epsilon_spent must be >= 0, got {self.epsilon_spent}")
        if not (0 <= self.delta_spent <= 1):
            raise ValueError(f"delta_spent must be in [0, 1], got {self.delta_spent}")

    def to_dict(self) -> dict[str, float]:
        return {"epsilon_spent": self.epsilon_spent, "delta_spent": self.delta_spent}

    @classmethod
    def from_dict(cls, d: dict[str, float]) -> "PrivacySpent":
        return cls(epsilon_spent=d["epsilon_spent"], delta_spent=d["delta_spent"])


class PrivacyAccountant(Protocol):
    """Structural type every accountant satisfies (parity: ``PrivacyAccountant`` Protocol,
    ``accountant/base.py:23-46``).

    ``state_dict``/``load_state_dict`` are part of the contract: the coordinator
    persists accounting history into round checkpoints so a resumed DP run reports the
    CUMULATIVE ε of the released model, not just the post-crash tail.
    """

    def add_noise_event(self, noise_multiplier: float, sampling_rate: float) -> None: ...

    def get_privacy_spent(self, delta: float) -> PrivacySpent: ...

    def state_dict(self) -> dict: ...

    def load_state_dict(self, state: dict) -> None: ...


class BasePrivacyAccountant:
    """Shared event log + budget validation (parity: ``BasePrivacyAccountant``,
    ``accountant/base.py:49-64``)."""

    def __init__(self) -> None:
        # (noise_multiplier, sampling_rate, count) — runs of identical events are collapsed
        # so 10k-step runs stay O(distinct configs), not O(steps).
        self._events: list[list[float]] = []

    @property
    def num_events(self) -> int:
        return int(sum(e[2] for e in self._events))

    def add_noise_event(
        self, noise_multiplier: float, sampling_rate: float, count: int = 1
    ) -> None:
        """Record ``count`` applications of the (σ, q) subsampled mechanism."""
        if noise_multiplier <= 0:
            raise ValueError(f"noise_multiplier must be > 0, got {noise_multiplier}")
        if not (0 < sampling_rate <= 1):
            raise ValueError(f"sampling_rate must be in (0, 1], got {sampling_rate}")
        if count < 1:
            raise ValueError(f"count must be >= 1, got {count}")
        if self._events and self._events[-1][:2] == [noise_multiplier, sampling_rate]:
            self._events[-1][2] += count
        else:
            self._events.append([noise_multiplier, sampling_rate, float(count)])

    def get_privacy_spent(self, delta: float) -> PrivacySpent:  # pragma: no cover - abstract
        raise NotImplementedError

    def validate_budget(self, epsilon: float, delta: float) -> bool:
        """True iff spend so far fits inside (ε, δ) (parity: ``accountant/base.py:49-53``)."""
        spent = self.get_privacy_spent(delta)
        return spent.epsilon_spent <= epsilon and spent.delta_spent <= delta

    def reset(self) -> None:
        self._events.clear()

    def state_dict(self) -> dict:
        """Serializable state for checkpoint/resume (new capability: the reference's
        accountants lose their history on restart)."""
        return {"events": [list(e) for e in self._events]}

    def load_state_dict(self, state: dict) -> None:
        self._events = [list(e) for e in state["events"]]


class GaussianAccountant(BasePrivacyAccountant):
    """Basic composition of per-event ε from the classic Gaussian-mechanism bound.

    Per event: the unamplified Gaussian cost ε₀ = √(2·ln(1.25·k/δ)) / σ (from
    σ = √(2 ln 1.25/δ)·Δ/ε, Dwork & Roth) amplified by subsampling via the EXACT bound
    ε_i = ln(1 + q·(e^{ε₀} − 1)) — valid for every q in (0, 1], reducing to ε₀ at q=1
    and to q·ε₀ only in the small-ε₀ limit.  (The naive linear form q·ε₀ over-claims
    amplification whenever ε₀ is not small; the reference uses it unconditionally,
    ``accountant/gaussian.py:33-48``.)  Each of the k events is evaluated at δ/k so that
    basic composition of k (ε_i, q·δ/k ≤ δ/k) guarantees yields a true (Σ ε_i, δ)
    guarantee at the queried δ.  (Composing at fixed per-event δ and still reporting δ —
    what the reference does — is anti-conservative in δ.)  Loose but simple;
    ``RDPAccountant`` is the tight one.
    """

    def get_privacy_spent(self, delta: float) -> PrivacySpent:
        if not (0 < delta < 1):
            raise ValueError(f"delta must be in (0, 1), got {delta}")
        k = self.num_events
        if k == 0:
            return PrivacySpent(epsilon_spent=0.0, delta_spent=0.0)
        c = math.sqrt(2.0 * math.log(1.25 * k / delta))

        def amplified(eps0: float, q: float) -> float:
            if q >= 1.0:
                return eps0
            if eps0 > 700.0:  # expm1 overflows; use the exact large-eps0 asymptote
                return eps0 + math.log(q)
            return math.log1p(q * math.expm1(eps0))

        eps = sum(count * amplified(c / sigma, q) for sigma, q, count in self._events)
        return PrivacySpent(epsilon_spent=float(eps), delta_spent=delta)


def _log_binom(n: int, k: int) -> float:
    return math.lgamma(n + 1) - math.lgamma(k + 1) - math.lgamma(n - k + 1)


def sampled_gaussian_rdp(sigma: float, q: float, orders: np.ndarray) -> np.ndarray:
    """Per-order RDP of ONE Poisson-subsampled Gaussian release, exactly.

    q = 1 is the plain Gaussian mechanism: RDP(α) = α/(2σ²) at every order.  For q < 1
    the exact closed form (Mironov, Talwar & Zhang 2019, "Rényi Differential Privacy of
    the Sampled Gaussian Mechanism", Table 1 / §3.3 — the computation TF-privacy and
    Opacus ship) exists at integer α ≥ 2:

        RDP(α) = log( Σ_{k=0..α} C(α,k)·(1−q)^{α−k}·q^k·e^{(k²−k)/(2σ²)} ) / (α−1)

    Non-integer orders (and α < 2) get +inf for q < 1, which simply excludes them from
    the min in the (ε, δ) conversion — evaluating a subset of orders is always a valid
    bound.  The widely-used q²α/(2σ²) approximation is NOT applied anywhere: it is only
    valid for σ ≳ 1 and α ≪ σ²·ln(1/q), and outside that regime it under-reports spend
    (e.g. at σ=0.44, q=0.1 it claims ~50× less ε than this exact form).
    """
    if q >= 1.0:
        return orders / (2.0 * sigma * sigma)
    out = np.full(orders.shape, np.inf)
    lq, l1q = math.log(q), math.log1p(-q)
    inv2s2 = 1.0 / (2.0 * sigma * sigma)
    for i, alpha in enumerate(orders):
        a = int(alpha)
        if alpha != a or a < 2:
            continue
        terms = [
            _log_binom(a, k) + k * lq + (a - k) * l1q + (k * k - k) * inv2s2
            for k in range(a + 1)
        ]
        m = max(terms)
        log_a = m + math.log(sum(math.exp(t - m) for t in terms))
        out[i] = max(0.0, log_a) / (alpha - 1.0)
    return out


class RDPAccountant(BasePrivacyAccountant):
    """Rényi-DP accounting for the subsampled Gaussian mechanism.

    Per event: the EXACT sampled-Gaussian RDP (``sampled_gaussian_rdp``) — never the
    q²α/(2σ²) small-q approximation, which the reference uses unconditionally
    (``accountant/rdp.py:41-62``) and which over-claims amplification outside its
    σ ≳ 1 validity regime.  Composition is additive in RDP; conversion uses the
    standard bound ε(δ) = min_α [ RDP(α) + ln(1/δ)/(α-1) ] (``accountant/rdp.py:90-115``).

    Client/example subsampling here is Poisson-style; the coordinator's fixed-size
    uniform cohort is accounted at q = cohort/N, the standard approximation
    (McMahan et al. 2018).
    """

    def __init__(self, orders: Sequence[float] = DEFAULT_RDP_ORDERS) -> None:
        super().__init__()
        if any(a <= 1 for a in orders):
            raise ValueError("all RDP orders must be > 1")
        self._orders = np.asarray(sorted(orders), dtype=np.float64)

    @property
    def orders(self) -> np.ndarray:
        return self._orders.copy()

    def total_rdp(self) -> np.ndarray:
        """Composed RDP(α) across all recorded events, one value per order."""
        rdp = np.zeros_like(self._orders)
        for sigma, q, count in self._events:
            rdp += count * sampled_gaussian_rdp(sigma, q, self._orders)
        return rdp

    def get_privacy_spent(self, delta: float) -> PrivacySpent:
        if not (0 < delta < 1):
            raise ValueError(f"delta must be in (0, 1), got {delta}")
        if not self._events:
            return PrivacySpent(epsilon_spent=0.0, delta_spent=0.0)
        rdp = self.total_rdp()
        eps = rdp + math.log(1.0 / delta) / (self._orders - 1.0)
        return PrivacySpent(epsilon_spent=float(np.min(eps)), delta_spent=delta)

    def optimal_order(self, delta: float) -> float:
        """The order achieving the minimum in the RDP→DP conversion (diagnostic)."""
        rdp = self.total_rdp()
        eps = rdp + math.log(1.0 / delta) / (self._orders - 1.0)
        return float(self._orders[int(np.argmin(eps))])


def noise_multiplier_for_budget(
    epsilon: float,
    delta: float,
    sampling_rate: float,
    num_events: int,
    orders: Sequence[float] = DEFAULT_RDP_ORDERS,
) -> float:
    """Smallest σ (to 1e-3) such that ``num_events`` subsampled-Gaussian events at rate q
    stay within (ε, δ) under RDP accounting.  New capability — the reference makes users
    pick σ by hand.  Binary search over σ; monotone because RDP ∝ 1/σ².
    """
    if num_events < 1:
        raise ValueError("num_events must be >= 1")

    def spent(sigma: float) -> float:
        acc = RDPAccountant(orders)
        acc.add_noise_event(sigma, sampling_rate, count=num_events)
        return acc.get_privacy_spent(delta).epsilon_spent

    lo, hi = 1e-3, 1.0
    while spent(hi) > epsilon:
        hi *= 2.0
        if hi > 1e6:
            raise ValueError("no feasible noise multiplier below 1e6 for this budget")
    while hi - lo > 1e-3:
        mid = (lo + hi) / 2.0
        if spent(mid) > epsilon:
            lo = mid
        else:
            hi = mid
    return hi
