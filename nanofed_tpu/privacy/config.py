"""Privacy configuration with validated bounds.

Parity with the reference's pydantic config (``nanofed/privacy/config.py:24-86``) and its
bound constants (``nanofed/privacy/constants.py:3-10``): ε ∈ [0.01, 10], δ ∈ [1e-10, 0.1],
positive clipping norm and noise multiplier, Gaussian|Laplacian noise.  Implemented as a
frozen dataclass (hashable — it rides into ``jit`` as a static argument) instead of a
pydantic model.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

MIN_EPSILON = 0.01
MAX_EPSILON = 10.0
MIN_DELTA = 1e-10
MAX_DELTA = 0.1


class NoiseType(enum.Enum):
    """Noise distribution for DP mechanisms (parity: ``NoiseType``,
    ``nanofed/privacy/config.py:17-21``)."""

    GAUSSIAN = "gaussian"
    LAPLACIAN = "laplacian"


@dataclass(frozen=True, slots=True)
class PrivacyConfig:
    """Differential-privacy budget and mechanism parameters.

    ``epsilon``/``delta`` are the *target budget* the accountant validates against;
    ``max_gradient_norm`` is the clipping bound C; ``noise_multiplier`` is σ (noise std is
    σ·C).  Bounds match the reference's validated ranges.
    """

    epsilon: float = 1.0
    delta: float = 1e-5
    max_gradient_norm: float = 1.0
    noise_multiplier: float = 1.0
    noise_type: NoiseType = NoiseType.GAUSSIAN

    def __post_init__(self) -> None:
        if not (MIN_EPSILON <= self.epsilon <= MAX_EPSILON):
            raise ValueError(
                f"epsilon must be in [{MIN_EPSILON}, {MAX_EPSILON}], got {self.epsilon}"
            )
        if not (MIN_DELTA <= self.delta <= MAX_DELTA):
            raise ValueError(f"delta must be in [{MIN_DELTA}, {MAX_DELTA}], got {self.delta}")
        if self.max_gradient_norm <= 0:
            raise ValueError("max_gradient_norm must be > 0")
        if self.noise_multiplier <= 0:
            raise ValueError("noise_multiplier must be > 0")
        if not isinstance(self.noise_type, NoiseType):
            raise ValueError(f"noise_type must be a NoiseType, got {self.noise_type!r}")


def require_gaussian_accounting(privacy: PrivacyConfig) -> None:
    """Reject accounting for non-Gaussian noise.

    The Gaussian/RDP accountants bound only the Gaussian mechanism; feeding them
    Laplacian events would report a meaningless (ε, δ).  (The reference silently does
    exactly that — ``nanofed/privacy/accountant/gaussian.py`` has no mechanism check;
    a quirk deliberately not carried over.)
    """
    from nanofed_tpu.core.exceptions import PrivacyError

    if privacy.noise_type is not NoiseType.GAUSSIAN:
        raise PrivacyError(
            f"privacy accounting supports only NoiseType.GAUSSIAN, got "
            f"{privacy.noise_type}; Laplacian noise has no accountant in this framework"
        )
