"""Central and local DP mechanisms over whole model pytrees.

Parity with ``nanofed/privacy/mechanisms.py``: clip a model update to a global-norm bound
then add calibrated noise (``mechanisms.py:85-129``), with a central variant (applied
server-side to each client's update before aggregation) and a local variant (applied
client-side; the reference forces batch_size=1 for it, ``mechanisms.py:148-158``).  Both
are pure jit-compatible functions here — the reference's stateful accounting side effect
is split out: mechanisms *return* the event they performed and the caller feeds the
accountant (keeps the compiled path functional).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import jax

from nanofed_tpu.core.types import PRNGKey, PyTree
from nanofed_tpu.privacy.accounting import BasePrivacyAccountant
from nanofed_tpu.privacy.config import PrivacyConfig, require_gaussian_accounting
from nanofed_tpu.privacy.noise import get_noise_generator, tree_add_noise
from nanofed_tpu.utils.trees import tree_clip_by_global_norm


class PrivacyType(enum.Enum):
    """Where the mechanism runs (parity: ``PrivacyType``, ``mechanisms.py:18-22``)."""

    CENTRAL = "central"
    LOCAL = "local"


@dataclass(frozen=True, slots=True)
class PrivacyMechanism:
    """A configured clip+noise mechanism.

    ``privatize`` is the pure hot path (jit/vmap-safe); ``record`` is the host-side
    accounting half.  ``batch_size`` enters the noise scale as σ·C/B, matching the
    reference's ``_compute_noise_scale`` (``mechanisms.py:77-83``); the local variant pins
    B=1 (``mechanisms.py:148-158``).
    """

    config: PrivacyConfig
    privacy_type: PrivacyType = PrivacyType.CENTRAL
    batch_size: int = 1

    def __post_init__(self) -> None:
        if self.batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if self.privacy_type is PrivacyType.LOCAL and self.batch_size != 1:
            raise ValueError("local DP uses batch_size=1 (each update is one user's data)")

    @property
    def noise_scale(self) -> float:
        return self.config.noise_multiplier * self.config.max_gradient_norm / self.batch_size

    def privatize(self, rng: PRNGKey, update: PyTree) -> PyTree:
        """Clip ``update`` to global norm C then add noise of scale σ·C/B.

        Parity: ``BasePrivacyMechanism.add_noise`` (``mechanisms.py:106-129``) minus the
        in-place accounting (see ``record``).
        """
        clipped, _ = tree_clip_by_global_norm(update, self.config.max_gradient_norm)
        gen = get_noise_generator(self.config.noise_type)
        return tree_add_noise(rng, clipped, self.noise_scale, gen)

    def record(
        self, accountant: BasePrivacyAccountant, sampling_rate: float = 1.0, count: int = 1
    ) -> None:
        """Feed ``count`` privatize calls into ``accountant`` (the host-side half of the
        reference's ``accountant.add_noise_event`` call inside ``add_noise``,
        ``mechanisms.py:119-121``)."""
        require_gaussian_accounting(self.config)
        accountant.add_noise_event(self.config.noise_multiplier, sampling_rate, count=count)


def make_privacy_mechanism(
    privacy_type: PrivacyType | str, config: PrivacyConfig, batch_size: int = 1
) -> PrivacyMechanism:
    """Factory (parity: ``PrivacyMechanismFactory.create``, ``mechanisms.py:161-174``)."""
    ptype = PrivacyType(privacy_type) if not isinstance(privacy_type, PrivacyType) else privacy_type
    if ptype is PrivacyType.LOCAL:
        return PrivacyMechanism(config=config, privacy_type=ptype, batch_size=1)
    return PrivacyMechanism(config=config, privacy_type=ptype, batch_size=batch_size)


def privatize_stacked_updates(
    rng: PRNGKey, stacked_params: PyTree, mechanism: PrivacyMechanism
) -> PyTree:
    """Central-DP the whole round in one shot: vmap ``privatize`` over the leading client
    axis with independent per-client keys.

    This is the TPU form of the reference's per-update loop in
    ``PrivacyAwareAggregator._process_central_updates`` (``aggregator/privacy.py:179-194``).
    """
    leaves = jax.tree.leaves(stacked_params)
    num_clients = leaves[0].shape[0]
    keys = jax.random.split(rng, num_clients)
    return jax.vmap(mechanism.privatize)(keys, stacked_params)
