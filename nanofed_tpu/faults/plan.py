"""Deterministic, seeded fault plans for the federation's failure modes.

ROADMAP items 1, 2, and 4c all make scale claims that presuppose failures —
flaky clients, overloaded servers, mid-round crashes — yet nothing in the repo
could *inject* one reproducibly.  This module is the missing half of every
robustness claim: a :class:`FaultPlan` is a frozen, JSON-serializable list of
fault events, either hand-written or drawn from a seed
(:meth:`FaultPlan.generate`), and a :class:`ChaosSchedule` is its consumable
runtime view — injection sites ask it "does a fault fire HERE, for THIS client,
in THIS round?" and every firing is counted in the metrics registry
(``nanofed_faults_injected_total{kind=...}``), so a chaos run's telemetry shows
exactly which failures it survived.

Fault kinds and their injection sites:

==============  ============================================================
kind            where it fires
==============  ============================================================
``crash``       scripted client loop / simulator cohort: the client stops
                participating from ``round`` on (``ChaosSchedule.crashed``)
``delay``       client boundary: ``seconds`` of extra latency before the
                round's submit (a straggler)
``skew``        client boundary: the submit's round header is shifted back by
                ``int(seconds)`` rounds — a clock-skewed straggler that
                exercises the server's stale-round 400 path
``corrupt``     client wire boundary: the submit body is bit-flipped in
                flight (``HTTPClient(wire_filter=...)``), exercising the
                server's bad-payload rejection
``duplicate``   client wire boundary: the last update is re-POSTed ``count``
                extra times with the SAME idempotency key (a retry storm),
                exercising the server's exactly-once dedupe
``drop``        server wire boundary (``HTTPServer(chaos=...)`` middleware):
                the connection is severed BEFORE the handler runs — the
                submit never happened; the client's RetryPolicy re-sends
``ack_drop``    server wire boundary: the handler runs (the update IS
                buffered) and the connection is severed before the response —
                the lost-ACK case idempotent submit keys exist for
``server_kill`` the ``NetworkCoordinator`` round loop: raises
                :class:`InjectedServerCrash` mid-round; recovery is the
                ``persistence.state_store`` resume path
``host_crash``  host boundary (``faults.host_injector.HostChaosInjector``
                inside a multi-host worker): the worker PROCESS exits
                mid-round — its peers surface the loss through the
                ``parallel.resilience`` watchdog/heartbeats and the
                supervisor re-forms the mesh over the survivors
``host_stall``  host boundary: the worker stops making progress but stays
                alive (heartbeats freeze, collectives never complete) — the
                failure mode a liveness check cannot see and only a
                deadline-bracketed dispatch can
``dcn_degrade`` host boundary: ``seconds`` of injected latency on this
                host's cross-host (DCN) exchanges for ``count`` rounds —
                degraded-but-alive inter-host links that must NOT trip the
                watchdog when the deadline is sized right
==============  ============================================================

Pure stdlib — importable by anything (the communication layer takes a schedule
duck-typed, so no import cycle).
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field, replace
from pathlib import Path
from typing import Any, Iterable

__all__ = [
    "FAULT_KINDS",
    "HOST_KINDS",
    "ChaosSchedule",
    "FaultEvent",
    "FaultPlan",
    "InjectedServerCrash",
]

FAULT_KINDS = (
    "crash", "delay", "skew", "corrupt", "duplicate", "drop", "ack_drop",
    "server_kill", "host_crash", "host_stall", "dcn_degrade",
)

#: Kinds the server-side wire middleware handles (everything else is a client-
#: boundary, host-boundary, or round-loop fault).
WIRE_KINDS = ("drop", "ack_drop", "delay")

#: Kinds targeting a whole HOST (a multi-host worker process) rather than one
#: client or the server: consumed by ``faults.host_injector`` inside the
#: worker, detected by ``parallel.resilience`` on the surviving peers.
HOST_KINDS = ("host_crash", "host_stall", "dcn_degrade")


class InjectedServerCrash(RuntimeError):
    """A ``server_kill`` fault firing in the round loop.

    Subclasses ``RuntimeError`` so ``persistence.state_store.is_recoverable``
    treats it exactly like a real crash: ``run_fault_tolerant`` (or the chaos
    harness) rebuilds the server + coordinator from the state store and the
    run resumes at the checkpointed round.
    """


@dataclass(frozen=True)
class FaultEvent:
    """One fault: ``kind`` fires against ``client`` in ``round``.

    ``seconds`` parameterizes ``delay`` (latency), ``skew`` (rounds of header
    skew, as an int), and ``dcn_degrade`` (injected cross-host latency);
    ``count`` is how many times a one-shot wire fault fires
    (``drop``/``ack_drop``), how many extra duplicates are sent, or how many
    rounds a ``dcn_degrade`` persists.  ``client`` is None for ``server_kill``
    and the host kinds; the host kinds instead carry ``host`` — the hosts-axis
    row (== ``jax.process_index`` at launch) the fault targets.  Simulator
    clients are ints, network clients strings — both are stored as given and
    compared as given.
    """

    kind: str
    round: int
    client: str | int | None = None
    seconds: float = 0.0
    count: int = 1
    host: int | None = None

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} (choose from {FAULT_KINDS})")
        if self.round < 0:
            raise ValueError("round must be >= 0")
        if self.count < 1:
            raise ValueError("count must be >= 1")
        if self.seconds < 0:
            raise ValueError("seconds must be >= 0")
        if self.kind == "server_kill" and self.client is not None:
            raise ValueError("server_kill is not a per-client fault")
        if self.kind in HOST_KINDS:
            if self.host is None:
                raise ValueError(f"{self.kind} needs a target host")
            if self.host < 0:
                raise ValueError("host must be >= 0")
            if self.client is not None:
                raise ValueError(f"{self.kind} is not a per-client fault")
        elif self.host is not None:
            raise ValueError(f"{self.kind} does not take a host")

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {"kind": self.kind, "round": self.round}
        if self.client is not None:
            d["client"] = self.client
        if self.seconds:
            d["seconds"] = self.seconds
        if self.count != 1:
            d["count"] = self.count
        if self.host is not None:
            d["host"] = self.host
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "FaultEvent":
        return cls(
            kind=str(d["kind"]),
            round=int(d["round"]),
            client=d.get("client"),
            seconds=float(d.get("seconds", 0.0)),
            count=int(d.get("count", 1)),
            host=None if d.get("host") is None else int(d["host"]),
        )


@dataclass(frozen=True)
class FaultPlan:
    """A frozen, seeded, JSON-serializable fault schedule.

    The ``seed`` is carried even for hand-written plans so the run artifact
    records which schedule produced it; :meth:`generate` draws a plan FROM the
    seed, making "round completes despite f crashes" a reproducible claim
    rather than a lucky run.
    """

    seed: int = 0
    events: tuple[FaultEvent, ...] = field(default_factory=tuple)

    def __post_init__(self) -> None:
        object.__setattr__(self, "events", tuple(self.events))

    @classmethod
    def generate(
        cls,
        seed: int,
        clients: Iterable[str | int],
        num_rounds: int,
        *,
        crash_fraction: float = 0.0,
        straggler_fraction: float = 0.0,
        straggler_delay_s: float = 1.0,
        drop_fraction: float = 0.0,
        duplicate_fraction: float = 0.0,
        corrupt_fraction: float = 0.0,
        server_kill_round: int | None = None,
        hosts: int = 0,
        host_crash_count: int = 0,
        host_stall_count: int = 0,
        dcn_degrade_fraction: float = 0.0,
        dcn_delay_s: float = 0.5,
    ) -> "FaultPlan":
        """Draw a plan from ``seed``: each ``*_fraction`` of the client
        population is assigned that fault at a seeded round.  Crashes land in
        the first half of the run (so the survival claim covers most rounds);
        wire faults are spread uniformly.  With ``hosts`` > 0 the host-boundary
        kinds draw too: ``host_crash_count``/``host_stall_count`` hosts (never
        the same host twice — a run must keep a quorum to recover INTO) fail at
        seeded mid-run rounds, and ``dcn_degrade_fraction`` of the hosts get
        ``dcn_delay_s`` of injected cross-host latency at a seeded round.
        Deterministic: the same arguments always yield the same plan."""
        rng = random.Random(seed)
        pool = list(clients)
        events: list[FaultEvent] = []

        def pick(fraction: float) -> list[str | int]:
            k = round(fraction * len(pool))
            return rng.sample(pool, k) if k else []

        for cid in pick(crash_fraction):
            events.append(FaultEvent(
                kind="crash", round=rng.randrange(max(1, num_rounds // 2)),
                client=cid,
            ))
        for cid in pick(straggler_fraction):
            events.append(FaultEvent(
                kind="delay", round=rng.randrange(num_rounds), client=cid,
                seconds=straggler_delay_s,
            ))
        for kind, fraction in (("drop", drop_fraction),
                               ("duplicate", duplicate_fraction),
                               ("corrupt", corrupt_fraction)):
            for cid in pick(fraction):
                events.append(FaultEvent(
                    kind=kind, round=rng.randrange(num_rounds), client=cid,
                ))
        if server_kill_round is not None:
            events.append(FaultEvent(kind="server_kill", round=server_kill_round))
        if host_crash_count or host_stall_count or dcn_degrade_fraction:
            if hosts < 1:
                raise ValueError("host faults need hosts >= 1 in generate()")
            host_pool = list(range(hosts))
            n_fail = host_crash_count + host_stall_count
            if n_fail > len(host_pool):
                raise ValueError(
                    f"cannot fail {n_fail} of {hosts} hosts (each host fails "
                    "at most once per plan)"
                )
            failed = rng.sample(host_pool, n_fail)
            for i, h in enumerate(failed):
                kind = "host_crash" if i < host_crash_count else "host_stall"
                # Mid-run like client crashes: rounds [1, num_rounds/2] so the
                # recovered mesh still has most of the run left to prove itself.
                events.append(FaultEvent(
                    kind=kind, round=1 + rng.randrange(max(1, num_rounds // 2)),
                    host=h,
                ))
            n_dcn = round(dcn_degrade_fraction * hosts)
            for h in rng.sample(host_pool, n_dcn) if n_dcn else []:
                events.append(FaultEvent(
                    kind="dcn_degrade", round=rng.randrange(num_rounds),
                    host=h, seconds=dcn_delay_s,
                ))
        events.sort(key=lambda e: (e.round, e.kind, str(e.client), -1 if e.host is None else e.host))
        return cls(seed=seed, events=tuple(events))

    # -- serialization ---------------------------------------------------

    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "events": [e.to_dict() for e in self.events]},
            indent=2,
        )

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        d = json.loads(text)
        return cls(
            seed=int(d.get("seed", 0)),
            events=tuple(FaultEvent.from_dict(e) for e in d.get("events", [])),
        )

    @classmethod
    def load(cls, path: str | Path) -> "FaultPlan":
        return cls.from_json(Path(path).read_text())

    def save(self, path: str | Path) -> None:
        Path(path).write_text(self.to_json())

    def with_events(self, *events: FaultEvent) -> "FaultPlan":
        return replace(self, events=(*self.events, *events))


class ChaosSchedule:
    """The consumable runtime view of a :class:`FaultPlan`.

    Injection sites query it; one-shot events (``drop``/``ack_drop``/
    ``duplicate``/``server_kill``) are CONSUMED as they fire, so a retried
    request meets the fault ``count`` times and then passes — which is exactly
    the semantics a retry policy must be proven against.  Every firing
    increments ``nanofed_faults_injected_total{kind=...}`` in the given
    registry (default: the process-wide one), so ``/metrics`` and
    ``telemetry.jsonl`` show which faults a run actually absorbed.

    Single-event-loop use only (like everything in ``communication``): no
    internal locking.
    """

    def __init__(self, plan: FaultPlan, registry: Any | None = None) -> None:
        from nanofed_tpu.observability.registry import get_registry

        self.plan = plan
        self._fired: dict[int, int] = {}  # event index -> times fired
        self._m_faults = (registry or get_registry()).counter(
            "nanofed_faults_injected_total",
            "Chaos-schedule faults actually fired, by kind",
            labels=("kind",),
        )

    def _take(self, index: int, event: FaultEvent) -> bool:
        """Consume one firing of a counted event; False once exhausted."""
        fired = self._fired.get(index, 0)
        if fired >= event.count:
            return False
        self._fired[index] = fired + 1
        self._m_faults.inc(kind=event.kind)
        return True

    # -- client-boundary queries -----------------------------------------

    def crashed(self, client: str | int, round_number: int) -> bool:
        """True when the plan crashed ``client`` at or before this round
        (crashes are permanent: a crashed client never reports again)."""
        for i, e in enumerate(self.plan.events):
            if e.kind == "crash" and e.client == client and e.round <= round_number:
                if self._fired.get(i, 0) == 0:
                    self._fired[i] = 1
                    self._m_faults.inc(kind="crash")
                return True
        return False

    def client_events(self, client: str | int, round_number: int) -> list[FaultEvent]:
        """The client-boundary faults (delay/skew/corrupt/duplicate) firing for
        this client's submit this round.  Each event applies to ONE logical
        submit and is consumed on return (a ``duplicate`` event's ``count`` is
        how many duplicates that submit sends, not how many submits it
        haunts)."""
        out = []
        for i, e in enumerate(self.plan.events):
            if e.client != client or e.round != round_number:
                continue
            if e.kind not in ("delay", "skew", "corrupt", "duplicate"):
                continue
            if self._fired.get(i, 0) == 0:
                self._fired[i] = 1
                self._m_faults.inc(kind=e.kind)
                out.append(e)
        return out

    # -- server-boundary queries -----------------------------------------

    def wire_fault(
        self, client: str | None, round_header: str | None
    ) -> FaultEvent | None:
        """The wire fault (drop/ack_drop/delay-at-server) to apply to THIS
        request, or None.  One-shot kinds are consumed per firing: a dropped
        request's retry gets through once ``count`` attempts have been
        severed."""
        if client is None:
            return None
        try:
            rnd = int(round_header) if round_header is not None else None
        except ValueError:
            rnd = None
        for i, e in enumerate(self.plan.events):
            if e.kind not in WIRE_KINDS or e.client != client:
                continue
            if rnd is not None and e.round != rnd:
                continue
            if self._take(i, e):
                return e
        return None

    # -- round-loop queries ----------------------------------------------

    def take_server_kill(self, round_number: int) -> bool:
        """True exactly once when the plan kills the server in this round."""
        for i, e in enumerate(self.plan.events):
            if e.kind == "server_kill" and e.round == round_number:
                if self._take(i, e):
                    return True
        return False

    # -- host-boundary queries (faults.host_injector) ---------------------

    def take_host_fault(self, host: int, round_number: int) -> FaultEvent | None:
        """The terminal host fault (``host_crash``/``host_stall``) firing
        against ``host`` at or before this round, consumed exactly once — a
        worker that survived its scheduled round (e.g. it was down for other
        reasons) still dies at the next boundary check, matching the permanent
        semantics of client ``crash``."""
        for i, e in enumerate(self.plan.events):
            if e.kind not in ("host_crash", "host_stall"):
                continue
            if e.host != host or e.round > round_number:
                continue
            if self._take(i, e):
                return e
        return None

    def dcn_delay(self, host: int, round_number: int) -> float:
        """Injected cross-host (DCN) latency for ``host`` this round: the sum
        of the ``dcn_degrade`` events covering it.  An event with ``count`` N
        degrades N consecutive dispatches starting at its round, each firing
        consumed (and counted) separately."""
        total = 0.0
        for i, e in enumerate(self.plan.events):
            if e.kind != "dcn_degrade" or e.host != host:
                continue
            if not (e.round <= round_number < e.round + e.count):
                continue
            if self._take(i, e):
                total += e.seconds
        return total

    def counts(self) -> dict[str, int]:
        """Fired-fault totals by kind (for run records / assertions)."""
        out: dict[str, int] = {}
        for i, n in self._fired.items():
            kind = self.plan.events[i].kind
            out[kind] = out.get(kind, 0) + n
        return out
