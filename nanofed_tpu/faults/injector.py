"""Client-boundary fault injection: a scripted federation client under a plan.

``ChaosClient`` wraps an ``HTTPClient`` and consults a :class:`ChaosSchedule`
before every submit, applying the client-side fault kinds exactly where a real
flaky client would produce them:

* ``crash``      — ``alive(round)`` turns False; the driving loop exits, which
  is what a crashed process looks like to the server (silence).
* ``delay``      — extra latency (via the injected clock) before the submit:
  a straggler that may or may not beat the round timeout.
* ``skew``       — the submit's round header is shifted back ``int(seconds)``
  rounds: a clock-skewed straggler, answered by the server's stale-round 400
  (and, for topk8 clients, folded by the ``_pending_base`` error-feedback
  contract — nothing is lost, the mass rides the next round).
* ``corrupt``    — the wire body is bit-flipped after signing
  (``HTTPClient(wire_filter=...)``): the server must reject it, never
  aggregate it.
* ``duplicate``  — the last update is re-POSTed with the SAME idempotency key
  ``count`` extra times: the retry storm the server's dedupe must fold at most
  once.

The wrapper deliberately does NOT re-implement the client protocol: training,
encoding, signing, retrying are all the real ``HTTPClient``'s — chaos only
perturbs the boundary, so what the tests prove is the production path.
"""

from __future__ import annotations

from typing import Any

from nanofed_tpu.communication.http_client import HTTPClient
from nanofed_tpu.faults.plan import ChaosSchedule
from nanofed_tpu.utils.clock import SYSTEM_CLOCK, Clock
from nanofed_tpu.utils.logger import Logger

__all__ = ["ChaosClient"]


def _flip_bits(body: bytes) -> bytes:
    """Deterministically corrupt a wire body (every 97th byte XOR 0xFF — enough
    to break any codec's structure, independent of payload size)."""
    out = bytearray(body)
    for i in range(0, len(out), 97):
        out[i] ^= 0xFF
    return bytes(out)


class ChaosClient:
    """Drives one ``HTTPClient`` through a fault plan.

    Use as a thin layer in a scripted client loop::

        chaos = ChaosClient(client, schedule, clock=clock)
        while chaos.alive(round_number):
            params, rnd, active = await client.fetch_global_model(like=template)
            ...train...
            await chaos.submit(trained, metrics, rnd)
    """

    def __init__(
        self,
        client: HTTPClient,
        schedule: ChaosSchedule,
        clock: Clock | None = None,
    ) -> None:
        self.client = client
        self.schedule = schedule
        self._clock = clock or SYSTEM_CLOCK
        self._log = Logger()

    def alive(self, round_number: int) -> bool:
        """False once the plan has crashed this client (permanently)."""
        return not self.schedule.crashed(self.client.client_id, round_number)

    async def submit(
        self, params: Any, metrics: dict[str, Any], round_number: int
    ) -> bool:
        """One logical submit with this round's planned faults applied."""
        events = self.schedule.client_events(self.client.client_id, round_number)
        delay = sum(e.seconds for e in events if e.kind == "delay")
        skew = next((int(e.seconds) for e in events if e.kind == "skew"), 0)
        corrupt = any(e.kind == "corrupt" for e in events)
        duplicates = sum(e.count for e in events if e.kind == "duplicate")
        if delay:
            self._log.info("chaos: %s straggling %.3fs in round %d",
                           self.client.client_id, delay, round_number)
            await self._clock.sleep(delay)
        if skew:
            # A skewed client BELIEVES it is on an older round: shift the header
            # the submit will carry.  Left skewed afterwards on purpose — the
            # client's next fetch_global_model resets current_round, exactly
            # like a real client re-syncing.
            self.client.current_round = round_number - skew
        previous_filter = self.client.wire_filter
        if corrupt:
            self.client.wire_filter = lambda endpoint, body: _flip_bits(body)
        try:
            ok = await self.client.submit_update(params, metrics)
        finally:
            self.client.wire_filter = previous_filter
        for _ in range(duplicates):
            # The retry storm: identical bytes, identical idempotency key.
            await self.client.resend_last_update()
        return ok
