"""Fault injection + resilience harness (PR 6's chaos subsystem).

Deterministic, seeded failure schedules (:class:`FaultPlan` /
:class:`ChaosSchedule`) injectable at three boundaries — the HTTP client
(:class:`ChaosClient`), the HTTP server wire (``HTTPServer(chaos=...)``
middleware), and the round loop (``NetworkCoordinator(chaos=...)`` raising
:class:`InjectedServerCrash`) — plus the production mechanisms they exercise:
client ``RetryPolicy`` backoff (``communication.retry``), server admission
control (429 + Retry-After), idempotent submit keys, straggler eviction, and
state-store crash recovery.  See docs/robustness.md.

``plan`` is pure stdlib; ``injector`` needs the ``[net]`` extra (aiohttp) and
is imported lazily.
"""

from nanofed_tpu.faults.host_injector import HostChaosInjector
from nanofed_tpu.faults.plan import (
    FAULT_KINDS,
    HOST_KINDS,
    ChaosSchedule,
    FaultEvent,
    FaultPlan,
    InjectedServerCrash,
)

__all__ = [
    "FAULT_KINDS",
    "HOST_KINDS",
    "ChaosClient",
    "ChaosSchedule",
    "FaultEvent",
    "FaultPlan",
    "HostChaosInjector",
    "InjectedServerCrash",
]


def __getattr__(name: str):
    if name == "ChaosClient":
        from nanofed_tpu.faults.injector import ChaosClient

        return ChaosClient
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
