"""Host-boundary fault injection: one multi-host WORKER under a plan.

``HostChaosInjector`` is the hosts-axis sibling of :class:`ChaosClient`: where
that wrapper perturbs one HTTP client's submits, this one perturbs one
``jax.distributed`` worker process's round loop, applying the host fault kinds
exactly where a real failing host would produce them:

* ``host_crash``  — the process exits immediately (``os._exit``, no cleanup,
  no Python teardown): to every peer this is indistinguishable from a kernel
  panic or preemption — sockets drop, heartbeats freeze, the in-flight gloo
  collective never completes.
* ``host_stall``  — the process stops making progress but STAYS ALIVE
  (``stall_now`` returns True and the worker parks in a sleep loop, never
  dispatching, never heartbeating): the failure mode liveness probes cannot
  see, detectable only by frozen heartbeat sequence numbers and by the
  collective watchdog's deadline on the peers.
* ``dcn_degrade`` — ``seconds`` of injected latency before this host's
  cross-host exchange for ``count`` rounds: a degraded-but-alive DCN link
  that must NOT trip a correctly-sized watchdog deadline.

Like ``ChaosClient``, the injector does not re-implement anything: the worker
asks it three questions per round and the production round program runs
untouched (traced code never sees the chaos — ``--strict``/fedlint clean).

Pure stdlib, importable by the harness worker before JAX initializes.
"""

from __future__ import annotations

import os
import time as _time

from nanofed_tpu.faults.plan import ChaosSchedule, FaultEvent

__all__ = ["HostChaosInjector"]

#: The exit code an injected ``host_crash`` dies with — distinctive, so the
#: supervisor can tell a planned kill from an organic worker bug in telemetry
#: (both recover the same way).
HOST_CRASH_EXIT_CODE = 31


class HostChaosInjector:
    """Drives one worker process through the host faults of a plan.

    Use at the top of the worker's round loop::

        injector = HostChaosInjector(schedule, host=process_id)
        for r in range(rounds):
            injector.maybe_fail(r)        # may os._exit / park forever
            clock.sleep(injector.dcn_delay_s(r))   # degraded DCN link
            ...watchdogged dispatch...
    """

    def __init__(self, schedule: ChaosSchedule, host: int) -> None:
        self.schedule = schedule
        self.host = int(host)

    # -- queries (side-effect-free beyond schedule consumption) -----------

    def take_fault(self, round_number: int) -> FaultEvent | None:
        """The terminal fault (``host_crash``/``host_stall``) due for this
        host at this round, consumed exactly once; None otherwise."""
        return self.schedule.take_host_fault(self.host, round_number)

    def dcn_delay_s(self, round_number: int) -> float:
        """Injected cross-host latency to apply before this round's dispatch."""
        return self.schedule.dcn_delay(self.host, round_number)

    # -- the actual boundary action ---------------------------------------

    def maybe_fail(self, round_number: int) -> None:
        """Apply the terminal fault due this round, if any: ``host_crash``
        exits the process with :data:`HOST_CRASH_EXIT_CODE`; ``host_stall``
        parks forever (alive, silent).  Returns normally when no fault fires."""
        event = self.take_fault(round_number)
        if event is None:
            return
        if event.kind == "host_crash":
            # No cleanup on purpose: atexit/finally handlers would make the
            # death look tidier than a real host loss.
            os._exit(HOST_CRASH_EXIT_CODE)
        # host_stall: alive but silent, forever.  Plain time.sleep (not the
        # injectable clock): a stalled host's time is nobody's schedule.
        while True:  # pragma: no cover - only the peers' watchdog ends this
            _time.sleep(3600)
