"""nanofed_tpu — a TPU-native federated learning framework.

A ground-up re-design of the capabilities of NanoFed (camille-004/nanofed) for JAX/XLA:
clients are a named mesh axis, local SGD runs under ``jit``+``vmap``, and FedAvg is a
``psum``-weighted mean over ICI instead of JSON over HTTP.  See SURVEY.md for the full
mapping to the reference.
"""

from nanofed_tpu.core import (
    ClientData,
    ClientMetrics,
    ClientUpdates,
    ModelUpdate,
    ModelVersion,
    NanoFedError,
)
from nanofed_tpu.utils import Logger, LogConfig, get_current_time, log_exec

__version__ = "0.4.0"

__all__ = [
    "ClientData",
    "ClientMetrics",
    "ClientUpdates",
    "LogConfig",
    "Logger",
    "ModelUpdate",
    "ModelVersion",
    "NanoFedError",
    "__version__",
    "get_current_time",
    "log_exec",
]
