"""Command-line interface.

The reference declares a CLI entry point that doesn't exist (``pyproject.toml:22-23`` names
``nanofed.cli:main`` but no module is shipped — SURVEY.md layer-map quirks).  This one is
real: ``run`` drives a simulated federated experiment (``--dp-epsilon`` engages
budget-calibrated central DP), ``serve`` hosts the real-network federation server
(``--secure`` for masked rounds, ``--validate`` for update validation), ``bench`` runs
the BASELINE.json suite, ``profile`` compiles the round programs WITHOUT running a
federation and prints the compiler's cost/roofline table, ``info`` prints environment
and model-zoo facts.
"""

from __future__ import annotations

import argparse
import json
import sys


def _cmd_info(_args: argparse.Namespace) -> int:
    import jax

    from nanofed_tpu import __version__
    from nanofed_tpu.models import list_models

    print(
        json.dumps(
            {
                "version": __version__,
                "jax": jax.__version__,
                "backend": jax.default_backend(),
                "devices": [str(d) for d in jax.devices()],
                "models": list_models(),
            },
            indent=2,
        )
    )
    return 0


def _cmd_run(args: argparse.Namespace) -> int:
    from nanofed_tpu.experiments import run_experiment

    if ((args.robust_trim is not None or args.robust_method is not None)
            and args.dp_epsilon is not None):
        # build_round_step refuses the combination too, but with a traceback; the
        # CLI should say why up front (the DP budget is calibrated for the clipped
        # uniform mean — a trimmed mean has a different sensitivity).
        print("error: --robust-trim cannot be combined with --dp-epsilon — the DP "
              "guarantee is calibrated for the clipped mean; a trimmed mean has a "
              "different sensitivity and the stated budget would be wrong",
              file=sys.stderr)
        return 2
    if args.scaffold and (
        args.dp_epsilon is not None
        or args.robust_trim is not None
        or args.robust_method is not None
    ):
        # Same up-front courtesy as above: the Coordinator refuses these too, with
        # a traceback (the control estimate is computed from the un-noised,
        # un-trimmed local trajectory).
        print("error: --scaffold cannot be combined with --dp-epsilon, "
              "--robust-trim, or --robust-method — DP noise / robust "
              "trimming/selection would bias the control estimate every later "
              "round relies on", file=sys.stderr)
        return 2

    central_privacy = None
    if args.dp_epsilon is not None:
        from nanofed_tpu.aggregation.privacy import PrivacyAwareAggregationConfig
        from nanofed_tpu.privacy import PrivacyConfig
        from nanofed_tpu.privacy.accounting import noise_multiplier_for_budget

        from nanofed_tpu.orchestration.types import cohort_size

        # Calibrate at the realized per-client inclusion probability (the coordinator
        # accounts spend at cohort/N, which ceil+floor make >= the nominal rate) so the
        # run actually spends the requested budget instead of over-noising.
        cohort = cohort_size(args.clients, args.participation)
        try:
            sigma = noise_multiplier_for_budget(
                args.dp_epsilon, args.dp_delta, sampling_rate=cohort / args.clients,
                num_events=args.rounds,
            )
            central_privacy = PrivacyAwareAggregationConfig(
                privacy=PrivacyConfig(
                    epsilon=args.dp_epsilon, delta=args.dp_delta,
                    max_gradient_norm=args.dp_clip, noise_multiplier=sigma,
                )
            )
        except ValueError as e:
            # Config bounds (eps in [0.01, 10], delta in [1e-10, 0.1]) or an
            # infeasible budget — a CLI error, not a traceback.
            print(f"error: invalid DP budget: {e}", file=sys.stderr)
            return 2
        print(f"# central DP: sigma={sigma:.4f} calibrated for "
              f"(eps={args.dp_epsilon}, delta={args.dp_delta}) over {args.rounds} "
              "rounds (tight RDP accounting)", file=sys.stderr)

    if args.retune_every > 0 and not args.autotune:
        print("error: --retune-every requires --autotune — the online retuner "
              "re-ranks the sweep's candidate table; without a sweep there is "
              "no table", file=sys.stderr)
        return 2

    if args.autotune:
        pinned = [
            flag for flag, engaged in (
                ("--client-chunk", args.client_chunk is not None),
                ("--rounds-per-block", args.rounds_per_block != 1),
                ("--model-shards", args.model_shards != 1),
                ("--hosts", args.hosts != 1),
            ) if engaged
        ]
        if pinned:
            # The tuner owns the swept knobs; a half-pinned sweep would silently
            # override the operator's explicit choice (or vice versa).
            print(f"error: --autotune cannot be combined with "
                  f"{', '.join(pinned)} — the cost-model sweep picks those "
                  "knobs; drop --autotune to set them by hand",
                  file=sys.stderr)
            return 2

    if args.distributed:
        # Activate jax.distributed BEFORE any backend init: afterwards
        # jax.devices() is the GLOBAL device list and --hosts can span real
        # processes.  Configuration rides the JAX_COORDINATOR_ADDRESS /
        # JAX_NUM_PROCESSES / JAX_PROCESS_ID env (or TPU-pod auto-detection)
        # — see parallel.initialize_distributed.
        from nanofed_tpu.parallel import initialize_distributed

        try:
            info = initialize_distributed()
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(f"# distributed: process {info['process_index']} of "
              f"{info['process_count']}", file=sys.stderr)
        if info["process_count"] > 1:
            # The Coordinator is single-controller: its host-built round
            # inputs (cohort slot arrays, weights, rng stacks) are committed
            # process-local arrays a multi-process sharding rejects at the
            # first dispatch.  Refuse up front with the working alternative
            # instead of failing round 1 with an XLA placement error.
            print(
                "error: `run` drives the single-controller Coordinator, "
                "which cannot feed a multi-process mesh (its host-built "
                "round inputs are process-local). Drive real multi-process "
                "rounds with scripts/multihost_harness.py: `federate` runs "
                "the full stack (a wire listener + ingest buffer per host "
                "draining into one cross-host psum per round), "
                "`smoke`/`bench` drive the simulated-client hierarchical "
                "program; single-process `--hosts N` exercises the same "
                "hierarchy on virtual hosts.",
                file=sys.stderr,
            )
            return 2

    if args.model_shards != 1 or args.hosts != 1:
        # Same up-front courtesy as the other invalid combinations: validate
        # against the device count HERE (the one place that forces backend
        # init) so the error is a CLI message, not a traceback —
        # run_experiment re-runs the identical shared validator.
        import jax

        from nanofed_tpu.parallel import mesh_shape_for_topology

        try:
            mesh_shape_for_topology(
                args.hosts, args.model_shards, len(jax.devices())
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    metrics = run_experiment(
        model=args.model,
        num_clients=args.clients,
        num_rounds=args.rounds,
        local_epochs=args.epochs,
        batch_size=args.batch_size,
        learning_rate=args.lr,
        scheme=args.scheme,
        participation=args.participation,
        data_dir=args.data_dir,
        out_dir=args.out_dir,
        seed=args.seed,
        train_size=args.train_size,
        client_chunk=args.client_chunk,
        compute_dtype=args.dtype,
        central_privacy=central_privacy,
        lr_schedule=args.lr_schedule,
        lr_min_factor=args.lr_min_factor,
        lr_decay_every=args.lr_decay_every,
        lr_decay_gamma=args.lr_decay_gamma,
        robust_trim_k=args.robust_trim,
        robust_method=args.robust_method,
        scaffold=args.scaffold,
        telemetry_dir=args.telemetry_dir,
        rounds_per_block=args.rounds_per_block,
        client_metrics_every=args.client_metrics_every,
        model_shards=args.model_shards,
        hosts=args.hosts,
        strict=args.strict,
        profile_programs=args.profile_programs,
        autotune=args.autotune,
        retune_every=args.retune_every,
        adapter_rank=args.adapter_rank,
        adapter_alpha=args.adapter_alpha,
    )
    print(json.dumps(metrics, indent=2, default=str))
    return 0


def _cmd_sweep(args: argparse.Namespace) -> int:
    """``profile --sweep``: run the compile-only autotune sweep (nanofed_tpu.
    tuning) — lower every candidate round-program configuration, score it with
    the compiler's cost model, and print the ranked table plus the fused-
    epilogue bytes-accessed comparison.  Zero round executions; the full table
    lands as ``<out-dir>/autotune_*.json`` and the sweep result is cached under
    ``.jax_cache/`` so a repeat sweep compiles nothing."""
    from nanofed_tpu.data import federate
    from nanofed_tpu.experiments import load_datasets_for
    from nanofed_tpu.models import get_model
    from nanofed_tpu.trainer import TrainingConfig
    from nanofed_tpu.tuning import (
        AutotuneError,
        PopulationSpec,
        TuningSpace,
        autotune,
        format_candidate_table,
    )

    mdl = get_model(args.model)
    train, _ = load_datasets_for(mdl, args.data_dir, args.train_size, args.seed)
    client_data = federate(
        train, num_clients=args.clients, scheme="iid",
        batch_size=args.batch_size, seed=args.seed,
    )
    training = TrainingConfig(
        batch_size=args.batch_size, local_epochs=args.epochs,
        learning_rate=args.lr, compute_dtype=args.dtype,
    )
    pop = PopulationSpec.from_client_data(client_data)
    num_rounds = max(args.rounds_per_block, 8)
    adapter = None
    if args.adapter_rank is not None:
        from nanofed_tpu.adapters import AdapterSpec

        adapter = AdapterSpec(rank=args.adapter_rank)
    # Explicit --client-chunk / --model-shards pin that axis of the sweep to a
    # single value (the same "pin via a single-valued space" mechanism
    # Coordinator.from_autotune documents) — never silently ignored.
    pins = {}
    if args.client_chunk is not None:
        pins["client_chunks"] = (args.client_chunk,)
    if args.model_shards != 1:
        pins["model_shards"] = (args.model_shards,)
    if args.hosts != 1:
        pins["hosts"] = (args.hosts,)
    space = None
    if pins:
        import dataclasses

        import jax

        # TuningSpace.default owns the multi-process hosts-axis rule AND the
        # adapter-rank ladder, so a pin on one knob cannot silently flatten
        # the other axes.
        space = dataclasses.replace(
            TuningSpace.default(
                pop, len(jax.devices()), training.batch_size, num_rounds,
                adapter_rank=args.adapter_rank,
            ),
            **pins,
        )
    telemetry = None
    if args.telemetry_dir is not None:
        from nanofed_tpu.observability import RunTelemetry

        telemetry = RunTelemetry(args.telemetry_dir)
    try:
        result = autotune(
            mdl, pop, training,
            participation=args.participation,
            num_rounds=num_rounds,
            space=space,
            telemetry=telemetry,
            force=args.force_sweep,
            adapter=adapter,
        )
    except AutotuneError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        if telemetry is not None:
            telemetry.close()
    if args.json:
        print(json.dumps(result.to_dict(), indent=2))
        return 0
    print(format_candidate_table(result))
    epi = result.epilogues
    if epi and "error" not in epi:
        print()
        for path in ("q8", "validated"):
            cmp = epi[path]
            pct = cmp.get("bytes_accessed_reduction_pct")
            print(
                f"{path} epilogue: fused {cmp['fused_bytes_accessed']:,.0f} "
                f"bytes vs unfused {cmp['unfused_bytes_accessed']:,.0f} bytes"
                + (f" ({pct:+.1f}% reduction)" if pct is not None else "")
            )
        print(f"epilogue basis: {epi['basis']}")
    if result.cache_hit:
        print("\n(cache hit: zero compiles this invocation)")
    if result.artifact_path:
        print(f"ranked table written to {result.artifact_path}")
    return 0


def _cmd_profile(args: argparse.Namespace) -> int:
    """Compile the round programs — single step, fused block, SCAFFOLD — WITHOUT
    running a federation, and print what the COMPILER says each costs: XLA
    ``cost_analysis`` FLOPs, peak device bytes, arithmetic intensity, and the
    roofline verdict against the platform's peaks table (see
    ``observability.profiling`` and docs/performance.md)."""
    if args.sweep:
        return _cmd_sweep(args)

    import jax

    from nanofed_tpu.data import federate
    from nanofed_tpu.experiments import load_datasets_for
    from nanofed_tpu.models import get_model
    from nanofed_tpu.observability import format_cost_table
    from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig
    from nanofed_tpu.parallel import mesh_shape_for_topology
    from nanofed_tpu.trainer import TrainingConfig

    try:
        mesh_shape = mesh_shape_for_topology(
            args.hosts, args.model_shards, len(jax.devices())
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    mdl = get_model(args.model)
    train, _ = load_datasets_for(mdl, args.data_dir, args.train_size, args.seed)
    client_data = federate(
        train, num_clients=args.clients, scheme="iid",
        batch_size=args.batch_size, seed=args.seed,
    )
    training = TrainingConfig(
        batch_size=args.batch_size, local_epochs=args.epochs,
        learning_rate=args.lr, compute_dtype=args.dtype,
    )

    adapter = None
    if args.adapter_rank is not None:
        from nanofed_tpu.adapters import AdapterSpec

        adapter = AdapterSpec(rank=args.adapter_rank)

    def build(scaffold: bool, rounds_per_block: int) -> Coordinator:
        # save_metrics=False: profiling must leave no run artifacts behind
        # (telemetry lands only where --telemetry-dir points).  num_rounds
        # merely has to admit the block length — nothing ever runs.
        return Coordinator(
            model=mdl, train_data=client_data,
            config=CoordinatorConfig(
                num_rounds=max(1, rounds_per_block),
                participation_rate=args.participation,
                seed=args.seed, save_metrics=False,
                rounds_per_block=rounds_per_block,
            ),
            training=training, scaffold=scaffold,
            client_chunk=args.client_chunk, mesh_shape=mesh_shape,
            telemetry_dir=args.telemetry_dir,
            adapter=None if scaffold else adapter,
        )

    reports = []
    coordinators = [build(scaffold=False, rounds_per_block=args.rounds_per_block)]
    if not args.no_scaffold and adapter is None:
        # The SCAFFOLD program is a different ROUND program (control-variate
        # state flows through it), so it gets its own coordinator + report.
        # Skipped in adapter mode: adapter SCAFFOLD is refused by construction.
        coordinators.append(build(scaffold=True, rounds_per_block=1))
    for coord in coordinators:
        reports.extend(coord.profile_programs())
        if coord.telemetry is not None:
            coord.telemetry.close()

    if args.json:
        print(json.dumps([r.to_dict() for r in reports], indent=2))
    else:
        print(format_cost_table(reports))
    return 0 if reports else 1


def _cmd_serve(args: argparse.Namespace) -> int:
    """Host a real-network federation server (the reference's HTTPServer+Coordinator
    pair, ``examples/mnist/run_experiment.py:89-131``, as one command)."""
    import asyncio

    import jax

    from nanofed_tpu.communication import HTTPServer, NetworkCoordinator, NetworkRoundConfig
    from nanofed_tpu.models import get_model

    if args.secure and args.validate:
        # Masked vectors are unvalidatable by construction (uniform uint32); a server
        # operator must not believe norm/z-score checks run when they cannot.
        print("error: --validate cannot be combined with --secure — masked updates "
              "are indistinguishable from noise; range enforcement in secure mode "
              "comes from quantization bounds and client-side DP clipping",
              file=sys.stderr)
        return 2

    if args.dropout_tolerant and not args.secure:
        print("error: --dropout-tolerant requires --secure (it is a secure-"
              "aggregation mode)", file=sys.stderr)
        return 2

    if args.ingest_batch is not None and args.validate:
        # The coordinator refuses this too, with a traceback; say why up front
        # (per-update validation needs individual update trees, which batched
        # ingest folds into the device buffer at submit time).
        print("error: --ingest-batch cannot be combined with --validate — "
              "batched ingest folds updates into a device buffer at submit "
              "time, so per-update shape/norm/z-score checks have nothing to "
              "inspect", file=sys.stderr)
        return 2
    if args.ingest_batch is None and (
        args.ingest_capacity is not None or args.decode_workers is not None
    ):
        print("error: --ingest-capacity/--decode-workers only apply with "
              "--ingest-batch (they size the batched ingest pipeline)",
              file=sys.stderr)
        return 2

    ingest = None
    if args.ingest_batch is not None:
        from nanofed_tpu.ingest import IngestConfig

        capacity = (
            args.ingest_capacity if args.ingest_capacity is not None else 1024
        )
        try:
            ingest = IngestConfig(
                capacity=capacity,
                batch_size=min(args.ingest_batch, capacity),
                decode_workers=(
                    args.decode_workers
                    if args.decode_workers is not None else 4
                ),
            )
        except ValueError as e:
            print(f"error: invalid ingest config: {e}", file=sys.stderr)
            return 2

    if args.async_buffer is not None:
        # Sync-only cohort flags are meaningless under FedBuff (no cohort barrier:
        # aggregations fire on buffer fill, and the buffer size IS --async-buffer);
        # silently accepting them would let an operator believe a completion gate
        # or enrollment cap is active when nothing reads it — same courtesy as the
        # --staleness-window refusal below.
        explicit = [
            flag for flag, value in (
                ("--min-clients", args.min_clients),
                ("--completion-rate", args.completion_rate),
                ("--max-clients", args.max_clients),
            ) if value is not None
        ]
        if explicit:
            print(f"error: {', '.join(explicit)} only appl"
                  f"{'ies' if len(explicit) == 1 else 'y'} to synchronous cohort "
                  "rounds — asynchronous --async-buffer mode has no cohort "
                  "barrier (aggregations fire when K updates are buffered)",
                  file=sys.stderr)
            return 2
    min_clients = args.min_clients if args.min_clients is not None else 1
    completion_rate = (
        args.completion_rate if args.completion_rate is not None else 1.0
    )

    if args.max_clients is not None and not args.dropout_tolerant:
        # Only the tolerant enrollment window reads the cap; silently ignoring it
        # would let an operator believe a larger cohort can enroll when the
        # exact-cohort path caps at min_clients.
        print("error: --max-clients only applies to the --dropout-tolerant "
              "enrollment window (plain --secure cohorts are exactly "
              "--min-clients)", file=sys.stderr)
        return 2

    if args.max_clients is not None and args.max_clients < min_clients:
        print(f"error: --max-clients ({args.max_clients}) must be >= --min-clients "
              f"({min_clients}) — reaching the cap freezes the enrollment "
              "window, which would close below the minimum", file=sys.stderr)
        return 2

    if args.async_buffer is not None and (args.secure or args.validate):
        # The coordinator refuses these too, with a traceback; say why up front.
        print("error: --async-buffer cannot be combined with --secure or "
              "--validate — asynchronous aggregation mixes staleness levels "
              "these round-locked mechanisms assume away", file=sys.stderr)
        return 2
    if args.async_buffer is not None and args.async_buffer < 1:
        print("error: --async-buffer must be >= 1", file=sys.stderr)
        return 2
    if args.async_buffer is not None and args.staleness_window is not None \
            and args.staleness_window < 1:
        print("error: --staleness-window must be >= 1 in async mode",
              file=sys.stderr)
        return 2
    if args.staleness_window is not None and args.async_buffer is None:
        # Same courtesy as --max-clients: a flag only async mode reads must not
        # be silently ignored — the operator would believe a window is active.
        print("error: --staleness-window only applies with --async-buffer",
              file=sys.stderr)
        return 2

    chaos = None
    if args.chaos_plan is not None:
        from nanofed_tpu.faults import ChaosSchedule, FaultPlan

        try:
            chaos = ChaosSchedule(FaultPlan.load(args.chaos_plan))
        except (OSError, ValueError, KeyError) as e:
            print(f"error: could not load chaos plan {args.chaos_plan!r}: {e}",
                  file=sys.stderr)
            return 2

    model = get_model(args.model)
    params = model.init(jax.random.key(args.seed))
    secure = None
    if args.secure:
        from nanofed_tpu.security.secure_agg import SecureAggregationConfig

        # Dropout-tolerant mode: the privacy floor must sit BELOW the enrolled cohort
        # size or the survivor gate fails every round that has a dropout — the whole
        # point of the mode.  One eviction's worth of slack mirrors the
        # secure-federation example; operators wanting more tolerance lower
        # --completion-rate.  The Shamir threshold is NOT wired here: it must exceed
        # half the cohort that ACTUALLY enrolls (split-view defense), so the
        # coordinator derives it when the enrollment window freezes the roster and
        # announces it to clients in the roster payload — a static value computed
        # from min_clients would be wrong for any larger roster.
        floor = (
            max(2, min_clients - 1) if args.dropout_tolerant
            else min_clients
        )
        secure = SecureAggregationConfig(
            min_clients=floor,
            dropout_tolerant=args.dropout_tolerant,
        )
    validation = None
    if args.validate:
        from nanofed_tpu.security.validation import ValidationConfig

        validation = ValidationConfig(max_norm=args.max_norm)

    state_store = None
    if args.state_dir is not None:
        from nanofed_tpu.persistence.state_store import FileStateStore

        state_store = FileStateStore(args.state_dir)

    async def serve() -> list[dict]:
        server = HTTPServer(
            host=args.host, port=args.port, max_inflight=args.max_inflight,
            chaos=chaos, ingest=ingest,
        )
        await server.start()
        try:
            coordinator = NetworkCoordinator(
                server, params,
                NetworkRoundConfig(
                    num_rounds=args.rounds,
                    min_clients=min_clients,
                    min_completion_rate=completion_rate,
                    round_timeout_s=args.timeout,
                    max_clients=args.max_clients,
                    straggler_evict_after=args.evict_stragglers,
                    async_buffer_k=args.async_buffer,
                    staleness_window=(
                        args.staleness_window
                        if args.staleness_window is not None else 4
                    ),
                ),
                validation=validation,
                secure=secure,
                telemetry_dir=args.telemetry_dir,
                state_store=state_store,
                chaos=chaos,
            )
            return await coordinator.run()
        finally:
            await server.stop()

    try:
        history = asyncio.run(serve())
    except TimeoutError as e:
        # Cohort never completed enrollment: keep the JSON-output contract.
        print(json.dumps([{"status": "FAILED", "error": str(e)}]))
        return 1
    except RuntimeError as e:
        from nanofed_tpu.faults import InjectedServerCrash

        if not isinstance(e, InjectedServerCrash):
            raise
        # A planned server kill: exactly what an operator's supervisor sees.
        # Re-running the same command with the same --state-dir resumes from
        # the last completed round's checkpoint.
        print(json.dumps([{
            "status": "CRASHED", "error": str(e),
            "resume": ("re-run with the same --state-dir to resume from the "
                       "last completed round" if args.state_dir is not None
                       else "no --state-dir: a restart would begin from round 0"),
        }]))
        return 1
    print(json.dumps(history, indent=2, default=str))
    return 0 if all(h["status"] == "COMPLETED" for h in history) else 1


def _cmd_chaos_plan(args: argparse.Namespace) -> int:
    """Generate a seeded FaultPlan (chaos harness) and print or save it —
    the operator surface for drills: `serve --chaos-plan` consumes the wire/
    client kinds, the hostchaos supervisor the host kinds."""
    from nanofed_tpu.faults import FaultPlan

    try:
        plan = FaultPlan.generate(
            args.seed,
            [f"c{i}" for i in range(args.clients)],
            args.rounds,
            crash_fraction=args.crash_fraction,
            straggler_fraction=args.straggler_fraction,
            straggler_delay_s=args.straggler_delay,
            drop_fraction=args.drop_fraction,
            duplicate_fraction=args.duplicate_fraction,
            corrupt_fraction=args.corrupt_fraction,
            server_kill_round=args.server_kill_round,
            hosts=args.hosts,
            host_crash_count=args.host_crashes,
            host_stall_count=args.host_stalls,
            dcn_degrade_fraction=args.dcn_degrade_fraction,
            dcn_delay_s=args.dcn_delay,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not plan.events:
        print("error: the requested plan is empty — give at least one "
              "fraction/count/round", file=sys.stderr)
        return 2
    if args.out is not None:
        plan.save(args.out)
        print(f"wrote {len(plan.events)} events to {args.out}")
    else:
        print(plan.to_json())
    return 0


def _cmd_metrics_summary(args: argparse.Namespace) -> int:
    """Digest a run's ``telemetry.jsonl`` (observability subsystem): per-phase span
    durations, round outcomes, and headline counters, as one JSON document."""
    from nanofed_tpu.observability import find_latest_telemetry, summarize_telemetry

    path = find_latest_telemetry(args.path)
    if path is None:
        print(f"error: no telemetry.jsonl found under {args.path!r} — run with "
              "--telemetry-dir (or the default runs dir with metrics saving on) "
              "first", file=sys.stderr)
        return 1
    print(json.dumps(summarize_telemetry(path), indent=2))
    return 0


def _cmd_trace(args: argparse.Namespace) -> int:
    """Merge a federation's per-host ``telemetry.jsonl`` streams (observability
    subsystem) into one clock-aligned story: the per-round critical-path
    digest on stdout, and — with ``--chrome-out`` — a host-laned Chrome/
    Perfetto timeline (load it at ui.perfetto.dev or chrome://tracing)."""
    from pathlib import Path

    from nanofed_tpu.observability import (
        clock_offsets,
        federation_timeline,
        load_host_streams,
        merge_timeline,
    )

    root = Path(args.path)
    streams = load_host_streams(root)
    if not streams:
        print(f"error: no telemetry.jsonl streams found under {root} — run "
              "the federate/hostchaos harness with --telemetry-dir first",
              file=sys.stderr)
        return 1
    if args.chrome_out is not None:
        timeline = merge_timeline(streams, clock_offsets(streams))
        out = Path(args.chrome_out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(timeline))
        print(f"# wrote {len(timeline['traceEvents'])} trace events to {out}",
              file=sys.stderr)
    digest = federation_timeline(root, include_trace_map=args.trace_map)
    print(json.dumps(digest, indent=2))
    resolution = digest.get("trace_resolution") or {}
    return 0 if resolution.get("resolved", True) else 1


def _cmd_audit(args: argparse.Namespace) -> int:
    """Audit the round programs at the jaxpr/AOT level WITHOUT running a
    federation (``analysis.program_audit``): collective-schedule consistency
    across cond branches, mesh discipline (declared axes, hosts-after-clients,
    the one-cross-host-tensor budget), donation vs memory_analysis, dtype
    drift, embedded host transfers.  Exit 1 on findings."""
    from nanofed_tpu.analysis.__main__ import _ensure_virtual_devices
    from nanofed_tpu.analysis.program_audit import (
        format_audit_reports, reference_catalog,
    )

    # The reference catalog needs the standard 8-device topology; on a bare
    # CPU host this must land in XLA_FLAGS before the backend initializes.
    _ensure_virtual_devices()
    catalog = reference_catalog()
    reports = catalog.audit_all(compile=not args.no_compile)

    if args.telemetry_dir is not None:
        from nanofed_tpu.observability import RunTelemetry

        telemetry = RunTelemetry(args.telemetry_dir)
        for report in reports:
            telemetry.record("audit", **report.to_dict())
        telemetry.close()

    if args.json:
        print(json.dumps([r.to_dict() for r in reports], indent=2))
    else:
        print(format_audit_reports(reports))
    return 0 if all(r.ok for r in reports) else 1


def _cmd_loadtest(args: argparse.Namespace) -> int:
    """Run the synthetic client swarm against one (or both) serving paths and
    print the artifact (also written under --out-dir)."""
    from nanofed_tpu.loadgen import run_loadtest_comparison

    modes = (
        ("per-submit", "ingest") if args.mode == "both" else (args.mode,)
    )
    artifact = run_loadtest_comparison(
        modes=modes,
        out_dir=args.out_dir,
        telemetry_dir=args.telemetry_dir,
        clients=args.clients,
        submits_per_client=args.submits_per_client,
        model=args.model,
        async_buffer_k=args.async_buffer,
        aggregations=args.aggregations,
        ingest_capacity=args.ingest_capacity,
        decode_workers=args.decode_workers,
        max_inflight=args.max_inflight,
        arrival=args.arrival,
        arrival_rate=args.rate,
        weight_skew=args.weight_skew,
        staleness_window=args.staleness_window,
        round_timeout_s=args.timeout,
        virtual_clock=args.virtual_clock,
        seed=args.seed,
        adapter_rank=args.adapter_rank,
    )
    print(json.dumps(artifact, indent=2))
    # A loadtest that lost submits outright (not 429-shed — those retry) is a
    # failed measurement; surface it in the exit code for CI.
    ok = all(
        rec.get("failed_submits", 0) == 0
        and rec["submit_latency_s"]["count"] > 0
        for rec in artifact["modes"].values()
    )
    return 0 if ok else 1


def _cmd_tenants(args: argparse.Namespace) -> int:
    """Run the multi-tenant service harness and print the artifact (also
    written under --out-dir).  Exit 1 when an untargeted tenant lost rounds
    or submits — the isolation claim IS the exit code."""
    from nanofed_tpu.service import run_tenant_service

    chaos: bool | str | None
    if args.chaos_tenant == "none":
        chaos = None
    elif args.chaos_tenant == "first":
        chaos = True
    else:
        chaos = args.chaos_tenant
    artifact = run_tenant_service(
        tenants=args.tenants,
        rounds=args.rounds,
        clients_per_tenant=args.clients,
        submits_per_client=args.submits_per_client,
        async_buffer_k=args.async_buffer,
        arrival=args.arrival,
        arrival_rate=args.rate,
        chaos_tenant=chaos,
        chaos_seed=args.chaos_seed,
        virtual_clock=args.virtual_clock,
        sequential_baseline=not args.no_sequential,
        hbm_budget_bytes=(
            int(args.hbm_budget) if args.hbm_budget is not None else None
        ),
        seed=args.seed,
        out_dir=args.out_dir,
        telemetry_dir=args.telemetry_dir,
        tag=args.tag,
    )
    print(json.dumps(artifact, indent=2))
    ok = (
        artifact["isolation"]["zero_rounds_lost"]
        and artifact["isolation"]["zero_failed_submits"]
    )
    return 0 if ok else 1


def _cmd_bench(args: argparse.Namespace) -> int:
    from nanofed_tpu.benchmarks import BENCHMARKS, run_benchmark

    if args.list:
        print(json.dumps(sorted(BENCHMARKS), indent=2))
        return 0
    overrides = {}
    if args.train_size is not None:
        overrides["train_size"] = args.train_size
    if args.rounds is not None:
        overrides["num_rounds"] = args.rounds
    if args.client_chunk is not None:
        overrides["client_chunk"] = args.client_chunk
    if args.dtype is not None:
        overrides["compute_dtype"] = args.dtype
    summary = run_benchmark(args.name, out_dir=args.out_dir, **overrides)
    print(json.dumps(summary, indent=2, default=str))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="nanofed-tpu", description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)

    sub.add_parser("info", help="print environment / model zoo info")

    run = sub.add_parser("run", help="run a federated training experiment")
    run.add_argument("--model", default="mnist_cnn")
    run.add_argument("--clients", type=int, default=10)
    run.add_argument("--rounds", type=int, default=2)
    run.add_argument("--epochs", type=int, default=2)
    run.add_argument("--batch-size", type=int, default=64)
    run.add_argument("--lr", type=float, default=0.1)
    run.add_argument("--scheme", default="iid", choices=["iid", "label_skew", "dirichlet"])
    run.add_argument("--participation", type=float, default=1.0)
    run.add_argument("--data-dir", default=None)
    run.add_argument("--out-dir", default="runs")
    run.add_argument("--seed", type=int, default=0)
    run.add_argument(
        "--train-size", type=int, default=None,
        help="cap the (synthetic) training set size; default = full dataset",
    )
    run.add_argument(
        "--client-chunk", type=int, default=None,
        help="train each device's resident clients in sequential chunks of this many "
        "(memory bound for clients >> chips)",
    )
    run.add_argument(
        "--dtype", default=None, choices=["bfloat16", "float32"],
        help="local-training compute dtype (mixed precision when bfloat16)",
    )
    run.add_argument(
        "--adapter-rank", type=int, default=None, metavar="R",
        help="parameter-efficient federation (nanofed_tpu.adapters): freeze "
        "the base model device-resident and federate only rank-R LoRA A/B "
        "deltas on the 2-D kernel leaves — training, aggregation, "
        "checkpoints, and wire payloads are adapter-sized (the full model "
        "only materializes at eval/versioned-model merges). Composes with "
        "--model-shards (the frozen base shards over the model axis) and "
        "with --autotune (R seeds the tuner's rank-ladder sweep)",
    )
    run.add_argument(
        "--adapter-alpha", type=float, default=None,
        help="LoRA alpha: the merged delta is (alpha/rank) * A @ B "
        "(default: alpha = rank, i.e. scale 1.0)",
    )
    run.add_argument(
        "--model-shards", type=int, default=1, metavar="N",
        help="split params + server optimizer state N ways over a second "
        "'model' mesh axis (FSDP-style; devices arrange as a (devices/N, N) "
        "clients x model mesh). Each leaf's largest divisible dimension is "
        "sharded; the model never materializes replicated between rounds. "
        "N must divide the device count; 1 = classic replicated layout",
    )
    run.add_argument(
        "--hosts", type=int, default=1, metavar="H",
        help="add a third 'hosts' mesh axis: devices arrange as an (H, "
        "devices/(H*model-shards), model-shards) hosts x clients x model "
        "mesh and the FedAvg reduce becomes HIERARCHICAL — host-local psum "
        "over clients (ICI), then ONE cross-host psum over hosts (DCN), so "
        "inter-host traffic per round is one model-sized tensor. Cohorts "
        "sample host-locally. Single-process this slices virtual hosts over "
        "the local devices; combine with --distributed on a real multi-host "
        "cluster. H * model-shards must divide the device count",
    )
    run.add_argument(
        "--distributed", action="store_true",
        help="call jax.distributed.initialize before anything (multi-host "
        "bring-up: JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID "
        "env, or TPU-pod auto-detection; CPU clusters get gloo collectives) "
        "so jax.devices() is the GLOBAL device list. Single-process "
        "environments make this a documented no-op; an ACTUAL multi-process "
        "environment is refused here — the Coordinator is single-controller, "
        "and scripts/multihost_harness.py (federate|smoke|bench) is the "
        "end-to-end multi-process driver",
    )
    run.add_argument(
        "--rounds-per-block", type=int, default=1,
        help="fuse this many rounds into ONE device program (lax.scan inside a "
        "single jit): no Python dispatch, no block_until_ready, no metrics "
        "transfer between fused rounds — host sync only at block boundaries. "
        "Falls back to single rounds for --scaffold/--robust-*/--dp-epsilon",
    )
    run.add_argument(
        "--client-metrics-every", type=int, default=1,
        help="dump per-client metric detail (weights/losses/update norms) into the "
        "round metrics JSON every N rounds; 0 = never. At 1000 clients each dump "
        "is a 1000-element device->host conversion",
    )
    run.add_argument(
        "--lr-schedule", default="constant",
        choices=["constant", "cosine", "linear", "step"],
        help="per-round client-lr schedule; rides a traced scalar through the "
        "compiled round step, so decaying costs zero recompiles",
    )
    run.add_argument("--lr-min-factor", type=float, default=0.0,
                     help="terminal lr fraction for cosine/linear; floor for step")
    run.add_argument("--lr-decay-every", type=int, default=10,
                     help="step schedule: rounds between decays")
    run.add_argument("--lr-decay-gamma", type=float, default=0.5,
                     help="step schedule: multiplier per decay")
    run.add_argument(
        "--scaffold", action="store_true",
        help="SCAFFOLD control-variate correction (Karimireddy et al. 2020): "
        "removes non-IID client drift at its source; shines under partial "
        "participation. Requires plain SGD (no momentum) and refuses --dp-epsilon "
        "and --robust-trim (each would bias the control estimate)")
    run.add_argument(
        "--robust-trim", type=int, default=None, metavar="K",
        help="Byzantine-robust aggregation: coordinate-wise trimmed mean dropping "
        "the K extremes per side (tolerates K colluding clients; unweighted over "
        "the kept ranks; incompatible with --dp-epsilon)",
    )
    run.add_argument(
        "--robust-method", default=None,
        choices=["trimmed_mean", "median", "multi_krum"],
        help="robust estimator: trimmed_mean (default when --robust-trim is set), "
        "median (knob-free, tolerates any Byzantine minority), or multi_krum "
        "(whole-update selection, --robust-trim acts as f); incompatible "
        "with --dp-epsilon",
    )
    run.add_argument(
        "--dp-epsilon", type=float, default=None,
        help="enable central DP-FedAvg with noise CALIBRATED to this epsilon budget "
        "over the run's rounds (tight RDP accounting); spend is reported per round "
        "and in the summary",
    )
    run.add_argument("--dp-delta", type=float, default=1e-5)
    run.add_argument("--dp-clip", type=float, default=1.0,
                     help="central-DP per-update clip norm C")
    run.add_argument(
        "--telemetry-dir", default=None,
        help="write the run's telemetry.jsonl (phase spans + round records + final "
        "metrics snapshot) here instead of the default <out-dir>; read it back "
        "with `nanofed-tpu metrics-summary`",
    )
    run.add_argument(
        "--strict", action="store_true",
        help="strict execution mode (analysis subsystem): contract-check the "
        "round program via jax.eval_shape at build time and run every device "
        "dispatch under jax.transfer_guard('disallow') — an implicit host "
        "transfer in the hot path raises instead of silently serializing it",
    )
    run.add_argument(
        "--autotune", action="store_true",
        help="let the COMPILER's cost model pick client_chunk / "
        "rounds-per-block / mesh shape / batch size (nanofed_tpu.tuning): a "
        "compile-only sweep lowers every candidate round program via AOT "
        "cost_analysis/memory_analysis — ZERO round executions before the "
        "first real round — scores by achievable roofline walltime on TPU "
        "(bytes-accessed ordering on CPU, basis stated), rejects candidates "
        "over the device HBM budget, writes the ranked table as "
        "<out-dir>/autotune_*.json, and caches the result under .jax_cache/ "
        "so repeat runs compile nothing. Incompatible with explicit "
        "--client-chunk/--rounds-per-block/--model-shards",
    )
    run.add_argument(
        "--retune-every", type=int, default=0, metavar="N",
        help="close the tuning loop online (requires --autotune): every N "
        "completed rounds, re-rank the sweep's candidate table by the "
        "walltimes the run actually realized (plus the device-occupancy "
        "gauge) and hot-swap the live round program at the next block "
        "boundary when measurements beat the AOT pick by more than the "
        "retuner's hysteresis. Every decision lands as a `retune` telemetry "
        "record, the summary carries a `retunes` block, and the measured "
        "numbers are written back into the autotune cache entry at run end. "
        "0 = off",
    )
    run.add_argument(
        "--profile-programs", action="store_true",
        help="profile every built round program at construction (XLA "
        "cost_analysis/memory_analysis + roofline verdict): reports land in "
        "the summary, as nanofed_program_* gauges, and as program_profile "
        "telemetry records. Pays a second XLA compile unless the persistent "
        "compilation cache is warm; `nanofed-tpu profile` does this without "
        "running a federation at all",
    )

    serve = sub.add_parser(
        "serve", help="host a real-network federation server (binary HTTP transport)"
    )
    serve.add_argument("--model", default="mnist_cnn")
    serve.add_argument("--host", default="127.0.0.1")
    serve.add_argument("--port", type=int, default=8080)
    serve.add_argument("--rounds", type=int, default=2)
    serve.add_argument(
        "--min-clients", type=int, default=None,
        help="synchronous rounds: cohort size to wait for (default 1); "
        "incompatible with --async-buffer")
    serve.add_argument(
        "--completion-rate", type=float, default=None,
        help="synchronous rounds: fraction of --min-clients required before "
        "aggregating (default 1.0); incompatible with --async-buffer")
    serve.add_argument("--timeout", type=float, default=300.0)
    serve.add_argument("--seed", type=int, default=0)
    serve.add_argument(
        "--secure", action="store_true",
        help="secure-aggregation rounds: clients enroll via /secagg and submit "
        "pairwise-masked updates; the server only ever sees the cohort sum",
    )
    serve.add_argument(
        "--dropout-tolerant", action="store_true",
        help="with --secure: Bonawitz double-masking — per-round ephemeral secrets, "
        "Shamir share recovery of dropped clients' masks, survivor-only FedAvg. "
        "min_clients becomes a true minimum: enrollment stays open for stragglers "
        "and the Shamir threshold is derived from the frozen roster (> n/2)",
    )
    serve.add_argument(
        "--max-clients", type=int, default=None,
        help="with --dropout-tolerant: cap the enrollment window (reaching it "
        "freezes the cohort immediately); default: unbounded until the roster "
        "has been quiet for the grace period",
    )
    serve.add_argument(
        "--validate", action="store_true",
        help="validate every drained update (shape / finite / norm / cohort z-score); "
        "invalid clients are dropped from the round",
    )
    serve.add_argument(
        "--async-buffer", type=int, default=None, metavar="K",
        help="asynchronous FedBuff mode: aggregate whenever K updates are "
        "buffered instead of waiting for a synchronized cohort; --rounds then "
        "counts aggregations. Incompatible with --secure/--validate")
    serve.add_argument(
        "--staleness-window", type=int, default=None,
        help="async mode only: accept updates based on any of the last W "
        "published versions (default 4; staleness discounted as (1+s)^-0.5)")
    serve.add_argument("--max-norm", type=float, default=100.0,
                       help="per-leaf norm cap for --validate")
    serve.add_argument(
        "--max-inflight", type=int, default=None, metavar="N",
        help="admission control: at most N update bodies in the read/decode "
        "pipeline at once; excess submits get an immediate 429 + Retry-After "
        "(clients with a RetryPolicy back off and re-send). Default: unbounded",
    )
    serve.add_argument(
        "--ingest-batch", type=int, default=None, metavar="K",
        help="batched device-resident ingest (nanofed_tpu.ingest): decoded "
        "deltas accumulate into a preallocated on-device buffer and ONE "
        "jit-compiled batched reduce fires per drain instead of one "
        "aggregation per client; npz decode moves into a bounded worker "
        "pool and a full buffer answers 429 + Retry-After. K is the "
        "EXPECTED drain size: the flush programs for batches up to K "
        "pre-compile at startup so no realistic drain compiles on the "
        "event loop (drain granularity itself is --async-buffer in FedBuff "
        "mode, the round barrier in sync mode). Incompatible with "
        "--validate",
    )
    serve.add_argument(
        "--ingest-capacity", type=int, default=None, metavar="N",
        help="with --ingest-batch: buffer slots (bounds device memory at "
        "N * params * 4 bytes and is the 429 backpressure point; "
        "default 1024)",
    )
    serve.add_argument(
        "--decode-workers", type=int, default=None, metavar="N",
        help="with --ingest-batch: bounded decode pool size (default 4) — "
        "the event loop never decompresses an update body itself",
    )
    serve.add_argument(
        "--evict-stragglers", type=int, default=0, metavar="K",
        help="sync rounds: evict a previously-seen client after K consecutive "
        "missed rounds, shrinking the round barrier (completion-rate graceful "
        "degradation) so one dead client stops costing every round a timeout; "
        "0 = never (default)",
    )
    serve.add_argument(
        "--state-dir", default=None, metavar="DIR",
        help="crash recovery: checkpoint every completed round's params + "
        "engine state here, and RESUME from the latest checkpoint at startup "
        "— a killed server re-run with the same --state-dir continues where "
        "it left off (clients re-sync via retried fetches / stale-round 400s)",
    )
    serve.add_argument(
        "--chaos-plan", default=None, metavar="PLAN.json",
        help="fault injection: load a seeded FaultPlan (nanofed_tpu.faults) "
        "and apply its wire faults (drop/ack_drop/delay) at the server "
        "boundary and its server_kill events in the round loop — for drills "
        "proving a deployment's retry/admission/recovery configuration "
        "actually survives the plan",
    )
    serve.add_argument(
        "--telemetry-dir", default=None,
        help="write this server run's telemetry.jsonl (round/phase spans + round "
        "records) here; live metrics are always scrapable at GET /metrics",
    )

    chaos_plan = sub.add_parser(
        "chaos-plan",
        help="generate a seeded FaultPlan JSON (nanofed_tpu.faults) — client "
        "wire faults and/or host-targeted mesh faults (host_crash/host_stall/"
        "dcn_degrade) — consumable by `serve --chaos-plan` and the multihost "
        "harness's hostchaos supervisor",
    )
    chaos_plan.add_argument("--seed", type=int, default=0)
    chaos_plan.add_argument("--clients", type=int, default=0,
                            help="client population the *_fraction draws "
                            "sample from (client ids are c0..cN-1)")
    chaos_plan.add_argument("--rounds", type=int, default=10)
    chaos_plan.add_argument("--crash-fraction", type=float, default=0.0)
    chaos_plan.add_argument("--straggler-fraction", type=float, default=0.0)
    chaos_plan.add_argument("--straggler-delay", type=float, default=1.0)
    chaos_plan.add_argument("--drop-fraction", type=float, default=0.0)
    chaos_plan.add_argument("--duplicate-fraction", type=float, default=0.0)
    chaos_plan.add_argument("--corrupt-fraction", type=float, default=0.0)
    chaos_plan.add_argument("--server-kill-round", type=int, default=None)
    chaos_plan.add_argument("--hosts", type=int, default=0,
                            help="hosts-axis size the host faults draw over")
    chaos_plan.add_argument("--host-crashes", type=int, default=0)
    chaos_plan.add_argument("--host-stalls", type=int, default=0)
    chaos_plan.add_argument("--dcn-degrade-fraction", type=float, default=0.0)
    chaos_plan.add_argument("--dcn-delay", type=float, default=0.5,
                            metavar="SECONDS")
    chaos_plan.add_argument("--out", default=None, metavar="PLAN.json",
                            help="write the plan here (default: stdout)")

    summary = sub.add_parser(
        "metrics-summary",
        help="digest a run's telemetry.jsonl: per-phase durations, round outcomes, "
        "headline counters",
    )
    summary.add_argument(
        "path", nargs="?", default="runs",
        help="a telemetry.jsonl, a run dir containing one, or a tree to search "
        "for the most recent one (default: runs)",
    )

    trace = sub.add_parser(
        "trace",
        help="merge a federation's per-host telemetry.jsonl streams into one "
        "clock-aligned timeline: per-round critical-path digest + trace "
        "resolution on stdout, optional Chrome/Perfetto trace file",
    )
    trace.add_argument(
        "path", nargs="?", default="runs",
        help="the --telemetry-dir of a federate/hostchaos run (per-host "
        "streams live in host_*/ subdirs; default: runs)",
    )
    trace.add_argument(
        "--chrome-out", default=None, metavar="TRACE.json",
        help="also write the merged host-laned Chrome trace_event file here "
        "(open at ui.perfetto.dev or chrome://tracing)",
    )
    trace.add_argument(
        "--trace-map", action="store_true",
        help="include the full per-trace consumption map in the JSON digest "
        "(one entry per accepted submit; large)",
    )

    profile = sub.add_parser(
        "profile",
        help="compile the round programs (single step, fused block, SCAFFOLD) "
        "WITHOUT running a federation and print the compiler's cost/roofline "
        "table: XLA cost_analysis FLOPs, peak device bytes, arithmetic "
        "intensity, compute- vs memory-bound verdict",
    )
    profile.add_argument("--model", default="mnist_cnn")
    profile.add_argument("--clients", type=int, default=16)
    profile.add_argument("--epochs", type=int, default=1)
    profile.add_argument("--batch-size", type=int, default=64)
    profile.add_argument("--lr", type=float, default=0.1)
    profile.add_argument("--seed", type=int, default=0)
    profile.add_argument("--data-dir", default=None)
    profile.add_argument(
        "--train-size", type=int, default=1024,
        help="training-set size (synthetic unless --data-dir has real data); "
        "only shapes matter — nothing executes",
    )
    profile.add_argument(
        "--participation", type=float, default=1.0,
        help="cohort participation rate: < 1 profiles the cohort-gathered "
        "program the real rounds would dispatch",
    )
    profile.add_argument(
        "--rounds-per-block", type=int, default=4,
        help="also profile the fused R-round block program at this R "
        "(1 = single-step only)",
    )
    profile.add_argument("--client-chunk", type=int, default=None)
    profile.add_argument(
        "--adapter-rank", type=int, default=None, metavar="R",
        help="with --sweep: sweep the parameter-efficient axis — every "
        "candidate lowers the frozen-base LoRA round program, the rank "
        "ladder {R/2, R, 2R} joins the space, and the epilogue cost table "
        "is sized to the adapter payload; the ranked table grows a 'lora' "
        "column",
    )
    profile.add_argument("--model-shards", type=int, default=1, metavar="N",
                         help="profile the 2-D clients x model (FSDP) programs")
    profile.add_argument(
        "--hosts", type=int, default=1, metavar="H",
        help="profile the 3-axis hosts x clients x model programs "
        "(hierarchical aggregation; virtual hosts over the local devices)",
    )
    profile.add_argument("--dtype", default=None, choices=["bfloat16", "float32"])
    profile.add_argument("--no-scaffold", action="store_true",
                         help="skip the SCAFFOLD round program")
    profile.add_argument(
        "--sweep", action="store_true",
        help="run the compile-only autotune sweep instead (nanofed_tpu."
        "tuning): rank every candidate (client_chunk x rounds_per_block x "
        "mesh shape x batch size) by the compiler's cost model, print the "
        "ranked table + the fused-epilogue bytes-accessed comparison, and "
        "write <out-dir>/autotune_*.json; zero round executions. Explicit "
        "--client-chunk/--model-shards pin that axis to the given value",
    )
    profile.add_argument(
        "--force-sweep", action="store_true",
        help="with --sweep: ignore the cached sweep result and re-compile "
        "every candidate",
    )
    profile.add_argument("--json", action="store_true",
                         help="full report dicts as JSON instead of the table")
    profile.add_argument(
        "--telemetry-dir", default=None,
        help="also append program_profile records to a telemetry.jsonl here "
        "(read back with `nanofed-tpu metrics-summary`)",
    )

    audit = sub.add_parser(
        "audit",
        help="audit the round programs at the jaxpr/AOT level WITHOUT running "
        "a federation: collective schedules (cond-branch consistency), mesh "
        "discipline (declared axes, hosts-after-clients hierarchy, cross-host "
        "byte budget), donation vs memory_analysis, dtype drift, embedded "
        "host transfers — across single-step, fused-block, SCAFFOLD, 2-D "
        "FSDP, 3-axis hierarchical, and adapter variants; exit 1 on findings",
    )
    audit.add_argument(
        "--no-compile", action="store_true",
        help="trace-only audit: skip the AOT compile (and with it the "
        "donation check) — faster on a cold compile cache",
    )
    audit.add_argument("--json", action="store_true",
                       help="full report dicts as JSON instead of the table")
    audit.add_argument(
        "--telemetry-dir", default=None,
        help="also append an `audit` record per program to a telemetry.jsonl "
        "here (read back with `nanofed-tpu metrics-summary`)",
    )

    loadtest = sub.add_parser(
        "loadtest",
        help="synthetic client swarm load harness (nanofed_tpu.loadgen): "
        "drive N concurrent submits against an in-process federation "
        "server and record p50/p99 submit latency, rounds/sec, and "
        "429/retry counts as a runs/loadtest_*.json artifact",
    )
    loadtest.add_argument("--clients", type=int, default=10_000)
    loadtest.add_argument("--submits-per-client", type=int, default=1)
    loadtest.add_argument(
        "--mode", default="both", choices=["per-submit", "ingest", "both"],
        help="serving path under test; 'both' runs the per-submit and "
        "batched-ingest paths on identical traffic and records the "
        "rounds/sec ratio",
    )
    loadtest.add_argument("--model", default="digits_mlp")
    loadtest.add_argument(
        "--adapter-rank", type=int, default=None, metavar="R",
        help="parameter-efficient wire mode (nanofed_tpu.adapters): the "
        "federated tree — model fetches, canned submit payloads, the "
        "engine's aggregation — is the rank-R LoRA adapter tree; the "
        "artifact records measured full-vs-adapter payload bytes",
    )
    loadtest.add_argument(
        "--async-buffer", type=int, default=64, metavar="K",
        help="FedBuff aggregation size K (the round engine runs in async "
        "mode: aggregations fire on buffer fill)",
    )
    loadtest.add_argument(
        "--aggregations", type=int, default=None,
        help="aggregations to run (default: total submits // K)",
    )
    loadtest.add_argument("--ingest-capacity", type=int, default=1024)
    loadtest.add_argument("--decode-workers", type=int, default=4)
    loadtest.add_argument("--max-inflight", type=int, default=512)
    loadtest.add_argument(
        "--arrival", default="poisson", choices=["poisson", "uniform", "burst"],
    )
    loadtest.add_argument(
        "--rate", type=float, default=2000.0,
        help="mean arrival rate, submits/sec (poisson & uniform)",
    )
    loadtest.add_argument(
        "--weight-skew", type=float, default=0.0,
        help="lognormal sigma over reported num_samples (0 = homogeneous)",
    )
    loadtest.add_argument("--staleness-window", type=int, default=4)
    loadtest.add_argument("--timeout", type=float, default=120.0,
                          help="per-aggregation round timeout (seconds)")
    loadtest.add_argument(
        "--virtual-clock", action="store_true",
        help="run arrivals/backoffs on a VirtualClock (deterministic, "
        "seconds of real time — what the CI smoke uses)",
    )
    loadtest.add_argument("--seed", type=int, default=0)
    loadtest.add_argument("--out-dir", default="runs")
    loadtest.add_argument(
        "--telemetry-dir", default=None,
        help="also append per-mode 'loadtest' telemetry records here "
        "(read back with `nanofed-tpu metrics-summary`)",
    )

    tenants = sub.add_parser(
        "tenants",
        help="multi-tenant federation service harness (nanofed_tpu.service): "
        "run N concurrent tenant jobs (distinct models/algorithms) over one "
        "device pool behind one listener, drive a swarm per tenant, target a "
        "chaos storm at one tenant, and record aggregate rounds/sec vs the "
        "sequential baseline + per-tenant p99 + the isolation proof as a "
        "runs/tenants_*.json artifact",
    )
    tenants.add_argument("--tenants", type=int, default=3,
                         help="concurrent tenant jobs (models/algorithms "
                         "cycle through the default roster)")
    tenants.add_argument("--rounds", type=int, default=4,
                         help="aggregations (fedbuff) / rounds (fedavg) per "
                         "tenant")
    tenants.add_argument("--clients", type=int, default=40,
                         help="swarm clients per tenant")
    tenants.add_argument("--submits-per-client", type=int, default=2)
    tenants.add_argument("--async-buffer", type=int, default=16, metavar="K")
    tenants.add_argument(
        "--arrival", default="poisson", choices=["poisson", "uniform", "burst"],
    )
    tenants.add_argument("--rate", type=float, default=500.0,
                         help="mean arrival rate, submits/sec per tenant")
    tenants.add_argument(
        "--chaos-tenant", default="first",
        help="tenant the wire-fault storm targets: a name, 'first' "
        "(default), or 'none' for a clean run",
    )
    tenants.add_argument("--chaos-seed", type=int, default=7)
    tenants.add_argument(
        "--no-sequential", action="store_true",
        help="skip the one-tenant-at-a-time baseline runs",
    )
    tenants.add_argument(
        "--virtual-clock", action="store_true",
        help="run arrivals/backoffs/timeouts on a VirtualClock "
        "(deterministic, seconds of real time — what the CI smoke uses)",
    )
    tenants.add_argument(
        "--hbm-budget", type=float, default=None, metavar="BYTES",
        help="per-device memory budget for the scheduler's admission "
        "bin-pack (default: the autotuner's provenance chain — env, "
        "runtime bytes_limit, published HBM table, else unbounded)",
    )
    tenants.add_argument("--seed", type=int, default=0)
    tenants.add_argument("--tag", default=None,
                         help="artifact name suffix (default: UTC stamp)")
    tenants.add_argument("--out-dir", default="runs")
    tenants.add_argument(
        "--telemetry-dir", default=None,
        help="also append per-tenant 'tenant' telemetry records here "
        "(read back with `nanofed-tpu metrics-summary`)",
    )

    bench = sub.add_parser("bench", help="run a named benchmark (BASELINE.json suite)")
    bench.add_argument("name", nargs="?", default="mnist_iid")
    bench.add_argument("--list", action="store_true", help="list benchmark names")
    bench.add_argument("--rounds", type=int, default=None)
    bench.add_argument("--train-size", type=int, default=None)
    bench.add_argument("--client-chunk", type=int, default=None)
    bench.add_argument("--dtype", default=None, choices=["bfloat16", "float32"])
    bench.add_argument("--out-dir", default="runs/bench")

    args = parser.parse_args(argv)
    if args.cmd == "info":
        return _cmd_info(args)
    if args.cmd == "bench":
        return _cmd_bench(args)
    if args.cmd == "serve":
        return _cmd_serve(args)
    if args.cmd == "chaos-plan":
        return _cmd_chaos_plan(args)
    if args.cmd == "metrics-summary":
        return _cmd_metrics_summary(args)
    if args.cmd == "trace":
        return _cmd_trace(args)
    if args.cmd == "profile":
        return _cmd_profile(args)
    if args.cmd == "audit":
        return _cmd_audit(args)
    if args.cmd == "loadtest":
        return _cmd_loadtest(args)
    if args.cmd == "tenants":
        return _cmd_tenants(args)
    return _cmd_run(args)


if __name__ == "__main__":
    sys.exit(main())
