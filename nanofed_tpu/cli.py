"""Command-line interface.

The reference declares a CLI entry point that doesn't exist (``pyproject.toml:22-23`` names
``nanofed.cli:main`` but no module is shipped — SURVEY.md layer-map quirks).  This one is
real: ``nanofed-tpu run`` drives a federated training run, ``info`` prints environment and
model-zoo facts.
"""

from __future__ import annotations

import argparse
import json
import sys


def _cmd_info(_args: argparse.Namespace) -> int:
    import jax

    from nanofed_tpu import __version__
    from nanofed_tpu.models import list_models

    print(
        json.dumps(
            {
                "version": __version__,
                "jax": jax.__version__,
                "backend": jax.default_backend(),
                "devices": [str(d) for d in jax.devices()],
                "models": list_models(),
            },
            indent=2,
        )
    )
    return 0


def _cmd_run(args: argparse.Namespace) -> int:
    from nanofed_tpu.experiments import run_experiment

    metrics = run_experiment(
        model=args.model,
        num_clients=args.clients,
        num_rounds=args.rounds,
        local_epochs=args.epochs,
        batch_size=args.batch_size,
        learning_rate=args.lr,
        scheme=args.scheme,
        participation=args.participation,
        data_dir=args.data_dir,
        out_dir=args.out_dir,
        seed=args.seed,
        train_size=args.train_size,
        client_chunk=args.client_chunk,
        compute_dtype=args.dtype,
    )
    print(json.dumps(metrics, indent=2, default=str))
    return 0


def _cmd_bench(args: argparse.Namespace) -> int:
    from nanofed_tpu.benchmarks import BENCHMARKS, run_benchmark

    if args.list:
        print(json.dumps(sorted(BENCHMARKS), indent=2))
        return 0
    overrides = {}
    if args.train_size is not None:
        overrides["train_size"] = args.train_size
    if args.rounds is not None:
        overrides["num_rounds"] = args.rounds
    if args.client_chunk is not None:
        overrides["client_chunk"] = args.client_chunk
    if args.dtype is not None:
        overrides["compute_dtype"] = args.dtype
    summary = run_benchmark(args.name, out_dir=args.out_dir, **overrides)
    print(json.dumps(summary, indent=2, default=str))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="nanofed-tpu", description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)

    sub.add_parser("info", help="print environment / model zoo info")

    run = sub.add_parser("run", help="run a federated training experiment")
    run.add_argument("--model", default="mnist_cnn")
    run.add_argument("--clients", type=int, default=10)
    run.add_argument("--rounds", type=int, default=2)
    run.add_argument("--epochs", type=int, default=2)
    run.add_argument("--batch-size", type=int, default=64)
    run.add_argument("--lr", type=float, default=0.1)
    run.add_argument("--scheme", default="iid", choices=["iid", "label_skew", "dirichlet"])
    run.add_argument("--participation", type=float, default=1.0)
    run.add_argument("--data-dir", default=None)
    run.add_argument("--out-dir", default="runs")
    run.add_argument("--seed", type=int, default=0)
    run.add_argument(
        "--train-size", type=int, default=None,
        help="cap the (synthetic) training set size; default = full dataset",
    )
    run.add_argument(
        "--client-chunk", type=int, default=None,
        help="train each device's resident clients in sequential chunks of this many "
        "(memory bound for clients >> chips)",
    )
    run.add_argument(
        "--dtype", default=None, choices=["bfloat16", "float32"],
        help="local-training compute dtype (mixed precision when bfloat16)",
    )

    bench = sub.add_parser("bench", help="run a named benchmark (BASELINE.json suite)")
    bench.add_argument("name", nargs="?", default="mnist_iid")
    bench.add_argument("--list", action="store_true", help="list benchmark names")
    bench.add_argument("--rounds", type=int, default=None)
    bench.add_argument("--train-size", type=int, default=None)
    bench.add_argument("--client-chunk", type=int, default=None)
    bench.add_argument("--dtype", default=None, choices=["bfloat16", "float32"])
    bench.add_argument("--out-dir", default="runs/bench")

    args = parser.parse_args(argv)
    if args.cmd == "info":
        return _cmd_info(args)
    if args.cmd == "bench":
        return _cmd_bench(args)
    return _cmd_run(args)


if __name__ == "__main__":
    sys.exit(main())
