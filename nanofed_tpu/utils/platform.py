"""Platform bring-up helpers: CPU-mesh forcing and watchdogged backend init.

Two hazards motivate this module (both observed in driver runs):

1. The environment may pre-select an out-of-tree accelerator platform (e.g.
   ``JAX_PLATFORMS=axon``) via a sitecustomize that imports jax at interpreter startup.
   Setting ``JAX_PLATFORMS=cpu`` in the *environment* of a fresh process is then too late —
   the config value was already bound at import.  :func:`force_cpu_mesh` forces the CPU
   platform correctly: config update + unregistering the accelerator plugin factory,
   before the first backend initialization.

2. A TPU process killed mid-run can wedge the device tunnel: every later backend init
   hangs *forever* inside ``jax.devices()`` with no Python-level timeout available.
   :func:`deadline` / :func:`init_devices_or_die` bound such hangs with a watchdog thread
   that prints a diagnostic (and an optional machine-readable JSON error line) and
   hard-exits, so callers fail fast with evidence instead of a silent rc=124.

The reference framework has no analogue (it never touches an accelerator); this is
TPU-runtime hardening that SURVEY.md §5 "failure detection" implies for the TPU build.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import sys
import threading
import time
from typing import Iterator


def log_stage(msg: str, *, t0: float | None = None) -> None:
    """Timestamped progress line on stderr (flushed), so a killed process leaves a
    diagnostic tail showing the last stage reached."""
    stamp = time.strftime("%H:%M:%S")
    rel = f" +{time.time() - t0:7.1f}s" if t0 is not None else ""
    print(f"[{stamp}{rel}] {msg}", file=sys.stderr, flush=True)


def force_cpu_mesh(n_devices: int = 8) -> None:
    """Force a virtual ``n_devices``-device CPU mesh, overriding any preset accelerator
    platform.  Safe to call whether or not jax is already imported; must be called before
    the first backend initialization (``jax.devices()`` etc.)."""
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n_devices}"
    if "xla_force_host_platform_device_count" in flags:
        # Replace a pre-set count (it may differ from n_devices) rather than skip.
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", flag, flags)
    else:
        flags = f"{flags} {flag}".strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

    # Unregister the out-of-tree accelerator plugin a sitecustomize may have registered:
    # its client init dials real hardware and can hang if the tunnel is busy/wedged.
    # Only the plugin is removed — built-in platform names must stay registered or MLIR
    # lowering-rule registration rejects them as unknown platforms.
    from jax._src import xla_bridge as _xb

    for plugin in ("axon",):
        _xb._backend_factories.pop(plugin, None)


@contextlib.contextmanager
def deadline(
    stage: str, timeout_s: float, *, error_json: dict | None = None, exit_code: int = 3
) -> Iterator[None]:
    """Bound a stage that may hang in native code (backend init, first compile).

    A daemon watchdog thread fires after ``timeout_s``: prints a diagnostic to stderr,
    optionally a machine-readable JSON line to stdout, then ``os._exit`` — the only way
    out when the main thread is stuck inside a C++ call that never returns.
    """
    done = threading.Event()

    def watchdog() -> None:
        if done.wait(timeout_s):
            return
        print(
            f"[watchdog] stage '{stage}' exceeded {timeout_s:.0f}s — "
            "accelerator backend likely wedged; aborting with diagnostic instead of hanging",
            file=sys.stderr,
            flush=True,
        )
        if error_json is not None:
            payload = dict(error_json)
            payload.setdefault("error", f"{stage} timed out after {timeout_s:.0f}s")
            print(json.dumps(payload), flush=True)
        os._exit(exit_code)

    t = threading.Thread(target=watchdog, name=f"deadline-{stage}", daemon=True)
    t.start()
    try:
        yield
    finally:
        done.set()


def init_devices_or_die(
    timeout_s: float = 120.0, *, error_json: dict | None = None
) -> list:
    """``jax.devices()`` with a watchdog (see :func:`deadline`)."""
    import jax

    with deadline("jax backend init", timeout_s, error_json=error_json):
        return jax.devices()


def enable_compilation_cache(cache_dir: str | os.PathLike | None = None) -> str:
    """Point jax's persistent compilation cache at ``cache_dir`` (default:
    ``$NANOFED_CACHE_DIR`` or ``./.jax_cache`` in the working tree — NOT the package
    install location, which may be read-only site-packages) so repeated driver/bench runs
    skip recompilation.  Returns the cache dir used."""
    import jax

    path = str(
        cache_dir
        or os.environ.get("NANOFED_CACHE_DIR")
        or os.path.join(os.getcwd(), ".jax_cache")
    )
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    # jax initializes its cache object ONCE, at the first compile — in a
    # process that already jitted something (a warm coordinator, a test run)
    # the object has latched (possibly to "no cache") and the config update
    # above would silently never take effect.  Reset so the next compile
    # re-initializes against the directory we just configured.
    try:
        from jax._src.compilation_cache import reset_cache

        reset_cache()
    except Exception:  # pragma: no cover - old/new jax layout drift
        pass
    return path
