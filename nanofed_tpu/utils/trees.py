"""Pytree arithmetic used across the framework.

These replace the reference's per-key Python dict loops over torch state_dicts (e.g. the
FedAvg reduce at ``nanofed/server/aggregator/fedavg.py:56-63`` and DP clipping at
``nanofed/privacy/mechanisms.py:85-104``) with ``jax.tree_util`` transforms that XLA fuses
into a handful of kernels.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.flatten_util
import jax.numpy as jnp

PyTree = Any


def tree_zeros_like(tree: PyTree) -> PyTree:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_add(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(tree: PyTree, s: jax.Array | float) -> PyTree:
    return jax.tree.map(lambda x: x * s, tree)


def tree_where(pred: jax.Array, a: PyTree, b: PyTree) -> PyTree:
    """Leafwise ``where(pred, a, b)`` with a scalar/broadcastable predicate."""
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def tree_vdot(a: PyTree, b: PyTree) -> jax.Array:
    """Sum of elementwise products across all leaves (a full inner product)."""
    leaves = jax.tree.leaves(jax.tree.map(lambda x, y: jnp.vdot(x, y), a, b))
    return jnp.sum(jnp.stack(leaves))


def tree_sq_norm(tree: PyTree) -> jax.Array:
    """Squared global L2 norm over every leaf."""
    leaves = jax.tree.leaves(jax.tree.map(lambda x: jnp.sum(jnp.square(x)), tree))
    return jnp.sum(jnp.stack(leaves))


def tree_global_norm(tree: PyTree) -> jax.Array:
    """Global L2 norm over all leaves — the quantity torch's ``clip_grad_norm_`` computes
    in the reference's DP clipping (``nanofed/trainer/private.py:54-63``)."""
    return jnp.sqrt(tree_sq_norm(tree))


def tree_clip_by_global_norm(tree: PyTree, max_norm: float | jax.Array) -> tuple[PyTree, jax.Array]:
    """Scale ``tree`` so its global norm is at most ``max_norm``.

    Returns ``(clipped, pre_clip_norm)``.  Parity with
    ``nanofed/privacy/mechanisms.py:85-104`` (clip coefficient ``C / (norm + 1e-6)``
    capped at 1).
    """
    norm = tree_global_norm(tree)
    coef = jnp.minimum(1.0, max_norm / (norm + 1e-12))
    return tree_scale(tree, coef), norm


def tree_weighted_mean(stacked: PyTree, weights: jax.Array, eps: float = 1e-12) -> PyTree:
    """Weighted mean over the leading axis of every leaf.

    ``stacked`` has leaves ``[C, ...]``; ``weights`` is ``[C]``.  This is the whole FedAvg
    reduce (``nanofed/server/aggregator/fedavg.py:46-78``) as one fused contraction per
    leaf instead of a Python loop over clients and keys.
    """
    total = weights.sum()
    denom = jnp.maximum(total, eps)

    def leaf_mean(leaf: jax.Array) -> jax.Array:
        w = weights.astype(leaf.dtype).reshape((-1,) + (1,) * (leaf.ndim - 1))
        return jnp.sum(leaf * w, axis=0) / denom.astype(leaf.dtype)

    return jax.tree.map(leaf_mean, stacked)


def tree_map_with_path_names(fn: Callable[[str, jax.Array], Any], tree: PyTree) -> PyTree:
    """Map with a '/'-joined string path per leaf (used by persistence and validation)."""

    def _fn(path, leaf):
        name = "/".join(_key_str(k) for k in path)
        return fn(name, leaf)

    return jax.tree_util.tree_map_with_path(_fn, tree)


def _key_str(k: Any) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "idx"):
        return str(k.idx)
    if hasattr(k, "name"):
        return str(k.name)
    return str(k)


def tree_flatten_with_names(tree: PyTree) -> tuple[list[tuple[str, jax.Array]], Any]:
    """Flatten to ``[(path_name, leaf), ...]`` plus the treedef, for serialization."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    named = [("/".join(_key_str(k) for k in path), leaf) for path, leaf in flat]
    return named, treedef


def tree_size(tree: PyTree) -> int:
    """Total number of scalar parameters."""
    return sum(x.size for x in jax.tree.leaves(tree))


def tree_cast(tree: PyTree, dtype: Any) -> PyTree:
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def tree_ravel(tree: PyTree) -> tuple[jax.Array, Callable[[jax.Array], PyTree]]:
    """Flatten a pytree into one 1-D vector plus an unravel function."""
    return jax.flatten_util.ravel_pytree(tree)
