"""Injectable clocks for the async communication stack.

Every wall-clock read and every sleep in ``nanofed_tpu.communication`` (round
deadlines, poll intervals, retry backoff) goes through a :class:`Clock`, so a
test — or the chaos harness — can swap in a :class:`VirtualClock` and make
timeout, straggler, and backoff behavior a pure function of the schedule
instead of host load.  This is what let
``test_heterogeneous_speed_federation_end_to_end`` drop its load-average gate:
on a virtual clock a "slow client" is slow by construction, not by hoping the
CI core is contended the right amount.

Design constraints:

* ``time()`` is MONOTONIC (the event loop's clock, not ``time.time``): round
  deadlines must never jump with NTP corrections.
* ``sleep()`` is async.  Synchronous callers that only need timestamps (the
  bench, the span tracer) keep using ``time.perf_counter`` directly — this
  module is for code whose *waiting* must be injectable.
"""

from __future__ import annotations

import asyncio
import heapq
import time as _time

__all__ = ["Clock", "SYSTEM_CLOCK", "VirtualClock"]


class Clock:
    """Real time: ``time()`` is the running event loop's monotonic clock
    (``time.monotonic`` when called off-loop, e.g. from constructors) and
    ``sleep`` is ``asyncio.sleep``."""

    def time(self) -> float:
        try:
            return asyncio.get_running_loop().time()
        except RuntimeError:
            return _time.monotonic()

    async def sleep(self, seconds: float) -> None:
        await asyncio.sleep(seconds)


#: Shared default instance — stateless, so one is enough for the process.
SYSTEM_CLOCK = Clock()


class VirtualClock(Clock):
    """Deterministic virtual time for async tests and seeded chaos schedules.

    ``time()`` returns the virtual now; ``sleep(d)`` parks the caller on a
    virtual deadline.  Time advances ONLY when every task that is going to run
    has run: a lazily-started advancer task yields the event loop
    ``grace_yields`` times (letting ready callbacks and localhost socket I/O
    complete), then jumps the clock to the earliest pending deadline and wakes
    that sleeper.  Consequences:

    * A 300 s virtual timeout expires in milliseconds of real time when nothing
      is coming — and *never* expires because the host core was contended,
      since blocking host work (a jit compile, a training step) freezes the
      advancer along with everything else.
    * Sleepers wake in deadline order, so "client A is 10x slower than
      client B" is an ordering guarantee, not a scheduling hint.

    Real socket I/O still happens (aiohttp runs unmodified); it completes
    during the grace yields, i.e. in ~zero virtual time.  Spurious early wakes
    relative to in-flight I/O are possible under extreme load, which is why
    poll loops must re-check their condition — the loops in
    ``communication`` all do.
    """

    def __init__(self, start: float = 0.0, grace_yields: int = 50) -> None:
        if grace_yields < 1:
            raise ValueError("grace_yields must be >= 1")
        self._now = float(start)
        self._grace = int(grace_yields)
        self._sleepers: list[tuple[float, int, asyncio.Future]] = []
        self._seq = 0
        self._advancer: asyncio.Task | None = None

    def time(self) -> float:
        return self._now

    def advance(self, seconds: float) -> None:
        """Manually move the clock forward (synchronous callers / setup code).
        Does NOT wake sleepers by itself — the advancer does that on its next
        pass, in deadline order."""
        if seconds < 0:
            raise ValueError("cannot move a clock backwards")
        self._now += seconds

    async def sleep(self, seconds: float) -> None:
        if seconds <= 0:
            # Pure yield, no deadline: matches asyncio.sleep(0) semantics.
            await asyncio.sleep(0)
            return
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        heapq.heappush(self._sleepers, (self._now + seconds, self._seq, fut))
        self._seq += 1
        self._ensure_advancer(loop)
        await fut

    def _ensure_advancer(self, loop: asyncio.AbstractEventLoop) -> None:
        if (
            self._advancer is None
            or self._advancer.done()
            or self._advancer.get_loop() is not loop
        ):
            # A fresh asyncio.run() gets a fresh advancer: tasks cannot cross
            # event loops, but a VirtualClock instance may outlive one.  The
            # advancer is never awaited — a crash in it would hang every
            # virtual sleeper silently without the logging sink (FED008).
            from nanofed_tpu.utils.aio import log_task_exception

            self._advancer = loop.create_task(self._advance_loop())
            self._advancer.add_done_callback(log_task_exception)

    async def _advance_loop(self) -> None:
        while self._sleepers:
            for _ in range(self._grace):
                # Let every ready task — and localhost socket I/O — run to
                # quiescence before time moves.
                await asyncio.sleep(0)
            if not self._sleepers:
                return
            wake, _, fut = heapq.heappop(self._sleepers)
            if fut.done():
                # The sleeping task was cancelled: its deadline is dead too —
                # advancing to it would spuriously expire every LIVE deadline
                # computed from time() (round timeouts, retry budgets).
                continue
            self._now = max(self._now, wake)
            fut.set_result(None)
