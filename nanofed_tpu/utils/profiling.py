"""Device-level profiling helpers (the deep end of SURVEY.md §5 'tracing/profiling').

The reference's only profiler is the ``log_exec`` wall-time decorator
(``nanofed/utils/logger.py:189-226``), which this framework keeps (``utils.logger``) —
but wall time alone cannot attribute a TPU round to compute vs HBM vs host gaps.  These
helpers wrap ``jax.profiler`` so a round (or any block) can be captured as an XLA/TPU
trace viewable in TensorBoard or Perfetto (``tensorboard --logdir <dir>`` →  Profile).
"""

from __future__ import annotations

import contextlib
import time
from pathlib import Path
from typing import Any, Callable, Iterator

import jax

from nanofed_tpu.utils.logger import Logger


@contextlib.contextmanager
def trace(log_dir: str | Path, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a device trace of the enclosed block::

        with trace("runs/profile"):
            coordinator.run_round()

    Writes a TensorBoard-profile/Perfetto trace under ``log_dir``.  Host-side
    ``annotate(...)`` / ``jax.profiler.TraceAnnotation`` blocks show up as named spans;
    every XLA executable, transfer, and host gap is attributed.
    """
    log_dir = str(log_dir)
    Logger().info("profiler trace -> %s", log_dir)
    if hasattr(jax.profiler, "ProfileOptions"):
        options = jax.profiler.ProfileOptions()
        options.host_tracer_level = host_tracer_level
        jax.profiler.start_trace(log_dir, profiler_options=options)
    else:
        # Older JAX has no ProfileOptions; the default host tracer level still
        # records host annotations, so the capture stays useful.
        jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span inside a :func:`trace` capture (host-side annotation)."""
    with jax.profiler.TraceAnnotation(name):
        yield


def device_time(fn: Callable[[], Any], reps: int = 3) -> dict[str, float]:
    """Honest on-device timing of a nullary callable: one untimed warm-up (compile),
    then ``reps`` blocked executions.  Returns min/median/max wall seconds.

    This is the measurement discipline every recorded artifact in ``runs/`` uses
    (compile excluded, ``block_until_ready`` so host-async dispatch can't lie).
    """
    import numpy as np

    jax.block_until_ready(fn())
    times = []
    for _ in range(reps):
        t = time.perf_counter()
        jax.block_until_ready(fn())
        times.append(time.perf_counter() - t)
    return {
        "min_s": float(np.min(times)),
        "median_s": float(np.median(times)),
        "max_s": float(np.max(times)),
    }
