"""Context-scoped singleton logger and execution-timing decorator.

Capability parity with ``nanofed/utils/logger.py`` (singleton ``Logger`` with a component
context stack, ANSI colors, console/file handlers, and the ``log_exec`` sync+async timing
decorator — the reference's only profiler, ``logger.py:189-226``).  Design differs: built on
stdlib ``logging`` adapters rather than a hand-rolled formatter chain, and ``log_exec``
optionally calls ``jax.block_until_ready`` on the result so timings mean something under
JAX's async dispatch.
"""

from __future__ import annotations

import functools
import inspect
import logging
import sys
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Iterator, TypeVar

_COLORS = {
    "DEBUG": "\033[36m",  # cyan
    "INFO": "\033[32m",  # green
    "WARNING": "\033[33m",  # yellow
    "ERROR": "\033[31m",  # red
    "CRITICAL": "\033[35m",  # magenta
}
_RESET = "\033[0m"
_DIM = "\033[2m"


@dataclass(frozen=True)
class LogConfig:
    """Parity with the reference's ``LogConfig`` (``nanofed/utils/__init__.py:1-4``)."""

    level: int = logging.INFO
    console: bool = True
    file_path: str | Path | None = None
    color: bool = True


class _ContextFormatter(logging.Formatter):
    def __init__(self, color: bool) -> None:
        super().__init__()
        self._color = color

    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%H:%M:%S", time.localtime(record.created))
        ctx = getattr(record, "nf_context", "")
        ctx_part = f"[{ctx}] " if ctx else ""
        level = record.levelname
        msg = record.getMessage()
        if self._color and sys.stderr.isatty():
            color = _COLORS.get(level, "")
            return f"{_DIM}{ts}{_RESET} {color}{level:<8}{_RESET} {ctx_part}{msg}"
        return f"{ts} {level:<8} {ctx_part}{msg}"


class Logger:
    """Singleton logger with a component-context stack.

    Usage::

        log = Logger()
        with log.context("coordinator"):
            log.info("round %d started", r)
    """

    _instance: "Logger | None" = None

    def __new__(cls) -> "Logger":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance._init()
        return cls._instance

    def _init(self) -> None:
        self._logger = logging.getLogger("nanofed_tpu")
        self._logger.propagate = False
        self._context: list[str] = []
        self.configure(LogConfig())

    def configure(self, config: LogConfig) -> None:
        """(Re)configure handlers; parity with ``Logger.configure``
        (``nanofed/utils/logger.py:90-115``)."""
        for h in list(self._logger.handlers):
            self._logger.removeHandler(h)
            h.close()
        self._logger.setLevel(config.level)
        if config.console:
            h = logging.StreamHandler(sys.stderr)
            h.setFormatter(_ContextFormatter(config.color))
            self._logger.addHandler(h)
        if config.file_path is not None:
            Path(config.file_path).parent.mkdir(parents=True, exist_ok=True)
            fh = logging.FileHandler(config.file_path)
            fh.setFormatter(_ContextFormatter(color=False))
            self._logger.addHandler(fh)

    @contextmanager
    def context(self, name: str) -> Iterator[None]:
        """Push a component name onto the context stack (``logger.py:79-88``)."""
        self._context.append(name)
        try:
            yield
        finally:
            self._context.pop()

    def _log(self, level: int, msg: str, *args: Any) -> None:
        self._logger.log(level, msg, *args, extra={"nf_context": ".".join(self._context)})

    def debug(self, msg: str, *args: Any) -> None:
        self._log(logging.DEBUG, msg, *args)

    def info(self, msg: str, *args: Any) -> None:
        self._log(logging.INFO, msg, *args)

    def warning(self, msg: str, *args: Any) -> None:
        self._log(logging.WARNING, msg, *args)

    def error(self, msg: str, *args: Any) -> None:
        self._log(logging.ERROR, msg, *args)


F = TypeVar("F", bound=Callable[..., Any])


def log_exec(fn: F | None = None, *, block: bool = False, level: int = logging.DEBUG) -> Any:
    """Decorator logging wall-clock time of sync or async functions.

    Parity: ``nanofed/utils/logger.py:189-226``.  With ``block=True`` the result is passed
    through ``jax.block_until_ready`` before the timer stops, so jitted functions report
    real device time, not dispatch time.
    """

    def deco(f: F) -> F:
        name = f.__qualname__

        if inspect.iscoroutinefunction(f):

            @functools.wraps(f)
            async def awrapper(*args: Any, **kwargs: Any) -> Any:
                t0 = time.perf_counter()
                try:
                    out = await f(*args, **kwargs)
                    if block:
                        import jax

                        out = jax.block_until_ready(out)
                    return out
                finally:
                    Logger()._log(level, "Completed %s in %.2fs", name, time.perf_counter() - t0)

            return awrapper  # type: ignore[return-value]

        @functools.wraps(f)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            t0 = time.perf_counter()
            try:
                out = f(*args, **kwargs)
                if block:
                    import jax

                    out = jax.block_until_ready(out)
                return out
            finally:
                Logger()._log(level, "Completed %s in %.2fs", name, time.perf_counter() - t0)

        return wrapper  # type: ignore[return-value]

    if fn is not None:
        return deco(fn)
    return deco
