"""Asyncio helpers: spawned tasks always get an exception sink.

A bare ``asyncio.create_task`` whose reference is only shield-awaited (or
awaited under a broad ``except Exception: pass``) loses its traceback — the
failure surfaces as "Task exception was never retrieved" at interpreter exit,
long after the run that hit it has reported success.  fedlint's FED008 flags
those sites; :func:`spawn_logged` is the sanctioned replacement: the returned
task carries a done-callback that retrieves and logs any non-cancellation
exception the moment the task finishes, whatever the awaiting side does.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Coroutine

__all__ = ["log_task_exception", "spawn_logged"]

_LOG = logging.getLogger("nanofed.aio")


def log_task_exception(task: asyncio.Task, log: logging.Logger | None = None) -> None:
    """Done-callback: retrieve (and log) the task's exception so it is never
    "never retrieved".  Cancellation is not an error.  Attachable directly —
    ``task.add_done_callback(log_task_exception)`` — for tasks that must be
    spawned through a specific loop rather than :func:`spawn_logged`."""
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        (log or _LOG).error(
            "background task %r crashed: %r", task.get_name(), exc,
            exc_info=exc,
        )


def spawn_logged(
    coro: Coroutine[Any, Any, Any],
    *,
    name: str | None = None,
    log: logging.Logger | None = None,
) -> asyncio.Task:
    """``asyncio.create_task`` with a guaranteed exception sink.

    The caller may still await / cancel / shield the returned task normally;
    the logging callback is additive (an exception that also propagates to an
    awaiter is logged once here and raised there — abnormal paths may report
    twice, silent loss never happens).
    """
    task = asyncio.create_task(coro, name=name)
    task.add_done_callback(lambda t: log_task_exception(t, log))
    return task
