"""UTC time helper. Parity: ``nanofed/utils/dates.py:4-5``."""

from __future__ import annotations

from datetime import datetime, timezone


def get_current_time() -> datetime:
    """Timezone-aware UTC now."""
    return datetime.now(timezone.utc)
