"""Utilities: logging, timing, dates, and pytree arithmetic.

Parity surface: ``nanofed/utils/__init__.py:1-4`` exports ``Logger``, ``LogConfig``,
``log_exec``, ``get_current_time``; this package adds the pytree helpers the functional
stack is built on.
"""

from nanofed_tpu.utils.dates import get_current_time
from nanofed_tpu.utils.logger import LogConfig, Logger, log_exec
from nanofed_tpu.utils.profiling import annotate, device_time, trace
from nanofed_tpu.utils.trees import (
    tree_add,
    tree_cast,
    tree_clip_by_global_norm,
    tree_flatten_with_names,
    tree_global_norm,
    tree_map_with_path_names,
    tree_ravel,
    tree_scale,
    tree_size,
    tree_sq_norm,
    tree_sub,
    tree_vdot,
    tree_weighted_mean,
    tree_where,
    tree_zeros_like,
)

__all__ = [
    "Logger",
    "LogConfig",
    "annotate",
    "device_time",
    "log_exec",
    "trace",
    "get_current_time",
    "tree_add",
    "tree_cast",
    "tree_clip_by_global_norm",
    "tree_flatten_with_names",
    "tree_global_norm",
    "tree_map_with_path_names",
    "tree_ravel",
    "tree_scale",
    "tree_size",
    "tree_sq_norm",
    "tree_sub",
    "tree_vdot",
    "tree_weighted_mean",
    "tree_where",
    "tree_zeros_like",
]
