"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

This is the TPU build's "fake backend" (SURVEY.md §4): where the reference mocks aiohttp
sessions, we simulate the device mesh with ``--xla_force_host_platform_device_count=8`` so
every ``shard_map``/collective path runs for real, just on CPU.
"""

import os

# Force CPU even when the environment pre-sets a TPU platform (e.g. JAX_PLATFORMS=axon):
# unit tests must exercise the multi-device code path, which needs 8 virtual devices.
# NOTE: a sitecustomize may import jax at interpreter startup (before this file), so env
# vars alone are too late for config-bound values — set the config explicitly too.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Unregister the out-of-tree accelerator plugin a sitecustomize may have registered: its
# client init dials real hardware (and hangs the whole test run if the device tunnel is
# busy/wedged).  Only the plugin is removed — built-in platform names (tpu/cuda/...) must
# stay registered or MLIR lowering-rule registration rejects them as unknown platforms.
from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)

jax.config.update("jax_threefry_partitionable", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture
def rng():
    return jax.random.key(0)


@pytest.fixture
def np_rng():
    return np.random.default_rng(0)
