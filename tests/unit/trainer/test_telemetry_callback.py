"""TelemetryCallback: local-training metrics bridged into the metrics registry."""

import jax
import jax.numpy as jnp
import numpy as np

from nanofed_tpu.data import pack_clients, synthetic_classification
from nanofed_tpu.core.types import ClientData
from nanofed_tpu.models import get_model
from nanofed_tpu.observability import MetricsRegistry
from nanofed_tpu.trainer import TelemetryCallback, Trainer, TrainingConfig


def _one_client(n=64, in_dim=8, classes=2, batch=16) -> ClientData:
    ds = synthetic_classification(n, classes, (in_dim,), seed=0)
    cd = pack_clients(ds, [np.arange(n)], batch_size=batch)
    return ClientData(*(jnp.asarray(a[0]) for a in cd))


def test_callback_bridges_epochs_and_batches_into_registry():
    reg = MetricsRegistry()
    m = get_model("linear", in_features=8, num_classes=2)
    params = m.init(jax.random.key(0))
    trainer = Trainer(
        m.apply,
        TrainingConfig(batch_size=16, local_epochs=3, collect_batch_metrics=True),
        callbacks=[TelemetryCallback(client_id="c7", registry=reg)],
    )
    trainer.fit(params, _one_client(), jax.random.key(1))

    epochs = reg.counter("nanofed_local_epochs_total", labels=("client",))
    batches = reg.counter("nanofed_local_batches_total", labels=("client",))
    last_loss = reg.gauge("nanofed_local_last_loss", labels=("client",))
    hist = reg.histogram("nanofed_local_epoch_loss", labels=("client",))
    assert epochs.value(client="c7") == 3
    assert batches.value(client="c7") == 3 * (64 // 16)
    assert last_loss.value(client="c7") > 0
    assert hist.sample_count(client="c7") == 3


def test_callback_skips_non_finite_and_non_numeric_metrics():
    reg = MetricsRegistry()
    cb = TelemetryCallback(client_id="x", registry=reg)
    cb.on_epoch_end(0, {"loss": float("nan"), "accuracy": "oops"})
    cb.on_epoch_end(1, {"loss": 0.5})
    assert reg.counter("nanofed_local_epochs_total", labels=("client",)).value(
        client="x"
    ) == 2
    # Only the finite loss was recorded.
    assert reg.histogram("nanofed_local_epoch_loss", labels=("client",)).sample_count(
        client="x"
    ) == 1
    assert reg.gauge("nanofed_local_last_loss", labels=("client",)).value(
        client="x"
    ) == 0.5
