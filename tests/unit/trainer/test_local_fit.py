"""Local trainer tests (analog: ``tests/unit/trainer/test_base_trainer.py`` /
``test_torch.py`` — tiny real models, exact behavioral assertions)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.core.types import ClientData
from nanofed_tpu.data import pack_clients, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.trainer import (
    Trainer,
    TrainingConfig,
    make_evaluator,
    make_local_fit,
)
from nanofed_tpu.trainer.callbacks import MetricsLogger
from nanofed_tpu.utils.trees import tree_sub, tree_global_norm


def _client(n=64, in_dim=8, classes=2, seed=0, batch=16):
    ds = synthetic_classification(n, classes, (in_dim,), seed=seed)
    return pack_clients(ds, [np.arange(n)], batch_size=batch)


def _one(cd: ClientData) -> ClientData:
    return ClientData(*(jnp.asarray(a[0]) for a in cd))


def test_local_fit_reduces_loss(rng):
    m = get_model("linear", in_features=8, num_classes=2)
    params = m.init(rng)
    data = _one(_client())
    fit = make_local_fit(m.apply, TrainingConfig(batch_size=16, local_epochs=5))
    res = fit(params, data, jax.random.key(1))
    assert float(res.epoch_loss[-1]) < float(res.epoch_loss[0])
    assert res.epoch_loss.shape == (5,)
    assert float(res.metrics.samples) == 64.0


def test_local_fit_changes_params_and_is_deterministic(rng):
    m = get_model("linear", in_features=8, num_classes=2)
    params = m.init(rng)
    data = _one(_client())
    fit = jax.jit(make_local_fit(m.apply, TrainingConfig(batch_size=16)))
    r1 = fit(params, data, jax.random.key(1))
    r2 = fit(params, data, jax.random.key(1))
    assert float(tree_global_norm(tree_sub(r1.params, params))) > 0
    np.testing.assert_array_equal(
        np.asarray(r1.params["fc"]["kernel"]), np.asarray(r2.params["fc"]["kernel"])
    )


def test_padding_does_not_affect_result(rng):
    """The correctness trap from SURVEY.md §7: padded samples must be exact no-ops."""
    m = get_model("linear", in_features=4, num_classes=2)
    params = m.init(rng)
    ds = synthetic_classification(32, 2, (4,), seed=3)
    tight = _one(pack_clients(ds, [np.arange(32)], batch_size=8))  # no padding
    padded = _one(pack_clients(ds, [np.arange(32)], batch_size=8, capacity=64))  # 32 pad slots
    # Use 1 epoch without shuffling effects: same seed shuffles differently for n=32 vs 64,
    # so compare against a config with batch_size == capacity (single full batch).
    fit_tight = make_local_fit(m.apply, TrainingConfig(batch_size=32, local_epochs=1))
    fit_pad = make_local_fit(m.apply, TrainingConfig(batch_size=64, local_epochs=1))
    r_tight = fit_tight(params, tight, jax.random.key(0))
    r_pad = fit_pad(params, padded, jax.random.key(0))
    # One full-batch gradient step over identical real samples => identical params.
    np.testing.assert_allclose(
        np.asarray(r_tight.params["fc"]["kernel"]),
        np.asarray(r_pad.params["fc"]["kernel"]),
        rtol=1e-5,
    )
    assert float(r_pad.metrics.samples) == 32.0  # mask-based, not capacity-based


def test_empty_client_is_noop(rng):
    m = get_model("linear", in_features=4, num_classes=2)
    params = m.init(rng)
    empty = ClientData(
        x=jnp.zeros((16, 4)), y=jnp.zeros((16,), jnp.int32), mask=jnp.zeros((16,))
    )
    fit = make_local_fit(m.apply, TrainingConfig(batch_size=8, local_epochs=2))
    res = fit(params, empty, jax.random.key(0))
    np.testing.assert_array_equal(
        np.asarray(res.params["fc"]["kernel"]), np.asarray(params["fc"]["kernel"])
    )
    assert float(res.metrics.samples) == 0.0


def test_max_batches_caps_work(rng):
    m = get_model("linear", in_features=4, num_classes=2)
    params = m.init(rng)
    data = _one(_client(n=64, in_dim=4, batch=8))
    fit_all = make_local_fit(m.apply, TrainingConfig(batch_size=8, collect_batch_metrics=True))
    fit_capped = make_local_fit(
        m.apply, TrainingConfig(batch_size=8, max_batches=2, collect_batch_metrics=True)
    )
    assert fit_all(params, data, jax.random.key(0)).batch_loss.shape == (1, 8)
    assert fit_capped(params, data, jax.random.key(0)).batch_loss.shape == (1, 2)


def test_fedprox_pulls_toward_anchor(rng):
    """With a strong (but stable: lr*mu < 2) prox_mu the local update stays near the
    round's starting params."""
    m = get_model("linear", in_features=8, num_classes=2)
    params = m.init(rng)
    data = _one(_client())
    free = make_local_fit(m.apply, TrainingConfig(batch_size=16, local_epochs=5))
    prox = make_local_fit(m.apply, TrainingConfig(batch_size=16, local_epochs=5, prox_mu=5.0))
    d_free = float(tree_global_norm(tree_sub(free(params, data, jax.random.key(1)).params, params)))
    d_prox = float(tree_global_norm(tree_sub(prox(params, data, jax.random.key(1)).params, params)))
    assert d_prox < d_free * 0.5


def test_vmap_over_clients(rng):
    m = get_model("linear", in_features=8, num_classes=2)
    params = m.init(rng)
    ds = synthetic_classification(96, 2, (8,), seed=0)
    cd = pack_clients(ds, [np.arange(0, 48), np.arange(48, 96)], batch_size=16)
    cd = jax.tree.map(jnp.asarray, cd)
    fit = make_local_fit(m.apply, TrainingConfig(batch_size=16))
    res = jax.vmap(fit, in_axes=(None, 0, 0))(params, cd, jax.random.split(jax.random.key(0), 2))
    assert res.metrics.loss.shape == (2,)
    assert res.params["fc"]["kernel"].shape[0] == 2


def test_evaluator_exact_on_known_params(rng):
    m = get_model("linear", in_features=4, num_classes=2)
    params = m.init(rng)
    data = _one(_client(n=32, in_dim=4, batch=8))
    ev = make_evaluator(m.apply, batch_size=8)
    out = ev(params, data)
    assert 0.0 <= float(out["accuracy"]) <= 1.0
    assert np.isfinite(float(out["loss"]))


def test_trainer_api_with_callbacks(rng, tmp_path):
    m = get_model("linear", in_features=8, num_classes=2)
    params = m.init(rng)
    data = _one(_client())
    sink = MetricsLogger(tmp_path / "metrics.json", client_id="c0")
    trainer = Trainer(
        m.apply,
        TrainingConfig(batch_size=16, local_epochs=3, collect_batch_metrics=True),
        callbacks=[sink],
    )
    new_params, metrics = trainer.fit(params, data, jax.random.key(0))
    assert set(metrics) == {"loss", "accuracy", "samples_processed"}
    import json

    payload = json.loads((tmp_path / "metrics.json").read_text())
    assert payload["client_id"] == "c0"
    assert len(payload["epochs"]) == 3
    assert len(payload["batches"]) == 3 * 4  # 64/16 steps per epoch


def test_trainer_forces_batch_metrics_for_callbacks(rng, tmp_path):
    """Callbacks with the default config must auto-enable collect_batch_metrics."""
    m = get_model("linear", in_features=8, num_classes=2)
    trainer = Trainer(
        m.apply,
        TrainingConfig(batch_size=16, local_epochs=1),  # collect_batch_metrics=False
        callbacks=[MetricsLogger(tmp_path / "m.json")],
    )
    assert trainer.config.collect_batch_metrics
    trainer.fit(m.init(rng), _one(_client()), jax.random.key(0))
    assert (tmp_path / "m.json").exists()


def test_evaluator_handles_misaligned_batch(rng):
    """Eval must never silently drop tail samples (batch_size not dividing n)."""
    m = get_model("linear", in_features=4, num_classes=2)
    params = m.init(rng)
    ds = synthetic_classification(100, 2, (4,), seed=7)
    from nanofed_tpu.data import pack_eval

    data = _one_eval(pack_eval(ds, batch_size=10))  # n=100
    full = make_evaluator(m.apply, batch_size=10)(params, data)
    odd = make_evaluator(m.apply, batch_size=64)(params, data)  # 100 % 64 != 0
    assert float(full["accuracy"]) == pytest.approx(float(odd["accuracy"]), abs=1e-6)
    assert float(full["loss"]) == pytest.approx(float(odd["loss"]), rel=1e-5)


def _one_eval(cd):
    return ClientData(*(jnp.asarray(a) for a in cd))


def test_config_validation():
    with pytest.raises(ValueError):
        TrainingConfig(batch_size=0)
    with pytest.raises(ValueError):
        TrainingConfig(local_epochs=0)
    with pytest.raises(ValueError):
        TrainingConfig(learning_rate=-1.0)
    with pytest.raises(ValueError):
        TrainingConfig(prox_mu=-0.1)
