"""Personalized evaluation: the split discipline, the measurement's purity, and the
claim itself (fine-tuning the global model on a skewed client's data beats the
global model on that client's own test split)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.data import federate, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.trainer import TrainingConfig
from nanofed_tpu.trainer.personalization import (
    make_personalized_evaluator,
    split_client_data,
)


@pytest.fixture(scope="module")
def mlp():
    return get_model("mlp", in_features=16, hidden=32, num_classes=4)


def _skewed(num_clients=8, n=1024):
    ds = synthetic_classification(n, 4, (16,), seed=0)
    return federate(ds, num_clients=num_clients, scheme="label_skew",
                    batch_size=16, shards_per_client=1)


def test_split_is_disjoint_and_respects_padding():
    cd = _skewed()
    train, test = split_client_data(cd, test_fraction=0.25, seed=3)
    m, tr, te = np.asarray(cd.mask), np.asarray(train.mask), np.asarray(test.mask)
    # Disjoint, covering exactly the real samples; padding stays on neither side.
    assert ((tr + te) == m).all()
    assert (tr * te == 0).all()
    # Roughly the requested fraction, and every client kept training samples.
    for c in range(m.shape[0]):
        real = m[c].sum()
        assert tr[c].sum() >= 1
        assert abs(te[c].sum() - 0.25 * real) <= 1


def test_split_single_sample_client_keeps_it_for_training():
    from nanofed_tpu.core.types import ClientData

    mask = np.zeros((2, 8), np.float32)
    mask[0, :4] = 1.0
    mask[1, 0] = 1.0  # one real sample
    cd = ClientData(x=jnp.zeros((2, 8, 3)), y=jnp.zeros((2, 8), jnp.int32),
                    mask=jnp.asarray(mask))
    train, test = split_client_data(cd, test_fraction=0.5, seed=0)
    assert float(np.asarray(train.mask)[1].sum()) == 1.0
    assert float(np.asarray(test.mask)[1].sum()) == 0.0


def test_split_validates_inputs():
    cd = _skewed()
    with pytest.raises(ValueError, match="test_fraction"):
        split_client_data(cd, test_fraction=1.0)
    one = jax.tree.map(lambda a: a[0], cd)
    with pytest.raises(ValueError, match="stacked"):
        split_client_data(one)


def test_personalization_beats_global_under_one_class_shards(mlp, devices):
    """The capability's whole claim: on 1-class shards, a few local fine-tune steps
    from the global initialization dominate the global model on the client's own
    held-out data.  (The global model must spread mass over 4 classes; the
    personalized one needs only the client's.)"""
    cd = _skewed()
    train, test = split_client_data(cd, test_fraction=0.25, seed=0)
    params = mlp.init(jax.random.key(0))
    evaluate = make_personalized_evaluator(
        mlp.apply, TrainingConfig(batch_size=16, local_epochs=3, learning_rate=0.2)
    )
    out = evaluate(params, train, test, jax.random.key(1))
    assert float(out["personal_accuracy"]) > float(out["global_accuracy"]) + 0.2
    assert float(out["personalization_gain"]) == pytest.approx(
        float(out["personal_accuracy"]) - float(out["global_accuracy"]), abs=1e-5
    )
    # Per-client arrays cover the population; weights come from test counts.
    assert out["personal_accuracy_per_client"].shape == (8,)
    assert float(out["test_counts"].sum()) == float(np.asarray(test.mask).sum())


def test_evaluation_is_pure(mlp, devices):
    """A measurement must not move the model: global params are untouched."""
    cd = _skewed()
    train, test = split_client_data(cd, test_fraction=0.25, seed=0)
    params = mlp.init(jax.random.key(0))
    before = jax.tree.map(lambda x: np.array(x), params)
    evaluate = make_personalized_evaluator(
        mlp.apply, TrainingConfig(batch_size=16, local_epochs=2, learning_rate=0.2)
    )
    evaluate(params, train, test, jax.random.key(1))
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(params)):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_empty_test_clients_carry_zero_weight(mlp, devices):
    """A client with no test samples must not dilute the population means."""
    cd = _skewed(num_clients=4)
    train, test = split_client_data(cd, test_fraction=0.25, seed=0)
    # Zero out client 0's test mask entirely.
    tm = np.asarray(test.mask).copy()
    tm[0] = 0.0
    test = test._replace(mask=jnp.asarray(tm))
    params = mlp.init(jax.random.key(0))
    evaluate = make_personalized_evaluator(
        mlp.apply, TrainingConfig(batch_size=16, local_epochs=1, learning_rate=0.2)
    )
    out = evaluate(params, train, test, jax.random.key(1))
    w = np.asarray(out["test_counts"])
    assert w[0] == 0.0
    manual = float((np.asarray(out["personal_accuracy_per_client"]) * w).sum() / w.sum())
    assert float(out["personal_accuracy"]) == pytest.approx(manual, abs=1e-6)
