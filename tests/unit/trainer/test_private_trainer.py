"""DP-SGD trainer tests (parity: ``tests/unit/trainer/test_private_trainer.py`` —
clipping, noise, budget behaviors — plus per-example-clipping checks the reference can't
make)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.core.types import ClientData
from nanofed_tpu.data import pack_clients, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.privacy import GaussianAccountant, PrivacyConfig, RDPAccountant
from nanofed_tpu.trainer import (
    TrainingConfig,
    local_fit_noise_events,
    make_dp_grad_fn,
    make_private_local_fit,
    record_local_fit,
    validate_privacy_budget,
)
from nanofed_tpu.trainer.private import get_privacy_spent
from nanofed_tpu.utils.trees import tree_global_norm, tree_sub


def _data(n=64, in_dim=8, classes=2, batch=16, seed=0):
    ds = synthetic_classification(n, classes, (in_dim,), seed=seed)
    cd = pack_clients(ds, [np.arange(n)], batch_size=batch)
    return ClientData(*(jnp.asarray(a[0]) for a in cd))


def _model(rng, in_dim=8, classes=2):
    m = get_model("linear", in_features=in_dim, num_classes=classes)
    return m, m.init(rng)


class TestDPGradFn:
    def test_grad_norm_bounded_by_clip(self, rng):
        """With negligible noise, the DP gradient's norm is ≤ C (mean of ≤C-norm terms)."""
        m, params = _model(rng)
        cfg = PrivacyConfig(max_gradient_norm=0.05, noise_multiplier=1e-6)
        grad_fn = make_dp_grad_fn(m.apply, cfg)
        d = _data()
        xb, yb, mb = d.x[:16], d.y[:16], d.mask[:16]
        grads, stats = grad_fn(params, xb, yb, mb, jax.random.key(1))
        assert float(tree_global_norm(grads)) <= 0.05 * 1.001
        assert float(stats.count) == 16.0

    def test_noise_changes_grads(self, rng):
        m, params = _model(rng)
        d = _data()
        xb, yb, mb = d.x[:16], d.y[:16], d.mask[:16]
        quiet = make_dp_grad_fn(m.apply, PrivacyConfig(noise_multiplier=1e-6))
        loud = make_dp_grad_fn(m.apply, PrivacyConfig(noise_multiplier=5.0))
        g0, _ = quiet(params, xb, yb, mb, jax.random.key(1))
        g1, _ = loud(params, xb, yb, mb, jax.random.key(1))
        assert float(tree_global_norm(tree_sub(g0, g1))) > 0.1

    def test_padded_examples_contribute_nothing(self, rng):
        """A padded example's clipped per-example gradient is zeroed before the sum."""
        m, params = _model(rng, in_dim=4)
        ds = synthetic_classification(16, 2, (4,), seed=1)
        cfg = PrivacyConfig(max_gradient_norm=1.0, noise_multiplier=1e-6)
        grad_fn = make_dp_grad_fn(m.apply, cfg)
        x = jnp.asarray(ds.x)
        y = jnp.asarray(ds.y)
        half_mask = jnp.concatenate([jnp.ones(8), jnp.zeros(8)])
        # Same real data, garbage in the padded slots:
        x_garbage = x.at[8:].set(1e3)
        g_ref, s_ref = grad_fn(params, x, y, half_mask, jax.random.key(2))
        g_pad, s_pad = grad_fn(params, x_garbage, y, half_mask, jax.random.key(2))
        np.testing.assert_allclose(
            np.asarray(jax.flatten_util.ravel_pytree(g_ref)[0]),
            np.asarray(jax.flatten_util.ravel_pytree(g_pad)[0]),
            rtol=1e-5,
        )
        assert float(s_ref.count) == 8.0 == float(s_pad.count)


class TestPrivateLocalFit:
    def test_trains_and_is_deterministic(self, rng):
        m, params = _model(rng)
        fit = jax.jit(
            make_private_local_fit(
                m.apply,
                TrainingConfig(batch_size=16, local_epochs=3),
                PrivacyConfig(max_gradient_norm=1.0, noise_multiplier=0.5),
            )
        )
        d = _data()
        r1 = fit(params, d, jax.random.key(1))
        r2 = fit(params, d, jax.random.key(1))
        assert float(r1.epoch_loss[-1]) < float(r1.epoch_loss[0])
        np.testing.assert_array_equal(np.asarray(r1.epoch_loss), np.asarray(r2.epoch_loss))

    def test_vmaps_over_clients(self, rng):
        m, params = _model(rng)
        fit = make_private_local_fit(
            m.apply, TrainingConfig(batch_size=16, local_epochs=1), PrivacyConfig()
        )
        ds = synthetic_classification(128, 2, (8,), seed=0)
        cd = pack_clients(ds, [np.arange(64), np.arange(64, 128)], batch_size=16)
        stacked = ClientData(*(jnp.asarray(a) for a in cd))
        keys = jax.random.split(jax.random.key(1), 2)
        res = jax.vmap(fit, in_axes=(None, 0, 0))(params, stacked, keys)
        assert res.metrics.loss.shape == (2,)
        assert np.isfinite(np.asarray(res.metrics.loss)).all()


class TestAccountingIntegration:
    def test_event_count_static(self):
        cfg = TrainingConfig(batch_size=16, local_epochs=3)
        assert local_fit_noise_events(cfg, data_capacity=64) == 12
        capped = TrainingConfig(batch_size=16, local_epochs=3, max_batches=2)
        assert local_fit_noise_events(capped, data_capacity=64) == 6

    def test_record_uses_true_sampling_rate(self):
        acc = RDPAccountant()
        t = TrainingConfig(batch_size=16, local_epochs=1)
        p = PrivacyConfig(noise_multiplier=1.0)
        record_local_fit(acc, p, t, data_capacity=64, num_samples=64)
        # q = 16/64 = 0.25, 4 events
        assert acc.state_dict()["events"] == [[1.0, 0.25, 4.0]]

    def test_budget_validation_flips(self):
        acc = GaussianAccountant()
        p = PrivacyConfig(epsilon=0.5, delta=1e-5, noise_multiplier=1.0)
        t = TrainingConfig(batch_size=32, local_epochs=1)
        assert validate_privacy_budget(acc, p)
        for _ in range(50):
            record_local_fit(acc, p, t, data_capacity=6400, num_samples=6400)
        assert not validate_privacy_budget(acc, p)
        assert get_privacy_spent(acc, p).epsilon_spent > 0.5
