"""Per-round lr schedules: host-side math + the traced-scale contract in the round step.

The reference has no lr scheduling at all; here the design constraint is TPU-specific —
a schedule must not recompile the round program (baking lr into the static
TrainingConfig would re-trace every round), so the scale rides as a traced scalar and
these tests pin (a) the schedule arithmetic, (b) that scaling is EXACTLY equivalent to
changing the configured lr, and (c) that varying the scale across calls reuses one
compiled program.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.trainer import TrainingConfig, make_local_fit, stack_rngs
from nanofed_tpu.trainer.schedules import SCHEDULES, lr_schedule_scale


# --- schedule arithmetic -----------------------------------------------------------


def test_constant_is_always_one():
    assert all(lr_schedule_scale("constant", r, 10) == 1.0 for r in range(12))


def test_cosine_endpoints_and_monotonicity():
    scales = [lr_schedule_scale("cosine", r, 10, min_factor=0.1) for r in range(10)]
    assert scales[0] == pytest.approx(1.0)
    # The last TRAINED round sits one step above the floor — landing exactly on a
    # min_factor=0 floor would make the final round a full-cost silent no-op.
    assert 0.1 < scales[-1] < 0.2
    assert all(a >= b for a, b in zip(scales, scales[1:]))  # monotone decreasing
    # Past the planned horizon: hold the terminal value, don't extrapolate.
    assert lr_schedule_scale("cosine", 10, 10, min_factor=0.1) == pytest.approx(0.1)
    assert lr_schedule_scale("cosine", 25, 10, min_factor=0.1) == pytest.approx(0.1)


def test_cosine_default_floor_never_zeroes_a_trained_round():
    # The default min_factor=0.0 must never hand a scheduled round scale 0.0 — that
    # round would train every client and discard every update.
    scales = [lr_schedule_scale("cosine", r, 50) for r in range(50)]
    assert min(scales) > 0.0


def test_linear_is_a_straight_line():
    scales = [lr_schedule_scale("linear", r, 5, min_factor=0.5) for r in range(5)]
    np.testing.assert_allclose(scales, [1.0, 0.9, 0.8, 0.7, 0.6], atol=1e-9)
    assert lr_schedule_scale("linear", 5, 5, min_factor=0.5) == pytest.approx(0.5)


def test_step_staircase_floor_and_horizon_hold():
    assert lr_schedule_scale("step", 0, 100, decay_every=10) == 1.0
    assert lr_schedule_scale("step", 9, 100, decay_every=10) == 1.0
    assert lr_schedule_scale("step", 10, 100, decay_every=10) == 0.5
    assert lr_schedule_scale("step", 29, 100, decay_every=10) == 0.25
    assert lr_schedule_scale(
        "step", 90, 100, decay_every=10, gamma=0.5, min_factor=0.1
    ) == pytest.approx(0.1)  # floored, not 0.5**9
    # Past the horizon: hold the round total_rounds-1 value (docstring contract),
    # don't keep decaying forever on an extended/resumed run.
    held = lr_schedule_scale("step", 19, 20, decay_every=10)
    assert lr_schedule_scale("step", 50, 20, decay_every=10) == held == 0.5


def test_single_round_run_has_no_room_to_decay():
    for s in ("cosine", "linear"):
        assert lr_schedule_scale(s, 0, 1, min_factor=0.0) == 1.0


def test_invalid_inputs_raise():
    with pytest.raises(ValueError, match="unknown lr schedule"):
        lr_schedule_scale("exponential", 0, 10)
    with pytest.raises(ValueError, match="min_factor"):
        lr_schedule_scale("cosine", 0, 10, min_factor=1.5)
    with pytest.raises(ValueError, match="decay_every"):
        lr_schedule_scale("step", 0, 10, decay_every=0)
    # gamma=0 would zero every post-decay round's updates (full-cost no-ops);
    # gamma>1 would silently GROW the lr.
    with pytest.raises(ValueError, match="gamma"):
        lr_schedule_scale("step", 0, 10, gamma=0.0)
    with pytest.raises(ValueError, match="gamma"):
        lr_schedule_scale("step", 0, 10, gamma=1.5)
    assert set(SCHEDULES) == {"constant", "cosine", "linear", "step"}


def test_coordinator_config_validates_gamma(tmp_path):
    from nanofed_tpu.orchestration import CoordinatorConfig

    with pytest.raises(ValueError, match="lr_decay_gamma"):
        CoordinatorConfig(num_rounds=2, lr_schedule="step", lr_decay_gamma=0.0)


# --- the traced scale in local_fit -------------------------------------------------


def _tiny_client(seed=0, n=8, d=4):
    from nanofed_tpu.core.types import ClientData

    rng = np.random.default_rng(seed)
    return ClientData(
        x=jnp.asarray(rng.normal(size=(n, d)), jnp.float32),
        y=jnp.asarray(rng.integers(0, 2, size=n)),
        mask=jnp.ones((n,), jnp.float32),
    )


def _params_of(fit, params, data, rng, lr_scale=None):
    out = fit(params, data, rng, lr_scale) if lr_scale is not None else fit(
        params, data, rng
    )
    return out.params


def test_lr_scale_equals_configured_lr(monkeypatch):
    """fit(lr=0.2, scale=0.5) must equal fit(lr=0.1) — including with momentum and
    FedProx, where the scale multiplies the post-momentum step exactly like lr."""
    from nanofed_tpu.models import get_model

    model = get_model("linear", in_features=4, num_classes=2)
    params = model.init(jax.random.key(0))
    data = _tiny_client()
    rng = jax.random.key(7)
    for extra in ({}, {"momentum": 0.9}, {"prox_mu": 0.1}):
        fit_hi = make_local_fit(
            model.apply, TrainingConfig(batch_size=4, local_epochs=2,
                                        learning_rate=0.2, **extra))
        fit_lo = make_local_fit(
            model.apply, TrainingConfig(batch_size=4, local_epochs=2,
                                        learning_rate=0.1, **extra))
        scaled = _params_of(jax.jit(fit_hi), params, data, rng,
                            lr_scale=jnp.float32(0.5))
        direct = _params_of(jax.jit(fit_lo), params, data, rng)
        for a, b in zip(jax.tree.leaves(scaled), jax.tree.leaves(direct)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_lr_scale_zero_freezes_params():
    from nanofed_tpu.models import get_model

    model = get_model("linear", in_features=4, num_classes=2)
    params = model.init(jax.random.key(0))
    fit = make_local_fit(model.apply, TrainingConfig(batch_size=4, local_epochs=3))
    out = fit(params, _tiny_client(), jax.random.key(1), jnp.float32(0.0))
    for a, b in zip(jax.tree.leaves(out.params), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert getattr(fit, "supports_lr_scale", False) is True


# --- the traced scale through the full SPMD round step -----------------------------


def test_round_step_lr_scale_varies_without_retrace(devices):
    """Different scales across rounds = one compiled program (the whole point of a
    traced scale), and scale semantics survive shard_map + vmap + the streaming
    chunk path."""
    from nanofed_tpu.data import pack_clients, synthetic_classification
    from nanofed_tpu.models import get_model
    from nanofed_tpu.parallel import (
        build_round_step,
        init_server_state,
        make_mesh,
        pad_client_count,
        pad_clients,
        replicated_sharding,
        shard_client_data,
    )
    from nanofed_tpu.aggregation import compute_weights, fedavg_strategy

    model = get_model("linear", in_features=6, num_classes=2)
    mesh = make_mesh()
    n_dev = len(mesh.devices.flat)
    ds = synthetic_classification(64, 2, (6,), seed=0)
    data = pack_clients(ds, [np.arange(i * 8, (i + 1) * 8) for i in range(8)],
                        batch_size=4)
    padded = pad_client_count(8, n_dev)
    data = shard_client_data(pad_clients(data, padded), mesh)
    num_samples = jnp.asarray(np.asarray(data.mask).sum(axis=1))
    weights = compute_weights(num_samples) * (num_samples > 0)
    strategy = fedavg_strategy()
    repl = replicated_sharding(mesh)
    params = jax.device_put(model.init(jax.random.key(0)), repl)
    sos = jax.device_put(init_server_state(strategy, params), repl)
    training = TrainingConfig(batch_size=4, local_epochs=1, learning_rate=0.2)

    # chunked (streaming reduce) so the scale is pinned through that path too
    step = build_round_step(model.apply, training, mesh, strategy, client_chunk=1)

    with jax.log_compiles(False):
        r1 = step(params, sos, data, weights,
                  stack_rngs(jax.random.key(1), padded), jnp.float32(1.0))
        n_compiles_after_first = step._cache_size()
        r2 = step(params, sos, data, weights,
                  stack_rngs(jax.random.key(1), padded), jnp.float32(0.25))
        assert step._cache_size() == n_compiles_after_first  # no retrace

    # Same rngs: the 0.25-scaled round must differ from full-rate (it trained) but
    # equal a quarter-lr config bit-for-bit.
    step_q = build_round_step(
        model.apply,
        TrainingConfig(batch_size=4, local_epochs=1, learning_rate=0.05),
        mesh, strategy, client_chunk=1,
    )
    rq = step_q(params, sos, data, weights, stack_rngs(jax.random.key(1), padded))
    for a, b in zip(jax.tree.leaves(r2.params), jax.tree.leaves(rq.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(r1.params), jax.tree.leaves(r2.params))
    )
    assert changed


def test_coordinator_cosine_schedule_end_to_end(tmp_path, devices):
    """A scheduled Coordinator runs, reports lr_scale per round, and its terminal
    round trains at ~min_factor."""
    from nanofed_tpu.data import federate, synthetic_classification
    from nanofed_tpu.models import get_model
    from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig

    cd = federate(synthetic_classification(64, 2, (6,), seed=0), num_clients=8,
                  scheme="iid", batch_size=4)
    coord = Coordinator(
        model=get_model("linear", in_features=6, num_classes=2),
        train_data=cd,
        config=CoordinatorConfig(num_rounds=4, seed=0, base_dir=tmp_path,
                                 save_metrics=False, lr_schedule="cosine",
                                 lr_min_factor=0.1),
        training=TrainingConfig(batch_size=4, local_epochs=1),
    )
    history = coord.run()
    scales = [m.agg_metrics["lr_scale"] for m in history]
    assert scales[0] == pytest.approx(1.0)
    # round 3 of 4: frac 0.75 -> 0.1 + 0.9*0.5*(1+cos(0.75*pi)) — above the floor
    # (the final trained round never lands ON min_factor).
    assert scales[-1] == pytest.approx(0.2318, abs=1e-3)
    assert all(a >= b for a, b in zip(scales, scales[1:]))


def test_coordinator_refuses_schedule_with_unaware_custom_fit(tmp_path, devices):
    from nanofed_tpu.data import federate, synthetic_classification
    from nanofed_tpu.models import get_model
    from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig

    model = get_model("linear", in_features=6, num_classes=2)
    cd = federate(synthetic_classification(64, 2, (6,), seed=0), num_clients=8,
                  scheme="iid", batch_size=4)

    def legacy_fit(gp, data, rng):  # no lr_scale, no marker
        raise NotImplementedError

    with pytest.raises(ValueError, match="supports_lr_scale"):
        Coordinator(
            model=model, train_data=cd,
            config=CoordinatorConfig(num_rounds=2, seed=0, base_dir=tmp_path,
                                     save_metrics=False, lr_schedule="cosine"),
            training=TrainingConfig(batch_size=4),
            local_fit=legacy_fit,
        )
