"""Mixed precision: bf16 compute must track fp32 training closely while keeping params,
gradients, and updates in float32."""

import jax
import jax.numpy as jnp
import numpy as np

from nanofed_tpu.core.types import ClientData
from nanofed_tpu.models import get_model
from nanofed_tpu.trainer import TrainingConfig
from nanofed_tpu.trainer.local import make_local_fit


def _data(seed=0, n=64, d=16, k=4):
    r = np.random.default_rng(seed)
    x = r.normal(size=(n, d)).astype(np.float32)
    w = r.normal(size=(d, k))
    y = np.argmax(x @ w, axis=1)
    return ClientData(x=jnp.asarray(x), y=jnp.asarray(y), mask=jnp.ones((n,)))


def test_bf16_params_stay_float32_and_loss_tracks_fp32():
    model = get_model("mlp", in_features=16, hidden=32, num_classes=4)
    params = model.init(jax.random.key(0))
    data = _data()
    rng = jax.random.key(1)

    fit32 = make_local_fit(
        model.apply, TrainingConfig(batch_size=16, local_epochs=5, learning_rate=0.1)
    )
    fit16 = make_local_fit(
        model.apply,
        TrainingConfig(
            batch_size=16, local_epochs=5, learning_rate=0.1, compute_dtype="bfloat16"
        ),
    )
    r32 = jax.jit(fit32)(params, data, rng)
    r16 = jax.jit(fit16)(params, data, rng)

    # Master params (and therefore the update) remain float32.
    for leaf in jax.tree.leaves(r16.params):
        assert leaf.dtype == jnp.float32
    # Both converge on the linearly-separable problem; epoch losses stay close.
    assert float(r16.epoch_loss[-1]) < float(r16.epoch_loss[0])
    np.testing.assert_allclose(
        np.asarray(r16.epoch_loss), np.asarray(r32.epoch_loss), rtol=0.15, atol=0.05
    )
    assert abs(float(r16.metrics.accuracy) - float(r32.metrics.accuracy)) < 0.1


def test_compute_dtype_threads_through_round_step(devices):
    from nanofed_tpu.aggregation import compute_weights, fedavg_strategy
    from nanofed_tpu.parallel import (
        build_round_step,
        init_server_state,
        make_mesh,
        shard_client_data,
    )
    from nanofed_tpu.trainer import stack_rngs

    mesh = make_mesh(devices)
    model = get_model("mlp", in_features=16, hidden=8, num_classes=4)
    c = 8
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *[_data(i, n=16) for i in range(c)])
    data = shard_client_data(stacked, mesh)
    training = TrainingConfig(
        batch_size=8, local_epochs=1, learning_rate=0.1, compute_dtype="bfloat16"
    )
    step = build_round_step(model.apply, training, mesh, fedavg_strategy())
    params = model.init(jax.random.key(0))
    sos = init_server_state(fedavg_strategy(), params)
    res = step(params, sos, data, compute_weights(data.num_samples),
               stack_rngs(jax.random.key(0), c))
    assert np.isfinite(float(res.metrics["loss"]))
    for leaf in jax.tree.leaves(res.params):
        assert leaf.dtype == jnp.float32
