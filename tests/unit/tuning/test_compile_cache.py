"""Compile-cache lifecycle tests: the hit/miss counter bridge, the manifest
round-trip + toolchain verification, and the ``warm()`` pre-compile pass."""

import json

import jax
import jax.numpy as jnp
import pytest

from nanofed_tpu.models import get_model
from nanofed_tpu.observability.registry import MetricsRegistry
from nanofed_tpu.trainer import TrainingConfig
from nanofed_tpu.tuning import (
    PopulationSpec,
    TuningSpace,
    build_manifest,
    verify_manifest,
    warm,
    write_manifest,
)
from nanofed_tpu.tuning import compile_cache
from nanofed_tpu.utils.platform import enable_compilation_cache
from nanofed_tpu.tuning.compile_cache import (
    COMPILE_CACHE_HITS,
    COMPILE_CACHE_MISSES,
    install_compile_cache_metrics,
)

MODEL = get_model("digits_mlp")
POP = PopulationSpec(num_clients=8, capacity=32, sample_shape=(8, 8, 1))
TRAINING = TrainingConfig(batch_size=16, local_epochs=1, learning_rate=0.1)
ONE_CAND_SPACE = TuningSpace(
    client_chunks=(None,), rounds_per_blocks=(1,), model_shards=(1,),
    batch_sizes=(16,),
)

# jax.monitoring keeps listeners forever, so the FIRST install in the process
# wins the registry (another test in the same pytest run — e.g. warm() — may
# have already installed with the default registry); read the counters from
# whichever registry the bridge actually adopted.
REGISTRY = MetricsRegistry()


def adopted_registry() -> MetricsRegistry:
    assert install_compile_cache_metrics(REGISTRY) is True
    return compile_cache._metrics_registry


class TestCounterBridge:
    def test_install_is_idempotent(self):
        reg = adopted_registry()
        assert install_compile_cache_metrics(MetricsRegistry()) is True
        # Later registries are NOT adopted (first-caller rule) — the counters
        # live in the first caller's registry and nowhere else.
        assert COMPILE_CACHE_HITS in reg.snapshot()

    def test_miss_then_hit_counted(self, tmp_path):
        REGISTRY = adopted_registry()
        # Route through enable_compilation_cache: it resets jax's latched
        # cache object, so this works even after earlier tests compiled with
        # a different (or no) cache dir in this process.
        enable_compilation_cache(tmp_path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            def misses():
                snap = REGISTRY.snapshot()
                return snap[COMPILE_CACHE_MISSES]["values"].get("", 0)

            def hits():
                snap = REGISTRY.snapshot()
                return snap[COMPILE_CACHE_HITS]["values"].get("", 0)

            m0, h0 = misses(), hits()
            x = jnp.ones((16, 16))
            jax.jit(lambda a: jnp.tanh(a) @ a.T)(x).block_until_ready()
            # XLA emits one miss event per cached module part, so assert
            # direction, not an exact count.
            m1, h1 = misses(), hits()
            assert m1 > m0 and h1 == h0
            # A DISTINCT jit of the same jaxpr replays from the persistent
            # cache: hits, no new miss.
            jax.jit(lambda a: jnp.tanh(a) @ a.T)(x).block_until_ready()
            assert hits() > h1 and misses() == m1
        finally:
            jax.config.update("jax_compilation_cache_dir", None)


class TestManifest:
    def test_build_and_write_round_trip(self, tmp_path):
        (tmp_path / "xla_entry_0").write_bytes(b"\x00" * 64)
        (tmp_path / "autotune_deadbeef.json").write_text(json.dumps(
            {"cache_key": "deadbeef" * 8, "winner": {"rounds_per_block": 2}}
        ))
        path = write_manifest(tmp_path)
        d = json.loads(path.read_text())
        assert d["xla_entries"] == 1 and d["xla_bytes"] == 64
        assert d["autotune_entries"][0]["cache_key"] == ("deadbeef" * 8)[:16]
        assert d["autotune_entries"][0]["winner"] == {"rounds_per_block": 2}
        assert d["toolchain"]["jax"] == str(jax.__version__)
        # Re-building does not count the manifest itself as an entry.
        assert build_manifest(tmp_path)["xla_entries"] == 1

    def test_verify_matching_toolchain(self, tmp_path):
        write_manifest(tmp_path)
        v = verify_manifest(tmp_path)
        assert v["compatible"] is True and v["reasons"] == []

    def test_verify_flags_foreign_jaxlib(self, tmp_path, monkeypatch):
        write_manifest(tmp_path)
        import jaxlib

        monkeypatch.setattr(jaxlib, "__version__", "0.0.0-foreign", raising=False)
        v = verify_manifest(tmp_path)
        assert v["compatible"] is False
        assert any("jaxlib" in r for r in v["reasons"])

    def test_verify_missing_manifest_is_stated_not_raised(self, tmp_path):
        v = verify_manifest(tmp_path / "nowhere")
        assert v["compatible"] is False
        assert any("no manifest" in r for r in v["reasons"])
        assert v["manifest"] is None


class TestWarm:
    def test_warm_compiles_and_stamps_manifest(self, tmp_path):
        cache = tmp_path / "cache"
        result = warm(
            MODEL, POP, TRAINING, num_rounds=2, space=ONE_CAND_SPACE,
            cache_dir=cache,
        )
        assert result.autotune.compiles == 1
        assert result.programs[0]["program"].startswith("cand_")
        assert result.programs[0]["compile_seconds"] > 0
        d = json.loads((cache / "manifest.json").read_text())
        assert d["warmed"]["compiles"] == 1
        assert d["warmed"]["model"] == MODEL.name
        assert d["warmed"]["cache_key"] == result.autotune.cache_key[:16]
        # The sweep table itself shipped into the cache dir.
        assert d["autotune_entries"]
        assert verify_manifest(cache)["compatible"] is True

    def test_rewarm_hits_the_autotune_cache(self, tmp_path):
        cache = tmp_path / "cache"
        warm(MODEL, POP, TRAINING, num_rounds=2, space=ONE_CAND_SPACE,
             cache_dir=cache)
        again = warm(MODEL, POP, TRAINING, num_rounds=2, space=ONE_CAND_SPACE,
                     cache_dir=cache)
        assert again.autotune.cache_hit is True
        assert again.autotune.compiles == 0
        assert again.programs == []
        manifest = json.loads((cache / "manifest.json").read_text())
        assert manifest["warmed"]["cache_hit"] is True

    def test_warm_emits_compile_records(self, tmp_path):
        class FakeTelemetry:
            def __init__(self):
                self.records = []

            def record(self, rtype, **fields):
                self.records.append({"type": rtype, **fields})

        tel = FakeTelemetry()
        warm(
            MODEL, POP, TRAINING, num_rounds=2, space=ONE_CAND_SPACE,
            cache_dir=tmp_path / "cache", telemetry=tel, force=True,
        )
        assert [r for r in tel.records if r["type"] == "compile"]
