"""OnlineRetuner unit tests: the measurement table, the eligibility scope
rule, hysteresis, the AOT-disagreement swap, and the cache write-back —
all pure control-loop arithmetic, zero compiles."""

import json

import pytest

from nanofed_tpu.tuning import (
    AutotuneResult,
    CandidateConfig,
    CandidateOutcome,
    OnlineRetuner,
)
from nanofed_tpu.tuning.autotuner import candidate_program_name

# The AOT table ranks RPB4 best (score 1.0) over RPB1 (score 2.0) —
# measurements will say otherwise.
RPB4 = CandidateConfig(None, 4, 1, 16)
RPB1 = CandidateConfig(None, 1, 1, 16)
CHUNKED = CandidateConfig(2, 1, 1, 16)
OTHER_MESH = CandidateConfig(None, 1, 2, 16)
OTHER_BATCH = CandidateConfig(None, 1, 1, 32)


def make_result(tmp_path=None, cache_key="k" * 64):
    outcomes = [
        CandidateOutcome(RPB4, True, score=1.0,
                         cost={"peak_bytes": 10, "compile_seconds": 1.0}),
        CandidateOutcome(RPB1, True, score=2.0,
                         cost={"peak_bytes": 5, "compile_seconds": 0.5}),
        CandidateOutcome(CHUNKED, True, score=3.0,
                         cost={"peak_bytes": 4, "compile_seconds": 0.5}),
        CandidateOutcome(OTHER_MESH, True, score=0.5,
                         cost={"peak_bytes": 6, "compile_seconds": 2.0}),
        CandidateOutcome(OTHER_BATCH, True, score=0.4,
                         cost={"peak_bytes": 6, "compile_seconds": 2.0}),
    ]
    return AutotuneResult(
        winner=RPB4, outcomes=outcomes,
        scoring_basis="test", platform="cpu", device_kind="cpu",
        num_devices=1, hbm_budget_bytes=None, budget_basis="none",
        cache_key=cache_key,
    )


def retuner(**kw):
    kw.setdefault("cache_dir", None)
    return OnlineRetuner(make_result(), **kw)


class TestObserve:
    def test_accumulates_and_averages(self):
        rt = retuner()
        rt.observe(RPB4, rounds=4, walltime_s=2.0, occupancy=0.5)
        rt.observe(RPB4, rounds=4, walltime_s=4.0, occupancy=0.7)
        assert rt.measured_s_per_round(RPB4) == pytest.approx(0.75)
        table = rt.measured_table()
        row = table[candidate_program_name(RPB4)]
        assert row["rounds"] == 8
        assert row["occupancy_mean"] == pytest.approx(0.6)

    def test_garbage_measurements_dropped(self):
        rt = retuner()
        rt.observe(RPB4, rounds=0, walltime_s=1.0)
        rt.observe(RPB4, rounds=2, walltime_s=float("nan"))
        rt.observe(RPB4, rounds=2, walltime_s=-1.0)
        assert rt.measured_s_per_round(RPB4) is None


class TestPropose:
    def test_insufficient_measurements_holds(self):
        rt = retuner(min_rounds=4)
        rt.observe(RPB4, rounds=2, walltime_s=1.0)
        d = rt.propose(RPB4)
        assert not d.swap
        assert "insufficient measurements" in d.reason

    def test_measured_ranking_beats_aot_ranking(self):
        """The headline loop: AOT ranked RPB4 over RPB1 (score 1.0 < 2.0), but
        measurements say RPB4 realizes 1.0 s/round — the calibrated estimate
        for RPB1 wins only if its own MEASUREMENT says so."""
        rt = retuner()
        rt.observe(RPB4, rounds=4, walltime_s=4.0)     # 1.0 s/round realized
        rt.observe(RPB1, rounds=2, walltime_s=0.5)     # 0.25 s/round realized
        d = rt.propose(RPB4)
        assert d.swap and d.new == RPB1
        assert d.basis == "measured"
        assert d.measured_s_per_round == pytest.approx(1.0)
        assert d.candidate_s_per_round == pytest.approx(0.25)
        assert d.delta == pytest.approx(0.75)

    def test_calibrated_estimate_never_swaps_uphill(self):
        """With only the incumbent measured, estimates scale by AOT score
        ratio — every alternative scores WORSE than the incumbent here, so
        no estimate can cross the hysteresis bar."""
        rt = retuner()
        rt.observe(RPB4, rounds=4, walltime_s=4.0)
        d = rt.propose(RPB4)
        assert not d.swap
        assert "hysteresis" in d.reason

    def test_calibrated_estimate_can_swap_downhill(self):
        """Incumbent RPB1 (score 2.0) measured; RPB4 (score 1.0) estimates at
        half the measured time — swap fires on the estimate basis."""
        rt = retuner()
        rt.observe(RPB1, rounds=4, walltime_s=4.0)
        d = rt.propose(RPB1)
        assert d.swap and d.new == RPB4
        assert d.basis.startswith("estimated")
        assert d.candidate_s_per_round == pytest.approx(0.5)

    def test_hysteresis_blocks_marginal_wins(self):
        rt = retuner(hysteresis=0.2)
        rt.observe(RPB4, rounds=4, walltime_s=4.0)
        rt.observe(RPB1, rounds=4, walltime_s=3.6)  # only 10% better
        d = rt.propose(RPB4)
        assert not d.swap
        assert "hysteresis" in d.reason
        assert d.candidate_s_per_round == pytest.approx(0.9)

    def test_scope_rule_marks_ineligible_with_reasons(self):
        """Mesh/batch/rank-changing candidates would reshard the resident
        world — they are considered, stated ineligible, never swapped to."""
        rt = retuner()
        rt.observe(RPB4, rounds=4, walltime_s=4.0)
        rt.observe(OTHER_MESH, rounds=4, walltime_s=0.1)   # fastest, ineligible
        rt.observe(OTHER_BATCH, rounds=4, walltime_s=0.1)
        d = rt.propose(RPB4)
        assert d.new != OTHER_MESH and d.new != OTHER_BATCH
        rows = {json.dumps(r["config"], sort_keys=True): r for r in d.considered}
        mesh_row = rows[json.dumps(OTHER_MESH.to_dict(), sort_keys=True)]
        batch_row = rows[json.dumps(OTHER_BATCH.to_dict(), sort_keys=True)]
        assert "mesh shape" in mesh_row["ineligible"]
        assert "batch size" in batch_row["ineligible"]

    def test_decision_serializes_for_telemetry(self):
        rt = retuner()
        rt.observe(RPB4, rounds=4, walltime_s=4.0)
        rt.observe(RPB1, rounds=4, walltime_s=1.0)
        d = rt.propose(RPB4).to_dict()
        assert d["swap"] is True
        assert d["old_program"] == candidate_program_name(RPB4)
        assert d["new_program"] == candidate_program_name(RPB1)
        assert d["considered"]
        json.dumps(d)  # JSON-clean

    def test_summary_counts_swaps(self):
        rt = retuner()
        rt.observe(RPB4, rounds=4, walltime_s=4.0)
        rt.propose(RPB4)                    # hold (hysteresis)
        rt.observe(RPB1, rounds=4, walltime_s=1.0)
        rt.propose(RPB4)                    # swap
        s = rt.summary()
        assert s["decisions"] == 2 and s["swaps"] == 1
        assert s["swap_history"][0]["new"] == RPB1.to_dict()


class TestWriteBack:
    def _seed_cache(self, tmp_path, result):
        path = tmp_path / f"autotune_{result.cache_key[:16]}.json"
        path.write_text(json.dumps(result.to_dict()))
        return path

    def test_measured_numbers_land_in_cache_entry(self, tmp_path):
        result = make_result()
        path = self._seed_cache(tmp_path, result)
        rt = OnlineRetuner(result, cache_dir=tmp_path)
        rt.observe(RPB4, rounds=4, walltime_s=4.0, occupancy=0.8)
        rt.observe(RPB1, rounds=4, walltime_s=1.0)
        rt.propose(RPB4)
        out = rt.write_back()
        assert out == path
        d = json.loads(path.read_text())
        by_cfg = {
            json.dumps(c["config"], sort_keys=True): c for c in d["candidates"]
        }
        row4 = by_cfg[json.dumps(RPB4.to_dict(), sort_keys=True)]
        row1 = by_cfg[json.dumps(RPB1.to_dict(), sort_keys=True)]
        assert row4["cost"]["measured_s_per_round"] == pytest.approx(1.0)
        assert row4["cost"]["measured_rounds"] == 4
        assert row4["cost"]["measured_occupancy_mean"] == pytest.approx(0.8)
        assert row1["cost"]["measured_s_per_round"] == pytest.approx(0.25)
        # The AOT numbers survive beside the measured ones.
        assert row4["cost"]["compile_seconds"] == 1.0
        assert d["measured"]["swaps"][0]["new"] == RPB1.to_dict()
        assert d["cache_key"] == result.cache_key

    def test_foreign_cache_entry_left_alone(self, tmp_path):
        result = make_result()
        path = tmp_path / f"autotune_{result.cache_key[:16]}.json"
        path.write_text(json.dumps({"cache_key": "different"}))
        rt = OnlineRetuner(result, cache_dir=tmp_path)
        rt.observe(RPB4, rounds=4, walltime_s=4.0)
        assert rt.write_back() is None
        assert json.loads(path.read_text()) == {"cache_key": "different"}

    def test_nothing_measured_writes_nothing(self, tmp_path):
        result = make_result()
        self._seed_cache(tmp_path, result)
        rt = OnlineRetuner(result, cache_dir=tmp_path)
        assert rt.write_back() is None

    def test_validation(self):
        with pytest.raises(ValueError, match="hysteresis"):
            retuner(hysteresis=1.5)
