"""Autotuner unit tests: ranking determinism, the memory-budget rejection path,
the CPU scoring fallback's stated basis, and the zero-compile cache hit."""

import json

import pytest

from nanofed_tpu.models import get_model
from nanofed_tpu.trainer import TrainingConfig
from nanofed_tpu.tuning import (
    AutotuneError,
    CandidateConfig,
    CandidateOutcome,
    PopulationSpec,
    TuningSpace,
    autotune,
    rank_candidates,
    resolve_hbm_budget,
)

MODEL = get_model("digits_mlp")
POP = PopulationSpec(num_clients=8, capacity=32, sample_shape=(8, 8, 1))
TRAINING = TrainingConfig(batch_size=16, local_epochs=1, learning_rate=0.1)
TINY_SPACE = TuningSpace(
    client_chunks=(None, 1),
    rounds_per_blocks=(1, 2),
    model_shards=(1,),
    batch_sizes=(16,),
)


def _sweep(tmp_path, **kwargs):
    defaults = dict(
        num_rounds=4, space=TINY_SPACE,
        cache_dir=tmp_path / "cache", out_dir=tmp_path / "runs",
        include_epilogues=False,
    )
    defaults.update(kwargs)
    return autotune(MODEL, POP, TRAINING, **defaults)


class TestRanking:
    def _outcome(self, chunk, rpb, shards, batch, score, peak=0, feasible=True,
                 reason=None):
        return CandidateOutcome(
            CandidateConfig(chunk, rpb, shards, batch),
            feasible=feasible, score=score, reject_reason=reason,
            cost={"peak_bytes": peak} if feasible else {},
        )

    def test_feasible_sorted_by_score(self):
        a = self._outcome(None, 1, 1, 16, score=3.0)
        b = self._outcome(1, 1, 1, 16, score=1.0)
        c = self._outcome(2, 1, 1, 16, score=2.0)
        assert [o.score for o in rank_candidates([a, b, c])] == [1.0, 2.0, 3.0]

    def test_exact_tie_prefers_larger_block(self):
        # The AOT cost model cannot see the host dispatch tax — identical
        # per-round cost must rank the fused block first.
        single = self._outcome(None, 1, 1, 16, score=2.0)
        fused = self._outcome(None, 8, 1, 16, score=2.0)
        ranked = rank_candidates([single, fused])
        assert ranked[0].config.rounds_per_block == 8

    def test_tie_then_smaller_peak_then_key(self):
        heavy = self._outcome(2, 4, 1, 16, score=2.0, peak=100)
        light = self._outcome(4, 4, 1, 16, score=2.0, peak=50)
        assert rank_candidates([heavy, light])[0] is light
        # Full tie: the stable candidate key decides, independent of input order.
        x = self._outcome(1, 4, 1, 16, score=2.0, peak=50)
        y = self._outcome(2, 4, 1, 16, score=2.0, peak=50)
        assert rank_candidates([y, x])[0] is x
        assert rank_candidates([x, y])[0] is x

    def test_rejected_follow_feasible_with_reasons(self):
        ok = self._outcome(None, 1, 1, 16, score=1.0)
        bad = self._outcome(1, 1, 1, 16, score=None, feasible=False,
                            reason="exceeds budget")
        ranked = rank_candidates([bad, ok])
        assert ranked[0] is ok
        assert ranked[1].reject_reason == "exceeds budget"


class TestSpace:
    def test_default_space_respects_geometry(self):
        space = TuningSpace.default(POP, n_devices=8, batch_size=16, num_rounds=10)
        assert None in space.client_chunks
        assert all(r <= 10 for r in space.rounds_per_blocks)
        # batch candidates must divide the packed capacity
        assert all(POP.capacity % b == 0 for b in space.batch_sizes)
        assert 2 in space.model_shards  # 8 devices admit a model axis

    def test_candidates_deduped_and_ordered(self):
        space = TuningSpace((None, None), (1,), (1,), (16,))
        assert len(space.candidates()) == 1


class TestBudgetResolution:
    def test_explicit_wins(self):
        budget, basis = resolve_hbm_budget(123456)
        assert budget == 123456 and "explicit" in basis

    def test_env_var(self, monkeypatch):
        monkeypatch.setenv("NANOFED_AUTOTUNE_HBM_BUDGET", "1e9")
        budget, basis = resolve_hbm_budget()
        assert budget == 1_000_000_000 and "NANOFED_AUTOTUNE_HBM_BUDGET" in basis

    def test_cpu_is_unbounded_not_fabricated(self, monkeypatch):
        monkeypatch.delenv("NANOFED_AUTOTUNE_HBM_BUDGET", raising=False)
        budget, basis = resolve_hbm_budget()
        # The CPU runtime reports no bytes_limit and no HBM row exists for it:
        # the budget must be honestly absent, never invented.
        assert budget is None
        assert "unbounded" in basis


class TestMemoryBudgetRejection:
    def test_all_rejected_raises_with_reasons(self, tmp_path):
        with pytest.raises(AutotuneError, match="exceeds the device HBM budget"):
            _sweep(tmp_path, hbm_budget_bytes=1024)
        # The artifact is still written first, with the full rejected table.
        artifacts = list((tmp_path / "runs").glob("autotune_*.json"))
        assert artifacts
        table = json.loads(artifacts[0].read_text())
        assert table["winner"] is None
        assert all(not c["feasible"] for c in table["candidates"])
        assert all(
            "exceeds the device HBM budget" in c["reject_reason"]
            for c in table["candidates"]
        )
        assert table["hbm_budget_bytes"] == 1024
        assert "explicit" in table["budget_basis"]

    def test_partial_rejection_keeps_feasible_winner(self, tmp_path):
        # First, learn the candidates' peaks with no budget...
        free = _sweep(tmp_path, cache_dir=None, out_dir=None)
        peaks = sorted(
            o.cost["peak_bytes"] for o in free.outcomes if o.feasible
        )
        assert peaks[0] < peaks[-1], "need distinct peaks to split the budget"
        # ...then set the budget between min and max: the heavy candidates must
        # be rejected, the winner drawn from the survivors.
        budget = (peaks[0] + peaks[-1]) // 2
        res = _sweep(tmp_path, cache_dir=None, out_dir=None,
                     hbm_budget_bytes=budget)
        rejected = [o for o in res.outcomes if not o.feasible]
        assert rejected and res.winner is not None
        winner_outcome = next(
            o for o in res.outcomes if o.feasible and o.config == res.winner
        )
        assert winner_outcome.cost["peak_bytes"] <= budget
        for o in rejected:
            assert "exceeds the device HBM budget" in o.reject_reason
            # Rejected-for-memory candidates still carry their measured cost,
            # so the table explains WHY they were over.
            assert o.cost["peak_bytes"] > budget


class TestCpuOrderingFallback:
    def test_basis_states_bytes_accessed_not_walltime(self, tmp_path):
        res = _sweep(tmp_path)
        assert "bytes-accessed ordering" in res.scoring_basis
        assert "NOT a predicted walltime" in res.scoring_basis
        # No fabricated peaks: no CPU candidate may carry a lower-bound walltime.
        for o in res.outcomes:
            assert "lower_bound_s_per_round" not in o.cost
        # The artifact carries the basis field verbatim.
        table = json.loads((tmp_path / "runs").glob("autotune_*.json").__next__()
                           .read_text())
        assert table["scoring_basis"] == res.scoring_basis

    def test_winner_is_min_bytes_per_round(self, tmp_path):
        res = _sweep(tmp_path)
        feasible = [o for o in res.outcomes if o.feasible]
        best = min(feasible, key=lambda o: o.score)
        assert res.winner == res.outcomes[0].config
        assert res.outcomes[0].score == best.score


class TestCacheAndFeasibility:
    def test_cache_hit_skips_all_compiles(self, tmp_path):
        first = _sweep(tmp_path)
        assert not first.cache_hit and first.compiles > 0
        second = _sweep(tmp_path)
        assert second.cache_hit
        assert second.compiles == 0
        assert second.winner == first.winner
        assert [o.to_dict() for o in second.outcomes] == [
            o.to_dict() for o in first.outcomes
        ]

    def test_force_resweeps(self, tmp_path):
        _sweep(tmp_path)
        forced = _sweep(tmp_path, force=True)
        assert not forced.cache_hit and forced.compiles > 0

    def test_population_change_misses_cache(self, tmp_path):
        _sweep(tmp_path)
        other_pop = PopulationSpec(num_clients=16, capacity=32,
                                   sample_shape=(8, 8, 1))
        res = autotune(
            MODEL, other_pop, TRAINING, num_rounds=4, space=TINY_SPACE,
            cache_dir=tmp_path / "cache", out_dir=None,
            include_epilogues=False,
        )
        assert not res.cache_hit

    def test_failed_sweep_is_not_cached(self, tmp_path):
        # An all-rejected sweep must raise EVERY time — the first failure must
        # not be cached as a winnerless result a later call silently returns.
        with pytest.raises(AutotuneError):
            _sweep(tmp_path, hbm_budget_bytes=1024)
        assert not list((tmp_path / "cache").glob("autotune_*.json"))
        with pytest.raises(AutotuneError):
            _sweep(tmp_path, hbm_budget_bytes=1024)

    def test_budget_change_misses_cache(self, tmp_path):
        # The budget changes which candidates are rejected (hence the winner),
        # so it is part of the cache key: an unbudgeted sweep's cache entry
        # must not answer a budgeted sweep.
        free = _sweep(tmp_path)
        peaks = sorted(o.cost["peak_bytes"] for o in free.outcomes if o.feasible)
        budgeted = _sweep(
            tmp_path, hbm_budget_bytes=(peaks[0] + peaks[-1]) // 2
        )
        assert not budgeted.cache_hit
        assert any(not o.feasible for o in budgeted.outcomes)

    def test_static_infeasibility_reasons(self, tmp_path):
        space = TuningSpace(
            client_chunks=(3,),       # does not divide per-device count
            rounds_per_blocks=(9,),   # exceeds num_rounds=4
            model_shards=(5,),        # does not divide 8 devices
            batch_sizes=(7,),         # does not divide capacity 32
        )
        with pytest.raises(AutotuneError):
            _sweep(tmp_path, space=space, cache_dir=None, out_dir=None)

    def test_eval_every_blocks_fused_candidates(self, tmp_path):
        space = TuningSpace((None,), (4,), (1,), (16,))
        with pytest.raises(AutotuneError, match="eval_every"):
            _sweep(tmp_path, space=space, eval_every=2, cache_dir=None,
                   out_dir=None)


class TestHostsAxis:
    """The hosts axis of the search space (3-axis hosts x clients x model
    meshes): static feasibility, the per-host-shard-vs-chunk rejection rule,
    and cache-key sensitivity — all exercised through rejection paths and pure
    helpers, zero compiles."""

    def test_candidates_cross_hosts_axis(self):
        space = TuningSpace((None,), (1,), (1,), (16,), hosts=(1, 2))
        cands = space.candidates()
        assert sorted(c.hosts for c in cands) == [1, 2]
        assert all(c.to_dict()["hosts"] in (1, 2) for c in cands)

    def test_hosts_default_is_single_host(self):
        assert TuningSpace((None,), (1,), (1,), (16,)).hosts == (1,)
        assert CandidateConfig(None, 1, 1, 16).hosts == 1

    def test_hosts_grid_rejection_is_stated(self, tmp_path):
        # 3 hosts cannot tile 8 devices: every candidate is rejected with the
        # grid reason in the artifact, never silently skipped.
        space = TuningSpace((None,), (1,), (1,), (16,), hosts=(3,))
        with pytest.raises(AutotuneError, match="does not divide"):
            _sweep(tmp_path, space=space, cache_dir=None, out_dir=None)

    def test_chunk_exceeding_per_host_shard_is_rejected(self, tmp_path):
        # hosts=2 over 8 devices -> 8 client shards -> 1 client/device at this
        # 8-client population; a chunk of 4 exceeds the per-host shard and
        # would silently no-op — the multi-host sweep must SAY so instead
        # (reusing _plan_layout's fallback rule).  Single-host the same chunk
        # follows the documented silent-degrade rule, so only the hosts=2
        # candidate dies; with no feasible single-host candidate in the space,
        # the sweep raises with the stated reason.
        space = TuningSpace((4,), (1,), (1,), (16,), hosts=(2,))
        with pytest.raises(AutotuneError, match="per-host client shard"):
            _sweep(tmp_path, space=space, cache_dir=None, out_dir=None)

    def test_hosts_axis_changes_cache_key(self):
        from nanofed_tpu.tuning.autotuner import compute_cache_key

        base = dict(
            model=MODEL, population=POP, training=TRAINING,
            participation=1.0, num_rounds=4, eval_every=0,
            device_kind="cpu", num_devices=8, hbm_budget=None,
        )
        one = compute_cache_key(
            space=TuningSpace((None,), (1,), (1,), (16,), hosts=(1,)), **base
        )
        two = compute_cache_key(
            space=TuningSpace((None,), (1,), (1,), (16,), hosts=(2,)), **base
        )
        assert one != two

class TestCompileBudget:
    """Compile-budget-aware sweep pruning: cheapest-predicted-compile first,
    budget exhaustion skips (stated, never silent), a deadline-blown compile
    wedges the sweep without killing it — the r14 postmortem features."""

    def _fake_eval(self, seconds_by_rpb):
        from nanofed_tpu.tuning.autotuner import CandidateOutcome

        def fake(cand, *a, **kw):
            s = seconds_by_rpb.get(cand.rounds_per_block, 0.1)
            return CandidateOutcome(
                cand, True, score=100.0 - cand.rounds_per_block,
                cost={"compile_seconds": s, "peak_bytes": 1,
                      "bytes_accessed_per_round": 100.0},
            )
        return fake

    def test_sweep_order_is_cheapest_compile_first(self):
        from nanofed_tpu.tuning.autotuner import (
            order_by_predicted_compile_cost,
            predicted_compile_cost,
        )

        space = TuningSpace(
            client_chunks=(None, 1), rounds_per_blocks=(1, 4),
            model_shards=(1, 2), batch_sizes=(16,),
        )
        ordered = order_by_predicted_compile_cost(space.candidates())
        costs = [predicted_compile_cost(c) for c in ordered]
        assert costs == sorted(costs)
        # The plain single-round unchunked unsharded candidate compiles first,
        # the fused+chunked+sharded one last.
        assert (ordered[0].client_chunk, ordered[0].rounds_per_block,
                ordered[0].model_shards) == (None, 1, 1)
        assert ordered[-1].rounds_per_block == 4
        assert ordered[-1].model_shards == 2
        # Deterministic: re-ordering the same set is a fixpoint.
        assert order_by_predicted_compile_cost(ordered) == ordered

    def test_budget_exhaustion_skips_remaining_stated(self, tmp_path, monkeypatch):
        from nanofed_tpu.tuning import autotuner

        monkeypatch.setattr(
            autotuner, "_evaluate_candidate", self._fake_eval({1: 5.0, 2: 5.0})
        )
        result = _sweep(
            tmp_path, compile_budget_s=6.0, cache_dir=None, out_dir=None,
        )
        skipped = [o for o in result.outcomes
                   if o.reject_reason and o.reject_reason.startswith("skipped:")]
        assert result.skipped == len(skipped) > 0
        assert result.compiles + result.skipped == len(result.outcomes)
        assert all("compile_budget" in o.reject_reason for o in skipped)
        assert result.compile_budget_s == 6.0
        # The cheap head still produced a feasible winner.
        assert result.winner is not None
        assert result.to_dict()["skipped"] == result.skipped

    def test_budget_truncated_sweep_is_not_cached(self, tmp_path, monkeypatch):
        from nanofed_tpu.tuning import autotuner

        monkeypatch.setattr(
            autotuner, "_evaluate_candidate", self._fake_eval({1: 5.0, 2: 5.0})
        )
        _sweep(tmp_path, compile_budget_s=6.0)
        assert not list((tmp_path / "cache").glob("autotune_*.json"))
        # A complete sweep under the same key IS cached.
        full = _sweep(tmp_path)
        assert full.skipped == 0
        assert list((tmp_path / "cache").glob("autotune_*.json"))

    def test_candidate_deadline_records_wedged_at(self, tmp_path, monkeypatch):
        import time as _time

        from nanofed_tpu.tuning import autotuner
        from nanofed_tpu.tuning.autotuner import (
            CandidateOutcome,
            candidate_program_name,
        )

        def slow_eval(cand, *a, **kw):
            if cand.rounds_per_block > 1:
                _time.sleep(5.0)
            return CandidateOutcome(
                cand, True, score=1.0,
                cost={"compile_seconds": 0.01, "peak_bytes": 1},
            )

        monkeypatch.setattr(autotuner, "_evaluate_candidate", slow_eval)
        result = _sweep(
            tmp_path, candidate_deadline_s=0.2, cache_dir=None, out_dir=None,
        )
        assert result.wedged_at is not None
        assert result.wedged_at.startswith("cand_")
        wedged = [o for o in result.outcomes
                  if o.reject_reason and o.reject_reason.startswith("wedged:")]
        assert len(wedged) == 1
        assert candidate_program_name(wedged[0].config) == result.wedged_at
        assert wedged[0].cost["wedged_at"] == pytest.approx(0.2)
        # Everything ordered after the wedge is skipped with the wedge named.
        after = [o for o in result.outcomes
                 if o.reject_reason and o.reject_reason.startswith("skipped:")]
        assert all(result.wedged_at in o.reject_reason for o in after)
        # The cheap candidates that compiled BEFORE the wedge hold the winner.
        assert result.winner is not None
        assert result.to_dict()["wedged_at"] == result.wedged_at

    def test_env_var_budget(self, tmp_path, monkeypatch):
        from nanofed_tpu.tuning import autotuner

        monkeypatch.setattr(
            autotuner, "_evaluate_candidate", self._fake_eval({1: 5.0, 2: 5.0})
        )
        monkeypatch.setenv("NANOFED_AUTOTUNE_COMPILE_BUDGET", "6.0")
        result = _sweep(tmp_path, cache_dir=None, out_dir=None)
        assert result.compile_budget_s == 6.0
        assert result.skipped > 0

    def test_compile_telemetry_records(self, tmp_path, monkeypatch):
        from nanofed_tpu.tuning import autotuner

        class FakeTelemetry:
            def __init__(self):
                self.records = []

            def record(self, rtype, **fields):
                self.records.append({"type": rtype, **fields})

        monkeypatch.setattr(
            autotuner, "_evaluate_candidate", self._fake_eval({})
        )
        tel = FakeTelemetry()
        result = _sweep(tmp_path, cache_dir=None, out_dir=None, telemetry=tel)
        compiles = [r for r in tel.records if r["type"] == "compile"]
        assert len(compiles) == result.compiles > 0
        for r in compiles:
            assert r["program"].startswith("cand_")
            assert r["seconds"] > 0
            assert r["cache_key"] == result.cache_key[:16]


class TestCacheKeyV5:
    def test_cache_key_folds_in_jax_versions_and_platform(self, monkeypatch):
        """v5 regression: a jaxlib upgrade (or a backend change) must MISS the
        cache — stale tuned configs from another toolchain are worse than a
        re-sweep."""
        import jax

        from nanofed_tpu.tuning.autotuner import compute_cache_key

        kwargs = dict(
            model=MODEL, population=POP, training=TRAINING,
            space=TINY_SPACE, participation=1.0, num_rounds=4, eval_every=0,
            device_kind="cpu", num_devices=8, hbm_budget=None,
        )
        before = compute_cache_key(**kwargs)
        monkeypatch.setattr(jax, "__version__", "0.0.0-other")
        after = compute_cache_key(**kwargs)
        assert before != after

        import jaxlib

        monkeypatch.undo()
        assert compute_cache_key(**kwargs) == before
        monkeypatch.setattr(
            jaxlib, "__version__", "0.0.0-other", raising=False
        )
        assert compute_cache_key(**kwargs) != before

    def test_winner_hosts_survives_artifact_round_trip(self):
        from nanofed_tpu.tuning.autotuner import AutotuneResult

        result = AutotuneResult(
            winner=CandidateConfig(None, 1, 1, 16, hosts=2),
            outcomes=[], scoring_basis="?", platform="cpu",
            device_kind="cpu", num_devices=8, hbm_budget_bytes=None,
            budget_basis="?", cache_key="k",
        )
        back = AutotuneResult.from_dict(result.to_dict())
        assert back.winner.hosts == 2


class TestForFleet:
    def test_rank_axis_is_the_union_of_tier_ladders(self):
        from nanofed_tpu.fleet import reference_fleet

        space = TuningSpace.for_fleet(
            reference_fleet(), POP, n_devices=8, batch_size=16, num_rounds=10
        )
        # tiers 4/8/32 -> ladders {2,4,8} | {4,8,16} | {16,32,64}
        assert space.adapter_ranks == (2, 4, 8, 16, 32, 64)
        # everything else matches the homogeneous default
        default = TuningSpace.default(POP, 8, 16, 10)
        assert space.client_chunks == default.client_chunks
        assert space.batch_sizes == default.batch_sizes

    def test_candidate_count_is_linear_in_distinct_ranks(self):
        from nanofed_tpu.fleet import reference_fleet

        prof = reference_fleet()
        space = TuningSpace.for_fleet(
            prof, POP, n_devices=8, batch_size=16, num_rounds=10
        )
        default = TuningSpace.default(POP, 8, 16, 10)
        per_rank = len(default.candidates()) // len(default.adapter_ranks)
        assert len(space.candidates()) == per_rank * 6  # not 3**tiers
