"""Frozen-base round-program tests: the adapter tree is the federated state,
the base is read-only boundary data, and adapter aggregation is trajectory-
equivalent to the dense reference on the merged params."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.adapters import (
    AdapterSpec,
    adapter_delta,
    init_adapters,
    make_adapter_apply,
    merge_adapters,
)
from nanofed_tpu.aggregation.base import fedavg_strategy
from nanofed_tpu.data import federate, synthetic_token_streams
from nanofed_tpu.models import get_model
from nanofed_tpu.parallel.mesh import (
    client_sharding,
    make_mesh,
    replicated_sharding,
    shard_params,
)
from nanofed_tpu.parallel.round_step import (
    FrozenBase,
    build_round_step,
    init_server_state,
)
from nanofed_tpu.trainer.config import TrainingConfig
from nanofed_tpu.trainer.local import make_local_fit, stack_rngs

VOCAB, SEQ, WIDTH, DEPTH, HEADS = 32, 8, 16, 1, 2
C = 8


@pytest.fixture(scope="module")
def setup():
    model = get_model(
        "transformer_lm", vocab=VOCAB, seq_len=SEQ, width=WIDTH,
        depth=DEPTH, heads=HEADS,
    )
    base = model.init(jax.random.key(0))
    spec = AdapterSpec(rank=2)
    adapters = init_adapters(spec, base, rng=1)
    ds = synthetic_token_streams(32 * C, vocab=VOCAB, seq_len=SEQ, seed=0)
    data = federate(ds, num_clients=C, batch_size=16, seed=0)
    training = TrainingConfig(batch_size=16, local_epochs=1, learning_rate=0.3)
    return model, base, spec, adapters, data, training


def _frozen(model, spec, base):
    return FrozenBase(
        base_like=base,
        bind=lambda bf: make_adapter_apply(model.apply, spec, bf),
    )


def _run_rounds(model, base, spec, adapters, data, training, mesh, n_rounds=3,
                client_chunk=None):
    strategy = fedavg_strategy()
    step = build_round_step(
        model.apply, training, mesh, strategy,
        params_like=adapters, frozen_base=_frozen(model, spec, base),
        client_chunk=client_chunk,
    )
    sos = init_server_state(strategy, adapters)
    base_d = shard_params(base, mesh)
    ad_d = shard_params(adapters, mesh)
    sos_d = shard_params(sos, mesh)
    csh = client_sharding(mesh)
    data_d = jax.tree.map(lambda a: jax.device_put(np.asarray(a), csh), data)
    weights = jax.device_put(
        jnp.asarray(np.asarray(data.mask).sum(1), jnp.float32),
        replicated_sharding(mesh),
    )
    losses = []
    for r in range(n_rounds):
        rngs = stack_rngs(jax.random.fold_in(jax.random.key(1), r), C)
        res = step(ad_d, sos_d, base_d, data_d, weights, rngs)
        ad_d, sos_d = res.params, res.server_opt_state
        losses.append(float(res.metrics["loss"]))
    return losses, jax.device_get(ad_d)


def test_loss_descends_and_base_is_untouched(setup):
    model, base, spec, adapters, data, training = setup
    mesh = make_mesh()
    losses, ad_after = _run_rounds(
        model, base, spec, adapters, data, training, mesh
    )
    assert losses[-1] < losses[0], losses
    # the federated state changed; the base was never an output at all
    assert any(
        not np.allclose(a, b)
        for a, b in zip(jax.tree.leaves(ad_after), jax.tree.leaves(adapters))
    )


def test_output_is_adapter_shaped_fixed_point(setup):
    model, base, spec, adapters, data, training = setup
    mesh = make_mesh()
    _, ad_after = _run_rounds(
        model, base, spec, adapters, data, training, mesh, n_rounds=1
    )
    assert jax.tree_util.tree_structure(ad_after) == jax.tree_util.tree_structure(
        adapters
    )
    for a, b in zip(jax.tree.leaves(ad_after), jax.tree.leaves(adapters)):
        assert a.shape == b.shape and a.dtype == b.dtype


def test_2d_mesh_parity_and_sharded_outputs(setup):
    model, base, spec, adapters, data, training = setup
    l1, a1 = _run_rounds(
        model, base, spec, adapters, data, training, make_mesh(), n_rounds=2
    )
    mesh2 = make_mesh(shape=(4, 2))
    strategy = fedavg_strategy()
    step = build_round_step(
        model.apply, training, mesh2, strategy,
        params_like=adapters, frozen_base=_frozen(model, spec, base),
    )
    ad_d = shard_params(adapters, mesh2)
    sos_d = shard_params(init_server_state(strategy, adapters), mesh2)
    base_d = shard_params(base, mesh2)
    csh = client_sharding(mesh2)
    data_d = jax.tree.map(lambda a: jax.device_put(np.asarray(a), csh), data)
    weights = jax.device_put(
        jnp.asarray(np.asarray(data.mask).sum(1), jnp.float32),
        replicated_sharding(mesh2),
    )
    l2 = []
    for r in range(2):
        rngs = stack_rngs(jax.random.fold_in(jax.random.key(1), r), C)
        res = step(ad_d, sos_d, base_d, data_d, weights, rngs)
        ad_d, sos_d = res.params, res.server_opt_state
        l2.append(float(res.metrics["loss"]))
    # float-reassociation parity (gathers/slices change reduction order)
    np.testing.assert_allclose(l1, l2, atol=1e-3)
    a2 = jax.device_get(ad_d)
    for x, y in zip(jax.tree.leaves(a1), jax.tree.leaves(a2)):
        np.testing.assert_allclose(x, y, atol=5e-3)
    # outputs stay in the params layout (some leaf is genuinely model-sharded)
    assert any(
        not leaf.sharding.is_fully_replicated
        for leaf in jax.tree.leaves(res.params)
    )


def test_adapter_aggregation_equals_dense_reference_on_merged_params(setup):
    """Trajectory parity (acceptance bar): FedAvg over adapter trees, merged
    into the base, equals the dense FedAvg of the same clients' MERGED deltas
    — because merge is affine in the adapter tree ONLY through the aggregated
    A/B themselves, the reference is computed from per-client local fits run
    outside the mesh program, aggregated on the adapter leaves, then merged."""
    model, base, spec, adapters, data, training = setup
    mesh = make_mesh()
    strategy = fedavg_strategy()
    step = build_round_step(
        model.apply, training, mesh, strategy,
        params_like=adapters, frozen_base=_frozen(model, spec, base),
    )
    sos = init_server_state(strategy, adapters)
    weights = jnp.asarray(np.asarray(data.mask).sum(1), jnp.float32)
    rngs = stack_rngs(jax.random.key(1), C)
    data_d = jax.tree.map(jnp.asarray, data)
    res = step(adapters, sos, base, data_d, weights, rngs)
    got_adapters = jax.device_get(res.params)

    # Dense reference: each client's fit via the SAME bound apply, outside the
    # mesh program; FedAvg on the adapter leaves; server SGD(1.0) applies the
    # aggregate — exact FedAvg semantics.
    fit = make_local_fit(
        make_adapter_apply(model.apply, spec, base), training
    )
    deltas = []
    for i in range(C):
        client = jax.tree.map(lambda x, i=i: jnp.asarray(np.asarray(x)[i]), data)
        out = fit(adapters, client, rngs[i])
        deltas.append(jax.tree.map(
            lambda p, g: np.asarray(p, np.float32) - np.asarray(g, np.float32),
            out.params, adapters,
        ))
    w = np.asarray(weights) / np.asarray(weights).sum()
    agg = jax.tree.map(
        lambda *leaves: sum(wi * d for wi, d in zip(w, leaves)), *deltas
    )
    want_adapters = jax.tree.map(
        lambda a, d: np.asarray(a, np.float32) + d, adapters, agg
    )
    for got, want in zip(
        jax.tree.leaves(got_adapters), jax.tree.leaves(want_adapters)
    ):
        # float tolerance: the in-mesh program reduces with psum + server
        # optax while the reference is a numpy host loop — reassociation only
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-4)
    # ... and therefore the MERGED models agree (the claim the bar states).
    merged_got = merge_adapters(base, got_adapters, spec)
    merged_want = merge_adapters(base, want_adapters, spec)
    for got, want in zip(
        jax.tree.leaves(merged_got), jax.tree.leaves(merged_want)
    ):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)


def test_zero_weight_round_is_identity(setup):
    model, base, spec, adapters, data, training = setup
    mesh = make_mesh()
    strategy = fedavg_strategy()
    step = build_round_step(
        model.apply, training, mesh, strategy,
        params_like=adapters, frozen_base=_frozen(model, spec, base),
    )
    sos = init_server_state(strategy, adapters)
    res = step(
        adapters, sos, base, jax.tree.map(jnp.asarray, data),
        jnp.zeros((C,), jnp.float32), stack_rngs(jax.random.key(1), C),
    )
    for a, b in zip(jax.tree.leaves(res.params), jax.tree.leaves(adapters)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_frozen_base_refuses_custom_fit(setup):
    model, base, spec, adapters, data, training = setup
    mesh = make_mesh()
    with pytest.raises(ValueError, match="frozen_base"):
        build_round_step(
            model.apply, training, mesh, fedavg_strategy(),
            params_like=adapters, frozen_base=_frozen(model, spec, base),
            local_fit=lambda g, d, r: None,
        )


def test_adapter_delta_is_what_the_wire_would_carry(setup):
    """The dense delta an adapter round represents has support EXACTLY on the
    targeted kernels — everything else (embeddings, biases, norms) is
    bitwise zero, which is why only adapter payloads need to cross HTTP."""
    model, base, spec, adapters, data, training = setup
    perturbed = jax.tree.map(lambda x: x + 0.01, adapters)
    dense = adapter_delta(spec, base, perturbed)
    from nanofed_tpu.adapters import target_paths
    from nanofed_tpu.utils.trees import tree_flatten_with_names

    targets = set(target_paths(spec, base))
    for name, leaf in tree_flatten_with_names(dense)[0]:
        if name in targets:
            assert np.abs(np.asarray(leaf)).max() > 0
        else:
            np.testing.assert_array_equal(np.asarray(leaf), 0.0)
