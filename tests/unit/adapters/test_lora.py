"""Unit tests for the LoRA adapter algebra (``nanofed_tpu.adapters.lora``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.adapters import (
    AdapterSpec,
    adapter_delta,
    adapter_param_count,
    adapter_wire_ratio,
    init_adapters,
    make_adapter_apply,
    merge_adapters,
    target_paths,
    unmerge_adapters,
)
from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.models import get_model
from nanofed_tpu.utils.trees import tree_flatten_with_names


@pytest.fixture(scope="module")
def mlp_base():
    model = get_model("mlp", in_features=16, hidden=32, num_classes=4)
    return model, model.init(jax.random.key(0))


@pytest.fixture(scope="module")
def transformer_base():
    model = get_model(
        "transformer_lm", vocab=32, seq_len=8, width=16, depth=2, heads=2
    )
    return model, model.init(jax.random.key(1))


def test_spec_targets_2d_kernels_only(transformer_base):
    _, base = transformer_base
    spec = AdapterSpec(rank=2)
    paths = target_paths(spec, base)
    named = dict(tree_flatten_with_names(base)[0])
    for p in paths:
        assert p.endswith("kernel")
        assert len(np.shape(named[p])) == 2
    # biases, layer norms, and embeddings are never adapted by the default spec
    assert not any("bias" in p or "ln" in p or "emb" in p for p in paths)


def test_spec_min_dim_excludes_small_matrices(mlp_base):
    _, base = mlp_base
    # fc2 kernel is [32, 4]: min dim 4 < min_dim 8 -> only fc1 is adapted
    spec = AdapterSpec(rank=2, min_dim=8)
    assert target_paths(spec, base) == ["fc1/kernel"]


def test_spec_no_match_raises(mlp_base):
    _, base = mlp_base
    with pytest.raises(NanoFedError, match="matches no leaf"):
        target_paths(AdapterSpec(rank=2, targets=("*nonexistent*",)), base)


def test_spec_validation():
    with pytest.raises(NanoFedError):
        AdapterSpec(rank=0)
    with pytest.raises(NanoFedError):
        AdapterSpec(rank=2, alpha=0.0)
    with pytest.raises(NanoFedError):
        AdapterSpec(rank=2, targets=())
    assert AdapterSpec(rank=4).scaling == 1.0  # alpha defaults to rank
    assert AdapterSpec(rank=4, alpha=8.0).scaling == 2.0


def test_init_shapes_and_identity_start(transformer_base):
    _, base = transformer_base
    spec = AdapterSpec(rank=3)
    ad = init_adapters(spec, base, rng=0)
    named = dict(tree_flatten_with_names(ad)[0])
    base_named = dict(tree_flatten_with_names(base)[0])
    for path in target_paths(spec, base):
        d_in, d_out = base_named[path].shape
        assert named[f"{path}/A"].shape == (d_in, 3)
        assert named[f"{path}/B"].shape == (3, d_out)
        # B = 0: the LoRA identity start
        np.testing.assert_array_equal(named[f"{path}/B"], 0.0)
    merged = merge_adapters(base, ad, spec)
    for a, b in zip(jax.tree.leaves(merged), jax.tree.leaves(base)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_init_is_seed_deterministic(mlp_base):
    _, base = mlp_base
    spec = AdapterSpec(rank=2, min_dim=4)
    a1 = init_adapters(spec, base, rng=7)
    a2 = init_adapters(spec, base, rng=7)
    a3 = init_adapters(spec, base, rng=8)
    for x, y in zip(jax.tree.leaves(a1), jax.tree.leaves(a2)):
        np.testing.assert_array_equal(x, y)
    assert any(
        not np.array_equal(x, y)
        for x, y in zip(jax.tree.leaves(a1), jax.tree.leaves(a3))
    )


def test_merge_unmerge_round_trip(transformer_base):
    _, base = transformer_base
    spec = AdapterSpec(rank=2, alpha=4.0)
    ad = init_adapters(spec, base, rng=0)
    # give B real mass so the delta is nonzero
    ad = jax.tree.map(lambda x: x + 0.05, ad)
    merged = merge_adapters(base, ad, spec)
    assert any(
        not np.allclose(np.asarray(m), np.asarray(b))
        for m, b in zip(jax.tree.leaves(merged), jax.tree.leaves(base))
    )
    back = unmerge_adapters(merged, ad, spec)
    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(base)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_adapter_delta_matches_merge(transformer_base):
    _, base = transformer_base
    spec = AdapterSpec(rank=2)
    ad = jax.tree.map(lambda x: x + 0.03, init_adapters(spec, base, rng=0))
    delta = adapter_delta(spec, base, ad)
    merged = merge_adapters(base, ad, spec)
    for d, m, b in zip(
        jax.tree.leaves(delta), jax.tree.leaves(merged), jax.tree.leaves(base)
    ):
        np.testing.assert_allclose(
            np.asarray(d), np.asarray(m) - np.asarray(b), atol=1e-6
        )


def test_make_adapter_apply_equals_apply_of_merged(transformer_base):
    model, base = transformer_base
    spec = AdapterSpec(rank=2)
    ad = jax.tree.map(lambda x: x + 0.02, init_adapters(spec, base, rng=0))
    x = jnp.asarray(np.random.default_rng(0).integers(0, 32, (4, 8)), jnp.int32)
    bound = make_adapter_apply(model.apply, spec, base)
    np.testing.assert_allclose(
        np.asarray(bound(ad, x)),
        np.asarray(model.apply(merge_adapters(base, ad, spec), x)),
        atol=1e-6,
    )


def test_param_counts_and_wire_ratio(transformer_base):
    _, base = transformer_base
    spec = AdapterSpec(rank=2)
    counts = adapter_param_count(spec, base)
    named = dict(tree_flatten_with_names(base)[0])
    want_trainable = sum(
        2 * (named[p].shape[0] + named[p].shape[1])
        for p in target_paths(spec, base)
    )
    assert counts["adapter_params"] == want_trainable
    assert counts["base_params"] == sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(base)
    )
    assert adapter_wire_ratio(spec, base) == pytest.approx(
        counts["base_params"] / counts["adapter_params"]
    )


def test_works_on_abstract_trees(transformer_base):
    """Shapes-only operation: the autotuner lowers adapter candidates from
    eval_shape output, never materializing the base."""
    model, _ = transformer_base
    base_abs = jax.eval_shape(lambda: model.init(jax.random.key(0)))
    spec = AdapterSpec(rank=2)
    ad = init_adapters(spec, base_abs, rng=0)
    assert target_paths(spec, base_abs)
    assert adapter_param_count(spec, base_abs)["adapter_params"] > 0
    assert all(isinstance(x, np.ndarray) for x in jax.tree.leaves(ad))


def test_stacked_scan_leaves_get_per_layer_adapters():
    """scan_layers=True stacks block kernels into [L, d_in, d_out] leaves; the
    adapter algebra must address per-layer slices — A [L, d_in, r], B
    [L, r, d_out] — and stay numerically identical to adapting each layer of
    the unrolled tree."""
    mu = get_model(
        "transformer_lm", vocab=32, seq_len=8, width=16, depth=3, heads=2
    )
    ms = get_model(
        "transformer_lm_scan", vocab=32, seq_len=8, width=16, depth=3, heads=2
    )
    pu, ps = mu.init(jax.random.key(1)), ms.init(jax.random.key(1))
    spec = AdapterSpec(rank=2)
    au = init_adapters(spec, pu, rng=0)
    a_s = init_adapters(spec, ps, rng=0)

    # Stacked A/B leaves carry the leading layer dim.
    wq = a_s["blocks"]["attn"]["wq"]["kernel"]
    assert wq["A"].shape == (3, 16, 2) and wq["B"].shape == (3, 2, 16)

    # Trainable count matches the unrolled tree (same adapted surface).
    assert (
        adapter_param_count(spec, ps)["adapter_params"]
        == adapter_param_count(spec, pu)["adapter_params"]
    )

    # B=0 start: merge is the identity.
    merged0 = merge_adapters(ps, a_s, spec)
    for a, b in zip(jax.tree.leaves(merged0), jax.tree.leaves(ps)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # With nonzero B the batched delta equals the per-layer matmul.
    bumped = jax.tree.map(lambda x: x + 0.1, a_s)
    delta = adapter_delta(spec, ps, bumped)
    d = np.asarray(delta["blocks"]["attn"]["wq"]["kernel"])
    A = np.asarray(bumped["blocks"]["attn"]["wq"]["kernel"]["A"])
    B = np.asarray(bumped["blocks"]["attn"]["wq"]["kernel"]["B"])
    for layer in range(3):
        np.testing.assert_allclose(
            d[layer], spec.scaling * A[layer] @ B[layer], atol=1e-6
        )

    # Merge/unmerge still round-trips on the stacked tree.
    out = unmerge_adapters(merge_adapters(ps, bumped, spec), bumped, spec)
    for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(ps)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_adapter_tree_rides_checkpoint_layout(transformer_base):
    """The adapter tree round-trips through the '/'-path npz codec like any
    params tree — a captured adapter payload IS a loadable checkpoint."""
    from nanofed_tpu.communication.codec import decode_params, encode_params

    _, base = transformer_base
    spec = AdapterSpec(rank=2)
    ad = init_adapters(spec, base, rng=0)
    out = decode_params(encode_params(ad), like=ad)
    for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(ad)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
