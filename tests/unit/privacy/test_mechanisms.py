"""Clip+noise mechanisms over pytrees (parity: ``tests/unit/privacy/test_mechanism.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.privacy import (
    GaussianAccountant,
    PrivacyConfig,
    PrivacyType,
    make_privacy_mechanism,
    privatize_stacked_updates,
)
from nanofed_tpu.utils.trees import tree_global_norm


def big_update():
    return {"w": jnp.full((10, 10), 5.0), "b": jnp.full((10,), 5.0)}


class TestMechanism:
    def test_clips_to_max_norm(self, rng):
        cfg = PrivacyConfig(max_gradient_norm=1.0, noise_multiplier=1e-6)
        mech = make_privacy_mechanism(PrivacyType.CENTRAL, cfg)
        out = mech.privatize(rng, big_update())
        assert float(tree_global_norm(out)) == pytest.approx(1.0, rel=1e-3)

    def test_small_update_not_scaled_up(self, rng):
        cfg = PrivacyConfig(max_gradient_norm=100.0, noise_multiplier=1e-6)
        mech = make_privacy_mechanism(PrivacyType.CENTRAL, cfg)
        small = {"w": jnp.ones((2,)) * 0.1}
        out = mech.privatize(rng, small)
        np.testing.assert_allclose(np.asarray(out["w"]), 0.1, atol=1e-3)

    def test_noise_scale_divides_by_batch(self):
        cfg = PrivacyConfig(max_gradient_norm=2.0, noise_multiplier=3.0)
        assert make_privacy_mechanism("central", cfg, batch_size=6).noise_scale == pytest.approx(1.0)
        assert make_privacy_mechanism("local", cfg).noise_scale == pytest.approx(6.0)

    def test_local_forces_batch_one(self):
        cfg = PrivacyConfig()
        mech = make_privacy_mechanism(PrivacyType.LOCAL, cfg, batch_size=32)
        assert mech.batch_size == 1

    def test_noise_actually_added(self, rng):
        cfg = PrivacyConfig(max_gradient_norm=1.0, noise_multiplier=1.0)
        mech = make_privacy_mechanism(PrivacyType.CENTRAL, cfg)
        zero = {"w": jnp.zeros((1000,))}
        out = mech.privatize(rng, zero)
        assert float(jnp.std(out["w"])) == pytest.approx(1.0, rel=0.1)

    def test_record_feeds_accountant(self):
        cfg = PrivacyConfig(noise_multiplier=2.0)
        mech = make_privacy_mechanism(PrivacyType.CENTRAL, cfg, batch_size=4)
        acc = GaussianAccountant()
        mech.record(acc, sampling_rate=0.5, count=3)
        assert acc.num_events == 3
        assert acc.state_dict()["events"] == [[2.0, 0.5, 3.0]]


class TestStackedPrivatization:
    def test_per_client_independent_noise(self, rng):
        cfg = PrivacyConfig(max_gradient_norm=1.0, noise_multiplier=1.0)
        mech = make_privacy_mechanism(PrivacyType.CENTRAL, cfg, batch_size=1)
        stacked = {"w": jnp.zeros((4, 100))}
        out = privatize_stacked_updates(rng, stacked, mech)
        assert out["w"].shape == (4, 100)
        rows = np.asarray(out["w"])
        for i in range(3):
            assert not np.array_equal(rows[i], rows[i + 1])

    def test_each_client_clipped(self, rng):
        cfg = PrivacyConfig(max_gradient_norm=1.0, noise_multiplier=1e-6)
        mech = make_privacy_mechanism(PrivacyType.CENTRAL, cfg, batch_size=1)
        stacked = {"w": jnp.full((3, 50), 9.0)}
        out = privatize_stacked_updates(rng, stacked, mech)
        norms = np.linalg.norm(np.asarray(out["w"]), axis=1)
        np.testing.assert_allclose(norms, 1.0, rtol=1e-3)

    def test_jit_compatible(self, rng):
        cfg = PrivacyConfig()
        mech = make_privacy_mechanism(PrivacyType.CENTRAL, cfg, batch_size=2)
        stacked = {"w": jnp.ones((2, 10))}
        out = jax.jit(lambda k, s: privatize_stacked_updates(k, s, mech))(rng, stacked)
        assert np.isfinite(np.asarray(out["w"])).all()


def test_laplacian_accounting_rejected():
    """Gaussian/RDP accountants only bound the Gaussian mechanism — recording Laplacian
    events must fail loudly instead of reporting a meaningless epsilon (a reference quirk
    deliberately not carried over)."""
    from nanofed_tpu.core.exceptions import PrivacyError
    from nanofed_tpu.privacy import (
        GaussianAccountant,
        NoiseType,
        PrivacyConfig,
        PrivacyMechanism,
        PrivacyType,
    )

    cfg = PrivacyConfig(noise_type=NoiseType.LAPLACIAN)
    mech = PrivacyMechanism(config=cfg, privacy_type=PrivacyType.CENTRAL)
    with pytest.raises(PrivacyError):
        mech.record(GaussianAccountant())

    from nanofed_tpu.trainer import TrainingConfig
    from nanofed_tpu.trainer.private import record_local_fit

    with pytest.raises(PrivacyError):
        record_local_fit(GaussianAccountant(), cfg, TrainingConfig(), 64, 64)
