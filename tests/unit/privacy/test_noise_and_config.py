"""Noise generators (shape/scale/seed reproducibility — parity with
``tests/unit/privacy/test_generators.py``) and config bounds (parity with
``test_config.py``)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.privacy import (
    GaussianNoiseGenerator,
    LaplacianNoiseGenerator,
    NoiseType,
    PrivacyConfig,
    get_noise_generator,
    tree_add_noise,
    tree_noise,
    validate_noise_input,
)


class TestGenerators:
    @pytest.mark.parametrize("gen", [GaussianNoiseGenerator(), LaplacianNoiseGenerator()])
    def test_shape_and_dtype(self, gen, rng):
        out = gen.sample(rng, (4, 7), 1.0)
        assert out.shape == (4, 7)

    def test_seed_reproducibility(self, rng):
        gen = GaussianNoiseGenerator()
        a = gen.sample(rng, (100,), 2.0)
        b = gen.sample(rng, (100,), 2.0)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        c = gen.sample(jax.random.key(1), (100,), 2.0)
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_gaussian_scale(self, rng):
        out = GaussianNoiseGenerator().sample(rng, (200_000,), 3.0)
        assert float(jnp.std(out)) == pytest.approx(3.0, rel=0.02)
        assert float(jnp.mean(out)) == pytest.approx(0.0, abs=0.05)

    def test_laplace_scale(self, rng):
        # Laplace(b) has std b*sqrt(2).
        out = LaplacianNoiseGenerator().sample(rng, (200_000,), 2.0)
        assert float(jnp.std(out)) == pytest.approx(2.0 * np.sqrt(2), rel=0.02)

    def test_zero_scale_is_zero(self, rng):
        out = GaussianNoiseGenerator().sample(rng, (10,), 0.0)
        np.testing.assert_array_equal(np.asarray(out), np.zeros(10))

    def test_validation(self):
        with pytest.raises(ValueError):
            validate_noise_input((-1, 3), 1.0)
        with pytest.raises(ValueError):
            validate_noise_input((3,), -1.0)

    def test_factory(self):
        assert isinstance(get_noise_generator(NoiseType.GAUSSIAN), GaussianNoiseGenerator)
        assert isinstance(get_noise_generator("laplacian"), LaplacianNoiseGenerator)


class TestTreeNoise:
    def test_leaves_get_independent_noise(self, rng):
        tree = {"a": jnp.zeros((50,)), "b": jnp.zeros((50,))}
        noised = tree_noise(rng, tree, 1.0)
        assert not np.array_equal(np.asarray(noised["a"]), np.asarray(noised["b"]))

    def test_add_noise_preserves_structure_and_dtype(self, rng):
        tree = {"w": jnp.ones((3, 4), jnp.bfloat16), "b": jnp.ones((4,))}
        out = tree_add_noise(rng, tree, 0.5)
        assert jax.tree.structure(out) == jax.tree.structure(tree)
        assert out["w"].dtype == jnp.bfloat16
        assert out["b"].shape == (4,)

    def test_jit_compatible(self, rng):
        tree = {"w": jnp.zeros((8,))}
        jitted = jax.jit(lambda k, t: tree_add_noise(k, t, 1.0))
        out = jitted(rng, tree)
        assert np.isfinite(np.asarray(out["w"])).all()


class TestPrivacyConfig:
    def test_defaults_valid(self):
        cfg = PrivacyConfig()
        assert cfg.epsilon == 1.0 and cfg.noise_type is NoiseType.GAUSSIAN

    @pytest.mark.parametrize(
        "kw",
        [
            {"epsilon": 0.001},
            {"epsilon": 100.0},
            {"delta": 1e-12},
            {"delta": 0.5},
            {"max_gradient_norm": 0.0},
            {"noise_multiplier": -1.0},
        ],
    )
    def test_bounds_enforced(self, kw):
        with pytest.raises(ValueError):
            PrivacyConfig(**kw)

    def test_frozen(self):
        cfg = PrivacyConfig()
        with pytest.raises(dataclasses.FrozenInstanceError):
            cfg.epsilon = 2.0

    def test_hashable_for_jit_static(self):
        assert hash(PrivacyConfig()) == hash(PrivacyConfig())
