"""Accountant math: exact values, composition, monotonicity, amplification, edge cases,
stress — the capability set of the reference's deepest suite
(``tests/unit/privacy/test_gaussian.py``, ``test_rdp.py``, ``test_privacy_properties.py``,
``test_privacy_edge_cases.py``, ``test_privacy_stress.py``)."""

import math

import numpy as np
import pytest

from nanofed_tpu.privacy import (
    GaussianAccountant,
    PrivacySpent,
    RDPAccountant,
    noise_multiplier_for_budget,
)


class TestPrivacySpent:
    def test_valid(self):
        s = PrivacySpent(epsilon_spent=1.0, delta_spent=1e-5)
        assert s.epsilon_spent == 1.0
        assert s.to_dict() == {"epsilon_spent": 1.0, "delta_spent": 1e-5}
        assert PrivacySpent.from_dict(s.to_dict()) == s

    def test_negative_epsilon_rejected(self):
        with pytest.raises(ValueError):
            PrivacySpent(epsilon_spent=-0.1, delta_spent=1e-5)

    def test_delta_out_of_range_rejected(self):
        with pytest.raises(ValueError):
            PrivacySpent(epsilon_spent=1.0, delta_spent=1.5)


class TestGaussianAccountant:
    def test_empty_spend_is_zero(self):
        acc = GaussianAccountant()
        spent = acc.get_privacy_spent(1e-5)
        assert spent.epsilon_spent == 0.0
        assert spent.delta_spent == 0.0

    def test_single_event_exact_value(self):
        # eps = ln(1 + q*(e^{eps0} - 1)) with eps0 = sqrt(2 ln(1.25/delta)) / sigma:
        # the EXACT subsampling amplification bound, not the small-eps linear q*eps0.
        acc = GaussianAccountant()
        acc.add_noise_event(noise_multiplier=2.0, sampling_rate=0.1)
        eps0 = math.sqrt(2 * math.log(1.25 / 1e-5)) / 2.0
        expect = math.log1p(0.1 * math.expm1(eps0))
        assert acc.get_privacy_spent(1e-5).epsilon_spent == pytest.approx(expect)
        # Strictly more conservative than the naive linear amplification.
        assert acc.get_privacy_spent(1e-5).epsilon_spent > 0.1 * eps0

    def test_full_participation_is_unamplified(self):
        acc = GaussianAccountant()
        acc.add_noise_event(noise_multiplier=2.0, sampling_rate=1.0)
        expect = math.sqrt(2 * math.log(1.25 / 1e-5)) / 2.0
        assert acc.get_privacy_spent(1e-5).epsilon_spent == pytest.approx(expect)

    def test_basic_composition_with_delta_split(self):
        """k events compose with each event evaluated at delta/k so the composed
        guarantee really holds at the queried delta (slightly superlinear in k — never
        the anti-conservative fixed-delta linear sum)."""
        a1, a10 = GaussianAccountant(), GaussianAccountant()
        a1.add_noise_event(1.0, 0.01)
        a10.add_noise_event(1.0, 0.01, count=10)
        e1 = a1.get_privacy_spent(1e-5).epsilon_spent
        e10 = a10.get_privacy_spent(1e-5).epsilon_spent
        eps0 = math.sqrt(2 * math.log(1.25 * 10 / 1e-5)) / 1.0
        expect = 10 * math.log1p(0.01 * math.expm1(eps0))
        assert e10 == pytest.approx(expect)
        assert e10 >= 10 * e1  # superlinear: delta/k makes each event cost more
        assert a10.get_privacy_spent(1e-5).delta_spent == 1e-5

    def test_epsilon_decreases_with_sigma(self):
        eps = []
        for sigma in [0.5, 1.0, 2.0, 4.0]:
            acc = GaussianAccountant()
            acc.add_noise_event(sigma, 0.1)
            eps.append(acc.get_privacy_spent(1e-5).epsilon_spent)
        assert eps == sorted(eps, reverse=True)

    def test_epsilon_scales_with_sampling_rate(self):
        acc_lo, acc_hi = GaussianAccountant(), GaussianAccountant()
        acc_lo.add_noise_event(1.0, 0.01)
        acc_hi.add_noise_event(1.0, 0.1)
        lo = acc_lo.get_privacy_spent(1e-5).epsilon_spent
        hi = acc_hi.get_privacy_spent(1e-5).epsilon_spent
        # Monotone in q, and sub-linear (ln(1+qX) is concave in q).
        assert lo < hi <= 10 * lo

    def test_tiny_sigma_subsampled_is_finite_not_overflow(self):
        """sigma small enough that e^{eps0} overflows must fall back to the exact
        large-eps0 asymptote ln(q)+eps0, not raise OverflowError."""
        acc = GaussianAccountant()
        acc.add_noise_event(0.005, 0.5)
        eps0 = math.sqrt(2 * math.log(1.25 / 1e-5)) / 0.005
        got = acc.get_privacy_spent(1e-5).epsilon_spent
        assert math.isfinite(got)
        assert got == pytest.approx(eps0 + math.log(0.5), rel=1e-9)

    def test_invalid_events_rejected(self):
        acc = GaussianAccountant()
        with pytest.raises(ValueError):
            acc.add_noise_event(0.0, 0.1)
        with pytest.raises(ValueError):
            acc.add_noise_event(1.0, 0.0)
        with pytest.raises(ValueError):
            acc.add_noise_event(1.0, 1.5)
        with pytest.raises(ValueError):
            acc.add_noise_event(1.0, 0.1, count=0)

    def test_invalid_delta_rejected(self):
        acc = GaussianAccountant()
        acc.add_noise_event(1.0, 0.1)
        for bad in [0.0, 1.0, -0.1]:
            with pytest.raises(ValueError):
                acc.get_privacy_spent(bad)

    def test_validate_budget(self):
        acc = GaussianAccountant()
        acc.add_noise_event(1.0, 0.01)
        assert acc.validate_budget(epsilon=10.0, delta=1e-5)
        assert not acc.validate_budget(epsilon=1e-6, delta=1e-5)

    def test_reset_and_state_roundtrip(self):
        acc = GaussianAccountant()
        acc.add_noise_event(1.0, 0.1, count=3)
        acc.add_noise_event(2.0, 0.2)
        state = acc.state_dict()
        acc2 = GaussianAccountant()
        acc2.load_state_dict(state)
        assert acc2.get_privacy_spent(1e-5) == acc.get_privacy_spent(1e-5)
        acc.reset()
        assert acc.num_events == 0
        assert acc.get_privacy_spent(1e-5).epsilon_spent == 0.0


class TestRDPAccountant:
    def test_empty_spend_is_zero(self):
        assert RDPAccountant().get_privacy_spent(1e-5).epsilon_spent == 0.0

    def test_single_event_matches_manual_conversion(self):
        acc = RDPAccountant(orders=[2.0, 8.0, 32.0])
        acc.add_noise_event(1.0, 0.1)
        # Exact sampled-Gaussian RDP at sigma=1, q=0.1 — values cross-checked against
        # direct numerical integration of E_{x~p0}[(mix/p0)^alpha] (6-decimal match):
        # RDP(2)=0.017037, RDP(8)=1.378361, RDP(32)=13.623138.
        manual = min(
            r + math.log(1e5) / (a - 1.0)
            for r, a in [(0.017037, 2.0), (1.378361, 8.0), (13.623138, 32.0)]
        )
        assert acc.get_privacy_spent(1e-5).epsilon_spent == pytest.approx(
            manual, rel=1e-4
        )

    def test_additive_rdp_composition(self):
        a1, a5 = RDPAccountant(), RDPAccountant()
        a1.add_noise_event(1.0, 0.05)
        a5.add_noise_event(1.0, 0.05, count=5)
        np.testing.assert_allclose(a5.total_rdp(), 5 * a1.total_rdp())

    def test_monotone_in_events(self):
        acc = RDPAccountant()
        prev = 0.0
        for _ in range(20):
            acc.add_noise_event(1.0, 0.05)
            cur = acc.get_privacy_spent(1e-5).epsilon_spent
            assert cur > prev
            prev = cur

    def test_tighter_than_gaussian_for_many_events(self):
        # The point of RDP: sublinear composition beats linear for long runs.
        g, r = GaussianAccountant(), RDPAccountant()
        g.add_noise_event(1.0, 0.01, count=10_000)
        r.add_noise_event(1.0, 0.01, count=10_000)
        assert (
            r.get_privacy_spent(1e-5).epsilon_spent
            < g.get_privacy_spent(1e-5).epsilon_spent
        )

    def test_amplification_by_subsampling(self):
        eps = []
        for q in [0.001, 0.01, 0.1, 1.0]:
            acc = RDPAccountant()
            acc.add_noise_event(1.0, q, count=100)
            eps.append(acc.get_privacy_spent(1e-5).epsilon_spent)
        assert eps == sorted(eps)

    def test_exact_rdp_never_below_q_squared_claim(self):
        """The q²α/(2σ²) approximation is invalid for small σ and over-claims
        amplification; the exact form must dominate it everywhere it matters.  At
        σ=0.44, q=0.1 the approximation claims RDP(2) ≈ 0.0517 while the exact value is
        1.008 (cross-checked by numerical integration) — a ~20× under-report that this
        accountant must never reproduce."""
        from nanofed_tpu.privacy.accounting import sampled_gaussian_rdp

        orders = np.array([2.0, 4.0, 8.0, 16.0, 32.0])
        for sigma in (0.44, 1.0, 2.0, 5.0):
            for q in (0.01, 0.1, 0.5):
                exact = sampled_gaussian_rdp(sigma, q, orders)
                approx = q * q * orders / (2 * sigma * sigma)
                assert (exact >= approx - 1e-12).all(), (sigma, q)
        exact = sampled_gaussian_rdp(0.44, 0.1, np.array([2.0]))
        assert exact[0] == pytest.approx(1.008279, rel=1e-4)

    def test_moderate_q_amplification_is_exact_not_forfeited(self):
        """q=0.5 gets the exact amplified bound: strictly below the q=1 cost (sampling
        does help) but strictly above the q=0.1 cost (monotone in q)."""
        mid, full, small = RDPAccountant(), RDPAccountant(), RDPAccountant()
        mid.add_noise_event(1.0, 0.5, count=10)
        full.add_noise_event(1.0, 1.0, count=10)
        small.add_noise_event(1.0, 0.1, count=10)
        e_mid = mid.get_privacy_spent(1e-5).epsilon_spent
        assert small.get_privacy_spent(1e-5).epsilon_spent < e_mid
        assert e_mid < full.get_privacy_spent(1e-5).epsilon_spent

    def test_fractional_orders_excluded_for_subsampled_events(self):
        """For q < 1 the closed form only exists at integer α ≥ 2 — fractional orders
        are excluded (inf), and an all-fractional grid reports inf (conservative),
        never a silent wrong number."""
        acc = RDPAccountant(orders=[1.25, 1.5, 2.0, 3.0])
        acc.add_noise_event(1.0, 0.1)
        rdp = acc.total_rdp()
        assert np.isinf(rdp[0]) and np.isinf(rdp[1])
        assert np.isfinite(rdp[2]) and np.isfinite(rdp[3])
        frac_only = RDPAccountant(orders=[1.25, 1.5])
        frac_only.add_noise_event(1.0, 0.1)
        assert frac_only.get_privacy_spent(1e-5).epsilon_spent == np.inf

    def test_orders_must_exceed_one(self):
        with pytest.raises(ValueError):
            RDPAccountant(orders=[0.5, 2.0])

    def test_optimal_order_in_grid(self):
        acc = RDPAccountant()
        acc.add_noise_event(1.0, 0.01, count=100)
        assert acc.optimal_order(1e-5) in set(acc.orders)

    def test_stress_100k_events_collapsed(self):
        # Runs of identical events collapse; 100k-step accounting is O(1) space.
        acc = RDPAccountant()
        acc.add_noise_event(1.1, 0.004, count=100_000)
        assert len(acc.state_dict()["events"]) == 1
        spent = acc.get_privacy_spent(1e-5)
        assert 0 < spent.epsilon_spent < 100


class TestNoiseCalibration:
    def test_calibrated_sigma_meets_budget(self):
        sigma = noise_multiplier_for_budget(
            epsilon=2.0, delta=1e-5, sampling_rate=0.01, num_events=1000
        )
        acc = RDPAccountant()
        acc.add_noise_event(sigma, 0.01, count=1000)
        assert acc.get_privacy_spent(1e-5).epsilon_spent <= 2.0
        # ... and is not wastefully large: slightly less noise must blow the budget.
        acc2 = RDPAccountant()
        acc2.add_noise_event(max(sigma - 0.05, 1e-3), 0.01, count=1000)
        assert acc2.get_privacy_spent(1e-5).epsilon_spent > 2.0

    def test_tighter_budget_needs_more_noise(self):
        s1 = noise_multiplier_for_budget(1.0, 1e-5, 0.01, 100)
        s2 = noise_multiplier_for_budget(5.0, 1e-5, 0.01, 100)
        assert s1 > s2


def test_docs_worked_example_numbers():
    """Pins the worked example in docs/concepts.md §12: 100 central-DP rounds at
    sigma=1, q=1, delta=1e-5."""
    g, r = GaussianAccountant(), RDPAccountant()
    g.add_noise_event(1.0, 1.0, count=100)
    r.add_noise_event(1.0, 1.0, count=100)
    assert g.get_privacy_spent(1e-5).epsilon_spent == pytest.approx(571.7, abs=0.1)
    assert r.get_privacy_spent(1e-5).epsilon_spent == pytest.approx(98.0, abs=0.1)
