"""Coordinate-wise trimmed-mean aggregation (Yin et al. 2018): the math against a
numpy reference, the Byzantine influence bound, the fail-closed floor, and the full
SPMD round step with an attacker in the cohort."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.aggregation import (
    RobustAggregationConfig,
    coordinate_median,
    multi_krum,
    robust_aggregate,
    robust_floor,
    trimmed_mean,
)
from nanofed_tpu.trainer import TrainingConfig, stack_rngs


def _np_trimmed_mean(vals, mask, k):
    """Per-coordinate numpy reference: drop k extremes per side among participants."""
    out = np.zeros(vals.shape[1:], np.float32)
    it = np.nditer(out, flags=["multi_index"])
    for _ in it:
        idx = it.multi_index
        col = np.sort(vals[(slice(None), *idx)][mask.astype(bool)])
        out[idx] = col[k:-k].mean() if len(col) > 2 * k else 0.0
    return out


@pytest.mark.parametrize("seed", range(5))
def test_matches_numpy_reference_with_masks(seed):
    rng = np.random.default_rng(seed)
    c = int(rng.integers(5, 12))
    k = int(rng.integers(1, 3))
    mask = np.zeros(c, np.float32)
    mask[rng.choice(c, size=int(rng.integers(2 * k + 1, c + 1)), replace=False)] = 1.0
    tree = {"w": rng.normal(size=(c, 3, 2)).astype(np.float32),
            "b": rng.normal(size=(c, 4)).astype(np.float32)}
    got, ok, _ = trimmed_mean(jax.tree.map(jnp.asarray, tree), jnp.asarray(mask), k)
    assert bool(ok)
    for key in tree:
        np.testing.assert_allclose(
            np.asarray(got[key]), _np_trimmed_mean(tree[key], mask, k),
            rtol=1e-5, atol=1e-6,
        )


def test_byzantine_influence_is_bounded():
    """One attacker submitting +/-1e9 per coordinate: with trim_k=1 the aggregate
    must stay inside the honest clients' value range, coordinate-wise."""
    rng = np.random.default_rng(0)
    honest = rng.normal(size=(6, 8)).astype(np.float32)
    attack = np.where(rng.random(8) < 0.5, 1e9, -1e9).astype(np.float32)
    vals = np.concatenate([honest, attack[None]], axis=0)
    mask = np.ones(7, np.float32)
    got, ok, kept = trimmed_mean({"w": jnp.asarray(vals)}, jnp.asarray(mask), 1)
    assert bool(ok) and float(kept) == 5.0  # 7 participants - 2*1
    g = np.asarray(got["w"])
    assert (g >= honest.min(axis=0) - 1e-6).all()
    assert (g <= honest.max(axis=0) + 1e-6).all()
    # And the unweighted mean WOULD have been destroyed — the trim is load-bearing.
    assert np.abs(vals.mean(axis=0)).max() > 1e8


def test_fails_closed_below_the_floor():
    # 2 participants with trim_k=1 < 2k+1=3: zero aggregate, ok=False.
    vals = jnp.asarray(np.ones((4, 3), np.float32))
    mask = jnp.asarray([1.0, 1.0, 0.0, 0.0])
    got, ok, kept = trimmed_mean({"w": vals}, mask, 1)
    assert not bool(ok) and float(kept) == 0.0
    np.testing.assert_array_equal(np.asarray(got["w"]), 0.0)


def test_config_validates():
    with pytest.raises(ValueError, match="trim_k"):
        RobustAggregationConfig(trim_k=0)
    with pytest.raises(ValueError, match="unknown robust method"):
        RobustAggregationConfig(method="krum")
    # median ignores trim_k entirely, including a zero one
    RobustAggregationConfig(trim_k=0, method="median")
    assert robust_floor(RobustAggregationConfig(trim_k=3)) == 7
    assert robust_floor(RobustAggregationConfig(method="median")) == 3


@pytest.mark.parametrize("seed", range(4))
def test_median_matches_numpy_reference_with_masks(seed):
    rng = np.random.default_rng(100 + seed)
    c = int(rng.integers(4, 11))
    mask = np.zeros(c, np.float32)
    m = int(rng.integers(3, c + 1))
    mask[rng.choice(c, size=m, replace=False)] = 1.0
    vals = rng.normal(size=(c, 5)).astype(np.float32)
    got, ok, kept = coordinate_median({"w": jnp.asarray(vals)}, jnp.asarray(mask))
    assert bool(ok)
    assert float(kept) == m  # participant count (not "ranks averaged")
    expected = np.median(vals[mask.astype(bool)], axis=0)
    np.testing.assert_allclose(np.asarray(got["w"]), expected, rtol=1e-5, atol=1e-6)


def test_median_outvotes_any_minority():
    # 3 attackers among 7: the median ignores them entirely (trimmed mean would
    # need trim_k=3, leaving only 1 rank — the median IS that estimator, knob-free).
    rng = np.random.default_rng(1)
    honest = rng.normal(size=(4, 6)).astype(np.float32)
    attack = np.full((3, 6), 1e9, np.float32)
    vals = np.concatenate([honest, attack], axis=0)
    got, ok, _ = coordinate_median({"w": jnp.asarray(vals)},
                                   jnp.ones(7, jnp.float32))
    assert bool(ok)
    g = np.asarray(got["w"])
    assert (g <= honest.max(axis=0) + 1e-6).all()


def test_median_fails_closed_below_three():
    got, ok, kept = coordinate_median(
        {"w": jnp.ones((4, 2))}, jnp.asarray([1.0, 1.0, 0.0, 0.0])
    )
    assert not bool(ok) and float(kept) == 0.0
    np.testing.assert_array_equal(np.asarray(got["w"]), 0.0)


def test_robust_aggregate_dispatches():
    vals = {"w": jnp.asarray(np.arange(15, dtype=np.float32).reshape(5, 3))}
    ones = jnp.ones(5, jnp.float32)
    med, _, _ = robust_aggregate(RobustAggregationConfig(method="median"), vals, ones)
    tm, _, _ = robust_aggregate(RobustAggregationConfig(trim_k=1), vals, ones)
    np.testing.assert_allclose(np.asarray(med["w"]), [6.0, 7.0, 8.0])
    np.testing.assert_allclose(np.asarray(tm["w"]), [6.0, 7.0, 8.0])  # symmetric data


@pytest.mark.parametrize("seed", range(5))
def test_multi_krum_matches_numpy_reference_with_masks(seed):
    rng = np.random.default_rng(seed)
    c = int(rng.integers(8, 14))
    f = 1
    mask = np.zeros(c, np.float32)
    mask[rng.choice(c, size=int(rng.integers(2 * f + 3, c + 1)), replace=False)] = 1.0
    tree = {"w": rng.normal(size=(c, 3, 2)).astype(np.float32),
            "b": rng.normal(size=(c, 4)).astype(np.float32)}
    got, ok, kept = multi_krum(jax.tree.map(jnp.asarray, tree), jnp.asarray(mask), f)
    assert bool(ok) and float(kept) == mask.sum() - f
    stacked = np.concatenate(
        [tree["w"].reshape(c, -1), tree["b"].reshape(c, -1)], axis=1
    )
    # Selection is over the JOINT vector; verify each leaf against the same choice.
    for key in tree:
        want = _np_multi_krum_joint(tree, stacked, mask, f)[key]
        np.testing.assert_allclose(np.asarray(got[key]), want, rtol=1e-4, atol=1e-5)


def _np_multi_krum_joint(tree, stacked, mask, f):
    idx = np.where(mask.astype(bool))[0]
    m = len(idx)
    flat = stacked[idx].astype(np.float64)
    d2 = ((flat[:, None, :] - flat[None, :, :]) ** 2).sum(-1)
    n_near = max(m - f - 2, 1)
    scores = np.array([np.sort(d2[i])[1:1 + n_near].sum() for i in range(m)])
    chosen = idx[np.argsort(scores, kind="stable")[: max(m - f, 1)]]
    return {k: tree[k][chosen].mean(axis=0) for k in tree}


def test_multi_krum_excludes_the_distant_attacker():
    """A jointly-distant update (coordinate-wise plausible, far from every honest
    peer) must not be selected — the attack profile per-coordinate trims can miss."""
    rng = np.random.default_rng(1)
    honest = rng.normal(0, 0.01, size=(7, 16)).astype(np.float32)
    # Attacker stays inside each coordinate's honest range but flips the SIGN
    # correlation pattern — small per-coordinate, large joint distance.
    attack = (honest.std(0) * np.where(np.arange(16) % 2 == 0, 2.5, -2.5)).astype(
        np.float32
    )
    vals = np.concatenate([honest, attack[None]], axis=0)
    got, ok, kept = multi_krum(
        {"w": jnp.asarray(vals)}, jnp.ones(8, jnp.float32), 1
    )
    assert bool(ok) and float(kept) == 7.0
    np.testing.assert_allclose(
        np.asarray(got["w"]), honest.mean(axis=0), rtol=1e-4, atol=1e-5
    )


def test_multi_krum_fails_closed_below_floor():
    vals = {"w": jnp.asarray(np.ones((6, 3), np.float32))}
    mask = jnp.asarray([1, 1, 1, 1, 0, 0], jnp.float32)  # m=4 < 2f+3=5
    got, ok, kept = multi_krum(vals, mask, 1)
    assert not bool(ok) and float(kept) == 0.0
    np.testing.assert_array_equal(np.asarray(got["w"]), 0.0)
    assert robust_floor(RobustAggregationConfig(method="multi_krum", trim_k=1)) == 5


def test_round_step_multi_krum_bounds_byzantine(devices):
    """Multi-Krum inside the jitted SPMD round step: an input-scaled attacker's
    whole update is deselected and the released params stay sane."""
    from nanofed_tpu.parallel import build_round_step, make_mesh

    mesh = make_mesh()
    model, strategy, data, weights, padded, params, sos = _round_setup(8, mesh)
    x = np.array(data.x)
    x[0] = x[0] * 1e4
    poisoned = data._replace(x=jnp.asarray(x))
    training = TrainingConfig(batch_size=4, local_epochs=1, learning_rate=0.2)
    res = build_round_step(
        model.apply, training, mesh, strategy,
        robust=RobustAggregationConfig(method="multi_krum", trim_k=1),
    )(params, sos, poisoned, weights, stack_rngs(jax.random.key(5), padded))
    step = max(
        float(jnp.abs(a - b).max())
        for a, b in zip(jax.tree.leaves(res.params), jax.tree.leaves(params))
    )
    assert step < 1.0
    assert float(res.metrics["robust_kept_clients"]) == 7.0  # m - f = 8 - 1


def test_round_step_median_bounds_byzantine(devices):
    from nanofed_tpu.parallel import build_round_step, make_mesh

    mesh = make_mesh()
    model, strategy, data, weights, padded, params, sos = _round_setup(8, mesh)
    x = np.array(data.x)
    x[0] = x[0] * 1e4
    poisoned = data._replace(x=jnp.asarray(x))
    training = TrainingConfig(batch_size=4, local_epochs=1, learning_rate=0.2)
    res = build_round_step(
        model.apply, training, mesh, strategy,
        robust=RobustAggregationConfig(method="median"),
    )(params, sos, poisoned, weights, stack_rngs(jax.random.key(5), padded))
    step = max(
        float(jnp.abs(a - b).max())
        for a, b in zip(jax.tree.leaves(res.params), jax.tree.leaves(params))
    )
    assert step < 1.0
    assert float(res.metrics["robust_kept_clients"]) == 8.0  # all participants


def test_metrics_are_trimmed_too(devices):
    """An attacker's NaN loss must not corrupt the reported round metrics: under
    robust aggregation the loss/accuracy scalars ride the SAME trimmed estimator
    as the deltas (a NaN sorts past the +inf padding and lands in the trimmed
    top-k ranks)."""
    from nanofed_tpu.parallel import build_round_step, make_mesh

    mesh = make_mesh()
    model, strategy, data, weights, padded, params, sos = _round_setup(8, mesh)
    x = np.array(data.x)
    x[0] = np.nan  # NaN inputs -> NaN loss (and NaN delta) for client 0
    poisoned = data._replace(x=jnp.asarray(x))
    training = TrainingConfig(batch_size=4, local_epochs=1, learning_rate=0.1)
    res = build_round_step(
        model.apply, training, mesh, strategy,
        robust=RobustAggregationConfig(trim_k=1),
    )(params, sos, poisoned, weights, stack_rngs(jax.random.key(3), padded))
    assert np.isfinite(float(res.metrics["loss"]))
    assert np.isfinite(float(res.metrics["accuracy"]))
    for leaf in jax.tree.leaves(res.params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_coordinator_refuses_infeasible_trim(tmp_path, devices):
    """A trim_k the sampled cohort can never satisfy would fail every round closed
    while reporting COMPLETED — refused at construction instead."""
    from nanofed_tpu.data import federate, synthetic_classification
    from nanofed_tpu.models import get_model
    from nanofed_tpu.orchestration import Coordinator, CoordinatorConfig

    cd = federate(synthetic_classification(64, 2, (6,), seed=0), num_clients=8,
                  scheme="iid", batch_size=4)
    with pytest.raises(ValueError, match="cohort of at least"):
        Coordinator(
            model=get_model("linear", in_features=6, num_classes=2),
            train_data=cd,
            config=CoordinatorConfig(num_rounds=2, seed=0, base_dir=tmp_path,
                                     save_metrics=False),
            training=TrainingConfig(batch_size=4),
            robust=RobustAggregationConfig(trim_k=4),  # needs 9 > 8 clients
        )


def _round_setup(n_clients, mesh):
    from nanofed_tpu.data import pack_clients, synthetic_classification
    from nanofed_tpu.models import get_model
    from nanofed_tpu.parallel import (
        init_server_state,
        pad_client_count,
        pad_clients,
        replicated_sharding,
        shard_client_data,
    )
    from nanofed_tpu.aggregation import compute_weights, fedavg_strategy

    model = get_model("linear", in_features=6, num_classes=2)
    ds = synthetic_classification(n_clients * 8, 2, (6,), seed=0)
    data = pack_clients(
        ds, [np.arange(i * 8, (i + 1) * 8) for i in range(n_clients)], batch_size=4
    )
    n_dev = len(mesh.devices.flat)
    padded = pad_client_count(n_clients, n_dev)
    data = shard_client_data(pad_clients(data, padded), mesh)
    num_samples = jnp.asarray(np.asarray(data.mask).sum(axis=1))
    weights = compute_weights(num_samples) * (num_samples > 0)
    strategy = fedavg_strategy()
    repl = replicated_sharding(mesh)
    params = jax.device_put(model.init(jax.random.key(0)), repl)
    sos = jax.device_put(init_server_state(strategy, params), repl)
    return model, strategy, data, weights, padded, params, sos


def test_round_step_with_byzantine_client(devices):
    """End-to-end through shard_map: a poisoned client (its data label-flipped and
    its slot amplified via a huge-loss regime is hard to fake deterministically, so
    we poison the DELTA path instead: one client's weight is fine but its local data
    drives an enormous update via lr) cannot blow up the robust round, while the
    plain weighted mean moves dramatically."""
    from nanofed_tpu.parallel import build_round_step, make_mesh

    mesh = make_mesh()
    model, strategy, data, weights, padded, params, sos = _round_setup(8, mesh)
    # Poison: client 0 trains at an insane effective lr by receiving pre-scaled
    # data (x * 1e4) — its delta explodes while everyone else's stays moderate.
    x = np.array(data.x)  # copy: device arrays are read-only views
    x[0] = x[0] * 1e4
    poisoned = data._replace(x=jnp.asarray(x))

    training = TrainingConfig(batch_size=4, local_epochs=1, learning_rate=0.2)
    rngs = stack_rngs(jax.random.key(1), padded)

    plain_step = build_round_step(model.apply, training, mesh, strategy)
    robust_step = build_round_step(
        model.apply, training, mesh, strategy,
        robust=RobustAggregationConfig(trim_k=1),
    )
    plain = plain_step(params, sos, poisoned, weights, rngs)
    robust = robust_step(params, sos, poisoned, weights, rngs)

    def max_step(res):
        return max(
            float(jnp.abs(a - b).max())
            for a, b in zip(jax.tree.leaves(res.params), jax.tree.leaves(params))
        )

    assert max_step(plain) > 10 * max_step(robust)
    assert max_step(robust) < 1.0  # honest-range-sized update
    assert float(robust.metrics["robust_kept_clients"]) == 6.0  # 8 - 2*trim_k


def test_robust_round_without_attackers_close_to_uniform_mean(devices):
    """No Byzantine clients: the trimmed mean is a mild re-weighting, not a
    different algorithm — one round's params should land near the plain round's."""
    from nanofed_tpu.parallel import build_round_step, make_mesh

    mesh = make_mesh()
    model, strategy, data, weights, padded, params, sos = _round_setup(8, mesh)
    training = TrainingConfig(batch_size=4, local_epochs=1, learning_rate=0.1)
    rngs = stack_rngs(jax.random.key(2), padded)
    uniform = (weights > 0).astype(jnp.float32)  # trimmed mean is unweighted
    plain = build_round_step(model.apply, training, mesh, strategy)(
        params, sos, data, uniform, rngs
    )
    robust = build_round_step(
        model.apply, training, mesh, strategy,
        robust=RobustAggregationConfig(trim_k=1),
    )(params, sos, data, weights, rngs)
    for a, b in zip(jax.tree.leaves(plain.params), jax.tree.leaves(robust.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0.05)


def test_robust_refuses_central_privacy(devices):
    from nanofed_tpu.aggregation.privacy import PrivacyAwareAggregationConfig
    from nanofed_tpu.models import get_model
    from nanofed_tpu.parallel import build_round_step, make_mesh
    from nanofed_tpu.privacy import PrivacyConfig

    with pytest.raises(ValueError, match="robust"):
        build_round_step(
            get_model("linear", in_features=4, num_classes=2).apply,
            TrainingConfig(batch_size=4),
            make_mesh(),
            robust=RobustAggregationConfig(trim_k=1),
            central_privacy=PrivacyAwareAggregationConfig(
                privacy=PrivacyConfig(epsilon=1.0, delta=1e-5)
            ),
        )