"""Aggregation tests — exact weighted-mean values like the reference's
``tests/unit/server/aggregator/test_fedavg.py:21-76``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from nanofed_tpu.aggregation import (
    aggregate_metrics,
    compute_weights,
    fedadam_strategy,
    fedavg_combine,
    fedyogi_strategy,
    fedavgm_strategy,
    fedavg_strategy,
    psum_weighted_mean,
    validate_updates,
)
from nanofed_tpu.core.exceptions import AggregationError
from nanofed_tpu.core.types import ClientMetrics, ClientUpdates
from nanofed_tpu.parallel import make_mesh


def _updates(params_list, weights, losses=None, accs=None, samples=None):
    c = len(params_list)
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)
    return ClientUpdates(
        params=stacked,
        weights=jnp.asarray(weights, jnp.float32),
        metrics=ClientMetrics(
            loss=jnp.asarray(losses if losses is not None else [0.0] * c),
            accuracy=jnp.asarray(accs if accs is not None else [0.0] * c),
            samples=jnp.asarray(samples if samples is not None else [1.0] * c),
        ),
    )


def test_fedavg_exact_weighted_average():
    # Two clients, weights 1:2 — parity with the reference's exact assertions.
    p1 = {"w": jnp.asarray([3.0, 0.0])}
    p2 = {"w": jnp.asarray([6.0, 3.0])}
    out = fedavg_combine(_updates([p1, p2], [1.0, 2.0]))
    np.testing.assert_allclose(np.asarray(out["w"]), [(3 + 12) / 3, (0 + 6) / 3])


def test_metric_aggregation_exact():
    # loss = 0.1 * 1/3 + 0.2 * 2/3 (the reference's documented example).
    m = ClientMetrics(
        loss=jnp.asarray([0.1, 0.2]),
        accuracy=jnp.asarray([1.0, 0.4]),
        samples=jnp.asarray([100.0, 200.0]),
    )
    out = aggregate_metrics(m, jnp.asarray([1.0, 2.0]))
    assert float(out["loss"]) == pytest.approx(0.1 / 3 + 0.4 / 3)
    assert float(out["accuracy"]) == pytest.approx(1 / 3 + 0.8 / 3)
    assert float(out["samples"]) == 300.0


def test_compute_weights_masking():
    w = compute_weights(jnp.asarray([10.0, 20.0, 30.0]), jnp.asarray([1.0, 0.0, 1.0]))
    np.testing.assert_allclose(np.asarray(w), [10.0, 0.0, 30.0])


def test_validate_updates_rejects_bad_tree():
    good = {"w": jnp.zeros((2, 3))}
    with pytest.raises(AggregationError):
        validate_updates(
            ClientUpdates(
                params={"other": jnp.zeros((2, 3))},
                weights=jnp.ones(2),
                metrics=ClientMetrics(jnp.zeros(2), jnp.zeros(2), jnp.zeros(2)),
            ),
            {"w": jnp.zeros(3)},
        )
    with pytest.raises(AggregationError):
        validate_updates(
            ClientUpdates(
                params={"w": jnp.zeros((2, 4))},
                weights=jnp.ones(2),
                metrics=ClientMetrics(jnp.zeros(2), jnp.zeros(2), jnp.zeros(2)),
            ),
            {"w": jnp.zeros(3)},
        )
    # Well-formed passes.
    validate_updates(
        ClientUpdates(
            params=good,
            weights=jnp.ones(2),
            metrics=ClientMetrics(jnp.zeros(2), jnp.zeros(2), jnp.zeros(2)),
        ),
        {"w": jnp.zeros(3)},
    )


def test_psum_weighted_mean_matches_host(devices):
    """The in-mesh reduction must equal the host-side weighted mean exactly."""
    mesh = make_mesh()
    c = 8
    tree = {"w": jnp.arange(c * 3, dtype=jnp.float32).reshape(c, 3)}
    weights = jnp.asarray([1.0, 2.0, 0.0, 1.0, 3.0, 1.0, 0.5, 2.5])

    expected = np.asarray(
        (tree["w"] * weights[:, None]).sum(0) / weights.sum()
    )

    def body(t, w):
        return psum_weighted_mean(t, w, "clients")

    # The compat shim, not jax.shard_map directly: the installed JAX may predate
    # shard_map's graduation out of jax.experimental (the shim resolves either way).
    from nanofed_tpu.parallel.mesh import shard_map

    out = jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(P("clients"), P("clients")), out_specs=P()
        )
    )(tree, weights)
    np.testing.assert_allclose(np.asarray(out["w"]), expected, rtol=1e-6)


def test_strategies_construct():
    assert fedavg_strategy().name == "fedavg"
    assert fedavgm_strategy().name == "fedavgm"
    assert fedadam_strategy().name == "fedadam"
    assert fedyogi_strategy().name == "fedyogi"


def test_fedyogi_round_applies_adaptive_delta():
    """FedYogi's server transform must consume the aggregated delta like the other
    adaptive strategies: first round's update magnitude ~ lr (Adam-family invariant
    |update| <= lr * (1+eps') at step 0), direction matching the delta's sign."""
    strat = fedyogi_strategy(learning_rate=0.1)
    params = {"w": jnp.zeros(3)}
    sos = strat.server_tx.init(params)
    agg_delta = {"w": jnp.asarray([0.5, -0.25, 0.0])}
    neg = jax.tree.map(jnp.negative, agg_delta)
    updates, _ = strat.server_tx.update(neg, sos, params)
    import optax

    new = optax.apply_updates(params, updates)
    w = np.asarray(new["w"])
    # The zero-delta coordinate moves only by yogi's initial-accumulator epsilon
    # artifact — negligible against lr, but not exactly zero like plain Adam.
    assert w[0] > 0 and w[1] < 0 and abs(w[2]) < 1e-3 * 0.1
    assert np.all(np.abs(w) <= 0.1 * 1.01)


def test_server_lr_schedule_steps_per_round():
    """Server-side lr schedules ride optax's step counter, which counts ROUNDS here
    because the server optimizer state persists across rounds (the complement of the
    client-side traced lr_scale).  A schedule that zeroes the lr from step 1 on must
    apply round 1's delta and freeze the params for round 2."""
    import optax

    strat = fedavgm_strategy(
        learning_rate=lambda step: jnp.where(step == 0, 1.0, 0.0), momentum=0.0
    )
    params = {"w": jnp.zeros(3)}
    sos = strat.server_tx.init(params)
    delta = {"w": jnp.ones(3)}

    def apply(params, sos):
        neg = jax.tree.map(jnp.negative, delta)
        updates, sos = strat.server_tx.update(neg, sos, params)
        return optax.apply_updates(params, updates), sos

    params, sos = apply(params, sos)  # round 0: lr 1.0 -> +delta
    np.testing.assert_allclose(np.asarray(params["w"]), 1.0)
    params, sos = apply(params, sos)  # round 1: lr 0.0 -> frozen
    np.testing.assert_allclose(np.asarray(params["w"]), 1.0)
