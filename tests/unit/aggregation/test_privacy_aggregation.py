"""Privacy-aware aggregation (parity: ``tests/unit/server/aggregator/
test_privacy_aggregation.py`` — central noise, local reweighting, min-client and budget
validation)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.aggregation import (
    PrivacyAwareAggregationConfig,
    apply_central_privacy,
    epsilon_adjusted_weights,
    record_central_privacy,
    validate_private_round,
)
from nanofed_tpu.core.exceptions import AggregationError
from nanofed_tpu.privacy import GaussianAccountant, PrivacyConfig, PrivacySpent, PrivacyType


class TestConfig:
    def test_required_clients_with_dropout_tolerance(self):
        cfg = PrivacyAwareAggregationConfig(min_clients=10, dropout_tolerance=0.3)
        assert cfg.required_clients == 7
        assert PrivacyAwareAggregationConfig(min_clients=1).required_clients == 1

    def test_bounds(self):
        with pytest.raises(ValueError):
            PrivacyAwareAggregationConfig(min_clients=0)
        with pytest.raises(ValueError):
            PrivacyAwareAggregationConfig(dropout_tolerance=1.5)


class TestValidation:
    def test_too_few_clients_rejected(self):
        cfg = PrivacyAwareAggregationConfig(min_clients=5)
        with pytest.raises(AggregationError, match="not enough clients"):
            validate_private_round(cfg, num_participants=3)
        validate_private_round(cfg, num_participants=5)

    def test_local_dp_requires_spends(self):
        cfg = PrivacyAwareAggregationConfig(privacy_type=PrivacyType.LOCAL)
        with pytest.raises(AggregationError, match="privacy_spent"):
            validate_private_round(cfg, num_participants=2)
        with pytest.raises(AggregationError, match="missing privacy budget"):
            validate_private_round(
                cfg, 2, [PrivacySpent(0.5, 1e-5), None]
            )

    def test_local_dp_budget_enforced(self):
        cfg = PrivacyAwareAggregationConfig(
            privacy=PrivacyConfig(epsilon=1.0), privacy_type=PrivacyType.LOCAL
        )
        with pytest.raises(AggregationError, match="exceeded budget"):
            validate_private_round(
                cfg, 2, [PrivacySpent(0.5, 1e-5), PrivacySpent(3.0, 1e-5)]
            )
        validate_private_round(
            cfg, 2, [PrivacySpent(0.5, 1e-5), PrivacySpent(0.9, 1e-5)]
        )


class TestCentral:
    def test_clips_and_noises_each_client(self, rng):
        cfg = PrivacyAwareAggregationConfig(
            privacy=PrivacyConfig(max_gradient_norm=1.0, noise_multiplier=1e-6)
        )
        deltas = {"w": jnp.full((4, 30), 7.0)}
        out = apply_central_privacy(rng, deltas, cfg)
        norms = np.linalg.norm(np.asarray(out["w"]), axis=1)
        np.testing.assert_allclose(norms, 1.0, rtol=1e-3)

    def test_noise_scale_shrinks_with_cohort(self, rng):
        priv = PrivacyConfig(max_gradient_norm=1.0, noise_multiplier=1.0)
        cfg = PrivacyAwareAggregationConfig(privacy=priv)
        small = apply_central_privacy(rng, {"w": jnp.zeros((2, 4000))}, cfg)
        large = apply_central_privacy(rng, {"w": jnp.zeros((40, 4000))}, cfg)
        # scale = sigma*C/K: 40-client noise std is 20x smaller than 2-client.
        assert float(jnp.std(small["w"])) > 5 * float(jnp.std(large["w"]))

    def test_jits_inside_round_step_style_fn(self, rng):
        cfg = PrivacyAwareAggregationConfig(privacy=PrivacyConfig())
        f = jax.jit(lambda k, d: apply_central_privacy(k, d, cfg))
        out = f(rng, {"w": jnp.ones((3, 5))})
        assert np.isfinite(np.asarray(out["w"])).all()

    def test_accounting_one_event_per_round(self):
        # The in-mesh reduce is ONE release per round (effective multiplier sigma,
        # independent of cohort size) — not K events.
        cfg = PrivacyAwareAggregationConfig(privacy=PrivacyConfig(noise_multiplier=2.0))
        acc = GaussianAccountant()
        record_central_privacy(acc, cfg, num_rounds=5)
        assert acc.state_dict()["events"] == [[2.0, 1.0, 5.0]]

    def test_accounting_amplified_by_client_subsampling(self):
        # With a randomly sampled cohort (participation_rate = q), each round is a
        # subsampled Gaussian release: the RDP accountant credits q^2 amplification,
        # so spend at q=0.1 is far below spend at q=1 for the same sigma.
        from nanofed_tpu.privacy.accounting import RDPAccountant

        cfg = PrivacyAwareAggregationConfig(privacy=PrivacyConfig(noise_multiplier=1.0))
        full, sub = RDPAccountant(), RDPAccountant()
        record_central_privacy(full, cfg, num_rounds=20)
        record_central_privacy(sub, cfg, num_rounds=20, sampling_rate=0.1)
        assert sub.state_dict()["events"] == [[1.0, 0.1, 20.0]]
        eps_full = full.get_privacy_spent(1e-5).epsilon_spent
        eps_sub = sub.get_privacy_spent(1e-5).epsilon_spent
        assert eps_sub < eps_full / 5


class TestLocalReweighting:
    def test_epsilon_weighting_normalizes(self):
        w = jnp.array([10.0, 10.0, 10.0])
        eps = jnp.array([1.0, 2.0, 3.0])
        out = np.asarray(epsilon_adjusted_weights(w, eps))
        assert out.sum() == pytest.approx(1.0)
        # More epsilon spent => higher weight.
        assert out[2] > out[1] > out[0]
        np.testing.assert_allclose(out, np.array([1, 2, 3]) / 6, rtol=1e-6)

    def test_combines_with_sample_counts(self):
        w = jnp.array([30.0, 10.0])
        eps = jnp.array([1.0, 1.0])
        out = np.asarray(epsilon_adjusted_weights(w, eps))
        np.testing.assert_allclose(out, [0.75, 0.25], rtol=1e-6)

    def test_zero_safe(self):
        out = np.asarray(epsilon_adjusted_weights(jnp.zeros(3), jnp.zeros(3)))
        assert np.isfinite(out).all()
