"""Release tooling: prepare_release dry-run safety + changelog generation
(parity: the reference ships prepare_release.py + changelog.py + release.sh)."""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]


def test_prepare_release_dry_run_changes_nothing():
    before = {
        p: p.read_text()
        for p in (REPO / "pyproject.toml", REPO / "nanofed_tpu" / "__init__.py")
    }
    out = subprocess.run(
        [sys.executable, "scripts/prepare_release.py", "9.9.9", "--dry-run"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    assert "-> 9.9.9" in out.stdout
    for p, text in before.items():
        assert p.read_text() == text, f"{p} modified by --dry-run"
    assert not (REPO / "docs" / "releases" / "9.9.9.md").exists()


def test_prepare_release_rejects_bad_version():
    out = subprocess.run(
        [sys.executable, "scripts/prepare_release.py", "not-a-version"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode != 0


def test_changelog_generates_markdown():
    out = subprocess.run(
        [sys.executable, "scripts/changelog.py", "9.9.9", "--since", "HEAD~3"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.startswith("## 9.9.9")
