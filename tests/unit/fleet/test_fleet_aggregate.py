"""Unit tests for heterogeneous-rank aggregation (``nanofed_tpu.fleet.aggregate``).

The load-bearing property is ROUTE PARITY: the padded einsum fast path must
produce exactly the dense reference aggregate (zero pad rows/columns contribute
nothing to the contraction), for any mix of ranks and weights.  Everything else
— pad exactness, SVD projection optimality, dead-direction revival — protects
an invariant of the dense-delta-space design.
"""

import numpy as np
import pytest

from nanofed_tpu.adapters import AdapterSpec, adapter_delta, init_adapters
from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.fleet import (
    AdapterUpdate,
    aggregate_dense,
    aggregate_padded,
    pad_adapters_to_rank,
    project_to_rank,
    projection_error,
    redistribute,
    reference_fleet,
    revive_adapters,
)
from nanofed_tpu.utils.trees import tree_flatten_with_names

BASE = {
    "dense1": {"kernel": np.zeros((48, 64), np.float32)},
    "dense2": {"kernel": np.zeros((64, 32), np.float32)},
}
ALPHA = 32.0  # the reference fleet's common alpha (max rank)


def _update(rank, seed, weight=1.0, tier=""):
    spec = AdapterSpec(rank=rank, alpha=ALPHA)
    adapters = init_adapters(spec, BASE, rng=seed)
    # give B nonzero content too, or every delta is identically zero
    rng = np.random.default_rng(seed + 1000)
    import jax

    adapters = jax.tree.map(
        lambda x: np.asarray(x) + rng.normal(0, 0.02, np.shape(x)).astype(np.float32),
        adapters,
    )
    return AdapterUpdate(spec=spec, adapters=adapters, weight=weight, tier=tier)


def _leaves(tree):
    return dict(tree_flatten_with_names(tree)[0])


def _max_abs_diff(t1, t2):
    l1, l2 = _leaves(t1), _leaves(t2)
    assert l1.keys() == l2.keys()
    return max(
        float(np.max(np.abs(np.asarray(l1[k]) - np.asarray(l2[k]))))
        for k in l1
    )


# -- route parity ------------------------------------------------------------


def test_padded_route_equals_dense_route_across_ranks():
    updates = [
        _update(4, seed=0, weight=3.0, tier="phone"),
        _update(4, seed=1, weight=1.0, tier="phone"),
        _update(8, seed=2, weight=2.0, tier="edge"),
        _update(32, seed=3, weight=5.0, tier="silo"),
    ]
    dense = aggregate_dense(updates, BASE)
    padded = aggregate_padded(updates, BASE)
    assert _max_abs_diff(dense, padded) < 1e-6


def test_padded_route_honors_explicit_pad_rank():
    updates = [_update(4, seed=0), _update(8, seed=1)]
    dense = aggregate_dense(updates, BASE)
    # over-padding beyond the cohort max is wasteful but still exact
    padded = aggregate_padded(updates, BASE, pad_rank=64)
    assert _max_abs_diff(dense, padded) < 1e-6
    with pytest.raises(NanoFedError, match="smaller than the cohort"):
        aggregate_padded(updates, BASE, pad_rank=4)


def test_single_update_aggregate_is_its_own_delta():
    u = _update(8, seed=7)
    dense = aggregate_dense([u], BASE)
    assert _max_abs_diff(dense, adapter_delta(u.spec, BASE, u.adapters)) < 1e-6


def test_aggregate_rejects_empty_and_mismatched_targets():
    with pytest.raises(NanoFedError, match="empty"):
        aggregate_dense([], BASE)
    with pytest.raises(NanoFedError, match="empty"):
        aggregate_padded([], BASE)
    u1 = _update(4, seed=0)
    spec2 = AdapterSpec(rank=8, alpha=ALPHA, targets=("*dense1*",))
    u2 = AdapterUpdate(spec=spec2, adapters=init_adapters(spec2, BASE, rng=1))
    with pytest.raises(NanoFedError, match="same leaves"):
        aggregate_padded([u1, u2], BASE)


def test_zero_weight_update_rejected():
    spec = AdapterSpec(rank=4, alpha=ALPHA)
    with pytest.raises(NanoFedError, match="weight"):
        AdapterUpdate(spec=spec, adapters=init_adapters(spec, BASE), weight=0.0)


# -- padding -----------------------------------------------------------------


def test_pad_adapters_preserves_delta_exactly():
    lo = AdapterSpec(rank=4, alpha=ALPHA)
    hi = AdapterSpec(rank=32, alpha=ALPHA)
    u = _update(4, seed=5)
    padded = pad_adapters_to_rank(u.adapters, lo, hi)
    d_lo = adapter_delta(lo, BASE, u.adapters)
    d_hi = adapter_delta(hi, BASE, padded)
    assert _max_abs_diff(d_lo, d_hi) == 0.0
    # shapes actually grew to the bucket rank
    named = _leaves(padded)
    assert named["dense1/kernel/A"].shape == (48, 32)
    assert named["dense1/kernel/B"].shape == (32, 64)


def test_pad_down_is_rejected():
    lo = AdapterSpec(rank=4, alpha=ALPHA)
    hi = AdapterSpec(rank=32, alpha=ALPHA)
    with pytest.raises(NanoFedError, match="project_to_rank"):
        pad_adapters_to_rank(init_adapters(hi, BASE), hi, lo)


# -- SVD projection ----------------------------------------------------------


def test_project_full_rank_reproduces_delta():
    u = _update(8, seed=9)
    dense = adapter_delta(u.spec, BASE, u.adapters)
    # rank 32 >= true rank 8: projection is lossless
    spec32 = AdapterSpec(rank=32, alpha=ALPHA)
    tree = project_to_rank(dense, spec32, BASE)
    back = adapter_delta(spec32, BASE, tree)
    assert _max_abs_diff(dense, back) < 1e-5
    err = projection_error(dense, spec32, BASE)
    assert err["__overall__"] < 1e-6


def test_project_truncation_is_frobenius_optimal():
    u = _update(32, seed=11)
    dense = adapter_delta(u.spec, BASE, u.adapters)
    spec4 = AdapterSpec(rank=4, alpha=ALPHA)
    tree = project_to_rank(dense, spec4, BASE)
    back = adapter_delta(spec4, BASE, tree)
    named_d, named_b = _leaves(dense), _leaves(back)
    err = projection_error(dense, spec4, BASE)
    for name in named_d:
        m = np.asarray(named_d[name], np.float64)
        approx = np.asarray(named_b[name], np.float64)
        achieved = np.linalg.norm(m - approx) / np.linalg.norm(m)
        # matches the analytic SVD tail (Eckart-Young: nothing does better)
        assert achieved == pytest.approx(err[name], abs=1e-5)
        assert 0.0 < err[name] < 1.0


def test_redistribute_covers_every_tier_at_its_rank():
    prof = reference_fleet()
    u = _update(32, seed=13)
    dense = adapter_delta(u.spec, BASE, u.adapters)
    trees = redistribute(dense, prof, BASE)
    assert set(trees) == {"phone", "edge", "silo"}
    assert _leaves(trees["phone"])["dense1/kernel/A"].shape == (48, 4)
    assert _leaves(trees["silo"])["dense1/kernel/A"].shape == (48, 32)


# -- revival -----------------------------------------------------------------


def test_revive_gives_dead_directions_gradient_flow_without_moving_delta():
    spec = AdapterSpec(rank=8, alpha=ALPHA)
    # zero delta — the round-0 case: every direction dead
    dense = {
        "dense1": {"kernel": np.zeros((48, 64), np.float32)},
        "dense2": {"kernel": np.zeros((64, 32), np.float32)},
    }
    tree = project_to_rank(dense, spec, BASE)
    named = _leaves(tree)
    assert float(np.abs(named["dense1/kernel/A"]).sum()) == 0.0
    revived = revive_adapters(tree, spec, seed=3)
    rn = _leaves(revived)
    # A columns are alive now, B rows still zero, so the delta is unchanged
    assert float(np.abs(rn["dense1/kernel/A"]).sum()) > 0.0
    assert float(np.abs(rn["dense1/kernel/B"]).sum()) == 0.0
    d = adapter_delta(spec, BASE, revived)
    assert _max_abs_diff(d, dense) == 0.0
    # deterministic in the seed (replicas publish identical views)
    again = revive_adapters(tree, spec, seed=3)
    assert _max_abs_diff(revived, again) == 0.0
    other = revive_adapters(tree, spec, seed=4)
    assert _max_abs_diff(revived, other) > 0.0


def test_revive_leaves_live_directions_untouched():
    u = _update(8, seed=17)
    revived = revive_adapters(u.adapters, u.spec, seed=0)
    assert _max_abs_diff(u.adapters, revived) == 0.0
