"""Unit tests for the server-side fleet edge (``nanofed_tpu.fleet.gateway``)."""

import jax
import numpy as np
import pytest

from nanofed_tpu.communication.codec import decode_params
from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.fleet import FleetGateway, TierClientState, reference_fleet
from nanofed_tpu.utils.trees import tree_flatten_with_names

BASE = {
    "dense1": {"kernel": np.full((32, 48), 0.1, np.float32)},
    "dense2": {"kernel": np.full((48, 16), -0.2, np.float32)},
}


@pytest.fixture()
def gateway():
    return FleetGateway(reference_fleet(), BASE)


def _global_at(step):
    rng = np.random.default_rng(step)
    return jax.tree.map(
        lambda x: np.asarray(x) + rng.normal(0, 0.05, np.shape(x)).astype(np.float32),
        BASE,
    )


def test_publish_builds_a_live_view_per_tier(gateway):
    gateway.publish(0, BASE)
    for name in ("phone", "edge", "silo"):
        view = gateway.view(name)
        named = dict(tree_flatten_with_names(view.tree)[0])
        rank = gateway.spec(name).rank
        assert named["dense1/kernel/A"].shape == (32, rank)
        # round 0: zero global delta, but the view must still TRAIN — revived
        # A columns are nonzero while B stays zero (delta unchanged)
        assert float(np.abs(named["dense1/kernel/A"]).sum()) > 0.0
        assert float(np.abs(named["dense1/kernel/B"]).sum()) == 0.0
        assert float(np.abs(view.flat_dense).max()) == 0.0
        # the GET /model body is the npz of exactly this tree
        decoded = decode_params(view.payload, like=view.tree)
        dn = dict(tree_flatten_with_names(decoded)[0])
        assert np.array_equal(dn["dense1/kernel/A"], named["dense1/kernel/A"])


def test_view_windowing_matches_ingest_rule(gateway):
    gateway.publish(0, BASE, window=1)
    gateway.publish(1, _global_at(1), window=1)
    gateway.publish(2, _global_at(2), window=1)
    assert sorted(r for r in gateway._views) == [1, 2]
    gateway.view("phone", 1)  # inside the window
    with pytest.raises(NanoFedError, match="no published fleet view"):
        gateway.view("phone", 0)  # pruned
    with pytest.raises(NanoFedError, match="no published fleet view"):
        gateway.view("phone", 3)  # never published


def test_unknown_tier_raises(gateway):
    gateway.publish(0, BASE)
    with pytest.raises(NanoFedError, match="no tier"):
        gateway.spec("watch")
    with pytest.raises(NanoFedError, match="no published fleet view"):
        gateway.view("watch")


@pytest.mark.parametrize("tier_name", ["phone", "edge", "silo"])
def test_decode_submit_yields_pure_training_progress(gateway, tier_name):
    gateway.publish(3, _global_at(3))
    view = gateway.view(tier_name)
    state = TierClientState(
        gateway.profile.tier(tier_name), gateway.spec(tier_name), view.tree
    )
    rng = np.random.default_rng(7)
    trained = jax.tree.map(
        lambda x: np.asarray(x, np.float32)
        + rng.normal(0, 0.03, np.shape(x)).astype(np.float32),
        view.tree,
    )
    body = state.encode(trained, seed=0)
    row = gateway.decode_submit(tier_name, body, round_number=3)
    assert row.dtype == np.float32 and row.ndim == 1
    # the row is flat(dense(trained)) - flat(dense(view)): nonzero progress,
    # bounded by the perturbation scale (codec noise included)
    assert float(np.abs(row).max()) > 0.0
    from nanofed_tpu.adapters import adapter_delta
    from nanofed_tpu.ingest.pipeline import flatten_params

    spec = gateway.spec(tier_name)
    expect = flatten_params(adapter_delta(spec, BASE, trained)) - view.flat_dense
    tol = {"silo": 1e-6, "edge": 0.05}.get(tier_name)
    if tol is not None:  # topk8 drops its tail by design — no bound to assert
        assert float(np.abs(row - expect).max()) < tol


def test_decode_submit_no_training_is_a_zero_row(gateway):
    gateway.publish(4, _global_at(4))
    view = gateway.view("silo")
    state = TierClientState(
        gateway.profile.tier("silo"), gateway.spec("silo"), view.tree
    )
    row = gateway.decode_submit("silo", state.encode(view.tree), round_number=4)
    assert float(np.abs(row).max()) < 1e-6


def test_stats_reports_per_tier_shape(gateway):
    gateway.publish(5, _global_at(5))
    stats = gateway.stats()
    assert stats["round"] == 5 and stats["live_rounds"] == [5]
    assert stats["tiers"]["phone"] == {
        "rank": 4,
        "codec": "topk8",
        "payload_bytes": stats["tiers"]["phone"]["payload_bytes"],
    }
    assert stats["tiers"]["silo"]["payload_bytes"] > stats["tiers"]["phone"][
        "payload_bytes"
    ]
