"""Mixed-tier codec round trips + error-feedback isolation (ISSUE 16 satellite).

Three clients of three tiers ship the SAME logical object — an adapter tree —
as npz, q8 delta, and topk8 delta; every payload must land back as the tree
the client holds (to its codec's fidelity), and each client's topk8 residual
must stay ITS residual: error feedback is per-client state, and a rejected
submit on one phone must not perturb another phone's (or another tier's)
accounting.
"""

import jax
import numpy as np
import pytest

from nanofed_tpu.adapters import AdapterSpec, init_adapters
from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.fleet import (
    DeviceTier,
    TierClientState,
    decode_tier_submit,
    reference_fleet,
)
from nanofed_tpu.utils.trees import tree_flatten_with_names

BASE = {
    "dense1": {"kernel": np.zeros((32, 48), np.float32)},
    "dense2": {"kernel": np.zeros((48, 16), np.float32)},
}
PROFILE = reference_fleet()
SPECS = PROFILE.specs()


def _published(tier_name, seed=0):
    """A plausible published tier tree: identity-init A, zero B, revived."""
    return init_adapters(SPECS[tier_name], BASE, rng=seed)


def _trained(published, seed):
    rng = np.random.default_rng(seed)
    return jax.tree.map(
        lambda x: np.asarray(x, np.float32)
        + rng.normal(0, 0.05, np.shape(x)).astype(np.float32),
        published,
    )


def _max_abs_diff(t1, t2):
    l1 = dict(tree_flatten_with_names(t1)[0])
    l2 = dict(tree_flatten_with_names(t2)[0])
    return max(
        float(np.max(np.abs(np.asarray(l1[k]) - np.asarray(l2[k]))))
        for k in l1
    )


def _l2(t1, t2):
    sq = jax.tree.map(
        lambda a, b: float(
            np.sum((np.asarray(a, np.float64) - np.asarray(b, np.float64)) ** 2)
        ),
        t1, t2,
    )
    return float(np.sqrt(sum(jax.tree.leaves(sq))))


def _state(tier_name, published):
    return TierClientState(
        PROFILE.tier(tier_name), SPECS[tier_name], published
    )


# -- round trips per codec ---------------------------------------------------


def test_f32_round_trip_is_exact():
    pub = _published("silo")
    st = _state("silo", pub)
    trained = _trained(pub, seed=1)
    body = st.encode(trained)
    back = decode_tier_submit(PROFILE.tier("silo"), body, pub, pub)
    assert _max_abs_diff(trained, back) == 0.0
    st.commit()
    assert st.submits == 1 and st.bytes_sent == len(body)


def test_q8_round_trip_lands_within_quantization_noise():
    pub = _published("edge")
    st = _state("edge", pub)
    trained = _trained(pub, seed=2)
    body = st.encode(trained, seed=0)
    back = decode_tier_submit(PROFILE.tier("edge"), body, pub, pub)
    # q8 quantizes the delta to ~1/256 of its per-leaf range
    assert _max_abs_diff(trained, back) < 0.01
    # and is unbiased enough that no residual machinery engages
    st.commit()
    assert st.residual_norm() == 0.0


def test_topk8_round_trip_ships_the_top_and_banks_the_tail():
    pub = _published("phone")
    st = _state("phone", pub)
    trained = _trained(pub, seed=3)
    body = st.encode(trained, seed=0)
    back = decode_tier_submit(PROFILE.tier("phone"), body, pub, pub)
    # residual is staged, not live, until the server answers
    assert st.residual_norm() == 0.0
    st.commit()
    # the unsent tail is exactly what the decode missed
    assert st.residual_norm() == pytest.approx(_l2(trained, back), rel=1e-5)
    assert st.residual_norm() > 0.0


def test_topk8_residual_rides_the_next_submit():
    pub = _published("phone")
    st = _state("phone", pub)
    trained = _trained(pub, seed=4)
    st.encode(trained, seed=0)
    st.commit()
    tail = st.residual_norm()
    assert tail > 0.0
    # next round: the server publishes fresh, the client resumes from it with
    # zero new local progress — the submit is then a PURE residual flush
    new_pub = _published("phone", seed=50)
    st.set_base(new_pub)
    body2 = st.encode(new_pub, seed=1)
    back2 = decode_tier_submit(PROFILE.tier("phone"), body2, new_pub, new_pub)
    st.commit()
    # the residual's top coordinates crossed the wire, so the tail shrank
    assert 0.0 < st.residual_norm() < tail
    assert _l2(back2, new_pub) > 0.0


def test_unknown_codec_rejected():
    tier = DeviceTier(name="x", fraction=1.0, codec="q8")
    object.__setattr__(tier, "codec", "gzip")
    with pytest.raises(NanoFedError, match="unknown codec"):
        decode_tier_submit(tier, b"", BASE, BASE)


def test_spec_rank_must_match_tier_rank():
    with pytest.raises(NanoFedError, match="rank"):
        TierClientState(PROFILE.tier("phone"), SPECS["silo"], _published("silo"))


# -- the staged-residual contract (reject path) ------------------------------


def test_topk8_reject_folds_and_pins_so_retry_does_not_double_count():
    pub = _published("phone")
    st = _state("phone", pub)
    trained = _trained(pub, seed=5)
    st.encode(trained, seed=0)
    st.reject(trained)
    # the WHOLE un-applied delta is banked; the fold point pins at `trained`
    assert st.residual_norm() == pytest.approx(_l2(trained, pub), rel=1e-5)
    # retry with zero new training: delta vs pending base is zero, the body
    # carries residual mass only — commit drains it instead of growing it
    body = st.encode(trained, seed=1)
    back = decode_tier_submit(PROFILE.tier("phone"), body, pub, pub)
    st.commit()
    assert st.residual_norm() < _l2(trained, pub)
    assert _l2(back, pub) > 0.0


def test_set_base_resets_retry_bookkeeping_but_keeps_residual():
    pub = _published("phone")
    st = _state("phone", pub)
    trained = _trained(pub, seed=6)
    st.encode(trained, seed=0)
    st.reject(trained)
    banked = st.residual_norm()
    assert banked > 0.0
    new_pub = _published("phone", seed=99)
    st.set_base(new_pub)
    assert st.base is new_pub
    assert st._pending_base is None and st._staged_residual is None
    assert st.residual_norm() == banked  # the tail still rides the next delta


# -- isolation (the satellite's core assertion) ------------------------------


def test_residuals_are_isolated_between_clients_and_tiers():
    pub_phone = _published("phone")
    pub_edge = _published("edge")
    phone_a = _state("phone", pub_phone)
    phone_b = _state("phone", pub_phone)
    edge = _state("edge", pub_edge)

    # phone_a suffers a reject; phone_b and edge complete clean rounds
    t_a = _trained(pub_phone, seed=7)
    phone_a.encode(t_a, seed=0)
    phone_a.reject(t_a)

    t_b = _trained(pub_phone, seed=8)
    phone_b.encode(t_b, seed=0)
    phone_b.commit()
    b_tail = phone_b.residual_norm()

    edge.encode(_trained(pub_edge, seed=9), seed=0)
    edge.commit()

    # a's banked mass is a's alone; b's tail is the normal topk8 tail; the q8
    # tier never grows a residual at all
    assert phone_a.residual_norm() == pytest.approx(_l2(t_a, pub_phone), rel=1e-5)
    assert 0.0 < b_tail < phone_a.residual_norm()
    assert edge.residual_norm() == 0.0

    # and a's retry/commit cycle moves nobody else's state
    phone_a.encode(t_a, seed=1)
    phone_a.commit()
    assert phone_b.residual_norm() == b_tail
    assert edge.residual_norm() == 0.0
