"""Unit tests for the analytic fleet-mix sweep (``nanofed_tpu.fleet.tuning``)."""

import numpy as np
import pytest

from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.fleet import (
    FleetMixCandidate,
    mix_candidates,
    profile_with_ranks,
    reference_fleet,
    sweep_fleet_mix,
)

BASE = {
    "dense1": {"kernel": np.zeros((64, 64), np.float32)},
    "dense2": {"kernel": np.zeros((64, 32), np.float32)},
}


def test_mix_candidates_cross_the_per_tier_ladders():
    prof = reference_fleet()  # ranks 4 / 8 / 32, each a 3-point ladder
    cands = mix_candidates(prof)
    assert len(cands) == 27
    # the profile's own ranks are one of the candidates
    assert FleetMixCandidate(
        ranks=(("phone", 4), ("edge", 8), ("silo", 32))
    ) in cands
    for c in cands:
        assert c.rank_for("phone") in (2, 4, 8)
        assert c.rank_for("silo") in (16, 32, 64)
    with pytest.raises(NanoFedError, match="no tier"):
        cands[0].rank_for("watch")


def test_profile_with_ranks_moves_only_ranks():
    prof = reference_fleet()
    cand = FleetMixCandidate(ranks=(("phone", 8), ("edge", 4), ("silo", 16)))
    p2 = profile_with_ranks(prof, cand)
    assert [t.adapter_rank for t in p2.tiers] == [8, 4, 16]
    assert [t.codec for t in p2.tiers] == [t.codec for t in prof.tiers]
    assert [t.fraction for t in p2.tiers] == [t.fraction for t in prof.tiers]
    assert p2.name == prof.name


def test_sweep_is_deterministic_and_scores_feasible_first():
    prof = reference_fleet()
    a = sweep_fleet_mix(prof, BASE, num_clients=100)
    b = sweep_fleet_mix(prof, BASE, num_clients=100)
    assert [o.candidate for o in a] == [o.candidate for o in b]
    assert all(o.feasible for o in a)
    scores = [o.score for o in a]
    assert scores == sorted(scores)
    # score is exactly bytes per unit of availability-weighted rank
    top = a[0]
    assert top.score == pytest.approx(
        top.wire_bytes_per_round / top.capacity
    )


def test_sweep_hbm_budget_rejects_with_a_reason():
    prof = reference_fleet()
    unbounded = sweep_fleet_mix(prof, BASE, num_clients=100)
    need = max(o.hbm_resident_bytes + o.hbm_peak_bytes for o in unbounded)
    # a budget below the smallest candidate's need rejects everything
    all_out = sweep_fleet_mix(prof, BASE, num_clients=100, hbm_budget_bytes=1)
    assert all(not o.feasible for o in all_out)
    assert all("hbm" in o.reject_reason for o in all_out)
    assert all(o.score is None for o in all_out)
    # a budget at the max need admits everything again
    all_in = sweep_fleet_mix(
        prof, BASE, num_clients=100, hbm_budget_bytes=need
    )
    assert all(o.feasible for o in all_in)


def test_sweep_step_cost_annotation_uses_the_max_rank():
    prof = reference_fleet()
    costs = {16: 0.1, 32: 0.2, 64: 0.4}
    outs = sweep_fleet_mix(prof, BASE, num_clients=100, step_costs=costs)
    for o in outs:
        max_rank = max(r for _, r in o.candidate.ranks)
        assert o.step_cost_s == costs.get(max_rank)


def test_outcome_to_dict_round_trips_the_headline_fields():
    outs = sweep_fleet_mix(reference_fleet(), BASE, num_clients=50)
    d = outs[0].to_dict()
    assert set(d) >= {
        "ranks", "feasible", "wire_bytes_per_round", "capacity",
        "hbm_resident_bytes", "hbm_peak_bytes", "score",
    }
    assert d["feasible"] is True
