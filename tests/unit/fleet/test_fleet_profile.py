"""Unit tests for fleet profiles (``nanofed_tpu.fleet.profile``)."""

import numpy as np
import pytest

from nanofed_tpu.core.exceptions import NanoFedError
from nanofed_tpu.fleet import DeviceTier, FleetProfile, reference_fleet

BASE = {
    "dense1": {"kernel": np.zeros((64, 64), np.float32)},
    "dense2": {"kernel": np.zeros((64, 32), np.float32)},
}


# -- DeviceTier validation ---------------------------------------------------


@pytest.mark.parametrize(
    "kwargs,match",
    [
        (dict(name="", fraction=1.0), "non-empty"),
        (dict(name="a/b", fraction=1.0), "non-empty"),
        (dict(name="t", fraction=0.0), "fraction"),
        (dict(name="t", fraction=1.5), "fraction"),
        (dict(name="t", fraction=1.0, adapter_rank=0), "adapter_rank"),
        (dict(name="t", fraction=1.0, codec="zstd"), "unknown codec"),
        (dict(name="t", fraction=1.0, batch_size=0), "batch_size"),
        (dict(name="t", fraction=1.0, arrival="fibonacci"), "arrival"),
        (dict(name="t", fraction=1.0, arrival_rate=0.0), "arrival_rate"),
        (dict(name="t", fraction=1.0, availability=0.0), "availability"),
        (dict(name="t", fraction=1.0, local_steps=0), "local_steps"),
        (dict(name="t", fraction=1.0, topk_fraction=0.0), "topk_fraction"),
    ],
)
def test_tier_validation(kwargs, match):
    with pytest.raises(NanoFedError, match=match):
        DeviceTier(**kwargs)


def test_tier_encoding_maps_codec_to_wire_value():
    assert DeviceTier(name="t", fraction=1.0, codec="f32").encoding == "npz"
    assert DeviceTier(name="t", fraction=1.0, codec="q8").encoding == "q8-delta"
    assert (
        DeviceTier(name="t", fraction=1.0, codec="topk8").encoding
        == "topk8-delta"
    )


# -- FleetProfile validation -------------------------------------------------


def test_profile_fractions_must_sum_to_one():
    with pytest.raises(NanoFedError, match="sum to"):
        FleetProfile(
            name="p",
            tiers=(
                DeviceTier(name="a", fraction=0.5),
                DeviceTier(name="b", fraction=0.4),
            ),
        )


def test_profile_rejects_duplicate_tier_names():
    with pytest.raises(NanoFedError, match="duplicate"):
        FleetProfile(
            name="p",
            tiers=(
                DeviceTier(name="a", fraction=0.5),
                DeviceTier(name="a", fraction=0.5),
            ),
        )


def test_profile_needs_at_least_one_tier():
    with pytest.raises(NanoFedError, match="at least one"):
        FleetProfile(name="p", tiers=())


def test_tier_lookup_and_max_rank():
    prof = reference_fleet()
    assert prof.tier("silo").adapter_rank == 32
    assert prof.max_rank == 32
    assert prof.max_rank_tier.name == "silo"
    with pytest.raises(NanoFedError, match="no tier"):
        prof.tier("watch")


# -- population_split --------------------------------------------------------


def test_population_split_is_exact_and_deterministic():
    prof = reference_fleet()
    for n in (3, 10, 97, 100, 1000):
        split = prof.population_split(n)
        assert sum(split.values()) == n
        assert all(v >= 1 for v in split.values())
        assert split == prof.population_split(n)  # deterministic
    # the dominant tier dominates
    split = prof.population_split(100)
    assert split["phone"] > split["edge"] > split["silo"]


def test_population_split_guarantees_min_one_even_for_thin_tiers():
    # silo is 5%: at n=3 the floor split would starve it to zero.
    split = reference_fleet().population_split(3)
    assert split == {"phone": 1, "edge": 1, "silo": 1}


def test_population_split_rejects_population_below_tier_count():
    with pytest.raises(NanoFedError, match="smaller than the tier count"):
        reference_fleet().population_split(2)


# -- specs / wire sizing -----------------------------------------------------


def test_specs_share_the_max_rank_alpha():
    specs = reference_fleet().specs()
    assert {s.alpha for s in specs.values()} == {32.0}
    assert specs["phone"].rank == 4 and specs["silo"].rank == 32
    # common alpha => scaling ratio is a pure rank ratio (the padding rescale)
    assert specs["phone"].scaling / specs["silo"].scaling == pytest.approx(8.0)


def test_wire_bytes_per_round_orders_codecs_sanely():
    out = reference_fleet().wire_bytes_per_round(BASE, 100)
    # per-UPDATE bytes: f32 at rank 32 must dwarf topk8 at rank 4
    assert out["silo"]["bytes_per_update"] > 20 * out["phone"]["bytes_per_update"]
    assert out["total_bytes_per_round"] == sum(
        out[t]["bytes_per_round"] for t in ("phone", "edge", "silo")
    )
    assert "analytic" in out["basis"]


def test_profile_dict_round_trip():
    prof = reference_fleet()
    clone = FleetProfile.from_dict(prof.to_dict())
    assert clone == prof
