"""parallel.resilience: the mesh tier's fault model.

The acceptance-bar test lives here: a ``host_stall`` — a peer that hangs
rather than crashes — is detected within the configured watchdog deadline, on
the VIRTUAL clock, by a dispatch that would otherwise hang FOREVER (the gloo
cross-host psum has no deadline of its own).  Plus: heartbeat/monitor
semantics (sequence-number freshness on the monitor's own clock, no
cross-host wall-clock comparison), sync watchdog bracketing with the
keep-alive tick, and the typed-failure/recoverability contract.
"""

import asyncio
import threading
import time

import pytest

from nanofed_tpu.parallel.resilience import (
    CollectiveWatchdog,
    Heartbeat,
    HostFailure,
    HostMonitor,
    no_orphans,
)
from nanofed_tpu.observability.registry import MetricsRegistry
from nanofed_tpu.persistence import is_recoverable
from nanofed_tpu.utils.clock import VirtualClock


def test_host_failure_is_typed_and_recoverable():
    exc = HostFailure("host_stall", host=2, round_number=7, detail="frozen")
    assert exc.kind == "host_stall" and exc.host == 2
    assert "host 2" in str(exc) and "round 7" in str(exc)
    # The recovery contract: a host loss retries like a server crash —
    # NanoFedError config bugs do not, HostFailure must.
    assert is_recoverable(exc)


# ---------------------------------------------------------------------------
# Heartbeat + HostMonitor
# ---------------------------------------------------------------------------


def test_stall_detection_rides_the_monitors_clock(tmp_path):
    clock = VirtualClock()
    reg = MetricsRegistry()
    hb0 = Heartbeat(tmp_path, 0)
    hb1 = Heartbeat(tmp_path, 1)
    monitor = HostMonitor(tmp_path, stall_timeout_s=10, clock=clock,
                          registry=reg)
    hb0.beat(round_number=0)
    hb1.beat(round_number=0)
    assert monitor.stalled() == []
    clock.advance(8)
    hb0.beat(round_number=1)  # host 0 advances; host 1 freezes
    assert monitor.stalled() == []  # 8s < timeout for host 1
    clock.advance(4)  # host 1 now 12s frozen, host 0 only 4s
    failures = monitor.stalled()
    assert [f.host for f in failures] == [1]
    assert failures[0].kind == "host_stall"
    # Flagged once, counted once — until recovery clears the verdict.
    assert monitor.stalled() == []
    counter = reg.counter("nanofed_host_failures_total", "", labels=("kind",))
    assert counter.value(kind="host_stall") == 1
    monitor.clear(1)
    hb1.beat(round_number=1)
    assert monitor.stalled() == []


def test_monitor_skips_torn_heartbeat_files(tmp_path):
    clock = VirtualClock()
    Heartbeat(tmp_path, 0).beat(round_number=3, generation=1)
    (tmp_path / "host_9.hb.json").write_text("{torn")
    monitor = HostMonitor(tmp_path, stall_timeout_s=5, clock=clock,
                          registry=MetricsRegistry())
    states = monitor.poll()
    assert list(states) == [0]
    assert states[0].round_number == 3 and states[0].generation == 1


def test_heartbeat_seq_increases_and_publishes_atomically(tmp_path):
    hb = Heartbeat(tmp_path, 4)
    hb.beat(round_number=0)
    hb.beat(round_number=1, status="committed")
    monitor = HostMonitor(tmp_path, stall_timeout_s=5, clock=VirtualClock(),
                          registry=MetricsRegistry())
    state = monitor.poll()[4]
    assert state.seq == 2 and state.status == "committed"
    assert not list(tmp_path.glob("*.tmp"))  # tmp never left behind


# ---------------------------------------------------------------------------
# CollectiveWatchdog — THE acceptance test: a stalled peer's hang is bounded
# ---------------------------------------------------------------------------


def test_stalled_peer_detected_within_deadline_on_virtual_clock():
    """Without the watchdog this dispatch hangs FOREVER (the stalled peer
    never arrives at the collective; awaiting it = awaiting a sleep to the
    end of time).  With it, the hang surfaces as a typed HostFailure at
    exactly the deadline — in virtual time, i.e. milliseconds of real time —
    and a recovery dispatch on the surviving mesh then succeeds."""
    clock = VirtualClock()
    watchdog = CollectiveWatchdog(30.0, clock=clock, registry=MetricsRegistry())

    async def dispatch_with_stalled_peer():
        await clock.sleep(10**9)  # the peer never shows up

    async def dispatch_on_survivors():
        await clock.sleep(1.0)
        return "round-result"

    async def main():
        t0 = clock.time()
        with pytest.raises(HostFailure) as err:
            await watchdog.guard(dispatch_with_stalled_peer(), round_number=5)
        assert err.value.kind == "collective_timeout"
        assert err.value.round_number == 5
        # Bounded detection: the failure fired AT the deadline, not at the
        # stalled peer's sleep horizon.
        assert clock.time() - t0 == pytest.approx(30.0)
        # The mesh re-forms and the next dispatch completes.
        assert await watchdog.guard(dispatch_on_survivors()) == "round-result"

    asyncio.run(main())


def test_guard_passes_results_and_dcn_grace():
    clock = VirtualClock()
    watchdog = CollectiveWatchdog(2.0, clock=clock, registry=MetricsRegistry())

    async def degraded_dispatch():
        await clock.sleep(2.5)  # over the base deadline, within the grace
        return 7

    async def main():
        return await watchdog.guard(degraded_dispatch(), dcn_grace_s=1.0)

    assert asyncio.run(main()) == 7


def test_sync_run_times_out_and_keeps_ticking():
    ticks = []
    release = threading.Event()
    watchdog = CollectiveWatchdog(0.4, registry=MetricsRegistry())
    with pytest.raises(HostFailure) as err:
        watchdog.run(
            lambda: release.wait(10), round_number=2,
            tick=lambda: ticks.append(time.monotonic()), tick_interval_s=0.1,
        )
    assert err.value.kind == "collective_timeout"
    # The keep-alive tick fired while blocked: a host WAITING on a collective
    # must keep heartbeating or the monitor misreads it as the stalled one.
    assert len(ticks) >= 2
    release.set()  # let the abandoned thread exit


def test_sync_run_propagates_dispatch_errors_unchanged():
    watchdog = CollectiveWatchdog(5.0, registry=MetricsRegistry())

    def exploding():
        raise ValueError("gloo says no")

    with pytest.raises(ValueError, match="gloo says no"):
        watchdog.run(exploding)
    assert watchdog.run(lambda: 3) == 3


def test_no_orphans_probe(tmp_path):
    import os
    import subprocess
    import sys

    assert no_orphans([]) == []
    p = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(30)"])
    try:
        assert no_orphans([p.pid]) == [p.pid]
    finally:
        p.kill()
        p.wait()
    assert no_orphans([p.pid]) == []
    assert os.getpid() in no_orphans([os.getpid()])
