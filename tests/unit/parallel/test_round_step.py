"""SPMD round-step tests on the 8-device CPU mesh — the "fake backend" replacing the
reference's mocked-aiohttp transport tests (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np

from nanofed_tpu.aggregation import compute_weights, fedavg_strategy, fedavgm_strategy
from nanofed_tpu.core.types import ClientData
from nanofed_tpu.data import federate, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.parallel import (
    build_round_step,
    init_server_state,
    make_mesh,
    pad_client_count,
    pad_clients,
    shard_client_data,
)
from nanofed_tpu.trainer import TrainingConfig, make_local_fit, stack_rngs
from nanofed_tpu.utils.trees import tree_weighted_mean


def _setup(num_clients=8, batch=16, n=512, classes=4, feat=8, seed=0):
    m = get_model("mlp", in_features=feat, hidden=16, num_classes=classes)
    ds = synthetic_classification(n, classes, (feat,), seed=seed)
    cd = federate(ds, num_clients=num_clients, scheme="iid", batch_size=batch, seed=seed)
    mesh = make_mesh()
    return m, cd, mesh


def test_round_step_matches_vmap_plus_host_mean(devices):
    """SPMD result == (vmap local_fit, host weighted mean): the mesh reduction is exact.

    Single-batch clients (batch_size == per-client capacity) on purpose: some jaxlib
    CPU backends (observed on 0.4.36) lower the epoch-shuffle PRNG inside
    ``jit(shard_map(...))`` to a DIFFERENT (still valid, still deterministic)
    permutation than the same key draws in a plain ``jit(vmap(...))`` — an upstream
    fused-lowering context dependence, not a framework bug.  With one batch per
    client the shuffle only permutes within the batch, whose sum-reductions are
    permutation-invariant, so this test pins what it is about — the mesh gather /
    weighting / psum reduction — exactly on every backend."""
    m, cd, mesh = _setup(batch=64)
    cfg = TrainingConfig(batch_size=64, local_epochs=1)
    params = m.init(jax.random.key(0))
    strat = fedavg_strategy()
    step = build_round_step(m.apply, cfg, mesh, strat)
    sos = init_server_state(strat, params)
    weights = compute_weights(jnp.asarray(cd.num_samples))
    rngs = stack_rngs(jax.random.key(7), 8)

    sharded = shard_client_data(cd, mesh)
    res = step(params, sos, sharded, weights, rngs)

    # Reference computation: plain vmap (no mesh) + host weighted mean.
    fit = make_local_fit(m.apply, cfg)
    cd_host = jax.tree.map(jnp.asarray, cd)
    host = jax.vmap(fit, in_axes=(None, 0, 0))(params, cd_host, rngs)
    expected = tree_weighted_mean(host.params, weights)

    for got, want in zip(jax.tree.leaves(res.params), jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    # Per-client metrics come back in client order.
    np.testing.assert_allclose(
        np.asarray(res.client_metrics.loss), np.asarray(host.metrics.loss), rtol=2e-4
    )


def test_zero_weight_round_is_identity(devices):
    """All clients masked out => FAILED-round semantics: params and state unchanged."""
    m, cd, mesh = _setup()
    cfg = TrainingConfig(batch_size=16)
    params = m.init(jax.random.key(0))
    strat = fedavgm_strategy()  # stateful server opt: state must also stay unchanged
    step = build_round_step(m.apply, cfg, mesh, strat)
    sos = init_server_state(strat, params)
    res = step(params, sos, shard_client_data(cd, mesh), jnp.zeros(8), stack_rngs(jax.random.key(0), 8))
    for got, want in zip(jax.tree.leaves(res.params), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    for got, want in zip(jax.tree.leaves(res.server_opt_state), jax.tree.leaves(sos)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert int(res.metrics["participating_clients"]) == 0


def test_partial_participation_masks_clients(devices):
    """Zero-weight clients must not influence the aggregate.

    Single-batch clients for the same reason as
    ``test_round_step_matches_vmap_plus_host_mean``: the comparison crosses program
    structures (shard_map vs plain vmap), and the multi-batch epoch shuffle is not
    bit-stable across those on every jaxlib CPU backend."""
    m, cd, mesh = _setup(batch=64)
    cfg = TrainingConfig(batch_size=64)
    params = m.init(jax.random.key(0))
    strat = fedavg_strategy()
    step = build_round_step(m.apply, cfg, mesh, strat)
    sos = init_server_state(strat, params)
    rngs = stack_rngs(jax.random.key(3), 8)
    sharded = shard_client_data(cd, mesh)

    ns = jnp.asarray(cd.num_samples)
    mask = jnp.asarray([1.0, 1.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0])
    res_masked = step(params, sos, sharded, compute_weights(ns, mask), rngs)

    # Same two clients alone on a fresh 2-client setup would give the same params:
    fit = make_local_fit(m.apply, cfg)
    cd_host = jax.tree.map(jnp.asarray, cd)
    host = jax.vmap(fit, in_axes=(None, 0, 0))(params, cd_host, rngs)
    w2 = compute_weights(ns, mask)
    expected = tree_weighted_mean(host.params, w2)
    for got, want in zip(jax.tree.leaves(res_masked.params), jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    assert int(res_masked.metrics["participating_clients"]) == 2


def test_client_padding_roundtrip(devices):
    """10 clients on 8 devices: pad to 16, dummies carry zero weight."""
    m = get_model("mlp", in_features=8, hidden=16, num_classes=4)
    ds = synthetic_classification(400, 4, (8,), seed=1)
    cd = federate(ds, num_clients=10, scheme="iid", batch_size=16, seed=1)
    mesh = make_mesh()
    padded_c = pad_client_count(10, 8)
    assert padded_c == 16
    padded = pad_clients(cd, padded_c)
    assert padded.x.shape[0] == 16
    np.testing.assert_array_equal(np.asarray(padded.mask[10:]).sum(), 0.0)

    cfg = TrainingConfig(batch_size=16)
    params = m.init(jax.random.key(0))
    strat = fedavg_strategy()
    step = build_round_step(m.apply, cfg, mesh, strat)
    sos = init_server_state(strat, params)
    weights = compute_weights(jnp.asarray(padded.num_samples)) * (
        jnp.asarray(padded.num_samples) > 0
    )
    res = step(
        params, sos, shard_client_data(padded, mesh), weights, stack_rngs(jax.random.key(0), 16)
    )
    assert int(res.metrics["participating_clients"]) == 10
    assert np.isfinite(np.asarray(res.metrics["loss"]))


def test_multi_round_training_learns(devices):
    m, cd, mesh = _setup(n=1024, batch=32)
    cfg = TrainingConfig(batch_size=32, local_epochs=2)
    params = m.init(jax.random.key(0))
    strat = fedavg_strategy()
    step = build_round_step(m.apply, cfg, mesh, strat)
    sos = init_server_state(strat, params)
    weights = compute_weights(jnp.asarray(cd.num_samples))
    sharded = shard_client_data(cd, mesh)
    losses = []
    for r in range(4):
        res = step(params, sos, sharded, weights, stack_rngs(jax.random.key(r), 8))
        params, sos = res.params, res.server_opt_state
        losses.append(float(res.metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7
