"""In-mesh validation: a poisoned client must be dropped from the reduce with weight 0,
leaving the aggregate identical to a round without that client."""

import jax
import jax.numpy as jnp
import numpy as np

from nanofed_tpu.aggregation import compute_weights, fedavg_strategy
from nanofed_tpu.core.types import ClientData
from nanofed_tpu.models import get_model
from nanofed_tpu.parallel import (
    build_round_step,
    init_server_state,
    make_mesh,
    shard_client_data,
)
from nanofed_tpu.security import ValidationConfig
from nanofed_tpu.trainer import TrainingConfig, stack_rngs


def _make_setup(devices, local_fit):
    mesh = make_mesh(devices)
    model = get_model("linear", in_features=4, num_classes=3)
    c, n = 8, 16
    rng = np.random.default_rng(0)
    data = ClientData(
        x=jnp.asarray(rng.normal(size=(c, n, 4)), jnp.float32),
        y=jnp.asarray(rng.integers(0, 3, size=(c, n))),
        mask=jnp.ones((c, n), jnp.float32),
    )
    data = shard_client_data(data, mesh)
    training = TrainingConfig(batch_size=8, local_epochs=1, learning_rate=0.1)
    strategy = fedavg_strategy()
    step = build_round_step(
        model.apply, training, mesh, strategy, local_fit=local_fit,
        validation=ValidationConfig(max_norm=100.0, min_clients_for_stats=100),
    )
    params = model.init(jax.random.key(0))
    return mesh, model, data, strategy, step, params


def test_nan_client_dropped(devices):
    from nanofed_tpu.trainer.local import make_local_fit

    model = get_model("linear", in_features=4, num_classes=3)
    training = TrainingConfig(batch_size=8, local_epochs=1, learning_rate=0.1)
    base = make_local_fit(model.apply, training)

    def nan_fit(gp, data, rng):
        res = base(gp, data, rng)
        # Poison via a data sentinel: a diverged client produces NaN params AND NaN
        # metrics, so both the param reduce and the metric reduce must survive it.
        poisoned = data.x[0, 0] > 1e5
        params = jax.tree.map(
            lambda p: jnp.where(poisoned, jnp.nan, p), res.params
        )
        metrics = jax.tree.map(
            lambda m: jnp.where(poisoned, jnp.nan, m), res.metrics
        )
        return res._replace(params=params, metrics=metrics)

    mesh, model, data, strategy, step, params = _make_setup(devices, nan_fit)
    sos = init_server_state(strategy, params)
    rngs = stack_rngs(jax.random.key(0), 8)
    weights = compute_weights(data.num_samples)

    # Clean run (no poisoning sentinel present).
    clean = step(params, sos, data, weights, rngs)
    assert not any(
        np.isnan(np.asarray(x)).any() for x in jax.tree.leaves(clean.params)
    )
    assert int(clean.metrics["valid_clients"]) == 8

    # Poison client 3 via the sentinel: it must be excluded, result stays finite.
    x = np.array(data.x)
    x[3, 0] = 1e6
    data_p = data._replace(x=jax.device_put(jnp.asarray(x), data.x.sharding))
    poisoned = step(params, sos, data_p, weights, rngs)
    assert not any(
        np.isnan(np.asarray(p)).any() for p in jax.tree.leaves(poisoned.params)
    )
    assert int(poisoned.metrics["valid_clients"]) == 7
    # The rejected client is visible: participation counts the pre-validation cohort.
    assert int(poisoned.metrics["participating_clients"]) == 8
    # Round-level metrics stay finite even though the dropped client reported NaN loss.
    assert np.isfinite(float(poisoned.metrics["loss"]))
    assert np.isfinite(float(poisoned.metrics["accuracy"]))


def test_nan_majority_does_not_skew_cohort_stats(devices):
    """Clients that failed finiteness must be excluded from the z-score cohort: with 4 of
    8 clients NaN-poisoned, the honest half must all remain valid."""
    from nanofed_tpu.trainer.local import make_local_fit

    model = get_model("linear", in_features=4, num_classes=3)
    training = TrainingConfig(batch_size=8, local_epochs=1, learning_rate=0.1)
    base = make_local_fit(model.apply, training)

    def nan_fit(gp, data, rng):
        res = base(gp, data, rng)
        poisoned = data.x[0, 0] > 1e5
        return res._replace(
            params=jax.tree.map(lambda p: jnp.where(poisoned, jnp.nan, p), res.params)
        )

    mesh = make_mesh(devices)
    c, n = 8, 16
    rng = np.random.default_rng(0)
    x = rng.normal(size=(c, n, 4)).astype(np.float32)
    for i in (0, 2, 4, 6):
        x[i, 0] = 1e6
    data = shard_client_data(
        ClientData(
            x=jnp.asarray(x),
            y=jnp.asarray(rng.integers(0, 3, size=(c, n))),
            mask=jnp.ones((c, n), jnp.float32),
        ),
        mesh,
    )
    step = build_round_step(
        model.apply, training, mesh, fedavg_strategy(), local_fit=nan_fit,
        # High z threshold: this test isolates NaN-exclusion from cohort stats; the
        # tightly-clustered honest norms would make any LOO z-score sensitive.
        validation=ValidationConfig(
            max_norm=100.0, min_clients_for_stats=3, z_score_threshold=10.0
        ),
    )
    params = model.init(jax.random.key(0))
    sos = init_server_state(fedavg_strategy(), params)
    result = step(
        params, sos, data, compute_weights(data.num_samples), stack_rngs(jax.random.key(0), c)
    )
    assert int(result.metrics["valid_clients"]) == 4
    assert not any(np.isnan(np.asarray(p)).any() for p in jax.tree.leaves(result.params))


def test_zscore_outlier_dropped(devices):
    from nanofed_tpu.trainer.local import make_local_fit

    model = get_model("linear", in_features=4, num_classes=3)
    training = TrainingConfig(batch_size=8, local_epochs=1, learning_rate=0.1)
    base = make_local_fit(model.apply, training)

    def scaling_fit(gp, data, rng):
        res = base(gp, data, rng)
        # Sentinel-marked client returns a 1000x-scaled delta (model poisoning).
        factor = jnp.where(data.x[0, 0] > 1e5, 1000.0, 1.0)
        params = jax.tree.map(
            lambda p, g: g + factor * (p - g), res.params, gp
        )
        return res._replace(params=params)

    mesh = make_mesh(devices)
    c, n = 8, 16
    rng = np.random.default_rng(0)
    x = rng.normal(size=(c, n, 4)).astype(np.float32)
    x[5, 0] = 1e6  # client 5 is the attacker
    data = shard_client_data(
        ClientData(
            x=jnp.asarray(x),
            y=jnp.asarray(rng.integers(0, 3, size=(c, n))),
            mask=jnp.ones((c, n), jnp.float32),
        ),
        mesh,
    )
    step = build_round_step(
        model.apply,
        training,
        mesh,
        fedavg_strategy(),
        local_fit=scaling_fit,
        validation=ValidationConfig(
            max_norm=1e9, min_clients_for_stats=5, z_score_threshold=2.0
        ),
    )
    params = model.init(jax.random.key(0))
    sos = init_server_state(fedavg_strategy(), params)
    result = step(params, sos, data, compute_weights(data.num_samples), stack_rngs(jax.random.key(0), c))
    assert int(result.metrics["valid_clients"]) == 7
    assert int(result.metrics["participating_clients"]) == 8
    # The update applied must be small — the 1000x delta was excluded.
    delta_norm = float(
        jnp.sqrt(
            sum(
                jnp.sum(jnp.square(a - b))
                for a, b in zip(jax.tree.leaves(result.params), jax.tree.leaves(params))
            )
        )
    )
    assert delta_norm < 10.0
