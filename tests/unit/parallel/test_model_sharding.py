"""FSDP-style model sharding on the 2-D ``clients x model`` mesh.

Every comparison here crosses program structures (1-D vs 2-D round programs),
so clients are SINGLE-BATCH (batch_size == per-client capacity) and the model
is dropout-free — the documented jaxlib-CPU caveat from
``test_round_step.py``: only the epoch-shuffle/dropout PRNG lowering differs
across program structures, never the mesh math this file pins.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from nanofed_tpu.aggregation import compute_weights, fedadam_strategy, fedavg_strategy
from nanofed_tpu.data import federate, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.parallel import (
    MODEL_AXIS,
    build_round_block,
    build_round_step,
    build_scaffold_round_step,
    init_server_state,
    make_mesh,
    shard_client_data,
    shard_params,
    stack_round_keys,
)
from nanofed_tpu.trainer import TrainingConfig, stack_rngs, stack_zero_controls, zero_controls
from nanofed_tpu.parallel.mesh import client_sharding


def _setup(num_clients=8, batch=64, classes=4, feat=8, seed=0):
    m = get_model("mlp", in_features=feat, hidden=16, num_classes=classes)
    ds = synthetic_classification(num_clients * batch, classes, (feat,), seed=seed)
    cd = federate(ds, num_clients=num_clients, scheme="iid", batch_size=batch, seed=seed)
    return m, cd


def _run_round(mesh_shape, strategy, m, cd, rounds=2):
    mesh = make_mesh(shape=mesh_shape)
    cfg = TrainingConfig(batch_size=64, local_epochs=1)
    params = m.init(jax.random.key(0))
    step = build_round_step(m.apply, cfg, mesh, strategy, params_like=params)
    p = shard_params(params, mesh)
    sos = shard_params(init_server_state(strategy, params), mesh)
    data = shard_client_data(cd, mesh)
    weights = compute_weights(jnp.asarray(cd.num_samples))
    res = None
    for r in range(rounds):
        res = step(p, sos, data, weights, stack_rngs(jax.random.key(r), 8))
        p, sos = res.params, res.server_opt_state
    return res


def test_2d_round_step_matches_1d(devices):
    """The acceptance property: a (4, 2) clients x model round step produces
    params within numerical tolerance of the 1-D run, and the params are
    VERIFIABLY model-sharded between rounds (asserted via .sharding, not
    shape)."""
    m, cd = _setup()
    strat = fedavg_strategy()
    res_1d = _run_round(None, strat, m, cd)
    res_2d = _run_round((4, 2), strat, m, cd)
    for got, want in zip(jax.tree.leaves(res_2d.params), jax.tree.leaves(res_1d.params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    specs = {
        jax.tree_util.keystr(path): leaf.sharding.spec
        for path, leaf in jax.tree_util.tree_flatten_with_path(res_2d.params)[0]
    }
    # Every MLP leaf has an even dim -> every leaf is genuinely sharded.
    assert specs["['fc1']['kernel']"] == P(None, MODEL_AXIS)
    assert specs["['fc1']['bias']"] == P(MODEL_AXIS)
    assert specs["['fc2']['kernel']"] == P(MODEL_AXIS)
    assert all(
        not leaf.sharding.is_fully_replicated
        for leaf in jax.tree.leaves(res_2d.params)
    )
    np.testing.assert_allclose(
        float(res_2d.metrics["loss"]), float(res_1d.metrics["loss"]), rtol=1e-5
    )


def test_2d_opt_state_is_model_sharded(devices):
    """A stateful server optimizer (FedAdam): its params-shaped slots live
    model-sharded too — the memory the model axis buys is params AND opt
    state."""
    m, cd = _setup()
    strat = fedadam_strategy()
    res_1d = _run_round(None, strat, m, cd)
    res_2d = _run_round((4, 2), strat, m, cd)
    for got, want in zip(jax.tree.leaves(res_2d.params), jax.tree.leaves(res_1d.params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    sharded = [
        leaf for leaf in jax.tree.leaves(res_2d.server_opt_state)
        if hasattr(leaf, "sharding") and not leaf.sharding.is_fully_replicated
    ]
    assert sharded, "no FedAdam slot came back model-sharded"


def test_2d_round_block_matches_single_rounds(devices):
    """The fused R-round block on a (4, 2) mesh: same params as R single 2-D
    rounds, carry model-sharded at the block boundary."""
    m, cd = _setup()
    strat = fedavg_strategy()
    mesh = make_mesh(shape=(4, 2))
    cfg = TrainingConfig(batch_size=64, local_epochs=1)
    params = m.init(jax.random.key(0))
    step = build_round_step(m.apply, cfg, mesh, strat, params_like=params)
    block = build_round_block(
        m.apply, cfg, mesh, strat, num_clients=8, padded_clients=8,
        params_like=params,
    )
    p0 = shard_params(params, mesh)
    sos0 = shard_params(init_server_state(strat, params), mesh)
    data = shard_client_data(cd, mesh)
    num_samples = jnp.asarray(cd.num_samples, dtype=jnp.float32)
    weights = compute_weights(num_samples)
    seed = 3

    p, sos = p0, sos0
    for r in range(3):
        base = jax.random.fold_in(jax.random.key(seed), r)
        res = step(p, sos, data, weights, stack_rngs(base, 8))
        p, sos = res.params, res.server_opt_state

    mask = jnp.ones((3, 8), jnp.float32)
    bres = block(
        p0, sos0, data, num_samples, stack_round_keys(seed, [0, 1, 2]),
        jnp.ones(3, jnp.float32), cohort_mask=mask,
    )
    for got, want in zip(jax.tree.leaves(bres.params), jax.tree.leaves(p)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    assert all(
        not leaf.sharding.is_fully_replicated
        for leaf in jax.tree.leaves(bres.params)
    )


def test_2d_scaffold_step_matches_1d(devices):
    """SCAFFOLD on the 2-D mesh: params, opt state, and the server control all
    model-sharded; math matches the 1-D control-variate round."""
    m, cd = _setup()
    strat = fedavg_strategy()
    cfg = TrainingConfig(batch_size=64, local_epochs=1)
    params = m.init(jax.random.key(0))
    results = {}
    for shape in (None, (4, 2)):
        mesh = make_mesh(shape=shape)
        step = build_scaffold_round_step(
            m.apply, cfg, mesh, 8, strategy=strat, params_like=params
        )
        p = shard_params(params, mesh)
        sos = shard_params(init_server_state(strat, params), mesh)
        cg = shard_params(zero_controls(params), mesh)
        cs = jax.device_put(stack_zero_controls(params, 8), client_sharding(mesh))
        data = shard_client_data(cd, mesh)
        weights = compute_weights(jnp.asarray(cd.num_samples))
        results[shape] = step(
            p, sos, cg, cs, data, weights, stack_rngs(jax.random.key(5), 8)
        )
    for got, want in zip(
        jax.tree.leaves(results[(4, 2)].params), jax.tree.leaves(results[None].params)
    ):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    for got, want in zip(
        jax.tree.leaves(results[(4, 2)].c_global),
        jax.tree.leaves(results[None].c_global),
    ):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    assert all(
        not leaf.sharding.is_fully_replicated
        for leaf in jax.tree.leaves(results[(4, 2)].c_global)
    )


def test_2d_validated_round_matches_1d(devices):
    """In-mesh update validation on the 2-D mesh: cohort stats ride the
    clients-psum on full deltas, so rejection decisions (and the numbers) match
    the 1-D program exactly."""
    from nanofed_tpu.security.validation import ValidationConfig

    m, cd = _setup()
    strat = fedavg_strategy()
    cfg = TrainingConfig(batch_size=64, local_epochs=1)
    params = m.init(jax.random.key(0))
    val = ValidationConfig(max_norm=100.0, z_score_threshold=1e9)
    results = {}
    for shape in (None, (4, 2)):
        mesh = make_mesh(shape=shape)
        step = build_round_step(
            m.apply, cfg, mesh, strat, validation=val, params_like=params
        )
        res = step(
            shard_params(params, mesh),
            shard_params(init_server_state(strat, params), mesh),
            shard_client_data(cd, mesh),
            compute_weights(jnp.asarray(cd.num_samples)),
            stack_rngs(jax.random.key(2), 8),
        )
        results[shape] = res
    assert int(results[(4, 2)].metrics["valid_clients"]) == 8
    for got, want in zip(
        jax.tree.leaves(results[(4, 2)].params), jax.tree.leaves(results[None].params)
    ):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_2d_central_dp_round_matches_1d(devices):
    """Central DP on the 2-D mesh: every model column derives the IDENTICAL
    full-shaped noise from the shared noise key before slicing its shard, so
    the noised aggregate equals the 1-D program's draw exactly."""
    from nanofed_tpu.aggregation.privacy import PrivacyAwareAggregationConfig
    from nanofed_tpu.privacy import PrivacyConfig

    m, cd = _setup()
    strat = fedavg_strategy()
    cfg = TrainingConfig(batch_size=64, local_epochs=1)
    params = m.init(jax.random.key(0))
    dp = PrivacyAwareAggregationConfig(
        privacy=PrivacyConfig(
            epsilon=1.0, delta=1e-5, max_gradient_norm=1.0, noise_multiplier=0.5
        )
    )
    results = {}
    for shape in (None, (4, 2)):
        mesh = make_mesh(shape=shape)
        step = build_round_step(
            m.apply, cfg, mesh, strat, central_privacy=dp, params_like=params
        )
        results[shape] = step(
            shard_params(params, mesh),
            shard_params(init_server_state(strat, params), mesh),
            shard_client_data(cd, mesh),
            compute_weights(jnp.asarray(cd.num_samples)),
            stack_rngs(jax.random.key(4), 8),
        )
    for got, want in zip(
        jax.tree.leaves(results[(4, 2)].params), jax.tree.leaves(results[None].params)
    ):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    assert all(
        not leaf.sharding.is_fully_replicated
        for leaf in jax.tree.leaves(results[(4, 2)].params)
    )


def test_2d_robust_round_matches_1d(devices):
    """Robust (trimmed-mean) aggregation on the 2-D mesh: the client-axis
    all_gather + trim runs on full deltas; each shard slices the identical
    trimmed aggregate."""
    from nanofed_tpu.aggregation.robust import RobustAggregationConfig

    m, cd = _setup()
    strat = fedavg_strategy()
    cfg = TrainingConfig(batch_size=64, local_epochs=1)
    params = m.init(jax.random.key(0))
    robust = RobustAggregationConfig(trim_k=1, method="trimmed_mean")
    results = {}
    for shape in (None, (4, 2)):
        mesh = make_mesh(shape=shape)
        step = build_round_step(
            m.apply, cfg, mesh, strat, robust=robust, params_like=params
        )
        results[shape] = step(
            shard_params(params, mesh),
            shard_params(init_server_state(strat, params), mesh),
            shard_client_data(cd, mesh),
            compute_weights(jnp.asarray(cd.num_samples)),
            stack_rngs(jax.random.key(6), 8),
        )
    for got, want in zip(
        jax.tree.leaves(results[(4, 2)].params), jax.tree.leaves(results[None].params)
    ):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_2d_build_requires_params_like(devices):
    m, _ = _setup()
    mesh = make_mesh(shape=(4, 2))
    with pytest.raises(ValueError, match="params_like"):
        build_round_step(m.apply, TrainingConfig(batch_size=64), mesh)


def test_model_axis_of_one_degenerates_to_replication(devices):
    """An (8, 1) mesh is a valid 2-D mesh whose FSDP layout is replication —
    same numbers as the 1-D mesh, every leaf fully replicated."""
    m, cd = _setup()
    strat = fedavg_strategy()
    res_1d = _run_round(None, strat, m, cd)
    res_81 = _run_round((8, 1), strat, m, cd)
    for got, want in zip(jax.tree.leaves(res_81.params), jax.tree.leaves(res_1d.params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    assert all(
        leaf.sharding.is_fully_replicated for leaf in jax.tree.leaves(res_81.params)
    )
