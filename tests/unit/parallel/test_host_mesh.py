"""The 3-axis ``hosts x clients x model`` mesh: construction rules, the
hierarchical client-axis collectives, the generalized :class:`MeshLayout`, and
— the acceptance bar — every round-program variant's parity against the 1-D
mesh on the virtual 8-device CPU grid (single-process virtual hosts; the REAL
2-process ``jax.distributed`` run is ``make multihost-smoke``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from nanofed_tpu.core.types import ClientData
from nanofed_tpu.parallel import (
    CLIENT_AXIS,
    HOST_AXIS,
    MeshLayout,
    client_axes,
    client_shard_count,
    client_sharding,
    hierarchical_all_gather,
    hierarchical_pmean,
    hierarchical_psum,
    host_axis_size,
    host_client_slice,
    make_mesh,
    mesh_shape,
    mesh_shape_for_topology,
    pad_client_count,
    pad_clients,
    shard_client_data,
    shard_host_local_data,
)
from nanofed_tpu.parallel.mesh import shard_map


# ---------------------------------------------------------------------------
# construction + shape helpers
# ---------------------------------------------------------------------------


def test_make_mesh_3d_axes_and_sizes(devices):
    mesh = make_mesh(shape=(2, 2, 2))
    assert mesh.axis_names == (HOST_AXIS, CLIENT_AXIS, "model")
    assert mesh_shape(mesh) == (2, 2, 2)
    assert host_axis_size(mesh) == 2
    assert client_shard_count(mesh) == 4  # hosts x clients jointly
    assert client_axes(mesh) == (HOST_AXIS, CLIENT_AXIS)


def test_make_mesh_3d_rejects_bad_products(devices):
    with pytest.raises(ValueError, match="needs 12 devices"):
        make_mesh(shape=(3, 2, 2))
    with pytest.raises(ValueError, match="positive"):
        make_mesh(shape=(0, 4, 2))
    with pytest.raises(ValueError, match="hosts, clients, model"):
        make_mesh(shape=(2, 2, 2, 1))


def test_mesh_shape_for_topology_rules():
    # hosts == 1 delegates to the 2-axis validator (None for the 1-D layout).
    assert mesh_shape_for_topology(1, 1, 8) is None
    assert mesh_shape_for_topology(1, 2, 8) == (4, 2)
    assert mesh_shape_for_topology(2, 1, 8) == (2, 4, 1)
    assert mesh_shape_for_topology(2, 2, 8) == (2, 2, 2)
    with pytest.raises(ValueError, match="does not divide"):
        mesh_shape_for_topology(3, 1, 8)
    with pytest.raises(ValueError, match="hosts must be"):
        mesh_shape_for_topology(0, 1, 8)


def test_client_sharding_is_joint_on_hosts_mesh(devices):
    mesh = make_mesh(shape=(2, 2, 2))
    spec = client_sharding(mesh).spec
    assert tuple(spec) == ((HOST_AXIS, CLIENT_AXIS),)
    # 1-D/2-D meshes keep the classic single-axis spec.
    assert tuple(client_sharding(make_mesh()).spec) == (CLIENT_AXIS,)


def test_host_client_slice_single_process_covers_everything(devices):
    mesh = make_mesh(shape=(2, 2, 2))
    assert host_client_slice(16, mesh) == (0, 16)


def test_shard_host_local_data_matches_global(devices):
    mesh = make_mesh(shape=(2, 4, 1))
    rng = np.random.default_rng(0)
    data = ClientData(
        x=rng.normal(size=(8, 4, 2)).astype(np.float32),
        y=rng.integers(0, 2, size=(8, 4)).astype(np.int32),
        mask=np.ones((8, 4), np.float32),
    )
    start, stop = host_client_slice(8, mesh)
    local = jax.tree.map(lambda a: a[start:stop], data)
    via_local = shard_host_local_data(local, mesh, 8)
    via_global = shard_client_data(data, mesh)
    np.testing.assert_array_equal(
        np.asarray(via_local.x), np.asarray(via_global.x)
    )
    assert via_local.x.sharding.spec == via_global.x.sharding.spec


# ---------------------------------------------------------------------------
# hierarchical collectives == flat collectives
# ---------------------------------------------------------------------------


def test_hierarchical_psum_matches_flat(devices):
    mesh = make_mesh(shape=(2, 4, 1))
    x = jnp.arange(8.0)

    def hier(v):
        return hierarchical_psum(v.sum(), (HOST_AXIS, CLIENT_AXIS))

    def flat(v):
        from jax import lax

        return lax.psum(v.sum(), (HOST_AXIS, CLIENT_AXIS))

    kw = dict(mesh=mesh, in_specs=P((HOST_AXIS, CLIENT_AXIS)), out_specs=P())
    import inspect

    sig = inspect.signature(shard_map).parameters
    flag = {f: False for f in ("check_rep", "check_vma") if f in sig}
    got_h = jax.jit(shard_map(hier, **kw, **flag))(x)
    got_f = jax.jit(shard_map(flat, **kw, **flag))(x)
    assert float(got_h) == pytest.approx(float(got_f))
    assert float(got_h) == pytest.approx(28.0)


def test_hierarchical_helpers_single_axis_degenerate(devices):
    mesh = make_mesh()

    def body(v):
        s = hierarchical_psum(v.sum(), CLIENT_AXIS)
        m = hierarchical_pmean(v.sum(), CLIENT_AXIS)
        g = hierarchical_all_gather(v, CLIENT_AXIS)
        return s, m, g

    import inspect

    sig = inspect.signature(shard_map).parameters
    flag = {f: False for f in ("check_rep", "check_vma") if f in sig}
    s, m, g = jax.jit(
        shard_map(body, mesh=mesh, in_specs=P(CLIENT_AXIS),
                  out_specs=(P(), P(), P(CLIENT_AXIS)), **flag)
    )(jnp.arange(8.0))
    assert float(s) == 28.0
    assert float(m) == 28.0 / 8
    assert g.shape == (8 * 8,)


def test_hierarchical_all_gather_collects_every_row(devices):
    mesh = make_mesh(shape=(2, 4, 1))

    def body(v):
        return hierarchical_all_gather(v, (HOST_AXIS, CLIENT_AXIS))

    import inspect

    sig = inspect.signature(shard_map).parameters
    flag = {f: False for f in ("check_rep", "check_vma") if f in sig}
    out = jax.jit(
        shard_map(body, mesh=mesh, in_specs=P((HOST_AXIS, CLIENT_AXIS)),
                  out_specs=P((HOST_AXIS, CLIENT_AXIS)), **flag)
    )(jnp.arange(8.0))
    # Every device gathered all 8 values (order may interleave host blocks —
    # consumers are permutation-invariant); the tiled output stacks 8 copies.
    assert out.shape == (64,)
    assert sorted(np.asarray(out)[:8].tolist()) == sorted(
        set(np.asarray(out).tolist())
    )


# ---------------------------------------------------------------------------
# MeshLayout generalization
# ---------------------------------------------------------------------------


def test_mesh_layout_client_axes(devices):
    assert MeshLayout(make_mesh()).client_axes == CLIENT_AXIS
    assert MeshLayout(make_mesh(shape=(4, 2))).client_axes == CLIENT_AXIS
    layout = MeshLayout(make_mesh(shape=(2, 2, 2)))
    assert layout.client_axes == (HOST_AXIS, CLIENT_AXIS)
    assert layout.n_hosts == 2
    assert layout.n_model_shards == 2
    assert tuple(layout.data_spec) == ((HOST_AXIS, CLIENT_AXIS),)
    assert layout.multi_axis and layout.raw_keys_at_boundary


def test_model_axis_layout_alias_still_importable():
    from nanofed_tpu.parallel import ModelAxisLayout

    assert ModelAxisLayout is MeshLayout


# ---------------------------------------------------------------------------
# round-program parity: 3-axis hierarchical == 1-D flat (float tolerance)
# ---------------------------------------------------------------------------


def _population(num_clients=16, cap=8):
    rng = np.random.default_rng(3)
    y = rng.integers(0, 10, size=(num_clients, cap)).astype(np.int32)
    x = rng.normal(size=(num_clients, cap, 8, 8, 1)).astype(np.float32)
    return ClientData(x=x, y=y, mask=np.ones((num_clients, cap), np.float32))


def _setup(shape, data, model, strategy):
    from nanofed_tpu.parallel import init_server_state, param_sharding

    mesh = make_mesh(shape=shape)
    padded = pad_client_count(data.x.shape[0], client_shard_count(mesh))
    d = pad_clients(data, padded)
    num_samples = jnp.asarray(np.asarray(d.mask).sum(axis=1), jnp.float32)
    d = shard_client_data(d, mesh)
    ph = model.init(jax.random.key(0))
    params = jax.device_put(ph, param_sharding(mesh, ph))
    sos_h = init_server_state(strategy, ph)
    sos = jax.device_put(sos_h, param_sharding(mesh, sos_h))
    return mesh, padded, d, num_samples, params, sos, ph


def _flat(tree):
    return np.concatenate([
        np.asarray(jax.device_get(x)).ravel() for x in jax.tree.leaves(tree)
    ])


@pytest.mark.parametrize("shape", [(2, 2, 2), (2, 4, 1)])
def test_round_step_parity_3d_vs_1d(devices, shape):
    from nanofed_tpu.aggregation import compute_weights, fedavg_strategy
    from nanofed_tpu.models import get_model
    from nanofed_tpu.parallel import build_round_step
    from nanofed_tpu.trainer import TrainingConfig
    from nanofed_tpu.trainer.local import stack_rngs

    model = get_model("digits_mlp")
    training = TrainingConfig(batch_size=8, local_epochs=1, learning_rate=0.1)
    strategy = fedavg_strategy()
    data = _population()
    outs = {}
    for tag, s in (("1d", None), ("3d", shape)):
        mesh, padded, d, ns, params, sos, _ = _setup(s, data, model, strategy)
        step = build_round_step(
            model.apply, training, mesh, strategy, params_like=params
        )
        weights = compute_weights(ns)
        rngs = stack_rngs(jax.random.key(7), padded)
        for _ in range(2):
            res = step(params, sos, d, weights, rngs)
            params, sos = res.params, res.server_opt_state
        outs[tag] = (_flat(params), float(res.metrics["loss"]))
    np.testing.assert_allclose(outs["1d"][0], outs["3d"][0], atol=5e-6)
    assert outs["1d"][1] == pytest.approx(outs["3d"][1], abs=1e-5)


@pytest.mark.slow  # ~22s of compiles; the tier-1 870s budget has no headroom.
# Tier-1 keeps the fused-block 3-D parity (test_3d_fused_round_block_matches_
# single_rounds) and step parity (test_round_step_parity_3d_vs_1d); the
# variants additionally run on the mesh in dryrun_multichip and CI's
# multihost-smoke exercises the real 2-process program.
def test_round_block_and_variants_parity_3d(devices):
    """Fused block, validated, robust, SCAFFOLD, and chunked-streaming paths
    all match the 1-D program on the (2, 2, 2) mesh — the hierarchical reduce
    is a re-association of the same sum, never different math."""
    from nanofed_tpu.aggregation import compute_weights, fedavg_strategy
    from nanofed_tpu.aggregation.robust import RobustAggregationConfig
    from nanofed_tpu.models import get_model
    from nanofed_tpu.parallel import (
        build_round_block,
        build_round_step,
        build_scaffold_round_step,
        stack_round_keys,
    )
    from nanofed_tpu.security.validation import ValidationConfig
    from nanofed_tpu.trainer import TrainingConfig
    from nanofed_tpu.trainer.local import stack_rngs
    from nanofed_tpu.trainer.scaffold import stack_zero_controls, zero_controls
    from nanofed_tpu.parallel import param_sharding

    model = get_model("digits_mlp")
    training = TrainingConfig(batch_size=8, local_epochs=1, learning_rate=0.1)
    strategy = fedavg_strategy()
    data = _population()
    out = {}
    for tag, shape in (("1d", None), ("3d", (2, 2, 2))):
        mesh, padded, d, ns, params, sos, ph = _setup(
            shape, data, model, strategy
        )
        weights = compute_weights(ns)
        rngs = stack_rngs(jax.random.key(7), padded)

        block = build_round_block(
            model.apply, training, mesh, strategy, num_clients=16,
            padded_clients=padded, params_like=params,
            collect_client_detail=False,
        )
        mask = jnp.asarray(np.tile(np.asarray(ns > 0, np.float32), (3, 1)))
        res = block(params, sos, d, ns, stack_round_keys(0, [0, 1, 2]),
                    jnp.ones(3), cohort_mask=mask)
        out[tag, "block"] = _flat(res.params)

        for kind, kwargs in (
            ("validated", dict(validation=ValidationConfig(max_norm=100.0))),
            ("robust", dict(robust=RobustAggregationConfig(trim_k=1))),
            ("chunked", dict(client_chunk=1)),
        ):
            step = build_round_step(
                model.apply, training, mesh, strategy, params_like=params,
                **kwargs,
            )
            res = step(params, sos, d, weights, rngs)
            out[tag, kind] = _flat(res.params)

        sstep = build_scaffold_round_step(
            model.apply, training, mesh, 16, strategy=strategy,
            params_like=params,
        )
        cg = jax.device_put(zero_controls(ph), param_sharding(mesh, ph))
        cs = jax.device_put(
            stack_zero_controls(ph, padded), client_sharding(mesh)
        )
        res = sstep(params, sos, cg, cs, d, weights, rngs)
        out[tag, "scaffold"] = _flat(res.params)

    for kind in ("block", "validated", "robust", "chunked", "scaffold"):
        np.testing.assert_allclose(
            out["1d", kind], out["3d", kind], atol=5e-6, err_msg=kind
        )
