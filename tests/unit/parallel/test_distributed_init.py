"""initialize_distributed: the single-process no-op path (the multi-host path needs a
real multi-process cluster; its contract is documented in docs/concepts.md and exercised
by jax.distributed itself)."""

import nanofed_tpu.parallel.mesh as mesh_mod
from nanofed_tpu.parallel import initialize_distributed


def test_single_process_noop(monkeypatch):
    """No coordinator configured anywhere -> no jax.distributed call, identity result."""
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    called = []
    monkeypatch.setattr(
        mesh_mod.jax.distributed, "initialize",
        lambda **kw: called.append(kw),
    )
    info = initialize_distributed()
    assert info == {"process_index": 0, "process_count": 1}
    assert called == []


def test_single_host_tpu_hostnames_is_noop(monkeypatch):
    """A single-entry TPU_WORKER_HOSTNAMES (one host, e.g. this repo's axon tunnel)
    must not trigger multi-host init."""
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    called = []
    monkeypatch.setattr(
        mesh_mod.jax.distributed, "initialize",
        lambda **kw: called.append(kw),
    )
    info = initialize_distributed()
    assert info["process_count"] == 1
    assert called == []


def test_explicit_coordinator_calls_jax_distributed(monkeypatch):
    """An explicit coordinator address routes through jax.distributed.initialize with
    the exact arguments given."""
    called = []
    monkeypatch.setattr(
        mesh_mod.jax.distributed, "initialize", lambda **kw: called.append(kw)
    )
    monkeypatch.setattr(mesh_mod.jax, "process_index", lambda: 1, raising=False)
    monkeypatch.setattr(mesh_mod.jax, "process_count", lambda: 4, raising=False)
    info = initialize_distributed(
        coordinator_address="10.0.0.1:8476", num_processes=4, process_id=1
    )
    assert called == [
        {"coordinator_address": "10.0.0.1:8476", "num_processes": 4, "process_id": 1}
    ]
    assert info == {"process_index": 1, "process_count": 4}


def test_env_vars_configure_init(monkeypatch):
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.2:9000")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
    monkeypatch.setenv("JAX_PROCESS_ID", "0")
    called = []
    monkeypatch.setattr(
        mesh_mod.jax.distributed, "initialize", lambda **kw: called.append(kw)
    )
    monkeypatch.setattr(mesh_mod.jax, "process_index", lambda: 0, raising=False)
    monkeypatch.setattr(mesh_mod.jax, "process_count", lambda: 2, raising=False)
    initialize_distributed()
    assert called == [
        {"coordinator_address": "10.0.0.2:9000", "num_processes": 2, "process_id": 0}
    ]


def test_partial_config_without_coordinator_raises(monkeypatch):
    """Process ids without a coordinator address must fail loudly — a silent
    single-process fallback would train N divergent models."""
    import pytest

    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    with pytest.raises(ValueError, match="coordinator address"):
        initialize_distributed(num_processes=4, process_id=2)


def test_force_calls_bare_initialize(monkeypatch):
    """force=True hands off to jax.distributed.initialize with no arguments so JAX's
    TPU-metadata auto-detection runs (plain multi-host TPU VMs)."""
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    called = []
    monkeypatch.setattr(
        mesh_mod.jax.distributed, "initialize", lambda **kw: called.append(kw)
    )
    monkeypatch.setattr(mesh_mod.jax, "process_index", lambda: 0, raising=False)
    monkeypatch.setattr(mesh_mod.jax, "process_count", lambda: 8, raising=False)
    info = initialize_distributed(force=True)
    assert called == [
        {"coordinator_address": None, "num_processes": None, "process_id": None}
    ]
    assert info["process_count"] == 8


def test_cpu_gloo_collectives_selected_before_init(monkeypatch):
    """On a CPU platform the wrapper must select the gloo cross-process
    collectives BEFORE jax.distributed.initialize — without them every
    multi-device program dies with XLA's 'Multiprocess computations aren't
    implemented on the CPU backend'.  An operator's explicit choice wins."""
    from types import SimpleNamespace

    from jax._src import xla_bridge

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    updates = []
    monkeypatch.setattr(
        mesh_mod.jax.config, "update",
        lambda name, value: updates.append((name, value)),
    )
    monkeypatch.setattr(
        xla_bridge, "CPU_COLLECTIVES_IMPLEMENTATION",
        SimpleNamespace(value="none"),
    )
    mesh_mod._enable_cpu_collectives()
    assert updates == [("jax_cpu_collectives_implementation", "gloo")]

    # Operator override: a non-"none" value is left alone.
    updates.clear()
    monkeypatch.setattr(
        xla_bridge, "CPU_COLLECTIVES_IMPLEMENTATION",
        SimpleNamespace(value="mpi"),
    )
    mesh_mod._enable_cpu_collectives()
    assert updates == []

    # Non-CPU platforms carry their own collectives: nothing to select.
    updates.clear()
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    monkeypatch.setattr(
        xla_bridge, "CPU_COLLECTIVES_IMPLEMENTATION",
        SimpleNamespace(value="none"),
    )
    mesh_mod._enable_cpu_collectives()
    assert updates == []


def test_multi_process_path_enables_collectives(monkeypatch):
    """The explicit-coordinator path routes through the collectives selection
    exactly once, before jax.distributed.initialize."""
    order = []
    monkeypatch.setattr(
        mesh_mod, "_enable_cpu_collectives",
        lambda: order.append("collectives"),
    )
    monkeypatch.setattr(
        mesh_mod.jax.distributed, "initialize",
        lambda **kw: order.append("initialize"),
    )
    monkeypatch.setattr(mesh_mod.jax, "process_index", lambda: 0, raising=False)
    monkeypatch.setattr(mesh_mod.jax, "process_count", lambda: 2, raising=False)
    initialize_distributed(
        coordinator_address="localhost:1", num_processes=2, process_id=0
    )
    assert order == ["collectives", "initialize"]


def test_single_process_path_touches_no_config(monkeypatch):
    """The documented no-op must not flip global config either."""
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    updates = []
    monkeypatch.setattr(
        mesh_mod.jax.config, "update",
        lambda name, value: updates.append((name, value)),
    )
    assert initialize_distributed()["process_count"] == 1
    assert updates == []
