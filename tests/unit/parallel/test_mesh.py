"""Mesh construction + sharding-helper edge cases: client padding below the
device count, the 2-D ``clients x model`` mesh layouts, and the per-leaf
FSDP fallback rules of ``param_partition_spec`` / ``param_sharding``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from nanofed_tpu.core.types import ClientData
from nanofed_tpu.parallel import (
    CLIENT_AXIS,
    MODEL_AXIS,
    client_axis_size,
    make_mesh,
    mesh_shape,
    model_axis_size,
    pad_client_count,
    pad_clients,
    param_partition_spec,
    param_sharding,
    shard_client_data,
    shard_params,
)


def _client_data(c=3, n=4, feat=2):
    rng = np.random.default_rng(0)
    return ClientData(
        x=rng.normal(size=(c, n, feat)).astype(np.float32),
        y=rng.integers(0, 2, size=(c, n)).astype(np.int32),
        mask=np.ones((c, n), np.float32),
    )


# ---------------------------------------------------------------------------
# pad_client_count / pad_clients with num_clients < n_devices
# ---------------------------------------------------------------------------


def test_pad_client_count_below_device_count():
    """Fewer clients than shards pads UP to one client per shard, never down."""
    assert pad_client_count(3, 8) == 8
    assert pad_client_count(1, 8) == 8
    assert pad_client_count(8, 8) == 8
    assert pad_client_count(9, 8) == 16


def test_pad_clients_below_device_count_zero_masks_dummies(devices):
    data = _client_data(c=3)
    padded = pad_clients(data, 8)
    assert padded.x.shape[0] == 8
    # Real clients' rows are untouched; dummies carry zero mask (=> zero weight).
    np.testing.assert_array_equal(np.asarray(padded.x[:3]), data.x)
    np.testing.assert_array_equal(np.asarray(padded.mask[3:]), 0.0)


def test_pad_clients_refuses_to_truncate():
    with pytest.raises(ValueError, match="cannot pad"):
        pad_clients(_client_data(c=5), 3)


# ---------------------------------------------------------------------------
# make_mesh shapes
# ---------------------------------------------------------------------------


def test_make_mesh_1d_default(devices):
    mesh = make_mesh()
    assert mesh.axis_names == (CLIENT_AXIS,)
    assert mesh_shape(mesh) == (8,)
    assert client_axis_size(mesh) == 8
    assert model_axis_size(mesh) == 1


def test_make_mesh_2d_shapes(devices):
    for shape in [(4, 2), (2, 4), (8, 1), (1, 8)]:
        mesh = make_mesh(shape=shape)
        assert mesh.axis_names == (CLIENT_AXIS, MODEL_AXIS)
        assert mesh_shape(mesh) == shape
        assert client_axis_size(mesh) == shape[0]
        assert model_axis_size(mesh) == shape[1]


def test_make_mesh_2d_rejects_bad_shapes(devices):
    with pytest.raises(ValueError, match="needs 6 devices"):
        make_mesh(shape=(3, 2))
    with pytest.raises(ValueError, match="positive"):
        make_mesh(shape=(0, 8))


# ---------------------------------------------------------------------------
# param_partition_spec fallback rules
# ---------------------------------------------------------------------------


def test_param_partition_spec_picks_largest_divisible_dim():
    assert param_partition_spec((8, 16), 2) == P(None, MODEL_AXIS)
    assert param_partition_spec((16, 4), 2) == P(MODEL_AXIS)
    # Tie on size: the first largest dim wins.
    assert param_partition_spec((16, 16), 2) == P(MODEL_AXIS)


def test_param_partition_spec_non_divisible_falls_back_to_replication():
    # No dim divisible by 4 -> replicate the whole leaf.
    assert param_partition_spec((3, 7), 4) == P()
    # Scalars and empty shapes replicate.
    assert param_partition_spec((), 4) == P()
    # One divisible dim among non-divisible ones is still sharded.
    assert param_partition_spec((3, 8, 5), 4) == P(None, MODEL_AXIS)


def test_param_partition_spec_single_shard_replicates():
    assert param_partition_spec((8, 16), 1) == P()


def test_param_partition_spec_never_shards_stacked_layer_dim():
    """Rank>=3 leaves are scan-stacked layer params [L, ...]: the leading dim
    indexes layers, so sharding it across the model axis would split the scan
    carry — dim 0 must never be chosen even when it is the largest divisible
    dim."""
    # L=8 divisible and largest: still skipped, largest remaining dim wins.
    assert param_partition_spec((8, 4, 6), 2) == P(None, None, MODEL_AXIS)
    # Only dim 0 divisible -> replicate rather than split the stack.
    assert param_partition_spec((8, 3, 5), 2) == P()
    # Rank-2 leaves keep the old behavior (dim 0 eligible).
    assert param_partition_spec((8, 5), 2) == P(MODEL_AXIS)
    # Stacked conv-style rank-4 leaves also skip dim 0.
    assert param_partition_spec((4, 3, 8, 5), 4) == P(None, None, MODEL_AXIS)


def test_param_sharding_mixed_tree(devices):
    mesh = make_mesh(shape=(2, 4))
    tree = {"kernel": jnp.zeros((8, 16)), "odd_bias": jnp.zeros((3,)), "s": jnp.zeros(())}
    shardings = param_sharding(mesh, tree)
    assert shardings["kernel"].spec == P(None, MODEL_AXIS)
    # 3 % 4 != 0 -> per-leaf replication fallback; scalar likewise.
    assert shardings["odd_bias"].is_fully_replicated
    assert shardings["s"].is_fully_replicated
    placed = shard_params(tree, mesh)
    assert placed["kernel"].sharding.spec == P(None, MODEL_AXIS)
    assert placed["odd_bias"].sharding.is_fully_replicated


def test_param_sharding_1d_mesh_is_replicated(devices):
    mesh = make_mesh()
    shardings = param_sharding(mesh, {"k": jnp.zeros((8, 16))})
    assert shardings["k"].is_fully_replicated


# ---------------------------------------------------------------------------
# 2-D shard_client_data layouts
# ---------------------------------------------------------------------------


def test_shard_client_data_2d_layout(devices):
    """Client data on a 2-D mesh: leading axis over clients, replicated over
    model — each model column holds its clients whole."""
    mesh = make_mesh(shape=(4, 2))
    data = shard_client_data(pad_clients(_client_data(c=3), 4), mesh)
    for leaf in jax.tree.leaves(data):
        spec = leaf.sharding.spec
        assert spec[0] == CLIENT_AXIS
        assert all(e is None for e in tuple(spec)[1:])
        # 4 client shards x 2 model columns: every device holds a quarter of
        # the clients, so each leaf has 8 addressable shards of 1 client each.
        assert len(leaf.sharding.device_set) == 8
        shard_rows = {s.data.shape[0] for s in leaf.addressable_shards}
        assert shard_rows == {1}


def test_shard_client_data_1d_unchanged(devices):
    mesh = make_mesh()
    data = shard_client_data(pad_clients(_client_data(c=3), 8), mesh)
    for leaf in jax.tree.leaves(data):
        assert leaf.sharding.spec[0] == CLIENT_AXIS
