"""Fused multi-round engine tests on the 8-device CPU mesh.

The load-bearing property: a fused R-round block is the SAME math as R
single-round ``round_step`` calls — same seeds, same cohorts, same schedule.
Single-batch clients throughout (batch_size == per-client capacity) so the
comparisons cross program structures (scan-of-shard_map vs shard_map) without
tripping the jaxlib CPU backends whose fused-context epoch-shuffle draw is
program-specific (see test_round_step.py for the diagnosis).
"""

import jax
import jax.numpy as jnp
import numpy as np

from nanofed_tpu.aggregation import compute_weights, fedavg_strategy
from nanofed_tpu.data import federate, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.parallel import (
    build_round_block,
    build_round_step,
    init_server_state,
    make_mesh,
    shard_client_data,
    stack_round_keys,
)
from nanofed_tpu.trainer import TrainingConfig, stack_rngs
from nanofed_tpu.trainer.schedules import lr_schedule_scales


def _setup(num_clients=8, batch=64, n=512, classes=4, feat=8, seed=0):
    m = get_model("mlp", in_features=feat, hidden=16, num_classes=classes)
    ds = synthetic_classification(n, classes, (feat,), seed=seed)
    cd = federate(ds, num_clients=num_clients, scheme="iid", batch_size=batch, seed=seed)
    mesh = make_mesh()
    return m, cd, mesh


def _single_round_reference(m, cfg, mesh, strat, cd, seed, rounds, lr_scales, weights):
    """R single-round calls, exactly as the coordinator drives them."""
    step = build_round_step(m.apply, cfg, mesh, strat)
    params = m.init(jax.random.key(0))
    sos = init_server_state(strat, params)
    sharded = shard_client_data(cd, mesh)
    c = cd.x.shape[0]
    per_round = []
    for i, r in enumerate(rounds):
        base = jax.random.fold_in(jax.random.key(seed), r)
        res = step(params, sos, sharded, weights, stack_rngs(base, c),
                   jnp.float32(lr_scales[i]))
        params, sos = res.params, res.server_opt_state
        per_round.append(res)
    return params, sos, per_round


def test_fused_block_equals_single_rounds_full_participation(devices):
    """Block of R rounds == R round_step calls: params AND stacked metrics, with a
    non-constant per-round lr schedule riding the traced [R] scale array."""
    m, cd, mesh = _setup()
    cfg = TrainingConfig(batch_size=64, local_epochs=1)
    strat = fedavg_strategy()
    seed, rounds = 3, [0, 1, 2]
    lr_scales = lr_schedule_scales("step", 0, 3, 10, decay_every=1, gamma=0.5)
    assert lr_scales == [1.0, 0.5, 0.25]
    ns = jnp.asarray(cd.num_samples, dtype=jnp.float32)
    weights = compute_weights(ns)

    ref_params, _, ref_rounds = _single_round_reference(
        m, cfg, mesh, strat, cd, seed, rounds, lr_scales, weights
    )

    block = build_round_block(
        m.apply, cfg, mesh, strat, num_clients=8, padded_clients=8,
    )
    params = m.init(jax.random.key(0))
    sos = init_server_state(strat, params)
    sharded = shard_client_data(cd, mesh)
    mask = np.ones((3, 8), dtype=np.float32)
    res = block(
        params, sos, sharded, ns, stack_round_keys(seed, rounds),
        jnp.asarray(lr_scales), cohort_mask=jnp.asarray(mask),
    )

    for got, want in zip(jax.tree.leaves(res.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    # Stacked per-round metrics match the single-round metrics round for round.
    for i in range(3):
        for key in ("loss", "accuracy", "participating_clients"):
            np.testing.assert_allclose(
                float(res.metrics[key][i]), float(ref_rounds[i].metrics[key]),
                rtol=1e-5, err_msg=f"round {i} metric {key}",
            )
        np.testing.assert_allclose(
            np.asarray(res.client_metrics.loss[i]),
            np.asarray(ref_rounds[i].client_metrics.loss), rtol=1e-4,
        )
        np.testing.assert_allclose(
            np.asarray(res.update_sq_norms[i]),
            np.asarray(ref_rounds[i].update_sq_norms), rtol=1e-3, atol=1e-7,
        )
    assert np.asarray(res.survivors).tolist() == [8, 8, 8]


def test_fused_block_cohort_mode_equals_single_rounds(devices):
    """Cohort gathering INSIDE the scan: host-sampled cohorts reproduce the
    single-round gathered path (client-stable keys, weights from gathered counts)."""
    m, cd, mesh = _setup(num_clients=16, batch=16, n=256)
    cfg = TrainingConfig(batch_size=16, local_epochs=1)
    strat = fedavg_strategy()
    seed, rounds, k, k_pad = 5, [0, 1, 2], 4, 8
    ns = jnp.asarray(cd.num_samples, dtype=jnp.float32)

    # Host cohort sampling, exactly like Coordinator._sample_cohort (no DP, no dropout).
    idx_rows = np.zeros((3, k_pad), dtype=np.int32)
    mask_rows = np.zeros((3, k_pad), dtype=np.float32)
    for i, r in enumerate(rounds):
        rng = np.random.default_rng(seed * 100_003 + r)
        sampled = rng.choice(16, size=k, replace=False)
        idx_rows[i, :k] = sampled
        mask_rows[i, :k] = 1.0

    # Reference: R single-round calls over the gathered cohort.
    step = build_round_step(m.apply, cfg, mesh, strat)
    params = m.init(jax.random.key(0))
    sos = init_server_state(strat, params)
    sharded = shard_client_data(cd, mesh)
    ref_metrics = []
    for i, r in enumerate(rounds):
        idx = jnp.asarray(idx_rows[i])
        data_r = jax.tree.map(lambda x: x[idx], sharded)
        weights = compute_weights(ns[idx], jnp.asarray(mask_rows[i]))
        base = jax.random.fold_in(jax.random.key(seed), r)
        rngs = stack_rngs(base, 16)[idx]
        res = step(params, sos, data_r, weights, rngs)
        params, sos = res.params, res.server_opt_state
        ref_metrics.append({k2: float(v) for k2, v in res.metrics.items()})
    ref_params = params

    block = build_round_block(
        m.apply, cfg, mesh, strat, num_clients=16, padded_clients=16,
        step_clients=k_pad, cohort_size=k,
    )
    params = m.init(jax.random.key(0))
    sos = init_server_state(strat, params)
    res = block(
        params, sos, sharded, ns, stack_round_keys(seed, rounds),
        jnp.ones(3), jnp.asarray(idx_rows), jnp.asarray(mask_rows),
    )
    for got, want in zip(jax.tree.leaves(res.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    for i in range(3):
        np.testing.assert_allclose(
            float(res.metrics["loss"][i]), ref_metrics[i]["loss"], rtol=1e-5
        )
        assert int(res.metrics["participating_clients"][i]) == 4
    assert np.asarray(res.survivors).tolist() == [4, 4, 4]


def test_device_sampling_is_deterministic_and_valid(devices):
    """On-device resampling: cohorts are valid without-replacement draws, the block
    is deterministic, and params actually train."""
    m, cd, mesh = _setup(num_clients=16, batch=16, n=256)
    cfg = TrainingConfig(batch_size=16, local_epochs=1)
    strat = fedavg_strategy()
    ns = jnp.asarray(cd.num_samples, dtype=jnp.float32)
    block = build_round_block(
        m.apply, cfg, mesh, strat, num_clients=16, padded_clients=16,
        step_clients=8, cohort_size=4,
    )
    params = m.init(jax.random.key(0))
    sos = init_server_state(strat, params)
    sharded = shard_client_data(cd, mesh)
    keys = stack_round_keys(0, [0, 1, 2, 3])
    res1 = block(params, sos, sharded, ns, keys, jnp.ones(4))
    res2 = block(params, sos, sharded, ns, keys, jnp.ones(4))
    assert np.asarray(res1.survivors).tolist() == [4, 4, 4, 4]
    ids = np.asarray(res1.cohort_ids)
    assert ids.shape == (4, 8)
    for row in ids:
        sampled = row[:4]
        assert len(set(sampled.tolist())) == 4  # without replacement
        assert (sampled < 16).all() and (sampled >= 0).all()
    # Different rounds draw different cohorts (fold_in of the round index).
    assert not np.array_equal(np.sort(ids[0][:4]), np.sort(ids[1][:4])) or \
        not np.array_equal(np.sort(ids[1][:4]), np.sort(ids[2][:4]))
    for a, b in zip(jax.tree.leaves(res1.params), jax.tree.leaves(res2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert all(np.isfinite(np.asarray(res1.metrics["loss"])))


def test_device_sampling_respects_cohort_size_at_full_step_width(devices):
    """Regression: cohort_size < num_clients with step_clients left at the padded
    default must still SAMPLE (cohort mode is derived from the cohort being a
    strict subset, not from the step width)."""
    m, cd, mesh = _setup(num_clients=16, batch=16, n=256)
    cfg = TrainingConfig(batch_size=16, local_epochs=1)
    strat = fedavg_strategy()
    ns = jnp.asarray(cd.num_samples, dtype=jnp.float32)
    block = build_round_block(
        m.apply, cfg, mesh, strat, num_clients=16, padded_clients=16,
        cohort_size=4,  # step_clients defaults to padded (16)
    )
    params = m.init(jax.random.key(0))
    sos = init_server_state(strat, params)
    res = block(params, sos, shard_client_data(cd, mesh), ns,
                stack_round_keys(0, [0, 1]), jnp.ones(2))
    assert np.asarray(res.survivors).tolist() == [4, 4]
    assert np.asarray(res.metrics["participating_clients"]).tolist() == [4, 4]


def test_below_completion_round_is_identity(devices):
    """A scanned round whose cohort mask falls below min_completion_rate leaves
    params AND server state untouched (FAILED-round semantics, in-device)."""
    m, cd, mesh = _setup()
    cfg = TrainingConfig(batch_size=64, local_epochs=1)
    strat = fedavg_strategy()
    ns = jnp.asarray(cd.num_samples, dtype=jnp.float32)
    block = build_round_block(
        m.apply, cfg, mesh, strat, num_clients=8, padded_clients=8,
        min_completion_rate=0.5,
    )
    params = m.init(jax.random.key(0))
    sos = init_server_state(strat, params)
    sharded = shard_client_data(cd, mesh)
    # Round 0: 2/8 survivors (< the 4 required) -> identity; round 1: full cohort.
    mask = np.zeros((2, 8), dtype=np.float32)
    mask[0, :2] = 1.0
    mask[1, :] = 1.0
    res = block(
        params, sos, sharded, ns, stack_round_keys(0, [0, 1]), jnp.ones(2),
        cohort_mask=jnp.asarray(mask),
    )
    assert np.asarray(res.survivors).tolist() == [2, 8]
    assert int(res.metrics["participating_clients"][0]) == 0

    # The single-round reference SKIPS failed rounds host-side; round 1 alone from
    # the same init must therefore match the block's final params.
    step = build_round_step(m.apply, cfg, mesh, strat)
    base = jax.random.fold_in(jax.random.key(0), 1)
    ref = step(params, sos, sharded, compute_weights(ns), stack_rngs(base, 8))
    for got, want in zip(jax.tree.leaves(res.params), jax.tree.leaves(ref.params)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_collect_client_detail_off_returns_none(devices):
    m, cd, mesh = _setup()
    cfg = TrainingConfig(batch_size=64, local_epochs=1)
    strat = fedavg_strategy()
    ns = jnp.asarray(cd.num_samples, dtype=jnp.float32)
    block = build_round_block(
        m.apply, cfg, mesh, strat, num_clients=8, padded_clients=8,
        collect_client_detail=False,
    )
    params = m.init(jax.random.key(0))
    sos = init_server_state(strat, params)
    res = block(
        params, sos, shard_client_data(cd, mesh), ns, stack_round_keys(0, [0, 1]),
        jnp.ones(2), cohort_mask=jnp.ones((2, 8)),
    )
    assert res.client_metrics is None
    assert res.update_sq_norms is None
    assert res.weights is None
    assert res.metrics["loss"].shape == (2,)
