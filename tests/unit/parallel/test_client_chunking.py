"""client_chunk memory-bounding: a chunked round (sequential lax.map over vmap chunks)
must produce bit-identical results to the full-vmap round."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.aggregation import compute_weights, fedavg_strategy
from nanofed_tpu.core.types import ClientData
from nanofed_tpu.models import get_model
from nanofed_tpu.parallel import (
    build_round_step,
    init_server_state,
    make_mesh,
    shard_client_data,
)
from nanofed_tpu.trainer import TrainingConfig, stack_rngs


def _setup(devices):
    mesh = make_mesh(devices)
    model = get_model("mlp", in_features=8, hidden=4, num_classes=3)
    c, n = 16, 8  # 2 clients per device
    rng = np.random.default_rng(0)
    data = shard_client_data(
        ClientData(
            x=jnp.asarray(rng.normal(size=(c, n, 8)), jnp.float32),
            y=jnp.asarray(rng.integers(0, 3, size=(c, n))),
            mask=jnp.ones((c, n), jnp.float32),
        ),
        mesh,
    )
    training = TrainingConfig(batch_size=4, local_epochs=2, learning_rate=0.1)
    params = model.init(jax.random.key(0))
    return mesh, model, data, training, params


def test_chunked_equals_unchunked(devices):
    mesh, model, data, training, params = _setup(devices)
    strategy = fedavg_strategy()
    sos = init_server_state(strategy, params)
    weights = compute_weights(data.num_samples)
    rngs = stack_rngs(jax.random.key(7), 16)

    full = build_round_step(model.apply, training, mesh, strategy)(
        params, sos, data, weights, rngs
    )
    chunked = build_round_step(model.apply, training, mesh, strategy, client_chunk=1)(
        params, sos, data, weights, rngs
    )
    for a, b in zip(jax.tree.leaves(full.params), jax.tree.leaves(chunked.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in full.metrics:
        np.testing.assert_allclose(
            np.asarray(full.metrics[k]), np.asarray(chunked.metrics[k])
        )
    np.testing.assert_array_equal(
        np.asarray(full.client_metrics.loss), np.asarray(chunked.client_metrics.loss)
    )


def test_chunk_larger_than_local_count_is_full_vmap(devices):
    # chunk >= per-device client count degrades gracefully to the unchunked path.
    mesh, model, data, training, params = _setup(devices)
    strategy = fedavg_strategy()
    step = build_round_step(model.apply, training, mesh, strategy, client_chunk=64)
    sos = init_server_state(strategy, params)
    res = step(params, sos, data, compute_weights(data.num_samples),
               stack_rngs(jax.random.key(0), 16))
    assert np.isfinite(float(res.metrics["loss"]))


def test_chunking_bounds_compiled_peak_memory(devices):
    """The HBM claim behind client_chunk, MEASURED: XLA's compiled temp-buffer peak for
    a chunked round must be well below the full-vmap round's (SURVEY.md §7 "clients >>
    chips" — a full vmap materializes every client's activations at once; lax.map over
    k-wide chunks scales live activations with k)."""
    mesh = make_mesh(devices[:1])  # all clients resident on ONE device
    # Activation-dominated shape (the regime chunking is FOR): big per-client batches
    # through a small model, so live activations (clients x batch x hidden) dwarf the
    # per-client params that both paths materialize.
    model = get_model("mlp", in_features=8, hidden=128, num_classes=10)
    c, n = 64, 512
    rng = np.random.default_rng(0)
    data = shard_client_data(
        ClientData(
            x=jnp.asarray(rng.normal(size=(c, n, 8)), jnp.float32),
            y=jnp.asarray(rng.integers(0, 10, size=(c, n))),
            mask=jnp.ones((c, n), jnp.float32),
        ),
        mesh,
    )
    training = TrainingConfig(batch_size=512, local_epochs=1, learning_rate=0.1)
    params = model.init(jax.random.key(0))
    strategy = fedavg_strategy()
    sos = init_server_state(strategy, params)
    weights = compute_weights(data.num_samples)
    rngs = stack_rngs(jax.random.key(0), c)

    def peak_temp(client_chunk):
        step = build_round_step(
            model.apply, training, mesh, strategy, client_chunk=client_chunk
        )
        compiled = step.lower(params, sos, data, weights, rngs).compile()
        return compiled.memory_analysis().temp_size_in_bytes

    full, chunked = peak_temp(None), peak_temp(4)
    # 64 resident clients vs 4-wide chunks: require at least a 4x reduction in peak
    # temp allocation (in practice it is larger; the bound is deliberately loose so
    # XLA layout changes don't flake the test).
    assert chunked * 4 <= full, (chunked, full)


def test_chunk_must_divide(devices):
    # 24 clients over 8 devices = 3 per device; chunk 2 does not divide.
    mesh = make_mesh(devices)
    model = get_model("mlp", in_features=8, hidden=4, num_classes=3)
    c, n = 24, 8
    rng = np.random.default_rng(0)
    data = shard_client_data(
        ClientData(
            x=jnp.asarray(rng.normal(size=(c, n, 8)), jnp.float32),
            y=jnp.asarray(rng.integers(0, 3, size=(c, n))),
            mask=jnp.ones((c, n), jnp.float32),
        ),
        mesh,
    )
    training = TrainingConfig(batch_size=4, local_epochs=1, learning_rate=0.1)
    params = model.init(jax.random.key(0))
    strategy = fedavg_strategy()
    step = build_round_step(model.apply, training, mesh, strategy, client_chunk=2)
    sos = init_server_state(strategy, params)
    with pytest.raises(Exception):  # raised at trace time inside jit/shard_map
        jax.block_until_ready(
            step(params, sos, data, compute_weights(data.num_samples),
                 stack_rngs(jax.random.key(0), c)).params
        )


def test_streamed_dp_chunking_matches_materialized(devices):
    """The streaming chunk reduce under central DP must match the materializing path:
    same clipping, same uniform weights, same noise draw (the noise key is independent
    of the reduction layout)."""
    from nanofed_tpu.aggregation.privacy import PrivacyAwareAggregationConfig
    from nanofed_tpu.privacy import PrivacyConfig
    from nanofed_tpu.security.validation import ValidationConfig

    mesh, model, data, training, params = _setup(devices)
    strategy = fedavg_strategy()
    cp = PrivacyAwareAggregationConfig(privacy=PrivacyConfig(
        epsilon=8.0, delta=1e-5, max_gradient_norm=0.5, noise_multiplier=0.3))
    sos = init_server_state(strategy, params)
    weights = compute_weights(data.num_samples)
    rngs = stack_rngs(jax.random.key(3), 16)

    full = build_round_step(model.apply, training, mesh, strategy,
                            central_privacy=cp)(params, sos, data, weights, rngs)
    streamed = build_round_step(model.apply, training, mesh, strategy,
                                central_privacy=cp, client_chunk=1)(
        params, sos, data, weights, rngs)
    for a, b in zip(jax.tree.leaves(full.params), jax.tree.leaves(streamed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(full.metrics["loss"]),
                               np.asarray(streamed.metrics["loss"]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(full.update_sq_norms),
                               np.asarray(streamed.update_sq_norms), rtol=1e-5)

    # Chunking + validation takes the materializing path (cohort stats need all
    # clients); with every check loosened past rejection it must agree with the
    # streaming result.
    validated = build_round_step(
        model.apply, training, mesh, strategy, central_privacy=cp, client_chunk=1,
        validation=ValidationConfig(max_norm=1e6, z_score_threshold=1e6),
    )(params, sos, data, weights, rngs)
    for a, b in zip(jax.tree.leaves(full.params), jax.tree.leaves(validated.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_streamed_zero_weight_round_is_noop(devices):
    """All-dropout round through the STREAMING path leaves params + server state
    untouched (same contract the materializing path pins)."""
    mesh, model, data, training, params = _setup(devices)
    strategy = fedavg_strategy()
    sos = init_server_state(strategy, params)
    rngs = stack_rngs(jax.random.key(0), 16)
    res = build_round_step(model.apply, training, mesh, strategy, client_chunk=1)(
        params, sos, data, jnp.zeros((16,)), rngs)
    for a, b in zip(jax.tree.leaves(res.params), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(res.server_opt_state), jax.tree.leaves(sos)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_streaming_equivalence_property_sweep(devices, seed):
    """Property-style sweep (the reference's hand-rolled-property pattern,
    tests/unit/privacy/test_privacy_properties.py): for random client counts, chunk
    sizes, weights (including zeros), and epochs, the streamed reduce equals the
    materialized full-vmap reduce within float tolerance."""
    rng = np.random.default_rng(seed)
    n_dev = 8
    per_dev = int(rng.integers(2, 5))
    c = n_dev * per_dev
    # Proper divisors only: chunk == per_dev would degrade to the full-vmap path and
    # make the streamed-vs-materialized comparison vacuous.
    divisors = [d for d in range(1, per_dev) if per_dev % d == 0]
    chunk = int(rng.choice(divisors))
    n, feats = 8, int(rng.integers(4, 10))
    epochs = int(rng.integers(1, 4))

    mesh = make_mesh(devices)
    model = get_model("mlp", in_features=feats, hidden=6, num_classes=3)
    data = shard_client_data(
        ClientData(
            x=jnp.asarray(rng.normal(size=(c, n, feats)), jnp.float32),
            y=jnp.asarray(rng.integers(0, 3, size=(c, n))),
            mask=jnp.asarray(rng.random(size=(c, n)) > 0.2, jnp.float32),
        ),
        mesh,
    )
    training = TrainingConfig(batch_size=4, local_epochs=epochs, learning_rate=0.2)
    params = model.init(jax.random.key(seed))
    strategy = fedavg_strategy()
    sos = init_server_state(strategy, params)
    weights = compute_weights(data.num_samples) * jnp.asarray(
        rng.random(size=(c,)) > 0.25, jnp.float32
    )
    rngs = stack_rngs(jax.random.key(seed + 100), c)

    full = build_round_step(model.apply, training, mesh, strategy)(
        params, sos, data, weights, rngs)
    streamed = build_round_step(model.apply, training, mesh, strategy,
                                client_chunk=chunk)(params, sos, data, weights, rngs)
    for a, b in zip(jax.tree.leaves(full.params), jax.tree.leaves(streamed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(full.metrics["loss"]),
                               np.asarray(streamed.metrics["loss"]),
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(full.update_sq_norms),
                               np.asarray(streamed.update_sq_norms),
                               rtol=2e-5, atol=1e-7)
