"""DP integration with the SPMD round step: DP-SGD clients (``local_fit`` override) and
the central-DP reduce (``central_privacy``) inside ``jit(shard_map(...))`` on the 8-device
mesh — the TPU analog of ``tests/integration/test_privacy_integration.py``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.aggregation import (
    PrivacyAwareAggregationConfig,
    compute_weights,
    fedavg_strategy,
)
from nanofed_tpu.data import federate, synthetic_classification
from nanofed_tpu.models import get_model
from nanofed_tpu.parallel import (
    build_round_step,
    init_server_state,
    make_mesh,
    pad_clients,
    shard_client_data,
)
from nanofed_tpu.privacy import PrivacyConfig
from nanofed_tpu.trainer import TrainingConfig, make_private_local_fit, stack_rngs
from nanofed_tpu.utils.trees import tree_global_norm, tree_sub


def _setup(devices, num_clients=8, in_dim=8, classes=2):
    mesh = make_mesh(devices)
    model = get_model("linear", in_features=in_dim, num_classes=classes)
    ds = synthetic_classification(num_clients * 32, classes, (in_dim,), seed=0)
    data = federate(ds, num_clients=num_clients, scheme="iid", batch_size=8, seed=0)
    data = shard_client_data(pad_clients(data, num_clients), mesh)
    weights = compute_weights(jnp.asarray(np.asarray(data.mask).sum(axis=1)))
    return mesh, model, data, weights


def test_dp_sgd_clients_in_round_step(devices):
    mesh, model, data, weights = _setup(devices)
    tcfg = TrainingConfig(batch_size=8, local_epochs=1, learning_rate=0.1)
    fit = make_private_local_fit(
        model.apply, tcfg, PrivacyConfig(max_gradient_norm=1.0, noise_multiplier=0.3)
    )
    step = build_round_step(model.apply, tcfg, mesh, fedavg_strategy(), local_fit=fit)
    params = model.init(jax.random.key(0))
    sos = init_server_state(fedavg_strategy(), params)
    res = step(params, sos, data, weights, stack_rngs(jax.random.key(1), 8))
    assert np.isfinite(float(res.metrics["loss"]))
    assert float(tree_global_norm(tree_sub(res.params, params))) > 0
    # Deterministic under the same keys despite noise (counter-based PRNG).
    res2 = step(params, sos, data, weights, stack_rngs(jax.random.key(1), 8))
    np.testing.assert_array_equal(
        np.asarray(jax.flatten_util.ravel_pytree(res.params)[0]),
        np.asarray(jax.flatten_util.ravel_pytree(res2.params)[0]),
    )


def test_central_privacy_reduce(devices):
    mesh, model, data, weights = _setup(devices)
    tcfg = TrainingConfig(batch_size=8, local_epochs=1, learning_rate=0.1)
    pacfg = PrivacyAwareAggregationConfig(
        privacy=PrivacyConfig(max_gradient_norm=0.5, noise_multiplier=1e-6)
    )
    step = build_round_step(
        model.apply, tcfg, mesh, fedavg_strategy(), central_privacy=pacfg
    )
    params = model.init(jax.random.key(0))
    sos = init_server_state(fedavg_strategy(), params)
    res = step(params, sos, data, weights, stack_rngs(jax.random.key(1), 8))
    # With clip C and negligible noise the applied aggregate delta norm is <= C.
    delta_norm = float(tree_global_norm(tree_sub(res.params, params)))
    assert 0 < delta_norm <= 0.5 * 1.001


def test_central_privacy_noise_enters_update(devices):
    mesh, model, data, weights = _setup(devices)
    tcfg = TrainingConfig(batch_size=8, local_epochs=1, learning_rate=0.1)
    quiet = PrivacyAwareAggregationConfig(
        privacy=PrivacyConfig(max_gradient_norm=0.5, noise_multiplier=1e-6)
    )
    loud = PrivacyAwareAggregationConfig(
        privacy=PrivacyConfig(max_gradient_norm=0.5, noise_multiplier=5.0)
    )
    params = model.init(jax.random.key(0))
    sos = init_server_state(fedavg_strategy(), params)
    rngs = stack_rngs(jax.random.key(1), 8)
    out = {}
    for name, cfg in [("quiet", quiet), ("loud", loud)]:
        step = build_round_step(model.apply, tcfg, mesh, fedavg_strategy(), central_privacy=cfg)
        out[name] = step(params, sos, data, weights, rngs).params
    diff = float(tree_global_norm(tree_sub(out["quiet"], out["loud"])))
    assert diff > 1e-4


def test_zero_participation_with_privacy_is_noop(devices):
    """All-masked round must leave params untouched even on the DP path."""
    mesh, model, data, _ = _setup(devices)
    tcfg = TrainingConfig(batch_size=8, local_epochs=1, learning_rate=0.1)
    pacfg = PrivacyAwareAggregationConfig(
        privacy=PrivacyConfig(max_gradient_norm=0.5, noise_multiplier=1.0)
    )
    step = build_round_step(model.apply, tcfg, mesh, fedavg_strategy(), central_privacy=pacfg)
    params = model.init(jax.random.key(0))
    sos = init_server_state(fedavg_strategy(), params)
    res = step(params, sos, data, jnp.zeros(8), stack_rngs(jax.random.key(1), 8))
    np.testing.assert_array_equal(
        np.asarray(jax.flatten_util.ravel_pytree(res.params)[0]),
        np.asarray(jax.flatten_util.ravel_pytree(params)[0]),
    )
