"""fedlint rule fixtures: each rule flags its bad snippet at the right line,
leaves the good twin clean, and honors a reasoned suppression."""

from __future__ import annotations

import textwrap

from nanofed_tpu.analysis import lint_source


def _lint(src: str, module: str = "fixture"):
    return lint_source(textwrap.dedent(src), module=module)


def _codes(diags):
    return [d.code for d in diags]


# ---------------------------------------------------------------------------
# FED000 — suppressions must carry a reason
# ---------------------------------------------------------------------------


class TestFed000:
    def test_reasonless_suppression_is_flagged(self):
        diags = _lint(
            """
            import jax

            def sample(key):
                a = jax.random.uniform(key, (3,))
                b = jax.random.normal(key, (3,))  # fedlint: disable=FED003
                return a + b
            """
        )
        # The malformed suppression is flagged AND does not suppress: the
        # underlying FED003 finding survives.
        assert _codes(diags) == ["FED000", "FED003"]
        assert diags[0].line == 6

    def test_reasoned_suppression_is_honored(self):
        diags = _lint(
            """
            import jax

            def sample(key):
                a = jax.random.uniform(key, (3,))
                b = jax.random.normal(key, (3,))  # fedlint: disable=FED003 (correlated on purpose: antithetic pair)
                return a + b
            """
        )
        assert diags == []


# ---------------------------------------------------------------------------
# FED001 — host sync in traced scope / hot path
# ---------------------------------------------------------------------------


class TestFed001:
    def test_float_cast_of_traced_value_flagged(self):
        diags = _lint(
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(x):
                y = jnp.sum(x)
                return float(y)
            """
        )
        assert _codes(diags) == ["FED001"]
        assert diags[0].line == 8

    def test_item_and_device_get_flagged_in_shard_map_body(self):
        diags = _lint(
            """
            import jax
            from nanofed_tpu.parallel.mesh import shard_map

            def body(x):
                host = jax.device_get(x)
                return x.sum().item()

            program = shard_map(body, mesh=None, in_specs=(), out_specs=())
            """
        )
        assert _codes(diags) == ["FED001", "FED001"]
        assert [d.line for d in diags] == [6, 7]

    def test_np_asarray_flagged_via_call_edge_propagation(self):
        # helper is traced because the scan BODY calls it — the call-edge
        # propagation the rule catalogue promises.
        diags = _lint(
            """
            import jax
            import numpy as np
            from jax import lax

            def helper(x):
                return np.asarray(x)

            def scanned(carry, x):
                return carry, helper(x)

            def run(xs):
                return lax.scan(scanned, 0.0, xs)
            """
        )
        assert _codes(diags) == ["FED001"]
        assert diags[0].line == 7

    def test_float_on_static_config_is_clean(self):
        diags = _lint(
            """
            import jax
            import jax.numpy as jnp

            def make(step_size):
                @jax.jit
                def step(x):
                    lr = float(step_size)
                    return x * lr
                return step
            """
        )
        assert diags == []

    def test_host_sync_outside_traced_scope_is_clean(self):
        diags = _lint(
            """
            import jax
            import numpy as np

            def fetch(x):
                return np.asarray(jax.device_get(x))
            """
        )
        assert diags == []

    def test_hot_path_block_until_ready_needs_suppression(self):
        src = """
        import jax

        def dispatch(params):
            jax.block_until_ready(params)
        """
        diags = _lint(src, module="nanofed_tpu.orchestration.fake")
        assert _codes(diags) == ["FED001"]
        assert diags[0].line == 5
        # The same module with a documented suppression is clean.
        sup = src.replace(
            "jax.block_until_ready(params)",
            "jax.block_until_ready(params)  "
            "# fedlint: disable=FED001 (block-boundary sync)",
        )
        assert _lint(sup, module="nanofed_tpu.orchestration.fake") == []
        # Outside the hot-path modules the non-traced call is clean.
        assert _lint(src, module="somewhere.else") == []


# ---------------------------------------------------------------------------
# FED002 — Python control flow on traced values
# ---------------------------------------------------------------------------


class TestFed002:
    def test_if_on_traced_value_flagged(self):
        diags = _lint(
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(x):
                s = jnp.sum(x)
                if s > 0:
                    return s
                return -s
            """
        )
        assert _codes(diags) == ["FED002"]
        assert diags[0].line == 8

    def test_while_on_traced_value_flagged(self):
        diags = _lint(
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(x):
                while jnp.max(x) > 1.0:
                    x = x * 0.5
                return x
            """
        )
        assert _codes(diags) == ["FED002"]
        assert diags[0].line == 7

    def test_static_branching_is_clean(self):
        diags = _lint(
            """
            import jax
            import jax.numpy as jnp

            def make(use_momentum, chunk):
                @jax.jit
                def step(x, mask):
                    n = x.shape[0]
                    if use_momentum:
                        x = x * 2
                    if chunk is not None and n % chunk != 0:
                        raise ValueError("bad chunk")
                    if mask is None:
                        mask = jnp.ones(n)
                    return x * mask
                return step
            """
        )
        assert diags == []

    def test_suppression_honored(self):
        diags = _lint(
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(x):
                s = jnp.sum(x)
                if s > 0:  # fedlint: disable=FED002 (concretization accepted: debug-only path)
                    return s
                return -s
            """
        )
        assert diags == []


# ---------------------------------------------------------------------------
# FED003 — PRNG key reuse
# ---------------------------------------------------------------------------


class TestFed003:
    def test_reuse_flagged_at_second_consumption(self):
        diags = _lint(
            """
            import jax

            def sample(key):
                a = jax.random.uniform(key, (3,))
                b = jax.random.normal(key, (3,))
                return a + b
            """
        )
        assert _codes(diags) == ["FED003"]
        assert diags[0].line == 6
        assert "'key'" in diags[0].message

    def test_split_between_draws_is_clean(self):
        diags = _lint(
            """
            import jax

            def sample(key):
                k1, k2 = jax.random.split(key)
                a = jax.random.uniform(k1, (3,))
                b = jax.random.normal(k2, (3,))
                return a + b
            """
        )
        assert diags == []

    def test_fold_in_derivation_is_clean(self):
        diags = _lint(
            """
            import jax

            def sample(key, rounds):
                outs = []
                for r in range(rounds):
                    k = jax.random.fold_in(key, r)
                    outs.append(jax.random.uniform(k, (3,)))
                return outs
            """
        )
        assert diags == []

    def test_cross_iteration_reuse_flagged(self):
        diags = _lint(
            """
            import jax

            def sample(key, rounds):
                outs = []
                for r in range(rounds):
                    outs.append(jax.random.uniform(key, (3,)))
                return outs
            """
        )
        assert _codes(diags) == ["FED003"]
        assert diags[0].line == 7

    def test_exclusive_branches_are_clean(self):
        diags = _lint(
            """
            import jax

            def sample(key, coin):
                if coin:
                    return jax.random.uniform(key, (3,))
                else:
                    return jax.random.normal(key, (3,))
            """
        )
        assert diags == []

    def test_suppression_honored(self):
        diags = _lint(
            """
            import jax

            def sample(key):
                a = jax.random.uniform(key, (3,))
                b = jax.random.normal(key, (3,))  # fedlint: disable=FED003 (paired draw reuses the key by design)
                return a + b
            """
        )
        assert diags == []


# ---------------------------------------------------------------------------
# FED004 — params-shaped jit without donation
# ---------------------------------------------------------------------------


class TestFed004:
    def test_lambda_jit_without_donation_flagged(self):
        diags = _lint(
            """
            import jax

            apply_update = jax.jit(lambda params, delta: params)
            """
        )
        assert _codes(diags) == ["FED004"]
        assert diags[0].line == 4

    def test_decorated_def_without_donation_flagged(self):
        diags = _lint(
            """
            import jax
            from functools import partial

            @jax.jit
            def apply_update(params, delta):
                return params
            """
        )
        assert _codes(diags) == ["FED004"]
        assert diags[0].line == 5

    def test_donated_variants_are_clean(self):
        diags = _lint(
            """
            import jax
            from functools import partial

            update_a = jax.jit(lambda params, d: params, donate_argnums=(0,))

            @partial(jax.jit, donate_argnums=(0,))
            def update_b(params, d):
                return params

            gather = jax.jit(lambda data, idx: data)
            """
        )
        assert diags == []

    def test_suppression_honored(self):
        diags = _lint(
            """
            import jax

            # fedlint: disable=FED004 (params reused by the caller after eval)
            evaluate = jax.jit(lambda params, data: params)
            """
        )
        assert diags == []


# ---------------------------------------------------------------------------
# FED005 — unlocked mutation of lock-guarded state
# ---------------------------------------------------------------------------

_SERVER_TEMPLATE = """
import asyncio


class Server:
    def __init__(self):
        self._lock = asyncio.Lock()
        self._updates = {}
        self._round = 0

    async def submit(self, cid, update):
        async with self._lock:
            self._updates[cid] = update

__EXTRA__
"""


def _server_src(extra: str) -> str:
    return _SERVER_TEMPLATE.replace("__EXTRA__", extra)


class TestFed005:
    def test_unlocked_mutation_of_guarded_attr_flagged(self):
        diags = _lint(_server_src("""
    def reset(self):
        self._updates.clear()
"""
        ))
        assert _codes(diags) == ["FED005"]
        assert "_updates" in diags[0].message and "reset" in diags[0].message

    def test_locked_everywhere_is_clean(self):
        diags = _lint(_server_src("""
    async def reset(self):
        async with self._lock:
            self._updates.clear()
"""
        ))
        assert diags == []

    def test_unguarded_attr_is_not_flagged(self):
        # _round is never mutated under the lock anywhere -> not shared-locked
        # state; mutating it unlocked is out of this rule's scope.
        diags = _lint(_server_src("""
    def advance(self):
        self._round += 1
"""
        ))
        assert diags == []

    def test_suppression_honored(self):
        diags = _lint(_server_src("""
    def reset(self):
        # fedlint: disable=FED005 (sync method on the event loop: no await point, handlers cannot interleave)
        self._updates.clear()
"""
        ))
        assert diags == []


# ---------------------------------------------------------------------------
# FED006 — blocking calls in async code
# ---------------------------------------------------------------------------


class TestFed006:
    def test_time_sleep_flagged(self):
        diags = _lint(
            """
            import time

            async def poll(server):
                time.sleep(1.0)
                return server.done
            """
        )
        assert _codes(diags) == ["FED006"]
        assert diags[0].line == 5

    def test_sync_file_io_flagged(self):
        diags = _lint(
            """
            async def dump(path, payload):
                with open(path, "w") as f:
                    f.write(payload)
                path.write_text(payload)
            """
        )
        assert _codes(diags) == ["FED006", "FED006"]
        assert [d.line for d in diags] == [3, 5]

    def test_asyncio_sleep_and_to_thread_are_clean(self):
        diags = _lint(
            """
            import asyncio

            async def poll(server):
                await asyncio.sleep(1.0)
                return await asyncio.to_thread(server.read)
            """
        )
        assert diags == []

    def test_sync_function_is_out_of_scope(self):
        diags = _lint(
            """
            import time

            def poll(server):
                time.sleep(1.0)
                return open("/tmp/x").read()
            """
        )
        assert diags == []

    def test_suppression_honored(self):
        diags = _lint(
            """
            import time

            async def poll(server):
                time.sleep(0.001)  # fedlint: disable=FED006 (sub-ms backoff, measured harmless)
                return server.done
            """
        )
        assert diags == []


class TestFed006UnboundedAwait:
    """The PR-6 extension: request handlers in communication/ must bound
    request-body awaits with asyncio.wait_for (slowloris defense)."""

    HANDLER = """
        class Server:
            async def _handle_update(self, request):
                body = await request.read()
                return body
        """

    def test_unbounded_read_in_communication_handler_flagged(self):
        diags = _lint(self.HANDLER, module="nanofed_tpu.communication.fake")
        assert _codes(diags) == ["FED006"]
        assert "asyncio.wait_for" in diags[0].message

    def test_json_and_text_also_flagged(self):
        diags = _lint(
            """
            class Server:
                async def _handle_register(self, request):
                    a = await request.json()
                    b = await request.text()
                    return a, b
            """,
            module="nanofed_tpu.communication.fake",
        )
        assert _codes(diags) == ["FED006", "FED006"]

    def test_wait_for_wrapped_read_is_clean(self):
        diags = _lint(
            """
            import asyncio

            class Server:
                async def _handle_update(self, request):
                    body = await asyncio.wait_for(request.read(), timeout=30.0)
                    return body
            """,
            module="nanofed_tpu.communication.fake",
        )
        assert diags == []

    def test_helper_indirection_is_clean(self):
        # The production shape: handlers delegate to a bounded _read_body.
        diags = _lint(
            """
            class Server:
                async def _handle_update(self, request):
                    body = await self._read_body(request)
                    return body
            """,
            module="nanofed_tpu.communication.fake",
        )
        assert diags == []

    def test_non_handler_and_other_packages_out_of_scope(self):
        # A client-side poller (not _handle*) and the same code outside
        # communication/ are both out of the rule's scope.
        diags = _lint(
            """
            class Client:
                async def fetch(self, resp):
                    return await resp.read()
            """,
            module="nanofed_tpu.communication.fake",
        )
        assert diags == []
        assert _lint(self.HANDLER, module="nanofed_tpu.orchestration.fake") == []


# ---------------------------------------------------------------------------
# FED007 — raw collective with a hardcoded axis-name string
# ---------------------------------------------------------------------------


class TestFed007:
    def test_hardcoded_axis_string_flagged(self):
        diags = _lint(
            """
            from jax import lax

            def reduce_update(u):
                return lax.psum(u, "clients")
            """,
            module="nanofed_tpu.parallel.fixture",
        )
        assert _codes(diags) == ["FED007"]
        assert diags[0].line == 5

    def test_keyword_axis_and_axis_index_flagged(self):
        diags = _lint(
            """
            from jax import lax

            def gather(u):
                i = lax.axis_index("clients")
                return lax.all_gather(u, axis_name="clients"), i
            """,
            module="nanofed_tpu.aggregation.fixture",
        )
        assert _codes(diags) == ["FED007", "FED007"]

    def test_axis_tuple_with_string_flagged(self):
        diags = _lint(
            """
            from jax import lax
            from nanofed_tpu.parallel.mesh import CLIENT_AXIS

            def hierarchical(u):
                return lax.psum(u, (CLIENT_AXIS, "hosts"))
            """,
            module="nanofed_tpu.parallel.fixture",
        )
        assert _codes(diags) == ["FED007"]

    def test_axis_constant_is_clean(self):
        diags = _lint(
            """
            from jax import lax
            from nanofed_tpu.parallel.mesh import CLIENT_AXIS

            def reduce_update(u, layout):
                a = lax.psum(u, CLIENT_AXIS)
                b = lax.pmean(u, layout.client_axis)
                return a + b
            """,
            module="nanofed_tpu.parallel.fixture",
        )
        assert diags == []

    def test_other_packages_out_of_scope(self):
        # MeshLayout does not own axis names outside parallel/aggregation —
        # a model-layer experiment may hardcode freely.
        diags = _lint(
            """
            from jax import lax

            def reduce_update(u):
                return lax.psum(u, "clients")
            """,
            module="nanofed_tpu.models.fixture",
        )
        assert diags == []

    def test_non_lax_namesake_is_clean(self):
        diags = _lint(
            """
            def reduce_update(u, layout):
                return layout.psum(u, "clients")
            """,
            module="nanofed_tpu.parallel.fixture",
        )
        assert diags == []

    def test_suppression_honored(self):
        diags = _lint(
            """
            from jax import lax

            def reduce_update(u):
                return lax.psum(u, "clients")  # fedlint: disable=FED007 (single-mesh microbenchmark: axis fixed by design)
            """,
            module="nanofed_tpu.parallel.fixture",
        )
        assert diags == []


# ---------------------------------------------------------------------------
# FED008 — fire-and-forget task without an exception sink
# ---------------------------------------------------------------------------


class TestFed008:
    def test_dropped_result_flagged(self):
        diags = _lint(
            """
            import asyncio

            async def kick(coro):
                asyncio.create_task(coro)
            """
        )
        assert _codes(diags) == ["FED008"]
        assert "result dropped" in diags[0].message

    def test_assigned_but_never_sunk_flagged(self):
        diags = _lint(
            """
            import asyncio

            async def kick(coro):
                task = asyncio.create_task(coro)
                await asyncio.sleep(1)
            """
        )
        assert _codes(diags) == ["FED008"]
        assert diags[0].line == 5

    def test_done_callback_is_a_sink(self):
        diags = _lint(
            """
            import asyncio

            async def kick(coro, log_exc):
                task = asyncio.create_task(coro)
                task.add_done_callback(log_exc)
                await asyncio.sleep(1)
            """
        )
        assert diags == []

    def test_plain_await_is_a_sink(self):
        diags = _lint(
            """
            import asyncio

            async def kick(coro):
                task = asyncio.create_task(coro)
                return await task
            """
        )
        assert diags == []

    def test_broadly_swallowed_await_is_not_a_sink(self):
        # The timeout-path idiom: `except Exception: pass` retrieves the
        # exception only to drop it — the traceback still vanishes.
        diags = _lint(
            """
            import asyncio

            async def kick(coro):
                task = asyncio.create_task(coro)
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
            """
        )
        assert _codes(diags) == ["FED008"]

    def test_gather_and_wait_count_as_sinks(self):
        diags = _lint(
            """
            import asyncio

            async def kick(a, b):
                t1 = asyncio.create_task(a)
                t2 = asyncio.ensure_future(b)
                await asyncio.gather(t1)
                done, _ = await asyncio.wait({t2})
            """
        )
        assert diags == []

    def test_self_attribute_sunk_in_other_method_is_clean(self):
        diags = _lint(
            """
            import asyncio

            class Tracker:
                def start(self, coro):
                    self._task = asyncio.create_task(coro)

                async def stop(self):
                    await self._task
            """
        )
        assert diags == []

    def test_self_attribute_never_sunk_flagged(self):
        diags = _lint(
            """
            import asyncio

            class Tracker:
                def start(self, coro):
                    self._task = asyncio.create_task(coro)
            """
        )
        assert _codes(diags) == ["FED008"]

    def test_suppression_honored(self):
        diags = _lint(
            """
            import asyncio

            async def kick(coro):
                asyncio.create_task(coro)  # fedlint: disable=FED008 (daemon heartbeat: failure is surfaced by the watchdog)
            """
        )
        assert diags == []


# ---------------------------------------------------------------------------
# FED009 — blocking file I/O inside async code
# ---------------------------------------------------------------------------


class TestFed009:
    def test_json_dump_in_async_def_flagged(self):
        diags = _lint(
            """
            import json

            async def persist(state, f):
                json.dump(state, f)
            """
        )
        assert _codes(diags) == ["FED009"]
        assert diags[0].line == 5

    def test_path_method_flagged(self):
        diags = _lint(
            """
            async def cleanup(path):
                path.unlink()
            """
        )
        assert _codes(diags) == ["FED009"]

    def test_nested_def_payload_is_exempt(self):
        # The fix idiom: the blocking body lives in a nested def shipped to
        # a thread — the async frame itself never blocks.
        diags = _lint(
            """
            import asyncio
            import json

            async def persist(state, f):
                def _write():
                    json.dump(state, f)
                await asyncio.to_thread(_write)
            """
        )
        assert diags == []

    def test_sync_function_is_out_of_scope(self):
        diags = _lint(
            """
            import json

            def persist(state, f):
                json.dump(state, f)
            """
        )
        assert diags == []

    def test_suppression_honored(self):
        diags = _lint(
            """
            import os

            async def rotate(src, dst):
                os.replace(src, dst)  # fedlint: disable=FED009 (atomic rename on tmpfs: sub-microsecond, cheaper than a thread hop)
            """
        )
        assert diags == []


# ---------------------------------------------------------------------------
# FED010 — wall-clock reads in Clock-injected subsystems
# ---------------------------------------------------------------------------


class TestFed010:
    def test_time_time_in_communication_flagged(self):
        diags = _lint(
            """
            import time

            def stamp():
                return time.time()
            """,
            module="nanofed_tpu.communication.fixture",
        )
        assert _codes(diags) == ["FED010"]
        assert diags[0].line == 5

    def test_datetime_now_in_service_flagged(self):
        diags = _lint(
            """
            import datetime

            def stamp():
                return datetime.datetime.now()
            """,
            module="nanofed_tpu.service.fixture",
        )
        assert _codes(diags) == ["FED010"]

    def test_injected_clock_is_clean(self):
        diags = _lint(
            """
            def stamp(clock):
                return clock.now()
            """,
            module="nanofed_tpu.loadgen.fixture",
        )
        assert diags == []

    def test_other_packages_out_of_scope(self):
        diags = _lint(
            """
            import time

            def stamp():
                return time.time()
            """,
            module="nanofed_tpu.models.fixture",
        )
        assert diags == []

    def test_suppression_honored(self):
        diags = _lint(
            """
            import time

            def stamp():
                return time.time()  # fedlint: disable=FED010 (forensics-only stamp: aligns the jsonl with external logs)
            """,
            module="nanofed_tpu.observability.fixture",
        )
        assert diags == []


# ---------------------------------------------------------------------------
# Traced-scope seeding v2: pallas_call + cross-module call edges
# ---------------------------------------------------------------------------


class TestTracedSeedingV2:
    def test_pallas_kernel_is_traced(self):
        diags = _lint(
            """
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                v = x_ref[...]
                o_ref[...] = v * v.sum().item()

            def run(x, shape):
                return pl.pallas_call(_kernel, out_shape=shape)(x)
            """
        )
        assert _codes(diags) == ["FED001"]
        assert diags[0].line == 6

    def test_call_edge_propagates_into_fleet_module(self, tmp_path):
        # Cross-file: a fleet-module round body is passed to shard_map and
        # delegates to a helper in a sibling module — traced-ness follows the
        # import edge, so the helper's host sync is flagged in ITS file.
        from nanofed_tpu.analysis import lint_paths

        pkg = tmp_path / "nanofed_tpu" / "fleet"
        pkg.mkdir(parents=True)
        (pkg / "helper.py").write_text(
            "def scale_update(u):\n"
            "    return u * u.sum().item()\n"
        )
        (pkg / "runner.py").write_text(
            "from nanofed_tpu.fleet.helper import scale_update\n"
            "from nanofed_tpu.parallel.mesh import shard_map\n"
            "\n"
            "def _body(u):\n"
            "    return scale_update(u)\n"
            "\n"
            "def build(mesh, spec):\n"
            "    return shard_map(_body, mesh=mesh, in_specs=(spec,),\n"
            "                     out_specs=spec)\n"
        )
        diags = lint_paths([tmp_path / "nanofed_tpu"])
        assert _codes(diags) == ["FED001"]
        assert diags[0].path.endswith("helper.py")
        assert diags[0].line == 2


# ---------------------------------------------------------------------------
# Engine plumbing
# ---------------------------------------------------------------------------


class TestEngine:
    def test_file_level_suppression(self):
        diags = _lint(
            """
            # fedlint: disable-file=FED003 (fixture exercising correlated draws)
            import jax

            def sample(key):
                a = jax.random.uniform(key, (3,))
                b = jax.random.normal(key, (3,))
                return a + b
            """
        )
        assert diags == []

    def test_select_filters_rules(self):
        from nanofed_tpu.analysis.fedlint import lint_source as ls

        src = textwrap.dedent(
            """
            import jax

            def sample(key):
                a = jax.random.uniform(key, (3,))
                b = jax.random.normal(key, (3,))
                return a + b

            update = jax.jit(lambda params, d: params)
            """
        )
        assert _codes(ls(src, select={"FED004"})) == ["FED004"]
        assert _codes(ls(src)) == ["FED003", "FED004"]

    def test_render_text_summarizes(self):
        from nanofed_tpu.analysis import render_text

        diags = _lint(
            """
            import jax

            def sample(key):
                a = jax.random.uniform(key, (3,))
                b = jax.random.normal(key, (3,))
                return a + b
            """
        )
        text = render_text(diags)
        assert "FED003" in text and "1 finding" in text
        assert render_text([]) == "fedlint: clean"

    def test_cli_entry_point(self, tmp_path):
        import subprocess
        import sys

        bad = tmp_path / "bad.py"
        bad.write_text(
            "import jax\n\n"
            "def sample(key):\n"
            "    a = jax.random.uniform(key, (3,))\n"
            "    b = jax.random.normal(key, (3,))\n"
            "    return a + b\n"
        )
        proc = subprocess.run(
            [sys.executable, "-m", "nanofed_tpu.analysis", str(bad)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 1
        assert "FED003" in proc.stdout
        good = tmp_path / "good.py"
        good.write_text("x = 1\n")
        proc = subprocess.run(
            [sys.executable, "-m", "nanofed_tpu.analysis", str(good)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0
        assert "clean" in proc.stdout
