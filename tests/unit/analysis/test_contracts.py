"""Runtime-contract unit tests: eval_shape validation of round programs and the
strict-mode transfer guard."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanofed_tpu.analysis import (
    ContractViolation,
    check_round_block,
    check_round_step,
    strict_mode,
)
from nanofed_tpu.parallel.round_step import RoundStepResult


def _sds(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _contract_args(n_clients=4, dim=3):
    params = {"w": _sds((dim,)), "b": _sds(())}
    sos = {"momentum": _sds((dim,))}
    data = {"x": _sds((n_clients, 8, dim)), "y": _sds((n_clients, 8), jnp.int32)}
    weights = _sds((n_clients,))
    rngs = jax.eval_shape(lambda: jax.random.split(jax.random.key(0), n_clients))
    return params, sos, data, weights, rngs


def _good_step(params, sos, data, weights, rngs, lr_scale=1.0):
    return RoundStepResult(
        params=params,
        server_opt_state=sos,
        metrics={"loss": jnp.zeros(()), "accuracy": jnp.zeros(())},
        client_metrics={"loss": jnp.zeros(weights.shape[0])},
        update_sq_norms=jnp.zeros(weights.shape[0]),
    )


class TestCheckRoundStep:
    def test_conforming_step_reports_ok(self):
        params, sos, data, weights, rngs = _contract_args()
        report = check_round_step(_good_step, params, sos, data, weights, rngs)
        assert report["program"] == "round_step"
        assert report["clients"] == 4
        assert report["metrics"] == ["accuracy", "loss"]

    def test_param_shape_drift_is_named(self):
        params, sos, data, weights, rngs = _contract_args()

        def drifting(params, sos, data, weights, rngs, lr_scale=1.0):
            res = _good_step(params, sos, data, weights, rngs)
            return res._replace(params={"w": params["w"][None], "b": params["b"]})

        with pytest.raises(ContractViolation, match=r"params\['w'\]"):
            check_round_step(drifting, params, sos, data, weights, rngs)

    def test_structure_drift_is_refused(self):
        params, sos, data, weights, rngs = _contract_args()

        def restructuring(params, sos, data, weights, rngs, lr_scale=1.0):
            res = _good_step(params, sos, data, weights, rngs)
            return res._replace(params={"w": params["w"]})  # dropped a leaf

        with pytest.raises(ContractViolation, match="tree structure"):
            check_round_step(restructuring, params, sos, data, weights, rngs)

    def test_nonscalar_metric_is_refused(self):
        params, sos, data, weights, rngs = _contract_args()

        def leaky(params, sos, data, weights, rngs, lr_scale=1.0):
            res = _good_step(params, sos, data, weights, rngs)
            return res._replace(metrics={"loss": jnp.zeros(weights.shape[0])})

        with pytest.raises(ContractViolation, match="weighted scalars"):
            check_round_step(leaky, params, sos, data, weights, rngs)

    def test_wrong_client_width_is_refused(self):
        params, sos, data, weights, rngs = _contract_args()

        def truncating(params, sos, data, weights, rngs, lr_scale=1.0):
            res = _good_step(params, sos, data, weights, rngs)
            return res._replace(update_sq_norms=jnp.zeros(2))

        with pytest.raises(ContractViolation, match="update_sq_norms"):
            check_round_step(truncating, params, sos, data, weights, rngs)

    def test_nothing_executes(self):
        # eval_shape only traces: a step that would crash at runtime but traces
        # fine passes shape validation without ever running.
        params, sos, data, weights, rngs = _contract_args()
        ran = []

        def effectful(params, sos, data, weights, rngs, lr_scale=1.0):
            ran.append(True)  # traced once — but no array math executes
            return _good_step(params, sos, data, weights, rngs)

        check_round_step(effectful, params, sos, data, weights, rngs)
        assert ran  # traced
        # The output leaves were abstract the whole way — nothing concrete.


class TestCheckRoundBlock:
    def test_real_round_block_conforms(self):
        from nanofed_tpu.data import pack_clients, synthetic_classification
        from nanofed_tpu.models import get_model
        from nanofed_tpu.parallel import (
            build_round_block,
            init_server_state,
            make_mesh,
            pad_client_count,
            pad_clients,
            shard_client_data,
            stack_round_keys,
        )
        from nanofed_tpu.aggregation import fedavg_strategy
        from nanofed_tpu.trainer import TrainingConfig

        model = get_model("linear", in_features=6, num_classes=3)
        mesh = make_mesh()
        n_dev = len(mesh.devices.flat)
        ds = synthetic_classification(32, 3, (6,), seed=0)
        data = pack_clients(ds, [np.arange(i * 8, (i + 1) * 8) for i in range(4)],
                            batch_size=8)
        padded = pad_client_count(4, n_dev)
        data = shard_client_data(pad_clients(data, padded), mesh)
        num_samples = jnp.asarray(np.asarray(data.mask).sum(axis=1), jnp.float32)
        strategy = fedavg_strategy()
        block = build_round_block(
            model.apply, TrainingConfig(batch_size=8, local_epochs=1), mesh,
            strategy, num_clients=4, padded_clients=padded,
        )
        params = model.init(jax.random.key(0))
        sos = init_server_state(strategy, params)
        rpb = 3
        report = check_round_block(
            block, params, sos, data, num_samples,
            jax.eval_shape(lambda: stack_round_keys(0, list(range(rpb)))),
            jax.ShapeDtypeStruct((rpb,), jnp.float32),
            cohort_mask=jax.ShapeDtypeStruct((rpb, padded), jnp.float32),
        )
        assert report["program"] == "round_block"
        assert report["rounds"] == rpb
        assert report["client_detail"] is True


class TestStrictMode:
    def test_device_resident_dispatch_passes(self):
        f = jax.jit(lambda x: x * 2)
        x = jnp.ones((8,))
        _ = f(x)  # compile outside the guard
        with strict_mode():
            y = f(x)
        assert float(y[0]) == 2.0

    def test_implicit_h2d_into_jit_raises(self):
        f = jax.jit(lambda x: x * 2)
        _ = f(jnp.ones((8,)))
        with pytest.raises(Exception, match="[Dd]isallow"):
            with strict_mode():
                f(np.ones((8,), np.float32))

    def test_guard_scopes_to_the_context(self):
        f = jax.jit(lambda x: x * 2)
        _ = f(jnp.ones((8,)))
        with strict_mode():
            pass
        # Outside the context implicit transfers are allowed again.
        y = f(np.ones((8,), np.float32))
        assert float(y[0]) == 2.0
