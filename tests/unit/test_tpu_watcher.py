"""The session-long tunnel watcher (scripts/tpu_watcher.py): probe loop mechanics.

Round 4's lesson was that a single early probe leaves a recovered tunnel unnoticed for
hours; the watcher's contract is (a) every attempt leaves a timestamped log line, (b)
the FIRST successful probe fires the campaign exactly once with --skip-probe (the
probe just passed — burning another 150 s probe budget would be waste), and (c) a
session of failures still exits with a log that proves the tunnel was re-checked.
Probes and the campaign are subprocesses, so they are stubbed at subprocess level —
no accelerator needed.
"""

import importlib.util
import sys
from pathlib import Path
from types import SimpleNamespace

REPO = Path(__file__).resolve().parent.parent.parent


def _load_watcher():
    spec = importlib.util.spec_from_file_location(
        "tpu_watcher", REPO / "scripts" / "tpu_watcher.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run(monkeypatch, tmp_path, probe_outcomes, argv):
    """Drive watcher.main() with scripted probe outcomes; returns (rc, calls, log)."""
    watcher = _load_watcher()
    monkeypatch.setattr(watcher, "REPO", tmp_path)
    (tmp_path / "runs").mkdir()
    calls = []
    outcomes = iter(probe_outcomes)

    monkeypatch.setattr(watcher, "measurement_running", lambda: False)

    def fake_run(argv_, capture_output=None, text=None, timeout=None):
        calls.append(("run", argv_))
        ok = next(outcomes)
        return SimpleNamespace(
            stdout='{"probe": "ok", "platform": "tpu"}' if ok
            else '{"probe": "timeout"}',
            returncode=0 if ok else 3,
        )

    def fake_call(argv_):
        calls.append(("call", argv_))
        return 0

    monkeypatch.setattr(watcher.subprocess, "run", fake_run)
    monkeypatch.setattr(watcher.subprocess, "call", fake_call)
    monkeypatch.setattr(watcher.time, "sleep", lambda s: None)
    monkeypatch.setattr(sys, "argv", ["tpu_watcher.py", *argv])
    rc = watcher.main()
    log = (tmp_path / "runs" / "tpu_campaign_t.log").read_text()
    return rc, calls, log


def test_first_success_fires_campaign_once_and_stops(monkeypatch, tmp_path):
    rc, calls, log = _run(
        monkeypatch, tmp_path, [False, False, True],
        ["--tag", "t", "--interval", "0.01", "--max-hours", "1"],
    )
    assert rc == 0
    probes = [c for c in calls if c[0] == "run"]
    fires = [c for c in calls if c[0] == "call"]
    assert len(probes) == 3
    assert len(fires) == 1  # exactly once, on FIRST success
    campaign_argv = fires[0][1]
    assert any("tpu_campaign.py" in str(a) for a in campaign_argv)
    assert "--skip-probe" in campaign_argv  # the probe just passed
    assert "--tag" in campaign_argv and "t" in campaign_argv
    # Every attempt logged, plus the success and the campaign result.
    assert log.count("probe #") == 3
    assert "probe #3: OK" in log
    assert "campaign finished rc=0" in log


def test_probe_defers_while_a_measurement_owns_the_core(monkeypatch, tmp_path):
    """A 150 s backend-init probe mid-benchmark distorts round times ~2x on this
    1-core host; the watcher must wait the cycle out, then resume probing."""
    watcher = _load_watcher()
    monkeypatch.setattr(watcher, "REPO", tmp_path)
    (tmp_path / "runs").mkdir()
    busy = iter([True, False])  # busy once, then clear
    monkeypatch.setattr(watcher, "measurement_running",
                        lambda: next(busy, False))
    probes = []

    def fake_run(argv_, capture_output=None, text=None, timeout=None):
        probes.append(argv_)
        return SimpleNamespace(stdout='{"probe": "ok"}', returncode=0)

    monkeypatch.setattr(watcher.subprocess, "run", fake_run)
    monkeypatch.setattr(watcher.subprocess, "call", lambda argv_: 0)
    monkeypatch.setattr(watcher.time, "sleep", lambda s: None)
    monkeypatch.setattr(sys, "argv",
                        ["tpu_watcher.py", "--tag", "t", "--interval", "0.01"])
    assert watcher.main() == 0
    assert len(probes) == 1  # deferred cycle never probed
    log = (tmp_path / "runs" / "tpu_campaign_t.log").read_text()
    assert "deferring the probe" in log
    assert "probe #1: OK" in log


def test_all_failures_exit_2_with_full_probe_record(monkeypatch, tmp_path):
    rc, calls, log = _run(
        monkeypatch, tmp_path, [False] * 50,
        ["--tag", "t", "--interval", "0.0001", "--max-hours", "1e-7"],
    )
    assert rc == 2
    assert not [c for c in calls if c[0] == "call"]  # campaign never fired
    # The round still leaves a timestamped record of every attempt (the r04 gap).
    assert log.count("probe #") >= 1
    assert "gave up" in log
