"""Direct unit coverage for the fault injectors (nanofed_tpu.faults.injector
and .host_injector): the one-shot consumption edges a chaos run's correctness
rests on — count exhaustion across retries, multiple kinds firing in the same
round against one client, per-kind metric labels — plus the ChaosClient
boundary actions against a stub client (no aiohttp, no server)."""

import asyncio

import pytest

from nanofed_tpu.faults import (
    FAULT_KINDS,
    ChaosSchedule,
    FaultEvent,
    FaultPlan,
    HostChaosInjector,
)
from nanofed_tpu.faults.injector import ChaosClient, _flip_bits
from nanofed_tpu.observability.registry import MetricsRegistry
from nanofed_tpu.utils.clock import VirtualClock


class StubClient:
    """The HTTPClient surface ChaosClient drives, minus the network: records
    every boundary action so the test can assert what a real client would
    have put on the wire."""

    def __init__(self, client_id="c0"):
        self.client_id = client_id
        self.wire_filter = None
        self.current_round = None
        self.submits = []
        self.resends = 0

    async def submit_update(self, params, metrics):
        # Capture what the wire filter would do to this submit's body.
        body = b"x" * 200
        if self.wire_filter is not None:
            body = self.wire_filter("/update", body)
        self.submits.append((params, self.current_round, body))
        return True

    async def resend_last_update(self):
        self.resends += 1
        return True


def _schedule(*events, registry=None):
    return ChaosSchedule(
        FaultPlan(events=tuple(events)), registry=registry or MetricsRegistry()
    )


def test_wire_fault_count_exhaustion_across_retries():
    # A drop with count=3 severs exactly three attempts of the SAME logical
    # submit; the fourth retry passes — the semantics RetryPolicy is proven
    # against.  ack_drop events for other clients are untouched.
    schedule = _schedule(
        FaultEvent(kind="drop", round=2, client="c0", count=3),
        FaultEvent(kind="ack_drop", round=2, client="c1"),
    )
    for _ in range(3):
        assert schedule.wire_fault("c0", "2").kind == "drop"
    assert schedule.wire_fault("c0", "2") is None  # retry #4 gets through
    assert schedule.wire_fault("c1", "2").kind == "ack_drop"
    assert schedule.wire_fault("c1", "2") is None
    assert schedule.counts() == {"drop": 3, "ack_drop": 1}


def test_wire_fault_ignores_malformed_round_header():
    schedule = _schedule(FaultEvent(kind="drop", round=1, client="c0"))
    # A garbage round header cannot be matched per-round; the event still
    # applies (rnd None matches any round of that client).
    assert schedule.wire_fault("c0", "not-a-round").kind == "drop"
    assert schedule.wire_fault("c0", "1") is None


def test_multiple_kinds_firing_in_the_same_round():
    # One client, one round, four client-boundary kinds at once: every event
    # fires exactly once, and the wire kinds stay independent of them.
    schedule = _schedule(
        FaultEvent(kind="delay", round=1, client="c0", seconds=0.25),
        FaultEvent(kind="skew", round=1, client="c0", seconds=1),
        FaultEvent(kind="corrupt", round=1, client="c0"),
        FaultEvent(kind="duplicate", round=1, client="c0", count=2),
        FaultEvent(kind="drop", round=1, client="c0"),
    )
    events = schedule.client_events("c0", 1)
    assert sorted(e.kind for e in events) == [
        "corrupt", "delay", "duplicate", "skew"
    ]
    assert schedule.client_events("c0", 1) == []  # all consumed
    assert schedule.wire_fault("c0", "1").kind == "drop"  # untouched by above
    assert schedule.counts() == {
        "delay": 1, "skew": 1, "corrupt": 1, "duplicate": 1, "drop": 1,
    }


def test_metric_labels_for_every_kind():
    # One event of EVERY kind, all consumed: the metrics registry must carry
    # one labeled sample per kind — the accounting a chaos run's telemetry
    # snapshot shows.
    reg = MetricsRegistry()
    schedule = _schedule(
        FaultEvent(kind="crash", round=0, client="c0"),
        FaultEvent(kind="delay", round=0, client="c1", seconds=0.1),
        FaultEvent(kind="skew", round=0, client="c2", seconds=1),
        FaultEvent(kind="corrupt", round=0, client="c3"),
        FaultEvent(kind="duplicate", round=0, client="c4"),
        FaultEvent(kind="drop", round=0, client="c5"),
        FaultEvent(kind="ack_drop", round=0, client="c6"),
        FaultEvent(kind="server_kill", round=0),
        FaultEvent(kind="host_crash", round=0, host=0),
        FaultEvent(kind="host_stall", round=0, host=1),
        FaultEvent(kind="dcn_degrade", round=0, host=2, seconds=0.1),
        registry=reg,
    )
    assert schedule.crashed("c0", 0)
    for cid in ("c1", "c2", "c3", "c4"):
        assert schedule.client_events(cid, 0)
    assert schedule.wire_fault("c5", "0")
    assert schedule.wire_fault("c6", "0")
    assert schedule.take_server_kill(0)
    assert schedule.take_host_fault(0, 0)
    assert schedule.take_host_fault(1, 0)
    assert schedule.dcn_delay(2, 0) > 0
    assert schedule.counts() == {kind: 1 for kind in FAULT_KINDS}
    text = reg.render_prometheus()
    for kind in FAULT_KINDS:
        assert f'nanofed_faults_injected_total{{kind="{kind}"}} 1' in text


def test_chaos_client_applies_all_boundary_actions():
    clock = VirtualClock()
    schedule = _schedule(
        FaultEvent(kind="crash", round=3, client="c0"),
        FaultEvent(kind="delay", round=1, client="c0", seconds=5.0),
        FaultEvent(kind="skew", round=1, client="c0", seconds=1),
        FaultEvent(kind="corrupt", round=1, client="c0"),
        FaultEvent(kind="duplicate", round=1, client="c0", count=2),
    )
    stub = StubClient()
    chaos = ChaosClient(stub, schedule, clock=clock)

    async def main():
        assert chaos.alive(0)
        t0 = clock.time()
        ok = await chaos.submit({"w": 1}, {}, 1)
        assert ok
        # delay rode the injected clock, not the wall.
        assert clock.time() - t0 == pytest.approx(5.0)
        return True

    assert asyncio.run(main())
    # skew: the submit carried a round header one back.
    assert stub.submits[0][1] == 0
    # corrupt: the wire filter flipped bits, and was restored afterwards.
    assert stub.submits[0][2] == _flip_bits(b"x" * 200)
    assert stub.wire_filter is None
    # duplicate: the retry storm re-POSTed count extra times.
    assert stub.resends == 2
    # crash: permanent from its round.
    assert chaos.alive(2) and not chaos.alive(3) and not chaos.alive(9)


def test_host_injector_consumes_and_delays():
    schedule = _schedule(
        FaultEvent(kind="host_crash", round=2, host=1),
        FaultEvent(kind="dcn_degrade", round=0, host=0, seconds=0.3, count=2),
    )
    ours = HostChaosInjector(schedule, host=0)
    theirs = HostChaosInjector(schedule, host=1)
    # maybe_fail is a no-op for an untargeted host (never exits the test!).
    ours.maybe_fail(0)
    assert ours.take_fault(5) is None
    assert ours.dcn_delay_s(0) == pytest.approx(0.3)
    assert ours.dcn_delay_s(1) == pytest.approx(0.3)
    assert ours.dcn_delay_s(2) == 0.0
    # The targeted host's fault is visible (take_fault — the query maybe_fail
    # acts on) and consumed exactly once.
    event = theirs.take_fault(3)
    assert event is not None and event.kind == "host_crash"
    assert theirs.take_fault(3) is None
