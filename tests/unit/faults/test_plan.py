"""Fault plans (nanofed_tpu.faults.plan): seeded determinism, JSON round-trip,
and the schedule's consumption semantics — the properties every chaos claim
("survives the plan") rests on."""

import json

import pytest

from nanofed_tpu.faults import ChaosSchedule, FaultEvent, FaultPlan
from nanofed_tpu.observability.registry import MetricsRegistry


def test_event_validation():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultEvent(kind="meteor", round=0)
    with pytest.raises(ValueError, match="round"):
        FaultEvent(kind="crash", round=-1, client="c0")
    with pytest.raises(ValueError, match="count"):
        FaultEvent(kind="drop", round=0, client="c0", count=0)
    with pytest.raises(ValueError, match="per-client"):
        FaultEvent(kind="server_kill", round=1, client="c0")


def test_generate_is_deterministic_in_the_seed():
    clients = [f"c{i}" for i in range(16)]
    a = FaultPlan.generate(7, clients, 10, crash_fraction=0.25,
                           straggler_fraction=0.25, drop_fraction=0.125)
    b = FaultPlan.generate(7, clients, 10, crash_fraction=0.25,
                           straggler_fraction=0.25, drop_fraction=0.125)
    c = FaultPlan.generate(8, clients, 10, crash_fraction=0.25,
                           straggler_fraction=0.25, drop_fraction=0.125)
    assert a == b
    assert a != c
    assert sum(1 for e in a.events if e.kind == "crash") == 4  # 25% of 16
    # Crashes land in the first half so the survival claim covers most rounds.
    assert all(e.round < 5 for e in a.events if e.kind == "crash")


def test_json_round_trip_and_file_io(tmp_path):
    plan = FaultPlan(seed=3, events=(
        FaultEvent(kind="crash", round=1, client="c2"),
        FaultEvent(kind="ack_drop", round=0, client="c0", count=2),
        FaultEvent(kind="delay", round=2, client="c1", seconds=1.5),
        FaultEvent(kind="server_kill", round=2),
    ))
    assert FaultPlan.from_json(plan.to_json()) == plan
    path = tmp_path / "plan.json"
    plan.save(path)
    assert FaultPlan.load(path) == plan
    # The artifact is plain JSON an operator can write by hand.
    raw = json.loads(path.read_text())
    assert raw["seed"] == 3 and len(raw["events"]) == 4


def test_crash_is_permanent_from_its_round():
    schedule = ChaosSchedule(
        FaultPlan(events=(FaultEvent(kind="crash", round=2, client="c1"),)),
        registry=MetricsRegistry(),
    )
    assert not schedule.crashed("c1", 0)
    assert not schedule.crashed("c1", 1)
    assert schedule.crashed("c1", 2)
    assert schedule.crashed("c1", 5)  # permanent
    assert not schedule.crashed("c2", 5)
    assert schedule.counts() == {"crash": 1}  # counted once, not per query


def test_wire_faults_are_consumed_per_count():
    reg = MetricsRegistry()
    schedule = ChaosSchedule(
        FaultPlan(events=(
            FaultEvent(kind="drop", round=0, client="c0", count=2),
        )),
        registry=reg,
    )
    assert schedule.wire_fault("c0", "0").kind == "drop"
    assert schedule.wire_fault("c0", "0").kind == "drop"
    assert schedule.wire_fault("c0", "0") is None  # exhausted: the retry gets through
    assert schedule.wire_fault("c0", "1") is None  # other rounds unaffected
    assert schedule.wire_fault(None, "0") is None
    assert schedule.counts() == {"drop": 2}
    text = reg.render_prometheus()
    assert 'nanofed_faults_injected_total{kind="drop"} 2' in text


def test_server_kill_fires_exactly_once():
    schedule = ChaosSchedule(
        FaultPlan(events=(FaultEvent(kind="server_kill", round=3),)),
        registry=MetricsRegistry(),
    )
    assert not schedule.take_server_kill(2)
    assert schedule.take_server_kill(3)
    assert not schedule.take_server_kill(3)  # consumed: the restarted run proceeds


def test_client_events_collects_this_rounds_faults():
    schedule = ChaosSchedule(
        FaultPlan(events=(
            FaultEvent(kind="delay", round=1, client="c0", seconds=0.5),
            FaultEvent(kind="skew", round=1, client="c0", seconds=2),
            FaultEvent(kind="corrupt", round=2, client="c0"),
            FaultEvent(kind="duplicate", round=1, client="c1", count=3),
        )),
        registry=MetricsRegistry(),
    )
    kinds = sorted(e.kind for e in schedule.client_events("c0", 1))
    assert kinds == ["delay", "skew"]
    assert [e.kind for e in schedule.client_events("c0", 2)] == ["corrupt"]
    assert [e.kind for e in schedule.client_events("c1", 1)] == ["duplicate"]
    # duplicate is counted: consumed after its count is spent.
    assert schedule.client_events("c1", 1) == []


# ---------------------------------------------------------------------------
# Host-targeted kinds (PR 13: host_crash / host_stall / dcn_degrade)
# ---------------------------------------------------------------------------


def test_host_event_validation():
    with pytest.raises(ValueError, match="needs a target host"):
        FaultEvent(kind="host_crash", round=1)
    with pytest.raises(ValueError, match="host must be"):
        FaultEvent(kind="host_stall", round=1, host=-1)
    with pytest.raises(ValueError, match="not a per-client"):
        FaultEvent(kind="host_crash", round=1, host=0, client="c0")
    with pytest.raises(ValueError, match="does not take a host"):
        FaultEvent(kind="crash", round=1, client="c0", host=0)


def test_host_events_json_round_trip():
    plan = FaultPlan(seed=11, events=(
        FaultEvent(kind="host_crash", round=2, host=1),
        FaultEvent(kind="host_stall", round=3, host=0),
        FaultEvent(kind="dcn_degrade", round=1, host=2, seconds=0.25, count=3),
    ))
    assert FaultPlan.from_json(plan.to_json()) == plan
    raw = json.loads(plan.to_json())
    assert {e["host"] for e in raw["events"]} == {0, 1, 2}


def test_generate_draws_host_faults_from_the_seed():
    a = FaultPlan.generate(5, [], 8, hosts=4, host_crash_count=1,
                           host_stall_count=1, dcn_degrade_fraction=0.5,
                           dcn_delay_s=0.3)
    b = FaultPlan.generate(5, [], 8, hosts=4, host_crash_count=1,
                           host_stall_count=1, dcn_degrade_fraction=0.5,
                           dcn_delay_s=0.3)
    assert a == b
    kinds = sorted(e.kind for e in a.events)
    assert kinds == ["dcn_degrade", "dcn_degrade", "host_crash", "host_stall"]
    # Terminal host faults never hit the same host twice (a quorum must
    # survive to recover into), and land mid-run like client crashes.
    terminal = [e for e in a.events if e.kind in ("host_crash", "host_stall")]
    assert len({e.host for e in terminal}) == 2
    assert all(1 <= e.round <= 4 for e in terminal)
    with pytest.raises(ValueError, match="hosts >= 1"):
        FaultPlan.generate(0, [], 8, host_crash_count=1)
    with pytest.raises(ValueError, match="at most once"):
        FaultPlan.generate(0, [], 8, hosts=2, host_crash_count=2,
                           host_stall_count=1)


def test_take_host_fault_is_permanent_and_consumed_once():
    schedule = ChaosSchedule(
        FaultPlan(events=(FaultEvent(kind="host_crash", round=2, host=1),)),
        registry=MetricsRegistry(),
    )
    assert schedule.take_host_fault(1, 0) is None
    assert schedule.take_host_fault(0, 5) is None  # other hosts unaffected
    event = schedule.take_host_fault(1, 4)  # at-or-before semantics
    assert event is not None and event.kind == "host_crash"
    assert schedule.take_host_fault(1, 5) is None  # consumed exactly once
    assert schedule.counts() == {"host_crash": 1}


def test_dcn_delay_covers_count_rounds_and_is_metered():
    reg = MetricsRegistry()
    schedule = ChaosSchedule(
        FaultPlan(events=(
            FaultEvent(kind="dcn_degrade", round=1, host=0, seconds=0.2,
                       count=2),
        )),
        registry=reg,
    )
    assert schedule.dcn_delay(0, 0) == 0.0
    assert schedule.dcn_delay(1, 1) == 0.0  # other host untouched
    assert schedule.dcn_delay(0, 1) == 0.2
    assert schedule.dcn_delay(0, 2) == 0.2
    assert schedule.dcn_delay(0, 3) == 0.0  # window over (count spent)
    assert 'kind="dcn_degrade"} 2' in reg.render_prometheus()
